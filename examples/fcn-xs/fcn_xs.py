"""Fully convolutional segmentation with skip connections (mirrors
reference example/fcn-xs/ — the FCN-8s/16s/32s pattern: conv backbone,
1x1 score head, Deconvolution upsampling, Crop to align skip scores,
per-pixel softmax).

Synthetic task: segment an image into 3 classes laid out as filled
rectangles. Exercises Deconvolution (transpose conv upsampling), Crop
with offset matching (the op pair every FCN variant depends on),
per-pixel SoftmaxOutput with multi_output, and elementwise fusion of
score maps — none of which any other tree touches.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx


def build(nclass):
    data = mx.sym.Variable("data")
    # backbone: two pooling stages -> /4 resolution
    c1 = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=16,
                            name="conv1")
    a1 = mx.sym.Activation(c1, act_type="relu")
    p1 = mx.sym.Pooling(a1, pool_type="max", kernel=(2, 2), stride=(2, 2))
    c2 = mx.sym.Convolution(p1, kernel=(3, 3), pad=(1, 1), num_filter=32,
                            name="conv2")
    a2 = mx.sym.Activation(c2, act_type="relu")
    p2 = mx.sym.Pooling(a2, pool_type="max", kernel=(2, 2), stride=(2, 2))
    # score heads at /4 and /2
    score4 = mx.sym.Convolution(p2, kernel=(1, 1), num_filter=nclass,
                                name="score4")
    score2 = mx.sym.Convolution(p1, kernel=(1, 1), num_filter=nclass,
                                name="score2")
    # upsample /4 scores x2, crop-align to the /2 map, fuse (FCN-16s)
    up2 = mx.sym.Deconvolution(score4, kernel=(4, 4), stride=(2, 2),
                               pad=(1, 1), num_filter=nclass, no_bias=True,
                               name="up2")
    up2c = mx.sym.Crop(up2, score2, name="crop2")
    fuse = up2c + score2
    # upsample to full resolution, crop-align to the input
    up1 = mx.sym.Deconvolution(fuse, kernel=(4, 4), stride=(2, 2),
                               pad=(1, 1), num_filter=nclass, no_bias=True,
                               name="up1")
    score = mx.sym.Crop(up1, data, name="crop1")
    return mx.sym.SoftmaxOutput(score, multi_output=True, name="softmax")


def make_data(rs, n, size, nclass):
    x = rs.uniform(0, 0.2, (n, 3, size, size)).astype(np.float32)
    y = np.zeros((n, size, size), np.float32)
    for i in range(n):
        cls = rs.randint(1, nclass)
        h0, w0 = rs.randint(0, size // 2, 2)
        h1 = h0 + rs.randint(size // 4, size // 2)
        w1 = w0 + rs.randint(size // 4, size // 2)
        y[i, h0:h1, w0:w1] = cls
        # class signature written into the pixels: learnable per-pixel
        x[i, :, h0:h1, w0:w1] += cls / float(nclass)
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--size", type=int, default=16)
    ap.add_argument("--nclass", type=int, default=3)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    x, y = make_data(rs, 128, args.size, args.nclass)
    it = mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True)

    mod = mx.mod.Module(build(args.nclass), context=mx.current_context())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 5e-3})
    for epoch in range(args.num_epochs):
        it.reset()
        correct = total = 0
        for batch in it:
            mod.forward(batch, is_train=True)
            pred = mod.get_outputs()[0].asnumpy()     # (B, C, H, W)
            lab = batch.label[0].asnumpy()
            correct += int((np.argmax(pred, 1) == lab).sum())
            total += lab.size
            mod.backward()
            mod.update()
        print("epoch %d pixel accuracy %.3f" % (epoch, correct / total))
    acc = correct / total
    assert acc > 0.9, acc
    print("FCN_XS_OK")


if __name__ == "__main__":
    main()
