"""CNN for sentence classification (mirrors reference
example/cnn_text_classification/text_cnn.py — Kim-2014 architecture:
embedding -> parallel conv branches with several filter widths ->
max-over-time pooling -> concat -> dropout -> FC -> softmax).

Synthetic task (zero-egress): a "sentence" is a sequence of token ids;
class 1 iff the trigram (3, 4, 5) occurs anywhere — exactly the local
n-gram pattern a width-3 text filter learns. Exercises the op paths no
other example hits together: Embedding in a conv pipeline, Reshape to
NCHW "text image", multi-branch Conv2D with full-width kernels,
max-over-time Pooling, Concat of branch outputs, Dropout.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx


def make_data(rs, n, seqlen, vocab):
    x = rs.randint(6, vocab, size=(n, seqlen)).astype(np.float32)
    y = rs.randint(0, 2, size=n).astype(np.float32)
    for i in range(n):
        if y[i] == 1:
            pos = rs.randint(0, seqlen - 3)
            x[i, pos:pos + 3] = [3, 4, 5]
    return x, y


def build(seqlen, vocab, embed=16, filters=(2, 3, 4), nfilt=8):
    data = mx.sym.Variable("data")                     # (B, T)
    emb = mx.sym.Embedding(data, input_dim=vocab, output_dim=embed,
                           name="embed")               # (B, T, E)
    img = mx.sym.Reshape(emb, shape=(-1, 1, seqlen, embed))
    branches = []
    for w in filters:
        c = mx.sym.Convolution(img, kernel=(w, embed), num_filter=nfilt,
                               name="conv%d" % w)      # (B, F, T-w+1, 1)
        a = mx.sym.Activation(c, act_type="relu")
        p = mx.sym.Pooling(a, pool_type="max",
                           kernel=(seqlen - w + 1, 1))  # max over time
        branches.append(p)
    h = mx.sym.Concat(*branches, dim=1)
    h = mx.sym.Flatten(h)
    h = mx.sym.Dropout(h, p=0.3)
    fc = mx.sym.FullyConnected(h, num_hidden=2, name="fc")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--seqlen", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=40)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    x, y = make_data(rs, 512, args.seqlen, args.vocab)
    it = mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True)

    mod = mx.mod.Module(build(args.seqlen, args.vocab),
                        context=mx.current_context())
    metric = mx.metric.Accuracy()
    mod.fit(it, eval_metric=metric, num_epoch=args.num_epochs,
            initializer=mx.initializer.Xavier(),
            optimizer="adam", optimizer_params={"learning_rate": 5e-3})
    it.reset()
    metric.reset()
    mod.score(it, metric)
    acc = metric.get()[1]
    print("final accuracy %.4f" % acc)
    assert acc > 0.85, acc
    print("TEXT_CNN_OK")


if __name__ == "__main__":
    main()
