"""Fast-RCNN detection head (mirrors reference example/rcnn/ — the
two-head design over ROI-pooled features: per-ROI class softmax +
smooth-L1 bbox regression on a shared trunk).

Synthetic detection task: one bright square per image; proposals are
jittered boxes around it plus background boxes. Exercises ROIPooling
(the op the whole rcnn family stands on), a rois input alongside data,
smooth_l1 + MakeLoss for the regression head grouped with a
SoftmaxOutput classification head, and per-ROI (not per-image) batch
semantics.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx


def build(pooled=4):
    data = mx.sym.Variable("data")
    rois = mx.sym.Variable("rois")                   # (R, 5) batch_idx,x1,y1,x2,y2
    cls_label = mx.sym.Variable("cls_label")         # (R,)
    bbox_target = mx.sym.Variable("bbox_target")     # (R, 4)
    x = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=16,
                           name="conv1")
    x = mx.sym.Activation(x, act_type="relu")
    feat = mx.sym.Convolution(x, kernel=(3, 3), pad=(1, 1), num_filter=16,
                              name="conv2")
    pool = mx.sym.ROIPooling(feat, rois, pooled_size=(pooled, pooled),
                             spatial_scale=1.0, name="roipool")
    flat = mx.sym.Flatten(pool)
    h = mx.sym.FullyConnected(flat, num_hidden=64, name="fc_trunk")
    h = mx.sym.Activation(h, act_type="relu")
    cls = mx.sym.FullyConnected(h, num_hidden=2, name="fc_cls")
    cls_head = mx.sym.SoftmaxOutput(cls, cls_label, name="cls_prob")
    hr = mx.sym.FullyConnected(flat, num_hidden=64, name="fc_reg_trunk")
    hr = mx.sym.Activation(hr, act_type="relu")
    reg = mx.sym.FullyConnected(hr, num_hidden=4, name="fc_reg")
    reg_loss = mx.sym.MakeLoss(
        mx.sym.mean(mx.sym.sum(mx.sym.smooth_l1(reg - bbox_target,
                                                scalar=1.0), axis=1)),
        grad_scale=1.0, name="bbox_loss")
    return mx.sym.Group([cls_head, reg_loss])


def make_data(rs, n, size=24, rois_per_img=8):
    x = rs.uniform(0, 0.1, (n, 1, size, size)).astype(np.float32)
    rois, cls, tgt = [], [], []
    for i in range(n):
        cx, cy = rs.randint(6, size - 10, 2)
        w = h = 8
        x[i, 0, cy:cy + h, cx:cx + w] += 1.0
        for r in range(rois_per_img):
            if r % 2 == 0:  # positive: jittered box around the object
                dx, dy = rs.randint(-2, 3, 2)
                bx, by = cx + dx, cy + dy
                rois.append([i, bx, by, bx + w - 1, by + h - 1])
                cls.append(1)
                # regression target: offset back to the true box, in
                # pooled-feature units
                tgt.append([-dx / 8.0, -dy / 8.0, 0.0, 0.0])
            else:  # background box
                bx, by = rs.randint(0, size - 8, 2)
                while abs(bx - cx) < 6 and abs(by - cy) < 6:
                    bx, by = rs.randint(0, size - 8, 2)
                rois.append([i, bx, by, bx + 7, by + 7])
                cls.append(0)
                tgt.append([0.0, 0.0, 0.0, 0.0])
    return (x, np.asarray(rois, np.float32), np.asarray(cls, np.float32),
            np.asarray(tgt, np.float32))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=30)
    ap.add_argument("--num-images", type=int, default=32)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    x, rois, cls, tgt = make_data(rs, args.num_images)

    # one "batch" = all images + all their ROIs (per-ROI batch semantics)
    mod = mx.mod.Module(build(), data_names=["data", "rois"],
                        label_names=["cls_label", "bbox_target"],
                        context=mx.current_context())
    from mxnet_tpu.io import DataBatch, DataDesc
    mod.bind(data_shapes=[DataDesc("data", x.shape),
                          DataDesc("rois", rois.shape)],
             label_shapes=[DataDesc("cls_label", cls.shape),
                           DataDesc("bbox_target", tgt.shape)])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 5e-3})
    batch = DataBatch([mx.nd.array(x), mx.nd.array(rois)],
                      [mx.nd.array(cls), mx.nd.array(tgt)], pad=0)
    for epoch in range(args.num_epochs):
        mod.forward(batch, is_train=True)
        cls_prob = mod.get_outputs()[0].asnumpy()
        reg_loss = float(mod.get_outputs()[1].asnumpy())
        acc = float((np.argmax(cls_prob, 1) == cls).mean())
        mod.backward()
        mod.update()
        print("epoch %d roi-cls acc %.3f bbox loss %.4f"
              % (epoch, acc, reg_loss))
    assert acc > 0.9, acc
    assert reg_loss < 0.02, reg_loss
    print("FAST_RCNN_OK")


if __name__ == "__main__":
    main()
