"""Train a sequence recogniser with CTC loss (mirrors reference
example/warpctc/ — lstm_ocr.py trains an LSTM over image slices with
the vendored warp-ctc plugin's WarpCTC op; here the native
``lax.scan`` CTC op (``mxnet_tpu/ops/ctc.py`` ≙ reference
src/operator/contrib/ctc_loss-inl.h) does the alignment-free loss, and
greedy best-path decoding with blank/repeat collapse checks accuracy.
No other tree trains through ``ctc_loss``).

Synthetic task: a length-4 digit string is rendered into 20 noisy
frames (each digit held for a couple of frames at a random position,
blanks between), so the frame-to-label alignment is genuinely unknown
— exactly what CTC marginalises over.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx

T = 20           # frames per sequence
L = 4            # labels per sequence
NDIGIT = 10      # classes 1..10 (0 is the CTC blank)
FDIM = 16        # frame feature dim


def render(rs, labels):
    """(L,) labels in [1..10] -> (T, FDIM) noisy frames."""
    x = 0.3 * rs.normal(size=(T, FDIM)).astype(np.float32)
    # each digit occupies 2 consecutive frames inside its quarter
    for i, d in enumerate(labels):
        start = i * (T // L) + rs.randint(0, T // L - 1)
        x[start:start + 2, int(d) - 1] += 2.5
        x[start:start + 2, NDIGIT + (int(d) - 1) % (FDIM - NDIGIT)] += 1.0
    return x


def make_data(rs, n):
    ys = rs.randint(1, NDIGIT + 1, (n, L)).astype(np.float32)
    xs = np.stack([render(rs, y) for y in ys])
    return xs, ys


def build():
    data = mx.sym.Variable("data")                  # (B, T, FDIM)
    label = mx.sym.Variable("label")                # (B, L)
    # temporal context is what separates repeated labels with a learned
    # blank — a frame-local classifier cannot do that (the reference's
    # lstm_ocr.py uses an LSTM encoder for the same reason)
    cell = mx.rnn.LSTMCell(num_hidden=48, prefix="lstm_")
    outputs, _ = cell.unroll(T, data, layout="NTC", merge_outputs=True)
    x = mx.sym.Reshape(outputs, shape=(-1, 48))
    x = mx.sym.FullyConnected(x, num_hidden=NDIGIT + 1, name="fc_out")
    logits = mx.sym.Reshape(x, shape=(-1, T, NDIGIT + 1), name="logits")
    nll = mx.sym.contrib.ctc_loss(logits, label)    # (B,)
    loss = mx.sym.MakeLoss(nll, name="ctc")
    return mx.sym.Group([loss, mx.sym.BlockGrad(logits)])


def greedy_decode(logits):
    """Best path: per-frame argmax, collapse repeats, drop blanks."""
    ids = logits.argmax(-1)
    out = []
    for row in ids:
        seq, prev = [], -1
        for c in row:
            if c != prev and c != 0:
                seq.append(int(c))
            prev = c
        out.append(seq)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=40)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--train-size", type=int, default=512)
    args = ap.parse_args()

    rs = np.random.RandomState(5)
    x_tr, y_tr = make_data(rs, args.train_size)
    x_te, y_te = make_data(rs, 128)

    from mxnet_tpu.io import DataDesc, DataBatch
    mod = mx.mod.Module(build(), data_names=["data", "label"],
                        label_names=[], context=mx.current_context())
    mod.bind(data_shapes=[DataDesc("data", (args.batch_size, T, FDIM)),
                          DataDesc("label", (args.batch_size, L))],
             label_shapes=None, for_training=True)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 5e-3})

    n = args.train_size // args.batch_size
    for epoch in range(args.num_epochs):
        losses = []
        for b in range(n):
            sl = slice(b * args.batch_size, (b + 1) * args.batch_size)
            mod.forward_backward(DataBatch(
                [mx.nd.array(x_tr[sl]), mx.nd.array(y_tr[sl])], []))
            mod.update()
            losses.append(float(mod.get_outputs()[0].asnumpy().mean()))
        if epoch % 5 == 0 or epoch == args.num_epochs - 1:
            print("epoch %d ctc nll %.3f" % (epoch, np.mean(losses)))

    # exact-sequence accuracy on held-out data
    correct = 0
    for b in range(len(x_te) // args.batch_size):
        sl = slice(b * args.batch_size, (b + 1) * args.batch_size)
        mod.forward(DataBatch(
            [mx.nd.array(x_te[sl]), mx.nd.array(y_te[sl])], []),
            is_train=False)
        logits = mod.get_outputs()[1].asnumpy()
        for seq, truth in zip(greedy_decode(logits), y_te[sl]):
            correct += seq == [int(v) for v in truth]
    acc = correct / float(len(x_te))
    print("exact-sequence accuracy %.3f" % acc)
    assert acc > 0.5, "CTC training failed to learn the task"
    print("ctc ok")


if __name__ == "__main__":
    main()
