"""Smoke-run every example in fast/synthetic mode.

Each example runs in its own subprocess (clean JAX state). Used by
tests/test_examples.py and handy as a one-shot sanity sweep.
"""
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))

EXAMPLES = [
    ("image-classification/train_mnist.py",
     ["--synthetic", "--num-epochs", "2", "--network", "mlp"]),
    ("image-classification/benchmark_score.py",
     ["--networks", "alexnet", "--batch-size", "4"]),
    ("gluon/word_language_model/train.py",
     ["--epochs", "1", "--vocab-size", "60", "--nhid", "32",
      "--emsize", "16", "--bptt", "8", "--batch-size", "8"]),
    ("rnn/bucketing_lstm.py",
     ["--num-epochs", "1", "--num-hidden", "32", "--batch-size", "8"]),
    ("sparse/linear_classification.py",
     ["--num-epochs", "2", "--num-features", "200"]),
    ("ssd/train_ssd.py", ["--iters", "2", "--batch-size", "4"]),
    ("parallel/train_moe_pipeline.py", []),
    ("model-parallel/lstm_stages.py", ["--num-stages", "4"]),
    ("autoencoder/autoencoder.py", ["--num-epochs", "6"]),
    ("gan/gan_synthetic.py", ["--iters", "150"]),
    ("adversary/fgsm.py", ["--iters", "80"]),
    ("multi-task/multitask.py", ["--num-epochs", "6"]),
    ("numpy-ops/custom_softmax.py", ["--num-epochs", "6"]),
    ("recommenders/matrix_fact.py", ["--num-epochs", "8"]),
    ("profiler/profiler_demo.py", []),
    ("cnn_text_classification/text_cnn.py", ["--num-epochs", "6"]),
    ("nce-loss/toy_nce.py", ["--num-epochs", "6"]),
    ("bi-lstm-sort/lstm_sort.py", ["--num-epochs", "8"]),
    ("vae/vae.py", ["--num-epochs", "10"]),
    ("neural-style/nstyle.py", ["--iters", "100"]),
    ("fcn-xs/fcn_xs.py", ["--num-epochs", "8"]),
    ("svm_mnist/svm_mnist.py", ["--num-epochs", "6"]),
    ("captcha/captcha_ocr.py", ["--num-epochs", "8"]),
    ("rcnn/fast_rcnn.py", ["--num-epochs", "30"]),
    ("dec/dec.py", ["--refine-iters", "25"]),
    ("stochastic-depth/sd_cifar.py", ["--num-epochs", "10"]),
    ("reinforcement-learning/reinforce_pole.py",
     ["--episodes", "24", "--batch-episodes", "4", "--max-steps", "60"]),
    ("bayesian-methods/sgld_regression.py",
     ["--num-epochs", "45", "--burn-in", "21"]),
    ("memcost/memcost.py", ["--depth", "12", "--hidden", "128"]),
    ("warpctc/ctc_seq_train.py",
     ["--num-epochs", "30", "--train-size", "256"]),
    ("speech-demo/lstm_acoustic.py",
     ["--num-epochs", "12", "--train-size", "192"]),
    ("dsd/dsd.py", ["--epochs-per-phase", "4"]),
    ("mxnet_adversarial_vae/avae.py", ["--iters", "400"]),
    ("module/seq_module.py", ["--num-epochs", "6"]),
    ("python-howto/howto.py", ["--num-epochs", "4"]),
    ("rnn-time-major/rnn_cell_demo.py", ["--num-epochs", "4"]),
    ("speech_recognition/deepspeech.py", ["--num-epochs", "24"]),
    ("kaggle-ndsb1/train_dsb.py", ["--num-epochs", "8"]),
    ("kaggle-ndsb2/train_heart.py", ["--num-epochs", "14"]),
    ("image-classification/fine_tune.py", ["--num-epochs", "6"]),
    ("gluon/lstm_crf/lstm_crf.py", ["--num-epochs", "8"]),
    ("gluon/super_resolution/super_resolution.py",
     ["--num-epochs", "200"]),
    ("gluon/tree_lstm/tree_lstm.py",
     ["--num-epochs", "16", "--train-size", "48", "--depth", "2",
      "--hidden", "12"]),
]


def run_one(rel, extra, force_cpu=True):
    env = dict(os.environ)
    repo_root = os.path.dirname(HERE)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    if force_cpu:
        env["MXNET_TPU_FORCE_CPU"] = "1"
        env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    script = os.path.join(HERE, rel)
    return subprocess.run([sys.executable, script] + extra, env=env,
                          capture_output=True, text=True, timeout=900)


def main():
    failures = []
    for rel, extra in EXAMPLES:
        print("== %s" % rel, flush=True)
        try:
            proc = run_one(rel, extra)
        except subprocess.TimeoutExpired:
            failures.append(rel)
            print("TIMED OUT")
            continue
        tail = "\n".join(proc.stdout.strip().splitlines()[-3:])
        print(tail)
        if proc.returncode != 0:
            failures.append(rel)
            print(proc.stderr[-2000:])
    if failures:
        print("FAILED: %s" % ", ".join(failures))
        sys.exit(1)
    print("all examples passed")


if __name__ == "__main__":
    main()
