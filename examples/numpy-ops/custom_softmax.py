"""Custom operator in Python (mirrors reference
example/numpy-ops/custom_softmax.py): a softmax-with-loss implemented as
a CustomOp/CustomOpProp pair and trained inside a normal Module graph —
the frontend custom-op subsystem end to end."""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx


class Softmax(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        y = np.exp(x - x.max(axis=1, keepdims=True))
        y /= y.sum(axis=1, keepdims=True)
        self.assign(out_data[0], req[0], mx.nd.array(y))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        l = in_data[1].asnumpy().ravel().astype(np.int64)
        y = out_data[0].asnumpy().copy()
        y[np.arange(l.shape[0]), l] -= 1.0
        self.assign(in_grad[0], req[0], mx.nd.array(y))


@mx.operator.register("example_softmax")
class SoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        data_shape = in_shape[0]
        label_shape = (in_shape[0][0],)
        return [data_shape, label_shape], [data_shape], []

    def create_operator(self, ctx, shapes, dtypes):
        return Softmax()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    n, dim, classes = 512, 10, 3
    centers = rs.uniform(-2, 2, size=(classes, dim)).astype(np.float32)
    y = rs.randint(0, classes, n)
    x = centers[y] + 0.3 * rs.normal(size=(n, dim)).astype(np.float32)

    it = mx.io.NDArrayIter(x.astype(np.float32), y.astype(np.float32),
                           batch_size=args.batch_size, shuffle=True,
                           label_name="softmax_label")

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    fc = mx.sym.FullyConnected(data, num_hidden=classes, name="fc")
    net = mx.sym.Custom(fc, label, op_type="example_softmax",
                        name="softmax")

    mod = mx.mod.Module(net, context=mx.current_context())
    mod.fit(it, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2,
                              "rescale_grad": 1.0 / args.batch_size},
            num_epoch=args.num_epochs, eval_metric="acc")
    it.reset()
    acc = dict(mod.score(it, mx.metric.Accuracy()))["accuracy"]
    print("custom-softmax accuracy %.3f" % acc)
    assert acc > 0.9, "custom-op training failed"


if __name__ == "__main__":
    main()
