"""Measure the memory cost of training with and without activation
mirroring (mirrors reference example/memcost/ — inception_memcost.py
compares resident memory with ``MXNET_BACKWARD_DO_MIRROR``; here the
knob maps to ``jax.checkpoint`` rematerialisation and the comparison
reads XLA's own compiled-memory analysis instead of nvidia-smi).

Builds a deep narrow MLP (activation-dominated, the regime mirroring
targets), compiles the fused fwd+bwd step both ways, and reports the
compiler's temp-buffer footprint. Mirroring must cut temp memory; the
price is recompute FLOPs.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def build(depth, hidden):
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    h = data
    for i in range(depth):
        h = mx.sym.FullyConnected(h, num_hidden=hidden, name="fc%d" % i)
        h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=10, name="head")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def temp_bytes(mirror, depth, hidden, batch):
    """Compile the executor's fused fwd+bwd program; return (XLA
    temp-allocation size, matmul count). The matmul count shows the
    recompute trade: mirroring re-runs forward dots in the backward."""
    os.environ["MXNET_BACKWARD_DO_MIRROR"] = "1" if mirror else "0"
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import random as _random

    sym = build(depth, hidden)
    exe = sym.simple_bind(ctx=mx.current_context(), grad_req="write",
                          data=(batch, hidden),
                          softmax_label=(batch,))
    prog = exe._prog
    grad_names = tuple(n for n in exe._arg_names
                       if exe._grad_req[n] != "null")
    fn = prog.fwd_bwd_fn(True, grad_names)
    args = {n: a._data for n, a in zip(exe._arg_names, exe.arg_arrays)}
    aux = {n: a._data for n, a in zip(exe._aux_names, exe.aux_arrays)}
    key = _random.take_key()
    hg = tuple([None] * exe.output_entries_len())
    lowered = fn.lower(args, aux, key, hg)
    dots = lowered.as_text().count("dot_general")
    compiled = lowered.compile()
    try:
        mem = compiled.memory_analysis()
        return int(mem.temp_size_in_bytes), dots
    except Exception:
        return None, dots  # backend ships no memory analysis


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--depth", type=int, default=24)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    plain, dots_p = temp_bytes(False, args.depth, args.hidden,
                               args.batch_size)
    mirrored, dots_m = temp_bytes(True, args.depth, args.hidden,
                                  args.batch_size)
    os.environ["MXNET_BACKWARD_DO_MIRROR"] = "0"

    print("matmuls plain %d -> mirrored %d (recompute in backward)"
          % (dots_p, dots_m))
    assert dots_m > dots_p, "mirroring emitted no rematerialisation"
    if plain is None or mirrored is None:
        print("memory analysis unavailable on this backend")
        print("memcost ok")
        return
    print("temp memory plain    : %.2f MiB" % (plain / 2**20))
    print("temp memory mirrored : %.2f MiB" % (mirrored / 2**20))
    # buffer-assignment peaks are backend-specific: the CPU backend can
    # schedule both variants to the same temp block at these sizes; on
    # TPU the saving is what MXNET_BACKWARD_DO_MIRROR exists for
    assert mirrored <= plain * 1.05, \
        "rematerialisation should not increase temp memory"
    print("memcost ok")


if __name__ == "__main__":
    main()
