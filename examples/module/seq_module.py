"""Module-API tour (mirrors reference example/module/ —
sequential_module.py, python_loss.py and mnist_mlp.py in one tree).

Three stages, each exercising a container no other example touches:

1. ``SequentialModule`` chaining two independently-built ``Module``s
   with ``auto_wiring`` (module 2's data is module 1's output) and
   ``take_labels`` (the label flows to the last module only).
2. ``PythonLossModule`` as the chain's head: the multiclass hinge
   gradient is computed in numpy on the host (the reference used
   numba; plain numpy keeps it dependency-free) and injected into the
   backward pass — the loss itself never exists as a graph node.
3. The intermediate-level API on a plain ``Module``
   (bind/init_params/forward/backward/update by hand) plus the
   prediction surface: ``iter_predict``, ``predict`` with and without
   ``merge_batches``, and ``score``.

Synthetic separable digits (10 Gaussian prototypes) stand in for
MNIST so the tree is egress-free.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx


def make_data(rs, n, protos):
    y = rs.randint(0, 10, n).astype(np.float32)
    x = protos[y.astype(int)] + 0.25 * rs.normal(size=(n, protos.shape[1])
                                                 ).astype(np.float32)
    return x, y


def mc_hinge_grad(scores, labels):
    """Multiclass hinge gradient, computed on the host in numpy."""
    scores = scores.asnumpy()
    labels = labels.asnumpy().astype(int)
    n, _ = scores.shape
    grad = np.zeros_like(scores)
    for i in range(n):
        margin = 1.0 + scores[i] - scores[i, labels[i]]
        margin[labels[i]] = 0.0
        pred = int(margin.argmax())
        if margin[pred] > 0:
            grad[i, labels[i]] -= 1.0
            grad[i, pred] += 1.0
    return grad / n


def feature_module():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="fc1", num_hidden=128)
    act1 = mx.sym.Activation(fc1, name="relu1", act_type="relu")
    return mx.mod.Module(act1, label_names=[], context=mx.current_context())


def head_module():
    data = mx.sym.Variable("data")
    fc2 = mx.sym.FullyConnected(data, name="fc2", num_hidden=64)
    act2 = mx.sym.Activation(fc2, name="relu2", act_type="relu")
    fc3 = mx.sym.FullyConnected(act2, name="fc3", num_hidden=10)
    sm = mx.sym.SoftmaxOutput(fc3, name="softmax")
    return mx.mod.Module(sm, context=mx.current_context())


def scores_module():
    data = mx.sym.Variable("data")
    fc2 = mx.sym.FullyConnected(data, name="fc2b", num_hidden=64)
    act2 = mx.sym.Activation(fc2, name="relu2b", act_type="relu")
    fc3 = mx.sym.FullyConnected(act2, name="fc3b", num_hidden=10)
    return mx.mod.Module(fc3, label_names=[], context=mx.current_context())


def run_sequential(args, train_it, val_it):
    mod_seq = mx.mod.SequentialModule()
    mod_seq.add(feature_module()) \
           .add(head_module(), take_labels=True, auto_wiring=True)
    mod_seq.fit(train_it,
                optimizer_params={"learning_rate": 0.02},
                initializer=mx.initializer.Xavier(),
                num_epoch=args.num_epochs)
    metric = mx.metric.Accuracy()
    val_it.reset()
    mod_seq.score(val_it, metric)
    return metric.get()[1]


def run_python_loss(args, train_it, val_it):
    mod = mx.mod.SequentialModule() \
            .add(feature_module()) \
            .add(mx.mod.PythonLossModule(grad_func=mc_hinge_grad),
                 take_labels=True, auto_wiring=True)
    # hinge grads are batch-normalised (unlike SoftmaxOutput's summed
    # grads), so this stage takes a proportionally larger step size
    mod.fit(train_it,
            optimizer_params={"learning_rate": 0.1},
            initializer=mx.initializer.Xavier(),
            num_epoch=args.num_epochs)
    # PythonLossModule's forward is identity, so scoring runs on the
    # raw scores emitted by the trailing FullyConnected.
    correct = total = 0
    val_it.reset()
    for preds, _, batch in mod.iter_predict(val_it):
        pred = preds[0].asnumpy().argmax(axis=1)
        lab = batch.label[0].asnumpy().astype(int)
        correct += int((pred == lab).sum())
        total += len(lab)
    return correct / float(total)


def run_intermediate(args, train_it, val_it):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, name="ifc1", num_hidden=128)
    act1 = mx.sym.Activation(fc1, act_type="relu")
    fc3 = mx.sym.FullyConnected(act1, name="ifc3", num_hidden=10)
    sm = mx.sym.SoftmaxOutput(fc3, name="softmax")

    mod = mx.mod.Module(sm, context=mx.current_context())
    mod.bind(data_shapes=train_it.provide_data,
             label_shapes=train_it.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(
        optimizer_params={"learning_rate": 0.02})
    metric = mx.metric.Accuracy()
    for _ in range(args.num_epochs):
        train_it.reset()
        metric.reset()
        for batch in train_it:
            mod.forward(batch)
            mod.update_metric(metric, batch.label)
            mod.backward()
            mod.update()

    # prediction-surface tour
    val_it.reset()
    for preds, i_batch, batch in mod.iter_predict(val_it):
        if i_batch == 0:
            assert preds[0].shape[1] == 10
    val_it.reset()
    merged = mod.predict(val_it)
    val_it.reset()
    unmerged = mod.predict(val_it, merge_batches=False)
    assert merged.shape[0] == sum(p[0].shape[0] for p in unmerged)
    val_it.reset()
    metric.reset()
    mod.score(val_it, metric)
    return metric.get()[1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    mx.random.seed(5)
    rs = np.random.RandomState(7)
    protos = rs.normal(0, 1.0, (10, 64)).astype(np.float32)
    xtr, ytr = make_data(rs, 1024, protos)
    xva, yva = make_data(rs, 256, protos)
    train_it = mx.io.NDArrayIter(xtr, ytr, batch_size=args.batch_size,
                                 shuffle=True, label_name="softmax_label")
    val_it = mx.io.NDArrayIter(xva, yva, batch_size=args.batch_size,
                               label_name="softmax_label")

    acc_seq = run_sequential(args, train_it, val_it)
    train_it.reset()
    acc_hinge = run_python_loss(args, train_it, val_it)
    train_it.reset()
    acc_mid = run_intermediate(args, train_it, val_it)

    print("sequential acc %.3f" % acc_seq)
    print("python-loss acc %.3f" % acc_hinge)
    print("intermediate acc %.3f" % acc_mid)
    # the hinge stage updates only the worst-violating class per sample,
    # so it converges slower than the softmax heads
    assert acc_seq > 0.85 and acc_hinge > 0.65 and acc_mid > 0.85
    print("module tour ok")


if __name__ == "__main__":
    main()
