"""Sparse linear classification (mirrors reference example/sparse/
linear_classification.py — baseline config 5): LibSVM input, a row-sparse
weight whose gradients only touch the feature rows present in each batch,
sparse (lazy-row) optimizer updates, and kvstore ``row_sparse_pull`` of
just those rows.

TPU-native note: the forward/backward is a dense XLA dot (storage
fallback, as the reference does for kernels without sparse FComputeEx);
the sparsity pays off in the gradient/update/communication path, which is
where the reference's design put it too (kvstore_dist.h:430-496).
"""
import argparse
import os
import tempfile

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.ndarray import sparse as sp


def write_synthetic_libsvm(path, num_samples=2000, num_features=1000,
                           nnz=12, seed=0):
    """Two-class data where the sign of a sparse linear functional decides
    the label; features written in libsvm 'label idx:val' lines."""
    rng = np.random.RandomState(seed)
    true_w = rng.normal(size=num_features)
    with open(path, "w") as f:
        for _ in range(num_samples):
            idx = np.sort(rng.choice(num_features, nnz, replace=False))
            val = rng.normal(size=nnz)
            label = 1.0 if true_w[idx].dot(val) > 0 else 0.0
            feats = " ".join("%d:%.4f" % (i, v) for i, v in zip(idx, val))
            f.write("%g %s\n" % (label, feats))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-features", type=int, default=1000)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--num-epochs", type=int, default=4)
    parser.add_argument("--kvstore", type=str, default="device")
    parser.add_argument("--lr", type=float, default=0.5)
    parser.add_argument("--data", type=str, default=None)
    args = parser.parse_args()

    if args.data is None:
        tmp = tempfile.mkdtemp()
        args.data = os.path.join(tmp, "train.libsvm")
        write_synthetic_libsvm(args.data, num_features=args.num_features)

    train = mx.io.LibSVMIter(data_libsvm=args.data,
                             data_shape=(args.num_features,),
                             batch_size=args.batch_size)

    kv = mx.kv.create(args.kvstore)
    weight = mx.nd.zeros((args.num_features, 2))
    bias = mx.nd.zeros((2,))
    kv.init("weight", weight)
    opt = mx.optimizer.create("sgd", learning_rate=args.lr,
                              rescale_grad=1.0 / args.batch_size)
    # update_on_kvstore: pushes apply the optimizer to the stored weight
    kv.set_optimizer(opt)
    b_state = opt.create_state(1, bias)

    metric = mx.metric.Accuracy()
    for epoch in range(args.num_epochs):
        train.reset()
        metric.reset()
        for batch in train:
            x = batch.data[0]          # CSRNDArray from the LibSVM iter
            y = batch.label[0]
            row_ids = mx.nd.array(
                np.unique(x.indices.asnumpy()), dtype="int64")
            # pull only the rows this batch touches (reference:
            # kvstore row_sparse_pull by row-id ranges)
            w_rsp = sp.zeros("row_sparse", weight.shape)
            kv.row_sparse_pull("weight", out=w_rsp, row_ids=row_ids)
            w_dense = w_rsp.tostype("default")

            w_dense.attach_grad()
            bias.attach_grad()
            with mx.autograd.record():
                pred = sp.dot(x, w_dense) + bias
                loss = mx.nd.softmax_cross_entropy(pred, y)
            loss.backward()

            # row-sparse gradient: only touched rows carry values; the
            # kvstore-side optimizer applies a lazy-row update on push
            grad_rsp = sp.cast_storage(w_dense.grad, "row_sparse")
            kv.push("weight", grad_rsp)
            opt.update(1, bias, bias.grad, b_state)

            metric.update([y], [mx.nd.softmax(pred)])
        print("epoch %d: train accuracy %.4f" % (epoch, metric.get()[1]))
    acc = metric.get()[1]
    print("final accuracy: %.4f" % acc)
    return acc


if __name__ == "__main__":
    main()
