"""Python API how-to tour (mirrors reference example/python-howto/ —
data_iter.py, multiple_outputs.py, monitor_weights.py, debug_conv.py).

Four short demos, each a pattern users of the reference reached for:

1. **data_iter** — pack a few synthetic images into RecordIO with
   ``MXIndexedRecordIO``, then read them back through
   ``ImageRecordIter`` with augmentation (crop/mirror) and the
   prefetching backend thread, inspecting ``data``/``label``/``pad``.
2. **multiple_outputs** — ``mx.sym.Group`` exposing an internal layer
   alongside the loss head; both come back from one ``forward``.
3. **monitor_weights** — ``mx.mon.Monitor`` with a norm stat function
   installed into ``FeedForward.fit`` to print per-layer tensor norms
   every N batches.
4. **debug_conv** — ``simple_bind`` a lone Convolution, poke an input
   in by hand, and look at the output — the minimal way to see what a
   single operator does.
"""
import argparse
import io as pyio
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import recordio


def demo_data_iter():
    from PIL import Image
    tmp = tempfile.mkdtemp(prefix="howto_rec_")
    rec_path = os.path.join(tmp, "toy.rec")
    idx_path = os.path.join(tmp, "toy.idx")
    writer = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    rs = np.random.RandomState(0)
    n = 12
    for i in range(n):
        img = Image.fromarray(
            rs.randint(0, 255, (36, 36, 3), dtype=np.uint8))
        buf = pyio.BytesIO()
        img.save(buf, format="JPEG")
        header = recordio.IRHeader(0, float(i % 3), i, 0)
        writer.write_idx(i, recordio.pack(header, buf.getvalue()))
    writer.close()

    it = mx.io.ImageRecordIter(
        path_imgrec=rec_path, path_imgidx=idx_path,
        data_shape=(3, 28, 28), batch_size=5,
        rand_crop=True, rand_mirror=True, shuffle=False,
        preprocess_threads=2, prefetch_buffer=2, round_batch=True)
    seen = 0
    for bidx, dbatch in enumerate(it):
        data = dbatch.data[0]
        label = dbatch.label[0]
        assert data.shape == (5, 3, 28, 28)
        seen += 5 - dbatch.pad
        print("batch %d labels %s pad %d"
              % (bidx, label.asnumpy().astype(int).tolist(), dbatch.pad))
    assert seen == n
    print("data_iter ok")


def demo_multiple_outputs():
    net = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data=net, name="fc1", num_hidden=16)
    net = mx.sym.Activation(data=fc1, name="relu1", act_type="relu")
    net = mx.sym.FullyConnected(data=net, name="fc2", num_hidden=4)
    out = mx.sym.SoftmaxOutput(data=net, name="softmax")
    group = mx.sym.Group([fc1, out])
    print("group outputs:", group.list_outputs())
    assert group.list_outputs() == ["fc1_output", "softmax_output"]

    ex = group.simple_bind(ctx=mx.current_context(),
                           data=(2, 8), grad_req="null")
    for name, arr in zip(ex._symbol.list_arguments(), ex.arg_arrays):
        if name != "data" and not name.endswith("label"):
            arr[:] = 0.1
    ex.forward(is_train=False,
               data=mx.nd.array(np.ones((2, 8), dtype=np.float32)))
    hidden, probs = ex.outputs
    assert hidden.shape == (2, 16) and probs.shape == (2, 4)
    np.testing.assert_allclose(probs.asnumpy().sum(axis=1), 1.0, rtol=1e-5)
    print("multiple_outputs ok")


def demo_monitor_weights(num_epochs):
    rs = np.random.RandomState(1)
    protos = rs.normal(0, 1.0, (10, 32)).astype(np.float32)
    y = rs.randint(0, 10, 512).astype(np.float32)
    x = protos[y.astype(int)] + 0.3 * rs.normal(size=(512, 32)).astype(
        np.float32)
    train = mx.io.NDArrayIter(x, y, batch_size=64, shuffle=True,
                              label_name="softmax_label")

    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, name="fc1", num_hidden=32)
    h = mx.sym.Activation(h, name="relu1", act_type="relu")
    h = mx.sym.FullyConnected(h, name="fc2", num_hidden=10)
    mlp = mx.sym.SoftmaxOutput(h, name="softmax")

    def norm_stat(d):
        return d.norm() / np.sqrt(d.size)

    mon = mx.mon.Monitor(4, norm_stat, pattern=".*weight")
    model = mx.model.FeedForward(
        ctx=mx.current_context(), symbol=mlp, num_epoch=num_epochs,
        learning_rate=0.1, momentum=0.9, wd=1e-5)
    model.fit(X=train, monitor=mon,
              batch_end_callback=mx.callback.Speedometer(64, 4))
    print("monitor_weights ok")


def demo_debug_conv():
    data = mx.sym.Variable("data")
    conv = mx.sym.Convolution(data=data, kernel=(3, 3), pad=(1, 1),
                              stride=(1, 1), num_filter=1, no_bias=True,
                              name="conv")
    ex = conv.simple_bind(ctx=mx.current_context(), data=(1, 3, 5, 5),
                          grad_req="null")
    # identity-ish kernel: all ones over a 3x3x3 window
    for name, arr in zip(ex._symbol.list_arguments(), ex.arg_arrays):
        if name == "conv_weight":
            arr[:] = 1.0
    x = np.ones((1, 3, 5, 5), dtype=np.float32)
    ex.forward(is_train=False, data=mx.nd.array(x))
    out = ex.outputs[0].asnumpy()
    assert out.shape == (1, 1, 5, 5)
    # interior pixels see the full 3x3x3=27 window of ones
    assert out[0, 0, 2, 2] == 27.0
    # corners see only 2x2x3=12
    assert out[0, 0, 0, 0] == 12.0
    print("conv out:\n", out[0, 0])
    print("debug_conv ok")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=4)
    args = ap.parse_args()
    demo_data_iter()
    demo_multiple_outputs()
    demo_monitor_weights(args.num_epochs)
    demo_debug_conv()
    print("howto ok")


if __name__ == "__main__":
    main()
