"""DeepSpeech2-style speech recognition (mirrors the scope of reference
example/speech_recognition/ — arch_deepspeech.py builds conv + BN stem
over the spectrogram, a stack of bidirectional GRUs, per-timestep FC,
and a WarpCTC head; main.py trains it with bucketing).

This compact tpu-native version keeps every architectural ingredient —
Convolution+BatchNorm spectrogram stem, ``BidirectionalCell`` over
``GRUCell`` (no other tree touches bi-GRU), per-timestep FC, and the
native ``contrib.ctc_loss`` — on a synthetic "spoken digits" task:
each utterance is a sequence of frequency-band tones (one band per
digit) with jittered duration, so CTC must learn alignment-free
transcription. Greedy CTC decoding must recover most digit strings.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.rnn import BidirectionalCell, GRUCell

NUM_DIGITS = 5          # vocabulary: digits 0..4 -> classes 1..5
BLANK = 0               # native ctc_loss reserves class 0 for blank
N_FREQ = 16             # spectrogram bins


def make_utterance(rs, digits):
    """Each digit rings its frequency band for 2-4 frames."""
    frames = []
    for d in digits:
        dur = rs.randint(2, 5)
        f = np.zeros((dur, N_FREQ), np.float32)
        lo = 1 + 2 * d
        f[:, lo:lo + 3] = 1.0
        f += 0.15 * rs.normal(size=f.shape).astype(np.float32)
        frames.append(f)
        gap = np.zeros((rs.randint(1, 3), N_FREQ), np.float32) \
            + 0.15 * rs.normal(size=(1, N_FREQ)).astype(np.float32)
        frames.append(gap)
    return np.concatenate(frames)


def make_dataset(rs, n, seq_frames, label_len):
    X = np.zeros((n, 1, seq_frames, N_FREQ), np.float32)
    Y = np.zeros((n, label_len), np.float32)   # 0 = CTC padding/blank
    for i in range(n):
        k = rs.randint(2, label_len + 1)
        digits = rs.randint(0, NUM_DIGITS, k)
        utt = make_utterance(rs, digits)[:seq_frames]
        X[i, 0, :len(utt)] = utt
        Y[i, :k] = digits + 1                  # classes 1..NUM_DIGITS
    return X, Y


def build(seq_frames, num_hidden, num_rnn_layers):
    data = mx.sym.Variable("data")          # (N, 1, T, F)
    label = mx.sym.Variable("label")        # (N, L)

    # conv stem over (time, freq) — stride 1 in time keeps T for CTC
    net = mx.sym.Convolution(data, kernel=(5, 5), stride=(1, 2),
                             pad=(2, 2), num_filter=8, name="conv1")
    net = mx.sym.BatchNorm(net, name="bn1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Convolution(net, kernel=(5, 3), stride=(1, 2),
                             pad=(2, 1), num_filter=8, name="conv2")
    net = mx.sym.BatchNorm(net, name="bn2")
    net = mx.sym.Activation(net, act_type="relu")

    # (N, C, T, F') -> (N, T, C*F') sequence
    net = mx.sym.transpose(net, axes=(0, 2, 1, 3))
    net = mx.sym.Reshape(net, shape=(0, 0, -1))

    for i in range(num_rnn_layers):
        cell = BidirectionalCell(
            GRUCell(num_hidden=num_hidden, prefix="gru_f%d_" % i),
            GRUCell(num_hidden=num_hidden, prefix="gru_b%d_" % i),
            output_prefix="bi%d_" % i)
        outputs, _ = cell.unroll(seq_frames, inputs=net,
                                 merge_outputs=True, layout="NTC")
        net = outputs                        # (N, T, 2H)

    net = mx.sym.Reshape(net, shape=(-1, 2 * num_hidden))
    net = mx.sym.FullyConnected(net, num_hidden=NUM_DIGITS + 1, name="fc")
    logits = mx.sym.Reshape(net, shape=(-1, seq_frames, NUM_DIGITS + 1))
    nll = mx.sym.contrib.ctc_loss(logits, label)        # (N, T, V) layout
    loss = mx.sym.MakeLoss(nll, name="ctc")
    preds = mx.sym.BlockGrad(logits, name="logits")
    return mx.sym.Group([loss, preds])


def greedy_decode(logits):
    """(N, T, V) -> list of collapsed, blank-stripped class lists."""
    best = logits.argmax(axis=2)            # (N, T)
    out = []
    for row in best:
        seq, prev = [], -1
        for t in row:
            if t != prev and t != BLANK:
                seq.append(int(t))
            prev = t
        out.append(seq)
    return out


def label_err(pred, lab):
    ref = [int(v) for v in lab if v > 0]
    if not ref:
        return 0.0
    # simple edit distance
    dp = np.arange(len(pred) + 1, dtype=np.int32)
    for j, r in enumerate(ref, 1):
        prev, dp[0] = dp[0], j
        for i, p in enumerate(pred, 1):
            cur = min(dp[i] + 1, dp[i - 1] + 1, prev + (p != r))
            prev, dp[i] = dp[i], cur
    return dp[len(pred)] / float(len(ref))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=30)
    ap.add_argument("--train-size", type=int, default=192)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-hidden", type=int, default=48)
    ap.add_argument("--num-rnn-layers", type=int, default=1)
    ap.add_argument("--seq-frames", type=int, default=24)
    ap.add_argument("--label-len", type=int, default=4)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    X, Y = make_dataset(rs, args.train_size, args.seq_frames,
                        args.label_len)
    it = mx.io.NDArrayIter(X, Y, batch_size=args.batch_size,
                           shuffle=True, label_name="label")

    sym = build(args.seq_frames, args.num_hidden, args.num_rnn_layers)
    mod = mx.mod.Module(sym, label_names=["label"],
                        context=mx.current_context())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 3e-3})

    for epoch in range(args.num_epochs):
        it.reset()
        losses = []
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
            losses.append(mod.get_outputs()[0].asnumpy().mean())
        if epoch % 4 == 0 or epoch == args.num_epochs - 1:
            print("epoch %d ctc nll %.3f" % (epoch, float(np.mean(losses))))

    # evaluate label error rate with greedy decoding
    it.reset()
    errs = []
    for batch in it:
        mod.forward(batch, is_train=False)
        logits = mod.get_outputs()[1].asnumpy()
        for pred, lab in zip(greedy_decode(logits),
                             batch.label[0].asnumpy()):
            errs.append(label_err(pred, lab))
    ler = float(np.mean(errs))
    print("label error rate %.3f" % ler)
    assert ler < 0.35, "bi-GRU CTC should mostly transcribe the tones"
    print("deepspeech ok")


if __name__ == "__main__":
    main()
