"""Dense-Sparse-Dense training (mirrors reference example/dsd/ —
train dense, prune the smallest weights and retrain under the sparsity
mask, then release the mask and retrain dense; the DSD schedule from
Han et al. that the reference drives with its sparse regularizers).

Exercises Module parameter surgery mid-training: get_params ->
magnitude mask -> set_params, and a batch_end_callback that re-applies
the mask after every optimizer step — an update-loop interposition no
other tree uses.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx


def build():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=64, name="fc2")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=10, name="fc3")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def make_data(rs, n, dim=32):
    protos = rs.normal(0, 1.0, (10, dim)).astype(np.float32)
    y = rs.randint(0, 10, n).astype(np.float32)
    x = protos[y.astype(int)] + 1.3 * rs.normal(size=(n, dim)).astype(
        np.float32)
    return x, y


def accuracy(mod, it):
    m = mx.metric.Accuracy()
    it.reset()
    mod.score(it, m)
    return m.get()[1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs-per-phase", type=int, default=6)
    ap.add_argument("--sparsity", type=float, default=0.8)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    np.random.seed(0)    # initializer draws and iterator shuffles use
    mx.random.seed(0)    # the global RNGs: seed both for repeatability
    rs = np.random.RandomState(2)
    x_all, y_all = make_data(rs, 1536)   # one draw: train/test share the
    x, y = x_all[:1024], y_all[:1024]    # class prototypes
    xt, yt = x_all[1024:], y_all[1024:]
    it = mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True)
    test_it = mx.io.NDArrayIter(xt, yt, batch_size=args.batch_size)

    mod = mx.mod.Module(build(), context=mx.current_context())
    opt = ("adam", {"learning_rate": 2e-3})

    # phase 1: DENSE
    mod.fit(it, num_epoch=args.epochs_per_phase,
            initializer=mx.initializer.Xavier(),
            optimizer=opt[0], optimizer_params=opt[1])
    acc_dense = accuracy(mod, test_it)

    # phase 2: SPARSE — magnitude-prune each weight matrix, keep
    # training with the mask re-applied after every update
    arg_p, aux_p = mod.get_params()
    masks = {}
    for name, arr in arg_p.items():
        if not name.endswith("_weight"):
            continue
        w = arr.asnumpy()
        thr = np.quantile(np.abs(w), args.sparsity)
        masks[name] = (np.abs(w) >= thr).astype(np.float32)
        arg_p[name] = mx.nd.array(w * masks[name])
    mod.set_params(arg_p, aux_p)

    def apply_masks(_param=None):
        ap_, au_ = mod.get_params()
        for name, m in masks.items():
            ap_[name] = mx.nd.array(ap_[name].asnumpy() * m)
        mod.set_params(ap_, au_)

    it.reset()
    mod.fit(it, num_epoch=args.epochs_per_phase,
            optimizer=opt[0], optimizer_params=opt[1],
            batch_end_callback=apply_masks, force_init=False)
    apply_masks()
    acc_sparse = accuracy(mod, test_it)
    live = np.mean([m.mean() for m in masks.values()])

    # phase 3: re-DENSE — drop the masks, lower lr, retrain everything
    # (init_optimizer is a no-op once initialized, so the lr change
    # needs an explicit force_init — the reference has the same rule,
    # module.py init_optimizer:472)
    it.reset()
    mod.init_optimizer(optimizer=opt[0],
                       optimizer_params={"learning_rate": 5e-4},
                       force_init=True)
    mod.fit(it, num_epoch=args.epochs_per_phase)
    acc_redense = accuracy(mod, test_it)

    print("dense %.3f -> sparse(%.0f%% pruned) %.3f -> re-dense %.3f"
          % (acc_dense, 100 * (1 - live), acc_sparse, acc_redense))
    assert acc_sparse > 0.7, "sparse phase collapsed"
    assert acc_redense >= acc_dense - 0.05, "DSD should roughly recover"
    print("dsd ok")


if __name__ == "__main__":
    main()
