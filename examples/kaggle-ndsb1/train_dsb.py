"""Kaggle NDSB-1 plankton pipeline (mirrors reference
example/kaggle-ndsb1/ — gen_img_list.py builds stratified .lst splits,
im2rec packs them, train_dsb.py trains a small convnet with an lr
schedule + gradient clipping, predict_dsb.py + submission_dsb.py turn
class probabilities into the competition CSV).

The whole competition loop runs here on synthetic "plankton" (one blob
shape per class), exercising a chain no other tree does end to end:
class-directory images -> ``tools/im2rec.py --list`` + pack (the real
CLI, in subprocesses) -> ``ImageRecordIter`` over the packed .rec ->
``Module.fit`` with ``MultiFactorScheduler`` and ``clip_gradient`` ->
``predict`` on an unlabeled test .rec -> probability-matrix submission
CSV (rows must sum to 1).
"""
import argparse
import csv
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx

CLASSES = ["acantharia", "copepod", "diatom", "shrimp"]
IMG = 24


def draw_class(rs, cls):
    """One distinguishable grayscale blob per class."""
    a = np.zeros((IMG, IMG), np.uint8)
    yy, xx = np.mgrid[:IMG, :IMG]
    cy, cx = rs.randint(8, IMG - 8, 2)
    if cls == 0:    # disc
        a[(yy - cy) ** 2 + (xx - cx) ** 2 < 30] = 220
    elif cls == 1:  # vertical bar
        a[:, max(0, cx - 2):cx + 2] = 220
    elif cls == 2:  # horizontal bar
        a[max(0, cy - 2):cy + 2, :] = 220
    else:           # cross
        a[:, max(0, cx - 1):cx + 1] = 220
        a[max(0, cy - 1):cy + 1, :] = 220
    noise = rs.randint(0, 40, a.shape).astype(np.uint8)
    return np.minimum(255, a + noise)


def write_images(root, rs, per_class):
    from PIL import Image
    for ci, cname in enumerate(CLASSES):
        d = os.path.join(root, cname)
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            img = Image.fromarray(draw_class(rs, ci), mode="L").convert(
                "RGB")
            img.save(os.path.join(d, "%s_%03d.jpg" % (cname, i)))


def im2rec(repo, argv):
    tool = os.path.join(repo, "tools", "im2rec.py")
    subprocess.run([sys.executable, tool] + argv, check=True, timeout=600)


def build():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=16,
                             pad=(1, 1), name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2))
    net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=32,
                             pad=(1, 1), name="conv2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2))
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=64, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=len(CLASSES), name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--per-class", type=int, default=60)
    ap.add_argument("--lr", type=float, default=0.003)
    args = ap.parse_args()

    repo = os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", ".."))
    rs = np.random.RandomState(0)
    work = tempfile.mkdtemp(prefix="ndsb1_")
    img_root = os.path.join(work, "train_imgs")
    write_images(img_root, rs, args.per_class)

    # 1) stratified list + pack via the im2rec CLI (reference gen_img_list
    #    + im2rec.cc step)
    prefix = os.path.join(work, "train")
    im2rec(repo, ["--list", "--recursive", "--shuffle", "1",
                  prefix, img_root])
    im2rec(repo, [prefix, img_root])

    test_root = os.path.join(work, "test_imgs", "unknown")
    os.makedirs(test_root)
    from PIL import Image
    test_labels = []
    for i in range(64):
        ci = rs.randint(0, len(CLASSES))
        test_labels.append(ci)
        Image.fromarray(draw_class(rs, ci), mode="L").convert("RGB").save(
            os.path.join(test_root, "img_%03d.jpg" % i))
    tprefix = os.path.join(work, "test")
    im2rec(repo, ["--list", "--recursive", tprefix,
                  os.path.dirname(test_root)])
    im2rec(repo, [tprefix, os.path.dirname(test_root)])

    # 2) train from the packed records with an lr schedule + clipping
    train_it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", data_shape=(3, IMG, IMG),
        batch_size=args.batch_size, shuffle=True, rand_mirror=True,
        mean_r=60.0, mean_g=60.0, mean_b=60.0,
        std_r=80.0, std_g=80.0, std_b=80.0)
    steps_per_epoch = max(1, (args.per_class * len(CLASSES))
                          // args.batch_size)
    sched = mx.lr_scheduler.MultiFactorScheduler(
        step=[steps_per_epoch * max(1, args.num_epochs // 2)], factor=0.3)
    mod = mx.mod.Module(build(), context=mx.current_context())
    metric = mx.metric.Accuracy()
    mod.fit(train_it, eval_metric=metric, num_epoch=args.num_epochs,
            initializer=mx.initializer.Xavier(),
            optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "clip_gradient": 5.0,
                              "lr_scheduler": sched})
    train_it.reset()
    metric.reset()
    mod.score(train_it, metric)
    acc = metric.get()[1]
    print("train accuracy %.3f" % acc)

    # 3) predict the test set and emit the probability submission
    test_it = mx.io.ImageRecordIter(
        path_imgrec=tprefix + ".rec", data_shape=(3, IMG, IMG),
        batch_size=args.batch_size,
        mean_r=60.0, mean_g=60.0, mean_b=60.0,
        std_r=80.0, std_g=80.0, std_b=80.0)
    probs = mod.predict(test_it).asnumpy()[:64]
    # row order comes from the packed .lst, exactly as the reference's
    # predict_dsb.py/submission_dsb.py pair reads it back
    with open(tprefix + ".lst") as f:
        names = [line.split("\t")[2].strip() for line in f]
    sub_path = os.path.join(work, "submission.csv")
    with open(sub_path, "w", newline="") as f:
        wr = csv.writer(f)
        wr.writerow(["image"] + CLASSES)
        for name, row in zip(names, probs):
            wr.writerow([os.path.basename(name)]
                        + ["%.5f" % p for p in row])
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-4)
    order = [int(os.path.basename(n).split("_")[1].split(".")[0])
             for n in names]
    truth = np.array([test_labels[i] for i in order])
    test_acc = float((probs.argmax(axis=1) == truth).mean())
    print("test accuracy %.3f (submission: %s)" % (test_acc, sub_path))
    assert acc > 0.9 and test_acc > 0.8
    print("ndsb1 ok")


if __name__ == "__main__":
    main()
