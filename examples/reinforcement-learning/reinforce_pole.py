"""REINFORCE policy gradient on a synthetic pole-balance task (mirrors
the scope of reference example/reinforcement-learning/ — dqn/a3c/ddpg
agents; this tree exercises the policy-gradient building blocks:
``pick`` over action probabilities, ``BlockGrad`` on the advantage
input, and a ``MakeLoss`` head driving Module's update loop directly,
an op combination no other example tree touches).

The environment is a linearised cart-pole implemented in numpy (no gym
in the image): state (x, x_dot, theta, theta_dot), two actions pushing
left/right, reward 1 per step until |theta| or |x| leaves bounds.
REINFORCE with a running-baseline should push mean episode length up.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx


class PoleEnv:
    """Euler-integrated inverted pendulum on a cart, numpy only."""

    DT = 0.02
    FORCE = 10.0
    GRAV = 9.8
    MASS_CART = 1.0
    MASS_POLE = 0.1
    LEN = 0.5

    def __init__(self, rs):
        self.rs = rs
        self.reset()

    def reset(self):
        self.s = self.rs.uniform(-0.05, 0.05, 4).astype(np.float32)
        return self.s.copy()

    def step(self, action):
        x, x_dot, th, th_dot = self.s
        force = self.FORCE if action == 1 else -self.FORCE
        total_m = self.MASS_CART + self.MASS_POLE
        pm_len = self.MASS_POLE * self.LEN
        tmp = (force + pm_len * th_dot ** 2 * np.sin(th)) / total_m
        th_acc = (self.GRAV * np.sin(th) - np.cos(th) * tmp) / \
            (self.LEN * (4.0 / 3.0 - self.MASS_POLE * np.cos(th) ** 2
                         / total_m))
        x_acc = tmp - pm_len * th_acc * np.cos(th) / total_m
        self.s = np.array([x + self.DT * x_dot,
                           x_dot + self.DT * x_acc,
                           th + self.DT * th_dot,
                           th_dot + self.DT * th_acc], np.float32)
        done = abs(self.s[0]) > 2.4 or abs(self.s[2]) > 12 * np.pi / 180
        return self.s.copy(), 1.0, done


def build_policy(num_actions=2):
    data = mx.sym.Variable("data")
    act = mx.sym.Variable("action")
    adv = mx.sym.Variable("advantage")
    h = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    h = mx.sym.Activation(h, act_type="tanh")
    logits = mx.sym.FullyConnected(h, num_hidden=num_actions, name="fc2")
    probs = mx.sym.SoftmaxActivation(logits, name="probs")
    # -E[log pi(a|s) * A]; the advantage is data, not a differentiable
    # path — BlockGrad documents that (reference a3c.py stops gradients
    # through the critic's value the same way)
    picked = mx.sym.pick(probs, act, axis=1)
    loss = mx.sym.MakeLoss(
        0.0 - mx.sym.log(picked + 1e-8) * mx.sym.BlockGrad(adv),
        name="pg_loss")
    return mx.sym.Group([loss, mx.sym.BlockGrad(probs)])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--episodes", type=int, default=60)
    ap.add_argument("--batch-episodes", type=int, default=4)
    ap.add_argument("--max-steps", type=int, default=120)
    ap.add_argument("--gamma", type=float, default=0.97)
    ap.add_argument("--lr", type=float, default=0.02)
    args = ap.parse_args()

    rs = np.random.RandomState(7)
    env = PoleEnv(rs)
    sym = build_policy()

    mod = mx.mod.Module(sym, data_names=["data", "action", "advantage"],
                        label_names=[], context=mx.current_context())
    bsz = args.batch_episodes * args.max_steps
    from mxnet_tpu.io import DataDesc, DataBatch
    mod.bind(data_shapes=[DataDesc("data", (bsz, 4)),
                          DataDesc("action", (bsz,)),
                          DataDesc("advantage", (bsz,))],
             label_shapes=None, for_training=True)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr})

    baseline = 0.0
    lengths = []
    n_batches = max(1, args.episodes // args.batch_episodes)
    for it in range(n_batches):
        states, actions, rets, ep_lens = [], [], [], []
        for _ in range(args.batch_episodes):
            s = env.reset()
            ep_s, ep_a, ep_r = [], [], []
            for _ in range(args.max_steps):
                # batch-1 inference rides the same module: a second jit
                # signature, not a rebind (executor.reshape semantics)
                mod.forward(DataBatch(
                    [mx.nd.array(s[None]), mx.nd.zeros((1,)),
                     mx.nd.zeros((1,))], []), is_train=False)
                p = mod.get_outputs()[1].asnumpy()[0]
                a = int(rs.rand() < p[1])
                ep_s.append(s)
                ep_a.append(a)
                s, r, done = env.step(a)
                ep_r.append(r)
                if done:
                    break
            # discounted returns
            g, run = np.zeros(len(ep_r), np.float32), 0.0
            for t in reversed(range(len(ep_r))):
                run = ep_r[t] + args.gamma * run
                g[t] = run
            states += ep_s
            actions += ep_a
            rets += list(g)
            ep_lens.append(len(ep_r))
        lengths.append(float(np.mean(ep_lens)))
        baseline = 0.9 * baseline + 0.1 * float(np.mean(rets))
        adv = np.asarray(rets, np.float32) - baseline
        n = len(states)
        pad = bsz - n
        x = np.concatenate([np.asarray(states, np.float32),
                            np.zeros((pad, 4), np.float32)])
        a = np.concatenate([np.asarray(actions, np.float32),
                            np.zeros(pad, np.float32)])
        ad = np.concatenate([adv, np.zeros(pad, np.float32)])
        mod.forward_backward(DataBatch(
            [mx.nd.array(x), mx.nd.array(a), mx.nd.array(ad)], []))
        mod.update()

    early = np.mean(lengths[:3])
    late = np.mean(lengths[-3:])
    print("episode length: first batches %.1f -> last %.1f" % (early, late))
    assert late > early, "policy gradient did not improve episode length"
    print("reinforce ok")


if __name__ == "__main__":
    main()
