"""Demonstrates the full parallelism menu on a virtual device mesh:
data (dp), sequence (sp via ring attention), tensor (tp), expert (ep via
all_to_all MoE), and pipeline (pp via the GPipe schedule).

These are the new-framework extensions beyond the 2017 reference
(SURVEY.md §2.3 last row); run on a real pod the same code spans chips
over ICI.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python train_moe_pipeline.py
"""
import os
import sys

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402


def main():
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import jax.numpy as jnp
    from mxnet_tpu import parallel

    rs = np.random.RandomState(0)
    E, F = 16, 32

    # --- expert parallelism: MoE FFN over 4 experts -----------------------
    n_exp = 4
    mesh = parallel.make_mesh({"ep": n_exp})
    x = rs.randn(n_exp, 8, E).astype(np.float32)
    out = parallel.moe_ffn(
        jnp.asarray(x),
        jnp.asarray(rs.randn(n_exp, E).astype(np.float32)),
        jnp.asarray(rs.randn(n_exp, F, E).astype(np.float32) * 0.1),
        jnp.asarray(rs.randn(n_exp, E, F).astype(np.float32) * 0.1),
        mesh)
    print("moe_ffn out", out.shape)

    # --- pipeline parallelism: 4 stages, 6 microbatches -------------------
    n_pp = 4
    mesh = parallel.make_mesh({"pp": n_pp})
    w = rs.randn(n_pp, E, E).astype(np.float32) * 0.3
    b = rs.randn(n_pp, E).astype(np.float32) * 0.1
    mb = rs.randn(6, 4, E).astype(np.float32)

    def stage(p, t):
        return jnp.tanh(t @ p["w"] + p["b"])

    out = parallel.pipeline_apply(stage, {"w": jnp.asarray(w),
                                          "b": jnp.asarray(b)},
                                  jnp.asarray(mb), mesh)
    print("pipeline out", out.shape)

    # --- dp x sp x tp: ring attention inside an SPMD train step -----------
    mesh = parallel.make_mesh({"dp": 2, "sp": 2, "tp": 2})
    B, H, S, D = 4, 2, 16, 8
    q = jnp.asarray(rs.randn(B, H, S, D).astype(np.float32))
    out = parallel.ring_attention(q, q, q, mesh, axis_name="sp",
                                  batch_axis_name="dp", causal=True)
    print("ring attention out", out.shape)
    print("OK")


if __name__ == "__main__":
    main()
