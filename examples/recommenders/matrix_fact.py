"""Matrix-factorization recommender (mirrors reference
example/recommenders/ / example/sparse/matrix_factorization.py): user
and item Embedding tables, dot-product score, squared loss. Embedding
gradients are row-sparse — only rows touched by the batch update, the
large-embedding training path SURVEY §2.3 targets."""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=12)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--users", type=int, default=200)
    ap.add_argument("--items", type=int, default=150)
    ap.add_argument("--factors", type=int, default=8)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    # ground-truth low-rank ratings
    u_true = rs.normal(scale=1.0, size=(args.users, args.factors))
    i_true = rs.normal(scale=1.0, size=(args.items, args.factors))
    n = 6000
    u = rs.randint(0, args.users, n)
    i = rs.randint(0, args.items, n)
    r = (u_true[u] * i_true[i]).sum(1) + 0.1 * rs.normal(size=n)

    it = mx.io.NDArrayIter(
        {"user": u.astype(np.float32), "item": i.astype(np.float32)},
        {"score_label": r.astype(np.float32)},
        batch_size=args.batch_size, shuffle=True)

    user = mx.sym.Variable("user")
    item = mx.sym.Variable("item")
    uemb = mx.sym.Embedding(user, input_dim=args.users,
                            output_dim=args.factors, name="user_emb")
    iemb = mx.sym.Embedding(item, input_dim=args.items,
                            output_dim=args.factors, name="item_emb")
    pred = mx.sym.sum(uemb * iemb, axis=1)
    net = mx.sym.LinearRegressionOutput(pred, name="score")

    mod = mx.mod.Module(net, data_names=["user", "item"],
                        label_names=["score_label"],
                        context=mx.current_context())
    mod.fit(it, optimizer="adam",
            optimizer_params={"learning_rate": 0.05,
                              "rescale_grad": 1.0 / args.batch_size},
            num_epoch=args.num_epochs, eval_metric="mse")

    it.reset()
    mse = dict(mod.score(it, mx.metric.MSE()))["mse"]
    rmse = float(np.sqrt(mse))
    base = float(np.sqrt(np.mean((r - r.mean()) ** 2)))
    print("rmse %.4f (predict-mean baseline %.4f)" % (rmse, base))
    assert rmse < base * 0.6, "matrix factorization failed to learn"


if __name__ == "__main__":
    main()
