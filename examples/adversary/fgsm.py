"""Fast-gradient-sign adversarial examples (mirrors reference
example/adversary/: train a classifier, take the loss gradient w.r.t.
the INPUT via autograd, perturb, re-evaluate)."""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


def make_data(rs, n=512, dim=16, classes=4):
    centers = rs.uniform(-2, 2, size=(classes, dim)).astype(np.float32)
    y = rs.randint(0, classes, n)
    x = centers[y] + 0.3 * rs.normal(size=(n, dim)).astype(np.float32)
    return x.astype(np.float32), y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epsilon", type=float, default=1.0)
    ap.add_argument("--iters", type=int, default=150)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    x, y = make_data(rs)
    xs, ys = mx.nd.array(x), mx.nd.array(y.astype(np.float32))

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"))
        net.add(nn.Dense(4))
    net.initialize(mx.initializer.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(args.iters):
        with mx.autograd.record():
            loss = ce(net(xs), ys).mean()
        loss.backward()
        trainer.step(x.shape[0])

    clean_acc = float((net(xs).asnumpy().argmax(1) == y).mean())

    # FGSM: gradient of the loss w.r.t. the INPUT
    xadv_in = mx.nd.array(x)
    xadv_in.attach_grad()
    with mx.autograd.record():
        loss = ce(net(xadv_in), ys).sum()
    loss.backward()
    x_adv = mx.nd.array(x + args.epsilon
                        * np.sign(xadv_in.grad.asnumpy()))
    adv_acc = float((net(x_adv).asnumpy().argmax(1) == y).mean())

    print("clean accuracy %.3f adversarial accuracy %.3f (eps=%.2f)"
          % (clean_acc, adv_acc, args.epsilon))
    assert clean_acc > 0.9, "classifier failed to train"
    assert adv_acc < clean_acc - 0.1, "FGSM perturbation had no effect"


if __name__ == "__main__":
    main()
