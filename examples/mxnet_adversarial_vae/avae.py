"""Adversarial variational autoencoder (mirrors reference
example/mxnet_adversarial_vae/ — a VAE whose decoder doubles as a GAN
generator: the encoder/decoder train on ELBO while a discriminator
scores decoded samples, and its gradient flows back into the decoder).

Three gluon networks trained jointly with autograd on a synthetic 2-D
mixture; exercises the three-network, two-optimizer training loop with
a gradient path THROUGH a frozen discriminator — a composition no
other tree runs (gan/ trains two nets, vae/ trains one).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

LATENT = 4


def real_batch(rs, n):
    centers = np.array([[2, 0], [-2, 0], [0, 2], [0, -2]], np.float32)
    c = centers[rs.randint(0, 4, n)]
    return c + 0.15 * rs.normal(size=(n, 2)).astype(np.float32)


def mlp(widths, out):
    net = nn.HybridSequential()
    with net.name_scope():
        for w in widths:
            net.add(nn.Dense(w, activation="relu"))
        net.add(nn.Dense(out))
    return net


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=400)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--adv-weight", type=float, default=0.05)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    np.random.seed(0)
    mx.random.seed(0)

    enc = mlp([32], 2 * LATENT)          # -> (mu, logvar)
    dec = mlp([32, 32], 2)
    disc = mlp([32, 32], 1)
    for net in (enc, dec, disc):
        net.initialize(mx.initializer.Xavier())
        net.hybridize()
    vae_tr = gluon.Trainer(
        dict(list(enc.collect_params().items())
             + list(dec.collect_params().items())),
        "adam", {"learning_rate": 3e-3})
    d_tr = gluon.Trainer(disc.collect_params(), "adam",
                         {"learning_rate": 1e-3})
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    b = args.batch_size
    ones, zeros = mx.nd.ones((b,)), mx.nd.zeros((b,))
    recon_hist, fool_hist = [], []
    for it in range(args.iters):
        xr = mx.nd.array(real_batch(rs, b))

        # -- discriminator: real decoded-from-prior vs dataset ----------
        z_prior = mx.nd.array(rs.normal(size=(b, LATENT))
                              .astype(np.float32))
        with autograd.record():
            fake = dec(z_prior)
            ld = bce(disc(xr), ones) + bce(disc(fake.detach()), zeros)
        ld.backward()
        d_tr.step(b)

        # -- VAE: ELBO + adversarial term through the FROZEN D ----------
        eps = mx.nd.array(rs.normal(size=(b, LATENT)).astype(np.float32))
        with autograd.record():
            h = enc(xr)
            # -4 shift: posterior starts tight (std ~0.14) so the
            # decoder sees signal through the noise from step one —
            # without it the unit-variance init collapses the latent
            mu, logvar = h[:, :LATENT], h[:, LATENT:] - 4.0
            z = mu + eps * mx.nd.exp(0.5 * logvar)
            xh = dec(z)
            recon = mx.nd.mean(mx.nd.square(xh - xr), axis=1)
            kl = -0.5 * mx.nd.mean(
                1 + logvar - mx.nd.square(mu) - mx.nd.exp(logvar), axis=1)
            fool = bce(disc(dec(z_prior)), ones)   # grads stop at disc's
            loss = recon + 0.05 * kl + args.adv_weight * fool  # params
        loss.backward()
        vae_tr.step(b)     # disc params NOT in this trainer: frozen

        recon_hist.append(float(recon.mean().asnumpy()))
        fool_hist.append(float(fool.mean().asnumpy()))

    early_r = np.mean(recon_hist[:20])
    late_r = np.mean(recon_hist[-20:])
    late_fool = np.mean(fool_hist[-20:])
    # at the adversarial equilibrium D cannot separate decoded samples
    # from data and the fooling BCE sits near ln2~0.69; a decoder D has
    # beaten outright shows 2-5 here (observed before the logvar-shift
    # fix), so bound it rather than demand sub-0.69
    print("recon %.4f -> %.4f | fool-bce %.3f" % (early_r, late_r,
                                                  late_fool))
    assert late_r < 0.5 * early_r, "reconstruction did not improve"
    assert late_fool < 1.5, \
        "adversarial path dead: D separates decoded samples outright"
    samples = dec(mx.nd.array(rs.normal(size=(256, LATENT))
                              .astype(np.float32))).asnumpy()
    spread = samples.std(axis=0)
    print("sample std %s" % np.round(spread, 3))
    assert spread.max() > 0.5, "decoder collapsed to a point"
    print("avae ok")


if __name__ == "__main__":
    main()
