"""Time-major fused-RNN language model (mirrors reference
example/rnn-time-major/rnn_cell_demo.py — a PTB-style LM built on the
fused ``sym.RNN`` op consuming (time, batch, feature), fed by a
time-major bucketed iterator).

Time-major is the fused kernel's native layout (the reference notes it
is "5%-20% faster" than batch-major there; here it skips the NTC<->TNC
swapaxes around the ``lax.scan`` over time). This tree is the only one
driving ``FusedRNNCell``/the fused RNN op through BucketingModule in
TNC layout end to end.

Synthetic next-token corpus (token+1 mod vocab) keeps it egress-free;
perplexity must approach 1 because the sequence rule is deterministic.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.rnn import BucketSentenceIter, FusedRNNCell


def synthetic_sentences(num=400, vocab=40, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(num):
        length = rng.randint(5, 30)
        start = rng.randint(0, vocab)
        out.append([(start + t) % vocab for t in range(length)])
    return out


def sym_gen_factory(vocab, num_hidden, num_embed, num_layers):
    def sym_gen(seq_len):
        data = mx.sym.Variable("data")            # (T, N) time-major
        label = mx.sym.Variable("softmax_label")  # (T, N)
        embed = mx.sym.Embedding(data=data, input_dim=vocab,
                                 output_dim=num_embed, name="embed")
        cell = FusedRNNCell(num_hidden=num_hidden, num_layers=num_layers,
                            mode="lstm", prefix="lstm_")
        # TNC in, TNC out — no transposes anywhere in the graph
        outputs, _ = cell.unroll(seq_len, inputs=embed, layout="TNC",
                                 merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, num_hidden))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=vocab,
                                     name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(data=pred, label=label, use_ignore=True,
                                    ignore_label=-1, name="softmax")
        return pred, ("data",), ("softmax_label",)
    return sym_gen


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--num-embed", type=int, default=32)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--num-epochs", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=40)
    args = ap.parse_args()

    # seed every RNG the path touches: framework init, numpy + stdlib
    # shuffles inside BucketSentenceIter.reset()
    mx.random.seed(2)
    np.random.seed(2)
    import random as _random
    _random.seed(2)
    buckets = [10, 20, 30]
    train = BucketSentenceIter(synthetic_sentences(vocab=args.vocab),
                               args.batch_size, buckets=buckets,
                               layout="TN")
    assert train.provide_data[0].shape[0] == buckets[-1], \
        "iterator must be time-major"

    sym_gen = sym_gen_factory(args.vocab, args.num_hidden, args.num_embed,
                              args.num_layers)
    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=train.default_bucket_key,
                                 context=mx.current_context())
    mod.fit(train, eval_metric=mx.metric.Perplexity(ignore_label=-1),
            optimizer="adam",
            optimizer_params={"learning_rate": 0.01,
                              "rescale_grad": 1.0 / args.batch_size},
            initializer=mx.initializer.Xavier(),
            num_epoch=args.num_epochs)
    train.reset()
    score = dict(mod.score(train, mx.metric.Perplexity(ignore_label=-1)))
    ppl = list(score.values())[0]
    print("final train perplexity: %.3f" % ppl)
    assert ppl < 1.8, "deterministic sequence should be nearly memorised"
    print("time-major ok")


if __name__ == "__main__":
    main()
