"""Bayesian regression with Stochastic Gradient Langevin Dynamics
(mirrors the scope of reference example/bayesian-methods/ — bdk_demo.py
trains with the ``sgld`` optimizer and averages posterior samples; this
tree is the only one exercising the SGLD optimizer end to end).

A small MLP regresses y = sin(3x) + eps. After burn-in, parameter
snapshots taken every few SGLD steps are posterior samples; averaging
their predictions (the posterior predictive mean) must beat the last
single sample on held-out RMSE, and the predictive std must be larger
where there is no training data — the classic Bayesian sanity checks.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx


def build():
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    h = mx.sym.Activation(h, act_type="tanh")
    h = mx.sym.FullyConnected(h, num_hidden=1, name="fc2")
    return mx.sym.LinearRegressionOutput(h, name="lro")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=60)
    ap.add_argument("--burn-in", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=5e-3)
    args = ap.parse_args()

    # SGLD noise rides the framework RNG; param init rides global
    # np.random - seed both for a reproducible run
    mx.random.seed(11)
    np.random.seed(11)
    rs = np.random.RandomState(3)
    # train only on [-1, 0] u [0.5, 1]: the gap probes epistemic
    # uncertainty
    x_tr = np.concatenate([rs.uniform(-1, 0, 96),
                           rs.uniform(0.5, 1, 64)]).astype(np.float32)
    y_tr = (np.sin(3 * x_tr) + 0.05 * rs.normal(size=x_tr.shape)
            ).astype(np.float32)
    # test past the data's right edge: extrapolation (x > 1) is where
    # posterior disagreement must show up
    x_te = np.linspace(-1, 2, 151).astype(np.float32)
    y_te = np.sin(3 * x_te).astype(np.float32)

    it = mx.io.NDArrayIter(x_tr[:, None], y_tr[:, None],
                           batch_size=args.batch_size, shuffle=True,
                           label_name="lro_label")
    mod = mx.mod.Module(build(), label_names=["lro_label"],
                        context=mx.current_context())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=True)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgld",
                       optimizer_params={"learning_rate": args.lr,
                                         "wd": 1e-4})

    snapshots = []
    from mxnet_tpu.io import DataBatch
    for epoch in range(args.num_epochs):
        it.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
        if epoch >= args.burn_in and epoch % 3 == 0:
            arg_p, _ = mod.get_params()
            snapshots.append({k: v.asnumpy() for k, v in arg_p.items()})

    def predict(params, x):
        h = np.tanh(x[:, None] @ params["fc1_weight"].T
                    + params["fc1_bias"])
        return (h @ params["fc2_weight"].T + params["fc2_bias"])[:, 0]

    preds = np.stack([predict(p, x_te) for p in snapshots])
    post_mean = preds.mean(0)
    post_std = preds.std(0)
    interp = (x_te >= -1) & (x_te <= 1)
    rmse_mean = float(np.sqrt(np.mean((post_mean - y_te)[interp] ** 2)))
    rmse_last = float(np.sqrt(np.mean((preds[-1] - y_te)[interp] ** 2)))
    off = x_te > 1.2
    seen = (x_te < -0.05)
    std_off = float(post_std[off].mean())
    std_seen = float(post_std[seen].mean())
    print("posterior samples=%d rmse(post-mean)=%.4f rmse(last)=%.4f"
          % (len(snapshots), rmse_mean, rmse_last))
    print("predictive std: off-data=%.4f seen=%.4f" % (std_off, std_seen))
    assert rmse_mean <= rmse_last * 1.05, "averaging should not hurt"
    assert std_off > std_seen, "uncertainty should rise off-data"
    print("sgld ok")


if __name__ == "__main__":
    main()
