"""Bucketing LSTM (mirrors reference example/rnn/bucketing) —
variable-length sequence training via BucketingModule + BucketSentenceIter,
one compiled executor per bucket sharing parameters.
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.rnn import BucketSentenceIter, LSTMCell, SequentialRNNCell


def synthetic_sentences(num=400, vocab=50, seed=0):
    """Sentences of varying length whose next-token is (token+1) mod vocab —
    trivially learnable, exercises the bucketing machinery."""
    rng = np.random.RandomState(seed)
    sentences = []
    for _ in range(num):
        length = rng.randint(5, 35)
        start = rng.randint(0, vocab)
        sentences.append([(start + t) % vocab for t in range(length)])
    return sentences


def sym_gen_factory(vocab, num_hidden, num_embed, num_layers):
    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data=data, input_dim=vocab,
                                 output_dim=num_embed, name="embed")
        stack = SequentialRNNCell()
        for i in range(num_layers):
            stack.add(LSTMCell(num_hidden=num_hidden, prefix="lstm_l%d_" % i))
        outputs, _ = stack.unroll(seq_len, inputs=embed, merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, num_hidden))
        pred = mx.sym.FullyConnected(data=pred, num_hidden=vocab, name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(data=pred, label=label, use_ignore=True,
                                    ignore_label=-1, name="softmax")
        return pred, ("data",), ("softmax_label",)
    return sym_gen


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-hidden", type=int, default=64)
    parser.add_argument("--num-embed", type=int, default=32)
    parser.add_argument("--num-layers", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=16)
    parser.add_argument("--num-epochs", type=int, default=3)
    parser.add_argument("--vocab", type=int, default=50)
    args = parser.parse_args()

    buckets = [10, 20, 30, 40]
    train = BucketSentenceIter(synthetic_sentences(vocab=args.vocab),
                               args.batch_size, buckets=buckets)
    sym_gen = sym_gen_factory(args.vocab, args.num_hidden, args.num_embed,
                              args.num_layers)
    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=train.default_bucket_key,
                                 context=mx.current_context())
    mod.fit(train, eval_metric=mx.metric.Perplexity(ignore_label=-1),
            optimizer="adam",
            optimizer_params={"learning_rate": 0.01,
                              "rescale_grad": 1.0 / args.batch_size},
            initializer=mx.initializer.Xavier(),
            num_epoch=args.num_epochs)
    train.reset()
    score = dict(mod.score(train, mx.metric.Perplexity(ignore_label=-1)))
    print("final train perplexity: %.3f" % list(score.values())[0])


if __name__ == "__main__":
    main()
