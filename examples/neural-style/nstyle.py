"""Neural style transfer (mirrors reference example/neural-style/
nstyle.py — optimise the INPUT IMAGE against content + Gram-matrix
style losses taken from conv-net feature maps).

Zero-egress twist: the reference downloads VGG-19 weights; here the
feature extractor is a small random-weight conv stack (random
projections preserve enough feature structure for the optimisation
mechanics — the point of the example is the machinery, which no other
tree exercises: an executor with grad_req="write" on the DATA input
only (args_grad for pixels, "null" for weights), Gram matrices via
Reshape + batch_dot with a transpose, multiple MakeLoss heads driven
through one backward, and a hand-rolled Adam step on the image).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx


def extractor(nf=(8, 16)):
    """Conv stack exposing relu feature maps (style) and the deepest
    map (content) — the reference's style/content symbol split
    (model_vgg19.py get_symbol style/content groups)."""
    data = mx.sym.Variable("data")
    x = data
    style_maps = []
    for i, f in enumerate(nf):
        x = mx.sym.Convolution(x, kernel=(3, 3), pad=(1, 1), num_filter=f,
                               name="conv%d" % i)
        x = mx.sym.Activation(x, act_type="relu")
        style_maps.append(x)
        x = mx.sym.Pooling(x, pool_type="avg", kernel=(2, 2), stride=(2, 2))
    return style_maps, x


def gram(sym, shape):
    """Gram matrix of a (1, C, H, W) feature map: (C, H*W) @ its own
    transpose, normalised (reference nstyle.py style_gram)."""
    c = shape[1]
    n = shape[2] * shape[3]
    flat = mx.sym.Reshape(sym, shape=(c, n))
    g = mx.sym.dot(flat, flat, transpose_b=True)
    return g / float(c * n)


def build(img_shape):
    style_maps, content_map = extractor()
    # infer feature shapes once to size the gram matrices
    probe = mx.sym.Group(style_maps + [content_map])
    _, out_shapes, _ = probe.infer_shape(data=img_shape)
    losses = []
    for i, (s, sh) in enumerate(zip(style_maps, out_shapes[:-1])):
        target = mx.sym.Variable("style_gram%d" % i)
        losses.append(mx.sym.MakeLoss(
            mx.sym.sum(mx.sym.square(gram(s, sh) - target)),
            name="style_loss%d" % i))
    content_target = mx.sym.Variable("content_map")
    losses.append(mx.sym.MakeLoss(
        5.0 * mx.sym.mean(mx.sym.square(content_map - content_target)),
        name="content_loss"))
    return mx.sym.Group(losses), out_shapes


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=120)
    ap.add_argument("--size", type=int, default=32)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    img_shape = (1, 3, args.size, args.size)
    # synthetic "photographs": smooth content image, high-frequency style
    gx, gy = np.meshgrid(np.linspace(-1, 1, args.size),
                         np.linspace(-1, 1, args.size))
    content = np.stack([gx, gy, gx * gy])[None].astype(np.float32)
    style = rs.uniform(-1, 1, img_shape).astype(np.float32)
    style = (style + np.roll(style, 1, axis=3)) / 2  # local correlation

    net, feat_shapes = build(img_shape)
    ctx = mx.current_context()
    arg_names = net.list_arguments()
    shape_kwargs = {"data": img_shape}
    for i, sh in enumerate(feat_shapes[:-1]):
        shape_kwargs["style_gram%d" % i] = (sh[1], sh[1])
    shape_kwargs["content_map"] = feat_shapes[-1]
    arg_shapes, _, _ = net.infer_shape(**shape_kwargs)
    args_dict = {}
    grads_dict = {}
    reqs = {}
    for name, sh in zip(arg_names, arg_shapes):
        args_dict[name] = mx.nd.array(rs.normal(0, 0.3, sh)
                                      .astype(np.float32)) \
            if "weight" in name else mx.nd.zeros(sh)
        if name == "data":
            grads_dict[name] = mx.nd.zeros(sh)
            reqs[name] = "write"
        else:
            reqs[name] = "null"
    exe = net.bind(ctx, args_dict, args_grad=grads_dict, grad_req=reqs)

    # record the style grams and content map as loss-head constants: a
    # second executor over the extractor alone (shared weight NDArrays)
    # reads the internal feature maps (reference nstyle.py does the same
    # with separate style/content executors)
    ext_syms, content_sym = extractor()
    ext = mx.sym.Group(ext_syms + [content_sym])
    ext_args = {n: args_dict[n] for n in ext.list_arguments()}
    ext_exe = ext.bind(ctx, ext_args, args_grad=None, grad_req="null")

    def feats(img):
        ext_args["data"][:] = img
        outs = [o.asnumpy() for o in ext_exe.forward(is_train=False)]
        grams = []
        for f in outs[:-1]:
            c = f.shape[1]
            n = f.shape[2] * f.shape[3]
            flat = f.reshape(c, n)
            grams.append(flat @ flat.T / float(c * n))
        return grams, outs[-1]

    style_grams, _ = feats(style)
    _, content_map = feats(content)
    for i, g in enumerate(style_grams):
        args_dict["style_gram%d" % i][:] = g
    args_dict["content_map"][:] = content_map

    # optimise the image with Adam (reference uses lbfgs/sgd variants)
    img = rs.uniform(-0.1, 0.1, img_shape).astype(np.float32)
    m = np.zeros(img_shape, np.float32)
    v = np.zeros(img_shape, np.float32)
    lr, b1, b2, eps = 0.05, 0.9, 0.999, 1e-8
    first = last = None
    for t in range(1, args.iters + 1):
        args_dict["data"][:] = img
        outs = exe.forward(is_train=True)
        loss = sum(float(o.asnumpy()) for o in outs)
        exe.backward()
        g = grads_dict["data"].asnumpy()
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        mh = m / (1 - b1 ** t)
        vh = v / (1 - b2 ** t)
        img = img - lr * mh / (np.sqrt(vh) + eps)
        if first is None:
            first = loss
        last = loss
        if t % 20 == 0:
            print("iter %d loss %.4f" % (t, loss))

    print("loss %.3f -> %.3f" % (first, last))
    assert last < 0.2 * first, (first, last)
    print("NSTYLE_OK")


if __name__ == "__main__":
    main()
