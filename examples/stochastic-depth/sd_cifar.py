"""Stochastic-depth residual training (mirrors reference
example/stochastic-depth/sd_cifar10.py — residual blocks that are
randomly DROPPED during training, with a linearly-decaying survival
schedule, and rescaled at inference).

Gluon-imperative implementation: the per-batch coin flips are host
randomness driving which compiled branch executes — the TPU-friendly
way to express data-INdependent stochastic architecture (each
configuration is a cached jit signature; no dynamic control flow inside
the program). Exercises per-block survival bookkeeping, train-vs-eval
scaling, and hybrid blocks whose forward changes across calls — a
pattern no other tree has.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


class SDBlock(gluon.HybridBlock):
    """Residual block with survival probability p: train time executes
    identity with prob (1-p) (the whole branch skipped — that is the
    compute saving the paper reports); eval time scales the branch by p.
    """

    def __init__(self, channels, p_survive, **kwargs):
        super().__init__(**kwargs)
        self.p = p_survive
        with self.name_scope():
            self.body = nn.HybridSequential(prefix="")
            self.body.add(nn.Dense(channels, activation="relu"))
            self.body.add(nn.Dense(channels))
        self._rs = np.random.RandomState(hash(self.prefix) % (2 ** 31))
        self.training = True

    def hybrid_forward(self, F, x):
        if self.training:
            if self._rs.rand() < self.p:
                return x + self.body(x)     # block survives
            return x                        # block dropped: zero compute
        # inference: expected-value rescaling of the residual branch
        return x + self.p * self.body(x)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=15)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--num-blocks", type=int, default=6)
    ap.add_argument("--p-final", type=float, default=0.5)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    DIM, NCLASS = 32, 4
    protos = rs.normal(0, 1.2, (NCLASS, DIM)).astype(np.float32)
    y = rs.randint(0, NCLASS, 1024)
    x = (protos[y] + 0.4 * rs.normal(size=(1024, DIM))).astype(np.float32)

    net = nn.HybridSequential()
    blocks = []
    with net.name_scope():
        net.add(nn.Dense(DIM, activation="relu"))
        for i in range(args.num_blocks):
            # linear decay: first block ~always survives, last at p_final
            p = 1.0 - (1.0 - args.p_final) * i / max(args.num_blocks - 1, 1)
            blk = SDBlock(DIM, p)
            blocks.append(blk)
            net.add(blk)
        net.add(nn.Dense(NCLASS))
    net.initialize(mx.initializer.Xavier())
    # complete deferred shapes with every branch live (a dropped block
    # would leave its params shapeless for the first backward)
    for b in blocks:
        b.training = False
    net(mx.nd.ones((1, DIM)))
    for b in blocks:
        b.training = True

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    data = mx.nd.array(x)
    label = mx.nd.array(y.astype(np.float32))
    n = x.shape[0]
    survived_counts = []
    for epoch in range(args.num_epochs):
        perm = rs.permutation(n)
        tot = 0.0
        for s in range(0, n, args.batch_size):
            idx = perm[s:s + args.batch_size]
            xb = mx.nd.array(x[idx])
            yb = mx.nd.array(y[idx].astype(np.float32))
            with mx.autograd.record():
                out = net(xb)
                loss = loss_fn(out, yb)
            loss.backward()
            trainer.step(xb.shape[0])
            tot += float(loss.mean().asnumpy())
        survived_counts.append(sum(b._rs.rand() < b.p for b in blocks))
        if epoch % 5 == 0:
            print("epoch %d mean loss %.4f" % (epoch, tot * args.batch_size / n))

    # eval: deterministic rescaled-depth network
    for b in blocks:
        b.training = False
    pred = np.argmax(net(data).asnumpy(), axis=1)
    acc = float((pred == y).mean())
    print("eval accuracy %.4f" % acc)
    assert acc > 0.9, acc
    # sanity: the schedule actually drops blocks during training
    assert any(c < args.num_blocks for c in survived_counts), survived_counts
    print("STOCHASTIC_DEPTH_OK")


if __name__ == "__main__":
    main()
