"""MLP autoencoder (mirrors reference example/autoencoder/ — the DEC
pretraining stage: encoder/decoder stack trained on reconstruction).
Synthetic data keeps it runnable in a zero-egress environment."""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx


def build(dims):
    data = mx.sym.Variable("data")
    x = data
    for i, d in enumerate(dims[1:]):           # encoder
        x = mx.sym.FullyConnected(x, num_hidden=d, name="enc%d" % i)
        x = mx.sym.Activation(x, act_type="relu")
    for i, d in enumerate(reversed(dims[:-1])):  # decoder
        x = mx.sym.FullyConnected(x, num_hidden=d, name="dec%d" % i)
        if i < len(dims) - 2:
            x = mx.sym.Activation(x, act_type="relu")
    return mx.sym.LinearRegressionOutput(x, data, name="rec")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=12)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--dim", type=int, default=32)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    # data living on a low-dimensional manifold: reconstruction is learnable
    basis = rs.normal(size=(4, args.dim)).astype(np.float32)
    codes = rs.normal(size=(512, 4)).astype(np.float32)
    x = codes @ basis + 0.05 * rs.normal(size=(512, args.dim)).astype(
        np.float32)

    it = mx.io.NDArrayIter(x, x[:, 0], batch_size=args.batch_size,
                           shuffle=True)
    net = build([args.dim, 24, 8])
    mod = mx.mod.Module(net, data_names=["data"], label_names=[],
                        context=mx.current_context())
    mod.bind(data_shapes=it.provide_data)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 1e-2})

    first = last = None
    for epoch in range(args.num_epochs):
        it.reset()
        se, n = 0.0, 0
        for batch in it:
            mod.forward(batch, is_train=True)
            rec = mod.get_outputs()[0].asnumpy()
            xb = batch.data[0].asnumpy()
            se += float(((rec - xb) ** 2).sum())
            n += xb.size
            mod.backward()
            mod.update()
        mse = se / n
        if first is None:
            first = mse
        last = mse
        print("epoch %d reconstruction mse %.5f" % (epoch, mse))
    print("final mse %.5f (from %.5f)" % (last, first))
    assert last < first * 0.5, "autoencoder did not learn"


if __name__ == "__main__":
    main()
