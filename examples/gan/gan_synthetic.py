"""Minimal GAN (mirrors reference example/gan/gan_mnist.py training
loop: alternate D on real/fake, then G through D) on a synthetic 2-D
mixture so it runs without datasets."""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


def real_batch(rs, n):
    # ring of 4 gaussians
    centers = np.array([[2, 0], [-2, 0], [0, 2], [0, -2]], np.float32)
    c = centers[rs.randint(0, 4, n)]
    return c + 0.15 * rs.normal(size=(n, 2)).astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=300)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--latent", type=int, default=8)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    np.random.seed(0)        # initializer draws use the global RNGs:
    mx.random.seed(0)        # seed both so the smoke sweep is repeatable
    G = nn.HybridSequential()
    with G.name_scope():
        G.add(nn.Dense(32, activation="relu"))
        G.add(nn.Dense(32, activation="relu"))
        G.add(nn.Dense(2))
    D = nn.HybridSequential()
    with D.name_scope():
        D.add(nn.Dense(32, activation="relu"))
        D.add(nn.Dense(32, activation="relu"))
        D.add(nn.Dense(1))
    for net in (G, D):
        net.initialize(mx.initializer.Xavier())
        net.hybridize()
    gt = gluon.Trainer(G.collect_params(), "adam", {"learning_rate": 1e-3})
    dt = gluon.Trainer(D.collect_params(), "adam", {"learning_rate": 1e-3})
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    ones = mx.nd.ones((args.batch_size,))
    zeros_l = mx.nd.zeros((args.batch_size,))
    d_loss = g_loss = None
    for it in range(args.iters):
        z = mx.nd.array(rs.normal(size=(args.batch_size, args.latent))
                        .astype(np.float32))
        real = mx.nd.array(real_batch(rs, args.batch_size))
        # D step
        with mx.autograd.record():
            fake = G(z)
            ld = bce(D(real), ones) + bce(D(fake.detach()), zeros_l)
            ld = ld.mean()
        ld.backward()
        dt.step(args.batch_size)
        # G step
        with mx.autograd.record():
            lg = bce(D(G(z)), ones).mean()
        lg.backward()
        gt.step(args.batch_size)
        d_loss, g_loss = float(ld.asnumpy()), float(lg.asnumpy())
        if it % 100 == 0:
            print("iter %d d_loss %.4f g_loss %.4f" % (it, d_loss, g_loss))

    # generated samples should land near the mixture (mean radius ~2)
    z = mx.nd.array(rs.normal(size=(256, args.latent)).astype(np.float32))
    samples = G(z).asnumpy()
    radii = np.linalg.norm(samples, axis=1)
    print("final d_loss %.4f g_loss %.4f mean_radius %.3f"
          % (d_loss, g_loss, float(radii.mean())))
    assert 0.8 < radii.mean() < 3.5, "generator collapsed away from data"


if __name__ == "__main__":
    main()
