"""Variational autoencoder (mirrors reference example/vae/VAE.py — the
symbolic VAE: encoder -> (mu, logvar) -> reparameterised sample ->
decoder, trained on Bernoulli reconstruction + KL with MakeLoss).

Synthetic data on a low-dimensional manifold keeps it runnable with
zero egress. Exercises: the reparameterisation trick with an epsilon
DATA input (reference VAE.py feeds eps the same way — random inside
the graph would break the deterministic executor contract), exp/square
elementwise chains, MakeLoss heads combined with Group, and a
multi-output executor where only loss heads produce gradients.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx


def build(ndim, nhid, nz):
    data = mx.sym.Variable("data")
    eps = mx.sym.Variable("eps")                  # N(0,1) sample, fed as data
    h = mx.sym.FullyConnected(data, num_hidden=nhid, name="enc1")
    h = mx.sym.Activation(h, act_type="tanh")
    mu = mx.sym.FullyConnected(h, num_hidden=nz, name="mu")
    logvar = mx.sym.FullyConnected(h, num_hidden=nz, name="logvar")
    z = mu + mx.sym.exp(0.5 * logvar) * eps       # reparameterisation
    d = mx.sym.FullyConnected(z, num_hidden=nhid, name="dec1")
    d = mx.sym.Activation(d, act_type="tanh")
    y = mx.sym.FullyConnected(d, num_hidden=ndim, name="dec2")
    # Gaussian reconstruction + analytic KL(q||N(0,1)), one scalar loss
    rec = mx.sym.sum(mx.sym.square(y - data), axis=1)
    kl = -0.5 * mx.sym.sum(1 + logvar - mx.sym.square(mu)
                           - mx.sym.exp(logvar), axis=1)
    loss = mx.sym.MakeLoss(mx.sym.mean(rec + 0.1 * kl), name="vae_loss")
    # expose the reconstruction too (BlockGrad: monitoring head only)
    return mx.sym.Group([loss, mx.sym.BlockGrad(y)])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=15)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--dim", type=int, default=24)
    ap.add_argument("--nz", type=int, default=4)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    basis = rs.normal(size=(args.nz, args.dim)).astype(np.float32)
    codes = rs.normal(size=(768, args.nz)).astype(np.float32)
    x = codes @ basis + 0.05 * rs.normal(size=(768, args.dim)).astype(
        np.float32)

    mod = mx.mod.Module(build(args.dim, 32, args.nz),
                        data_names=["data", "eps"], label_names=[],
                        context=mx.current_context())
    it = mx.io.NDArrayIter(
        {"data": x, "eps": rs.normal(size=(768, args.nz)).astype(np.float32)},
        batch_size=args.batch_size, shuffle=False)
    mod.bind(data_shapes=it.provide_data)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 1e-2})

    first = last = None
    for epoch in range(args.num_epochs):
        # fresh eps every epoch — the stochastic part of the estimator
        it = mx.io.NDArrayIter(
            {"data": x,
             "eps": rs.normal(size=(768, args.nz)).astype(np.float32)},
            batch_size=args.batch_size, shuffle=False)
        tot = n = 0.0
        for batch in it:
            mod.forward(batch, is_train=True)
            tot += float(mod.get_outputs()[0].asnumpy())
            n += 1
            mod.backward()
            mod.update()
        loss = tot / n
        if first is None:
            first = loss
        last = loss
        print("epoch %d elbo-loss %.4f" % (epoch, loss))

    print("loss %.3f -> %.3f" % (first, last))
    assert last < 0.5 * first, (first, last)
    # reconstruction head: decode with eps=0 must approximate the input
    it0 = mx.io.NDArrayIter(
        {"data": x, "eps": np.zeros((768, args.nz), np.float32)},
        batch_size=args.batch_size, shuffle=False)
    se = n = 0.0
    for batch in it0:
        mod.forward(batch, is_train=False)
        rec = mod.get_outputs()[1].asnumpy()
        xb = batch.data[0].asnumpy()
        se += float(((rec - xb) ** 2).mean()) * xb.shape[0]
        n += xb.shape[0]
    mse = se / n
    var = float(x.var())
    print("recon mse %.4f (data var %.4f)" % (mse, var))
    assert mse < 0.5 * var, (mse, var)
    print("VAE_OK")


if __name__ == "__main__":
    main()
