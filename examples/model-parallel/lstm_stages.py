"""Model parallelism (mirrors reference example/model-parallel/ — the
8-GPU LSTM with per-layer Context placement).

TPU-native design: instead of per-layer `Context` assignment with copy
nodes (graph_executor.cc:318-440), layers are sharded over a
`jax.sharding.Mesh` "stage" axis with explicit sharding annotations —
XLA inserts the cross-device transfers that the reference's
cross_device_copy op did by hand.
"""
import argparse

import numpy as np


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-stages", type=int, default=4)
    parser.add_argument("--hidden", type=int, default=64)
    parser.add_argument("--seq-len", type=int, default=16)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--cpu-mesh", action=argparse.BooleanOptionalAction,
                        default=True,
                        help="run on a virtual CPU mesh; --no-cpu-mesh uses "
                             "the attached accelerator devices")
    args = parser.parse_args()

    if args.cpu_mesh:
        import os
        os.environ.setdefault(
            "XLA_FLAGS",
            "--xla_force_host_platform_device_count=%d" % args.num_stages)
        import jax
        jax.config.update("jax_platforms", "cpu")

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from mxnet_tpu.parallel import make_mesh

    mesh = make_mesh({"stage": args.num_stages})
    H, T, N = args.hidden, args.seq_len, args.batch_size
    rng = np.random.RandomState(0)

    # one LSTM layer per stage: weights laid out (stage, ...) and sharded
    # along the stage axis — each device owns exactly one layer's weights
    wx = jnp.asarray(rng.normal(scale=0.1,
                                size=(args.num_stages, H, 4 * H)))
    wh = jnp.asarray(rng.normal(scale=0.1,
                                size=(args.num_stages, H, 4 * H)))
    b = jnp.zeros((args.num_stages, 4 * H))
    x = jnp.asarray(rng.normal(size=(T, N, H)).astype(np.float32))

    def lstm_layer(x_seq, wx_l, wh_l, b_l):
        def step(carry, xt):
            h, c = carry
            gates = xt @ wx_l + h @ wh_l + b_l
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h = jax.nn.sigmoid(o) * jnp.tanh(c)
            return (h, c), h
        init = (jnp.zeros((x_seq.shape[1], H)), jnp.zeros((x_seq.shape[1], H)))
        _, out = jax.lax.scan(step, init, x_seq)
        return out

    def stacked(x, wx, wh, b):
        # sequential dependency between stages expressed as a scan over the
        # stage axis; XLA schedules each iteration on the stage's device
        def body(h_seq, layer_params):
            wx_l, wh_l, b_l = layer_params
            return lstm_layer(h_seq, wx_l, wh_l, b_l), ()
        out, _ = jax.lax.scan(body, x, (wx, wh, b))
        return out.mean()

    from jax.sharding import NamedSharding
    stage_sharded = NamedSharding(mesh, P("stage"))
    replicated = NamedSharding(mesh, P())
    wx = jax.device_put(wx, stage_sharded)
    wh = jax.device_put(wh, stage_sharded)
    b = jax.device_put(b, stage_sharded)
    x = jax.device_put(x, replicated)

    step = jax.jit(jax.value_and_grad(stacked, argnums=(1, 2, 3)),
                   out_shardings=(replicated,
                                  (stage_sharded, stage_sharded,
                                   stage_sharded)))
    loss, grads = step(x, wx, wh, b)
    jax.block_until_ready(grads)
    print("stage-parallel LSTM: %d stages, loss %.5f, grad wx shape %s "
          "sharded over %s"
          % (args.num_stages, float(loss), grads[0].shape,
             grads[0].sharding.spec))


if __name__ == "__main__":
    main()
