"""Profiler usage (mirrors reference example/profiler/profiler_matmul.py):
wrap a run in profiler start/stop, dump the chrome trace, report it."""
import argparse
import glob
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--size", type=int, default=256)
    args = ap.parse_args()

    with tempfile.TemporaryDirectory() as td:
        trace = os.path.join(td, "profile_matmul.json")
        mx.profiler.set_config(profile_all=True, filename=trace)
        mx.profiler.set_state("run")

        a = mx.nd.array(np.random.rand(args.size, args.size)
                        .astype(np.float32))
        b = mx.nd.array(np.random.rand(args.size, args.size)
                        .astype(np.float32))
        for _ in range(args.iters):
            c = mx.nd.dot(a, b)
        c.wait_to_read()

        mx.profiler.set_state("stop")
        mx.profiler.dump()
        produced = glob.glob(os.path.join(td, "*"))
        assert produced, "profiler produced no trace"
        sizes = {os.path.basename(p): os.path.getsize(p) for p in produced}
        print("trace files:", sizes)
        assert any(s > 0 for s in sizes.values())
        print("profiler demo OK")


if __name__ == "__main__":
    main()
