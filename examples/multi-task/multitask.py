"""Multi-task training: one trunk, two softmax heads grouped into a
single symbol (mirrors reference example/multi-task/example_multi_task.py
— Group(softmax1, softmax2), a Module with two label inputs and a
per-head metric)."""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    n, dim = 512, 12
    centers = rs.uniform(-2, 2, size=(4, dim)).astype(np.float32)
    y1 = rs.randint(0, 4, n)                 # task 1: which center
    y2 = (y1 % 2).astype(np.int64)           # task 2: its parity
    x = centers[y1] + 0.3 * rs.normal(size=(n, dim)).astype(np.float32)

    it = mx.io.NDArrayIter(
        {"data": x.astype(np.float32)},
        {"softmax1_label": y1.astype(np.float32),
         "softmax2_label": y2.astype(np.float32)},
        batch_size=args.batch_size, shuffle=True)

    data = mx.sym.Variable("data")
    trunk = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    trunk = mx.sym.Activation(trunk, act_type="relu")
    h1 = mx.sym.FullyConnected(trunk, num_hidden=4, name="head1")
    h2 = mx.sym.FullyConnected(trunk, num_hidden=2, name="head2")
    out = mx.sym.Group([
        mx.sym.SoftmaxOutput(h1, name="softmax1"),
        mx.sym.SoftmaxOutput(h2, name="softmax2"),
    ])

    mod = mx.mod.Module(out, data_names=["data"],
                        label_names=["softmax1_label", "softmax2_label"],
                        context=mx.current_context())
    mod.fit(it, optimizer="adam",
            optimizer_params={"learning_rate": 1e-2,
                              "rescale_grad": 1.0 / args.batch_size},
            num_epoch=args.num_epochs, eval_metric="acc")

    it.reset()
    correct1 = correct2 = total = 0
    for batch in it:
        mod.forward(batch, is_train=False)
        o1, o2 = (o.asnumpy() for o in mod.get_outputs())
        l1 = batch.label[0].asnumpy()
        l2 = batch.label[1].asnumpy()
        correct1 += int((o1.argmax(1) == l1).sum())
        correct2 += int((o2.argmax(1) == l2).sum())
        total += len(l1)
    acc1, acc2 = correct1 / total, correct2 / total
    print("task1 accuracy %.3f task2 accuracy %.3f" % (acc1, acc2))
    assert acc1 > 0.9 and acc2 > 0.9, "multi-task training failed"


if __name__ == "__main__":
    main()
