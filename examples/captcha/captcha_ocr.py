"""Multi-digit captcha OCR (mirrors reference example/captcha/ —
a conv net emitting one softmax per character position over a shared
trunk, trained with a multi-position label vector).

Synthetic captchas: each of 4 character slots renders as a distinct
horizontal band pattern. Exercises label_width > 1 iterators,
SliceChannel/Reshape fan-out to per-position SoftmaxOutput heads
grouped into one symbol, and multi-head metric accounting — the
multi-label pattern no other tree runs.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx

NCHAR = 4
NCLASS = 6


def build():
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")        # (B, NCHAR)
    x = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1), num_filter=16,
                           name="conv1")
    x = mx.sym.Activation(x, act_type="relu")
    x = mx.sym.Pooling(x, pool_type="max", kernel=(2, 2), stride=(2, 2))
    x = mx.sym.Flatten(x)
    x = mx.sym.FullyConnected(x, num_hidden=64, name="fc_trunk")
    x = mx.sym.Activation(x, act_type="relu")
    labels = mx.sym.SliceChannel(label, num_outputs=NCHAR, axis=1,
                                 squeeze_axis=True, name="slice_label")
    heads = []
    for i in range(NCHAR):
        fc = mx.sym.FullyConnected(x, num_hidden=NCLASS, name="fc%d" % i)
        heads.append(mx.sym.SoftmaxOutput(fc, labels[i], name="sm%d" % i))
    return mx.sym.Group(heads)


def make_data(rs, n, size=16):
    x = rs.uniform(0, 0.1, (n, 1, size, size)).astype(np.float32)
    y = rs.randint(0, NCLASS, (n, NCHAR)).astype(np.float32)
    band = size // NCHAR
    for i in range(n):
        for c in range(NCHAR):
            # character identity encoded as the band's stripe period
            cls = int(y[i, c])
            rows = slice(c * band, (c + 1) * band)
            stripe = (np.arange(size) % (cls + 2) == 0).astype(np.float32)
            x[i, 0, rows, :] += stripe[None, :]
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=32)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    x, y = make_data(rs, 512)
    it = mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True)

    mod = mx.mod.Module(build(), context=mx.current_context())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 5e-3})
    for epoch in range(args.num_epochs):
        it.reset()
        correct = total = 0
        for batch in it:
            mod.forward(batch, is_train=True)
            preds = [o.asnumpy() for o in mod.get_outputs()]
            lab = batch.label[0].asnumpy()
            for c in range(NCHAR):
                correct += int((np.argmax(preds[c], 1) == lab[:, c]).sum())
                total += lab.shape[0]
            mod.backward()
            mod.update()
        print("epoch %d per-char accuracy %.3f" % (epoch, correct / total))
    acc = correct / total
    assert acc > 0.9, acc
    print("CAPTCHA_OK")


if __name__ == "__main__":
    main()
