"""Frame-level acoustic model: an LSTM labels every frame of an
utterance (mirrors reference example/speech-demo/ — train_lstm.py's
per-frame state classifier over Kaldi features; also covers
example/rnn-time-major/: the unroll, iterator and softmax all run in
TNC/time-major layout, which no other tree exercises).

Synthetic utterances: a 3-state left-to-right Markov chain emits
prototype+noise frames, so correct labelling needs temporal context —
a per-frame-only classifier plateaus lower than the LSTM.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx

T = 24        # frames per utterance
FDIM = 12     # filterbank-like feature dim
NSTATE = 3


def make_utterances(rs, n):
    protos = rs.normal(0, 1.0, (NSTATE, FDIM)).astype(np.float32)
    xs = np.zeros((n, T, FDIM), np.float32)
    ys = np.zeros((n, T), np.float32)
    for i in range(n):
        state, t = 0, 0
        dur = rs.randint(4, 10)
        for t in range(T):
            if dur == 0 and state < NSTATE - 1:
                state += 1
                dur = rs.randint(4, 10)
            dur = max(0, dur - 1)
            # emissions overlap heavily; the state is mostly
            # recoverable from POSITION in the utterance, i.e. memory
            xs[i, t] = protos[state] * 0.35 + \
                0.8 * rs.normal(size=FDIM).astype(np.float32)
            ys[i, t] = state
    return xs, ys


def build(num_hidden):
    # time-major end to end: data arrives (T, N, F), per-frame softmax
    # flattens over (T*N,) — the reference's rnn-time-major layout,
    # which keeps the scan axis leading
    data = mx.sym.Variable("data")                  # (T, N, F)
    label = mx.sym.Variable("softmax_label")        # (T, N)
    cell = mx.rnn.LSTMCell(num_hidden=num_hidden, prefix="lstm_")
    outputs, _ = cell.unroll(T, data, layout="TNC", merge_outputs=True)
    x = mx.sym.Reshape(outputs, shape=(-1, num_hidden))
    x = mx.sym.FullyConnected(x, num_hidden=NSTATE, name="fc")
    lab = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(x, lab, name="softmax")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=12)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--num-hidden", type=int, default=32)
    ap.add_argument("--train-size", type=int, default=256)
    args = ap.parse_args()

    # init must be reproducible: initializers draw from GLOBAL np.random
    # (mx.random.seed alone does not cover them)
    mx.random.seed(4)
    np.random.seed(4)
    rs = np.random.RandomState(11)
    xs, ys = make_utterances(rs, args.train_size)
    xt, yt = make_utterances(rs, 96)

    # time-major batches: (T, N, F) / (T, N)
    from mxnet_tpu.io import DataDesc, DataBatch
    B = args.batch_size
    mod = mx.mod.Module(build(args.num_hidden),
                        context=mx.current_context())
    mod.bind(data_shapes=[DataDesc("data", (T, B, FDIM), layout="TNC")],
             label_shapes=[DataDesc("softmax_label", (T, B),
                                    layout="TN")],
             for_training=True)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 5e-3})

    n = args.train_size // B
    for epoch in range(args.num_epochs):
        for b in range(n):
            sl = slice(b * B, (b + 1) * B)
            mod.forward_backward(DataBatch(
                [mx.nd.array(xs[sl].transpose(1, 0, 2))],
                [mx.nd.array(ys[sl].T)]))
            mod.update()

    def frame_acc(x_all, y_all):
        hits = total = 0
        for b in range(len(x_all) // B):
            sl = slice(b * B, (b + 1) * B)
            mod.forward(DataBatch(
                [mx.nd.array(x_all[sl].transpose(1, 0, 2))],
                [mx.nd.array(y_all[sl].T)]), is_train=False)
            pred = mod.get_outputs()[0].asnumpy().argmax(-1)
            hits += (pred == y_all[sl].T.reshape(-1)).sum()
            total += pred.size
        return hits / float(total)

    acc = frame_acc(xt, yt)
    print("held-out frame accuracy %.3f" % acc)
    assert acc > 0.6, "LSTM acoustic model failed to learn"
    print("speech demo ok")


if __name__ == "__main__":
    main()
