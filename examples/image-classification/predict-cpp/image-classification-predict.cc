// C++ image-classification inference over the predict C ABI (parity:
// reference example/image-classification/predict-cpp/
// image-classification-predict.cc — load symbol JSON + params, set the
// input image, forward, read class probabilities).
//
// Build (from repo root, after `make`):
//   g++ -std=c++17 examples/image-classification/predict-cpp/\
//       image-classification-predict.cc -o predict \
//       -L mxnet_tpu/_lib -lmxtpu_c_api -Wl,-rpath,mxnet_tpu/_lib
// Run:
//   PYTHONPATH=. MXNET_TPU_FORCE_CPU=1 ./predict model-symbol.json \
//       model-0000.params 1,3,32,32
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

typedef unsigned int mx_uint;
typedef float mx_float;
typedef void* PredictorHandle;

extern "C" {
const char* MXGetLastError();
int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id, mx_uint num_input,
                 const char** input_keys, const mx_uint* input_shape_indptr,
                 const mx_uint* input_shape_data, PredictorHandle* out);
int MXPredSetInput(PredictorHandle handle, const char* key,
                   const mx_float* data, mx_uint size);
int MXPredForward(PredictorHandle handle);
int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint** shape_data, mx_uint* shape_ndim);
int MXPredGetOutput(PredictorHandle handle, mx_uint index, mx_float* data,
                    mx_uint size);
int MXPredFree(PredictorHandle handle);
}

#define CHECK(x)                                              \
  do {                                                        \
    if ((x) != 0) {                                           \
      std::fprintf(stderr, "FAIL %s: %s\n", #x,              \
                   MXGetLastError());                         \
      std::exit(1);                                           \
    }                                                         \
  } while (0)

static std::vector<char> ReadFile(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (f == nullptr) {
    std::fprintf(stderr, "cannot open %s\n", path);
    std::exit(1);
  }
  std::fseek(f, 0, SEEK_END);
  long n = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::vector<char> buf(n);
  if (std::fread(buf.data(), 1, n, f) != static_cast<size_t>(n)) {
    std::fprintf(stderr, "short read on %s\n", path);
    std::exit(1);
  }
  std::fclose(f);
  return buf;
}

int main(int argc, char** argv) {
  if (argc < 4) {
    std::fprintf(stderr,
                 "usage: %s symbol.json params N,C,H,W\n", argv[0]);
    return 1;
  }
  std::vector<char> symbol = ReadFile(argv[1]);
  symbol.push_back('\0');
  std::vector<char> params = ReadFile(argv[2]);

  // parse the input shape "N,C,H,W"
  std::vector<mx_uint> shape;
  for (char* tok = std::strtok(argv[3], ","); tok != nullptr;
       tok = std::strtok(nullptr, ",")) {
    shape.push_back(static_cast<mx_uint>(std::atoi(tok)));
  }
  mx_uint indptr[2] = {0, static_cast<mx_uint>(shape.size())};
  const char* keys[1] = {"data"};

  PredictorHandle pred = nullptr;
  CHECK(MXPredCreate(symbol.data(), params.data(),
                     static_cast<int>(params.size()), 1, 0, 1, keys, indptr,
                     shape.data(), &pred));

  size_t n_in = 1;
  for (auto s : shape) n_in *= s;
  std::vector<mx_float> img(n_in);
  unsigned int seed = 11;
  for (auto& v : img) {
    seed = seed * 1103515245u + 12345u;
    v = static_cast<float>((seed >> 8) & 0xffffff) /
        static_cast<float>(0x1000000);
  }
  CHECK(MXPredSetInput(pred, "data", img.data(),
                       static_cast<mx_uint>(n_in)));
  CHECK(MXPredForward(pred));

  mx_uint* oshape = nullptr;
  mx_uint ondim = 0;
  CHECK(MXPredGetOutputShape(pred, 0, &oshape, &ondim));
  size_t n_out = 1;
  for (mx_uint i = 0; i < ondim; ++i) n_out *= oshape[i];
  std::vector<mx_float> probs(n_out);
  CHECK(MXPredGetOutput(pred, 0, probs.data(),
                        static_cast<mx_uint>(n_out)));

  // argmax per row of the (batch, classes) output
  size_t classes = oshape[ondim - 1];
  double psum = 0.0;
  for (auto p : probs) psum += p;
  int best = 0;
  for (size_t j = 1; j < classes; ++j) {
    if (probs[j] > probs[best]) best = static_cast<int>(j);
  }
  std::printf("PREDICT_OK classes=%zu best=%d prob=%.4f prob_sum=%.3f\n",
              classes, best, probs[best], psum);
  return 0;
}
