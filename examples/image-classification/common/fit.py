"""Shared training-loop driver for the image-classification examples.

Mirrors the reference's example/image-classification/common/fit.py:113-210
(kvstore creation, optimizer wiring, LR schedule, checkpoint callbacks,
Speedometer) on the TPU-native stack.
"""
import logging
import os

import mxnet_tpu as mx


def add_fit_args(parser):
    parser.add_argument("--network", type=str, default="mlp")
    parser.add_argument("--num-classes", type=int, default=10)
    parser.add_argument("--num-epochs", type=int, default=3)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--momentum", type=float, default=0.9)
    parser.add_argument("--wd", type=float, default=1e-4)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--optimizer", type=str, default="sgd")
    parser.add_argument("--kv-store", type=str, default="local")
    parser.add_argument("--lr-factor", type=float, default=0.1)
    parser.add_argument("--lr-step-epochs", type=str, default="")
    parser.add_argument("--disp-batches", type=int, default=20)
    parser.add_argument("--model-prefix", type=str, default=None)
    parser.add_argument("--load-epoch", type=int, default=None)
    parser.add_argument("--synthetic", action="store_true", default=False)
    return parser


def _lr_scheduler(args, epoch_size):
    if not args.lr_step_epochs:
        return args.lr, None
    epochs = [int(e) for e in args.lr_step_epochs.split(",") if e]
    begin = args.load_epoch or 0
    lr = args.lr
    for e in epochs:
        if begin >= e:
            lr *= args.lr_factor
    steps = [epoch_size * (e - begin) for e in epochs if e > begin]
    if not steps:
        return lr, None
    return lr, mx.lr_scheduler.MultiFactorScheduler(step=steps,
                                                    factor=args.lr_factor)


def fit(args, network, data_loader):
    """Train `network` (a Symbol) on the iterators from `data_loader(args)`."""
    logging.basicConfig(level=logging.INFO)
    kv = mx.kvstore.create(args.kv_store)
    train, val = data_loader(args)

    arg_params, aux_params = None, None
    if args.model_prefix and args.load_epoch is not None:
        _, arg_params, aux_params = mx.model.load_checkpoint(
            args.model_prefix, args.load_epoch)

    epoch_size = max(train.num_data // args.batch_size, 1) \
        if hasattr(train, "num_data") else 100
    lr, sched = _lr_scheduler(args, epoch_size)

    optimizer_params = {
        "learning_rate": lr,
        "wd": args.wd,
        "rescale_grad": 1.0 / args.batch_size,
    }
    if args.optimizer in ("sgd", "nag"):
        optimizer_params["momentum"] = args.momentum
    if sched is not None:
        optimizer_params["lr_scheduler"] = sched

    checkpoint = None
    if args.model_prefix:
        os.makedirs(os.path.dirname(args.model_prefix) or ".", exist_ok=True)
        checkpoint = mx.callback.do_checkpoint(args.model_prefix)

    mod = mx.mod.Module(network, context=mx.current_context())
    mod.fit(train,
            eval_data=val,
            eval_metric=["acc"],
            optimizer=args.optimizer,
            optimizer_params=optimizer_params,
            arg_params=arg_params,
            aux_params=aux_params,
            initializer=mx.initializer.Xavier(magnitude=2.0),
            num_epoch=args.num_epochs,
            begin_epoch=args.load_epoch or 0,
            batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                       args.disp_batches),
            epoch_end_callback=checkpoint,
            kvstore=kv)
    return mod
