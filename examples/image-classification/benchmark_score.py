"""Inference scoring harness — fps for the model zoo (mirrors reference
example/image-classification/benchmark_score.py:41-50)."""
import argparse
import time

import numpy as np

import mxnet_tpu as mx


def score(network, batch_size, image_shape=(3, 224, 224), num_batches=10,
          num_layers=None):
    import os
    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    import symbols
    kwargs = {}
    if num_layers:
        kwargs["num_layers"] = num_layers
    sym = symbols.get_symbol(network, 1000, **kwargs)
    data_shape = (batch_size,) + image_shape
    mod = mx.mod.Module(sym, label_names=["softmax_label"])
    mod.bind(data_shapes=[("data", data_shape)],
             label_shapes=[("softmax_label", (batch_size,))],
             for_training=False)
    mod.init_params(mx.initializer.Xavier())
    batch = mx.io.DataBatch(
        data=[mx.nd.array(np.random.rand(*data_shape))],
        label=[mx.nd.zeros((batch_size,))])
    # warmup (first call compiles)
    mod.forward(batch, is_train=False)
    mod.get_outputs()[0].wait_to_read()
    tic = time.time()
    for _ in range(num_batches):
        mod.forward(batch, is_train=False)
    mod.get_outputs()[0].wait_to_read()
    return num_batches * batch_size / (time.time() - tic)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--networks", type=str, default="alexnet,resnet")
    parser.add_argument("--batch-size", type=int, default=32)
    args = parser.parse_args()
    for net in args.networks.split(","):
        kwargs = {"num_layers": 50} if net == "resnet" else {}
        fps = score(net, args.batch_size, **kwargs)
        print("network: %-10s batch: %d  %.1f images/sec"
              % (net, args.batch_size, fps))
