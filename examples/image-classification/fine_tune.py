"""Transfer learning / fine-tuning (mirrors reference
example/image-classification/fine-tune.py — load a trained checkpoint,
truncate at a feature layer, attach a fresh classifier head, and train
with the backbone frozen via ``fixed_param_names``).

Stage 1 trains a small convnet on a 4-class "source" task and saves a
checkpoint. Stage 2 loads it, cuts the graph at the flatten layer
(``get_internals()``), adds a new head for a 3-class "target" task,
seeds the backbone with the loaded params (``allow_missing`` covers
the new head), and fits with every backbone param frozen. The frozen
weights must be bit-identical after training, and the target task must
still be learned through the new head alone.
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx

IMG = 12


def draw(rs, cls, n):
    """Classes are oriented bars; source task = 4 ways, target = 3."""
    x = np.zeros((n, 1, IMG, IMG), np.float32)
    for i in range(n):
        c = int(cls[i])
        a = np.zeros((IMG, IMG), np.float32)
        p = rs.randint(2, IMG - 2)
        if c == 0:
            a[p, :] = 1.0
        elif c == 1:
            a[:, p] = 1.0
        elif c == 2:
            np.fill_diagonal(a, 1.0)
        else:
            a[p, :] = 1.0
            a[:, p] = 1.0
        x[i, 0] = a + 0.1 * rs.normal(size=(IMG, IMG))
    return x


def backbone(data):
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8,
                             pad=(1, 1), name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2),
                         stride=(2, 2))
    net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=16,
                             pad=(1, 1), name="conv2")
    net = mx.sym.Activation(net, act_type="relu")
    return mx.sym.Flatten(net, name="flatten")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=6)
    ap.add_argument("--batch-size", type=int, default=32)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    mx.random.seed(9)
    work = tempfile.mkdtemp(prefix="finetune_")
    prefix = os.path.join(work, "source")

    # ---- stage 1: source task ------------------------------------------
    ys = rs.randint(0, 4, 512).astype(np.float32)
    xs = draw(rs, ys, 512)
    it = mx.io.NDArrayIter(xs, ys, batch_size=args.batch_size,
                           shuffle=True, label_name="softmax_label")
    src = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(backbone(mx.sym.Variable("data")),
                              num_hidden=4, name="src_fc"),
        name="softmax")
    mod = mx.mod.Module(src, context=mx.current_context())
    mod.fit(it, num_epoch=args.num_epochs,
            initializer=mx.initializer.Xavier(),
            optimizer_params={"learning_rate": 0.01})
    mod.save_checkpoint(prefix, args.num_epochs)

    # ---- stage 2: load, truncate, new head, frozen backbone ------------
    sym, arg_params, aux_params = mx.model.load_checkpoint(
        prefix, args.num_epochs)
    features = sym.get_internals()["flatten_output"]
    net = mx.sym.FullyConnected(features, num_hidden=3, name="tgt_fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    backbone_params = [n for n in net.list_arguments()
                       if n.startswith(("conv1", "conv2"))]
    yt = rs.randint(0, 3, 384).astype(np.float32)
    xt = draw(rs, yt, 384)
    it2 = mx.io.NDArrayIter(xt, yt, batch_size=args.batch_size,
                            shuffle=True, label_name="softmax_label")
    tuned = mx.mod.Module(net, context=mx.current_context(),
                          fixed_param_names=backbone_params)
    frozen_before = {n: arg_params[n].asnumpy() for n in backbone_params}
    # fit seeds the backbone from the checkpoint params; allow_missing
    # lets the fresh head fall back to the initializer
    tuned.fit(it2, num_epoch=args.num_epochs,
              arg_params=arg_params, aux_params=aux_params,
              allow_missing=True, initializer=mx.initializer.Xavier(),
              optimizer_params={"learning_rate": 0.01})

    args_after, _ = tuned.get_params()
    for n in backbone_params:
        np.testing.assert_array_equal(args_after[n].asnumpy(),
                                      frozen_before[n], err_msg=n)
    metric = mx.metric.Accuracy()
    it2.reset()
    tuned.score(it2, metric)
    acc = metric.get()[1]
    print("target-task accuracy %.3f (backbone frozen)" % acc)
    assert acc > 0.9, "new head should learn on frozen features"
    print("fine-tune ok")


if __name__ == "__main__":
    main()
