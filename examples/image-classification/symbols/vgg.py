"""VGG symbol (mirrors reference symbols/vgg.py — stacked 3x3 conv
blocks from the Simonyan & Zisserman configs, optional BN)."""
import mxnet_tpu as mx

# layers-per-stage for each supported depth (VGG paper table 1)
CONFIGS = {
    11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
    13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
    16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
    19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512]),
}


def get_symbol(num_classes, num_layers=16, batch_norm=False, **kwargs):
    if num_layers not in CONFIGS:
        raise ValueError("vgg depth must be one of %s" % list(CONFIGS))
    layers, filters = CONFIGS[num_layers]
    net = mx.sym.Variable("data")
    for stage, (n, f) in enumerate(zip(layers, filters)):
        for i in range(n):
            net = mx.sym.Convolution(net, kernel=(3, 3), pad=(1, 1),
                                     num_filter=f,
                                     name="conv%d_%d" % (stage + 1, i + 1))
            if batch_norm:
                net = mx.sym.BatchNorm(net,
                                       name="bn%d_%d" % (stage + 1, i + 1))
            net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2),
                             stride=(2, 2), name="pool%d" % (stage + 1))
    net = mx.sym.Flatten(net)
    for i, hidden in enumerate((4096, 4096)):
        net = mx.sym.FullyConnected(net, num_hidden=hidden,
                                    name="fc%d" % (6 + i))
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.Dropout(net, p=0.5)
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc8")
    return mx.sym.SoftmaxOutput(net, name="softmax")
