"""GoogLeNet / Inception-v1 symbol (mirrors reference
symbols/googlenet.py — the Szegedy et al. 2014 inception modules with
1x1/3x3/5x5/pool-proj branches)."""
import mxnet_tpu as mx


def conv(data, num_filter, kernel, stride=(1, 1), pad=(0, 0), name=None):
    c = mx.sym.Convolution(data, num_filter=num_filter, kernel=kernel,
                           stride=stride, pad=pad, name="conv_%s" % name)
    return mx.sym.Activation(c, act_type="relu", name="relu_%s" % name)


def inception(data, f1, f3r, f3, f5r, f5, proj, name):
    b1 = conv(data, f1, (1, 1), name="%s_1x1" % name)
    b3 = conv(data, f3r, (1, 1), name="%s_3x3r" % name)
    b3 = conv(b3, f3, (3, 3), pad=(1, 1), name="%s_3x3" % name)
    b5 = conv(data, f5r, (1, 1), name="%s_5x5r" % name)
    b5 = conv(b5, f5, (5, 5), pad=(2, 2), name="%s_5x5" % name)
    bp = mx.sym.Pooling(data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                        pool_type="max", name="%s_pool" % name)
    bp = conv(bp, proj, (1, 1), name="%s_proj" % name)
    return mx.sym.Concat(b1, b3, b5, bp, name="%s_concat" % name)


def get_symbol(num_classes, **kwargs):
    data = mx.sym.Variable("data")
    net = conv(data, 64, (7, 7), stride=(2, 2), pad=(3, 3), name="stem1")
    net = mx.sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                         pool_type="max")
    net = conv(net, 64, (1, 1), name="stem2r")
    net = conv(net, 192, (3, 3), pad=(1, 1), name="stem2")
    net = mx.sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                         pool_type="max")
    net = inception(net, 64, 96, 128, 16, 32, 32, "3a")
    net = inception(net, 128, 128, 192, 32, 96, 64, "3b")
    net = mx.sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                         pool_type="max")
    net = inception(net, 192, 96, 208, 16, 48, 64, "4a")
    net = inception(net, 160, 112, 224, 24, 64, 64, "4b")
    net = inception(net, 128, 128, 256, 24, 64, 64, "4c")
    net = inception(net, 112, 144, 288, 32, 64, 64, "4d")
    net = inception(net, 256, 160, 320, 32, 128, 128, "4e")
    net = mx.sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                         pool_type="max")
    net = inception(net, 256, 160, 320, 32, 128, 128, "5a")
    net = inception(net, 384, 192, 384, 48, 128, 128, "5b")
    net = mx.sym.Pooling(net, kernel=(7, 7), stride=(1, 1),
                         pool_type="avg", global_pool=True)
    net = mx.sym.Flatten(net)
    net = mx.sym.Dropout(net, p=0.4)
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")
