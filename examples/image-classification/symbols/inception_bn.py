"""Inception-BN symbol (mirrors reference symbols/inception-bn.py —
the BN-Inception network of Ioffe & Szegedy 2015: inception modules
with two stacked 3x3s in place of the 5x5, BatchNorm after every
conv, avg/max pool-through variants)."""
import mxnet_tpu as mx


def conv(data, num_filter, kernel, stride=(1, 1), pad=(0, 0), name=None):
    c = mx.sym.Convolution(data, num_filter=num_filter, kernel=kernel,
                           stride=stride, pad=pad, no_bias=True,
                           name="%s_conv" % name)
    c = mx.sym.BatchNorm(c, fix_gamma=False, name="%s_bn" % name)
    return mx.sym.Activation(c, act_type="relu", name="%s_relu" % name)


def inception(data, f1, f3r, f3, fd3r, fd3, proj, pool, name):
    b1 = conv(data, f1, (1, 1), name="%s_1x1" % name)
    b3 = conv(data, f3r, (1, 1), name="%s_3x3r" % name)
    b3 = conv(b3, f3, (3, 3), pad=(1, 1), name="%s_3x3" % name)
    bd = conv(data, fd3r, (1, 1), name="%s_d3x3r" % name)
    bd = conv(bd, fd3, (3, 3), pad=(1, 1), name="%s_d3x3a" % name)
    bd = conv(bd, fd3, (3, 3), pad=(1, 1), name="%s_d3x3b" % name)
    bp = mx.sym.Pooling(data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                        pool_type=pool, name="%s_pool" % name)
    bp = conv(bp, proj, (1, 1), name="%s_proj" % name)
    return mx.sym.Concat(b1, b3, bd, bp, name="%s_concat" % name)


def inception_down(data, f3r, f3, fd3r, fd3, name):
    """stride-2 module: no 1x1 branch, pool passes through un-projected"""
    b3 = conv(data, f3r, (1, 1), name="%s_3x3r" % name)
    b3 = conv(b3, f3, (3, 3), stride=(2, 2), pad=(1, 1),
              name="%s_3x3" % name)
    bd = conv(data, fd3r, (1, 1), name="%s_d3x3r" % name)
    bd = conv(bd, fd3, (3, 3), pad=(1, 1), name="%s_d3x3a" % name)
    bd = conv(bd, fd3, (3, 3), stride=(2, 2), pad=(1, 1),
              name="%s_d3x3b" % name)
    bp = mx.sym.Pooling(data, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                        pool_type="max", name="%s_pool" % name)
    return mx.sym.Concat(b3, bd, bp, name="%s_concat" % name)


def get_symbol(num_classes, **kwargs):
    data = mx.sym.Variable("data")
    net = conv(data, 64, (7, 7), stride=(2, 2), pad=(3, 3), name="stem1")
    net = mx.sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                         pool_type="max")
    net = conv(net, 64, (1, 1), name="stem2r")
    net = conv(net, 192, (3, 3), pad=(1, 1), name="stem2")
    net = mx.sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                         pool_type="max")
    net = inception(net, 64, 64, 64, 64, 96, 32, "avg", "3a")
    net = inception(net, 64, 64, 96, 64, 96, 64, "avg", "3b")
    net = inception_down(net, 128, 160, 64, 96, "3c")
    net = inception(net, 224, 64, 96, 96, 128, 128, "avg", "4a")
    net = inception(net, 192, 96, 128, 96, 128, 128, "avg", "4b")
    net = inception(net, 160, 128, 160, 128, 160, 128, "avg", "4c")
    net = inception(net, 96, 128, 192, 160, 192, 128, "avg", "4d")
    net = inception_down(net, 128, 192, 192, 256, "4e")
    net = inception(net, 352, 192, 320, 160, 224, 128, "avg", "5a")
    net = inception(net, 352, 192, 320, 192, 224, 128, "max", "5b")
    net = mx.sym.Pooling(net, kernel=(7, 7), pool_type="avg",
                         global_pool=True)
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")
