"""Inception-v3 symbol (mirrors reference symbols/inception-v3.py —
Szegedy et al. 2015: factorised 7x7 -> 1x7/7x1 modules, grid-reduction
modules, 299x299 input)."""
import mxnet_tpu as mx


def conv(data, num_filter, kernel, stride=(1, 1), pad=(0, 0), name=None):
    c = mx.sym.Convolution(data, num_filter=num_filter, kernel=kernel,
                           stride=stride, pad=pad, no_bias=True,
                           name="%s_conv" % name)
    c = mx.sym.BatchNorm(c, fix_gamma=True, eps=0.001, name="%s_bn" % name)
    return mx.sym.Activation(c, act_type="relu", name="%s_relu" % name)


def inc_a(data, proj, name):
    b1 = conv(data, 64, (1, 1), name="%s_1x1" % name)
    b5 = conv(data, 48, (1, 1), name="%s_5x5r" % name)
    b5 = conv(b5, 64, (5, 5), pad=(2, 2), name="%s_5x5" % name)
    b3 = conv(data, 64, (1, 1), name="%s_3x3r" % name)
    b3 = conv(b3, 96, (3, 3), pad=(1, 1), name="%s_3x3a" % name)
    b3 = conv(b3, 96, (3, 3), pad=(1, 1), name="%s_3x3b" % name)
    bp = mx.sym.Pooling(data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                        pool_type="avg")
    bp = conv(bp, proj, (1, 1), name="%s_proj" % name)
    return mx.sym.Concat(b1, b5, b3, bp)


def red_a(data, name):
    b3 = conv(data, 384, (3, 3), stride=(2, 2), name="%s_3x3" % name)
    bd = conv(data, 64, (1, 1), name="%s_d3x3r" % name)
    bd = conv(bd, 96, (3, 3), pad=(1, 1), name="%s_d3x3a" % name)
    bd = conv(bd, 96, (3, 3), stride=(2, 2), name="%s_d3x3b" % name)
    bp = mx.sym.Pooling(data, kernel=(3, 3), stride=(2, 2),
                        pool_type="max")
    return mx.sym.Concat(b3, bd, bp)


def inc_b(data, mid, name):
    b1 = conv(data, 192, (1, 1), name="%s_1x1" % name)
    b7 = conv(data, mid, (1, 1), name="%s_7r" % name)
    b7 = conv(b7, mid, (1, 7), pad=(0, 3), name="%s_1x7" % name)
    b7 = conv(b7, 192, (7, 1), pad=(3, 0), name="%s_7x1" % name)
    bd = conv(data, mid, (1, 1), name="%s_d7r" % name)
    bd = conv(bd, mid, (7, 1), pad=(3, 0), name="%s_d7a" % name)
    bd = conv(bd, mid, (1, 7), pad=(0, 3), name="%s_d7b" % name)
    bd = conv(bd, mid, (7, 1), pad=(3, 0), name="%s_d7c" % name)
    bd = conv(bd, 192, (1, 7), pad=(0, 3), name="%s_d7d" % name)
    bp = mx.sym.Pooling(data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                        pool_type="avg")
    bp = conv(bp, 192, (1, 1), name="%s_proj" % name)
    return mx.sym.Concat(b1, b7, bd, bp)


def red_b(data, name):
    b3 = conv(data, 192, (1, 1), name="%s_3r" % name)
    b3 = conv(b3, 320, (3, 3), stride=(2, 2), name="%s_3x3" % name)
    b7 = conv(data, 192, (1, 1), name="%s_7r" % name)
    b7 = conv(b7, 192, (1, 7), pad=(0, 3), name="%s_1x7" % name)
    b7 = conv(b7, 192, (7, 1), pad=(3, 0), name="%s_7x1" % name)
    b7 = conv(b7, 192, (3, 3), stride=(2, 2), name="%s_3x3b" % name)
    bp = mx.sym.Pooling(data, kernel=(3, 3), stride=(2, 2),
                        pool_type="max")
    return mx.sym.Concat(b3, b7, bp)


def inc_c(data, name):
    b1 = conv(data, 320, (1, 1), name="%s_1x1" % name)
    b3 = conv(data, 384, (1, 1), name="%s_3r" % name)
    b3a = conv(b3, 384, (1, 3), pad=(0, 1), name="%s_1x3" % name)
    b3b = conv(b3, 384, (3, 1), pad=(1, 0), name="%s_3x1" % name)
    bd = conv(data, 448, (1, 1), name="%s_dr" % name)
    bd = conv(bd, 384, (3, 3), pad=(1, 1), name="%s_d3" % name)
    bda = conv(bd, 384, (1, 3), pad=(0, 1), name="%s_d1x3" % name)
    bdb = conv(bd, 384, (3, 1), pad=(1, 0), name="%s_d3x1" % name)
    bp = mx.sym.Pooling(data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                        pool_type="avg")
    bp = conv(bp, 192, (1, 1), name="%s_proj" % name)
    return mx.sym.Concat(b1, b3a, b3b, bda, bdb, bp)


def get_symbol(num_classes, **kwargs):
    data = mx.sym.Variable("data")
    net = conv(data, 32, (3, 3), stride=(2, 2), name="stem1")
    net = conv(net, 32, (3, 3), name="stem2")
    net = conv(net, 64, (3, 3), pad=(1, 1), name="stem3")
    net = mx.sym.Pooling(net, kernel=(3, 3), stride=(2, 2),
                         pool_type="max")
    net = conv(net, 80, (1, 1), name="stem4")
    net = conv(net, 192, (3, 3), name="stem5")
    net = mx.sym.Pooling(net, kernel=(3, 3), stride=(2, 2),
                         pool_type="max")
    net = inc_a(net, 32, "mixed0")
    net = inc_a(net, 64, "mixed1")
    net = inc_a(net, 64, "mixed2")
    net = red_a(net, "mixed3")
    net = inc_b(net, 128, "mixed4")
    net = inc_b(net, 160, "mixed5")
    net = inc_b(net, 160, "mixed6")
    net = inc_b(net, 192, "mixed7")
    net = red_b(net, "mixed8")
    net = inc_c(net, "mixed9")
    net = inc_c(net, "mixed10")
    net = mx.sym.Pooling(net, kernel=(8, 8), pool_type="avg",
                         global_pool=True)
    net = mx.sym.Flatten(net)
    net = mx.sym.Dropout(net, p=0.5)
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")
