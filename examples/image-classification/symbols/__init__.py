"""Symbol model zoo for the image-classification examples
(mirrors reference example/image-classification/symbols/)."""
from . import mlp, lenet, alexnet, resnet


def get_symbol(network, num_classes, **kwargs):
    return {
        "mlp": mlp,
        "lenet": lenet,
        "alexnet": alexnet,
        "resnet": resnet,
    }[network].get_symbol(num_classes=num_classes, **kwargs)
