"""Symbol model zoo for the image-classification examples
(mirrors reference example/image-classification/symbols/)."""
from . import (mlp, lenet, alexnet, resnet, vgg, googlenet, mobilenet,
               resnext, inception_bn, inception_v3)

_MODULES = {
    "mlp": mlp,
    "lenet": lenet,
    "alexnet": alexnet,
    "resnet": resnet,
    "vgg": vgg,
    "googlenet": googlenet,
    "mobilenet": mobilenet,
    "resnext": resnext,
    "inception-bn": inception_bn,
    "inception-v3": inception_v3,
}


def get_symbol(network, num_classes, **kwargs):
    return _MODULES[network].get_symbol(num_classes=num_classes, **kwargs)
