"""MobileNet-v1 symbol (mirrors reference symbols/mobilenet.py —
depthwise-separable conv stacks via grouped Convolution, width
multiplier via the alpha kwarg)."""
import mxnet_tpu as mx


def conv_bn(data, num_filter, kernel, stride=(1, 1), pad=(0, 0),
            num_group=1, name=None):
    c = mx.sym.Convolution(data, num_filter=num_filter, kernel=kernel,
                           stride=stride, pad=pad, num_group=num_group,
                           no_bias=True, name="%s_conv" % name)
    c = mx.sym.BatchNorm(c, fix_gamma=False, name="%s_bn" % name)
    return mx.sym.Activation(c, act_type="relu", name="%s_relu" % name)


def dw_sep(data, in_ch, out_ch, stride, name):
    """depthwise 3x3 (groups == channels) then pointwise 1x1"""
    dw = conv_bn(data, in_ch, (3, 3), stride=stride, pad=(1, 1),
                 num_group=in_ch, name="%s_dw" % name)
    return conv_bn(dw, out_ch, (1, 1), name="%s_pw" % name)


def get_symbol(num_classes, alpha=1.0, **kwargs):
    def ch(n):
        return max(8, int(n * alpha))
    data = mx.sym.Variable("data")
    net = conv_bn(data, ch(32), (3, 3), stride=(2, 2), pad=(1, 1),
                  name="stem")
    plan = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
            (256, 256, 1), (256, 512, 2)] \
        + [(512, 512, 1)] * 5 + [(512, 1024, 2), (1024, 1024, 1)]
    for i, (cin, cout, s) in enumerate(plan):
        net = dw_sep(net, ch(cin), ch(cout), (s, s), "sep%d" % i)
    net = mx.sym.Pooling(net, kernel=(7, 7), pool_type="avg",
                         global_pool=True)
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")
