"""ResNet symbol (mirrors reference symbols/resnet.py — v1 bottleneck/basic
units, configurable depth; BN+relu pre-activation omitted for the v1 form)."""
import mxnet_tpu as mx


def residual_unit(data, num_filter, stride, dim_match, name,
                  bottle_neck=True):
    if bottle_neck:
        body = mx.sym.Convolution(data=data, num_filter=num_filter // 4,
                                  kernel=(1, 1), stride=stride, no_bias=True,
                                  name=name + "_conv1")
        body = mx.sym.BatchNorm(data=body, fix_gamma=False, eps=2e-5,
                                momentum=0.9, name=name + "_bn1")
        body = mx.sym.Activation(data=body, act_type="relu")
        body = mx.sym.Convolution(data=body, num_filter=num_filter // 4,
                                  kernel=(3, 3), pad=(1, 1), no_bias=True,
                                  name=name + "_conv2")
        body = mx.sym.BatchNorm(data=body, fix_gamma=False, eps=2e-5,
                                momentum=0.9, name=name + "_bn2")
        body = mx.sym.Activation(data=body, act_type="relu")
        body = mx.sym.Convolution(data=body, num_filter=num_filter,
                                  kernel=(1, 1), no_bias=True,
                                  name=name + "_conv3")
        body = mx.sym.BatchNorm(data=body, fix_gamma=False, eps=2e-5,
                                momentum=0.9, name=name + "_bn3")
    else:
        body = mx.sym.Convolution(data=data, num_filter=num_filter,
                                  kernel=(3, 3), stride=stride, pad=(1, 1),
                                  no_bias=True, name=name + "_conv1")
        body = mx.sym.BatchNorm(data=body, fix_gamma=False, eps=2e-5,
                                momentum=0.9, name=name + "_bn1")
        body = mx.sym.Activation(data=body, act_type="relu")
        body = mx.sym.Convolution(data=body, num_filter=num_filter,
                                  kernel=(3, 3), pad=(1, 1), no_bias=True,
                                  name=name + "_conv2")
        body = mx.sym.BatchNorm(data=body, fix_gamma=False, eps=2e-5,
                                momentum=0.9, name=name + "_bn2")
    if dim_match:
        shortcut = data
    else:
        shortcut = mx.sym.Convolution(data=data, num_filter=num_filter,
                                      kernel=(1, 1), stride=stride,
                                      no_bias=True, name=name + "_sc")
        shortcut = mx.sym.BatchNorm(data=shortcut, fix_gamma=False, eps=2e-5,
                                    momentum=0.9, name=name + "_sc_bn")
    return mx.sym.Activation(data=body + shortcut, act_type="relu")


def get_symbol(num_classes=1000, num_layers=18, image_shape="3,224,224",
               **kwargs):
    configs = {
        18: ([2, 2, 2, 2], False),
        34: ([3, 4, 6, 3], False),
        50: ([3, 4, 6, 3], True),
        101: ([3, 4, 23, 3], True),
        152: ([3, 8, 36, 3], True),
    }
    units, bottle_neck = configs[num_layers]
    filter_list = [256, 512, 1024, 2048] if bottle_neck \
        else [64, 128, 256, 512]

    data = mx.sym.Variable("data")
    body = mx.sym.Convolution(data=data, num_filter=64, kernel=(7, 7),
                              stride=(2, 2), pad=(3, 3), no_bias=True,
                              name="conv0")
    body = mx.sym.BatchNorm(data=body, fix_gamma=False, eps=2e-5,
                            momentum=0.9, name="bn0")
    body = mx.sym.Activation(data=body, act_type="relu")
    body = mx.sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2),
                          pad=(1, 1), pool_type="max")

    for i, (n_units, n_filter) in enumerate(zip(units, filter_list)):
        stride = (1, 1) if i == 0 else (2, 2)
        body = residual_unit(body, n_filter, stride, False,
                             "stage%d_unit1" % (i + 1), bottle_neck)
        for j in range(n_units - 1):
            body = residual_unit(body, n_filter, (1, 1), True,
                                 "stage%d_unit%d" % (i + 1, j + 2),
                                 bottle_neck)

    pool = mx.sym.Pooling(data=body, global_pool=True, kernel=(7, 7),
                          pool_type="avg")
    flat = mx.sym.Flatten(data=pool)
    fc1 = mx.sym.FullyConnected(data=flat, num_hidden=num_classes, name="fc1")
    return mx.sym.SoftmaxOutput(data=fc1, name="softmax")
