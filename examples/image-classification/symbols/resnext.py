"""ResNeXt symbol (mirrors reference symbols/resnext.py — aggregated
residual transforms: the bottleneck's 3x3 runs as a grouped conv with
`num_group` cardinality)."""
import mxnet_tpu as mx


def resnext_unit(data, num_filter, stride, dim_match, num_group, name):
    mid = num_filter // 2
    body = mx.sym.Convolution(data, num_filter=mid, kernel=(1, 1),
                              no_bias=True, name=name + "_conv1")
    body = mx.sym.BatchNorm(body, fix_gamma=False, eps=2e-5,
                            name=name + "_bn1")
    body = mx.sym.Activation(body, act_type="relu")
    body = mx.sym.Convolution(body, num_filter=mid, kernel=(3, 3),
                              stride=stride, pad=(1, 1),
                              num_group=num_group, no_bias=True,
                              name=name + "_conv2")
    body = mx.sym.BatchNorm(body, fix_gamma=False, eps=2e-5,
                            name=name + "_bn2")
    body = mx.sym.Activation(body, act_type="relu")
    body = mx.sym.Convolution(body, num_filter=num_filter, kernel=(1, 1),
                              no_bias=True, name=name + "_conv3")
    body = mx.sym.BatchNorm(body, fix_gamma=False, eps=2e-5,
                            name=name + "_bn3")
    if dim_match:
        shortcut = data
    else:
        shortcut = mx.sym.Convolution(data, num_filter=num_filter,
                                      kernel=(1, 1), stride=stride,
                                      no_bias=True, name=name + "_sc")
        shortcut = mx.sym.BatchNorm(shortcut, fix_gamma=False, eps=2e-5,
                                    name=name + "_sc_bn")
    return mx.sym.Activation(body + shortcut, act_type="relu")


# depth -> units per stage (same table as resnet bottleneck depths)
UNITS = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}


def get_symbol(num_classes, num_layers=50, num_group=32, **kwargs):
    if num_layers not in UNITS:
        raise ValueError("resnext depth must be one of %s" % list(UNITS))
    units = UNITS[num_layers]
    filters = [256, 512, 1024, 2048]
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, num_filter=64, kernel=(7, 7),
                             stride=(2, 2), pad=(3, 3), no_bias=True,
                             name="conv0")
    net = mx.sym.BatchNorm(net, fix_gamma=False, eps=2e-5, name="bn0")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                         pool_type="max")
    for stage, (n, f) in enumerate(zip(units, filters)):
        stride = (1, 1) if stage == 0 else (2, 2)
        net = resnext_unit(net, f, stride, False, num_group,
                           "stage%d_unit0" % stage)
        for i in range(1, n):
            net = resnext_unit(net, f, (1, 1), True, num_group,
                               "stage%d_unit%d" % (stage, i))
    net = mx.sym.Pooling(net, kernel=(7, 7), pool_type="avg",
                         global_pool=True)
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")
