"""Train an MLP/LeNet on MNIST — the reference's first baseline workload
(example/image-classification/train_mnist.py).

Uses mx.io.MNISTIter when the idx-ubyte files are present; otherwise
generates synthetic MNIST-shaped data so the script runs without
downloads.
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(__file__))
import mxnet_tpu as mx  # noqa: E402
from common import fit  # noqa: E402
import symbols  # noqa: E402


def _synthetic_mnist(n=2048, seed=0):
    """MNIST-shaped, linearly separable-ish digit blobs."""
    rng = np.random.RandomState(seed)
    protos = rng.rand(10, 1, 28, 28).astype(np.float32)
    y = rng.randint(0, 10, n)
    x = protos[y] + 0.3 * rng.rand(n, 1, 28, 28).astype(np.float32)
    return x, y.astype(np.float32)


def get_mnist_iter(args):
    flat = args.network == "mlp"
    data_dir = getattr(args, "data_dir", "data")
    train_img = os.path.join(data_dir, "train-images-idx3-ubyte")
    if not args.synthetic and os.path.exists(train_img):
        train = mx.io.MNISTIter(
            image=train_img,
            label=os.path.join(data_dir, "train-labels-idx1-ubyte"),
            batch_size=args.batch_size, shuffle=True, flat=flat)
        val = mx.io.MNISTIter(
            image=os.path.join(data_dir, "t10k-images-idx3-ubyte"),
            label=os.path.join(data_dir, "t10k-labels-idx1-ubyte"),
            batch_size=args.batch_size, flat=flat)
        return train, val
    x, y = _synthetic_mnist()
    if flat:
        x = x.reshape(len(x), -1)
    split = int(0.9 * len(x))
    train = mx.io.NDArrayIter(x[:split], y[:split], args.batch_size,
                              shuffle=True)
    val = mx.io.NDArrayIter(x[split:], y[split:], args.batch_size)
    return train, val


def main():
    parser = argparse.ArgumentParser(description="train mnist")
    parser.add_argument("--data-dir", type=str, default="data")
    fit.add_fit_args(parser)
    args = parser.parse_args()
    net = symbols.get_symbol(args.network, args.num_classes)
    mod = fit.fit(args, net, get_mnist_iter)
    train, val = get_mnist_iter(args)
    acc = dict(mod.score(val, mx.metric.Accuracy()))["accuracy"]
    print("final validation accuracy: %.4f" % acc)
    return acc


if __name__ == "__main__":
    main()
