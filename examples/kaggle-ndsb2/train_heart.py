"""Kaggle NDSB-2 heart-volume regression (mirrors reference
example/kaggle-ndsb2/Train.py — a LeNet over the per-frame DIFFERENCES
of a 30-frame cardiac MRI clip, predicting the volume as a binned
cumulative distribution through ``LogisticRegressionOutput`` (600 bins
in the reference, 100 here at toy scale), scored
with a CRPS metric that isotonises the predicted CDF; data flows in
through ``CSVIter``).

Everything distinctive survives here at toy scale: ``SliceChannel``
frame splitting + frame differencing in the graph, ``fix_gamma``
BatchNorm, Dropout, a multi-output ``LogisticRegressionOutput`` CDF
head, the monotonic-repair CRPS metric via ``mx.metric.np``, and
``CSVIter`` with a non-scalar ``label_shape`` — none of which any
other tree combines.

Synthetic "hearts": a pulsing disc whose radius over 30 frames encodes
the volume label. CRPS on held-out clips must beat the
predict-the-prior baseline by a wide margin.
"""
import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx

FRAMES = 30
SIDE = 16
BINS = 100


def make_clip(rs, volume):
    """30 frames of a disc pulsing around a volume-dependent radius."""
    clip = np.zeros((FRAMES, SIDE, SIDE), np.float32)
    yy, xx = np.mgrid[:SIDE, :SIDE]
    cy = cx = SIDE // 2
    base_r = 2.0 + 4.0 * volume / BINS
    for t in range(FRAMES):
        r = base_r * (1.0 + 0.3 * np.sin(2 * np.pi * t / FRAMES))
        clip[t][(yy - cy) ** 2 + (xx - cx) ** 2 < r * r] = 255.0
    clip += 8.0 * rs.normal(size=clip.shape).astype(np.float32)
    return clip


def encode_label(volumes):
    """Volume -> its CDF over the bin grid (reference encode_label)."""
    return np.array([(v < np.arange(BINS)) for v in volumes],
                    dtype=np.float32)


def crps(label, pred):
    """Reference CRPS: isotonise the CDF, then mean squared difference."""
    pred = pred.copy()
    for j in range(pred.shape[1] - 1):
        pred[:, j + 1] = np.maximum(pred[:, j + 1], pred[:, j])
    return np.sum(np.square(label - pred)) / label.size


def build():
    source = mx.sym.Variable("data")
    source = (source - 128) * (1.0 / 128)
    frames = mx.sym.SliceChannel(source, num_outputs=FRAMES)
    diffs = [frames[i + 1] - frames[i] for i in range(FRAMES - 1)]
    source = mx.sym.Concat(*diffs)
    net = mx.sym.Convolution(source, kernel=(5, 5), num_filter=16,
                             name="conv1")
    net = mx.sym.BatchNorm(net, fix_gamma=True)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2))
    net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=16,
                             name="conv2")
    net = mx.sym.BatchNorm(net, fix_gamma=True)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2))
    flatten = mx.sym.Flatten(net)
    flatten = mx.sym.Dropout(flatten)
    fc1 = mx.sym.FullyConnected(data=flatten, num_hidden=BINS)
    # name it softmax so it matches the iterator's label name, exactly
    # like the reference comment says
    return mx.sym.LogisticRegressionOutput(data=fc1, name="softmax")


def write_csvs(work, rs, n, tag):
    volumes = rs.uniform(5, BINS - 5, n)
    data = np.stack([make_clip(rs, v) for v in volumes])
    data_csv = os.path.join(work, "%s-data.csv" % tag)
    label_csv = os.path.join(work, "%s-label.csv" % tag)
    np.savetxt(data_csv, data.reshape(n, -1), delimiter=",", fmt="%.1f")
    np.savetxt(label_csv, encode_label(volumes), delimiter=",", fmt="%g")
    return data_csv, label_csv, volumes


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=14)
    ap.add_argument("--train-size", type=int, default=160)
    ap.add_argument("--batch-size", type=int, default=32)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    work = tempfile.mkdtemp(prefix="ndsb2_")
    tr_data, tr_label, _ = write_csvs(work, rs, args.train_size, "train")
    va_data, va_label, _ = write_csvs(work, rs, 64, "val")

    data_train = mx.io.CSVIter(data_csv=tr_data,
                               data_shape=(FRAMES, SIDE, SIDE),
                               label_csv=tr_label, label_shape=(BINS,),
                               batch_size=args.batch_size)
    data_val = mx.io.CSVIter(data_csv=va_data,
                             data_shape=(FRAMES, SIDE, SIDE),
                             label_csv=va_label, label_shape=(BINS,),
                             batch_size=args.batch_size)

    mod = mx.mod.Module(build(), context=mx.current_context())
    metric = mx.metric.np(crps)
    mod.fit(data_train, eval_data=data_val, eval_metric=metric,
            num_epoch=args.num_epochs,
            initializer=mx.initializer.Xavier(),
            optimizer="adam",
            optimizer_params={"learning_rate": 2e-3})

    data_val.reset()
    metric.reset()
    mod.score(data_val, metric)
    score = metric.get()[1]

    # predict-the-training-prior baseline: a flat 0.5 CDF everywhere
    labels = np.loadtxt(va_label, delimiter=",")
    base = crps(labels, np.full_like(labels, 0.5))
    print("val CRPS %.4f (flat-prior baseline %.4f)" % (score, base))
    assert score < base * 0.4, "CDF head should beat the prior easily"
    print("ndsb2 ok")


if __name__ == "__main__":
    main()
