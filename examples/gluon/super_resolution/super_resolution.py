"""ESPCN super-resolution (mirrors reference
example/gluon/super_resolution.py — conv stack ending in an
``upscale^2``-channel conv whose output pixel-shuffles (the reshape/
transpose ``_rearrange``) into the upscaled image; L2 loss; PSNR eval).

Same sub-pixel rearrange chain (including the reference's -4/-3
reshape codes), trained on synthetic band-limited textures so the 2x
upscale is learnable: PSNR must clearly beat nearest-neighbour
upsampling.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..",
                                ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn
from mxnet_tpu import ndarray as F


def _rearrange(raw, upscale):
    """(N, r^2, H, W) -> (N, 1, H*r, W*r) — the reference's pixel
    shuffle, verbatim reshape codes."""
    splitted = F.reshape(raw, shape=(0, -4, -1, upscale ** 2, 0, 0))
    unflatten = F.reshape(splitted, shape=(0, 0, -4, upscale, upscale,
                                           0, 0))
    swapped = F.transpose(unflatten, axes=(0, 1, 4, 2, 5, 3))
    return F.reshape(swapped, shape=(0, 0, -3, -3))


class SuperResolutionNet(gluon.Block):
    def __init__(self, upscale):
        super().__init__()
        with self.name_scope():
            self.conv1 = nn.Conv2D(32, (5, 5), padding=(2, 2))
            self.conv2 = nn.Conv2D(32, (3, 3), padding=(1, 1))
            self.conv3 = nn.Conv2D(16, (3, 3), padding=(1, 1))
            self.conv4 = nn.Conv2D(upscale ** 2, (3, 3), padding=(1, 1))
        self.upscale = upscale

    def forward(self, x):
        x = F.Activation(self.conv1(x), act_type="relu")
        x = F.Activation(self.conv2(x), act_type="relu")
        x = F.Activation(self.conv3(x), act_type="relu")
        return _rearrange(self.conv4(x), self.upscale)


def make_images(rs, n, size):
    """Smooth band-limited textures: sums of low-frequency waves."""
    yy, xx = np.mgrid[:size, :size] / float(size)
    imgs = np.zeros((n, 1, size, size), np.float32)
    for i in range(n):
        img = np.zeros((size, size))
        for _ in range(4):
            fx, fy = rs.uniform(0.5, 3, 2)
            ph = rs.uniform(0, 2 * np.pi, 2)
            img += rs.uniform(0.2, 1.0) * np.sin(
                2 * np.pi * fx * xx + ph[0]) * np.sin(
                2 * np.pi * fy * yy + ph[1])
        img = (img - img.min()) / (np.ptp(img) + 1e-9)
        imgs[i, 0] = img
    return imgs


def psnr(a, b):
    mse = float(np.mean((a - b) ** 2))
    return 10.0 * np.log10(1.0 / max(mse, 1e-10))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=300)
    ap.add_argument("--upscale", type=int, default=2)
    ap.add_argument("--size", type=int, default=32)
    ap.add_argument("--train-size", type=int, default=64)
    args = ap.parse_args()

    mx.random.seed(3)
    np.random.seed(3)
    rs = np.random.RandomState(3)
    hi = make_images(rs, args.train_size, args.size)
    lo = hi[:, :, ::args.upscale, ::args.upscale]   # decimated input
    hi_t, lo_t = nd.array(hi), nd.array(lo)
    hi_v = make_images(rs, 16, args.size)
    lo_v = hi_v[:, :, ::args.upscale, ::args.upscale]

    net = SuperResolutionNet(args.upscale)
    net.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    l2 = gluon.loss.L2Loss()

    for epoch in range(args.num_epochs):
        with autograd.record():
            out = net(lo_t)
            loss = l2(out, hi_t)
        loss.backward()
        trainer.step(args.train_size)
        if epoch % 10 == 0 or epoch == args.num_epochs - 1:
            print("epoch %d l2 %.5f" % (epoch,
                                        float(loss.mean().asnumpy())))

    pred = net(nd.array(lo_v)).asnumpy()
    model_psnr = psnr(np.clip(pred, 0, 1), hi_v)
    nearest = np.repeat(np.repeat(lo_v, args.upscale, axis=2),
                        args.upscale, axis=3)
    base_psnr = psnr(nearest, hi_v)
    print("PSNR: model %.2f dB vs nearest-neighbour %.2f dB"
          % (model_psnr, base_psnr))
    assert model_psnr > base_psnr + 2.0, \
        "sub-pixel net should beat nearest clearly"
    print("super-resolution ok")


if __name__ == "__main__":
    main()
