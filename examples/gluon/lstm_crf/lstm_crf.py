"""BiLSTM-CRF sequence tagger (mirrors reference
example/gluon/lstm_crf.py — imperative gluon Block with a CRF layer:
the forward algorithm as differentiable log-partition, Viterbi decode
at inference).

TPU-first deviation from the reference: the forward recursion is
VECTORISED over tags (one logsumexp per timestep instead of the
reference's per-tag python loop), so each step is one fused XLA
reduction; the transition matrix is a proper gluon Parameter trained
with everything else. Synthetic tagging grammar (determiner-noun-verb
agreement) stands in for the tutorial data; Viterbi accuracy must
approach 1.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..",
                                ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import Block, nn, rnn

START, STOP = 0, 1           # special tags
TAGS = {"<start>": 0, "<stop>": 1, "DET": 2, "NOUN": 3, "VERB": 4}
K = len(TAGS)


def log_sum_exp(x, axis):
    m = nd.max(x, axis=axis, keepdims=True)
    return (nd.log(nd.sum(nd.exp(x - m), axis=axis, keepdims=True))
            + m).reshape((-1,))


class BiLSTM_CRF(Block):
    def __init__(self, vocab_size, embedding_dim, hidden_dim):
        super().__init__()
        with self.name_scope():
            self.embed = nn.Embedding(vocab_size, embedding_dim)
            self.lstm = rnn.LSTM(hidden_dim // 2, bidirectional=True,
                                 layout="TNC")
            self.hidden2tag = nn.Dense(K)
            # transitions[i, j]: score of moving TO tag i FROM tag j
            self.transitions = self.params.get(
                "transitions", shape=(K, K),
                init=mx.initializer.Normal(0.1))

    def _features(self, sentence):
        emb = self.embed(sentence).reshape((len(sentence), 1, -1))
        out = self.lstm(emb)
        return self.hidden2tag(out.reshape((len(sentence), -1)))

    def _forward_alg(self, feats):
        """log Z, vectorised: one logsumexp over previous tags/step."""
        trans = self.transitions.data()
        alphas = nd.array([-10000.0] * K)
        alphas[START] = 0.0
        for t in range(feats.shape[0]):
            # next[j] = LSE_i(alpha[i] + trans[j, i]) + feat[j]
            scores = alphas.reshape((1, K)) + trans
            alphas = log_sum_exp(scores, axis=1) + feats[t]
        terminal = alphas + trans[STOP]
        return log_sum_exp(terminal.reshape((1, K)), axis=1)

    def _score_sentence(self, feats, tags):
        trans = self.transitions.data()
        score = nd.zeros((1,))
        prev = START
        for t in range(feats.shape[0]):
            cur = int(tags[t])
            score = score + trans[cur, prev] + feats[t, cur]
            prev = cur
        return score + trans[STOP, prev]

    def neg_log_likelihood(self, sentence, tags):
        feats = self._features(sentence)
        return self._forward_alg(feats) - self._score_sentence(feats, tags)

    def viterbi(self, sentence):
        """Best path (numpy DP over the trained scores; inference only)."""
        feats = self._features(sentence).asnumpy()
        trans = self.transitions.data().asnumpy()
        score = np.full(K, -10000.0)
        score[START] = 0.0
        back = []
        for t in range(len(feats)):
            m = score[None, :] + trans          # (to, from)
            bp = m.argmax(axis=1)
            score = m.max(axis=1) + feats[t]
            back.append(bp)
        score = score + trans[STOP]
        best = int(score.argmax())
        path = [best]
        for bp in reversed(back):
            best = int(bp[best])
            path.append(best)
        path.reverse()
        assert path[0] == START
        return path[1:]


def make_corpus(rs, n):
    """det noun verb [det noun] sentences over a toy vocabulary."""
    dets = ["the", "a"]
    nouns = ["dog", "cat", "bird", "fish"]
    verbs = ["chased", "saw", "ate"]
    vocab = {w: i for i, w in enumerate(dets + nouns + verbs)}
    tag_of = {**{w: TAGS["DET"] for w in dets},
              **{w: TAGS["NOUN"] for w in nouns},
              **{w: TAGS["VERB"] for w in verbs}}
    data = []
    for _ in range(n):
        sent = [rs.choice(dets), rs.choice(nouns), rs.choice(verbs)]
        if rs.rand() < 0.5:
            sent += [rs.choice(dets), rs.choice(nouns)]
        words = nd.array([vocab[w] for w in sent])
        tags = [tag_of[w] for w in sent]
        data.append((words, tags))
    return data, vocab


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=8)
    ap.add_argument("--train-size", type=int, default=24)
    args = ap.parse_args()

    mx.random.seed(1)
    np.random.seed(1)
    rs = np.random.RandomState(1)
    data, vocab = make_corpus(rs, args.train_size)

    model = BiLSTM_CRF(len(vocab), embedding_dim=8, hidden_dim=8)
    model.initialize()
    trainer = gluon.Trainer(model.collect_params(), "sgd",
                            {"learning_rate": 0.01, "wd": 1e-4})

    for epoch in range(args.num_epochs):
        total = 0.0
        for words, tags in data:
            with autograd.record():
                loss = model.neg_log_likelihood(words, tags)
            loss.backward()
            trainer.step(1)
            total += float(loss.asnumpy()[0])
        if epoch % 2 == 0 or epoch == args.num_epochs - 1:
            print("epoch %d nll %.3f" % (epoch, total / len(data)))

    correct = total_tags = 0
    for words, tags in data:
        pred = model.viterbi(words)
        correct += sum(int(p == t) for p, t in zip(pred, tags))
        total_tags += len(tags)
    acc = correct / total_tags
    print("viterbi tag accuracy %.3f" % acc)
    assert acc > 0.95, "CRF should nail the deterministic grammar"
    print("lstm-crf ok")


if __name__ == "__main__":
    main()
