"""Child-Sum Tree-LSTM (mirrors reference example/gluon/tree_lstm/ —
Tai et al. 2015 recursive composition over per-sample tree structures,
the canonical imperative-gluon workload: the compute graph is rebuilt
per example from its parse tree, something a static symbolic graph
cannot express).

Task: Boolean formula evaluation. Each sample is a random binary tree
whose leaves are literals (0/1) and whose internal nodes are AND or OR
gates (the gate type is an input token, its semantics unlearned); the
model must learn to EVALUATE the formula by recursing bottom-up.
Accuracy must clear 0.95 — impossible without using the structure.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..",
                                ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import Block, nn

# token vocabulary: 0, 1, AND, OR
TOK_ZERO, TOK_ONE, TOK_AND, TOK_OR = range(4)


class ChildSumTreeLSTMCell(Block):
    """(parity: the reference tree_lstm ChildSumLSTMCell)"""

    def __init__(self, hidden):
        super().__init__()
        self._h = hidden
        with self.name_scope():
            self.embed = nn.Embedding(4, hidden)
            self.W_iou = nn.Dense(3 * hidden)          # input, output, u
            self.U_iou = nn.Dense(3 * hidden, use_bias=False)
            self.W_f = nn.Dense(hidden)
            self.U_f = nn.Dense(hidden, use_bias=False)

    def node(self, token, children):
        """children: list of (h, c); returns (h, c), each (1, H)."""
        x = self.embed(nd.array([token]))
        if children:
            h_tilde = children[0][0]
            for h_k, _ in children[1:]:
                h_tilde = h_tilde + h_k
        else:
            h_tilde = nd.zeros((1, self._h))
        iou = self.W_iou(x) + self.U_iou(h_tilde)
        H = self._h
        i = nd.sigmoid(iou[:, :H])
        o = nd.sigmoid(iou[:, H:2 * H])
        u = nd.tanh(iou[:, 2 * H:])
        c = i * u
        wfx = self.W_f(x)                 # constant across children
        for h_k, c_k in children:
            f_k = nd.sigmoid(wfx + self.U_f(h_k))
            c = c + f_k * c_k
        h = o * nd.tanh(c)
        return h, c


class TreeClassifier(Block):
    def __init__(self, hidden):
        super().__init__()
        with self.name_scope():
            self.cell = ChildSumTreeLSTMCell(hidden)
            self.out = nn.Dense(2)

    def encode(self, tree):
        token, kids = tree
        states = [self.encode(k) for k in kids]
        return self.cell.node(token, states)

    def forward(self, tree):
        h, _ = self.encode(tree)
        return self.out(h)


def random_tree(rs, depth):
    """(token, children); leaves are literals, gates are AND/OR."""
    if depth == 0 or rs.rand() < 0.3:
        return (int(rs.randint(0, 2)), [])
    gate = TOK_AND if rs.rand() < 0.5 else TOK_OR
    return (gate, [random_tree(rs, depth - 1),
                   random_tree(rs, depth - 1)])


def evaluate(tree):
    token, kids = tree
    if not kids:
        return token
    vals = [evaluate(k) for k in kids]
    return (min(vals) if token == TOK_AND else max(vals))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=12)
    ap.add_argument("--train-size", type=int, default=80)
    ap.add_argument("--hidden", type=int, default=16)
    ap.add_argument("--depth", type=int, default=3)
    args = ap.parse_args()
    if args.depth < 1:
        ap.error("--depth must be >= 1 (depth-0 trees are bare literals)")

    mx.random.seed(7)
    np.random.seed(7)
    rs = np.random.RandomState(7)
    data = []
    while len(data) < args.train_size:
        t = random_tree(rs, args.depth)
        if t[1]:                       # skip bare-literal "trees"
            data.append((t, evaluate(t)))

    model = TreeClassifier(args.hidden)
    model.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "adam",
                            {"learning_rate": 0.03})
    sce = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.num_epochs):
        total = 0.0
        for tree, label in data:
            with autograd.record():
                logits = model(tree)
                loss = sce(logits, nd.array([label]))
            loss.backward()
            trainer.step(1)
            total += float(loss.asnumpy()[0])
        if epoch % 3 == 0 or epoch == args.num_epochs - 1:
            print("epoch %d loss %.4f" % (epoch, total / len(data)))

    correct = 0
    for tree, label in data:
        pred = int(model(tree).asnumpy().argmax())
        correct += int(pred == label)
    acc = correct / len(data)
    print("formula evaluation accuracy %.3f" % acc)
    assert acc > 0.95, "recursive evaluation should be learnable"
    print("tree-lstm ok")


if __name__ == "__main__":
    main()
