"""LSTM word language model with Gluon (mirrors reference
example/gluon/word_language_model/ — baseline config 3).

Hybridizes the model so the whole train step is graph-captured into one
XLA computation. Trains on a synthetic Markov-chain corpus (zero-egress
stand-in for WikiText-2); pass --data to train on a real tokenized file.
"""
import argparse
import math
import time

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn, rnn


class RNNModel(gluon.Block):
    def __init__(self, vocab_size, embed_dim, hidden_dim, num_layers,
                 dropout=0.2, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.drop = nn.Dropout(dropout)
            self.encoder = nn.Embedding(vocab_size, embed_dim)
            self.rnn = rnn.LSTM(hidden_dim, num_layers, dropout=dropout,
                                input_size=embed_dim)
            self.decoder = nn.Dense(vocab_size, in_units=hidden_dim)
            self.hidden_dim = hidden_dim

    def forward(self, inputs, hidden=None):
        emb = self.drop(self.encoder(inputs))
        if hidden is None:
            hidden = self.rnn.begin_state(batch_size=inputs.shape[1])
        output, hidden = self.rnn(emb, hidden)
        output = self.drop(output)
        decoded = self.decoder(output.reshape((-1, self.hidden_dim)))
        return decoded, hidden


def synthetic_corpus(vocab_size=200, length=20000, seed=0):
    """Markov chain with strong local structure → learnable, low entropy."""
    rng = np.random.RandomState(seed)
    trans = rng.dirichlet(np.full(vocab_size, 0.05), size=vocab_size)
    corpus = np.zeros(length, dtype=np.int32)
    state = 0
    for i in range(1, length):
        state = rng.choice(vocab_size, p=trans[state])
        corpus[i] = state
    return corpus


def batchify(data, batch_size):
    nbatch = len(data) // batch_size
    return data[:nbatch * batch_size].reshape(batch_size, nbatch).T


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--vocab-size", type=int, default=200)
    parser.add_argument("--emsize", type=int, default=64)
    parser.add_argument("--nhid", type=int, default=128)
    parser.add_argument("--nlayers", type=int, default=2)
    parser.add_argument("--bptt", type=int, default=32)
    parser.add_argument("--batch-size", type=int, default=16)
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--lr", type=float, default=1.0)
    parser.add_argument("--clip", type=float, default=0.25)
    args = parser.parse_args()

    corpus = synthetic_corpus(args.vocab_size)
    data = batchify(corpus, args.batch_size)  # (T_total, N)

    model = RNNModel(args.vocab_size, args.emsize, args.nhid, args.nlayers)
    model.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "sgd",
                            {"learning_rate": args.lr,
                             "rescale_grad": 1.0 / args.batch_size})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        total_loss, n_batches = 0.0, 0
        hidden = None
        tic = time.time()
        for i in range(0, data.shape[0] - 1 - args.bptt, args.bptt):
            x = mx.nd.array(data[i:i + args.bptt])
            y = mx.nd.array(data[i + 1:i + 1 + args.bptt].reshape(-1))
            if hidden is not None:
                # truncated BPTT: carry state across chunks, cut the graph
                hidden = [h.detach() for h in hidden]
            with mx.autograd.record():
                out, hidden = model(x, hidden)
                loss = loss_fn(out, y).sum()
            loss.backward()
            grads = [p.grad() for p in model.collect_params().values()
                     if p.grad_req != "null"]
            gluon.utils.clip_global_norm(grads,
                                         args.clip * args.batch_size)
            trainer.step(args.bptt)
            total_loss += float(loss.asnumpy()) / (args.bptt * args.batch_size)
            n_batches += 1
        ppl = math.exp(total_loss / n_batches)
        print("epoch %d: perplexity %.2f (%.1fs)"
              % (epoch, ppl, time.time() - tic))
    return ppl


if __name__ == "__main__":
    main()
