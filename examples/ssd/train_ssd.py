"""SSD-style detection training step (mirrors reference example/ssd/ —
baseline config 4): multi-scale features → MultiBoxPrior anchors →
MultiBoxTarget assignment → cls + loc losses → MultiBoxDetection decode
with NMS. Synthetic boxes; the point is exercising the contrib ops
end-to-end.
"""
import argparse

import numpy as np

import mxnet_tpu as mx


def build_ssd(num_classes=2, num_anchors_cfg=((0.2, 0.4), (0.5, 0.7))):
    """Tiny two-scale SSD head over a conv backbone
    (reference: example/ssd/symbol/symbol_builder.py:90-109)."""
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")

    body = mx.sym.Convolution(data=data, num_filter=16, kernel=(3, 3),
                              pad=(1, 1), name="c1")
    body = mx.sym.Activation(body, act_type="relu")
    feat1 = mx.sym.Pooling(body, kernel=(2, 2), stride=(2, 2),
                           pool_type="max")          # 1/2 scale
    body = mx.sym.Convolution(feat1, num_filter=32, kernel=(3, 3),
                              pad=(1, 1), name="c2")
    body = mx.sym.Activation(body, act_type="relu")
    feat2 = mx.sym.Pooling(body, kernel=(2, 2), stride=(2, 2),
                           pool_type="max")          # 1/4 scale

    cls_preds, loc_preds, anchors = [], [], []
    for i, (feat, sizes) in enumerate(zip([feat1, feat2], num_anchors_cfg)):
        na = len(sizes)
        cls = mx.sym.Convolution(feat, num_filter=na * (num_classes + 1),
                                 kernel=(3, 3), pad=(1, 1),
                                 name="cls_pred%d" % i)
        cls = mx.sym.transpose(cls, axes=(0, 2, 3, 1))
        cls_preds.append(mx.sym.Reshape(cls, shape=(0, -1, num_classes + 1)))
        loc = mx.sym.Convolution(feat, num_filter=na * 4, kernel=(3, 3),
                                 pad=(1, 1), name="loc_pred%d" % i)
        loc = mx.sym.transpose(loc, axes=(0, 2, 3, 1))
        loc_preds.append(mx.sym.Reshape(loc, shape=(0, -1)))
        anchors.append(mx.sym.contrib.MultiBoxPrior(
            feat, sizes=list(sizes), ratios=[1.0, 2.0, 0.5][:1]))

    cls_pred = mx.sym.Concat(*cls_preds, dim=1)     # (N, A, C+1)
    loc_pred = mx.sym.Concat(*loc_preds, dim=1)     # (N, A*4)
    anchor = mx.sym.Concat(*anchors, dim=1)         # (1, A, 4)
    cls_pred_t = mx.sym.transpose(cls_pred, axes=(0, 2, 1))

    loc_target, loc_mask, cls_target = mx.sym.contrib.MultiBoxTarget(
        anchor, label, cls_pred_t)
    cls_prob = mx.sym.SoftmaxOutput(data=cls_pred_t, label=cls_target,
                                    multi_output=True, use_ignore=True,
                                    ignore_label=-1, name="cls_prob")
    loc_diff = loc_mask * (loc_pred - loc_target)
    loc_loss = mx.sym.MakeLoss(mx.sym.smooth_l1(loc_diff, scalar=1.0),
                               name="loc_loss")
    det = mx.sym.contrib.MultiBoxDetection(cls_prob, loc_pred, anchor,
                                           nms_threshold=0.5)
    det = mx.sym.BlockGrad(det, name="det")
    return mx.sym.Group([cls_prob, loc_loss, det])


def synthetic_batch(batch_size, size=32, num_obj=2, seed=0):
    rng = np.random.RandomState(seed)
    imgs = rng.rand(batch_size, 3, size, size).astype(np.float32)
    labels = np.full((batch_size, num_obj, 5), -1, np.float32)
    for b in range(batch_size):
        for o in range(num_obj):
            cx, cy = rng.uniform(0.2, 0.8, 2)
            w, h = rng.uniform(0.1, 0.3, 2)
            labels[b, o] = [rng.randint(0, 2), cx - w / 2, cy - h / 2,
                            cx + w / 2, cy + h / 2]
    return imgs, labels


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--iters", type=int, default=10)
    args = parser.parse_args()

    net = build_ssd()
    imgs, labels = synthetic_batch(args.batch_size)
    mod = mx.mod.Module(net, data_names=["data"], label_names=["label"])
    mod.bind(data_shapes=[("data", imgs.shape)],
             label_shapes=[("label", labels.shape)])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.01,
                                         "rescale_grad": 1.0 / args.batch_size})
    batch = mx.io.DataBatch(data=[mx.nd.array(imgs)],
                            label=[mx.nd.array(labels)])
    for i in range(args.iters):
        mod.forward_backward(batch)
        mod.update()
    mod.forward(batch, is_train=False)
    det = mod.get_outputs()[2].asnumpy()
    kept = (det[:, :, 0] >= 0).sum()
    print("training ran %d iters; detection output %s, %d boxes kept"
          % (args.iters, det.shape, kept))


if __name__ == "__main__":
    main()
