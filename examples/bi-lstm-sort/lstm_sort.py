"""Sort a sequence of symbols with a bidirectional LSTM (mirrors
reference example/bi-lstm-sort/lstm_sort.py — the classic BiLSTM
sanity task: input k random tokens, output the same tokens sorted;
every output position needs BOTH directions' context).

Exercises: BidirectionalCell over LSTMCell (unroll + output merge),
per-timestep shared-weight FullyConnected via Reshape, multi-timestep
SoftmaxOutput with sequence labels, and the rnn-cell parameter sharing
machinery — a combination no other example tree runs.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx


def build(seqlen, vocab, nhid):
    data = mx.sym.Variable("data")                      # (B, T)
    label = mx.sym.Variable("softmax_label")            # (B, T)
    emb = mx.sym.Embedding(data, input_dim=vocab, output_dim=nhid,
                           name="embed")                # (B, T, H)
    bi = mx.rnn.BidirectionalCell(
        mx.rnn.LSTMCell(nhid, prefix="l_"),
        mx.rnn.LSTMCell(nhid, prefix="r_"))
    outputs, _ = bi.unroll(seqlen, inputs=emb, merge_outputs=True,
                           layout="NTC")                # (B, T, 2H)
    flat = mx.sym.Reshape(outputs, shape=(-1, 2 * nhid))
    logits = mx.sym.FullyConnected(flat, num_hidden=vocab, name="cls")
    lab = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(logits, lab, name="softmax")


def make_data(rs, n, seqlen, vocab):
    x = rs.randint(0, vocab, size=(n, seqlen)).astype(np.float32)
    y = np.sort(x, axis=1)
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=12)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--seqlen", type=int, default=6)
    ap.add_argument("--vocab", type=int, default=8)
    ap.add_argument("--nhid", type=int, default=32)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    x, y = make_data(rs, 1024, args.seqlen, args.vocab)
    it = mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True)

    mod = mx.mod.Module(build(args.seqlen, args.vocab, args.nhid),
                        context=mx.current_context())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 5e-3})
    for epoch in range(args.num_epochs):
        it.reset()
        correct = total = 0
        for batch in it:
            mod.forward(batch, is_train=True)
            pred = mod.get_outputs()[0].asnumpy()       # (B*T, V)
            lab = batch.label[0].asnumpy().reshape(-1)
            correct += int((np.argmax(pred, 1) == lab).sum())
            total += lab.size
            mod.backward()
            mod.update()
        print("epoch %d per-token sort accuracy %.3f"
              % (epoch, correct / total))
    acc = correct / total
    assert acc > 0.8, acc
    print("BI_LSTM_SORT_OK")


if __name__ == "__main__":
    main()
