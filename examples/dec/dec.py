"""Deep Embedded Clustering (mirrors reference example/dec/dec.py —
autoencoder pretraining, then cluster refinement: Student-t soft
assignment against learnable centroids, self-training on the sharpened
target distribution, KL loss).

Synthetic mixture-of-Gaussians data keeps it egress-free and lets the
final clustering be scored against ground truth. Exercises the pieces
no other tree combines: a pretrained encoder re-entered as a feature
extractor, extra trainable variables (centroids) OUTSIDE the network
weights, broadcast_sub/square distance matrices, and a custom KL
objective through MakeLoss.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx


def encoder_sym(dims):
    x = mx.sym.Variable("data")
    for i, d in enumerate(dims):
        x = mx.sym.FullyConnected(x, num_hidden=d, name="enc%d" % i)
        if i < len(dims) - 1:
            x = mx.sym.Activation(x, act_type="relu")
    return x


def dec_sym(dims, k):
    """Encoder + Student-t soft assignment + KL(P||Q) loss.
    q_ij = (1 + |z_i - mu_j|^2)^-1, normalised; p is fed as data."""
    z = encoder_sym(dims)                                # (B, d)
    mu = mx.sym.Variable("centroids", shape=(k, dims[-1]))
    p = mx.sym.Variable("target_p")                      # (B, k)
    zb = mx.sym.Reshape(z, shape=(-1, 1, dims[-1]))
    mub = mx.sym.Reshape(mu, shape=(1, k, dims[-1]))
    d2 = mx.sym.sum(mx.sym.square(mx.sym.broadcast_sub(zb, mub)), axis=2)
    q_un = 1.0 / (1.0 + d2)
    q = mx.sym.broadcast_div(q_un, mx.sym.sum(q_un, axis=1, keepdims=True))
    kl = mx.sym.sum(p * (mx.sym.log(p + 1e-10) - mx.sym.log(q + 1e-10)),
                    axis=1)
    loss = mx.sym.MakeLoss(mx.sym.mean(kl), name="kl_loss")
    return mx.sym.Group([loss, mx.sym.BlockGrad(q)])


def make_data(rs, n, dim, k):
    centers = rs.normal(0, 4.0, (k, dim)).astype(np.float32)
    y = rs.randint(0, k, n)
    x = centers[y] + rs.normal(0, 0.6, (n, dim)).astype(np.float32)
    return x.astype(np.float32), y


def cluster_acc(assign, y, k):
    """Best-match accuracy via greedy label alignment (the reference
    uses the Hungarian algorithm; greedy is fine at k=4)."""
    total = 0
    used = set()
    for c in range(k):
        counts = np.bincount(y[assign == c], minlength=k).astype(float)
        for u in used:
            counts[u] = -1
        best = int(np.argmax(counts))
        used.add(best)
        total += int(counts[best]) if counts[best] > 0 else 0
    return total / len(y)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pretrain-epochs", type=int, default=12)
    ap.add_argument("--refine-iters", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=256)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    DIM, K, NZ = 16, 4, 4
    x, y = make_data(rs, 1024, DIM, K)

    # stage 1: autoencoder pretraining of the encoder (reference dec.py
    # reuses the example/autoencoder stack the same way)
    enc_dims = [12, NZ]
    data = mx.sym.Variable("data")
    h = data
    for i, d in enumerate(enc_dims):
        h = mx.sym.FullyConnected(h, num_hidden=d, name="enc%d" % i)
        if i < len(enc_dims) - 1:
            h = mx.sym.Activation(h, act_type="relu")
    r = h
    for i, d in enumerate([12, DIM]):
        r = mx.sym.FullyConnected(r, num_hidden=d, name="dec%d" % i)
        if i == 0:
            r = mx.sym.Activation(r, act_type="relu")
    ae = mx.sym.LinearRegressionOutput(r, data, name="rec")
    ae_mod = mx.mod.Module(ae, label_names=[], context=mx.current_context())
    it = mx.io.NDArrayIter(x, None, batch_size=args.batch_size, shuffle=True)
    ae_mod.bind(data_shapes=it.provide_data)
    ae_mod.init_params(mx.initializer.Xavier())
    ae_mod.init_optimizer(optimizer="adam",
                          optimizer_params={"learning_rate": 3e-3})
    for epoch in range(args.pretrain_epochs):
        it.reset()
        for batch in it:
            ae_mod.forward(batch, is_train=True)
            ae_mod.backward()
            ae_mod.update()

    # stage 2: DEC refinement — encoder weights carry over; centroids
    # initialise from per-class feature means of a q-argmax warm pass
    arg_p, aux_p = ae_mod.get_params()
    dec = dec_sym(enc_dims, K)
    mod = mx.mod.Module(dec, data_names=["data", "target_p"],
                        label_names=[], context=mx.current_context())
    from mxnet_tpu.io import DataBatch, DataDesc
    B = x.shape[0]
    mod.bind(data_shapes=[DataDesc("data", (B, DIM)),
                          DataDesc("target_p", (B, K))])
    # feature pass to seed centroids (kmeans-lite: random + one mean step)
    enc_only = encoder_sym(enc_dims)
    feat_mod = mx.mod.Module(enc_only, label_names=[],
                             context=mx.current_context())
    feat_mod.bind(data_shapes=[DataDesc("data", (B, DIM))])
    feat_mod.init_params(arg_params=arg_p, aux_params=aux_p,
                         allow_missing=False, initializer=None)
    feat_mod.forward(DataBatch([mx.nd.array(x)], [], pad=0), is_train=False)
    z = feat_mod.get_outputs()[0].asnumpy()
    # farthest-point (kmeans++-style) seeding avoids the two-centroids-
    # in-one-cluster local optimum a random seed can hit
    first = int(rs.randint(B))
    chosen = [first]
    for _ in range(K - 1):
        d2s = np.min(((z[:, None, :] - z[chosen][None]) ** 2).sum(2), axis=1)
        chosen.append(int(np.argmax(d2s)))
    mu = z[chosen].copy()
    for _ in range(10):  # plain kmeans on features
        d2 = ((z[:, None, :] - mu[None]) ** 2).sum(2)
        a = np.argmin(d2, 1)
        for c in range(K):
            if (a == c).any():
                mu[c] = z[a == c].mean(0)

    init_args = dict(arg_p)
    init_args["centroids"] = mx.nd.array(mu)
    mod.init_params(arg_params=init_args, aux_params=aux_p,
                    allow_missing=True, initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9})

    xb = mx.nd.array(x)
    for t in range(args.refine_iters):
        # E-ish step: current q -> sharpened target p (self-training)
        mod.forward(DataBatch([xb, mx.nd.zeros((B, K))], [], pad=0),
                    is_train=False)
        q = mod.get_outputs()[1].asnumpy()
        w = (q ** 2) / q.sum(0, keepdims=True)
        p = w / w.sum(1, keepdims=True)
        # M step: one KL gradient step on encoder + centroids
        mod.forward(DataBatch([xb, mx.nd.array(p)], [], pad=0),
                    is_train=True)
        kl = float(mod.get_outputs()[0].asnumpy())
        mod.backward()
        mod.update()
        if t % 10 == 0:
            acc = cluster_acc(np.argmax(q, 1), y, K)
            print("iter %d kl %.4f cluster-acc %.3f" % (t, kl, acc))

    acc = cluster_acc(np.argmax(q, 1), y, K)
    print("final cluster accuracy %.3f" % acc)
    assert acc > 0.85, acc
    print("DEC_OK")


if __name__ == "__main__":
    main()
