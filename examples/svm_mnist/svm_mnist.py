"""Multiclass SVM on image features (mirrors reference
example/svm_mnist/svm_mnist.py — the same MLP but trained with
SVMOutput's hinge loss instead of softmax cross-entropy, both the L2
and L1 margin variants).

Synthetic separable digits keep it egress-free. Exercises SVMOutput
(margin/regularization_coefficient/use_linear — no other tree touches
the hinge-loss head) and compares the two margin modes converge.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx


def build(use_linear):
    data = mx.sym.Variable("data")
    h = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    h = mx.sym.FullyConnected(h, num_hidden=10, name="fc2")
    return mx.sym.SVMOutput(h, margin=1.0, regularization_coefficient=1e-3,
                            use_linear=use_linear, name="svm")


def make_data(rs, n, dim=64):
    protos = rs.normal(0, 1.0, (10, dim)).astype(np.float32)
    y = rs.randint(0, 10, n).astype(np.float32)
    x = protos[y.astype(int)] + 0.3 * rs.normal(size=(n, dim)).astype(
        np.float32)
    return x, y


def train_one(use_linear, args, x, y):
    it = mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True,
                           label_name="svm_label")
    mod = mx.mod.Module(build(use_linear), label_names=["svm_label"],
                        context=mx.current_context())
    metric = mx.metric.Accuracy()
    mod.fit(it, eval_metric=metric, num_epoch=args.num_epochs,
            initializer=mx.initializer.Xavier(),
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    it.reset()
    metric.reset()
    mod.score(it, metric)
    return metric.get()[1]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=8)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    x, y = make_data(rs, 1024)
    for use_linear in (False, True):
        acc = train_one(use_linear, args, x, y)
        print("%s-SVM accuracy %.4f" % ("L1" if use_linear else "L2", acc))
        assert acc > 0.9, (use_linear, acc)
    print("SVM_MNIST_OK")


if __name__ == "__main__":
    main()
