"""Noise-contrastive estimation over a large output vocabulary (mirrors
reference example/nce-loss/toy_nce.py + nce.py — the nce_loss graph:
label embedding as the output-layer weight rows, sampled negatives,
per-candidate logistic loss).

Task (synthetic, zero-egress): predict y = (3x) mod V from token x over
a "large" vocab V. Full softmax would touch all V rows every step; NCE
touches 1 true + K noise rows. Exercises: Embedding used as a sampled
output matrix, broadcast_mul + sum(axis) inner products,
LogisticRegressionOutput with per-candidate labels, Reshape/Concat in
the label path.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx


def nce_loss(data, label_cands, label_tgt, vocab, nhid, k):
    """data: (B, nhid) hidden vector; label_cands: (B, 1+K) candidate
    ids (col 0 = true); label_tgt: (B, 1+K) 1-vs-0 targets. The
    candidate rows of the output matrix come through an Embedding
    lookup — the NCE trick (reference nce.py:18-37)."""
    w = mx.sym.Embedding(label_cands, input_dim=vocab, output_dim=nhid,
                         name="out_weight")           # (B, 1+K, nhid)
    b = mx.sym.Embedding(label_cands, input_dim=vocab, output_dim=1,
                         name="out_bias")             # (B, 1+K, 1)
    h = mx.sym.Reshape(data, shape=(-1, 1, nhid))     # (B, 1, nhid)
    prod = mx.sym.broadcast_mul(w, h)                 # (B, 1+K, nhid)
    logit = mx.sym.sum(prod, axis=2) + mx.sym.Reshape(b, shape=(-1, 1 + k))
    return mx.sym.LogisticRegressionOutput(logit, label_tgt, name="nce")


def build(vocab, nhid, k):
    x = mx.sym.Variable("data")
    cands = mx.sym.Variable("cands")
    tgt = mx.sym.Variable("tgt")
    emb = mx.sym.Embedding(x, input_dim=vocab, output_dim=nhid,
                           name="in_embed")           # (B, 1, nhid) for T=1
    h = mx.sym.Flatten(emb)
    h = mx.sym.FullyConnected(h, num_hidden=nhid, name="fc")
    h = mx.sym.Activation(h, act_type="tanh")
    return nce_loss(h, cands, tgt, vocab, nhid, k)


def make_batch(rs, n, vocab, k):
    x = rs.randint(0, vocab, size=(n, 1)).astype(np.float32)
    true = (3 * x[:, 0].astype(np.int64)) % vocab
    noise = rs.randint(0, vocab, size=(n, k))
    cands = np.concatenate([true[:, None], noise], axis=1).astype(np.float32)
    tgt = np.zeros((n, 1 + k), np.float32)
    tgt[:, 0] = 1.0
    return x, cands, tgt, true


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-epochs", type=int, default=10)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=100)
    ap.add_argument("--nhid", type=int, default=32)
    ap.add_argument("--negatives", type=int, default=8)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    x, cands, tgt, true = make_batch(rs, 1024, args.vocab, args.negatives)
    it = mx.io.NDArrayIter({"data": x, "cands": cands}, {"tgt": tgt},
                           batch_size=args.batch_size, shuffle=False)

    mod = mx.mod.Module(build(args.vocab, args.nhid, args.negatives),
                        data_names=["data", "cands"], label_names=["tgt"],
                        context=mx.current_context())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 1e-2})
    for epoch in range(args.num_epochs):
        it.reset()
        tot = n = 0.0
        for batch in it:
            mod.forward(batch, is_train=True)
            p = mod.get_outputs()[0].asnumpy()       # sigmoid per candidate
            tot += float(((p[:, 0] > 0.5) == 1).sum())
            n += p.shape[0]
            mod.backward()
            mod.update()
        print("epoch %d true-candidate recall %.3f" % (epoch, tot / n))

    # evaluation: rank the true row against the sampled noise rows —
    # NCE training must push the true candidate's score to the top
    it.reset()
    correct = total = 0
    for batch in it:
        mod.forward(batch, is_train=False)
        p = mod.get_outputs()[0].asnumpy()
        correct += int((np.argmax(p, axis=1) == 0).sum())
        total += p.shape[0]
    acc = correct / total
    print("true-vs-noise ranking accuracy %.4f" % acc)
    assert acc > 0.9, acc
    print("TOY_NCE_OK")


if __name__ == "__main__":
    main()
