# Native runtime components (parity: the reference's C++ core build).
# The compute path is JAX/XLA; these libs cover the host-side runtime the
# reference implemented natively: RecordIO scan + threaded batch loading,
# and the dependency engine scheduling host-side async work.

CXX ?= g++
CXXFLAGS ?= -O3 -std=c++17 -fPIC -pthread -Wall
LIB_DIR := mxnet_tpu/_lib

PY_INCLUDES := $(shell python3-config --includes)
PY_LDFLAGS := $(shell python3-config --embed --ldflags 2>/dev/null || \
                      python3-config --ldflags)

all: $(LIB_DIR)/libmxtpu_io.so $(LIB_DIR)/libmxtpu_engine.so \
     $(LIB_DIR)/libmxtpu_storage.so $(LIB_DIR)/libmxtpu_predict.so \
     $(LIB_DIR)/libmxtpu_c_api.so tools/im2rec

# native list->RecordIO packer (parity: reference tools/im2rec.cc)
tools/im2rec: src/im2rec.cc
	$(CXX) $(CXXFLAGS) -o $@ $<

$(LIB_DIR)/libmxtpu_predict.so: src/c_predict_api.cc src/embed_common.cc
	@mkdir -p $(LIB_DIR)
	$(CXX) $(CXXFLAGS) $(PY_INCLUDES) -shared -o $@ $^ $(PY_LDFLAGS)

# full ABI in one library (like the reference's single libmxnet.so):
# general C API + predict API + shared embed machinery
$(LIB_DIR)/libmxtpu_c_api.so: src/c_api.cc src/c_predict_api.cc \
                              src/embed_common.cc
	@mkdir -p $(LIB_DIR)
	$(CXX) $(CXXFLAGS) $(PY_INCLUDES) -shared -o $@ $^ $(PY_LDFLAGS)

$(LIB_DIR)/libmxtpu_storage.so: src/storage.cc
	@mkdir -p $(LIB_DIR)
	$(CXX) $(CXXFLAGS) -shared -o $@ $<

$(LIB_DIR)/libmxtpu_io.so: src/recordio.cc
	@mkdir -p $(LIB_DIR)
	$(CXX) $(CXXFLAGS) -shared -o $@ $<

$(LIB_DIR)/libmxtpu_engine.so: src/engine.cc
	@mkdir -p $(LIB_DIR)
	$(CXX) $(CXXFLAGS) -shared -o $@ $<

test: all
	python -m pytest tests/ -q

# C++ unit tests for the native layer (parity: reference tests/cpp/)
testcpp: tests/cpp/test_native
	./tests/cpp/test_native

tests/cpp/test_native: tests/cpp/test_native.cc src/engine.cc src/storage.cc
	$(CXX) $(CXXFLAGS) -o $@ $^

clean:
	rm -rf $(LIB_DIR)
	rm -f tools/im2rec

.PHONY: all test clean
