"""Benchmark: ResNet-50 ImageNet-shape training throughput (img/s) + MFU.

Baseline of record (BASELINE.md): the reference's published 109 img/s for
ResNet-50 batch-32 training on 1x K80 (example/image-classification/
README.md:147-155). This harness runs the same workload shape — forward
+ backward + SGD-momentum update, batch images at 224x224 — as ONE jitted
XLA program on the local accelerator, with the TPU-native configuration:
channels-last (NHWC) layout end to end (which also triggers the
space-to-depth stem rewrite, ops/nn.py:_conv_s2d_7x7s2), bf16-resident
weights with fp32 master copies in the optimizer (the reference's
mp_sgd_update scheme, optimizer_op.cc:39-299), synthetic on-device data
(compute-bound measurement, matching the reference's benchmark_score.py
methodology).

See PERF.md for the measured roofline analysis of the MFU number.

Robustness (rounds 3 AND 4 lost their numbers — r3 to a PJRT init hang,
r4 to the driver's outer timeout killing a harness whose worst-case
budget exceeded the driver window; this layout makes the raw measurement
un-losable):
  - backend init hangs are PER-PROCESS and init-time on this relayed
    backend, so the supervisor runs a cheap ~60s probe child in a LOOP —
    a later process can win even when an earlier one hung — and launches
    the expensive raw child only after a probe has succeeded;
  - the global deadline defaults to 1500s, strictly inside the driver's
    observed ~27-30 min window, and every phase budget is clipped to the
    time remaining;
  - the raw measurement runs in its own child; on TimeoutExpired the
    supervisor salvages whatever JSON the child already printed from
    TimeoutExpired.stdout;
  - the optional Module.fit phase runs in a SEPARATE child with its own
    budget, so it can hang or die without touching the raw number;
  - the harness ALWAYS prints a final JSON line — the measurement on
    success, an {"error": ...} diagnostic otherwise; a round where the
    backend never initialises is marked {"skipped": true} so it reads as
    unmeasurable, not as a zero;
  - partial results are emitted as they land ({..., "partial": true}
    lines), so an outer kill mid-phase salvages everything already
    measured;
  - phase deadlines are CLI-tunable: --budget-s 1200 rescales the total,
    --budget-s probe=60,raw=600,module=300 pins individual phases.

Prints one JSON line:
  {"metric", "value", "unit", "vs_baseline", "mfu", "device", ...}
"""
import json
import os
import subprocess
import sys
import time

BASELINE_IMG_S = 109.0  # reference ResNet-50 1xK80 (BASELINE.md)
SMOKE = os.environ.get("MXTPU_BENCH_SMOKE", "") == "1"
BATCH = 8 if SMOKE else int(os.environ.get("MXTPU_BENCH_BATCH", "128"))
IMG = 64 if SMOKE else 224
ITERS = 2 if SMOKE else 20
LR = 0.05
MOMENTUM = 0.9
# bf16-resident weights + fp32 master in the optimizer (mp_sgd scheme)
BF16 = True

# Per-phase budgets (seconds). The raw child gets the lion's share; the
# module phase is optional and must never eat the raw number's budget.
# TOTAL_DEADLINE bounds the whole harness and every phase budget is
# clipped to the time remaining. Default 1500s: the round-4 driver
# killed the harness ~27-30 min in, so the budget must fit INSIDE that
# window with margin (rc=124 twice running is why this is conservative).
PROBE_TIMEOUT = 75
PROBE_GAP = 20
RAW_TIMEOUT = 900
RAW_MIN = 240          # don't bother launching a raw child with less
MODULE_TIMEOUT = 540   # covers the fused AND phase-split fit measurements
DP_TIMEOUT = 900       # the optional data-parallel fused-vs-kvstore A/B:
                       # up to 2 legs PER axis size (vs module's 2 total),
                       # so it gets the raw-child-scale budget; a kill
                       # mid-sweep truncates to the sizes already banked
                       # (stdout partials AND the artifact update per size)
SERVE_TIMEOUT = 420    # the optional serving sweep (bucketed engine vs
                       # sequential Predictor + open-loop offered-load
                       # ladder); partial emission per load point
DECODE_TIMEOUT = 420   # the optional autoregressive-decode sweep
                       # (continuous-batching slot engine vs static
                       # whole-batch waves); partial emission per leg
TOTAL_DEADLINE = float(os.environ.get("MXTPU_BENCH_DEADLINE", "1500"))
# consecutive failed/timed-out probes before the supervisor stops
# burning budget on a dead tunnel and emits the diagnostic immediately
# (r03-r05 spent 10+ probes rediscovering the same outage)
PROBE_FAIL_LIMIT = int(os.environ.get("MXTPU_BENCH_PROBE_FAILS", "3"))


def _apply_budget_args(argv):
    """``--budget-s S`` / ``--budget-s probe=60,raw=600,module=300``:
    per-phase deadlines from the command line (BENCH_r03/r04 died rc=124
    to the DRIVER's outer timeout — the driver can now hand its window
    in; a bare number bounds the whole schedule, since every phase budget
    is clipped to the time remaining under it). Returns argv with the
    budget flags stripped; unknown phase names fail loudly."""
    global TOTAL_DEADLINE, PROBE_TIMEOUT, RAW_TIMEOUT, MODULE_TIMEOUT
    global DP_TIMEOUT, SERVE_TIMEOUT
    vals, rest, i = [], [], 0
    while i < len(argv):
        a = argv[i]
        if a == "--budget-s":
            i += 1
            if i >= len(argv):
                raise SystemExit("--budget-s: missing value "
                                 "(seconds, or probe=S,raw=S,...)")
            vals.append(argv[i])
        elif a.startswith("--budget-s="):
            vals.append(a.split("=", 1)[1])
        else:
            rest.append(a)
        i += 1
    names = {"probe": "PROBE_TIMEOUT", "raw": "RAW_TIMEOUT",
             "module": "MODULE_TIMEOUT", "dp": "DP_TIMEOUT",
             "serve": "SERVE_TIMEOUT", "total": "TOTAL_DEADLINE"}
    for v in vals:
        for part in v.split(","):
            if "=" in part:
                k, s = part.split("=", 1)
                if k not in names:
                    raise SystemExit("--budget-s: unknown phase %r "
                                     "(probe|raw|module|dp|serve|total)" % k)
            else:
                k, s = "total", part
            try:
                globals()[names[k]] = float(s)
            except ValueError:
                raise SystemExit("--budget-s: bad seconds value %r" % s)
    return rest

# Peak dense bf16 FLOP/s per chip by device kind (public spec sheets).
PEAK_FLOPS = [
    ("v6", 918e12), ("trillium", 918e12),
    ("v5p", 459e12), ("v5 lite", 197e12), ("v5e", 197e12), ("v5litepod", 197e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 46e12),
]

# Analytic ResNet-50 forward cost at 224x224, counting one MAC as 2 FLOPs
# (the convention every published MFU number uses): ~4.1 GFLOP/image.
# Backward is ~2x forward (grad wrt activations + grad wrt weights), so a
# train step is ~3x forward. The XLA cost model counts ~1.8x this
# (rematerialised fusions and formatting ops are billed as FLOPs), so the
# output reports BOTH: "mfu" from the cost model and "mfu_analytic" from
# this number — the latter is the one comparable to external reports.
ANALYTIC_FWD_FLOPS_PER_IMG_224 = 4.1e9


def peak_flops_for(kind):
    k = kind.lower()
    for sub, val in PEAK_FLOPS:
        if sub in k:
            return val
    return None


def _init_device(jax):
    """First touch of the accelerator backend. Flaky-init (RuntimeError)
    is retried in-process; a hard HANG is the supervisor's problem — it
    probed init in a disposable child and bounds this child's runtime."""
    if SMOKE:  # harness logic check: cpu platform only, no accel touch
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()[0]
    last = None
    for attempt in range(3):
        try:
            return jax.devices()[0]
        except RuntimeError as e:
            last = e
            print("bench: backend init attempt %d failed: %s"
                  % (attempt + 1, e), file=sys.stderr, flush=True)
            try:
                jax._src.xla_bridge.backends.cache_clear()
            except Exception:
                pass
            if attempt + 1 < 3:
                time.sleep(10.0 * (attempt + 1))
    raise last


def probe():
    """Disposable child: init the backend and report the device kind.
    If PJRT hangs at C level, the supervisor kills this process — no
    state leaks into the measurement child."""
    import jax
    dev = _init_device(jax)
    print(json.dumps({"device": dev.device_kind, "platform": dev.platform}),
          flush=True)


def child():
    import numpy as np
    import jax
    import jax.numpy as jnp

    dev = _init_device(jax)
    print("bench: device =", dev.device_kind, file=sys.stderr, flush=True)

    # Pinning default_device to host keeps every eager op (deferred-shape
    # pass, param init) off the accelerator; the first accel touch is the
    # jitted train step itself.
    cpu = jax.local_devices(backend="cpu")[0]

    with jax.default_device(cpu):
        import mxnet_tpu as mx
        from mxnet_tpu.gluon.model_zoo import vision
        from mxnet_tpu.gluon.block import make_pure_fn

        # Channels-last end to end — the MXU-native image layout
        # (mxnet_tpu/layout.py; effect quantified in PERF.md).
        mx.layout.set_default_layout("NHWC")
        np.random.seed(0)
        # MXTPU_BENCH_NET picks the model-zoo family member (the driver
        # path always measures resnet50_v1, the baseline of record; the
        # reference also publishes 18/34/101/152 numbers — BASELINE.md)
        net_name = os.environ.get("MXTPU_BENCH_NET", "resnet50_v1")
        net = getattr(vision, net_name)()
        net.initialize(mx.initializer.Xavier())
        net(mx.nd.ones((1, 32, 32, 3)))  # complete deferred shapes (on CPU)
        fn, raw_params, param_names = make_pure_fn(net, train=True)
        host_params = [np.asarray(p) for p in raw_params]

    n_params = len(host_params)
    bf16 = jnp.bfloat16
    # BatchNorm scale/shift and moving stats stay fp32 in the COMPUTE list
    # too (the cudnn BN convention; bf16 moving-average increments would
    # underflow) — only conv/fc weights are bf16-resident.
    keep_fp32 = [any(t in n for t in ("gamma", "beta", "running_mean",
                                      "running_var"))
                 for n in param_names]

    # Multi-precision step, the reference's mp_sgd_update scheme
    # (optimizer_op.cc:39-299): the compute path reads RESIDENT bf16
    # weights; fp32 master copies are touched only by the optimizer
    # update, which also emits the next step's bf16 weights. BatchNorm
    # running stats write back through the fp32 master list.
    # pbf holds ONLY the bf16-resident entries (conv/fc weights); fp32-kept
    # params (BN) come straight from the master list — aliasing them into
    # pbf would donate the same buffer twice.
    lowp = [BF16 and not keep_fp32[i] for i in range(n_params)]
    lowp_pos = {i: j for j, i in enumerate(
        [i for i in range(n_params) if lowp[i]])}

    def train_step(master, mom, pbf, x, y, rng):
        full = [pbf[lowp_pos[i]] if lowp[i] else master[i]
                for i in range(n_params)]

        def loss_f(ps):
            (logits,), aux = fn(ps, [x], rng)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            loss = -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))
            return loss, aux

        (loss, aux), grads = jax.value_and_grad(loss_f, has_aux=True)(full)
        new_master, new_mom, new_pbf = [], [], []
        for i in range(n_params):
            if i in aux:  # BatchNorm running stats: direct writeback (fp32)
                a32 = aux[i].astype(jnp.float32)
                new_master.append(a32)
                new_mom.append(mom[i])
                if lowp[i]:
                    new_pbf.append(a32.astype(bf16))
                continue
            m = MOMENTUM * mom[i] - LR * grads[i].astype(jnp.float32)
            w = master[i] + m
            new_master.append(w)
            new_mom.append(m)
            if lowp[i]:
                new_pbf.append(w.astype(bf16))
        return new_master, new_mom, new_pbf, loss

    step = jax.jit(train_step, donate_argnums=(0, 1, 2))   # mxlint: disable=jit-site -- standalone bench step: AOT-compiled below and registered via card_from_compiled, the card contract the wrapper exists for

    x = jax.device_put(
        np.random.uniform(-1, 1, (BATCH, IMG, IMG, 3)).astype(np.float32), dev)
    if BF16:
        x = x.astype(bf16)
    y = jax.device_put(
        np.random.randint(0, 1000, BATCH).astype(np.int32), dev)
    with jax.default_device(dev):
        rng = jax.random.key(0)
    master = [jax.device_put(p, dev) for p in host_params]
    mom = [jax.device_put(np.zeros_like(p), dev) for p in host_params]
    pbf = [master[i].astype(bf16) for i in range(n_params) if lowp[i]]

    # AOT-compile once; the SAME executable provides the FLOP count (its
    # own cost model), runs the warmup, AND runs the timing loop — one
    # callable throughout, no reliance on jit-cache behaviour. The
    # executable's cost/memory analysis is captured as a PROGRAM CARD
    # through the executor's shared card builder and registered in
    # telemetry.programs(), so tools/mfu_capture.py reads the step's
    # FLOPs/bytes straight from the bench line instead of requiring an
    # xprof hlo_stats capture.
    step_flops = None
    step_bytes = None
    step_card = None
    run = step
    try:
        from mxnet_tpu.executor import card_from_compiled
        from mxnet_tpu import telemetry as _tel
        t_c0 = time.perf_counter()
        compiled = step.lower(master, mom, pbf, x, y, rng).compile()
        run = compiled
        step_card = card_from_compiled("bench_step", compiled,
                                       entry="bench_step")
        step_card["compile_ms"] = round((time.perf_counter() - t_c0) * 1e3, 1)
        _tel.record_program(step_card)
        step_flops = step_card["flops"] or None
        step_bytes = step_card["bytes_accessed"] or None
    except Exception as e:
        print("bench: AOT compile/cost_analysis unavailable, using jit:", e,
              file=sys.stderr)

    # warmup. NOTE: the final sync is a scalar fetch — block_until_ready
    # alone does not drain the execution queue on relayed PJRT backends.
    for _ in range(3):
        master, mom, pbf, loss = run(master, mom, pbf, x, y, rng)
    float(loss)

    import contextlib
    trace_dir = os.environ.get("MXTPU_BENCH_TRACE", "")
    tracer = (jax.profiler.trace(trace_dir) if trace_dir  # mfu_capture lane
              else contextlib.nullcontext())
    with tracer:
        t0 = time.perf_counter()
        for _ in range(ITERS):
            master, mom, pbf, loss = run(master, mom, pbf, x, y, rng)
        float(loss)
        dt = time.perf_counter() - t0

    img_s = BATCH * ITERS / dt
    out = {
        "metric": "%s_train_throughput" % net_name.replace("_v1", ""),
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
        "device": dev.device_kind,
        "batch": BATCH,
        "layout": "NHWC",
        "precision": "bf16+fp32-master" if BF16 else "fp32",
    }
    try:
        from mxnet_tpu import telemetry as _tel
        out["process"] = _tel.process_identity()
    except Exception:                       # telemetry must never cost a run
        pass
    peak = peak_flops_for(dev.device_kind)
    if step_flops:
        flops_s = step_flops * ITERS / dt
        out["tflops_per_s"] = round(flops_s / 1e12, 2)
        if peak:
            out["mfu"] = round(flops_s / peak, 4)
    # per-step cost/memory card figures (mfu_capture's no-xprof path
    # and the PERF.md "Memory & cost telemetry" table read these)
    if step_flops:
        out["step_flops"] = step_flops
    if step_bytes:
        out["step_bytes_accessed"] = step_bytes
    if step_card is not None:
        out["program_card"] = {
            k: step_card.get(k) for k in
            ("id", "kind", "flops", "bytes_accessed", "peak_bytes",
             "argument_bytes", "output_bytes", "temp_bytes",
             "generated_code_bytes", "compile_ms")}
    # Analytic-FLOP MFU (the externally comparable number — see the
    # ANALYTIC_FWD_FLOPS_PER_IMG_224 comment).
    analytic_step = (3.0 * ANALYTIC_FWD_FLOPS_PER_IMG_224
                     * (IMG / 224.0) ** 2 * BATCH)
    a_flops_s = analytic_step * ITERS / dt
    out["tflops_per_s_analytic"] = round(a_flops_s / 1e12, 2)
    if peak:
        out["mfu_analytic"] = round(a_flops_s / peak, 4)

    print(json.dumps(out), flush=True)


def _telemetry_summary():
    """Trimmed ``mx.telemetry.snapshot()`` for the BENCH/MULTICHIP
    artifacts: the full counter registry (dispatches by kind, jit
    compiles vs. hits, fused-fallback codes, transfer bytes, blocking
    syncs) plus the fit-phase span percentiles — the per-phase numbers
    the next perf PR starts from. ``_module_fit_throughput`` resets the
    registry at the top of its timed window, so this reads as one leg's
    accounting."""
    try:
        import mxnet_tpu as mx
        snap = mx.telemetry.snapshot()
    except Exception as e:                  # telemetry must never cost a run
        return {"error": str(e)}
    from mxnet_tpu import telemetry as _tel
    spans = {k: v for k, v in snap["spans"].items()
             if k in _tel.FIT_PHASE_SPANS or k in _tel.SERVE_SPANS}
    # keep the flag: a disabled-telemetry leg's all-zero counters must
    # read as "instrumentation off", not as a measured zero
    return {"enabled": snap["enabled"], "counters": snap["counters"],
            "spans": spans,
            # rank/host identity: every banked bench JSON names the
            # process that measured it (fleet artifacts share one dir)
            "process": snap["process"],
            # per-leg program cards + the online FLOP/s estimate: what a
            # step COSTS, next to what it MEASURED
            "programs": snap["programs"], "online": snap["online"]}


# the executor-path children sample the flight recorder at this
# interval so BENCH/MULTICHIP artifacts gain per-phase TIMELINES
# (counter deltas, queue depth, ledger bytes, MFU per tick) next to
# the endpoint snapshots
BENCH_SAMPLER_MS = 100.0


def _sampler_begin():
    """Start (or restart the window of) the flight-recorder sampler for
    one bench leg. Telemetry must never cost a run — failures degrade
    to 'no series in the artifact'."""
    try:
        from mxnet_tpu import flight
        flight.series_clear()
        flight.sampler_start(BENCH_SAMPLER_MS)
    except Exception as e:
        print("bench: flight sampler unavailable: %s" % e,
              file=sys.stderr)


def _series_window(n=240):
    """The sampler's banked time-series window for the current leg."""
    try:
        from mxnet_tpu import flight
        return flight.series_window(n)
    except Exception as e:
        return {"error": str(e)}


_ROBUSTNESS_PREFIXES = ("faults.", "serving.shed", "serving.retries",
                        "serving.breaker", "serving.deadline",
                        "serving.dispatch_failures", "checkpoint.",
                        "divergence.", "training.preempted")


def _robustness_counters():
    """Per-leg fault/shed/resume counters (ISSUE 7): the robustness
    trajectory banked NEXT to the throughput trajectory, so a BENCH
    round records whether its numbers were measured under injected
    faults / shedding / resumes (all zeros = a clean leg — still worth
    recording, it's the claim the chaos lane checks against)."""
    try:
        from mxnet_tpu import telemetry
        return {k: v for k, v in telemetry.counters().items()
                if k.startswith(_ROBUSTNESS_PREFIXES)}
    except Exception as e:                  # telemetry must never cost a run
        return {"error": str(e)}


def module_child():
    """Separate child for the OPTIONAL user-facing-path measurement:
    Module.fit through the whole-step fused program AND, budget
    permitting, the phase-split oracle with the knob pinned off — the
    PERF.md "Module.fit gap" A/B in one child. The fused number is
    printed the moment it exists (partial-result emission: a hang in the
    phase-split leg leaves the fused line salvageable); any hang/crash
    here is absorbed by the supervisor without touching the raw number."""
    import jax
    dev = _init_device(jax)
    old_pin = os.environ.get("MXNET_MODULE_FUSED_STEP")
    try:
        os.environ["MXNET_MODULE_FUSED_STEP"] = "1"
        _sampler_begin()
        img_s, fallback = _module_fit_throughput(dev)
        out = {"module_fit_img_s": round(img_s, 2)}
        if fallback is not None:
            # a silent fallback would record two phase-split numbers as
            # the A/B — mark the leg so the number reads as what it
            # measured
            out["module_fit_fused_fallback"] = fallback
        out["telemetry"] = _telemetry_summary()
        out["robustness"] = _robustness_counters()
        # the leg's per-tick timeline next to its endpoint snapshot
        out["series"] = _series_window()
        print(json.dumps(out), flush=True)
        os.environ["MXNET_MODULE_FUSED_STEP"] = "0"
        _sampler_begin()
        img_s, _ = _module_fit_throughput(dev)
        out["module_fit_phase_split_img_s"] = round(img_s, 2)
        out["telemetry_phase_split"] = _telemetry_summary()
        out["robustness_phase_split"] = _robustness_counters()
        out["series_phase_split"] = _series_window()
        print(json.dumps(out), flush=True)
    finally:
        _restore_pin(old_pin)


def _restore_pin(old):
    """Put MXNET_MODULE_FUSED_STEP back (the A/B children flip it; an
    in-process caller — the harness tests drive the children directly —
    must not inherit the last leg's pin)."""
    if old is None:
        os.environ.pop("MXNET_MODULE_FUSED_STEP", None)
    else:
        os.environ["MXNET_MODULE_FUSED_STEP"] = old


def _module_fit_throughput(dev, contexts=None, kvstore="local",
                           module_kwargs=None):
    """Throughput of the USER-FACING training path — Module.fit itself
    (symbolic ResNet-50, bf16 executor via the InferType pass, fp32
    master weights in the optimizer, metric updates included) — so
    framework overhead above the raw fused step is a measured number.

    ``contexts`` (default: one device) selects the data-parallel mesh:
    the per-chip batch stays ``BATCH`` and the GLOBAL batch scales with
    the axis size, so per-axis img/s reads as scaling efficiency.
    ``kvstore`` feeds straight into Module.fit — the dp A/B runs the
    fused-SPMD step (subsumed in-process kvstore) against the pinned-off
    kvstore phase-split path."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.io import DataDesc, DataBatch, DataIter

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "examples", "image-classification"))
    from symbols.resnet import get_symbol

    n_iters = ITERS
    img = IMG
    sym = get_symbol(num_classes=1000, num_layers=50,
                     image_shape="3,%d,%d" % (img, img))
    bf16 = np.dtype(jnp.bfloat16)
    if contexts is None:
        contexts = [mx.tpu() if dev.platform != "cpu" else mx.cpu()]
    batch = BATCH * len(contexts)

    class _DeviceBatchIter(DataIter):
        """Synthetic iterator handing out the SAME device-resident batch
        (benchmark_score methodology — measures compute+framework, not
        host->device feeding; tools/decode_bench.py covers the input
        pipeline)."""

        def __init__(self, n):
            super().__init__(batch)
            rs = np.random.RandomState(0)
            xb = jax.device_put(rs.uniform(
                -1, 1, (batch, 3, img, img)).astype(np.float32), dev)
            yb = jax.device_put(rs.randint(
                0, 1000, batch).astype(np.float32), dev)
            from mxnet_tpu.ndarray.ndarray import _wrap
            self._batch = DataBatch([_wrap(xb.astype(bf16))],
                                    [_wrap(yb)], pad=0)
            self.n = n
            self.i = 0

        @property
        def provide_data(self):
            return [DataDesc("data", (batch, 3, img, img), dtype=bf16)]

        @property
        def provide_label(self):
            return [DataDesc("softmax_label", (batch,))]

        def reset(self):
            self.i = 0

        def next(self):
            if self.i >= self.n:
                raise StopIteration
            self.i += 1
            return self._batch

    mod = mx.mod.Module(sym, context=contexts, **(module_kwargs or {}))
    opt_params = {"learning_rate": LR, "momentum": MOMENTUM,
                  "multi_precision": True}
    metric = mx.metric.Accuracy()
    warm = _DeviceBatchIter(3)
    # warmup epoch binds, initializes, and compiles the fused program
    mod.fit(warm, eval_metric=metric, num_epoch=1, kvstore=kvstore,
            initializer=mx.initializer.Xavier(),
            optimizer="sgd", optimizer_params=opt_params)
    # The fit loop is fully asynchronous (fused one-dispatch update,
    # device-accumulated metric), so batch-end marks measure DISPATCH
    # rate; the clock may only stop after the device queue drains. Time
    # from the first batch mark to a post-fit scalar fetch and count the
    # remaining batches (epoch-end work rides inside the window — over a
    # real epoch it amortises to noise; n_iters is set high enough that
    # it stays <5% here too).
    marks = []
    n = max(n_iters, 40)
    timed = _DeviceBatchIter(n)
    # clean telemetry window: the banked snapshot covers the TIMED epoch
    # only (bind/compile/warmup accounting would read as steady-state)
    mx.telemetry.reset()
    mod.fit(timed, eval_metric=metric, num_epoch=1, kvstore=kvstore,
            optimizer="sgd", optimizer_params=opt_params,
            batch_end_callback=lambda p: marks.append(time.perf_counter()))
    # drain the queue: fetch every trainable param so the clock covers
    # the queued optimizer steps regardless of argument ordering
    import jax.numpy as _jnp
    float(sum(_jnp.sum(mod._exec.arg_dict[name]._data)
              for name in mod._param_names))
    dt = time.perf_counter() - marks[0]
    return batch * (len(marks) - 1) / dt, mod._fused_fallback_reason


def dp_child():
    """Data-parallel A/B child: Module.fit through the fused-SPMD step
    (in-process kvstore subsumed into the ONE mesh program) vs the
    kvstore phase-split path, per dp-axis size, per-chip batch pinned at
    BATCH. Every axis size's numbers are printed the moment they exist
    (partial-result emission — a hang at a larger axis size salvages the
    smaller ones), and the final object is also banked into the
    MULTICHIP artifact dir so the scaling trajectory is recorded per
    round. In smoke mode the mesh is the virtual 8-device CPU host."""
    import jax
    if SMOKE:
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
    dev = _init_device(jax)
    import mxnet_tpu as mx
    n_dev = len([d for d in jax.devices() if d.platform == dev.platform])
    axes_env = os.environ.get("MXTPU_BENCH_DP_AXES", "")
    if axes_env:
        sizes = [int(s) for s in axes_env.split(",")]
        dropped = [k for k in sizes if k > n_dev]
        if dropped:
            # skip ONLY the oversized entries — later valid sizes in the
            # operator's list must still be measured
            print("bench: dp axis size(s) %s exceed %d devices, skipped"
                  % (dropped, n_dev), file=sys.stderr, flush=True)
        sizes = [k for k in sizes if k <= n_dev]
    else:
        sizes, k = [], 1
        while k <= n_dev:
            sizes.append(k)
            k *= 2
    mk_ctx = mx.tpu if dev.platform != "cpu" else mx.cpu
    out = {"lane": "dp_ab", "device": dev.device_kind,
           "n_devices": n_dev, "per_chip_batch": BATCH, "dp": {}}
    old_pin = os.environ.get("MXNET_MODULE_FUSED_STEP")
    try:
        for k in sizes:
            contexts = [mk_ctx(i) for i in range(k)]
            # at k=1 _create_kvstore resolves 'device' to NO kvstore, so
            # the split leg is the plain phase-split baseline — mark it
            # so the table never reads as a kvstore measurement there
            entry = {"split_kvstore_active": k > 1}
            os.environ["MXNET_MODULE_FUSED_STEP"] = "1"
            _sampler_begin()
            img_s, fallback = _module_fit_throughput(dev, contexts=contexts,
                                                     kvstore="device")
            entry["fused_img_s"] = round(img_s, 2)
            entry["telemetry"] = _telemetry_summary()
            entry["series"] = _series_window()
            if fallback is not None:
                # a silently fallen-back leg must not read as a fused
                # number
                entry["fused_fallback"] = getattr(fallback, "code",
                                                  str(fallback))
            os.environ["MXNET_MODULE_FUSED_STEP"] = "0"
            img_s, _ = _module_fit_throughput(dev, contexts=contexts,
                                              kvstore="device")
            entry["kvstore_img_s"] = round(img_s, 2)
            out["dp"][str(k)] = entry
            print(json.dumps(dict(out, partial=True)), flush=True)
            # re-bank the artifact after EVERY axis size: a hang/kill at
            # a larger mesh (the failure mode this lane exists to catch)
            # must not lose the sizes already measured
            _write_dp_artifact(dict(out, ok=False, skipped=False,
                                    truncated=True))
    finally:
        _restore_pin(old_pin)
    print(json.dumps(out), flush=True)
    _write_dp_artifact(dict(out, ok=True, skipped=False))


def _mp_bench_rules(mp):
    """ResNet partition rules for the mp A/B: shard conv/FC weight
    output channels (and batch-norm scale/shift vectors) over ``mp``.
    Non-divisible shapes downgrade to replicate (warned + counted) —
    the point of the lane is the LAYOUT cost A/B, not rule surgery
    per architecture."""
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel import PartitionRules
    return PartitionRules([
        (r"(conv\d*|fc\d*)_weight$", P("mp")),
        (r"weight$", P("mp")),
        (r"(gamma|beta|bias)$", P("mp")),
    ])


def _write_mp_artifact(obj):
    """MULTICHIP artifact for the per-layout A/B (same schema stance as
    the dp artifact: partial writes marked, final write ok=True)."""
    art_dir = os.environ.get("MXTPU_ARTIFACT_DIR", "/tmp/mxtpu_artifacts")
    try:
        os.makedirs(art_dir, exist_ok=True)
        with open(os.path.join(art_dir, "multichip_mp_ab.json"), "w") as f:
            f.write(json.dumps(obj) + "\n")
    except OSError as e:
        print("bench: mp artifact write failed: %s" % e, file=sys.stderr)


def mp_child():
    """Partition-layout A/B child (ISSUE 15): Module.fit through the
    fused SPMD step on the SAME devices and global batch under two
    LAYOUTS — params replicated (plain dp over all devices) vs
    rule-sharded over a dp x mp mesh — banking per-layout img/s,
    telemetry and the per-layout PROGRAM CARDS (the card's
    ``partition`` block names the layout, so the corpus rows stay
    attributable). In smoke mode the mesh is the virtual 8-device CPU
    host as 2x4; on a TPU slice the mp axis defaults to 4 (v5e-8 ->
    2x4) or 2 when fewer chips answer. Partial results print per
    layout, mirroring dp_child's salvage discipline."""
    import jax
    if SMOKE:
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
    dev = _init_device(jax)
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    n_dev = len([d for d in jax.devices() if d.platform == dev.platform])
    if n_dev < 2:
        out = {"lane": "mp_ab", "skipped": True,
               "reason": "mp A/B needs >=2 devices, found %d" % n_dev}
        print(json.dumps(out), flush=True)
        _write_mp_artifact(dict(out, ok=False))
        return
    mp = int(os.environ.get("MXTPU_BENCH_MP", "4"))
    while mp > 1 and n_dev % mp:
        mp //= 2
    dp = n_dev // max(mp, 1)
    mk_ctx = mx.tpu if dev.platform != "cpu" else mx.cpu
    contexts = [mk_ctx(i) for i in range(n_dev)]
    layouts = {
        "replicated": None,
        "dp%dxmp%d" % (dp, mp): {
            "partition_rules": _mp_bench_rules(mp),
            "mesh_axes": {"dp": dp, "mp": mp},
        },
    }
    out = {"lane": "mp_ab", "device": dev.device_kind,
           "n_devices": n_dev, "per_chip_batch": BATCH,
           "mesh_axes": {"dp": dp, "mp": mp}, "layouts": {}}
    old_pin = os.environ.get("MXNET_MODULE_FUSED_STEP")
    try:
        os.environ["MXNET_MODULE_FUSED_STEP"] = "1"
        for name, kw in layouts.items():
            _sampler_begin()
            img_s, fallback = _module_fit_throughput(
                dev, contexts=contexts, kvstore="device",
                module_kwargs=kw)
            entry = {"img_s": round(img_s, 2),
                     "telemetry": _telemetry_summary(),
                     "series": _series_window()}
            if fallback is not None:
                entry["fused_fallback"] = getattr(fallback, "code",
                                                  str(fallback))
            # the layout's train_step card: what this layout COSTS
            # (FLOPs/bytes/peak HBM) plus its partition stamp
            entry["program_cards"] = {
                k: {kk: c.get(kk) for kk in
                    ("kind", "flops", "bytes_accessed", "peak_bytes",
                     "compile_ms", "dispatches", "partition")}
                for k, c in telemetry.programs().items()
                if c.get("kind") == "train_step" and c.get("dispatches")}
            out["layouts"][name] = entry
            print(json.dumps(dict(out, partial=True)), flush=True)
            _write_mp_artifact(dict(out, ok=False, truncated=True))
    finally:
        _restore_pin(old_pin)
    names = list(out["layouts"])
    if len(names) == 2 and all(
            out["layouts"][n].get("img_s") for n in names):
        out["mp_vs_replicated"] = round(
            out["layouts"][names[1]]["img_s"]
            / out["layouts"][names[0]]["img_s"], 3)
    print(json.dumps(out), flush=True)
    _write_mp_artifact(dict(out, ok=True))


def serve_child():
    """Inference-serving sweep: the bucketed micro-batching engine
    (mxnet_tpu/serving.py) vs the one-request-at-a-time Predictor loop,
    then an OPEN-LOOP offered-load ladder — requests arrive on a fixed
    schedule regardless of completions (the serving regime where queue
    depth and latency percentiles mean something), at fractions of the
    measured burst capacity. Every phase's numbers print the moment
    they exist ({"partial": true} lines), so a kill mid-ladder salvages
    the points already measured; per-bucket program cards ride in the
    artifact so a round records what each bucket COSTS next to what it
    served. Smoke mode swaps ResNet-50 for a tiny MLP (harness-logic
    check on CPU)."""
    import numpy as np
    import jax
    dev = _init_device(jax)
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.predictor import Predictor
    from mxnet_tpu.serving import InferenceEngine

    rng = np.random.RandomState(0)
    if SMOKE:
        d = 16
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
        sym = mx.sym.SoftmaxOutput(net, name="softmax")
        row = (d,)
        n_req, max_batch = 256, 16
    else:
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "examples", "image-classification"))
        from symbols.resnet import get_symbol
        sym = get_symbol(num_classes=1000, num_layers=50,
                         image_shape="3,%d,%d" % (IMG, IMG))
        row = (3, IMG, IMG)
        n_req, max_batch = 128, 32
    arg_shapes, _, aux_shapes = sym.infer_shape_partial(
        data=(1,) + row)
    params = {}
    for name, shape in zip(sym.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            continue
        params["arg:" + name] = mx.nd.array(
            rng.normal(0, 0.05, shape).astype(np.float32))
    for name, shape in zip(sym.list_auxiliary_states(), aux_shapes):
        # BatchNorm moving stats: mean 0 / var 1 keeps activations sane
        fill = np.ones if name.endswith("moving_var") else np.zeros
        params["aux:" + name] = mx.nd.array(fill(shape, np.float32))

    out = {"lane": "serving", "device": dev.device_kind,
           "n_requests": n_req, "max_batch": max_batch}
    reqs = [rng.uniform(-1, 1, (1,) + row).astype(np.float32)
            for _ in range(min(n_req, 64))]

    def req_at(i):
        return reqs[i % len(reqs)]

    # leg 1: the one-request-at-a-time facade (the pre-engine baseline)
    pred = Predictor(sym, params, {"data": (1,) + row})
    pred.forward(data=req_at(0))
    pred.get_output(0).asnumpy()          # compile outside the window
    n_un = min(n_req, 48)
    t0 = time.perf_counter()
    for i in range(n_un):
        pred.forward(data=req_at(i))
        pred.get_output(0).asnumpy()
    out["unbatched_req_s"] = round(n_un / (time.perf_counter() - t0), 2)
    print(json.dumps(dict(out, partial=True)), flush=True)

    # leg 2: burst capacity through the bucketed engine (all buckets
    # AOT-compiled at construction — exactly one program per signature;
    # with the persisted compile cache populated from a prior round,
    # construction deserializes instead of invoking XLA — the startup
    # wall and compile-cache counters bank the cold-vs-warm trajectory)
    _sampler_begin()      # per-tick timeline across burst + ladder
    t_eng = time.perf_counter()
    engine = InferenceEngine(sym, params, {"data": (1,) + row},
                             max_batch=max_batch, max_wait_ms=2.0,
                             max_inflight=4)
    out["engine_startup_s"] = round(time.perf_counter() - t_eng, 3)
    out["compile_cache"] = {
        k: v for k, v in telemetry.counters().items()
        if k.startswith("compile_cache.")}
    cards = engine.program_cards()
    out["buckets"] = engine.buckets
    out["program_cards"] = {
        k: {kk: c.get(kk) for kk in
            ("kind", "flops", "bytes_accessed", "peak_bytes",
             "compile_ms", "dispatches")}
        for k, c in cards.items()}
    out["compiles_per_bucket"] = round(
        len(cards) / len(engine.buckets), 2)
    telemetry.reset()
    t0 = time.perf_counter()
    futs = [engine.submit(data=req_at(i)) for i in range(n_req)]
    for f in futs:
        f.result(timeout=600)
    burst = n_req / (time.perf_counter() - t0)
    out["burst_req_s"] = round(burst, 2)
    out["serve_speedup"] = round(burst / out["unbatched_req_s"], 2) \
        if out["unbatched_req_s"] else None
    lat = telemetry.span_stats("serve_request").get("serve_request", {})
    out["burst_latency_ms"] = {k: lat.get(k)
                               for k in ("p50_ms", "p95_ms", "p99_ms")}
    print(json.dumps(dict(out, partial=True)), flush=True)

    # leg 3: open-loop ladder at fractions of burst capacity — arrivals
    # on a fixed schedule; latency is measured from the SCHEDULED
    # arrival (coordinated-omission-free)
    out["offered_loads"] = {}
    for frac in (0.5, 0.8, 0.95):
        rate = burst * frac
        telemetry.reset()
        lats, t0 = [], time.perf_counter()
        pend = []
        for i in range(n_req):
            sched = t0 + i / rate
            now = time.perf_counter()
            if sched > now:
                time.sleep(sched - now)
            fut = engine.submit(data=req_at(i))
            # stamp at RESOLUTION (the done callback runs on the
            # resolver thread at set_result) — collecting in submission
            # order would charge an early-resolved request for every
            # slower future ahead of it. list.append is GIL-atomic.
            fut.add_done_callback(
                lambda f, s=sched: lats.append(
                    (time.perf_counter() - s) * 1e3))
            pend.append(fut)
        for fut in pend:
            fut.result(timeout=600)
        dt = time.perf_counter() - t0
        lats.sort()
        # per-load fill from THIS window's counters (engine.stats() is
        # cumulative since construction)
        c = telemetry.counters()
        rows = c.get("serving.batch_rows", 0)
        pad = c.get("serving.pad_rows", 0)
        pct = telemetry._percentile      # the ONE percentile rule
        out["offered_loads"]["%.2f" % frac] = {
            "offered_req_s": round(rate, 2),
            "achieved_req_s": round(n_req / dt, 2),
            "latency_ms": {
                "p50": round(pct(lats, 50), 3),
                "p95": round(pct(lats, 95), 3),
                "p99": round(pct(lats, 99), 3),
            },
            "batch_fill": round(rows / (rows + pad), 4)
            if rows + pad else None,
            "batches": c.get("serving.batches", 0),
        }
        print(json.dumps(dict(out, partial=True)), flush=True)
    out["telemetry"] = _telemetry_summary()
    # the per-tick timeline across burst + offered-load ladder: the
    # perf trajectory gains per-phase timelines, not just endpoints
    out["series"] = _series_window()
    # the robustness trajectory: overload-control + fault counters for
    # this leg, plus the engine's own shed/retry/breaker accounting
    st = engine.stats()
    out["robustness"] = {
        "counters": _robustness_counters(),
        "engine": {k: st.get(k) for k in
                   ("shed_requests", "shed_rows", "shed_by_cause",
                    "retries", "dispatch_failures", "breaker",
                    "queued_rows", "max_queue_rows", "deadline_ms")},
    }
    engine.close()        # appends the corpus record when one is configured
    # the corpus-fed autotuner's plan for this round's traffic — what
    # the NEXT round's engine would pick instead of pow-2 buckets
    try:
        from mxnet_tpu import compile_cache
        from mxnet_tpu.tuner import plan_serving
        out["autotune_plan"] = plan_serving(
            compile_cache.corpus_records(kind="serving"),
            max_batch=max_batch)
    except Exception as e:
        print("bench: autotune plan unavailable: %s" % e, file=sys.stderr)
        out["autotune_plan"] = None
    print(json.dumps(out), flush=True)


def decode_child():
    """Continuous-batching decode sweep (mxnet_tpu/decode.py): the
    slot-pool engine streaming an open-loop skewed-length workload vs
    wave-synchronized static whole-batch decode of the same work
    through the same programs, plus per-token latency percentiles from
    the ``serve_decode_step`` spans (coordinated-omission-free: the
    spans time the dispatch cadence itself, with all work queued up
    front). Smoke mode shrinks the cell (harness-logic check on CPU);
    a real accelerator round banks the decode tokens/s trajectory
    PERF.md tracks."""
    import numpy as np
    import jax
    dev = _init_device(jax)
    from mxnet_tpu import telemetry
    from mxnet_tpu.decode import DecodeEngine, AttentionDecodeCell

    rng = np.random.RandomState(0)
    if SMOKE:
        cell = AttentionDecodeCell(vocab=256, embed=64, heads=8,
                                   head_dim=16, max_len=64)
        slots, waves, short, long_ = 8, 4, 4, 32
    else:
        cell = AttentionDecodeCell(vocab=8192, embed=512, heads=8,
                                   head_dim=64, max_len=512)
        slots, waves, short, long_ = 16, 4, 16, 192
    prompt_len = 4 if SMOKE else 16

    out = {"lane": "decode", "device": dev.device_kind,
           "slots": slots, "waves": waves,
           "gen_short": short, "gen_long": long_}

    def prompt():
        return rng.randint(1, cell.vocab - 1, prompt_len) \
            .astype(np.int32)

    _sampler_begin()
    t_eng = time.perf_counter()
    engine = DecodeEngine(cell, cell.init_params(1), slots=slots,
                          max_prompt_len=prompt_len * 2,
                          max_new_tokens=long_)
    out["engine_startup_s"] = round(time.perf_counter() - t_eng, 3)
    out["program_cards"] = {
        k: {kk: c.get(kk) for kk in
            ("kind", "flops", "peak_bytes", "compile_ms", "dispatches")}
        for k, c in engine.program_cards().items()}
    out["kv_cache_bytes"] = engine.stats()["kv_cache_bytes"]
    print(json.dumps(dict(out, partial=True)), flush=True)

    plan = [[(prompt(), long_ if s == 0 else short)
             for s in range(slots)] for _ in range(waves)]
    total_tokens = sum(n for wave in plan for _, n in wave)
    stream = sorted((seq for wave in plan for seq in wave),
                    key=lambda s: -s[1])

    # leg 1: static whole-batch (wave-synchronized — finished lanes
    # idle until the wave's longest member completes)
    telemetry.reset()
    t0 = time.perf_counter()
    for wave in plan:
        futs = [engine.submit(p, max_new_tokens=n) for p, n in wave]
        for f in futs:
            f.result(timeout=600)
    dt_static = time.perf_counter() - t0
    out["static_tok_s"] = round(total_tokens / dt_static, 1)
    print(json.dumps(dict(out, partial=True)), flush=True)

    # leg 2: continuous — same work, open-loop, per-step admission
    telemetry.reset()
    t0 = time.perf_counter()
    futs = [engine.submit(p, max_new_tokens=n) for p, n in stream]
    for f in futs:
        f.result(timeout=600)
    dt_cont = time.perf_counter() - t0
    snap = telemetry.snapshot()
    lat = snap["spans"].get("serve_decode_step", {})
    out.update({
        "total_tokens": total_tokens,
        "continuous_tok_s": round(total_tokens / dt_cont, 1),
        "decode_speedup": round(dt_static / dt_cont, 2),
        "token_latency_ms": {k: lat.get(k)
                             for k in ("p50_ms", "p95_ms", "p99_ms")},
        "jit_compiles_timed": snap["spans"].get(
            "jit_compile", {}).get("count", 0),
        "counters": {k: v for k, v in snap["counters"].items()
                     if k.startswith("decode.")},
    })
    out["series"] = _series_window()
    st = engine.stats()
    out["stats"] = {k: st.get(k) for k in
                    ("tokens", "steps", "slot_fill", "shed_requests",
                     "retries", "dispatch_failures")}
    engine.close()       # appends the decode corpus record when configured
    print(json.dumps(out), flush=True)


def _write_dp_artifact(obj):
    """MULTICHIP artifact schema superset: n_devices/ok/skipped plus the
    per-axis-size img/s table (ok=False+truncated=True until the sweep
    completes, so a killed run reads as partial, not as a clean round)."""
    art_dir = os.environ.get("MXTPU_ARTIFACT_DIR", "/tmp/mxtpu_artifacts")
    try:
        os.makedirs(art_dir, exist_ok=True)
        with open(os.path.join(art_dir, "multichip_dp_ab.json"), "w") as f:
            f.write(json.dumps(obj) + "\n")
    except OSError as e:
        print("bench: dp artifact write failed: %s" % e, file=sys.stderr)


def _last_json_line(text):
    """Salvage the last parseable JSON object line from child stdout.
    TimeoutExpired.stdout may be bytes even under text=True."""
    if isinstance(text, bytes):
        text = text.decode("utf-8", "replace")
    for line in reversed((text or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                pass
    return None


def _phase_cache_env():
    """Persisted compile cache for the executor-path children (module/
    dp/serve): one dir under the artifact tree keeps it across rounds
    on one box, so later rounds deserialize instead of re-invoking
    XLA. Returned as CHILD env only — supervise() must not mutate its
    own process env (the harness tests run supervise in-process, and
    an inherited cache would leak into every later in-process test)."""
    if os.environ.get("MXNET_COMPILE_CACHE"):
        return {}
    art_dir = os.environ.get("MXTPU_ARTIFACT_DIR", "/tmp/mxtpu_artifacts")
    # uid-scoped: cache entries are pickles, and the default artifact
    # tree lives under world-writable /tmp — a predictable shared path
    # would let another local user plant deserialization payloads
    # (compile_cache additionally refuses untrusted dirs at load)
    return {"MXNET_COMPILE_CACHE": os.path.join(
        art_dir, "compile_cache-uid%d" % os.getuid())}


def _run_phase(mode, timeout, env_extra=None):
    """Run one child phase; return (parsed_json_or_None, timed_out)."""
    env = None
    if env_extra:
        env = dict(os.environ)
        env.update(env_extra)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), mode],
            stdout=subprocess.PIPE, text=True, timeout=timeout, env=env)
    except subprocess.TimeoutExpired as e:
        # the child prints its JSON the moment it has it — salvage it
        return _last_json_line(e.stdout), True
    parsed = _last_json_line(proc.stdout)
    if proc.returncode != 0:
        print("bench: %s exited rc=%d" % (mode, proc.returncode),
              file=sys.stderr, flush=True)
    return parsed, False


def supervise():
    """Probe-gated supervision under one hard deadline.

    Init hangs on this relayed backend are per-process: a probe child
    that hangs says nothing about the NEXT process, so the supervisor
    probes cheaply (~75s child) in a loop for as long as the budget
    allows and launches the expensive raw child only after a probe
    succeeds — but PROBE_FAIL_LIMIT consecutive dead probes mark the
    tunnel down for the round and the diagnostic is emitted
    immediately instead of burning the whole deadline rediscovering it
    (r03-r05 spent 10+ probes that way). A raw child that then fails
    sends us back to probing. Whatever happens, exactly one final JSON
    line is printed — the measurement, or an {"error": ...} diagnostic
    the driver can record — and the cold-start seconds of every probe
    attempt ride in it either way.
    """
    t0 = time.monotonic()

    def remaining():
        return TOTAL_DEADLINE - (time.monotonic() - t0)

    def phase_budget(want):
        # strictly bounded by the global deadline (a floor above
        # remaining() would overrun it); 1s keeps subprocess.run valid
        return max(1.0, min(want, remaining()))

    if SMOKE:
        out, _ = _run_phase("--child", phase_budget(RAW_TIMEOUT))
        if out and "value" in out:
            print(json.dumps(out))
            return 0
        print(json.dumps({"error": "smoke child yielded no measurement"}))
        return 1

    out = None
    probes = fails = consec_probe_fails = 0
    probe_aborted = False
    probe_info = None
    probe_seconds = []       # cold-start wall per probe attempt
    while out is None and remaining() > PROBE_TIMEOUT:
        t_probe = time.monotonic()
        info, timed_out = _run_phase("--probe", phase_budget(PROBE_TIMEOUT))
        probes += 1
        probe_seconds.append(round(time.monotonic() - t_probe, 1))
        if not info:
            consec_probe_fails += 1
            print("bench: probe %d %s (%.0fs left)" %
                  (probes, "timed out" if timed_out else "failed",
                   remaining()), file=sys.stderr, flush=True)
            if consec_probe_fails >= PROBE_FAIL_LIMIT:
                # dead tunnel: every further probe would rediscover the
                # same outage — emit the partial diagnostic NOW and
                # hand the unburned budget back to the driver
                probe_aborted = True
                print("bench: %d consecutive dead probes — marking the "
                      "backend down for this round" % consec_probe_fails,
                      file=sys.stderr, flush=True)
                break
            time.sleep(min(PROBE_GAP, max(0.0, remaining() - PROBE_TIMEOUT)))
            continue
        consec_probe_fails = 0
        probe_info = info
        print("bench: probe %d ok: %s" % (probes, json.dumps(info)),
              file=sys.stderr, flush=True)
        if remaining() < RAW_MIN:
            break  # too late to measure; the diagnostic reports the probe
        out, timed_out = _run_phase(
            "--child", phase_budget(RAW_TIMEOUT),
            env_extra={"MXNET_FUSED_BN_ADD_RELU": "0"})  # pinned baseline
        if out and "value" in out:
            if timed_out:
                out["salvaged"] = True
            break
        out = None
        fails += 1
        print("bench: raw attempt %d yielded no measurement (%.0fs left)"
              % (fails, remaining()), file=sys.stderr, flush=True)
        if fails >= 3:
            break

    if out is None:
        if probe_info is None:
            detail = "backend never initialised in any probe child"
            if probe_aborted:
                detail += (" (%d consecutive dead probes; remaining "
                           "probes skipped)" % consec_probe_fails)
        elif fails:
            detail = "raw child failed after successful probe"
        else:
            detail = "deadline expired before a raw attempt could start"
        diag = {
            "error": "no measurement",
            # skipped=true marks a CLEAN no-backend round for the record
            # books: the number was never measurable, not measured-as-zero
            # (a tunnel outage must not read as a regression)
            "skipped": probe_info is None,
            "probes": probes, "probe_ok": probe_info is not None,
            "probe_seconds": probe_seconds,
            "probe_aborted": probe_aborted,
            "raw_fails": fails, "deadline_s": TOTAL_DEADLINE,
            "detail": detail,
        }
        if probe_info:
            diag["probe_device"] = probe_info
        print(json.dumps(diag))
        return 1
    out["probe_seconds"] = probe_seconds

    # partial-result emission: the raw number is banked on stdout NOW —
    # if a later optional phase hangs past the driver's window, the kill
    # salvages this line instead of zeroing the round
    print(json.dumps(dict(out, partial=True)), flush=True)

    if (os.environ.get("MXTPU_BENCH_MODULE", "1") == "1"
            and remaining() > 180):
        mod_out, _ = _run_phase("--module-child",
                                phase_budget(MODULE_TIMEOUT),
                                env_extra=_phase_cache_env())
        if mod_out and "module_fit_img_s" in mod_out:
            out.update((k, v) for k, v in mod_out.items()
                       if k.startswith("module_fit"))
            print(json.dumps(dict(out, partial=True)), flush=True)
        else:
            print("bench: module phase yielded no number (raw result kept)",
                  file=sys.stderr, flush=True)

    # data-parallel A/B (fused-SPMD vs kvstore phase-split per axis
    # size) — optional like the module phase, banked as partials
    if (os.environ.get("MXTPU_BENCH_DP", "1") == "1"
            and remaining() > 180):
        dp_out, _ = _run_phase("--dp-child", phase_budget(DP_TIMEOUT),
                               env_extra=_phase_cache_env())
        if dp_out and dp_out.get("dp"):
            out["dp"] = dp_out["dp"]
            out["dp_per_chip_batch"] = dp_out.get("per_chip_batch", BATCH)
            print(json.dumps(dict(out, partial=True)), flush=True)
        else:
            print("bench: dp phase yielded no number (raw result kept)",
                  file=sys.stderr, flush=True)

    # serving sweep (bucketed micro-batching engine vs the sequential
    # Predictor facade + the open-loop offered-load ladder) — optional,
    # banked as partials like the module/dp phases
    if (os.environ.get("MXTPU_BENCH_SERVE", "1") == "1"
            and remaining() > 120):
        sv_out, _ = _run_phase("--serve-child", phase_budget(SERVE_TIMEOUT),
                               env_extra=_phase_cache_env())
        if sv_out and sv_out.get("lane") == "serving":
            out["serving"] = {k: v for k, v in sv_out.items()
                              if k not in ("lane", "partial")}
            print(json.dumps(dict(out, partial=True)), flush=True)
        else:
            print("bench: serve phase yielded no number (raw result kept)",
                  file=sys.stderr, flush=True)

    # autoregressive decode sweep (continuous-batching slot engine vs
    # static whole-batch waves) — optional, banked as partials
    if (os.environ.get("MXTPU_BENCH_DECODE", "1") == "1"
            and remaining() > 120):
        dc_out, _ = _run_phase("--decode-child",
                               phase_budget(DECODE_TIMEOUT),
                               env_extra=_phase_cache_env())
        if dc_out and dc_out.get("lane") == "decode":
            out["decode"] = {k: v for k, v in dc_out.items()
                             if k not in ("lane", "partial")}
            print(json.dumps(dict(out, partial=True)), flush=True)
        else:
            print("bench: decode phase yielded no number (raw result "
                  "kept)", file=sys.stderr, flush=True)

    # opportunistic A/B of the fused BN-tail kernel (PERF.md: the
    # end-to-end number, not the isolated pass, decides the knob)
    if (os.environ.get("MXTPU_BENCH_AB", "1") == "1"
            and remaining() > RAW_MIN):
        ab_out, ab_timed_out = _run_phase(
            "--child", phase_budget(RAW_TIMEOUT),
            env_extra={"MXNET_FUSED_BN_ADD_RELU": "1"})
        if ab_out and "value" in ab_out:
            out["img_s_fused_bn_tail"] = ab_out["value"]
            if ab_timed_out:
                out["fused_bn_tail_salvaged"] = True
        else:
            print("bench: fused-BN A/B yielded no number",
                  file=sys.stderr, flush=True)

    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    _argv = _apply_budget_args(sys.argv[1:])
    if "--child" in _argv:
        child()
    elif "--probe" in _argv:
        probe()
    elif "--module-child" in _argv:
        module_child()
    elif "--dp-child" in _argv:
        dp_child()
    elif "--mp-child" in _argv:
        mp_child()
    elif "--serve-child" in _argv:
        serve_child()
    elif "--decode-child" in _argv:
        decode_child()
    else:
        sys.exit(supervise())
