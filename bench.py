"""Benchmark: ResNet-50 ImageNet-shape training throughput (img/s).

Baseline of record (BASELINE.md): the reference's published 109 img/s for
ResNet-50 batch-32 training on 1x K80 (example/image-classification/
README.md:147-155). This harness runs the same workload shape — forward
+ backward + SGD-momentum update, batch images at 224x224 — as ONE jitted
XLA program on the local accelerator, bf16 matmul precision (MXU native),
synthetic on-device data (compute-bound measurement, matching the
reference's benchmark_score.py methodology).

Prints one JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import time

import numpy as np

BASELINE_IMG_S = 109.0  # reference ResNet-50 1xK80 (BASELINE.md)
BATCH = 128
LR = 0.05
MOMENTUM = 0.9
# bf16 compute with fp32 master weights — the multi-precision scheme the
# reference implements as mp_sgd_update (optimizer_op.cc), MXU-native here
BF16 = True


def main():
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.gluon.block import make_pure_fn

    np.random.seed(0)
    net = vision.resnet50_v1()
    net.initialize(mx.initializer.Xavier())
    net(mx.nd.ones((1, 3, 32, 32)))  # complete deferred shapes
    fn, raw_params, _ = make_pure_fn(net, train=True)

    n_params = len(raw_params)

    def train_step(params, mom, x, y, rng):
        def loss_f(ps):
            if BF16:
                ps = [p.astype(jnp.bfloat16) for p in ps]
                xc = x.astype(jnp.bfloat16)
            else:
                xc = x
            (logits,), aux = fn(ps, [xc], rng)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            loss = -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))
            return loss, aux

        (loss, aux), grads = jax.value_and_grad(loss_f, has_aux=True)(params)
        new_params = []
        new_mom = []
        for i in range(n_params):
            if i in aux:  # BatchNorm running stats: direct writeback
                new_params.append(aux[i].astype(params[i].dtype))
                new_mom.append(mom[i])
                continue
            m = MOMENTUM * mom[i] - LR * grads[i].astype(params[i].dtype)
            new_mom.append(m)
            new_params.append(params[i] + m)
        return new_params, new_mom, loss

    step = jax.jit(train_step, donate_argnums=(0, 1))

    x = jnp.asarray(np.random.uniform(-1, 1, (BATCH, 3, 224, 224))
                    .astype(np.float32))
    y = jnp.asarray(np.random.randint(0, 1000, BATCH).astype(np.int32))
    rng = jax.random.key(0)
    params = [jnp.asarray(p) for p in raw_params]
    mom = [jnp.zeros_like(p) for p in params]

    # warmup / compile. NOTE: the final sync is a scalar fetch —
    # block_until_ready alone does not drain the execution queue on
    # relayed PJRT backends.
    for _ in range(3):
        params, mom, loss = step(params, mom, x, y, rng)
    float(loss)

    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        params, mom, loss = step(params, mom, x, y, rng)
    float(loss)
    dt = time.perf_counter() - t0

    img_s = BATCH * iters / dt
    print(json.dumps({
        "metric": "resnet50_train_throughput",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 3),
    }))


if __name__ == "__main__":
    main()
