"""Sustained-feed probe: decode running CONCURRENTLY with a consumer.

`tools/decode_bench.py` measures raw decode capacity; this probe proves
the property that actually matters for keeping the chip busy — the
pipeline (threaded JPEG decode -> batch assembly -> prefetch double
buffer, the reference's iter_image_recordio_2.cc:660-760 design)
OVERLAPS decode with consumption, so feeding a consumer that takes
`t_step` per batch costs max(decode, consume) wall-clock, not the sum.

A deployment points `--target-img-s` at its measured train throughput
(bench.py's img/s): the probe reports whether the feed sustained it,
the overlap efficiency, and how many decode cores at the measured
per-core rate the target needs.

Usage:
    python tools/feed_probe.py [--threads N] [--images M] [--size HxW]
                               [--batch B] [--target-img-s R]
Prints one JSON line.
"""
import argparse
import io as _io
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# host-side probe: never touch the accelerator (axon init can hang when
# the tunnel is down, and decode throughput is a CPU property anyway)
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pack_synthetic_rec(rec_path, images, h, w, seed=0):
    from PIL import Image
    from mxnet_tpu import recordio
    rs = np.random.RandomState(seed)
    rec = recordio.MXRecordIO(rec_path, "w")
    for i in range(images):
        arr = rs.randint(0, 255, (h, w, 3), np.uint8)
        buf = _io.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG", quality=90)
        rec.write(recordio.pack(
            recordio.IRHeader(0, float(i % 10), i, 0), buf.getvalue()))
    rec.close()


def run_probe(threads, images, h, w, batch, target_img_s=None, epochs=2,
              target_fraction=1.0):
    """Returns the probe result dict (no printing). ``target_fraction``
    scales the default target (measured decode capacity) — a deployment
    sizes decode cores with headroom, so sustaining ~100% of capacity on
    the same cores is not the operative claim."""
    from mxnet_tpu.image import ImageIter
    from mxnet_tpu.io import PrefetchingIter

    with tempfile.TemporaryDirectory() as td:
        rec_path = os.path.join(td, "probe.rec")
        pack_synthetic_rec(rec_path, images, h, w)

        def make_iter():
            return ImageIter(batch_size=batch, data_shape=(3, h, w),
                             path_imgrec=rec_path,
                             preprocess_threads=threads)

        # phase 1: decode-only capacity (warm epoch first)
        it = make_iter()
        for _ in it:
            pass
        n = 0
        t0 = time.perf_counter()
        for _ in range(epochs):
            it.reset()
            for b in it:
                n += b.data[0].shape[0]
        decode_img_s = n / (time.perf_counter() - t0)

        # consumer pace: the measured train rate, or decode capacity
        # scaled by target_fraction
        if target_img_s is not None:
            target = float(target_img_s)
            if target <= 0:
                raise ValueError("--target-img-s must be positive, got %r"
                                 % target_img_s)
        else:
            target = decode_img_s * float(target_fraction)
        t_step = batch / target

        # phase 2: decode CONCURRENT with a paced consumer behind the
        # prefetch double buffer
        feed = PrefetchingIter(make_iter())
        for _ in feed:   # warm epoch
            pass
        n = 0
        t0 = time.perf_counter()
        for _ in range(epochs):
            feed.reset()
            for b in feed:
                time.sleep(t_step)  # the "train step"
                n += b.data[0].shape[0]
        wall = time.perf_counter() - t0
        delivered_img_s = n / wall

        consume_time = n / target
        decode_time = n / decode_img_s
        serial_time = consume_time + decode_time
        ideal_time = max(consume_time, decode_time)
        # 1.0 = perfect overlap (wall == max of the two phases);
        # 0.0 = fully serialised (wall == sum)
        overlap = (serial_time - wall) / (serial_time - ideal_time) \
            if serial_time > ideal_time else 1.0

        per_core = decode_img_s / max(threads, 1)
        return {
            "metric": "sustained_feed",
            "value": round(delivered_img_s, 1),
            "unit": "img/s",
            "decode_img_s": round(decode_img_s, 1),
            "target_img_s": round(target, 1),
            "sustained": bool(delivered_img_s >= 0.85 * min(target,
                                                            decode_img_s)),
            "overlap_efficiency": round(max(0.0, min(overlap, 1.0)), 3),
            "threads": threads,
            "per_core_img_s": round(per_core, 1),
            "cores_needed_for_target": int(np.ceil(target / per_core)),
            "image_size": "%dx%d" % (h, w),
            "batch": batch,
        }


def _worker_decode(rec_path, h, w, batch, num_parts, part_index, epochs,
                   conn):
    """One decode worker: its shard of the rec (num_parts/part_index —
    the dmlc-core sharded-read contract every reference iterator
    honours), reporting (images, seconds, checksum-of-ids)."""
    from mxnet_tpu.image import ImageIter
    it = ImageIter(batch_size=batch, data_shape=(3, h, w),
                   path_imgrec=rec_path, preprocess_threads=1,
                   num_parts=num_parts, part_index=part_index)
    for _ in it:  # warm epoch (JIT/caches)
        pass
    n = 0
    ids = 0
    t0 = time.perf_counter()
    for _ in range(epochs):
        it.reset()
        for b in it:
            bs = b.data[0].shape[0] - b.pad
            n += bs
            ids += int(np.sum(np.asarray(b.label[0].asnumpy()[:bs])))
    conn.send((n, time.perf_counter() - t0, ids))
    conn.close()


def run_worker_probe(workers, images, h, w, batch, epochs=2):
    """Aggregate decode rate across N worker PROCESSES, each on its own
    shard — the process-scaling model behind PERF.md's multi-core feed
    sizing (per-core rate x N cores). On a 1-core host the processes
    time-slice, so the validated claims are (a) sharding covers every
    image exactly once and (b) aggregation adds no coordination loss
    beyond the scheduler (aggregate ~= single-process rate); the rate
    MULTIPLIES only with real cores."""
    import multiprocessing as mp
    with tempfile.TemporaryDirectory() as td:
        rec_path = os.path.join(td, "probe.rec")
        pack_synthetic_rec(rec_path, images, h, w)

        # single-process baseline on the full set
        parent, child = mp.Pipe()
        _worker_decode(rec_path, h, w, batch, 1, 0, epochs, child)
        base_n, base_dt, base_ids = parent.recv()
        base_rate = base_n / base_dt

        ctx = mp.get_context("spawn")
        pipes, procs = [], []
        t0 = time.perf_counter()
        for i in range(workers):
            pr, cw = ctx.Pipe()
            p = ctx.Process(target=_worker_decode,
                            args=(rec_path, h, w, batch, workers, i,
                                  epochs, cw))
            p.start()
            # drop the parent's child-end reference so a worker dying
            # before send() surfaces as EOFError instead of a hang
            cw.close()
            pipes.append(pr)
            procs.append(p)
        try:
            results = [pr.recv() for pr in pipes]
        except EOFError:
            for p in procs:
                p.terminate()
            raise RuntimeError("a decode worker died before reporting "
                               "(see its stderr above)")
        for p in procs:
            p.join()
        wall = time.perf_counter() - t0

        total = sum(r[0] for r in results)
        ids = sum(r[2] for r in results)
        # aggregate = sum of the workers' CONCURRENT decode rates (their
        # timed loops overlap); parent wall additionally pays per-process
        # interpreter+jax startup (~seconds), which a real deployment
        # pays once per epoch-spanning worker, not per measurement
        agg_rate = sum(r[0] / r[1] for r in results)
        return {
            "metric": "worker_decode_scaling",
            "value": round(agg_rate, 1),
            "unit": "img/s",
            "workers": workers,
            "single_process_img_s": round(base_rate, 1),
            "per_worker_img_s": [round(r[0] / r[1], 1) for r in results],
            "images_total": total,
            "shard_exact_cover": bool(total == base_n and ids == base_ids),
            "host_cores": os.cpu_count() or 1,
            "wall_with_startup_s": round(wall, 2),
            # on >=N-core hosts the model predicts ~N * per-core rate;
            # on fewer cores the workers time-slice and this ratio is the
            # scheduler overhead, not the scaling multiple
            "scaling_efficiency_vs_single": round(agg_rate / base_rate, 3),
        }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--threads", type=int, default=os.cpu_count() or 1)
    ap.add_argument("--images", type=int, default=256)
    ap.add_argument("--size", default="224x224")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--target-img-s", type=float, default=None,
                    help="consumer rate to sustain (e.g. bench.py's "
                         "measured img/s); default: decode capacity "
                         "scaled by --target-fraction")
    ap.add_argument("--target-fraction", type=float, default=1.0)
    ap.add_argument("--workers", type=int, default=0,
                    help="N>0: measure aggregate decode across N worker "
                         "PROCESSES on disjoint shards instead of the "
                         "threaded overlap probe")
    args = ap.parse_args()
    h, w = (int(x) for x in args.size.split("x"))
    if args.workers > 0:
        print(json.dumps(run_worker_probe(args.workers, args.images, h, w,
                                          args.batch)))
        return
    print(json.dumps(run_probe(args.threads, args.images, h, w, args.batch,
                               args.target_img_s,
                               target_fraction=args.target_fraction)))


if __name__ == "__main__":
    main()
