"""Distributed job launcher (parity: reference tools/launch.py, which drove
the dmlc tracker to spawn scheduler/server/worker processes over
ssh/mpi/yarn/sge/local).

TPU-native design: training is single-program SPMD — there are no
parameter-server roles. The launcher spawns N identical worker processes
wired together through ``jax.distributed`` (coordinator address +
process id), exactly how multi-host TPU pods are driven. ``--launcher
local`` forks the N processes on this host (the reference's localhost
test mode, used by tests/nightly/dist_sync_kvstore.py); ``--launcher
ssh`` prints/executes per-host commands.

Role env vars are still exported (DMLC_ROLE=worker, DMLC_NUM_WORKER,
DMLC_WORKER_ID) so reference launch scripts keep working; servers
(``-s``) are accepted and ignored with a note, since all-reduce replaces
the parameter server.

Elastic posture: each worker heartbeats into ``--heartbeat-dir`` (shared
filesystem) and gates every cross-process collective on peer liveness
(mxnet_tpu/heartbeat.py). A worker that dies mid-training is detected
within ``--heartbeat-timeout`` seconds by its peers, which re-mesh over
the survivors and resume from the last checkpoint when the training
script passes ``fit(checkpoint=...)`` — see README "Distributed
training" for what is lost on a member death.
"""
import argparse
import os
import shlex
import shutil
import signal
import subprocess
import sys
import tempfile


def build_env(rank, args):
    env = dict(os.environ)
    env.update({
        "DMLC_ROLE": "worker",
        "DMLC_NUM_WORKER": str(args.num_workers),
        "DMLC_WORKER_ID": str(rank),
        "MXNET_TPU_COORDINATOR": "%s:%d" % (args.host, args.port),
        "MXNET_TPU_NUM_PROCESSES": str(args.num_workers),
        "MXNET_TPU_PROCESS_ID": str(rank),
        # liveness surface (mxnet_tpu/heartbeat.py; reference
        # get_num_dead_node via scheduler heartbeats, kvstore.h:338 —
        # promoted to the pre-collective gate + elastic re-mesh)
        "MXTPU_HEARTBEAT_DIR": args.heartbeat_dir,
        "MXTPU_HEARTBEAT_INTERVAL": str(args.heartbeat_interval),
        "MXTPU_HEARTBEAT_TIMEOUT": str(args.heartbeat_timeout),
    })
    if args.force_cpu:
        env["MXNET_TPU_FORCE_CPU"] = "1"
        env.setdefault("XLA_FLAGS",
                       "--xla_force_host_platform_device_count=%d"
                       % args.devices_per_worker)
    return env


def launch_local(args, command):
    procs = []
    for rank in range(args.num_workers):
        procs.append(subprocess.Popen(command,
                                      env=build_env(rank, args)))

    def _terminate(*_):
        for p in procs:
            p.terminate()
        sys.exit(1)

    signal.signal(signal.SIGINT, _terminate)
    signal.signal(signal.SIGTERM, _terminate)
    rc = 0
    for p in procs:
        rc |= p.wait()
    return rc


def launch_ssh(args, command):
    hosts = [h.strip() for h in open(args.hostfile) if h.strip()]
    procs = []
    for rank in range(args.num_workers):
        host = hosts[rank % len(hosts)]
        env = build_env(rank, args)
        exports = " ".join("%s=%s" % (k, shlex.quote(v))
                           for k, v in env.items()
                           if k.startswith(("DMLC_", "MXNET_TPU_",
                                            "MXTPU_", "XLA_")))
        dst = shlex.quote(args.sync_dst_dir) if args.sync_dst_dir else "~"
        remote = "cd %s && env %s %s" % (
            dst, exports, " ".join(shlex.quote(c) for c in command))
        procs.append(subprocess.Popen(["ssh", "-o",
                                       "StrictHostKeyChecking=no", host,
                                       remote]))
    rc = 0
    for p in procs:
        rc |= p.wait()
    return rc


def main():
    parser = argparse.ArgumentParser(
        description="Launch a distributed job",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-n", "--num-workers", type=int, required=True)
    parser.add_argument("-s", "--num-servers", type=int, default=0,
                        help="accepted for CLI parity; all-reduce replaces "
                             "parameter servers, so this is ignored")
    parser.add_argument("--launcher", type=str, default="local",
                        choices=["local", "ssh"])
    parser.add_argument("-H", "--hostfile", type=str, default=None)
    parser.add_argument("--host", type=str, default="127.0.0.1",
                        help="coordinator address")
    parser.add_argument("--port", type=int, default=9357)
    parser.add_argument("--sync-dst-dir", type=str, default=None)
    parser.add_argument("--force-cpu", action="store_true",
                        help="run workers on virtual CPU devices (testing)")
    parser.add_argument("--heartbeat-dir", type=str, default=None,
                        help="shared dir for worker liveness heartbeats "
                             "(default: a per-port tempdir, wiped at launch)")
    parser.add_argument("--heartbeat-interval", type=float, default=1.0,
                        help="seconds between liveness beats")
    parser.add_argument("--heartbeat-timeout", type=float, default=10.0,
                        help="beat staleness after which a worker is "
                             "declared dead (drives how fast survivors "
                             "re-mesh)")
    parser.add_argument("--devices-per-worker", type=int, default=1)
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args()

    if not args.command:
        parser.error("no command given")
    if args.num_servers:
        print("note: -s/--num-servers ignored — gradients are all-reduced "
              "over the device mesh, no parameter-server processes exist")
    if args.launcher == "ssh" and not args.hostfile:
        parser.error("ssh launcher needs -H hostfile")

    if args.heartbeat_dir is None:
        args.heartbeat_dir = os.path.join(tempfile.gettempdir(),
                                          "mxtpu-hb-%d" % args.port)
    # stale worker-* files from a previous job on this port would read as
    # dead nodes — start each job from a clean directory
    if os.path.isdir(args.heartbeat_dir):
        shutil.rmtree(args.heartbeat_dir, ignore_errors=True)
    os.makedirs(args.heartbeat_dir, exist_ok=True)

    launch = launch_local if args.launcher == "local" else launch_ssh
    sys.exit(launch(args, args.command))


if __name__ == "__main__":
    main()
