"""rec2idx — rebuild the .idx index for an existing RecordIO file
(parity: reference tools/rec2idx.py). Each line of the .idx is
``<record id>\t<byte offset>`` so MXIndexedRecordIO can seek.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("MXNET_TPU_FORCE_CPU", "1")
from mxnet_tpu import recordio  # noqa: E402


def main():
    ap = argparse.ArgumentParser(
        description="Create an index file from a RecordIO file")
    ap.add_argument("record", help="path to the .rec file")
    ap.add_argument("index", help="path of the .idx to write")
    args = ap.parse_args()

    reader = recordio.MXRecordIO(args.record, "r")
    entries = []
    while True:
        pos = reader.tell()
        buf = reader.read()
        if buf is None:
            break
        try:
            header, _ = recordio.unpack(buf)
            rid = header.id
        except Exception:
            rid = len(entries)
        entries.append((rid, pos))
    ids = [rid for rid, _ in entries]
    if len(set(ids)) != len(ids):
        # duplicate header ids (commonly all-zero) would collapse the
        # index to one reachable record per id - key the whole file
        # sequentially instead
        print("duplicate record ids; keying sequentially")
        entries = [(i, pos) for i, (_, pos) in enumerate(entries)]
    with open(args.index, "w") as out:
        for rid, pos in entries:
            out.write("%d\t%d\n" % (rid, pos))
    print("wrote %d entries to %s" % (len(entries), args.index))


if __name__ == "__main__":
    main()
