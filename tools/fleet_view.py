#!/usr/bin/env python3
"""fleet_view: join N ranks' flight-recorder artifacts into ONE
cluster view (ISSUE 18).

Usage::

    python tools/fleet_view.py FLIGHT_DIR [--json] [--trace OUT.json]

A fleet shares one ``MXNET_FLIGHT_DIR``; every rank banks its own
rank-stamped postmortem (``postmortem-r<rank>-<pid>-<seq>-<reason>
.json``) and series JSONL there. This tool reads them all and answers
the questions no single rank's dump can:

* **who is dead** — union of every dump's recorded dead ranks, the
  ``dead_worker`` extras, and any rank whose own newest dump is a
  ``worker_abort``;
* **who made everyone wait** — the straggler ranking: each rank's
  ``gate_wait`` spans carry the attributed last-arriver in ctx, so the
  fleet-wide blame table is a join, not a guess. ``dist.straggler``
  events ride along as corroboration;
* **one timebase** — per-rank clock offsets solved from matched gate
  crossings: a (channel, generation) gate crossing is a SHARED event
  every participating rank records within one gate-poll interval, so
  ``offset[r] = median over matched crossings of (end_r - end_ref)``.
  The reference is the lowest parsed rank;
* **one trace** — ``--trace`` writes a merged chrome://tracing /
  perfetto JSON with one process track per rank (offset-corrected),
  instant markers for straggler/fault/elastic events, and cross-rank
  flow arrows tying each gate generation's crossings together.

``--json`` emits the machine-readable fleet summary
(``mxnet_tpu.fleet/1``). Corrupt or half-written per-rank dumps
degrade to a NAMED warning on stderr — exit 2 only when ZERO ranks
parse. Stdlib-only, like flight_view (which it imports for the
single-dump loader).
"""
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import flight_view  # noqa: E402  (the single-dump loader/validator)

FLEET_SCHEMA = "mxnet_tpu.fleet/1"

_PM_RE = re.compile(r"^postmortem-r(\d+)-\d+-\d+-.*\.json$")
_PM_LEGACY_RE = re.compile(r"^postmortem-\d+-\d+-.*\.json$")
_SERIES_RE = re.compile(r"^flight-series-r(\d+)-\d+\.jsonl$")

# events that become instant markers on the merged trace
_MARKER_EVENTS = ("dist.straggler", "fault.injected", "flight.postmortem",
                  "elastic.dead_worker", "elastic.resumed")


def _percentile(sorted_vals, pct):
    if not sorted_vals:
        return None
    k = (len(sorted_vals) - 1) * pct / 100.0
    lo = int(k)
    hi = min(lo + 1, len(sorted_vals) - 1)
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * (k - lo)


def discover(directory):
    """Per-rank artifact paths: ``{rank: {"dumps": [paths newest
    first], "series": [paths]}}``. Legacy unranked dumps (pre-fleet
    ``postmortem-<pid>-...``) land under rank None and are resolved by
    their embedded process block at load time."""
    try:
        names = os.listdir(directory)
    except OSError as e:
        raise flight_view.MalformedDump(
            "cannot list %s: %s" % (directory, e))
    out = {}

    def slot(rank):
        return out.setdefault(rank, {"dumps": [], "series": []})

    for name in sorted(names):
        path = os.path.join(directory, name)
        m = _PM_RE.match(name)
        if m:
            slot(int(m.group(1)))["dumps"].append(path)
            continue
        if _PM_LEGACY_RE.match(name):
            slot(None)["dumps"].append(path)
            continue
        m = _SERIES_RE.match(name)
        if m:
            slot(int(m.group(1)))["series"].append(path)
    for rec in out.values():
        rec["dumps"].sort(key=_mtime, reverse=True)
    return out


def _mtime(path):
    try:
        return os.path.getmtime(path)
    except OSError:
        return 0.0


def load_fleet(directory):
    """One primary (= newest parseable) dump per rank plus its series
    samples: ``({rank: {...}}, warnings)``. Every malformed artifact
    becomes a named warning; only a fleet with ZERO parseable ranks is
    an error (the caller exits 2)."""
    found = discover(directory)
    ranks, warnings = {}, []
    for rank, arts in sorted(found.items(),
                             key=lambda kv: (kv[0] is None, kv[0])):
        rec = None
        for path in arts["dumps"]:
            try:
                rec = flight_view.load_dump(path)
            except flight_view.MalformedDump as e:
                warnings.append("skipping malformed dump: %s" % e)
                continue
            actual = rank
            if actual is None:        # legacy name: ask the record
                actual = (rec.get("process") or {}).get("rank", 0)
            if actual in ranks:
                rec = None            # a ranked dump already won
                break
            ranks[actual] = {"path": path, "rec": rec, "series": []}
            break
        if rec is None and not arts["dumps"] and arts["series"]:
            # a rank can flush its series ring at exit without ever
            # dumping a postmortem — still part of the fleet picture
            ranks.setdefault(rank, {"path": None, "rec": None,
                                    "series": []})
    for rank, arts in found.items():
        if rank is None or rank not in ranks:
            continue
        for path in arts["series"]:
            try:
                with open(path) as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            ranks[rank]["series"].append(json.loads(line))
            except (OSError, ValueError) as e:
                warnings.append("skipping malformed series %s: %s"
                                % (path, e))
    return ranks, warnings


# ---------------------------------------------------------------------------
# Clock-offset solve
# ---------------------------------------------------------------------------

def gate_crossings(rec):
    """``{(channel, generation): end_epoch_s}`` from a dump's
    ``gate_wait`` spans. The END of a crossing is the shared instant:
    every rank leaves the gate within one poll interval of the last
    arrival, while the start (= its own arrival) is exactly the skew
    being measured."""
    out = {}
    for span in rec.get("spans") or []:
        if span.get("name") != "gate_wait":
            continue
        ctx = span.get("ctx") or {}
        ch, gen = ctx.get("channel"), ctx.get("generation")
        if ch is None or gen is None or span.get("ts") is None:
            continue
        out[(str(ch), int(gen))] = (float(span["ts"])
                                    + float(span.get("dur_ms") or 0.0)
                                    / 1e3)
    return out


def solve_offsets(ranks):
    """Per-rank clock offset (seconds to SUBTRACT from that rank's
    timestamps to land on the reference rank's timebase) via the
    median over matched gate crossings. Returns ``(reference_rank,
    {rank: offset_s}, {rank: matched_count})``."""
    crossings = {r: gate_crossings(d["rec"]) for r, d in ranks.items()
                 if d["rec"] is not None}
    parsed = sorted(crossings)
    if not parsed:
        return None, {}, {}
    ref = parsed[0]
    offsets, matched = {ref: 0.0}, {ref: len(crossings[ref])}
    for r in parsed[1:]:
        common = sorted(set(crossings[r]) & set(crossings[ref]))
        matched[r] = len(common)
        if not common:
            offsets[r] = 0.0
            continue
        deltas = sorted(crossings[r][k] - crossings[ref][k]
                        for k in common)
        offsets[r] = _percentile(deltas, 50)
    return ref, offsets, matched


# ---------------------------------------------------------------------------
# Fleet summary
# ---------------------------------------------------------------------------

def _rank_summary(rank, data):
    rec = data["rec"]
    out = {"rank": rank, "n_series_samples": len(data["series"]),
           "dump": data["path"]}
    if rec is None:
        out.update({"reason": None, "host": None, "mfu": None,
                    "step_p95_ms": None, "gate_wait_ms": {},
                    "crossings": {}})
        return out
    proc = rec.get("process") or {}
    counters = rec.get("counters") or {}
    steps = sorted(s.get("dur_ms") or 0.0
                   for s in rec.get("spans") or []
                   if s.get("name") == "step")
    gate_wait = {k[len("heartbeat.gate_wait_ms."):]: round(v, 3)
                 for k, v in counters.items()
                 if k.startswith("heartbeat.gate_wait_ms.")}
    crossings = {k[len("heartbeat.gate_crossings."):]: v
                 for k, v in counters.items()
                 if k.startswith("heartbeat.gate_crossings.")}
    out.update({
        "reason": rec.get("reason"),
        "ts": rec.get("ts"),
        "host": proc.get("host"),
        "pid": rec.get("pid"),
        "mfu": (rec.get("online") or {}).get("mfu"),
        "step_p95_ms": (round(_percentile(steps, 95), 3)
                        if steps else None),
        "gate_wait_ms": gate_wait,
        "crossings": crossings,
    })
    return out


def _dead_ranks(ranks):
    dead = set()
    for rank, data in ranks.items():
        rec = data["rec"]
        if rec is None:
            continue
        if rec.get("reason") == "worker_abort":
            dead.add(rank)
        dead.update((rec.get("process") or {}).get("dead_ranks") or [])
        extra = rec.get("extra") or {}
        if isinstance(extra, dict):
            dead.update(extra.get("dead_ranks") or [])
    return sorted(int(r) for r in dead)


def straggler_ranking(ranks):
    """Fleet-wide blame table: each recorded ``gate_wait`` span blames
    its attributed last-arriver for the span's wait (self-waits — the
    straggler observing its own ~0 wait — don't count), and
    ``dist.straggler`` verdicts are tallied per named rank. Sorted
    worst first."""
    blame = {}

    def slot(r):
        return blame.setdefault(int(r), {
            "rank": int(r), "blamed_wait_ms": 0.0,
            "blamed_crossings": 0, "straggler_events": 0})

    for rank, data in ranks.items():
        rec = data["rec"]
        if rec is None:
            continue
        for span in rec.get("spans") or []:
            if span.get("name") != "gate_wait":
                continue
            ctx = span.get("ctx") or {}
            last = ctx.get("last_rank")
            if last is None or int(last) == int(rank):
                continue
            s = slot(last)
            s["blamed_wait_ms"] += float(span.get("dur_ms") or 0.0)
            s["blamed_crossings"] += 1
        for ev in rec.get("events") or []:
            if ev.get("kind") != "dist.straggler":
                continue
            named = (ev.get("data") or {}).get("rank")
            if named is not None:
                slot(named)["straggler_events"] += 1
    out = sorted(blame.values(),
                 key=lambda s: (-s["blamed_wait_ms"],
                                -s["straggler_events"]))
    for s in out:
        s["blamed_wait_ms"] = round(s["blamed_wait_ms"], 3)
    return out


def summarize(ranks, warnings):
    ref, offsets, matched = solve_offsets(ranks)
    return {
        "schema": FLEET_SCHEMA,
        "n_ranks": len(ranks),
        "ranks": {str(r): _rank_summary(r, d)
                  for r, d in sorted(ranks.items())},
        "dead_ranks": _dead_ranks(ranks),
        "stragglers": straggler_ranking(ranks),
        "clock": {
            "reference_rank": ref,
            "offsets_s": {str(r): round(o, 6)
                          for r, o in sorted(offsets.items())},
            "matched_crossings": {str(r): m
                                  for r, m in sorted(matched.items())},
        },
        "warnings": warnings,
    }


# ---------------------------------------------------------------------------
# Merged trace
# ---------------------------------------------------------------------------

def merged_trace(ranks):
    """One chrome://tracing JSON over every parsed rank: pid = rank
    (its own track, offset-corrected onto the reference timebase),
    span ctx preserved as args, instant markers for
    straggler/fault/elastic events, and one flow arrow per gate
    generation tying the ranks' crossings together."""
    ref, offsets, _matched = solve_offsets(ranks)
    events = []
    gate_flow = {}          # (channel, gen) -> [(adj_end_us, rank, tid)]
    for rank, data in sorted(ranks.items()):
        rec = data["rec"]
        if rec is None:
            continue
        proc = rec.get("process") or {}
        off = offsets.get(rank, 0.0)
        events.append({"ph": "M", "name": "process_name", "pid": rank,
                       "tid": 0,
                       "args": {"name": "rank %d (%s)%s" % (
                           rank, proc.get("host", "?"),
                           " [dead]" if rec.get("reason")
                           == "worker_abort" else "")}})
        events.append({"ph": "M", "name": "process_sort_index",
                       "pid": rank, "tid": 0,
                       "args": {"sort_index": rank}})
        tids = set()
        for span in rec.get("spans") or []:
            ts = span.get("ts")
            if ts is None:
                continue
            tid = span.get("tid") or 0
            tids.add(tid)
            ctx = span.get("ctx") or {}
            start_us = (float(ts) - off) * 1e6
            dur_us = float(span.get("dur_ms") or 0.0) * 1e3
            ev = {"ph": "X", "name": span.get("name", "?"),
                  "pid": rank, "tid": tid,
                  "ts": start_us, "dur": dur_us}
            if ctx:
                ev["args"] = ctx
            events.append(ev)
            if span.get("name") == "gate_wait" \
                    and ctx.get("channel") is not None \
                    and ctx.get("generation") is not None:
                key = (str(ctx["channel"]), int(ctx["generation"]))
                gate_flow.setdefault(key, []).append(
                    (start_us + dur_us, rank, tid))
        for ev in rec.get("events") or []:
            if ev.get("kind") not in _MARKER_EVENTS:
                continue
            events.append({"ph": "i", "name": ev["kind"], "pid": rank,
                           "tid": 0, "s": "p",
                           "ts": (float(ev.get("ts", 0.0)) - off) * 1e6,
                           "args": ev.get("data") or {}})
        for tid in sorted(tids):
            events.append({"ph": "M", "name": "thread_name",
                           "pid": rank, "tid": tid,
                           "args": {"name": "host thread %d" % tid}})
    for (channel, gen), ends in sorted(gate_flow.items()):
        if len(ends) < 2:
            continue
        ends.sort()
        fid = "gate:%s:%d" % (channel, gen)
        first_us, first_rank, first_tid = ends[0]
        events.append({"ph": "s", "cat": "gate", "name": "gate",
                       "id": fid, "pid": first_rank, "tid": first_tid,
                       "ts": first_us})
        for i, (us, rank, tid) in enumerate(ends[1:]):
            events.append({"ph": "f" if i == len(ends) - 2 else "t",
                           "cat": "gate", "name": "gate", "id": fid,
                           "pid": rank, "tid": tid, "ts": us,
                           "bp": "e"})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "metadata": {"schema": FLEET_SCHEMA,
                         "reference_rank": ref}}


# ---------------------------------------------------------------------------
# Text render
# ---------------------------------------------------------------------------

def render(summary, out=sys.stdout):
    w = out.write
    w("fleet view: %d rank(s)\n" % summary["n_ranks"])
    dead = summary["dead_ranks"]
    w("  dead ranks: %s\n" % (dead if dead else "(none)"))
    clock = summary["clock"]
    w("  clock: reference rank %s; offsets (s): %s; matched "
      "crossings: %s\n"
      % (clock["reference_rank"], clock["offsets_s"],
         clock["matched_crossings"]))
    w("\nper-rank:\n")
    w("  %4s %-12s %-16s %8s %10s %12s\n"
      % ("rank", "host", "reason", "mfu", "step_p95", "gate_wait_ms"))
    for _r, rs in sorted(summary["ranks"].items(),
                         key=lambda kv: int(kv[0])):
        w("  %4s %-12s %-16s %8s %10s %12s\n"
          % (rs["rank"], rs.get("host") or "-",
             (rs.get("reason") or "-")[:16],
             "-" if rs.get("mfu") is None else "%.3f" % rs["mfu"],
             "-" if rs.get("step_p95_ms") is None
             else "%.1f" % rs["step_p95_ms"],
             "-" if not rs.get("gate_wait_ms")
             else ",".join("%s:%.0f" % kv
                           for kv in sorted(rs["gate_wait_ms"]
                                            .items()))))
    stragglers = summary["stragglers"]
    w("\nstraggler ranking (blamed gate wait, fleet-wide):\n")
    for s in stragglers or []:
        w("  rank %d: %.1f ms over %d crossings, %d dist.straggler "
          "event(s)\n"
          % (s["rank"], s["blamed_wait_ms"], s["blamed_crossings"],
             s["straggler_events"]))
    if not stragglers:
        w("  (no attributed gate waits)\n")
    for warning in summary["warnings"]:
        w("warning: %s\n" % warning)
    w("\n")


def main(argv):
    args, as_json, trace_path = [], False, None
    it = iter(argv[1:])
    for a in it:
        if a == "--json":
            as_json = True
        elif a == "--trace":
            trace_path = next(it, None)
            if trace_path is None:
                print("usage: fleet_view.py FLIGHT_DIR [--json] "
                      "[--trace OUT.json]", file=sys.stderr)
                return 2
        elif a.startswith("--"):
            print("fleet_view: unknown option %r" % a, file=sys.stderr)
            return 2
        else:
            args.append(a)
    if len(args) != 1:
        print("usage: fleet_view.py FLIGHT_DIR [--json] "
              "[--trace OUT.json]", file=sys.stderr)
        return 2
    try:
        ranks, warnings = load_fleet(args[0])
    except flight_view.MalformedDump as e:
        print("fleet_view: %s" % e, file=sys.stderr)
        return 2
    for warning in warnings:
        print("fleet_view: warning: %s" % warning, file=sys.stderr)
    if not any(d["rec"] is not None for d in ranks.values()):
        print("fleet_view: no parseable rank dumps in %s" % args[0],
              file=sys.stderr)
        return 2
    if trace_path:
        with open(trace_path, "w") as f:
            json.dump(merged_trace(ranks), f)
        print("fleet_view: wrote merged trace %s" % trace_path,
              file=sys.stderr)
    summary = summarize(ranks, warnings)
    if as_json:
        json.dump(summary, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        render(summary)
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main(sys.argv))
    except BrokenPipeError:
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
        sys.exit(0)
