"""Parse training logs into a table (parity: reference tools/parse_log.py).

Reads the fit() logging format::

    INFO:root:Epoch[0] Batch [20]  Speed: 16470.55 samples/sec  accuracy=1.0
    INFO:root:Epoch[0] Train-accuracy=0.95
    INFO:root:Epoch[0] Time cost=1.744
    INFO:root:Epoch[0] Validation-accuracy=0.93

and prints per-epoch train/validation metric + mean speed, markdown or
tsv.
"""
import argparse
import re
import sys
from collections import defaultdict

RE_EPOCH_METRIC = re.compile(
    r"Epoch\[(\d+)\]\s+(Train|Validation)-([\w-]+)=([0-9.eE+-]+)")
RE_SPEED = re.compile(r"Epoch\[(\d+)\].*Speed:\s*([0-9.]+)")
RE_TIME = re.compile(r"Epoch\[(\d+)\]\s+Time cost=([0-9.]+)")


def parse(lines):
    rows = defaultdict(dict)
    speeds = defaultdict(list)
    for line in lines:
        m = RE_EPOCH_METRIC.search(line)
        if m:
            epoch, kind, metric, val = m.groups()
            rows[int(epoch)]["%s-%s" % (kind.lower(), metric)] = float(val)
        m = RE_SPEED.search(line)
        if m:
            speeds[int(m.group(1))].append(float(m.group(2)))
        m = RE_TIME.search(line)
        if m:
            rows[int(m.group(1))]["time"] = float(m.group(2))
    for epoch, s in speeds.items():
        rows[epoch]["speed"] = sum(s) / len(s)
    return dict(rows)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("logfile", nargs="?", default="-")
    parser.add_argument("--format", choices=["markdown", "tsv"],
                        default="markdown")
    args = parser.parse_args()

    f = sys.stdin if args.logfile == "-" else open(args.logfile)
    rows = parse(f)
    if not rows:
        print("no epochs found")
        return
    cols = sorted({k for r in rows.values() for k in r})
    if args.format == "markdown":
        print("| epoch | " + " | ".join(cols) + " |")
        print("| --- | " + " | ".join("---" for _ in cols) + " |")
        fmt = "| %d | " + " | ".join("%s" for _ in cols) + " |"
    else:
        print("epoch\t" + "\t".join(cols))
        fmt = "%d\t" + "\t".join("%s" for _ in cols)
    for epoch in sorted(rows):
        vals = tuple(("%.6g" % rows[epoch][c]) if c in rows[epoch] else "-"
                     for c in cols)
        print(fmt % ((epoch,) + vals))


if __name__ == "__main__":
    main()
