"""Fresh-capture MFU/roofline analysis for the bench workload.

One command reproduces PERF.md's breakdown table and roofline ceiling
from a NEW xprof capture (so the analysis tracks the current code, not
round-3's trace):

    python tools/mfu_capture.py              # real chip (or CPU smoke:
    MXTPU_BENCH_SMOKE=1 python tools/mfu_capture.py)

Runs ``bench.py --child`` with MXTPU_BENCH_TRACE set, finds the
resulting ``.xplane.pb``, aggregates per-op self time into the same
categories PERF.md uses (convolution fusions / elementwise loop
fusions / copy-and-data-formatting / other), and re-derives the
memory-bound MFU ceiling from the step's FLOPs and bytes.

FLOPs/bytes come from the bench child's PROGRAM CARD first
(``telemetry.programs()`` — the compile-time ``cost_analysis`` /
``memory_analysis`` figures the child embeds in its JSON line as
``step_flops``/``step_bytes_accessed``), so the roofline no longer
NEEDS an xprof capture; the xplane ``hlo_stats`` aggregation remains
as the fallback byte source (older children) and still feeds the
per-category self-time table when a trace materialises.
"""
import argparse
import glob
import json
import os
import re
import subprocess
import sys
import tempfile

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

# HBM bandwidth by device kind (public spec sheets), for the
# FLOP/byte break-even in the roofline re-derivation
HBM_BW = [("v6", 1.6e12), ("trillium", 1.6e12), ("v5p", 2.77e12),
          ("v5 lite", 8.19e11), ("v5e", 8.19e11), ("v5litepod", 8.19e11),
          ("v4", 1.2e12), ("v3", 9.0e11), ("v2", 7.0e11)]


def hbm_bw_for(kind):
    k = kind.lower()
    for sub, val in HBM_BW:
        if sub in k:
            return val
    return None


def run_traced_child(trace_dir, timeout):
    env = dict(os.environ)
    env["MXTPU_BENCH_TRACE"] = trace_dir
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "--child"],
            stdout=subprocess.PIPE, text=True, timeout=timeout, env=env)
        stdout = proc.stdout
    except subprocess.TimeoutExpired as e:
        stdout = e.stdout or b""
        if isinstance(stdout, bytes):
            stdout = stdout.decode("utf-8", "replace")
    for ln in reversed(stdout.splitlines()):
        ln = ln.strip()
        if ln.startswith("{"):
            try:
                return json.loads(ln)
            except ValueError:
                pass
    return None


def find_xplane(trace_dir):
    hits = sorted(glob.glob(os.path.join(
        trace_dir, "**", "*.xplane.pb"), recursive=True))
    return hits[-1] if hits else None


def categorise(name, category_hint=""):
    text = (category_hint or "") + " " + name
    if re.search(r"convolution|%conv", text, re.I):
        return "convolution fusions"
    if re.search(r"copy|transpose|bitcast|data formatting|pad", text, re.I):
        return "copy/data-formatting"
    if re.search(r"select-and-scatter", text, re.I):
        return "select-and-scatter"
    if re.search(r"fusion|add|multiply|divide|maximum|loop", text, re.I):
        return "elementwise loop fusions"
    return "other"


_SKIP = re.compile(
    r"ThunkExecutor|wait for completion|^\$|np\.asarray|^\s*$|"
    r"^python$|profiler|RunExecutable|ExecuteComputation|BufferAlloc",
    re.I)


def hlo_op_rows(xplane_path):
    """Aggregate per-HLO-op self time (and bytes, when the plane carries
    byte stats) straight from the xplane proto — no tool-data converter
    needed. Returns [{name, dur_ps, bytes}]."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2
    xs = xplane_pb2.XSpace()
    with open(xplane_path, "rb") as f:
        xs.ParseFromString(f.read())
    # prefer accelerator planes; otherwise the host XLA-client lines
    planes = [p for p in xs.planes if "/device:" in p.name.lower()
              or "tpu" in p.name.lower()]
    host_fallback = not planes
    if host_fallback:
        planes = [p for p in xs.planes if p.name == "/host:CPU"]
    agg = {}
    for pl in planes:
        emeta = {k: v for k, v in pl.event_metadata.items()}
        smeta = {k: v.name for k, v in pl.stat_metadata.items()}
        lines = list(pl.lines)
        if host_fallback:
            lines = [ln for ln in lines if "XLA" in ln.name]
        else:
            # device planes carry module/step summary lines whose events
            # span all ops — summing them would double-count; keep the
            # op-level line(s) only
            op_lines = [ln for ln in lines if "ops" in ln.name.lower()]
            if op_lines:
                lines = op_lines
            else:
                lines = [ln for ln in lines
                         if not re.search(r"module|step", ln.name, re.I)]
        for line in lines:
            for ev in line.events:
                md = emeta.get(ev.metadata_id)
                name = (md.display_name or md.name) if md else "?"
                if _SKIP.search(name):
                    continue
                row = agg.setdefault(name, {"name": name, "dur_ps": 0,
                                            "bytes": 0.0, "category": ""})
                row["dur_ps"] += ev.duration_ps
                for st in ev.stats:
                    sname = smeta.get(st.metadata_id, "").lower()
                    # ONLY the aggregate byte counter; per-memory-space
                    # breakdowns ("bytes accessed0{}", ...) would
                    # double-count
                    if sname.replace("_", " ").strip() == "bytes accessed":
                        which = st.WhichOneof("value")
                        if which in ("int64_value", "uint64_value",
                                     "double_value"):
                            row["bytes"] += float(getattr(st, which))
                    elif "category" in sname:
                        which = st.WhichOneof("value")
                        if which == "str_value":
                            row["category"] = st.str_value
                        elif which == "ref_value":
                            row["category"] = smeta.get(st.ref_value, "")
    return list(agg.values())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--timeout", type=float, default=900)
    ap.add_argument("--trace-dir", default="")
    args = ap.parse_args()

    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="mfu_trace_")
    print("mfu_capture: tracing into", trace_dir, file=sys.stderr)
    bench_line = run_traced_child(trace_dir, args.timeout)
    if not bench_line or "value" not in bench_line:
        print(json.dumps({"error": "traced bench child yielded no "
                          "measurement", "bench": bench_line}))
        return 1

    # the bench child's program card carries the step's compile-time
    # FLOPs and bytes — the online source that makes the xprof capture
    # optional for the roofline arithmetic
    card_flops = bench_line.get("step_flops")
    card_bytes = bench_line.get("step_bytes_accessed")

    xplane = find_xplane(trace_dir)
    if not xplane and not card_bytes:
        print(json.dumps({"error": "no xplane.pb written and the bench "
                          "child carried no program card",
                          "bench": bench_line}))
        return 1

    out = {"bench": bench_line, "xplane": xplane}
    bytes_total = 0.0
    if xplane:
        rows = hlo_op_rows(xplane)
        shares = {}
        total_ps = 0
        for row in rows:
            total_ps += row["dur_ps"]
            cat = categorise(row["name"], row.get("category", ""))
            shares[cat] = shares.get(cat, 0) + row["dur_ps"]
            bytes_total += row["bytes"]
        top = sorted(rows, key=lambda r: -r["dur_ps"])[:8]
        out.update({
            "hlo_rows": len(rows),
            "op_time_total_ms": round(total_ps / 1e9, 2),
            "self_time_share": {
                k: round(v / total_ps, 4) for k, v in sorted(
                    shares.items(), key=lambda kv: -kv[1])}
            if total_ps else {},
            "top_ops": [{"name": r["name"][:60],
                         "ms": round(r["dur_ps"] / 1e9, 2)} for r in top],
        })
    # roofline ceiling re-derivation (PERF.md arithmetic, fresh inputs):
    # FLOP/byte of the step vs the chip's break-even ratio. Byte source
    # priority: program card (exact, compile-time) > xplane hlo_stats.
    from bench import peak_flops_for, ITERS  # noqa: E402
    peak = peak_flops_for(bench_line.get("device", ""))
    bw = hbm_bw_for(bench_line.get("device", ""))
    if card_bytes:
        bytes_per_step = float(card_bytes)
        out["bytes_source"] = "program_card"
    elif bytes_total:
        bytes_per_step = bytes_total / ITERS
        out["bytes_source"] = "xplane_hlo_stats"
    else:
        bytes_per_step = None
    if bytes_per_step and bench_line.get("tflops_per_s") and peak and bw:
        step_s = (bench_line["batch"] / bench_line["value"])
        flops_per_step = (float(card_flops) if card_flops
                          else bench_line["tflops_per_s"] * 1e12 * step_s)
        intensity = flops_per_step / bytes_per_step
        out["bytes_accessed_per_step"] = bytes_per_step
        out["flop_per_byte"] = round(intensity, 1)
        out["mfu_roofline_ceiling"] = round(
            min(1.0, intensity / (peak / bw)), 3)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
