#!/usr/bin/env bash
# One-shot validation gate: everything the repo claims, in one command.
#   bash tools/run_checks.sh          # full gate (lint + build + tests)
#   bash tools/run_checks.sh lint     # static stage only — no native
#                                     # build, no jax import, seconds
set -e
cd "$(dirname "$0")/.."

lint_stage() {
  echo "== mxlint (AST static analysis)"
  # replaces the old grep stanzas (raw jax.jit / raw dispatch_hook),
  # which an aliased `from jax import jit` walked straight past.
  # Thirteen rules across four families — direct (jit-site,
  # dispatch-hook, lock-discipline, host-sync, donation-safety,
  # registry-consistency), mxflow interprocedural (lockset,
  # trace-purity + transitive layers), mxsync concurrency
  # (thread-race, collective-discipline) and mxlife lifecycle
  # (future-lifecycle, resource-release, torn-state-on-raise) — all
  # stdlib-only: this stage needs no jax import and no native build.
  # Zero unsuppressed findings over the runtime, the tools and the
  # bench harness, against the committed grandfather file
  # tools/mxlint_baseline.json. `python tools/mxlint.py --explain
  # <rule>` documents any rule that fires; the pre-commit loop is
  # `python tools/mxlint.py --changed ...` (tools/pre-commit.sample).
  python tools/mxlint.py mxnet_tpu tools bench.py
  # the rule registry itself stays consistent: 13 ids, each with a
  # fixture pair (the meta-test enforces the pairing; this is the
  # jax-free smoke that the CLI agrees)
  test "$(python tools/mxlint.py --list-rules | wc -l)" -eq 13
}

if [ "${1:-}" = "lint" ]; then
  lint_stage
  echo "LINT OK"
  exit 0
fi

lint_stage
echo "== native build"
make -s
echo "== C++ unit tests"
make -s testcpp
echo "== python suite (virtual 8-device CPU mesh)"
python -m pytest tests/ -q
echo "== multichip dryrun (8 virtual devices: dp/sp/tp + Module dp + pp/ep)"
python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('MULTICHIP OK')"
echo "== bench harness smoke (CPU)"
MXTPU_BENCH_SMOKE=1 python bench.py
echo "== amalgamation build + tests"
python -m pytest tests/test_amalgamation.py -q
echo "ALL CHECKS PASSED"
