#!/usr/bin/env bash
# One-shot validation gate: everything the repo claims, in one command.
#   bash tools/run_checks.sh
set -e
cd "$(dirname "$0")/.."

echo "== telemetry dispatch lint"
# every dispatch site must report through executor.record_dispatch (which
# fans out to the telemetry registry); a raw single-slot hook CALL
# anywhere else silently clobbers other subscribers
if grep -rn "dispatch_hook(" --include='*.py' mxnet_tpu tools bench.py \
        | grep -v "^mxnet_tpu/executor.py:"; then
  echo "FAIL: raw dispatch_hook( call outside mxnet_tpu/executor.py —"
  echo "      report dispatches via executor.record_dispatch /"
  echo "      subscribe via telemetry.on_dispatch"
  exit 1
fi

echo "== instrumented-jit lint"
# every executor/module/serving jitted program must compile through the
# instrumented wrapper (_InstrumentedProgram: explicit lower().compile(),
# program card, recompile-cause diagnosis, OOM enrichment) — a raw
# jax.jit( in these layers would dodge every program-card guarantee
# (and, on the serving path, the one-compile-per-bucket accounting)
if grep -n "jax\.jit(" mxnet_tpu/executor.py mxnet_tpu/predictor.py \
        mxnet_tpu/serving.py mxnet_tpu/compile_cache.py \
        mxnet_tpu/faults.py mxnet_tpu/checkpoint.py \
        mxnet_tpu/module/*.py \
        | grep -v "the ONE instrumented jit site"; then
  echo "FAIL: raw jax.jit( call outside the executor's instrumented"
  echo "      wrapper — route programs through _InstrumentedProgram"
  echo "      so they get a program card (telemetry.programs())"
  exit 1
fi

echo "== native build"
make -s
echo "== C++ unit tests"
make -s testcpp
echo "== python suite (virtual 8-device CPU mesh)"
python -m pytest tests/ -q
echo "== multichip dryrun (8 virtual devices: dp/sp/tp + Module dp + pp/ep)"
python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('MULTICHIP OK')"
echo "== bench harness smoke (CPU)"
MXTPU_BENCH_SMOKE=1 python bench.py
echo "== amalgamation build + tests"
python -m pytest tests/test_amalgamation.py -q
echo "ALL CHECKS PASSED"
