#!/usr/bin/env bash
# One-shot validation gate: everything the repo claims, in one command.
#   bash tools/run_checks.sh
set -e
cd "$(dirname "$0")/.."

echo "== native build"
make -s
echo "== C++ unit tests"
make -s testcpp
echo "== python suite (virtual 8-device CPU mesh)"
python -m pytest tests/ -q
echo "== multichip dryrun (8 virtual devices: dp/sp/tp + Module dp + pp/ep)"
python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('MULTICHIP OK')"
echo "== bench harness smoke (CPU)"
MXTPU_BENCH_SMOKE=1 python bench.py
echo "== amalgamation build + tests"
python -m pytest tests/test_amalgamation.py -q
echo "ALL CHECKS PASSED"
