#!/usr/bin/env python
"""mxlint — AST static analysis for the runtime's own invariants.

Usage::

    python tools/mxlint.py [options] <paths...>

    python tools/mxlint.py mxnet_tpu tools bench.py        # the CI gate
    python tools/mxlint.py --json out.json mxnet_tpu       # JSON report
    python tools/mxlint.py --rules jit-site mxnet_tpu      # one rule
    python tools/mxlint.py --update-baseline mxnet_tpu tools bench.py
    python tools/mxlint.py --changed mxnet_tpu tools bench.py  # pre-commit

Options:
    --rules a,b,...      run only these rule ids (default: all)
    --list-rules         print the rule ids and exit 0
    --explain RULE       print the rule's documentation, its finding
                         format and its fixture pair under
                         tests/lint_fixtures/, then exit 0 (exit 2 on
                         an unknown rule id) — the fast way for a new
                         contributor to see what a rule polices and
                         what compliant code looks like
    --baseline PATH      grandfather file (default:
                         tools/mxlint_baseline.json; 'none' disables)
    --update-baseline    rewrite the baseline from the current findings
                         (stale entries pruned) and exit 0
    --json [PATH]        emit the JSON report to PATH (or stdout when no
                         PATH follows); the text report is skipped
    --changed            lint only files touched vs the git merge-base
                         PLUS their transitive reverse call-graph
                         dependents (a changed callee changes its
                         callers' effect summaries). Findings are
                         filtered to the subset — keeping sinks whose
                         witness chain crosses it — and stale-baseline
                         hygiene is skipped. With a valid dep cache
                         only the subset plus its import closure is
                         PARSED (the fast pre-commit loop); otherwise
                         the whole path set is parsed and the cache
                         refreshed.
    --changed-base REF   base ref for --changed (default: origin/main,
                         falling back to main, then HEAD — on the
                         default branch this means "what my working
                         tree touches", the pre-commit loop)
    --dep-cache PATH     dependency-skeleton cache written by full
                         runs and consumed by --changed (default:
                         .mxlint_depcache.json at the repo root;
                         'none' disables). Purely an accelerator: a
                         stale or absent cache falls back to the full
                         parse, never to wrong results.

Exit codes (stable; run_checks.sh and the tier-1 lane key on them):
    0  clean — no unsuppressed, non-baselined findings (stale-baseline
       entries and suppressed/baselined findings only warn)
    1  findings
    2  usage error (unknown flag/rule, missing path)

Suppression grammar (the justification is REQUIRED)::

    something_flagged()   # mxlint: disable=<rule> -- why this is safe

The analyzer itself lives in ``mxnet_tpu/analysis`` (stdlib-only: no
jax import, no native build — ``bash tools/run_checks.sh lint`` runs it
standalone).
"""
import json
import os
import sys
import types

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

# import the analysis package WITHOUT executing mxnet_tpu/__init__.py
# (which pulls in jax, ~5s and a hard dependency): a stub parent whose
# __path__ points at the package directory lets the normal import
# machinery load mxnet_tpu.analysis standalone — the lint stage of
# run_checks.sh must work on a box with no jax and no native build
if "mxnet_tpu" not in sys.modules:
    _pkg = types.ModuleType("mxnet_tpu")
    _pkg.__path__ = [os.path.join(ROOT, "mxnet_tpu")]
    sys.modules["mxnet_tpu"] = _pkg

from mxnet_tpu.analysis import run, ALL_RULE_IDS          # noqa: E402
from mxnet_tpu.analysis.core import Baseline              # noqa: E402

DEFAULT_BASELINE = os.path.join(ROOT, "tools", "mxlint_baseline.json")
DEFAULT_DEP_CACHE = os.path.join(ROOT, ".mxlint_depcache.json")


def usage(msg):
    sys.stderr.write("mxlint: %s\n(see tools/mxlint.py --help)\n" % msg)
    return 2


def _git(*args):
    import subprocess
    try:
        proc = subprocess.run(["git"] + list(args), cwd=ROOT,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True,
                              timeout=30)
    except (OSError, subprocess.TimeoutExpired) as e:
        return None, str(e)
    if proc.returncode != 0:
        return None, proc.stderr.strip()
    return proc.stdout, None


def changed_files(base_ref=None):
    """Repo-relative .py paths touched vs the merge-base (committed,
    staged, unstaged) plus untracked files, or (None, error)."""
    base = None
    for ref in ([base_ref] if base_ref else ["origin/main", "main"]):
        out, _err = _git("merge-base", "HEAD", ref)
        if out is not None:
            base = out.strip()
            break
    if base is None and base_ref:
        return None, "cannot resolve --changed-base %r" % base_ref
    if base is None:
        base = "HEAD"
    # -z: NUL-separated, unquoted — a path with a space (or a name git
    # would C-quote) must come back intact, not split into fragments
    # that silently match nothing
    out, err = _git("diff", "--name-only", "-z", base)
    if out is None:
        return None, "git diff failed: %s" % err
    files = {f for f in out.split("\0") if f}
    out, err = _git("ls-files", "--others", "--exclude-standard", "-z")
    if out is not None:
        files.update(f for f in out.split("\0") if f)
    # deleted files stay in the set: a deleted callee changes its
    # callers' effect summaries, and the dep cache's reverse map still
    # knows who called it — the closure lints those callers
    return sorted(f for f in files if f.endswith(".py")), None


def explain_rule(rid):
    """Print one rule's story: its module docstring (what it polices,
    how to comply/suppress), the finding format, and the fixture pair
    a contributor can read/run. Exit 0, or 2 on an unknown id."""
    from mxnet_tpu.analysis.rules import rule_table
    table = rule_table()
    if rid not in table:
        return usage("unknown rule %r (known: %s)"
                     % (rid, ", ".join(ALL_RULE_IDS)))
    rule = table[rid]
    import inspect
    doc = (inspect.getdoc(inspect.getmodule(type(rule)))
           or "").strip()
    print("rule: %s" % rid)
    print("=" * (6 + len(rid)))
    print(doc)
    print()
    print("finding format: <rule, path, line, col, message> — rendered")
    print("as 'path:line:col: %s: <message>'; baseline identity is" % rid)
    print("(rule, path, anchor) where anchor is the stripped finding")
    print("line, so unrelated edits never invalidate an entry.")
    print()
    print("fixture pair (run them to see the rule fire / stay silent):")
    for name in getattr(rule, "fixture_basenames", ()):
        path = os.path.join("tests", "lint_fixtures", name)
        kind = "violation" if "violation" in name else "compliant"
        print("  %-10s %s" % (kind + ":", path))
    print()
    print("try: python tools/mxlint.py --baseline none --rules %s "
          "tests/lint_fixtures/%s" % (
              rid, getattr(rule, "fixture_basenames", ("", ))[0]))
    return 0


def main(argv):
    paths = []
    rules = None
    baseline = DEFAULT_BASELINE
    update_baseline = False
    json_path = None
    want_json = False
    changed = False
    changed_base = None
    dep_cache = DEFAULT_DEP_CACHE

    args = list(argv)
    while args:
        a = args.pop(0)
        if a in ("-h", "--help"):
            print(__doc__)
            return 0
        if a == "--list-rules":
            print("\n".join(ALL_RULE_IDS))
            return 0
        if a == "--explain":
            if not args:
                return usage("--explain needs a rule id")
            return explain_rule(args.pop(0))
        if a == "--rules":
            if not args:
                return usage("--rules needs a comma-separated id list")
            rules = [r.strip() for r in args.pop(0).split(",") if r.strip()]
            continue
        if a == "--baseline":
            if not args:
                return usage("--baseline needs a path (or 'none')")
            baseline = args.pop(0)
            if baseline.lower() == "none":
                baseline = None
            continue
        if a == "--update-baseline":
            update_baseline = True
            continue
        if a == "--changed":
            changed = True
            continue
        if a == "--changed-base":
            if not args:
                return usage("--changed-base needs a git ref")
            changed_base = args.pop(0)
            continue
        if a == "--dep-cache":
            if not args:
                return usage("--dep-cache needs a path (or 'none')")
            dep_cache = args.pop(0)
            if dep_cache.lower() == "none":
                dep_cache = None
            continue
        if a == "--json":
            want_json = True
            if args and args[0] == "-":          # explicit stdout
                json_path = args.pop(0)
            elif args and not args[0].startswith("-"):
                if args[0].endswith(".json"):
                    json_path = args.pop(0)
                elif not os.path.exists(args[0]):
                    # neither an existing lint path nor a recognizable
                    # output path — guessing either way silently does
                    # the wrong thing, so refuse
                    return usage(
                        "--json operand %r is neither an existing lint "
                        "path nor a .json output path; use '-' for "
                        "stdout or an output path ending in .json"
                        % args[0])
            continue
        if a.startswith("-"):
            return usage("unknown option %r" % a)
        paths.append(a)
    if not paths:
        return usage("no paths given")

    # analysis runs with repo-relative display paths so baseline entries
    # and reports are machine-independent; relative CLI paths resolve
    # against the CWD as usual
    abs_paths = [os.path.abspath(p) for p in paths]
    missing = [p for p, ap in zip(paths, abs_paths)
               if not os.path.exists(ap)]
    if missing:
        return usage("no such path(s): %s" % ", ".join(missing))

    if update_baseline and baseline is None:
        return usage("--update-baseline with '--baseline none' has no "
                     "file to write; give --baseline a path")
    if changed and update_baseline:
        return usage("--changed lints a partial view; refusing to "
                     "rewrite the baseline from it")
    if changed_base and not changed:
        return usage("--changed-base only makes sense with --changed")

    only = None
    if changed:
        only, err = changed_files(changed_base)
        if only is None:
            return usage(err)
        if not only:
            print("mxlint (--changed): no python files touched — "
                  "nothing to lint")
            return 0

    try:
        if update_baseline:
            # partition against an EMPTY baseline: every current
            # unsuppressed finding lands in the fresh file, stale
            # entries implicitly pruned
            report = run(abs_paths, rules=rules, baseline=Baseline(),
                         root=ROOT, dep_cache=dep_cache)
            out_path = baseline
            doc = Baseline.render(report.findings)
            if rules:
                # a partial-rule run only refreshes ITS rules' entries —
                # wiping the others would fail the next full gate run
                prior = Baseline.load(out_path)
                doc["findings"] = sorted(
                    doc["findings"]
                    + [{"rule": r, "path": p, "anchor": a, "count": n}
                       for (r, p, a), n in prior.entries.items()
                       if r not in set(report.rules)],
                    key=lambda e: (e["rule"], e["path"], e["anchor"]))
            with open(out_path, "w") as f:
                json.dump(doc, f, indent=2, sort_keys=True)
                f.write("\n")
            print("mxlint: baseline %s rewritten with %d finding(s)"
                  % (os.path.relpath(out_path), len(report.findings)))
            return 0
        report = run(abs_paths, rules=rules, baseline=baseline, root=ROOT,
                     only=only, expand_dependents=changed,
                     dep_cache=dep_cache)
    except ValueError as e:          # unknown rule id
        return usage(str(e))
    except FileNotFoundError as e:
        return usage("no such path: %s" % e)

    if changed and not want_json:
        # the audit line for a "0 findings" on a partial view: exactly
        # what closure was linted (touched + reverse dependents), what
        # was parsed to support it, and how many findings anchored
        # OUTSIDE the subset survived only via their witness chains
        c = report.closure or {}
        print("mxlint (--changed): %d touched + %d reverse "
              "dependent(s) = %d file(s) linted, %d parsed (dep cache "
              "%s); %d chain finding(s) kept from outside the subset"
              % (len(c.get("touched", only)), c.get("dependents", 0),
                 len(report.subset or []), report.files,
                 report.dep_cache or "off", c.get("via_kept", 0)))
    if want_json:
        doc = json.dumps(report.to_dict(), indent=2, sort_keys=True)
        if json_path and json_path != "-":
            with open(json_path, "w") as f:
                f.write(doc + "\n")
        else:
            print(doc)
    else:
        print(report.render_text())
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
