"""Diagnose script — OS/hardware/python/framework/accelerator report
(parity: reference tools/diagnose.py; the network-mirror checks are
dropped — this build is zero-egress by design).

Usage: python tools/diagnose.py [--accelerator 0]
The accelerator probe touches the backend and can HANG when the TPU
tunnel is down, so it runs in a bounded subprocess.
"""
import argparse
import os
import platform
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def section(title):
    print("-" * 24)
    print(title)


def diag_python():
    section("Python")
    print("version      :", sys.version.replace("\n", " "))
    print("executable   :", sys.executable)


def diag_os():
    section("OS")
    print("platform     :", platform.platform())
    print("system       :", platform.system(), platform.release())
    print("machine      :", platform.machine())


def diag_hardware():
    section("Hardware")
    print("cpu count    :", os.cpu_count())
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith(("MemTotal", "MemAvailable")):
                    print(line.strip())
    except OSError:
        pass
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("model name"):
                    print("cpu model    :",
                          line.split(":", 1)[1].strip())
                    break
    except OSError:
        pass


def diag_framework():
    section("Framework")
    os.environ.setdefault("MXNET_TPU_FORCE_CPU", "1")
    import mxnet_tpu as mx
    print("mxnet_tpu    :", mx.__version__,
          "(", os.path.dirname(mx.__file__), ")")
    import jax
    print("jax          :", jax.__version__)
    import numpy
    print("numpy        :", numpy.__version__)
    lib = os.path.join(os.path.dirname(mx.__file__), "_lib",
                       "libmxtpu_c_api.so")
    print("native C ABI :", "built" if os.path.exists(lib) else
          "NOT BUILT (run `make`)")


def diag_accelerator(timeout):
    section("Accelerator")
    code = ("import jax; d = jax.devices()[0]; "
            "print(d.platform, d.device_kind)")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout)
        out = proc.stdout.strip()
        print("devices      :", out or proc.stderr.strip()[-200:])
    except subprocess.TimeoutExpired:
        print("devices      : backend init HUNG after %ds "
              "(tunnel down?)" % timeout)


def main():
    ap = argparse.ArgumentParser()
    for choice in ("python", "os", "hardware", "framework",
                   "accelerator"):
        ap.add_argument("--" + choice, default=1, type=int)
    ap.add_argument("--timeout", default=60, type=int)
    args = ap.parse_args()
    if args.python:
        diag_python()
    if args.os:
        diag_os()
    if args.hardware:
        diag_hardware()
    if args.framework:
        diag_framework()
    if args.accelerator:
        diag_accelerator(args.timeout)


if __name__ == "__main__":
    main()
