#!/usr/bin/env python3
"""Break down where Module.fit's wall-clock goes vs the raw fused step
(PERF.md: the round-5 bench measured 157.9 img/s user-path vs 2254 raw).

Times each fit-loop phase IN ISOLATION on the attached accelerator:
  - forward_backward (the fused executor program)
  - update           (FusedUpdater one-dispatch step)
  - update_metric    (device-accumulated Accuracy)
  - epoch-end get_params/set_params round trip

Run on a TPU host:  python tools/module_fit_probe.py
Smoke (CPU):        MXTPU_PROBE_SMOKE=1 python tools/module_fit_probe.py
Fit-smoke lane:     python tools/module_fit_probe.py --fit-smoke \
                        [--json-out PATH]
  (tier-1 CI: tiny-MLP Module.fit on the CPU backend, 20 batches, fused
  vs phase-split A/B with per-batch dispatch counts — the user-path
  trajectory is captured every round even when the TPU tunnel is down)
DP-smoke lane:      python tools/module_fit_probe.py --dp-smoke \
                        [--json-out PATH]
  (tier-1 CI: tiny-MLP Module.fit on the virtual 8-device CPU mesh —
  the fused-SPMD data-parallel step vs the kvstore phase-split path;
  asserts dp-fused >= phase-split img/s and EXACTLY 1 jitted-program
  dispatch per batch via the mx.telemetry dispatch registry)
MP-smoke lane:      python tools/module_fit_probe.py --mp-smoke \
                        [--json-out PATH]
  (tier-1 CI: the same MLP on the 8-device CPU mesh laid out as a 2x4
  dp x mp mesh with every parameter rule-sharded over mp
  (parallel.partition.PartitionRules): gates 1 fused dispatch/batch,
  zero fused fallbacks, per-device committed param bytes ~ 1/mp of
  the replicated layout per the buffer ledger, and fused >=
  phase-split img/s)
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SMOKE = os.environ.get("MXTPU_PROBE_SMOKE", "") == "1"
FIT_SMOKE = "--fit-smoke" in sys.argv
DP_SMOKE = "--dp-smoke" in sys.argv
MP_SMOKE = "--mp-smoke" in sys.argv
DIST_SMOKE = "--dist-smoke" in sys.argv
DIST_CHILD = "--dist-child" in sys.argv
# a dist child that dies on an injected fault exits THROUGH
# mx.dist.abort with this code (destructor-free death: a crashing
# worker must not drag survivors into the coordination shutdown
# barrier); the parent gates on it
DIST_FAULT_RC = 21
N_DEV = 8
BATCH = 8 if SMOKE else 128
IMG = 32 if SMOKE else 224
ITERS = 2 if SMOKE else 10

if DP_SMOKE or MP_SMOKE:
    # the virtual mesh flag must land before the CPU backend initialises
    _flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=%d" % N_DEV
        ).strip()

import numpy as np
import jax
import jax.numpy as jnp

if SMOKE or FIT_SMOKE or DP_SMOKE or MP_SMOKE or DIST_SMOKE or DIST_CHILD:
    jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu.io import DataDesc

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..",
    "examples", "image-classification"))
from symbols.resnet import get_symbol


def timed(label, fn, fence, iters=ITERS):
    """``fence`` must return (or contain) buffers DATA-DEPENDENT on the
    work ``fn`` queued — a fresh unrelated transfer does NOT drain the
    compute queue, so fencing on one under-reports any async phase."""
    fn()  # warm
    np.asarray(jax.tree_util.tree_leaves(
        jax.block_until_ready(fence()))[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    np.asarray(jax.tree_util.tree_leaves(
        jax.block_until_ready(fence()))[0])
    dt = (time.perf_counter() - t0) / iters
    print("%-28s %8.2f ms" % (label, dt * 1e3), flush=True)
    return dt


def main():
    dev = jax.devices()[0]
    print("device:", dev.device_kind, flush=True)
    sym = get_symbol(num_classes=1000, num_layers=50,
                     image_shape="3,%d,%d" % (IMG, IMG))
    bf16 = np.dtype(jnp.bfloat16)
    mod = mx.mod.Module(sym, context=mx.tpu() if dev.platform != "cpu"
                        else mx.cpu())
    mod.bind(data_shapes=[DataDesc("data", (BATCH, 3, IMG, IMG),
                                   dtype=bf16)],
             label_shapes=[DataDesc("softmax_label", (BATCH,))],
             for_training=True)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9,
                                         "multi_precision": True})
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.uniform(-1, 1, (BATCH, 3, IMG, IMG))
                    .astype(np.float32)).astype(bf16)
    y = mx.nd.array(rs.randint(0, 1000, BATCH).astype(np.float32))
    from mxnet_tpu.io import DataBatch
    batch = DataBatch([x], [y], pad=0)
    metric = mx.metric.Accuracy()

    def grad_fence():
        return [g._data for g in mod._exec.grad_arrays if g is not None]

    def param_fence():
        return [mod._exec.arg_dict[n]._data for n in mod._param_names[:1]]

    def metric_fence():
        return metric._dev_sum

    results = {}
    results["forward_backward_ms"] = timed(
        "forward_backward", lambda: mod.forward_backward(batch),
        grad_fence) * 1e3
    results["update_ms"] = timed("update", lambda: mod.update(),
                                 param_fence) * 1e3
    results["update_metric_ms"] = timed(
        "update_metric",
        lambda: mod.update_metric(metric, batch.label), metric_fence) * 1e3

    def whole_step():
        mod.forward_backward(batch)
        mod.update()
        mod.update_metric(metric, batch.label)

    step_s = timed("whole step (fb+upd+metric)", whole_step,
                   lambda: (param_fence(), metric_fence()))
    results["step_ms"] = step_s * 1e3
    results["step_img_s"] = BATCH / step_s

    def epoch_end():
        arg_p, aux_p = mod.get_params()
        mod.set_params(arg_p, aux_p)

    results["epoch_end_get_set_ms"] = timed(
        "epoch-end get/set_params", epoch_end, param_fence,
        iters=max(2, ITERS // 3)) * 1e3

    print(json.dumps({k: round(v, 2) for k, v in results.items()}),
          flush=True)


def _smoke_lane(lane, contexts, kvstore, rounds, nbatch, batch,
                speed_key, extra=None, json_out=None, module_kwargs=None):
    """The ONE tier-1 lane harness both smoke lanes share: tiny-MLP
    ``Module.fit``, fused whole-step program vs phase-split oracle, with
    jitted-program dispatch counts per batch AND per-phase host-span
    timings read from the TELEMETRY registry (``mx.telemetry`` — the
    probe used to install its own single-slot ``executor.dispatch_hook``
    and duplicate the accounting; the multi-subscriber registry owns it
    now), and interleaved best-of timing (one epoch is a ~10ms window
    and share-throttled CI boxes drift in sustained speed — timing the
    two paths back to back inside each round keeps the RATIO honest
    under drift, and the min converges on the dispatch floor under spike
    noise). One JSON object on stdout (and to ``json_out``) — the
    artifact the CI lane banks each round. Returns (out, dispatch)."""
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.io import DataIter, DataDesc, DataBatch

    d, c = 16, 4
    rs = np.random.RandomState(0)

    class _PreslicedIter(DataIter):
        """Device-resident pre-sliced batches (bench/benchmark_score
        methodology): the lane measures framework DISPATCH overhead —
        the thing the fused step removes — not numpy slicing; the input
        pipeline has its own probes (tools/decode_bench.py)."""

        def __init__(self):
            super().__init__(batch)
            self._batches = [DataBatch(
                [mx.nd.array(rs.uniform(-1, 1, (batch, d))
                             .astype(np.float32))],
                [mx.nd.array(rs.randint(0, c, batch)
                             .astype(np.float32))], pad=0)
                for _ in range(nbatch)]
            self.i = 0

        @property
        def provide_data(self):
            return [DataDesc("data", (batch, d))]

        @property
        def provide_label(self):
            return [DataDesc("softmax_label", (batch,))]

        def reset(self):
            self.i = 0

        def next(self):
            if self.i >= len(self._batches):
                raise StopIteration
            self.i += 1
            return self._batches[self.i - 1]

    def mlp():
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.FullyConnected(net, num_hidden=c, name="fc2")
        return mx.sym.SoftmaxOutput(net, name="softmax")

    opt_params = {"learning_rate": 0.05, "momentum": 0.9}

    def setup(fused):
        os.environ["MXNET_MODULE_FUSED_STEP"] = "1" if fused else "0"
        mod = mx.mod.Module(mlp(), context=contexts,
                            **(module_kwargs or {}))
        metric = mx.metric.Accuracy()
        train = _PreslicedIter()
        # warm epoch: bind + init + compile land outside the timed window
        mod.fit(train, eval_metric=metric, num_epoch=1, kvstore=kvstore,
                initializer=mx.initializer.Xavier(),
                optimizer="sgd", optimizer_params=opt_params)
        reason = mod._fused_fallback_reason
        if fused and reason is not None:
            raise SystemExit("%s: fused path fell back: %s (%s)"
                             % (lane, reason, getattr(reason, "code", "?")))
        if not fused and getattr(reason, "code", None) != "env_pin":
            raise SystemExit("%s: phase-split leg expected the env_pin "
                             "fallback code, got %r" % (lane, reason))
        return mod, metric, train

    def epoch(state, fused):
        mod, metric, train = state
        os.environ["MXNET_MODULE_FUSED_STEP"] = "1" if fused else "0"
        # clean registry window: the counters/spans read after this
        # epoch describe THIS epoch alone
        telemetry.reset()
        t0 = time.perf_counter()
        mod.fit(train, eval_metric=metric, num_epoch=1, kvstore=kvstore,
                optimizer="sgd", optimizer_params=opt_params)
        # the loop is async — close the window on a data-dependent fetch
        metric.get()
        float(np.asarray(
            mod._exec.arg_dict[mod._param_names[0]]._data).sum())
        return time.perf_counter() - t0

    states = {True: setup(True), False: setup(False)}
    dts = {True: float("inf"), False: float("inf")}
    dispatch = {True: {}, False: {}}
    phases = {True: {}, False: {}}
    cards = {True: {}, False: {}}
    # the lane's accounting READS the registry, so recording must be on
    # for its window regardless of the ambient MXNET_TELEMETRY pin
    # (restored after — the lane must not flip the session's state)
    was_enabled = telemetry.enabled()
    telemetry.enable()
    try:
        for _ in range(rounds):
            for f in (True, False):
                dt = epoch(states[f], f)
                if dt <= dts[f]:
                    # bank the registry window of the BEST round, so
                    # the per-phase timings in the artifact describe
                    # the same epoch as the best-of img/s next to them
                    dts[f] = dt
                    dispatch[f] = telemetry.dispatch_counts()
                    phases[f] = {
                        name: {"count": s["count"],
                               "total_ms": s["total_ms"],
                               "p50_ms": s["p50_ms"],
                               "p95_ms": s["p95_ms"]}
                        for name, s in telemetry.span_stats().items()
                        if name in telemetry.FIT_PHASE_SPANS}
                    # program cards dispatched in the banked window:
                    # what each leg's step COSTS (FLOPs / peak HBM)
                    # rides next to what it measured
                    cards[f] = {
                        k: {kk: c.get(kk) for kk in
                            ("kind", "flops", "bytes_accessed",
                             "peak_bytes", "compile_ms", "dispatches")}
                        for k, c in telemetry.programs().items()
                        if c.get("dispatches")}
    finally:
        if not was_enabled:
            telemetry.disable()

    def report(f):
        return {
            "img_s": round(batch * nbatch / dts[f], 1),
            "dispatches_per_batch": round(
                sum(dispatch[f].values()) / nbatch, 2),
            "dispatch_counts": dispatch[f],
            "phase_spans": phases[f],
            "program_cards": cards[f],
        }

    fused, split = report(True), report(False)
    out = {"lane": lane, "platform": jax.devices()[0].platform}
    out.update(extra or {})
    out.update({
        "batch": batch, "nbatch": nbatch,
        "fused": fused, "phase_split": split,
        speed_key: round(fused["img_s"] / split["img_s"], 2),
    })
    line = json.dumps(out)
    print(line, flush=True)
    if json_out:
        with open(json_out, "w") as f:
            f.write(line + "\n")
    return out, dispatch


# the fit-smoke gate floor/ceiling: the recalibrated expectation is
# clamped into [FIT_GATE_FLOOR, FIT_GATE_CAP] — the lane always demands
# SOME fused win, and never demands more than the old absolute 3x
FIT_GATE_FLOOR = 1.2
FIT_GATE_CAP = 3.0
FIT_GATE_MARGIN = 0.7    # pass at 70% of the span-predicted speedup


def _recalibrated_fit_gate(out):
    """The fit-smoke speedup gate, recalibrated IN-RUN from the banked
    phase spans instead of an absolute ratio. The absolute >=3x gate
    false-fails on share-throttled boxes (2.4x at seed there): when the
    box inflates the non-dispatch overhead (python loop, callbacks,
    iterator) that BOTH legs pay, the achievable ratio shrinks even
    though the fused path still removes the whole dispatch chain. So
    predict the achievable wall from the split leg's own accounting —
    fused_wall ~= split_wall - split_dispatch_spans + fused_dispatch
    spans (the fused step replaces the split chain, everything else
    stays) — and gate at FIT_GATE_MARGIN of that prediction, clamped to
    [FIT_GATE_FLOOR, FIT_GATE_CAP]. On a healthy box the prediction is
    ~3-4x so the gate stays ~3x-strength; on a throttled box it relaxes
    to what the box can actually show. Dispatch-count gates stay
    absolute — they are noise-free."""
    # leaf phases only: fit_batch NESTS feed/step/... and would double
    # count; io_next is iterator time both legs pay identically
    leaf = ("feed", "step", "opt_update", "metric_update",
            "metric_fetch", "kv_push", "kv_pull")

    def disp_ms(leg):
        return sum(s.get("total_ms", 0.0)
                   for name, s in out[leg]["phase_spans"].items()
                   if name in leaf)

    wall_ms = {leg: out["batch"] * out["nbatch"] / out[leg]["img_s"] * 1e3
               for leg in ("fused", "phase_split")}
    predicted_fused = max(wall_ms["phase_split"] - disp_ms("phase_split")
                          + disp_ms("fused"), 1e-6)
    expected = max(wall_ms["phase_split"] / predicted_fused, 1.0)
    gate = min(FIT_GATE_CAP, max(FIT_GATE_FLOOR,
                                 FIT_GATE_MARGIN * expected))
    return round(expected, 2), round(gate, 2)


def fit_smoke(json_out=None, nbatch=20, batch=32):
    """Tier-1 smoke lane: tiny-MLP ``Module.fit`` on the CPU backend,
    fused whole-step program vs phase-split oracle (best-of-9
    interleaved), gated against the in-run recalibrated speedup
    expectation (see ``_recalibrated_fit_gate``)."""
    import mxnet_tpu as mx
    out, dispatch = _smoke_lane(
        "module_fit_smoke", mx.cpu(), "local", rounds=9,
        nbatch=nbatch, batch=batch, speed_key="fit_speedup",
        json_out=None)
    expected, gate = _recalibrated_fit_gate(out)
    out["fit_speedup_expected"] = expected
    out["fit_gate"] = gate
    # the fit acceptance gates: the deterministic dispatch counts plus
    # the recalibrated throughput ratio
    try:
        assert out["fused"]["dispatches_per_batch"] <= 2.0, out["fused"]
        assert out["phase_split"]["dispatches_per_batch"] == 3.0, \
            out["phase_split"]
        assert out["fit_speedup"] >= gate, (out["fit_speedup"], gate)
        out["gates_passed"] = True
    except AssertionError:
        out["gates_passed"] = False
        raise
    finally:
        line = json.dumps(out)
        print(line, flush=True)
        if json_out:
            with open(json_out, "w") as f:
                f.write(line + "\n")
    return out


def dp_smoke(json_out=None, nbatch=12, batch=32):
    """Tier-1 dp lane: tiny-MLP ``Module.fit`` on the virtual 8-device
    CPU mesh, the whole-step fused SPMD program (multi-context +
    subsumed ``device`` kvstore) vs the kvstore phase-split path.
    Asserts the two load-bearing dp properties — EXACTLY 1 dispatch per
    batch on the fused path (telemetry dispatch counters) and dp-fused
    throughput >= the phase-split path — and banks the JSON artifact
    stamped with the gate outcome
    (a gate-failing round must not read as a healthy record in the
    artifact dir; 5 rounds keeps the tier-1 lane's wall-clock small)."""
    import mxnet_tpu as mx

    n_dev = min(N_DEV, jax.device_count())
    assert n_dev >= 2, "dp-smoke needs the virtual multi-device CPU mesh"
    contexts = [mx.cpu(i) for i in range(n_dev)]
    out, dispatch = _smoke_lane(
        "module_fit_dp_smoke", contexts, "device", rounds=5,
        nbatch=nbatch, batch=batch, speed_key="dp_speedup",
        extra={"n_devices": n_dev}, json_out=None)
    # the dp acceptance gates (ISSUE 2): one program per batch, and the
    # fused SPMD step at least as fast as the kvstore phase-split path
    try:
        assert dispatch[True] == {"train_step": nbatch}, dispatch[True]
        assert out["fused"]["dispatches_per_batch"] == 1.0, out
        assert out["fused"]["img_s"] >= out["phase_split"]["img_s"], out
        out["gates_passed"] = True
    except AssertionError:
        out["gates_passed"] = False
        raise
    finally:
        if json_out:
            with open(json_out, "w") as f:
                f.write(json.dumps(out) + "\n")


def _mp_rules():
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel import PartitionRules
    # every tensor of the lane MLP shards over mp (weights row-wise,
    # biases element-wise) — the per-device parameter footprint drops
    # to ~1/mp of the replicated layout, which the ledger gate below
    # pins
    return PartitionRules([
        (r"fc\d+_weight$", P("mp", None)),
        (r"fc\d+_bias$", P("mp")),
    ])


MP_AXES = {"dp": 2, "mp": 4}


def _mp_ledger_param_bytes(module_kwargs, contexts, batch):
    """Per-device committed parameter bytes of one freshly bound lane
    module, per the buffer LEDGER (the ``param`` kind under the mesh
    context key tracks summed per-shard bytes across devices)."""
    import gc
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.io import DataDesc
    d, c = 16, 4

    def mlp():
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.FullyConnected(net, num_hidden=c, name="fc2")
        return mx.sym.SoftmaxOutput(net, name="softmax")

    # collect any earlier module's parameter wrappers first: their live
    # ledger charges under the same mesh key would pollute this reading
    gc.collect()
    telemetry.reset()
    mod = mx.mod.Module(mlp(), context=contexts, **(module_kwargs or {}))
    mod.bind(data_shapes=[DataDesc("data", (batch, d))],
             label_shapes=[DataDesc("softmax_label", (batch,))])
    mod.init_params(mx.initializer.Xavier())
    led = telemetry.ledger().get("mesh(%ddev)" % len(contexts), {})
    total = led.get("by_kind", {}).get("param", 0)
    return total / max(len(contexts), 1)


def mp_smoke(json_out=None, nbatch=12, batch=32):
    """Tier-1 mp lane (ISSUE 15): tiny-MLP ``Module.fit`` on the
    8-device CPU mesh laid out as a 2x4 dp x mp mesh with every
    parameter rule-sharded over ``mp``, vs the kvstore phase-split
    path on the same layout. Gates the four load-bearing dp x mp
    properties:

    - EXACTLY 1 fused dispatch per batch (the 2-D layout still ships
      one donated SPMD program);
    - ZERO fused fallbacks (the rules path never silently phase-splits
      — the lane harness raises on any fused-leg fallback and the
      dispatch-count gate re-checks the banked window);
    - params-alive bytes per device ~ 1/mp of the replicated layout,
      per the buffer ledger's committed ``param`` accounting;
    - fused throughput >= the phase-split path."""
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry

    n_dev = min(N_DEV, jax.device_count())
    assert n_dev >= 8, "mp-smoke needs the 8-device virtual CPU mesh"
    contexts = [mx.cpu(i) for i in range(n_dev)]
    mp = MP_AXES["mp"]
    module_kwargs = {"partition_rules": _mp_rules(),
                     "mesh_axes": dict(MP_AXES)}
    out, dispatch = _smoke_lane(
        "module_fit_mp_smoke", contexts, "device", rounds=5,
        nbatch=nbatch, batch=batch, speed_key="mp_speedup",
        extra={"n_devices": n_dev, "mesh_axes": dict(MP_AXES)},
        json_out=None, module_kwargs=module_kwargs)
    # ledger leg: per-device committed param bytes, rules vs replicated
    was_enabled = telemetry.enabled()
    telemetry.enable()
    try:
        per_dev_mp = _mp_ledger_param_bytes(module_kwargs, contexts,
                                            batch)
        per_dev_repl = _mp_ledger_param_bytes(None, contexts, batch)
    finally:
        if not was_enabled:
            telemetry.disable()
    ratio = per_dev_mp / per_dev_repl if per_dev_repl else None
    out["ledger"] = {
        "param_bytes_per_device_mp": per_dev_mp,
        "param_bytes_per_device_replicated": per_dev_repl,
        "ratio": None if ratio is None else round(ratio, 4),
        "mp": mp,
    }
    try:
        # 1 dispatch/batch, and the banked fused window saw ONLY the
        # fused program (zero fallbacks: a phase-split batch would add
        # fwd_bwd/opt_update dispatches to the window)
        assert dispatch[True] == {"train_step": nbatch}, dispatch[True]
        assert out["fused"]["dispatches_per_batch"] == 1.0, out
        # per-device param bytes ~ 1/mp of replicated (biases and the
        # tiny fc2 rows leave a little slack above the exact 1/mp)
        assert ratio is not None and ratio <= 1.5 / mp, out["ledger"]
        assert out["fused"]["img_s"] >= out["phase_split"]["img_s"], out
        out["gates_passed"] = True
    except AssertionError:
        out["gates_passed"] = False
        raise
    finally:
        line = json.dumps(out)
        print(line, flush=True)
        if json_out:
            with open(json_out, "w") as f:
                f.write(line + "\n")
    return out


# ---------------------------------------------------------------------------
# dist-smoke: 2-process fused dist_sync + elastic chaos leg (ISSUE 12)
# ---------------------------------------------------------------------------

DIST_D, DIST_C = 16, 4


def _dist_mlp():
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=DIST_C, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _dist_arg(name, default=None, cast=str):
    if name not in sys.argv:
        return default
    i = sys.argv.index(name) + 1
    if i >= len(sys.argv):
        raise SystemExit("%s: missing value" % name)
    return cast(sys.argv[i])


def dist_child():
    """ONE worker of the dist lane: deterministic global batches, this
    rank's slice fed locally, fused dist_sync Module.fit. Writes a JSON
    result (params as float64 lists so the parent can gate bit-equality
    across ranks and rtol vs the single-process oracle). Run with the
    MXNET_TPU_COORDINATOR trio in the env for the 2-process legs, or
    without it as the single-process oracle."""
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry, dist as mxdist
    from mxnet_tpu.io import DataIter, DataDesc, DataBatch

    json_out = _dist_arg("--json-out")
    nproc = _dist_arg("--dist-nproc", 1, int)
    epochs = _dist_arg("--dist-epochs", 2, int)
    nbatch = _dist_arg("--dist-nbatch", 6, int)
    global_batch = _dist_arg("--dist-global-batch", 32, int)
    seed = _dist_arg("--dist-seed", 1234, int)
    ckpt_dir = _dist_arg("--dist-ckpt")
    rank = mxdist.rank()
    local = global_batch // nproc
    sl = slice(rank * local, (rank + 1) * local)

    rs = np.random.RandomState(seed)
    batches = [(rs.uniform(-1, 1, (global_batch, DIST_D))
                .astype(np.float32),
                rs.randint(0, DIST_C, global_batch).astype(np.float32))
               for _ in range(nbatch)]

    class _It(DataIter):
        def __init__(self):
            super().__init__(local)
            self.i = 0

        @property
        def provide_data(self):
            return [DataDesc("data", (local, DIST_D))]

        @property
        def provide_label(self):
            return [DataDesc("softmax_label", (local,))]

        def reset(self):
            self.i = 0

        def next(self):
            if self.i >= nbatch:
                raise StopIteration
            x, y = batches[self.i]
            self.i += 1
            return DataBatch([mx.nd.array(x[sl])],
                             [mx.nd.array(y[sl])], pad=0)

    telemetry.enable()
    # Xavier draws from numpy's GLOBAL generator — identical init across
    # ranks and across the oracle leg needs an explicit seed (the dist
    # commit also broadcasts rank 0's values, but the oracle leg has no
    # one to broadcast from)
    np.random.seed(seed)
    mgr = None
    if ckpt_dir:
        mgr = mx.CheckpointManager(
            os.path.join(ckpt_dir, "r%d" % rank, "model"), keep_last=3)
    mod = mx.mod.Module(_dist_mlp(), context=mx.cpu())
    metric = mx.metric.Accuracy()
    mod.fit(_It(), eval_metric=metric, num_epoch=epochs,
            kvstore="dist_sync", initializer=mx.initializer.Xavier(),
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
            checkpoint=mgr)
    reason = mod._fused_fallback_reason
    snap = telemetry.counters()
    params, _ = mod.get_params()
    res = {
        "rank": rank,
        "nproc": nproc,
        "fallback_code": getattr(reason, "code", None),
        "kvstore_dist_fallbacks": snap.get("fused_fallback.kvstore_dist",
                                           0),
        "dist_counters": {k: int(v) for k, v in snap.items()
                          if k.startswith(("kvstore.dist", "elastic"))},
        "acc": metric.get()[1],
        "finite": bool(all(
            np.isfinite(np.asarray(v.asnumpy())).all()
            for v in params.values())),
        "params": {k: np.asarray(v.asnumpy(), np.float64).tolist()
                   for k, v in sorted(params.items())},
        "completed": True,
    }
    if json_out:
        with open(json_out, "w") as f:
            json.dump(res, f)
    mxdist.finalize()
    print("dist child rank=%d done" % rank, flush=True)


def _dist_child_main():
    import traceback
    try:
        dist_child()
    except BaseException:
        traceback.print_exc()
        sys.stderr.flush()
        from mxnet_tpu import dist as mxdist
        if mxdist.initialized():
            # die WITHOUT destructors: a crashing worker that tears
            # down its coordination client drags every survivor into
            # the fatal shutdown barrier — exactly what the elastic
            # tier exists to avoid
            mxdist.abort(DIST_FAULT_RC)
        raise


def dist_smoke(json_out=None):
    """Tier-1 dist lane: real 2-process ``dist_sync`` on one box
    (``jax.distributed`` over localhost, gloo CPU collectives).

    Leg A (fused): both workers run the fused donated-buffer train step
    over the process-spanning dp mesh — gates zero ``kvstore_dist``
    fallback events and BIT-EQUAL params across ranks.
    Leg B (oracle): a single-process run at the same global batch —
    gates params equal at rtol=1e-5 (the cross-host psum reassociates
    the batch reduction; bit-equality is reported, not required).
    Leg C (chaos): rank 1 is killed deterministically mid-epoch by an
    injected ``kv_collective`` fault — gates that rank 0 detects the
    death via the liveness gate, re-meshes, resumes from the last
    atomic checkpoint, FINISHES the run (exit 0, finite params,
    elastic counters), and that the postmortem names rank 1 and parses
    via tools/flight_view.py. Every leg runs under a hard timeout: a
    hung process fails the lane."""
    import shutil
    import socket
    import subprocess
    import tempfile

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    work = tempfile.mkdtemp(prefix="mxtpu-dist-smoke-")
    out = {"lane": "module_fit_dist_smoke", "platform": "cpu"}
    epochs, nbatch, gbatch = 2, 6, 32

    def _free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def _spawn(tag, rank, nproc, port, args, env_extra):
        env = dict(os.environ, JAX_PLATFORMS="cpu", MXNET_TELEMETRY="1")
        env.pop("XLA_FLAGS", None)
        env.pop("MXNET_FAULTS", None)
        hb = os.path.join(work, "hb-%s" % tag)
        os.makedirs(hb, exist_ok=True)
        if nproc > 1:
            env.update({
                "MXNET_TPU_COORDINATOR": "127.0.0.1:%d" % port,
                "MXNET_TPU_NUM_PROCESSES": str(nproc),
                "MXNET_TPU_PROCESS_ID": str(rank),
                "MXTPU_HEARTBEAT_DIR": hb,
                # 15 beats of staleness margin: a share-throttled box
                # can gap a beat thread well past one interval
                "MXTPU_HEARTBEAT_INTERVAL": "0.2",
                "MXTPU_HEARTBEAT_TIMEOUT": "3.0",
                "MXTPU_GATE_TIMEOUT": "60",
            })
        env.update(env_extra)
        jout = os.path.join(work, "%s-r%d.json" % (tag, rank))
        cmd = [sys.executable,
               os.path.join(root, "tools", "module_fit_probe.py"),
               "--dist-child", "--json-out", jout,
               "--dist-nproc", str(nproc), "--dist-epochs", str(epochs),
               "--dist-nbatch", str(nbatch),
               "--dist-global-batch", str(gbatch)] + args
        log = open(os.path.join(work, "%s-r%d.log" % (tag, rank)), "wb")
        p = subprocess.Popen(cmd, stdout=log, stderr=log, env=env,
                             cwd=root)
        p._mxtpu_json = jout
        p._mxtpu_log = log
        return p

    def _leg(tag, procs, timeout_s):
        """Wait for every proc under ONE deadline; kill stragglers —
        a hung worker is a lane FAILURE, never a hung lane."""
        import time as _time
        deadline = _time.monotonic() + timeout_s
        rcs, results = [], []
        try:
            for p in procs:
                left = max(1.0, deadline - _time.monotonic())
                try:
                    p.wait(timeout=left)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
                    raise SystemExit(
                        "dist-smoke[%s]: worker hung past %ds (killed); "
                        "logs under %s" % (tag, timeout_s, work))
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                p._mxtpu_log.close()
        for p in procs:
            rcs.append(p.returncode)
            try:
                with open(p._mxtpu_json) as f:
                    results.append(json.load(f))
            except (OSError, ValueError):
                results.append(None)
        return rcs, results

    try:
        # -- leg A: 2-process fused dist_sync ---------------------------
        port = _free_port()
        procs = [_spawn("fused", r, 2, port, [], {}) for r in (0, 1)]
        rcs, res = _leg("fused", procs, 240)
        a0, a1 = res
        out["fused"] = {
            "rcs": rcs,
            "fallback_codes": [r and r["fallback_code"] for r in res],
            "kvstore_dist_fallbacks": [
                r["kvstore_dist_fallbacks"] if r else None for r in res],
            "dist_counters": a0 and a0["dist_counters"],
            "acc": [r and r["acc"] for r in res],
        }

        # -- leg B: single-process oracle, same global batch ------------
        procs = [_spawn("single", 0, 1, 0, [], {})]
        rcs_s, res_s = _leg("single", procs, 180)
        single = res_s[0]
        out["single"] = {"rcs": rcs_s, "acc": single and single["acc"]}

        # -- leg C: chaos — kill rank 1 mid-epoch, rank 0 recovers ------
        # gate crossings before the steps: one kv-channel crossing per
        # broadcasting kv.init call (the probe net has 4 params —
        # fc1/fc2 weight+bias — initialised one call each) + one
        # step-channel crossing at the first dist commit (both added
        # by the mxsync collective-discipline fixes), then one step
        # crossing per fused step — nbatch gens per epoch.
        # n = 5 + nbatch + 3 dies in epoch 1 at batch index 2, AFTER
        # the epoch-0-end checkpoint exists
        chaos_epochs = 3
        fault_n = 5 + nbatch + 3
        # ONE flight dir shared by both ranks (the fleet posture:
        # rank-stamped filenames keep the artifacts apart) — rank 0's
        # dead_worker dump, rank 1's worker_abort dump and the series
        # JSONLs all land here for the merged cluster view
        flight = os.path.join(work, "flight")
        os.makedirs(flight, exist_ok=True)
        ckpt = os.path.join(work, "ckpt")
        port = _free_port()
        epochs = chaos_epochs
        procs = [
            _spawn("chaos", 0, 2, port, ["--dist-ckpt", ckpt],
                   {"MXNET_FLIGHT_DIR": flight,
                    "MXNET_METRICS_INTERVAL_MS": "200"}),
            # rank 1 is first a STRAGGLER (every dispatch delayed),
            # then DIES at the deterministic crossing. A dispatch-side
            # delay is INVISIBLE to gate arrival order — rank 0 absorbs
            # it blocked in the previous step's completion await, so
            # both ranks reach the next gate together — which is
            # exactly what the self-time half of the verdict exists
            # for: rank 1 publishes ~delay more own-work time per
            # crossing and the streak machine must emit dist.straggler
            # naming it. 250 ms keeps the published skew well clear of
            # the 50 ms threshold even when rank 0 does epoch-boundary
            # work (checkpoint, eval) inside the same window.
            _spawn("chaos", 1, 2, port, ["--dist-ckpt", ckpt],
                   {"MXNET_FLIGHT_DIR": flight,
                    "MXNET_METRICS_INTERVAL_MS": "200",
                    "MXNET_FAULTS":
                        "dispatch:delay=250:first=50;"
                        "kv_collective:raise:n=%d" % fault_n}),
        ]
        rcs_c, res_c = _leg("chaos", procs, 300)
        c0 = res_c[0]
        pms = sorted(f for f in os.listdir(flight)
                     if f.endswith("dead_worker.json"))
        pm_summary = None
        if pms:
            view = subprocess.run(
                [sys.executable,
                 os.path.join(root, "tools", "flight_view.py"),
                 os.path.join(flight, pms[0]), "--json"],
                stdout=subprocess.PIPE, text=True, timeout=60, cwd=root)
            if view.returncode == 0:
                pm_summary = json.loads(view.stdout)
        # the merged cluster view: every rank's dump joined, clocks
        # aligned from matched gate crossings, ONE artifact (ISSUE 18)
        fleet_trace = os.path.join(work, "chaos-fleet-trace.json")
        fleet = subprocess.run(
            [sys.executable,
             os.path.join(root, "tools", "fleet_view.py"),
             flight, "--json", "--trace", fleet_trace],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            timeout=60, cwd=root)
        fleet_summary = None
        if fleet.returncode == 0:
            fleet_summary = json.loads(fleet.stdout)
        out["chaos"] = {
            "rcs": rcs_c,
            "survivor": c0 and {
                "completed": c0["completed"], "finite": c0["finite"],
                "elastic": c0["dist_counters"]},
            "postmortems": pms,
            "postmortem_extra": pm_summary and pm_summary.get("extra"),
            "fleet_rc": fleet.returncode,
            "fleet": fleet_summary and {
                "n_ranks": fleet_summary["n_ranks"],
                "dead_ranks": fleet_summary["dead_ranks"],
                "stragglers": fleet_summary["stragglers"],
                "clock": fleet_summary["clock"],
                "warnings": fleet_summary["warnings"]},
        }

        # -- gates ------------------------------------------------------
        try:
            # A: fused across processes, zero dist fallbacks, replicas
            # bit-equal
            assert rcs == [0, 0], out["fused"]
            assert all(r and r["completed"] for r in res), out["fused"]
            assert [r["fallback_code"] for r in res] == [None, None], \
                out["fused"]
            assert [r["kvstore_dist_fallbacks"] for r in res] == [0, 0], \
                out["fused"]
            assert a0["dist_counters"].get("kvstore.dist.fused_steps") \
                == 2 * nbatch, a0["dist_counters"]
            bit_equal_ranks = all(
                np.array_equal(np.array(a0["params"][k]),
                               np.array(a1["params"][k]))
                for k in a0["params"])
            assert bit_equal_ranks, "replicas diverged across ranks"
            # B: matches the single-process oracle at the same global
            # batch (psum reassociation noise only)
            assert rcs_s == [0] and single and single["completed"]
            max_abs = max(
                float(np.abs(np.array(a0["params"][k])
                             - np.array(single["params"][k])).max())
                for k in a0["params"])
            out["oracle_max_abs_diff"] = max_abs
            out["oracle_bit_equal"] = all(
                np.array_equal(np.array(a0["params"][k]),
                               np.array(single["params"][k]))
                for k in a0["params"])
            assert all(
                np.allclose(np.array(a0["params"][k]),
                            np.array(single["params"][k]),
                            rtol=1e-5, atol=1e-6)
                for k in a0["params"]), "2-proc vs single: %r" % max_abs
            # C: deterministic kill, detected, re-meshed, resumed,
            # finished; postmortem names rank 1
            assert rcs_c[1] == DIST_FAULT_RC, rcs_c
            assert rcs_c[0] == 0, rcs_c
            assert c0 and c0["completed"] and c0["finite"], out["chaos"]
            el = c0["dist_counters"]
            assert el.get("elastic.dead_workers") == 1, el
            assert el.get("elastic.remesh") == 1, el
            assert el.get("elastic.resumed") == 1, el
            assert pms, "no dead_worker postmortem written"
            assert pm_summary is not None, "flight_view failed to parse"
            extra = pm_summary["extra"]
            assert extra["dead_ranks"] == [1], extra
            assert extra["epoch"] == 1 and extra["nbatch"] == 2, extra
            # C (fleet): ONE merged cluster view over the shared
            # flight dir — the killed rank is named dead, the
            # pre-death gate-wait spike is attributed to IT (rank 0's
            # dispatch ran undelayed, so every excess wait blames
            # rank 1), clocks align to within one gate-poll interval
            # (same box: the solved offset must be ~0), and the
            # survivor's dump carries the victim's own postmortem
            assert fleet.returncode == 0, fleet.stderr
            assert fleet_summary["n_ranks"] >= 2, fleet_summary
            assert fleet_summary["dead_ranks"] == [1], fleet_summary
            stragglers = fleet_summary["stragglers"]
            assert stragglers and stragglers[0]["rank"] == 1, stragglers
            assert stragglers[0]["straggler_events"] > 0, stragglers
            offs = fleet_summary["clock"]["offsets_s"]
            assert all(abs(o) <= 0.25 for o in offs.values()), offs
            assert any(int(m) > 0 for r, m in
                       fleet_summary["clock"]["matched_crossings"]
                       .items() if int(r) != 0), fleet_summary["clock"]
            with open(fleet_trace) as f:
                trace = json.load(f)
            tracks = {e["pid"] for e in trace["traceEvents"]
                      if e.get("name") == "process_name"}
            assert tracks >= {0, 1}, tracks
            peers = extra.get("peer_postmortems") or []
            assert any(p["rank"] == 1 and p["reason"] == "worker_abort"
                       for p in peers), peers
            out["gates_passed"] = True
        except AssertionError:
            out["gates_passed"] = False
            raise
    finally:
        # params are bulky and served their purpose — keep the artifact
        # readable
        line = json.dumps(out)
        print(line, flush=True)
        if json_out:
            with open(json_out, "w") as f:
                f.write(line + "\n")
        if out.get("gates_passed"):
            shutil.rmtree(work, ignore_errors=True)
        else:
            print("dist-smoke: logs kept under %s" % work, flush=True)
    return out


def _json_out_arg():
    if "--json-out" not in sys.argv:
        return None
    i = sys.argv.index("--json-out") + 1
    if i >= len(sys.argv) or sys.argv[i].startswith("--"):
        raise SystemExit("--json-out: missing output path")
    return sys.argv[i]


if __name__ == "__main__":
    if DIST_CHILD:
        _dist_child_main()
    elif DIST_SMOKE:
        dist_smoke(json_out=_json_out_arg())
    elif MP_SMOKE:
        mp_smoke(json_out=_json_out_arg())
    elif DP_SMOKE:
        dp_smoke(json_out=_json_out_arg())
    elif FIT_SMOKE:
        fit_smoke(json_out=_json_out_arg())
    else:
        main()
