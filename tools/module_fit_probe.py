#!/usr/bin/env python3
"""Break down where Module.fit's wall-clock goes vs the raw fused step
(PERF.md: the round-5 bench measured 157.9 img/s user-path vs 2254 raw).

Times each fit-loop phase IN ISOLATION on the attached accelerator:
  - forward_backward (the fused executor program)
  - update           (FusedUpdater one-dispatch step)
  - update_metric    (device-accumulated Accuracy)
  - epoch-end get_params/set_params round trip

Run on a TPU host:  python tools/module_fit_probe.py
Smoke (CPU):        MXTPU_PROBE_SMOKE=1 python tools/module_fit_probe.py
Fit-smoke lane:     python tools/module_fit_probe.py --fit-smoke \
                        [--json-out PATH]
  (tier-1 CI: tiny-MLP Module.fit on the CPU backend, 20 batches, fused
  vs phase-split A/B with per-batch dispatch counts — the user-path
  trajectory is captured every round even when the TPU tunnel is down)
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SMOKE = os.environ.get("MXTPU_PROBE_SMOKE", "") == "1"
FIT_SMOKE = "--fit-smoke" in sys.argv
BATCH = 8 if SMOKE else 128
IMG = 32 if SMOKE else 224
ITERS = 2 if SMOKE else 10

import numpy as np
import jax
import jax.numpy as jnp

if SMOKE or FIT_SMOKE:
    jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu.io import DataDesc

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..",
    "examples", "image-classification"))
from symbols.resnet import get_symbol


def timed(label, fn, fence, iters=ITERS):
    """``fence`` must return (or contain) buffers DATA-DEPENDENT on the
    work ``fn`` queued — a fresh unrelated transfer does NOT drain the
    compute queue, so fencing on one under-reports any async phase."""
    fn()  # warm
    np.asarray(jax.tree_util.tree_leaves(
        jax.block_until_ready(fence()))[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    np.asarray(jax.tree_util.tree_leaves(
        jax.block_until_ready(fence()))[0])
    dt = (time.perf_counter() - t0) / iters
    print("%-28s %8.2f ms" % (label, dt * 1e3), flush=True)
    return dt


def main():
    dev = jax.devices()[0]
    print("device:", dev.device_kind, flush=True)
    sym = get_symbol(num_classes=1000, num_layers=50,
                     image_shape="3,%d,%d" % (IMG, IMG))
    bf16 = np.dtype(jnp.bfloat16)
    mod = mx.mod.Module(sym, context=mx.tpu() if dev.platform != "cpu"
                        else mx.cpu())
    mod.bind(data_shapes=[DataDesc("data", (BATCH, 3, IMG, IMG),
                                   dtype=bf16)],
             label_shapes=[DataDesc("softmax_label", (BATCH,))],
             for_training=True)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9,
                                         "multi_precision": True})
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.uniform(-1, 1, (BATCH, 3, IMG, IMG))
                    .astype(np.float32)).astype(bf16)
    y = mx.nd.array(rs.randint(0, 1000, BATCH).astype(np.float32))
    from mxnet_tpu.io import DataBatch
    batch = DataBatch([x], [y], pad=0)
    metric = mx.metric.Accuracy()

    def grad_fence():
        return [g._data for g in mod._exec.grad_arrays if g is not None]

    def param_fence():
        return [mod._exec.arg_dict[n]._data for n in mod._param_names[:1]]

    def metric_fence():
        return metric._dev_sum

    results = {}
    results["forward_backward_ms"] = timed(
        "forward_backward", lambda: mod.forward_backward(batch),
        grad_fence) * 1e3
    results["update_ms"] = timed("update", lambda: mod.update(),
                                 param_fence) * 1e3
    results["update_metric_ms"] = timed(
        "update_metric",
        lambda: mod.update_metric(metric, batch.label), metric_fence) * 1e3

    def whole_step():
        mod.forward_backward(batch)
        mod.update()
        mod.update_metric(metric, batch.label)

    step_s = timed("whole step (fb+upd+metric)", whole_step,
                   lambda: (param_fence(), metric_fence()))
    results["step_ms"] = step_s * 1e3
    results["step_img_s"] = BATCH / step_s

    def epoch_end():
        arg_p, aux_p = mod.get_params()
        mod.set_params(arg_p, aux_p)

    results["epoch_end_get_set_ms"] = timed(
        "epoch-end get/set_params", epoch_end, param_fence,
        iters=max(2, ITERS // 3)) * 1e3

    print(json.dumps({k: round(v, 2) for k, v in results.items()}),
          flush=True)


def fit_smoke(json_out=None, nbatch=20, batch=32):
    """Tier-1 smoke lane: tiny-MLP ``Module.fit`` on the CPU backend,
    fused whole-step program vs phase-split oracle, with jitted-program
    dispatch counts per batch (``executor.dispatch_hook``). One JSON
    object on stdout (and to ``json_out`` when given) — the artifact the
    CI lane banks each round."""
    import mxnet_tpu as mx
    import mxnet_tpu.executor as _ex
    from mxnet_tpu.io import DataIter, DataDesc, DataBatch

    d, c = 16, 4
    rs = np.random.RandomState(0)

    class _PreslicedIter(DataIter):
        """Device-resident pre-sliced batches (bench/benchmark_score
        methodology): the lane measures framework DISPATCH overhead —
        the thing the fused step removes — not numpy slicing; the input
        pipeline has its own probes (tools/decode_bench.py)."""

        def __init__(self):
            super().__init__(batch)
            self._batches = [DataBatch(
                [mx.nd.array(rs.uniform(-1, 1, (batch, d))
                             .astype(np.float32))],
                [mx.nd.array(rs.randint(0, c, batch)
                             .astype(np.float32))], pad=0)
                for _ in range(nbatch)]
            self.i = 0

        @property
        def provide_data(self):
            return [DataDesc("data", (batch, d))]

        @property
        def provide_label(self):
            return [DataDesc("softmax_label", (batch,))]

        def reset(self):
            self.i = 0

        def next(self):
            if self.i >= len(self._batches):
                raise StopIteration
            self.i += 1
            return self._batches[self.i - 1]

    def mlp():
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
        net = mx.sym.Activation(net, act_type="relu")
        net = mx.sym.FullyConnected(net, num_hidden=c, name="fc2")
        return mx.sym.SoftmaxOutput(net, name="softmax")

    opt_params = {"learning_rate": 0.05, "momentum": 0.9}

    def setup(fused):
        os.environ["MXNET_MODULE_FUSED_STEP"] = "1" if fused else "0"
        mod = mx.mod.Module(mlp(), context=mx.cpu())
        metric = mx.metric.Accuracy()
        train = _PreslicedIter()
        # warm epoch: bind + init + compile land outside the timed window
        mod.fit(train, eval_metric=metric, num_epoch=1,
                initializer=mx.initializer.Xavier(),
                optimizer="sgd", optimizer_params=opt_params)
        if fused and mod._fused_fallback_reason is not None:
            raise SystemExit("fit-smoke: fused path fell back: %s"
                             % mod._fused_fallback_reason)
        return mod, metric, train

    def epoch(state, fused, counts):
        mod, metric, train = state
        os.environ["MXNET_MODULE_FUSED_STEP"] = "1" if fused else "0"
        counts.clear()
        t0 = time.perf_counter()
        mod.fit(train, eval_metric=metric, num_epoch=1,
                optimizer="sgd", optimizer_params=opt_params)
        # the loop is async — close the window on a data-dependent fetch
        metric.get()
        float(np.asarray(
            mod._exec.arg_dict[mod._param_names[0]]._data).sum())
        return time.perf_counter() - t0

    states = {True: setup(True), False: setup(False)}
    dts = {True: float("inf"), False: float("inf")}
    dispatch = {True: {}, False: {}}
    _ex.dispatch_hook = None
    try:
        # best-of-9, INTERLEAVED: one epoch is a ~10ms window, and
        # share-throttled CI boxes drift in sustained speed — timing the
        # two paths back to back inside each round keeps the RATIO
        # honest under drift, and the min converges on the dispatch
        # floor under spike noise
        for _ in range(9):
            for f in (True, False):
                counts = dispatch[f]
                _ex.dispatch_hook = lambda kind: counts.__setitem__(
                    kind, counts.get(kind, 0) + 1)
                dts[f] = min(dts[f], epoch(states[f], f, counts))
    finally:
        _ex.dispatch_hook = None

    def report(f):
        return {
            "img_s": round(batch * nbatch / dts[f], 1),
            "dispatches_per_batch": round(
                sum(dispatch[f].values()) / nbatch, 2),
            "dispatch_counts": dispatch[f],
        }

    fused, split = report(True), report(False)
    out = {
        "lane": "module_fit_smoke",
        "platform": jax.devices()[0].platform,
        "batch": batch, "nbatch": nbatch,
        "fused": fused, "phase_split": split,
        "fit_speedup": round(fused["img_s"] / split["img_s"], 2),
    }
    line = json.dumps(out)
    print(line, flush=True)
    if json_out:
        with open(json_out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    if FIT_SMOKE:
        path = None
        if "--json-out" in sys.argv:
            i = sys.argv.index("--json-out") + 1
            if i >= len(sys.argv) or sys.argv[i].startswith("--"):
                raise SystemExit("--json-out: missing output path")
            path = sys.argv[i]
        fit_smoke(json_out=path)
    else:
        main()
