#!/usr/bin/env python3
"""Break down where Module.fit's wall-clock goes vs the raw fused step
(PERF.md: the round-5 bench measured 157.9 img/s user-path vs 2254 raw).

Times each fit-loop phase IN ISOLATION on the attached accelerator:
  - forward_backward (the fused executor program)
  - update           (FusedUpdater one-dispatch step)
  - update_metric    (device-accumulated Accuracy)
  - epoch-end get_params/set_params round trip

Run on a TPU host:  python tools/module_fit_probe.py
Smoke (CPU):        MXTPU_PROBE_SMOKE=1 python tools/module_fit_probe.py
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SMOKE = os.environ.get("MXTPU_PROBE_SMOKE", "") == "1"
BATCH = 8 if SMOKE else 128
IMG = 32 if SMOKE else 224
ITERS = 2 if SMOKE else 10

import numpy as np
import jax
import jax.numpy as jnp

if SMOKE:
    jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu.io import DataDesc

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..",
    "examples", "image-classification"))
from symbols.resnet import get_symbol


def timed(label, fn, fence, iters=ITERS):
    """``fence`` must return (or contain) buffers DATA-DEPENDENT on the
    work ``fn`` queued — a fresh unrelated transfer does NOT drain the
    compute queue, so fencing on one under-reports any async phase."""
    fn()  # warm
    np.asarray(jax.tree_util.tree_leaves(
        jax.block_until_ready(fence()))[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    np.asarray(jax.tree_util.tree_leaves(
        jax.block_until_ready(fence()))[0])
    dt = (time.perf_counter() - t0) / iters
    print("%-28s %8.2f ms" % (label, dt * 1e3), flush=True)
    return dt


def main():
    dev = jax.devices()[0]
    print("device:", dev.device_kind, flush=True)
    sym = get_symbol(num_classes=1000, num_layers=50,
                     image_shape="3,%d,%d" % (IMG, IMG))
    bf16 = np.dtype(jnp.bfloat16)
    mod = mx.mod.Module(sym, context=mx.tpu() if dev.platform != "cpu"
                        else mx.cpu())
    mod.bind(data_shapes=[DataDesc("data", (BATCH, 3, IMG, IMG),
                                   dtype=bf16)],
             label_shapes=[DataDesc("softmax_label", (BATCH,))],
             for_training=True)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9,
                                         "multi_precision": True})
    rs = np.random.RandomState(0)
    x = mx.nd.array(rs.uniform(-1, 1, (BATCH, 3, IMG, IMG))
                    .astype(np.float32)).astype(bf16)
    y = mx.nd.array(rs.randint(0, 1000, BATCH).astype(np.float32))
    from mxnet_tpu.io import DataBatch
    batch = DataBatch([x], [y], pad=0)
    metric = mx.metric.Accuracy()

    def grad_fence():
        return [g._data for g in mod._exec.grad_arrays if g is not None]

    def param_fence():
        return [mod._exec.arg_dict[n]._data for n in mod._param_names[:1]]

    def metric_fence():
        return metric._dev_sum

    results = {}
    results["forward_backward_ms"] = timed(
        "forward_backward", lambda: mod.forward_backward(batch),
        grad_fence) * 1e3
    results["update_ms"] = timed("update", lambda: mod.update(),
                                 param_fence) * 1e3
    results["update_metric_ms"] = timed(
        "update_metric",
        lambda: mod.update_metric(metric, batch.label), metric_fence) * 1e3

    def whole_step():
        mod.forward_backward(batch)
        mod.update()
        mod.update_metric(metric, batch.label)

    step_s = timed("whole step (fb+upd+metric)", whole_step,
                   lambda: (param_fence(), metric_fence()))
    results["step_ms"] = step_s * 1e3
    results["step_img_s"] = BATCH / step_s

    def epoch_end():
        arg_p, aux_p = mod.get_params()
        mod.set_params(arg_p, aux_p)

    results["epoch_end_get_set_ms"] = timed(
        "epoch-end get/set_params", epoch_end, param_fence,
        iters=max(2, ITERS // 3)) * 1e3

    print(json.dumps({k: round(v, 2) for k, v in results.items()}),
          flush=True)


if __name__ == "__main__":
    main()
