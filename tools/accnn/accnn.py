"""ACCNN — accelerate a trained network by low-rank factorization
(parity: reference tools/accnn/ — acc_conv.py's SVD split of k x k
convolutions into a vertical (k x 1) + horizontal (1 x k) rank-d pair
[Jaderberg et al. 2014] and acc_fc.py's two-FC SVD split, driven by a
rank table).

Given a checkpoint, every Convolution whose name appears in the rank
table is replaced in the symbol JSON by ``<name>_v`` (d filters,
kh x 1, carries the vertical factor, no bias) followed by ``<name>_h``
(original filters, 1 x kw, carries the horizontal factor and the
original bias); FullyConnected layers split into ``<name>_red`` /
``<name>_rec``. Factor weights come from the SVD of the trained
tensor, so the factored net approximates the original without
retraining (fine-tune afterwards for exactness — same workflow as the
reference).

Usage:
  python tools/accnn/accnn.py --model prefix --epoch N \
      --ranks '{"conv1": 8, "fc1": 16}' --output prefix-acc
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
os.environ.setdefault("MXNET_TPU_FORCE_CPU", "1")

import numpy as np


def factor_conv(w, rank):
    """W (out, in, kh, kw) ~= H (out, rank, 1, kw) * V (rank, in, kh, 1).

    Solved by SVD of M[(in, kh), (out, kw)] — the exact scheme of
    reference acc_conv.py.
    """
    out_c, in_c, kh, kw = w.shape
    m = w.transpose(1, 2, 0, 3).reshape(in_c * kh, out_c * kw)
    u, s, vt = np.linalg.svd(m, full_matrices=False)
    rank = int(min(rank, len(s)))
    root_s = np.sqrt(s[:rank])
    v = (u[:, :rank] * root_s).T.reshape(rank, in_c, kh, 1)
    h = (vt[:rank, :].T * root_s).reshape(out_c, kw, rank) \
        .transpose(0, 2, 1).reshape(out_c, rank, 1, kw)
    return v.astype(w.dtype), h.astype(w.dtype)


def factor_fc(w, rank):
    """W (out, in) ~= A (out, rank) @ B (rank, in)."""
    u, s, vt = np.linalg.svd(w, full_matrices=False)
    rank = int(min(rank, len(s)))
    root_s = np.sqrt(s[:rank])
    a = (u[:, :rank] * root_s).astype(w.dtype)
    b = ((vt[:rank, :].T * root_s).T).astype(w.dtype)
    return b, a    # (reduce, reconstruct)


def _attr_tuple(attrs, key, default):
    v = attrs.get(key)
    if v is None:
        return default
    return tuple(int(x) for x in v.strip("()").replace(" ", "").split(",")
                 if x)


def accelerate(symbol_json, arg_params, ranks):
    """Rewrite the graph + params. Returns (new_json, new_args)."""
    graph = json.loads(symbol_json)
    nodes = graph["nodes"]
    new_nodes = []
    idmap = {}           # old node id -> (new id, output index)
    new_args = dict(arg_params)
    factored = set()     # layer names actually rewritten

    def emit(node):
        new_nodes.append(node)
        return len(new_nodes) - 1

    def var(name):
        return {"op": "null", "name": name, "inputs": []}

    for old_id, node in enumerate(nodes):
        op = node.get("op")
        name = node["name"]
        attrs = dict(node.get("attrs") or node.get("param") or {})
        mapped_inputs = [[idmap[src][0], out_ix, 0]
                         for src, out_ix, *_ in node["inputs"]]

        if op == "Convolution" and name in ranks \
                and _attr_tuple(attrs, "kernel", (1, 1)) > (1, 1) \
                and int(attrs.get("num_group", 1)) == 1:
            rank = ranks[name]
            kh, kw = _attr_tuple(attrs, "kernel", (1, 1))
            sh, sw = _attr_tuple(attrs, "stride", (1, 1)) or (1, 1)
            ph, pw = _attr_tuple(attrs, "pad", (0, 0)) or (0, 0)
            dh, dw = _attr_tuple(attrs, "dilate", (1, 1)) or (1, 1)
            num_filter = int(attrs["num_filter"])
            no_bias = str(attrs.get("no_bias", "False")) in ("True", "1")
            factored.add(name)

            w = np.asarray(arg_params[name + "_weight"])
            v, h = factor_conv(w, rank)
            new_args[name + "_v_weight"] = v
            new_args[name + "_h_weight"] = h
            if not no_bias:
                new_args[name + "_h_bias"] = np.asarray(
                    arg_params[name + "_bias"])

            data_in = mapped_inputs[0]
            vw = emit(var(name + "_v_weight"))
            v_id = emit({
                "op": "Convolution", "name": name + "_v",
                "attrs": {"kernel": "(%d, 1)" % kh,
                          "stride": "(%d, 1)" % sh,
                          "pad": "(%d, 0)" % ph,
                          "dilate": "(%d, 1)" % dh,
                          "num_filter": str(v.shape[0]),
                          "no_bias": "True"},
                "inputs": [data_in, [vw, 0, 0]]})
            hw = emit(var(name + "_h_weight"))
            h_inputs = [[v_id, 0, 0], [hw, 0, 0]]
            if not no_bias:
                hb = emit(var(name + "_h_bias"))
                h_inputs.append([hb, 0, 0])
            h_id = emit({
                "op": "Convolution", "name": name + "_h",
                "attrs": {"kernel": "(1, %d)" % kw,
                          "stride": "(1, %d)" % sw,
                          "pad": "(0, %d)" % pw,
                          "dilate": "(1, %d)" % dw,
                          "num_filter": str(num_filter),
                          "no_bias": str(no_bias)},
                "inputs": h_inputs})
            idmap[old_id] = (h_id, 0)
            continue

        if op == "FullyConnected" and name in ranks:
            rank = ranks[name]
            factored.add(name)
            num_hidden = int(attrs["num_hidden"])
            no_bias = str(attrs.get("no_bias", "False")) in ("True", "1")
            w = np.asarray(arg_params[name + "_weight"])
            b_red, a_rec = factor_fc(w, rank)
            new_args[name + "_red_weight"] = b_red
            new_args[name + "_rec_weight"] = a_rec
            if not no_bias:
                new_args[name + "_rec_bias"] = np.asarray(
                    arg_params[name + "_bias"])
            data_in = mapped_inputs[0]
            rw = emit(var(name + "_red_weight"))
            red = emit({
                "op": "FullyConnected", "name": name + "_red",
                "attrs": {"num_hidden": str(b_red.shape[0]),
                          "no_bias": "True"},
                "inputs": [data_in, [rw, 0, 0]]})
            cw = emit(var(name + "_rec_weight"))
            rec_inputs = [[red, 0, 0], [cw, 0, 0]]
            if not no_bias:
                cb = emit(var(name + "_rec_bias"))
                rec_inputs.append([cb, 0, 0])
            rec = emit({
                "op": "FullyConnected", "name": name + "_rec",
                "attrs": {"num_hidden": str(num_hidden),
                          "no_bias": str(no_bias)},
                "inputs": rec_inputs})
            idmap[old_id] = (rec, 0)
            continue

        # the variables of factored layers are rewritten to _v/_h (or
        # _red/_rec) names; a factored layer's original weight/bias
        # nodes are dead ONLY once the rewrite actually happened —
        # layers named in the rank table but skipped (1x1, grouped)
        # keep their variables. Because variable nodes precede their
        # consumer in topo order, dead ones are dropped in a second
        # pass below; here every null node is kept provisionally.

        node = dict(node)
        node["inputs"] = mapped_inputs
        idmap[old_id] = (emit(node), 0)

    # remap heads, then prune dead variable nodes (the originals of
    # factored layers, now consumerless)
    heads = [[idmap[h[0]][0], h[1] if len(h) > 1 else 0, 0]
             for h in graph["heads"]]
    used = set(h[0] for h in heads)
    for n in new_nodes:
        for src, _, _ in n["inputs"]:
            used.add(src)
    keep = [i for i, n in enumerate(new_nodes)
            if n["op"] != "null" or i in used]
    remap = {old: new for new, old in enumerate(keep)}
    pruned = []
    for i in keep:
        n = dict(new_nodes[i])
        n["inputs"] = [[remap[src], ix, k] for src, ix, k in n["inputs"]]
        pruned.append(n)
    heads = [[remap[h[0]], h[1], h[2]] for h in heads]
    new_nodes = pruned
    for nm in factored:
        new_args.pop(nm + "_weight", None)
        new_args.pop(nm + "_bias", None)
    arg_nodes = [i for i, n in enumerate(new_nodes) if n["op"] == "null"]
    out = {"nodes": new_nodes, "arg_nodes": arg_nodes,
           "heads": heads,
           "node_row_ptr": list(range(len(new_nodes) + 1))}
    for k in ("attrs",):
        if k in graph:
            out[k] = graph[k]
    return json.dumps(out), new_args


def select_ranks(sym, arg_params, data_shape, speedup):
    """Pick a rank per eligible conv to hit a FLOPs speedup (parity:
    reference rank_selection.py — same objective family: keep the most
    singular energy subject to factored cost <= cost/speedup. The
    reference solves it with a dict-keyed DP; here the monotone
    energy-threshold form is solved by bisection, which reaches the
    same frontier for this cost model)."""
    graph = json.loads(sym.tojson())
    internals = sym.get_internals()
    _, out_shapes, _ = internals.infer_shape_partial(data=data_shape)
    shape_of = dict(zip(internals.list_outputs(), out_shapes))
    nodes = graph["nodes"]
    # note: conv input channels and spectra come from the weight tensor
    # itself, so producers of any shape/output-arity are fine here

    convs = []
    for node in nodes:
        if node.get("op") != "Convolution":
            continue
        attrs = dict(node.get("attrs") or {})
        kh, kw = _attr_tuple(attrs, "kernel", (1, 1))
        if (kh, kw) <= (1, 1) or int(attrs.get("num_group", 1)) != 1:
            continue
        name = node["name"]
        oshape = shape_of[name + "_output"]
        xy = int(np.prod(oshape[2:]))
        n_f = int(attrs["num_filter"])
        w = np.asarray(arg_params[name + "_weight"])
        c_in = w.shape[1]          # channels from the weight itself
        svals = np.linalg.svd(
            w.transpose(1, 2, 0, 3).reshape(c_in * kh, -1),
            compute_uv=False)
        # factored pair cost per unit rank: vertical kh x 1 over c_in
        # channels + horizontal 1 x kw into n_f filters
        per_rank = (kh * c_in + kw * n_f) * xy
        full = kh * kw * n_f * c_in * xy
        convs.append((name, svals, per_rank, full))

    if not convs:
        return {}
    total = sum(c[3] for c in convs)
    budget = total / float(speedup)

    def ranks_at(tau):
        out = {}
        for name, svals, per_rank, _ in convs:
            energy = np.cumsum(svals ** 2) / np.sum(svals ** 2)
            d = int(np.searchsorted(energy, tau) + 1)
            out[name] = max(1, min(d, len(svals)))
        return out

    lo, hi = 0.0, 1.0
    for _ in range(40):
        mid = (lo + hi) / 2
        cost = sum(ranks_at(mid)[n] * pr for n, _, pr, _ in convs)
        if cost > budget:
            hi = mid
        else:
            lo = mid
    return ranks_at(lo)


def main():
    import mxnet_tpu as mx
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", required=True, help="checkpoint prefix")
    ap.add_argument("--epoch", type=int, default=0)
    ap.add_argument("--ranks", default=None,
                    help='JSON rank table, e.g. \'{"conv1": 8}\'')
    ap.add_argument("--speedup", type=float, default=None,
                    help="pick conv ranks automatically for this "
                         "FLOPs speedup (reference rank_selection.py)")
    ap.add_argument("--data-shape", default="1,3,224,224",
                    help="input shape for --speedup cost analysis")
    ap.add_argument("--output", required=True, help="output prefix")
    args = ap.parse_args()
    if (args.ranks is None) == (args.speedup is None):
        ap.error("exactly one of --ranks / --speedup is required")

    sym, arg_params, aux_params = mx.model.load_checkpoint(
        args.model, args.epoch)
    arg_np = {k: v.asnumpy() for k, v in arg_params.items()}
    if args.speedup is not None:
        shape = tuple(int(x) for x in args.data_shape.split(","))
        ranks = select_ranks(sym, arg_np, shape, args.speedup)
        print("selected ranks:", json.dumps(ranks))
    else:
        ranks = json.loads(args.ranks)
    new_json, new_args = accelerate(sym.tojson(), arg_np, ranks)

    with open(args.output + "-symbol.json", "w") as f:
        f.write(new_json)
    save_dict = {"arg:" + k: mx.nd.array(v) for k, v in new_args.items()}
    save_dict.update({"aux:" + k: v for k, v in aux_params.items()})
    mx.nd.save("%s-%04d.params" % (args.output, args.epoch), save_dict)
    old_n = sum(v.size for v in arg_np.values())
    new_n = sum(v.size for v in new_args.values())
    print("params: %d -> %d (%.1f%%)" % (old_n, new_n,
                                         100.0 * new_n / old_n))


if __name__ == "__main__":
    main()
