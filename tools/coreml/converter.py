"""MXNet-checkpoint -> CoreML NeuralNetwork converter.

Parity: reference tools/coreml/converter/_mxnet_converter.py + _layers.py
— walk the symbol graph in topological order, map each supported op to a
CoreML NeuralNetwork layer carrying the trained weights, and emit the
model spec. The reference drives coremltools' NeuralNetworkBuilder; this
converter builds the SAME spec structure as plain dicts, and
``save_spec`` writes it as JSON (`<out>.mlmodel.json`) — same layer
list, same weight payloads (base64). ``spec_to_mlmodel`` converts that
spec to a binary .mlmodel via coremltools' NeuralNetworkBuilder on a
machine that has coremltools (it cannot be installed in this
zero-egress image, so that path is best-effort and unexercised here;
the JSON spec is the tested artifact).

Supported ops (the reference's registry): Convolution, FullyConnected,
Activation, Pooling, Flatten, Reshape, SoftmaxOutput/softmax,
BatchNorm, elemwise_add, Concat. Anything else raises with the op name
(the reference errors the same way).
"""
from __future__ import annotations

import base64
import json

import numpy as np


def _b64(arr):
    arr = np.ascontiguousarray(arr, np.float32)
    return {"shape": list(arr.shape),
            "data": base64.b64encode(arr.tobytes()).decode("ascii")}


def _nodes_topo(sym):
    graph = json.loads(sym.tojson())
    return graph["nodes"], graph["heads"]


def convert(sym, arg_params, aux_params, input_shape, class_labels=None,
            mode=None):
    """Returns the CoreML spec as a plain dict (the builder-level
    representation; serialization is the caller's concern)."""
    nodes, heads = _nodes_topo(sym)
    layers = []
    out_of = {}      # node id -> blob name

    # the network input is the argument that carries no trained weights
    # (the reference derives it from the symbol's arguments the same way)
    known_params = set(arg_params) | set(aux_params)
    data_names = [n for n in sym.list_arguments() if n not in known_params]
    if not data_names:
        raise ValueError("no data input found in symbol arguments")
    input_name = data_names[0]
    for i, node in enumerate(nodes):
        if node["op"] == "null" and node["name"] == input_name:
            out_of[i] = input_name

    def param(name):
        if name in arg_params:
            return arg_params[name].asnumpy()
        if name in aux_params:
            return aux_params[name].asnumpy()
        raise KeyError("parameter %r missing from checkpoint" % name)

    for i, node in enumerate(nodes):
        op, name = node["op"], node["name"]
        attrs = node.get("attrs", node.get("param", {})) or {}
        if op == "null":
            continue
        in_blobs = [out_of[inp[0]] for inp in node["inputs"]
                    if inp[0] in out_of]
        out_blob = name + "_output"
        if op == "Convolution":
            w = param(name + "_weight")
            layer = {"type": "convolution", "name": name,
                     "input": in_blobs[:1], "output": [out_blob],
                     "kernel": json.loads(attrs["kernel"].replace("(", "[")
                                          .replace(")", "]")),
                     "stride": json.loads(attrs.get("stride", "(1, 1)")
                                          .replace("(", "[")
                                          .replace(")", "]")),
                     "pad": json.loads(attrs.get("pad", "(0, 0)")
                                       .replace("(", "[")
                                       .replace(")", "]")),
                     "num_filter": int(attrs["num_filter"]),
                     "weights": _b64(w)}
            if attrs.get("no_bias", "False") not in ("True", "true"):
                layer["bias"] = _b64(param(name + "_bias"))
            layers.append(layer)
        elif op == "FullyConnected":
            layer = {"type": "innerProduct", "name": name,
                     "input": in_blobs[:1], "output": [out_blob],
                     "num_hidden": int(attrs["num_hidden"]),
                     "weights": _b64(param(name + "_weight"))}
            if attrs.get("no_bias", "False") not in ("True", "true"):
                layer["bias"] = _b64(param(name + "_bias"))
            layers.append(layer)
        elif op == "Activation":
            layers.append({"type": "activation", "name": name,
                           "input": in_blobs[:1], "output": [out_blob],
                           "act_type": attrs.get("act_type", "relu")})
        elif op == "Pooling":
            layers.append({
                "type": "pooling", "name": name,
                "input": in_blobs[:1], "output": [out_blob],
                "pool_type": attrs.get("pool_type", "max"),
                "kernel": json.loads(attrs.get("kernel", "(2, 2)")
                                     .replace("(", "[").replace(")", "]")),
                "stride": json.loads(attrs.get("stride", "(1, 1)")
                                     .replace("(", "[").replace(")", "]")),
                "global": attrs.get("global_pool", "False")
                in ("True", "true")})
        elif op in ("Flatten", "flatten"):
            layers.append({"type": "flatten", "name": name,
                           "input": in_blobs[:1], "output": [out_blob]})
        elif op in ("Reshape", "reshape"):
            tgt = attrs.get("shape", "()")
            layers.append({"type": "reshape", "name": name,
                           "input": in_blobs[:1], "output": [out_blob],
                           "shape": json.loads(
                               tgt.replace("(", "[").replace(")", "]"))})
        elif op in ("SoftmaxOutput", "softmax"):
            layers.append({"type": "softmax", "name": name,
                           "input": in_blobs[:1], "output": [out_blob]})
        elif op == "BatchNorm":
            layers.append({
                "type": "batchnorm", "name": name,
                "input": in_blobs[:1], "output": [out_blob],
                "gamma": _b64(param(name + "_gamma")),
                "beta": _b64(param(name + "_beta")),
                "mean": _b64(param(name + "_moving_mean")),
                "variance": _b64(param(name + "_moving_var")),
                "eps": float(attrs.get("eps", 1e-3))})
        elif op in ("elemwise_add", "_Plus", "broadcast_add"):
            layers.append({"type": "add", "name": name,
                           "input": in_blobs, "output": [out_blob]})
        elif op == "Concat":
            layers.append({"type": "concat", "name": name,
                           "input": in_blobs, "output": [out_blob]})
        else:
            raise ValueError(
                "CoreML conversion not supported for op %r (node %r) — "
                "same unsupported-op contract as the reference converter"
                % (op, name))
        out_of[i] = out_blob

    spec = {
        "format": "mxnet_tpu-coreml-spec-v1",
        "description": {
            "input": [{"name": input_name, "shape": list(input_shape)}],
            "output": [{"name": out_of[heads[0][0]]}],
            "class_labels": list(class_labels) if class_labels else None,
            "mode": mode,
        },
        "neuralNetwork": {"layers": layers},
    }
    return spec


def save_spec(spec, path):
    """Write the spec as JSON (the tested artifact; see module
    docstring). ``path`` gets a ``.json`` suffix unless it has one."""
    out = path if path.endswith(".json") else path + ".json"
    with open(out, "w") as f:
        json.dump(spec, f)
    return out


def spec_to_mlmodel(spec, path):
    """Best-effort binary .mlmodel emission on a coremltools host (the
    builder calls mirror the reference's _layers.py; this path cannot
    run in the zero-egress build image and is therefore unexercised by
    the test suite — the JSON spec is the artifact of record)."""
    try:
        from coremltools.models import datatypes
        from coremltools.models.neural_network import NeuralNetworkBuilder
        import coremltools
    except ImportError as e:
        raise ImportError(
            "coremltools is required for binary .mlmodel output; "
            "use save_spec for the JSON form") from e
    inp = spec["description"]["input"][0]
    out_name = spec["description"]["output"][0]["name"]
    builder = NeuralNetworkBuilder(
        [(inp["name"], datatypes.Array(*inp["shape"][1:]))],
        [(out_name, None)])
    for l in spec["neuralNetwork"]["layers"]:
        kind = l["type"]
        if kind == "convolution":
            w = decode_weights(l["weights"])
            b = decode_weights(l["bias"]) if "bias" in l else None
            builder.add_convolution(
                name=l["name"], kernel_channels=w.shape[1],
                output_channels=w.shape[0], height=l["kernel"][0],
                width=l["kernel"][1], stride_height=l["stride"][0],
                stride_width=l["stride"][1], border_mode="valid",
                groups=1, W=np.transpose(w, (2, 3, 1, 0)), b=b,
                has_bias=b is not None, input_name=l["input"][0],
                output_name=l["output"][0],
                padding_top=l["pad"][0], padding_bottom=l["pad"][0],
                padding_left=l["pad"][1], padding_right=l["pad"][1])
        elif kind == "innerProduct":
            w = decode_weights(l["weights"])
            b = decode_weights(l["bias"]) if "bias" in l else None
            builder.add_inner_product(
                name=l["name"], W=w, b=b, input_channels=w.shape[1],
                output_channels=w.shape[0], has_bias=b is not None,
                input_name=l["input"][0], output_name=l["output"][0])
        elif kind == "activation":
            # MXNet act names -> coremltools non_linearity names
            act_map = {"relu": "RELU", "sigmoid": "SIGMOID",
                       "tanh": "TANH", "softrelu": "SOFTPLUS"}
            builder.add_activation(
                name=l["name"],
                non_linearity=act_map.get(l["act_type"],
                                          l["act_type"].upper()),
                input_name=l["input"][0], output_name=l["output"][0])
        elif kind == "pooling":
            pool_map = {"max": "MAX", "avg": "AVERAGE", "sum": "L2"}
            builder.add_pooling(
                name=l["name"], height=l["kernel"][0],
                width=l["kernel"][1], stride_height=l["stride"][0],
                stride_width=l["stride"][1],
                layer_type=pool_map.get(l["pool_type"],
                                        l["pool_type"].upper()),
                padding_type="VALID",
                input_name=l["input"][0], output_name=l["output"][0],
                is_global=l.get("global", False))
        elif kind == "flatten":
            builder.add_flatten(name=l["name"], mode=0,
                                input_name=l["input"][0],
                                output_name=l["output"][0])
        elif kind == "reshape":
            builder.add_reshape(name=l["name"],
                                input_name=l["input"][0],
                                output_name=l["output"][0],
                                target_shape=tuple(l["shape"]), mode=0)
        elif kind == "softmax":
            builder.add_softmax(name=l["name"], input_name=l["input"][0],
                                output_name=l["output"][0])
        elif kind == "batchnorm":
            builder.add_batchnorm(
                name=l["name"],
                channels=len(decode_weights(l["gamma"])),
                gamma=decode_weights(l["gamma"]),
                beta=decode_weights(l["beta"]),
                mean=decode_weights(l["mean"]),
                variance=decode_weights(l["variance"]),
                input_name=l["input"][0], output_name=l["output"][0],
                epsilon=l["eps"])
        elif kind == "add":
            builder.add_elementwise(
                name=l["name"], input_names=l["input"],
                output_name=l["output"][0], mode="ADD")
        elif kind == "concat":
            builder.add_elementwise(
                name=l["name"], input_names=l["input"],
                output_name=l["output"][0], mode="CONCAT")
        else:
            raise ValueError("unsupported layer kind %r" % kind)
    model = coremltools.models.MLModel(builder.spec)
    model.save(path)
    return path


def load_spec(path):
    with open(path) as f:
        return json.load(f)


def decode_weights(entry):
    raw = base64.b64decode(entry["data"])
    return np.frombuffer(raw, np.float32).reshape(entry["shape"])
