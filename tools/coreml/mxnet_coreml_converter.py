#!/usr/bin/env python3
"""CLI (parity: reference tools/coreml/mxnet_coreml_converter.py):

    python tools/coreml/mxnet_coreml_converter.py \
        --model-prefix model --epoch 0 \
        --input-shape 1,3,32,32 --output-file model.mlmodel
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax

jax.config.update("jax_platforms", "cpu")  # conversion is host-side


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model-prefix", required=True)
    ap.add_argument("--epoch", type=int, default=0)
    ap.add_argument("--input-shape", required=True)
    ap.add_argument("--output-file", required=True)
    ap.add_argument("--class-labels", default=None,
                    help="comma-separated labels")
    args = ap.parse_args()

    import mxnet_tpu as mx
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from converter import convert, save_spec  # noqa: E402

    sym, arg_params, aux_params = mx.model.load_checkpoint(
        args.model_prefix, args.epoch)
    shape = tuple(int(x) for x in args.input_shape.split(","))
    labels = args.class_labels.split(",") if args.class_labels else None
    spec = convert(sym, arg_params, aux_params, shape, class_labels=labels)
    try:
        from converter import spec_to_mlmodel
        out = spec_to_mlmodel(spec, args.output_file)
    except ImportError:
        out = save_spec(spec, args.output_file)
    n = len(spec["neuralNetwork"]["layers"])
    print("converted %d layers -> %s" % (n, out))


if __name__ == "__main__":
    main()
