"""Sparse-kernel microbenchmarks (parity: reference
benchmark/python/sparse/{dot.py,cast_storage.py,sparse_op.py} — the
harness the reference ships for its CSR kernels, no published numbers).

Measures the compressed-representation kernels on the attached device at
embedding-scale shapes: dot(csr, dense) fwd, its transpose, rsp<->csr
cast_storage, and csr+csr elemwise_add. Prints one JSON line per case.

    python tools/sparse_bench.py [--rows N] [--cols N] [--density D]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# host-side default: the axon backend can hang when the tunnel is down,
# and the env var JAX_PLATFORMS is overridden by the axon sitecustomize
# — the config.update call BEFORE any backend touch is the reliable
# switch. Set MXTPU_SPARSE_BENCH_TPU=1 on a chip-attached host.
import jax  # noqa: E402

if os.environ.get("MXTPU_SPARSE_BENCH_TPU") != "1":
    jax.config.update("jax_platforms", "cpu")

import numpy as np


def _payload(out):
    """The compressed payload to sync on — NEVER the ._data property,
    which lazily materialises the dense view for sparse arrays."""
    for attr in ("_csr_data", "_rsp_data"):
        o = getattr(out, attr, None)
        if o is not None:
            return o
    return getattr(out, "_data", out)


def bench(fn, iters=10):
    import jax
    jax.block_until_ready(_payload(fn()))  # warm-up
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    jax.block_until_ready(_payload(out))
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--cols", type=int, default=512)
    ap.add_argument("--density", type=float, default=0.00001)
    ap.add_argument("--rhs-cols", type=int, default=64)
    args = ap.parse_args()

    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.ndarray import sparse as sp

    rs = np.random.RandomState(0)
    nnz = max(int(args.rows * args.cols * args.density), 1)
    # unique sorted (row, col) keys: CSR kernels assume no duplicate
    # coordinates
    keys = np.unique(rs.randint(0, args.rows * args.cols, nnz)
                     .astype(np.int64))
    rows, cols = keys // args.cols, keys % args.cols
    nnz = len(keys)
    counts = np.bincount(rows, minlength=args.rows)
    indptr = np.concatenate([[0], np.cumsum(counts)])
    vals = rs.randn(nnz).astype(np.float32)
    csr = sp.CSRNDArray(vals, cols, indptr, (args.rows, args.cols))
    rhs = mx.nd.array(rs.randn(args.cols, args.rhs_cols)
                      .astype(np.float32))
    rhs_t = mx.nd.array(rs.randn(args.rows, args.rhs_cols)
                        .astype(np.float32))

    dev = jax.devices()[0].platform
    base = {"device": dev, "rows": args.rows, "cols": args.cols,
            "nnz": int(nnz)}

    t = bench(lambda: sp.dot(csr, rhs))
    print(json.dumps({**base, "metric": "dot_csr_dense",
                      "value": round(t * 1e3, 3), "unit": "ms",
                      "gflops": round(2 * nnz * args.rhs_cols / t / 1e9,
                                      2)}))
    t = bench(lambda: sp.dot(csr, rhs_t, transpose_a=True))
    print(json.dumps({**base, "metric": "dot_csrT_dense",
                      "value": round(t * 1e3, 3), "unit": "ms"}))
    t = bench(lambda: csr.tostype("row_sparse"))
    print(json.dumps({**base, "metric": "cast_csr_to_rsp",
                      "value": round(t * 1e3, 3), "unit": "ms"}))
    rsp = csr.tostype("row_sparse")
    t = bench(lambda: rsp.tostype("csr"))
    print(json.dumps({**base, "metric": "cast_rsp_to_csr",
                      "value": round(t * 1e3, 3), "unit": "ms"}))
    t = bench(lambda: sp.elemwise_add(csr, csr))
    print(json.dumps({**base, "metric": "elemwise_add_csr_csr",
                      "value": round(t * 1e3, 3), "unit": "ms"}))


if __name__ == "__main__":
    main()
