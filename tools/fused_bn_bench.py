#!/usr/bin/env python3
"""A/B the fused BN-apply+add+relu Pallas kernel against the composed
XLA chain on the attached accelerator (PERF.md 'next levers').

Measures the block-tail elementwise pass in isolation at ResNet-50
stage shapes. Run on a TPU host:

    python tools/fused_bn_bench.py            # all stage shapes
    MXTPU_FB_ITERS=100 python tools/fused_bn_bench.py

Prints one line per shape: fused vs composed us/pass and the ratio.
On CPU it still runs (interpret mode) but timings are meaningless —
the point of the tool is the on-chip A/B.
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from mxnet_tpu.pallas.fused_bn import scale_bias_add_relu

ITERS = int(os.environ.get("MXTPU_FB_ITERS", "50"))

# ResNet-50 batch-128 NHWC block-tail shapes (stage outputs)
SHAPES = [
    (128, 56, 56, 256),
    (128, 28, 28, 512),
    (128, 14, 14, 1024),
    (128, 7, 7, 2048),
]


def bench(fn, *args):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / ITERS * 1e6


def main():
    dev = jax.devices()[0]
    print("device:", dev.device_kind)
    dt = jnp.bfloat16 if dev.platform == "tpu" else jnp.float32
    shapes = SHAPES
    if dev.platform != "tpu":
        # interpret-mode Pallas is a serial CPU emulation: stage-size
        # tensors would take minutes per call. Tiny shapes keep the tool
        # runnable as a smoke check; the numbers only mean something on
        # the chip.
        shapes = [(2, 7, 7, 64)]
    for shape in shapes:
        c = shape[-1]
        rs = np.random.RandomState(0)
        x = jax.device_put(rs.randn(*shape).astype(np.float32)).astype(dt)
        r = jax.device_put(rs.randn(*shape).astype(np.float32)).astype(dt)
        s = jax.device_put(rs.rand(c).astype(np.float32) + 0.5)
        b = jax.device_put(rs.randn(c).astype(np.float32))

        fused = jax.jit(lambda x, s, b, r: scale_bias_add_relu(x, s, b, r))   # mxlint: disable=jit-site -- throwaway microbench kernel; no card/cache contract to honour, timings are the whole output

        @jax.jit   # mxlint: disable=jit-site -- same standalone A/B microbench; never dispatched by the runtime
        def composed(x, s, b, r):
            return jnp.maximum(x * s.astype(x.dtype) + b.astype(x.dtype)
                               + r, jnp.zeros((), x.dtype))

        t_fused = bench(fused, x, s, b, r)
        t_comp = bench(composed, x, s, b, r)
        gb = 3 * np.prod(shape) * np.dtype(dt).itemsize / 1e9
        print("%s  fused %8.1f us (%5.0f GB/s)  composed %8.1f us "
              "(%5.0f GB/s)  ratio %.3f"
              % (shape, t_fused, gb / (t_fused / 1e6),
                 t_comp, gb / (t_comp / 1e6), t_comp / t_fused))


if __name__ == "__main__":
    main()
