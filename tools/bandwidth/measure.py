"""KVStore bandwidth probe (parity: reference tools/bandwidth/measure.py):
times push(grad)/pull(weight) rounds over the device mesh and reports
effective all-reduce GB/s — the number the reference measured for its
CommCPU/CommDevice/NCCL backends, here for XLA collectives over ICI.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--kvstore", type=str, default="device")
    parser.add_argument("--num-shards", type=int, default=4,
                        help="simulated devices pushing per key")
    parser.add_argument("--size-mb", type=float, default=16.0)
    parser.add_argument("--rounds", type=int, default=10)
    parser.add_argument("--force-cpu", action="store_true")
    args = parser.parse_args()

    if args.force_cpu:
        os.environ["MXNET_TPU_FORCE_CPU"] = "1"
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")

    import numpy as np
    import mxnet_tpu as mx

    n = int(args.size_mb * 1024 * 1024 / 4)
    shape = (n,)
    kv = mx.kv.create(args.kvstore)
    kv.init(0, mx.nd.zeros(shape))
    shards = [mx.nd.array(np.full(shape, i + 1, np.float32))
              for i in range(args.num_shards)]
    out = mx.nd.zeros(shape)

    # warmup
    kv.push(0, shards)
    kv.pull(0, out=out)
    out.wait_to_read()

    tic = time.time()
    for _ in range(args.rounds):
        kv.push(0, shards)
        kv.pull(0, out=out)
    out.wait_to_read()
    dt = (time.time() - tic) / args.rounds
    # bytes moved per round: each shard in + result out
    gb = args.size_mb * (args.num_shards + 1) / 1024.0
    print("kvstore=%s shards=%d size=%.0fMB: %.2f ms/round, %.2f GB/s"
          % (args.kvstore, args.num_shards, args.size_mb, dt * 1e3,
             gb / dt))


if __name__ == "__main__":
    main()
