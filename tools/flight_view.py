#!/usr/bin/env python3
"""flight_view: pretty-print one flight-recorder postmortem dump.

Usage::

    python tools/flight_view.py POSTMORTEM.json [--json]

Renders the black box a crash left behind (``mxnet_tpu/flight.py``):

* header — trigger reason, when, the exception (an injected fault's
  site is surfaced), the trigger's extra facts (e.g. the dying batch's
  member req_ids);
* event timeline — the last-N discrete events (faults, sheds, breaker
  trips, checkpoint saves) with time-to-crash offsets;
* top counter deltas — summed over the recent time-series window (the
  sampler's per-interval deltas), falling back to the cumulative
  counters when no sampler ran;
* slowest requests — per-req_id wait / batch / d2h / resolve breakdown
  reconstructed from the causal span ring, with each request's bucket
  padding joined from the batch events;
* engine + fault-registry state.

``--json`` emits the computed summary as JSON instead. Exit codes:
0 = rendered, 2 = malformed dump (unreadable, unparseable, wrong
schema, or missing required sections) — so a lane can gate "the
postmortem a chaos run produced is a REAL one".

Stdlib-only (the dump is plain JSON; no framework import needed).
"""
import json
import os
import sys
import time

REQUIRED = ("schema", "reason", "ts", "counters", "events", "spans")
SCHEMA_PREFIX = "mxnet_tpu.flight/"

# events shown in the timeline section (newest last)
TIMELINE_EVENTS = 40
TOP_COUNTERS = 15
SLOWEST_REQUESTS = 10


class MalformedDump(Exception):
    pass


def load_dump(path):
    """Parse + validate one postmortem file; raises MalformedDump."""
    try:
        with open(path) as f:
            rec = json.load(f)
    except OSError as e:
        raise MalformedDump("cannot read %s: %s" % (path, e))
    except ValueError as e:
        raise MalformedDump("%s is not valid JSON: %s" % (path, e))
    if not isinstance(rec, dict):
        raise MalformedDump("%s: top-level value is not an object"
                            % path)
    missing = [k for k in REQUIRED if k not in rec]
    if missing:
        raise MalformedDump("%s: missing required keys: %s"
                            % (path, ", ".join(missing)))
    if not str(rec.get("schema", "")).startswith(SCHEMA_PREFIX):
        raise MalformedDump("%s: schema %r is not a %s* dump"
                            % (path, rec.get("schema"), SCHEMA_PREFIX))
    if not isinstance(rec["events"], list) \
            or not isinstance(rec["spans"], list) \
            or not isinstance(rec["counters"], dict):
        raise MalformedDump("%s: events/spans/counters have the wrong "
                            "shape" % path)
    return rec


def counter_deltas(rec):
    """{counter: delta} over the dump's time-series window; cumulative
    counters when no sampler samples rode along."""
    totals = {}
    for sample in rec.get("series") or []:
        for k, v in (sample.get("counters") or {}).items():
            totals[k] = totals.get(k, 0) + v
    if totals:
        return totals, "series window (%d samples)" % len(rec["series"])
    return dict(rec["counters"]), "cumulative counters (no sampler ran)"


def _span_req_ids(span):
    ctx = span.get("ctx") or {}
    if ctx.get("req_id") is not None:
        return [ctx["req_id"]]
    return list(ctx.get("req_ids") or [])


def request_breakdown(rec):
    """Per-request latency breakdown from the causal span ring:
    [{req_id, total_ms, wait_ms, batch_ms, d2h_ms, resolve_ms,
    pad_rows, bucket}] sorted slowest-total first. ``resolve_ms`` is
    the total minus the named phases — queueing on the resolver pool
    plus slicing (the "inflight" slack)."""
    per = {}
    for span in rec["spans"]:
        name = span.get("name")
        if name not in ("serve_wait", "serve_batch", "serve_d2h",
                        "serve_request"):
            continue
        for rid in _span_req_ids(span):
            d = per.setdefault(rid, {})
            # a request appears once per phase; keep the max defensively
            d[name] = max(d.get(name, 0.0), span.get("dur_ms") or 0.0)
    pads = {}
    for ev in rec["events"]:
        if ev.get("kind") != "serving.batch":
            continue
        data = ev.get("data") or {}
        for rid in data.get("req_ids") or []:
            pads[rid] = {"pad_rows": data.get("pad_rows"),
                         "bucket": data.get("bucket")}
    out = []
    for rid, d in per.items():
        total = d.get("serve_request")
        if total is None:
            continue          # still in flight when the process died
        wait = d.get("serve_wait", 0.0)
        batch = d.get("serve_batch", 0.0)
        d2h = d.get("serve_d2h", 0.0)
        out.append({
            "req_id": rid,
            "total_ms": round(total, 3),
            "wait_ms": round(wait, 3),
            "batch_ms": round(batch, 3),
            "d2h_ms": round(d2h, 3),
            "resolve_ms": round(max(0.0, total - wait - batch - d2h),
                                3),
            "pad_rows": pads.get(rid, {}).get("pad_rows"),
            "bucket": pads.get(rid, {}).get("bucket"),
        })
    out.sort(key=lambda r: -r["total_ms"])
    return out


def summarize(rec):
    """The machine-readable summary ``--json`` emits."""
    deltas, source = counter_deltas(rec)
    top = sorted(deltas.items(), key=lambda kv: -abs(kv[1]))
    return {
        "reason": rec["reason"],
        "ts": rec["ts"],
        "pid": rec.get("pid"),
        "exception": rec.get("exception"),
        "extra": rec.get("extra"),
        "top_counters": top[:TOP_COUNTERS],
        "counters_source": source,
        "n_events": len(rec["events"]),
        "n_spans": len(rec["spans"]),
        "n_series": len(rec.get("series") or []),
        "slowest_requests": request_breakdown(rec)[:SLOWEST_REQUESTS],
        "engines": rec.get("engines"),
        "faults": rec.get("faults"),
    }


def _fmt_ts(epoch_s):
    try:
        return time.strftime("%Y-%m-%d %H:%M:%S",
                             time.localtime(epoch_s))
    except (TypeError, ValueError, OverflowError):
        return str(epoch_s)


def _fmt_data(data, width=72):
    if not data:
        return ""
    text = json.dumps(data, sort_keys=True)
    return text if len(text) <= width else text[:width - 1] + "…"


def render(rec, out=sys.stdout):
    w = out.write
    exc = rec.get("exception") or {}
    w("flight postmortem: %s\n" % rec["reason"])
    w("  at %s (pid %s)\n" % (_fmt_ts(rec["ts"]), rec.get("pid")))
    if exc:
        w("  exception: %s: %s\n" % (exc.get("type"),
                                     (exc.get("message") or "")[:200]))
        if exc.get("fault_site"):
            w("  injected fault site: %s\n" % exc["fault_site"])
    extra = rec.get("extra")
    if extra:
        w("  extra: %s\n" % _fmt_data(extra, width=200))

    events = rec["events"][-TIMELINE_EVENTS:]
    w("\nevent timeline (last %d of %d; dt = seconds before dump):\n"
      % (len(events), len(rec["events"])))
    for ev in events:
        dt = rec["ts"] - ev.get("ts", rec["ts"])
        w("  -%7.3fs  %-24s %s\n"
          % (dt, ev.get("kind", "?"), _fmt_data(ev.get("data"))))
    if not events:
        w("  (empty ring)\n")

    deltas, source = counter_deltas(rec)
    w("\ntop counter deltas — %s:\n" % source)
    for name, val in sorted(deltas.items(),
                            key=lambda kv: -abs(kv[1]))[:TOP_COUNTERS]:
        w("  %-44s %12s\n" % (name, val))
    if not deltas:
        w("  (none)\n")

    reqs = request_breakdown(rec)
    w("\nslowest requests (of %d resolved in the ring; ms):\n"
      % len(reqs))
    w("  %8s %9s %9s %9s %9s %9s %5s\n"
      % ("req_id", "total", "wait", "batch", "d2h", "resolve", "pad"))
    for r in reqs[:SLOWEST_REQUESTS]:
        w("  %8s %9.2f %9.2f %9.2f %9.2f %9.2f %5s\n"
          % (r["req_id"], r["total_ms"], r["wait_ms"], r["batch_ms"],
             r["d2h_ms"], r["resolve_ms"],
             "-" if r["pad_rows"] is None else r["pad_rows"]))
    if not reqs:
        w("  (no resolved requests in the span ring)\n")

    engines = rec.get("engines") or []
    if engines:
        w("\nengines:\n")
        for e in engines:
            w("  queued_rows=%s/%s breaker_open=%s "
              "consecutive_failures=%s closed=%s\n"
              % (e.get("queued_rows"), e.get("max_queue_rows"),
                 e.get("breaker_open"), e.get("consecutive_failures"),
                 e.get("closed")))
    faults = rec.get("faults") or {}
    if faults.get("spec"):
        w("\nfault registry: spec=%r counts=%s\n"
          % (faults["spec"], faults.get("counts")))
    w("\n")


def main(argv):
    args = [a for a in argv[1:] if not a.startswith("--")]
    as_json = "--json" in argv[1:]
    bad = [a for a in argv[1:] if a.startswith("--") and a != "--json"]
    if bad or len(args) != 1:
        print("usage: flight_view.py POSTMORTEM.json [--json]",
              file=sys.stderr)
        return 2
    try:
        rec = load_dump(args[0])
    except MalformedDump as e:
        print("flight_view: malformed dump: %s" % e, file=sys.stderr)
        return 2
    if as_json:
        json.dump(summarize(rec), sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        render(rec)
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main(sys.argv))
    except BrokenPipeError:
        # `flight_view.py dump | head` closes our stdout mid-render —
        # that's the reader's prerogative, not an error. Point stdout
        # at devnull so interpreter shutdown doesn't re-raise.
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
        sys.exit(0)
