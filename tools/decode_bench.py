"""JPEG decode+augment throughput probe.

Parity: the reference measures its input pipeline via
iter_image_recordio_2's multithreaded decode (src/io/
iter_image_recordio_2.cc:660-760); this probe packs synthetic JPEGs into
RecordIO and measures ImageIter decode img/s at a given thread count, so
a deployment can check the pipeline feeds the accelerator (compare
against bench.py's img/s).

Usage: python tools/decode_bench.py [--threads N] [--images M]
                                    [--size HxW] [--batch B]
Prints one JSON line: {"metric": "jpeg_decode_throughput", ...}
"""
import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# host-side probe: never touch the accelerator (axon init can hang when
# the tunnel is down, and decode throughput is a CPU property anyway)
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--threads", type=int, default=os.cpu_count() or 1)
    ap.add_argument("--images", type=int, default=256)
    ap.add_argument("--size", default="224x224")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--epochs", type=int, default=2)
    args = ap.parse_args()
    h, w = (int(x) for x in args.size.split("x"))

    from mxnet_tpu.image import ImageIter
    # one packing methodology for both probes: PERF.md compares their
    # numbers, so the JPEG quality/seed/header must not drift apart
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from feed_probe import pack_synthetic_rec

    with tempfile.TemporaryDirectory() as td:
        rec_path = os.path.join(td, "probe.rec")
        pack_synthetic_rec(rec_path, args.images, h, w)

        it = ImageIter(batch_size=args.batch, data_shape=(3, h, w),
                       path_imgrec=rec_path,
                       preprocess_threads=args.threads)
        # warm epoch (thread pool spin-up, page cache)
        for _ in it:
            pass
        n = 0
        t0 = time.perf_counter()
        for _ in range(args.epochs):
            it.reset()
            for batch in it:
                n += batch.data[0].shape[0]
        dt = time.perf_counter() - t0
        print(json.dumps({
            "metric": "jpeg_decode_throughput",
            "value": round(n / dt, 1),
            "unit": "img/s",
            "threads": args.threads,
            "image_size": "%dx%d" % (h, w),
            "batch": args.batch,
        }))


if __name__ == "__main__":
    main()
