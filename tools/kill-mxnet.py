"""Kill stray distributed-training processes on this host
(parity: reference tools/kill-mxnet.py)."""
import argparse
import os
import signal
import subprocess


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("pattern", nargs="?", default="mxnet_tpu",
                        help="substring of the command line to match")
    parser.add_argument("--signal", type=int, default=signal.SIGTERM)
    args = parser.parse_args()

    me = os.getpid()
    out = subprocess.run(["ps", "-eo", "pid,args"], capture_output=True,
                         text=True).stdout
    killed = 0
    for line in out.splitlines()[1:]:
        parts = line.strip().split(None, 1)
        if len(parts) != 2:
            continue
        pid, cmd = int(parts[0]), parts[1]
        if args.pattern in cmd and "python" in cmd and pid != me \
                and "kill-mxnet" not in cmd:
            try:
                os.kill(pid, args.signal)
                killed += 1
                print("killed %d: %s" % (pid, cmd[:80]))
            except ProcessLookupError:
                pass
    print("%d processes signalled" % killed)


if __name__ == "__main__":
    main()
