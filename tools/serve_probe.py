#!/usr/bin/env python3
"""Serving-path probe: the micro-batching engine vs the one-request-
at-a-time Predictor facade.

Serve-smoke lane:   python tools/serve_probe.py --serve-smoke \
                        [--json-out PATH]
  (tier-1 CI: tiny-MLP on the CPU backend — the batched
  ``serving.InferenceEngine`` vs a sequential ``Predictor.forward``
  loop, interleaved best-of timing. Gates: batched sustained
  throughput >= 3x unbatched at max_batch >= 8, and EXACTLY one
  compile per bucket signature via ``telemetry.programs()``. The JSON
  artifact banks both throughputs, the request p50/p95/p99 and the
  per-bucket program cards every round.)
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.predictor import Predictor
from mxnet_tpu.serving import InferenceEngine

D, C, HID = 16, 4, 64
N_REQ = 256
MAX_BATCH = 16
ROUNDS = 5
SPEEDUP_GATE = 3.0


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=HID, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=C, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _params(symbol):
    rng = np.random.RandomState(0)
    shapes, _, _ = symbol.infer_shape_partial(data=(2, D))
    return {"arg:" + n: mx.nd.array(rng.normal(0, 0.1, s)
                                    .astype(np.float32))
            for n, s in zip(symbol.list_arguments(), shapes)
            if n not in ("data", "softmax_label")}


def serve_smoke(json_out=None, n_req=N_REQ, rounds=ROUNDS):
    sym = _mlp()
    params = _params(sym)
    rng = np.random.RandomState(1)
    reqs = [rng.normal(size=(1, D)).astype(np.float32)
            for _ in range(n_req)]

    pred = Predictor(sym, params, {"data": (1, D)})
    pred.forward(data=reqs[0])        # compile the unbatched signature
    pred.get_output(0).asnumpy()
    engine = InferenceEngine(sym, params, {"data": (1, D)},
                             max_batch=MAX_BATCH, max_wait_ms=1.0,
                             max_inflight=4)
    # the bucket cache as warmup built it — captured BEFORE the timed
    # windows (each window telemetry.reset() clears the registry; cards
    # re-register on dispatch, so the post-traffic registry only shows
    # the buckets the last window happened to use)
    cards = engine.program_cards()

    def unbatched_epoch():
        t0 = time.perf_counter()
        for x in reqs:
            pred.forward(data=x)
            pred.get_output(0).asnumpy()
        return time.perf_counter() - t0

    def batched_epoch():
        t0 = time.perf_counter()
        futs = [engine.submit(data=x) for x in reqs]
        for f in futs:
            f.result(timeout=300)
        return time.perf_counter() - t0

    # interleaved best-of (the module_fit_probe timing discipline:
    # back-to-back legs keep the RATIO honest under CI share drift; the
    # min converges on the dispatch floor under spike noise)
    dt_un = dt_b = float("inf")
    batched_window = {}
    was_enabled = telemetry.enabled()
    telemetry.enable()
    try:
        for _ in range(rounds):
            dt_un = min(dt_un, unbatched_epoch())
            telemetry.reset()
            dt = batched_epoch()
            if dt <= dt_b:
                dt_b = dt
                snap = telemetry.snapshot()
                batched_window = {
                    "counters": {k: v for k, v in snap["counters"].items()
                                 if k.startswith(("serving.",
                                                  "dispatch."))},
                    "spans": {k: v for k, v in snap["spans"].items()
                              if k in telemetry.SERVE_SPANS},
                    # _InstrumentedProgram._build times every program
                    # build as a jit_compile span — the engine dispatch
                    # path never touches the jit.compile COUNTER (that
                    # counts _GraphProgram entry-point lookups), so the
                    # span count is the one signal that catches a
                    # per-batch recompile inside the timed window
                    "jit_compiles": snap["spans"].get(
                        "jit_compile", {}).get("count", 0),
                }
    finally:
        if not was_enabled:
            telemetry.disable()

    lat = batched_window.get("spans", {}).get("serve_request", {})
    out = {
        "lane": "serve_smoke",
        "platform": jax.devices()[0].platform,
        "n_requests": n_req,
        "max_batch": MAX_BATCH,
        "buckets": engine.buckets,
        "unbatched_req_s": round(n_req / dt_un, 1),
        "batched_req_s": round(n_req / dt_b, 1),
        "serve_speedup": round(dt_un / dt_b, 2),
        "latency_ms": {k: lat.get(k)
                       for k in ("p50_ms", "p95_ms", "p99_ms")},
        "batch_fill": engine.stats()["batch_fill"],
        "telemetry": batched_window,
        "program_cards": {
            k: {kk: c.get(kk) for kk in
                ("kind", "signature", "flops", "peak_bytes",
                 "compile_ms", "dispatches")}
            for k, c in cards.items()},
        "compiles_per_bucket": round(len(cards) / len(engine.buckets), 2),
    }
    engine.close()
    # the serving acceptance gates (ISSUE 5): exactly one compiled
    # program per bucket signature, ZERO compiles inside the timed
    # steady-state window (every dispatch a cache hit), and sustained
    # batched throughput >= SPEEDUP_GATE x the sequential Predictor loop
    try:
        assert len(cards) == len(engine.buckets), \
            ("compiles != buckets", sorted(cards), engine.buckets)
        assert batched_window.get("jit_compiles", -1) == 0, batched_window
        assert out["serve_speedup"] >= SPEEDUP_GATE, out["serve_speedup"]
        out["gates_passed"] = True
    except AssertionError:
        out["gates_passed"] = False
        raise
    finally:
        line = json.dumps(out)
        print(line, flush=True)
        if json_out:
            with open(json_out, "w") as f:
                f.write(line + "\n")
    return out


def _json_out_arg():
    if "--json-out" not in sys.argv:
        return None
    i = sys.argv.index("--json-out") + 1
    if i >= len(sys.argv) or sys.argv[i].startswith("--"):
        raise SystemExit("--json-out: missing output path")
    return sys.argv[i]


if __name__ == "__main__":
    if "--serve-smoke" in sys.argv:
        serve_smoke(json_out=_json_out_arg())
    else:
        raise SystemExit("usage: serve_probe.py --serve-smoke "
                         "[--json-out PATH]")
