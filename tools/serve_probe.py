#!/usr/bin/env python3
"""Serving-path probe: the micro-batching engine vs the one-request-
at-a-time Predictor facade, and the zero-cold-start compile tier.

Serve-smoke lane:   python tools/serve_probe.py --serve-smoke \
                        [--json-out PATH]
  (tier-1 CI: tiny-MLP on the CPU backend — the batched
  ``serving.InferenceEngine`` vs a sequential ``Predictor.forward``
  loop, interleaved best-of timing. Gates: batched sustained
  throughput >= 3x unbatched at max_batch >= 8, and EXACTLY one
  compile per bucket signature via ``telemetry.programs()``. The JSON
  artifact banks both throughputs, the request p50/p95/p99 and the
  per-bucket program cards every round; the engine's measured serving
  data lands in the card corpus for the autotuner.)

Warm-smoke lane:    python tools/serve_probe.py --warm-smoke \
                        [--json-out PATH]
  (tier-1 CI for the PERSISTED compile cache, ISSUE 6: two fresh
  processes construct the same serving engine over one shared
  ``MXNET_COMPILE_CACHE`` dir. The first (cold) compiles and stores
  every bucket program; the second (warm) must register ZERO
  ``jit_compile`` spans, >= bucket-count deserialize hits, produce
  bit-identical outputs, and start up inside the in-run recalibrated
  ratio gate — the compile share the cold leg's own spans prove the
  warm leg skips, with margin, clamped to [0.25x, 0.85x] of cold.)

Chaos-smoke lane:   python tools/serve_probe.py --chaos-smoke \
                        [--json-out PATH]
  (tier-1 CI for the OVERLOAD-CONTROL path, ISSUE 7: the engine runs
  an open-loop offered-load ladder up to 2x its measured capacity with
  ``MXNET_FAULTS``-style injected dispatch faults (a per-dispatch
  delay throttling capacity + probabilistic raises exercising the
  retry budget), a bounded admission queue and per-request deadlines.
  Gates: ZERO hung futures (every submitted future resolves), shed
  counters > 0 at 2x offered load, admitted-request p99 <= the
  configured deadline, and the injected-fault telemetry counter equals
  the registry's exact fire count.)

Postmortem-smoke lane:  python tools/serve_probe.py --postmortem-smoke \
                            [--json-out PATH]
  (tier-1 CI for the FLIGHT RECORDER, ISSUE 10: the chaos ladder runs
  with the metrics sampler on and an injected TERMINAL dispatch fault
  — ``dispatch:raise:first=K`` outlasting the retry budget, so one
  batch fails for good. Gates: a postmortem JSON appears in the flight
  dir, ``tools/flight_view.py`` parses it (and REJECTS a corrupted
  copy non-zero), the dump names the injected fault's site and exactly
  the dying batch's member req_ids, the sampler banked a non-empty
  time-series window, and the measured flight-recorder work — causal-
  id spans, events, sampler ticks — stays under the <2% telemetry
  overhead guard.)
"""
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu import compile_cache
from mxnet_tpu.predictor import Predictor
from mxnet_tpu.serving import InferenceEngine

D, C, HID = 16, 4, 64
N_REQ = 256
MAX_BATCH = 16
ROUNDS = 5
SPEEDUP_GATE = 3.0

# warm-smoke model: deep enough that XLA compile dominates a cold
# start (the tier this lane gates exists to delete that cost); the
# fixed startup work (bind, shape inference, rng key) is identical
# across the legs
WARM_LAYERS, WARM_HID, WARM_D = 32, 192, 32
WARM_MAX_BATCH = 32
# warm-smoke startup-ratio gate, recalibrated IN-RUN (ISSUE 14): the
# old absolute <=0.25x false-fails on share-throttled boxes (0.47x
# measured at seed there) where the python/infer overhead BOTH legs
# pay dwarfs the compile time the warm leg skips. Predict the
# achievable ratio from the COLD leg's own compile-span share —
# warm ~= cold - (trace+compile) + deserialize, so the ratio floor is
# 1 - compile_share — gate at WARM_GATE_MARGIN of that prediction
# (deserialize + noise headroom), clamped to [FLOOR, CAP]: a healthy
# compile-dominated box still gates at the old 0.25x strength, and no
# box ever passes without a REAL warm win. The fit-smoke gate (PR 6,
# tools/module_fit_probe.py) pioneered this recalibrate-from-the-
# oracle-leg's-own-accounting pattern.
WARM_RATIO_FLOOR = 0.25      # never demands better than the old gate
WARM_RATIO_CAP = 0.85        # always demands a real warm win
WARM_GATE_MARGIN = 1.4       # headroom over the span-predicted ratio


def _recalibrated_warm_gate(cold):
    """(predicted warm/cold ratio, gate) from the cold leg's banked
    compile/trace span seconds; (None, CAP) when the cold leg carries
    no usable accounting (the gate then only demands some win)."""
    startup = float(cold.get("startup_s") or 0.0)
    skipped = (float(cold.get("jit_compile_s") or 0.0)
               + float(cold.get("jit_trace_s") or 0.0))
    if startup <= 0 or skipped <= 0:
        return None, WARM_RATIO_CAP
    share = min(skipped / startup, 1.0)
    predicted = max(1.0 - share, 0.0)
    gate = min(WARM_RATIO_CAP,
               max(WARM_RATIO_FLOOR, predicted * WARM_GATE_MARGIN))
    return round(predicted, 3), round(gate, 3)


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=HID, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=C, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _params(symbol):
    rng = np.random.RandomState(0)
    shapes, _, _ = symbol.infer_shape_partial(data=(2, D))
    return {"arg:" + n: mx.nd.array(rng.normal(0, 0.1, s)
                                    .astype(np.float32))
            for n, s in zip(symbol.list_arguments(), shapes)
            if n not in ("data", "softmax_label")}


def serve_smoke(json_out=None, n_req=N_REQ, rounds=ROUNDS):
    # bank this lane's measured serving data into the card corpus
    # (engine.close() appends) so the autotuner has a trajectory even
    # on rounds where nothing else served traffic
    os.environ.setdefault("MXNET_CARD_CORPUS", os.path.join(
        os.environ.get("MXTPU_ARTIFACT_DIR", "/tmp/mxtpu_artifacts"),
        "card_corpus.jsonl"))
    sym = _mlp()
    params = _params(sym)
    rng = np.random.RandomState(1)
    reqs = [rng.normal(size=(1, D)).astype(np.float32)
            for _ in range(n_req)]

    pred = Predictor(sym, params, {"data": (1, D)})
    pred.forward(data=reqs[0])        # compile the unbatched signature
    pred.get_output(0).asnumpy()
    engine = InferenceEngine(sym, params, {"data": (1, D)},
                             max_batch=MAX_BATCH, max_wait_ms=1.0,
                             max_inflight=4)
    # the bucket cache as warmup built it — captured BEFORE the timed
    # windows (each window telemetry.reset() clears the registry; cards
    # re-register on dispatch, so the post-traffic registry only shows
    # the buckets the last window happened to use)
    cards = engine.program_cards()

    def unbatched_epoch():
        t0 = time.perf_counter()
        for x in reqs:
            pred.forward(data=x)
            pred.get_output(0).asnumpy()
        return time.perf_counter() - t0

    def batched_epoch():
        t0 = time.perf_counter()
        futs = [engine.submit(data=x) for x in reqs]
        for f in futs:
            f.result(timeout=300)
        return time.perf_counter() - t0

    # interleaved best-of (the module_fit_probe timing discipline:
    # back-to-back legs keep the RATIO honest under CI share drift; the
    # min converges on the dispatch floor under spike noise)
    dt_un = dt_b = float("inf")
    batched_window = {}
    was_enabled = telemetry.enabled()
    telemetry.enable()
    try:
        for _ in range(rounds):
            dt_un = min(dt_un, unbatched_epoch())
            telemetry.reset()
            dt = batched_epoch()
            if dt <= dt_b:
                dt_b = dt
                snap = telemetry.snapshot()
                batched_window = {
                    "counters": {k: v for k, v in snap["counters"].items()
                                 if k.startswith(("serving.",
                                                  "dispatch."))},
                    "spans": {k: v for k, v in snap["spans"].items()
                              if k in telemetry.SERVE_SPANS},
                    # _InstrumentedProgram._build times every program
                    # build as a jit_compile span — the engine dispatch
                    # path never touches the jit.compile COUNTER (that
                    # counts _GraphProgram entry-point lookups), so the
                    # span count is the one signal that catches a
                    # per-batch recompile inside the timed window
                    "jit_compiles": snap["spans"].get(
                        "jit_compile", {}).get("count", 0),
                }
    finally:
        if not was_enabled:
            telemetry.disable()

    lat = batched_window.get("spans", {}).get("serve_request", {})
    out = {
        "lane": "serve_smoke",
        "platform": jax.devices()[0].platform,
        "n_requests": n_req,
        "max_batch": MAX_BATCH,
        "buckets": engine.buckets,
        "unbatched_req_s": round(n_req / dt_un, 1),
        "batched_req_s": round(n_req / dt_b, 1),
        "serve_speedup": round(dt_un / dt_b, 2),
        "latency_ms": {k: lat.get(k)
                       for k in ("p50_ms", "p95_ms", "p99_ms")},
        "batch_fill": engine.stats()["batch_fill"],
        "telemetry": batched_window,
        "program_cards": {
            k: {kk: c.get(kk) for kk in
                ("kind", "signature", "flops", "peak_bytes",
                 "compile_ms", "dispatches")}
            for k, c in cards.items()},
        "compiles_per_bucket": round(len(cards) / len(engine.buckets), 2),
    }
    engine.close()
    # what the corpus-fed autotuner would plan from the recorded
    # trajectory (informational here; unit-tested in test_tuner.py)
    try:
        from mxnet_tpu.tuner import plan_serving
        out["autotune_plan"] = plan_serving(
            compile_cache.corpus_records(kind="serving"),
            max_batch=MAX_BATCH)
    except Exception:
        out["autotune_plan"] = None
    # the serving acceptance gates (ISSUE 5): exactly one compiled
    # program per bucket signature, ZERO compiles inside the timed
    # steady-state window (every dispatch a cache hit), and sustained
    # batched throughput >= SPEEDUP_GATE x the sequential Predictor loop
    try:
        assert len(cards) == len(engine.buckets), \
            ("compiles != buckets", sorted(cards), engine.buckets)
        assert batched_window.get("jit_compiles", -1) == 0, batched_window
        assert out["serve_speedup"] >= SPEEDUP_GATE, out["serve_speedup"]
        out["gates_passed"] = True
    except AssertionError:
        out["gates_passed"] = False
        raise
    finally:
        line = json.dumps(out)
        print(line, flush=True)
        if json_out:
            with open(json_out, "w") as f:
                f.write(line + "\n")
    return out


def _warm_mlp():
    data = mx.sym.Variable("data")
    net = data
    for i in range(WARM_LAYERS):
        net = mx.sym.FullyConnected(net, num_hidden=WARM_HID,
                                    name="wfc%d" % i)
        net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=C, name="whead")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def warm_child():
    """One process's leg of the warm-smoke A/B: construct (and warm up)
    the serving engine over the ambient ``MXNET_COMPILE_CACHE``, serve
    a fixed probe request, and report the startup wall next to the
    compile-vs-deserialize telemetry split. Cold or warm is decided
    entirely by what the cache dir already holds."""
    sym = _warm_mlp()
    rng = np.random.RandomState(0)
    shapes, _, _ = sym.infer_shape_partial(data=(2, WARM_D))
    params = {"arg:" + n: mx.nd.array(rng.normal(0, 0.05, s)
                                      .astype(np.float32))
              for n, s in zip(sym.list_arguments(), shapes)
              if n not in ("data", "softmax_label")}
    probe_req = rng.normal(size=(1, WARM_D)).astype(np.float32)
    telemetry.enable()
    telemetry.reset()
    t0 = time.perf_counter()
    engine = InferenceEngine(sym, params, {"data": (1, WARM_D)},
                             max_batch=WARM_MAX_BATCH, max_wait_ms=1.0,
                             max_inflight=4)
    startup_s = time.perf_counter() - t0
    outs = engine.submit(data=probe_req).result(timeout=120)
    snap = telemetry.snapshot()
    spans = {k: snap["spans"].get(k, {}).get("count", 0)
             for k in telemetry.COMPILE_SPANS}
    span_s = {k: round(snap["spans"].get(k, {}).get("total_ms", 0.0)
                       / 1e3, 4)
              for k in telemetry.COMPILE_SPANS}
    out = {
        "lane": "warm_child",
        "cache_dir": compile_cache.cache_dir(),
        "startup_s": round(startup_s, 3),
        "buckets": engine.buckets,
        "jit_trace_spans": spans["jit_trace"],
        "jit_compile_spans": spans["jit_compile"],
        "jit_deserialize_spans": spans["jit_deserialize"],
        # wall SECONDS per compile-tier span — the cold leg's own
        # accounting the in-run gate recalibration predicts from
        "jit_trace_s": span_s["jit_trace"],
        "jit_compile_s": span_s["jit_compile"],
        "jit_deserialize_s": span_s["jit_deserialize"],
        "compile_cache": {k: v for k, v in snap["counters"].items()
                          if k.startswith("compile_cache.")},
        "sources": sorted({c.get("source") for c in
                           snap["programs"].values() if c.get("source")}),
        # bit-exactness probe: the warm (deserialized) leg must produce
        # exactly what the cold (compiled) leg produced
        "probe_sum": float(np.float64(outs[0].astype(np.float64).sum())),
    }
    engine.close()
    print(json.dumps(out), flush=True)
    return out


def warm_smoke(json_out=None):
    """The warm-start acceptance lane (ISSUE 6): two FRESH processes
    over one shared compile-cache dir. Process 1 (cold) populates the
    store; process 2 (warm) must skip XLA entirely — zero
    ``jit_compile`` spans, deserialize hits >= bucket count — match
    the cold outputs bit-for-bit, and start inside the IN-RUN
    recalibrated ratio gate (the compile share the cold leg's own
    spans say the warm leg can skip, with margin, clamped to
    [0.25, 0.85] — see ``_recalibrated_warm_gate``, ISSUE 14)."""
    cache = tempfile.mkdtemp(prefix="mxtpu_warm_smoke_cc_")
    legs = {}
    try:
        for leg in ("cold", "warm"):
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       MXNET_COMPILE_CACHE=cache)
            env.pop("XLA_FLAGS", None)       # single-device lane
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--warm-child"],
                stdout=subprocess.PIPE, text=True, timeout=420, env=env)
            parsed = None
            for line in reversed(proc.stdout.splitlines()):
                if line.strip().startswith("{"):
                    parsed = json.loads(line)
                    break
            assert proc.returncode == 0 and parsed is not None, \
                ("warm-smoke %s child failed" % leg, proc.returncode,
                 proc.stdout[-2000:])
            legs[leg] = parsed
    finally:
        shutil.rmtree(cache, ignore_errors=True)
    cold, warm = legs["cold"], legs["warm"]
    n_buckets = len(cold["buckets"])
    predicted, gate = _recalibrated_warm_gate(cold)
    out = {
        "lane": "warm_smoke",
        "platform": jax.devices()[0].platform,
        "n_buckets": n_buckets,
        "cold": cold,
        "warm": warm,
        "warm_vs_cold": round(warm["startup_s"] / cold["startup_s"], 3)
        if cold["startup_s"] else None,
        # the in-run recalibrated gate + its inputs, banked so a lane
        # failure is diagnosable from the artifact alone
        "ratio_gate": gate,
        "predicted_warm_vs_cold": predicted,
        "ratio_gate_floor": WARM_RATIO_FLOOR,
        "ratio_gate_cap": WARM_RATIO_CAP,
        "ratio_gate_margin": WARM_GATE_MARGIN,
    }
    try:
        # cold leg: every bucket compiled AND persisted
        assert cold["jit_compile_spans"] >= n_buckets, cold
        assert cold["compile_cache"].get(
            "compile_cache.store", 0) >= n_buckets, cold
        # warm leg: ZERO XLA compiles, every program a deserialize hit
        assert warm["jit_compile_spans"] == 0, warm
        assert warm["compile_cache"].get(
            "compile_cache.hit", 0) >= n_buckets, warm
        assert warm["jit_deserialize_spans"] >= n_buckets, warm
        assert warm["sources"] == ["disk_cache"], warm
        # the deserialized programs compute the SAME function
        assert warm["probe_sum"] == cold["probe_sum"], (cold, warm)
        # and the whole point: the warm start is the fraction of the
        # cold wall this box can actually show (the compile share the
        # warm leg skips, with margin — clamped so a compile-dominated
        # box still gates at the old 0.25x strength)
        assert out["warm_vs_cold"] <= out["ratio_gate"], \
            (out["warm_vs_cold"], out["ratio_gate"], predicted)
        out["gates_passed"] = True
    except AssertionError:
        out["gates_passed"] = False
        raise
    finally:
        line = json.dumps(out)
        print(line, flush=True)
        if json_out:
            with open(json_out, "w") as f:
                f.write(line + "\n")
    return out


# chaos-smoke knobs: the injected per-dispatch DELAY throttles the CPU
# lane's capacity to something an open-loop schedule can actually
# overload inside a CI window; the RAISE probability exercises the
# retry budget; the bounded queue + deadline are what 2x offered load
# then slams into
CHAOS_DELAY_MS = 4.0
CHAOS_RAISE_P = 0.12
CHAOS_SEED = 11
CHAOS_DEADLINE_MS = 150.0
CHAOS_QUEUE_ROWS = 48
CHAOS_N_REQ = 384
CHAOS_SPEC = "dispatch:delay=%g" % CHAOS_DELAY_MS
CHAOS_SPEC_FAULTY = CHAOS_SPEC + \
    ";dispatch:raise:p=%g,seed=%d" % (CHAOS_RAISE_P, CHAOS_SEED)


def chaos_smoke(json_out=None, n_req=CHAOS_N_REQ):
    """The fault-tolerant-serving acceptance lane (ISSUE 7)."""
    from mxnet_tpu import faults
    from mxnet_tpu.serving import (DeadlineExceeded, QueueOverflow,
                                   CircuitOpen)
    sym = _mlp()
    params = _params(sym)
    rng = np.random.RandomState(1)
    reqs = [rng.normal(size=(1, D)).astype(np.float32)
            for _ in range(64)]
    telemetry.enable()
    engine = InferenceEngine(
        sym, params, {"data": (1, D)}, max_batch=MAX_BATCH,
        max_wait_ms=1.0, max_inflight=4,
        max_queue_rows=CHAOS_QUEUE_ROWS,
        deadline_ms=CHAOS_DEADLINE_MS, overload="shed",
        retry_budget=2, retry_backoff_ms=1.0,
        breaker_threshold=50)          # tripping would mask the ladder
    out = {
        "lane": "chaos_smoke",
        "platform": jax.devices()[0].platform,
        "n_requests": n_req,
        "max_batch": MAX_BATCH,
        "deadline_ms": CHAOS_DEADLINE_MS,
        "max_queue_rows": CHAOS_QUEUE_ROWS,
        "fault_spec": CHAOS_SPEC_FAULTY,
        "offered_loads": {},
    }
    try:
        # capacity under the injected dispatch DELAY (the throttle is
        # part of the chaos environment, so the ladder's fractions are
        # fractions of the environment's real capacity)
        faults.configure(CHAOS_SPEC)
        t0 = time.perf_counter()
        done = 0
        while done < n_req // 2:
            # closed-loop waves under the admission bound: capacity is
            # what the throttled engine sustains, measured without
            # tripping the very shedding the ladder exists to test
            wave = min(CHAOS_QUEUE_ROWS // 2, n_req // 2 - done)
            futs = [engine.submit(data=reqs[i % len(reqs)])
                    for i in range(wave)]
            engine.flush()
            for f in futs:
                f.result(timeout=120)
            done += wave
        capacity = done / (time.perf_counter() - t0)
        out["capacity_req_s"] = round(capacity, 1)

        # open-loop ladder with raises on top of the delay; latency is
        # measured from the SCHEDULED arrival (coordinated-omission-
        # free), admission sheds raise synchronously at submit
        faults.configure(CHAOS_SPEC_FAULTY)
        for frac in (1.0, 2.0):
            faults.reset_counts()
            telemetry.reset()
            rate = capacity * frac
            pend, lats = [], []
            admission_shed = 0
            t0 = time.perf_counter()
            for i in range(n_req):
                sched = t0 + i / rate
                now = time.perf_counter()
                if sched > now:
                    time.sleep(sched - now)
                try:
                    fut = engine.submit(data=reqs[i % len(reqs)])
                except (QueueOverflow, CircuitOpen):
                    admission_shed += 1
                    continue
                fut.add_done_callback(
                    lambda f, s=sched: lats.append(
                        (time.perf_counter() - s) * 1e3)
                    if not f.exception() else None)
                pend.append(fut)
            engine.flush()
            ok = shed = failed = hung = 0
            for fut in pend:
                try:
                    fut.result(timeout=120)
                    ok += 1
                except DeadlineExceeded:
                    shed += 1
                except Exception:
                    failed += 1
            hung = sum(0 if f.done() else 1 for f in pend)
            lats.sort()
            pct = telemetry._percentile
            st = engine.stats()
            fired = faults.counts().get("dispatch", {}).get("fired", 0)
            injected = telemetry.counters().get(
                "faults.injected.dispatch", 0)
            out["offered_loads"]["%.1f" % frac] = {
                "offered_req_s": round(rate, 1),
                "submitted": len(pend),
                "ok": ok,
                "shed_admission": admission_shed,
                "shed_deadline": shed,
                "failed": failed,
                "hung": hung,
                "shed_rate": round(
                    (admission_shed + shed) / float(n_req), 4),
                "admitted_latency_ms": {
                    "p50": round(pct(lats, 50), 3),
                    "p95": round(pct(lats, 95), 3),
                    "p99": round(pct(lats, 99), 3),
                } if lats else None,
                "retries": st["retries"],
                "dispatch_failures": st["dispatch_failures"],
                "breaker": st["breaker"],
                "faults_fired": fired,
                "faults_injected_counter": injected,
                "queued_rows": st["queued_rows"],
            }
            print(json.dumps(dict(out, partial=True)), flush=True)
    finally:
        faults.clear()
        engine.close()
    out["stats"] = {k: v for k, v in engine.stats().items()
                    if k in ("requests", "resolved", "shed_requests",
                             "shed_rows", "shed_by_cause", "retries",
                             "dispatch_failures", "breaker")}
    hot = out["offered_loads"]["2.0"]
    try:
        # the ISSUE 7 chaos gates, all deterministic:
        # 1. zero hung futures at 2x offered load under injected faults
        assert hot["hung"] == 0, hot
        # 2. the engine SHED (bounded queue / deadlines actually bit)
        assert hot["shed_admission"] + hot["shed_deadline"] > 0, hot
        # 3. admitted requests kept their deadline promise
        assert hot["admitted_latency_ms"]["p99"] <= CHAOS_DEADLINE_MS, hot
        # 4. exact injection accounting: telemetry == registry, > 0
        assert hot["faults_fired"] > 0, hot
        assert hot["faults_injected_counter"] == hot["faults_fired"], hot
        # 5. the bounded queue held
        assert hot["queued_rows"] <= CHAOS_QUEUE_ROWS, hot
        # 6. every admitted request resolved one way or the other
        assert hot["ok"] + hot["shed_deadline"] + hot["failed"] \
            == hot["submitted"], hot
        out["gates_passed"] = True
    except AssertionError:
        out["gates_passed"] = False
        raise
    finally:
        line = json.dumps(out)
        print(line, flush=True)
        if json_out:
            with open(json_out, "w") as f:
                f.write(line + "\n")
    return out


# postmortem-smoke knobs: the raise rule must outlast the retry budget
# on ONE batch (initial attempt + retry_budget retries all land inside
# first=K) so the failure is TERMINAL; the delay keeps the CPU lane's
# capacity overloadable like the chaos lane
PM_RETRY_BUDGET = 1
PM_RAISE_FIRST = PM_RETRY_BUDGET + 2     # every attempt of batch 1 + slack
PM_SPEC_TERMINAL = "%s;dispatch:raise:first=%d" % (CHAOS_SPEC,
                                                   PM_RAISE_FIRST)
PM_SAMPLER_MS = 25.0
PM_N_REQ = 192
PM_OVERHEAD_FRAC = 0.02


def postmortem_smoke(json_out=None, n_req=PM_N_REQ):
    """The flight-recorder acceptance lane (ISSUE 10)."""
    import subprocess as _subprocess
    from mxnet_tpu import faults, flight
    sym = _mlp()
    params = _params(sym)
    rng = np.random.RandomState(1)
    reqs = [rng.normal(size=(1, D)).astype(np.float32)
            for _ in range(64)]
    art_dir = os.environ.get("MXTPU_ARTIFACT_DIR", "/tmp/mxtpu_artifacts")
    fdir = os.path.join(art_dir, "flight")
    os.makedirs(fdir, exist_ok=True)
    for name in os.listdir(fdir):          # this RUN's dumps only
        if name.startswith("postmortem-") and name.endswith(".json"):
            os.unlink(os.path.join(fdir, name))
    telemetry.enable()
    telemetry.reset()
    flight.configure(fdir)
    flight.series_clear()
    flight.sampler_start(PM_SAMPLER_MS)
    out = {
        "lane": "postmortem_smoke",
        "platform": jax.devices()[0].platform,
        "n_requests": n_req,
        "max_batch": MAX_BATCH,
        "fault_spec": PM_SPEC_TERMINAL,
        "flight_dir": fdir,
        "sampler_interval_ms": PM_SAMPLER_MS,
    }
    engine = InferenceEngine(
        sym, params, {"data": (1, D)}, max_batch=MAX_BATCH,
        max_wait_ms=1.0, max_inflight=4,
        max_queue_rows=CHAOS_QUEUE_ROWS,
        deadline_ms=CHAOS_DEADLINE_MS, overload="shed",
        retry_budget=PM_RETRY_BUDGET, retry_backoff_ms=1.0,
        breaker_threshold=0)       # the TERMINAL failure is the story,
                                   # not a breaker fast-fail masking it
    try:
        # phase 1: the terminal fault — one batch's every attempt
        # raises, its futures fail, the flight recorder dumps
        faults.configure(PM_SPEC_TERMINAL)
        doomed = [engine.submit(data=reqs[i % len(reqs)])
                  for i in range(6)]
        engine.flush()
        failed_rids = []
        for f in doomed:
            try:
                f.result(timeout=120)
            except Exception:
                failed_rids.append(f.req_id)
        out["failed_requests"] = len(failed_rids)
        out["failed_req_ids"] = sorted(failed_rids)

        # phase 2: the chaos ladder under the delay throttle (faults
        # still active minus the spent raise rule) — closed-loop waves
        # like the chaos capacity phase; zero hung futures gates the
        # recorder added no new stalls
        faults.configure(CHAOS_SPEC)
        t0 = time.perf_counter()
        hung = 0
        done = 0
        while done < n_req:
            wave = min(CHAOS_QUEUE_ROWS // 2, n_req - done)
            futs = [engine.submit(data=reqs[i % len(reqs)])
                    for i in range(wave)]
            engine.flush()
            for f in futs:
                try:
                    f.result(timeout=120)
                except Exception:
                    pass
                if not f.done():
                    hung += 1
            done += wave
        wall = time.perf_counter() - t0
        out["ladder_req_s"] = round(done / wall, 1)
        out["hung"] = hung

        # phase 3: the flight-recorder work model (the <2% guard with
        # the SAMPLER and CAUSAL IDS on): count the recorder ops the
        # ladder actually performed, microbenchmark their unit costs
        # (min over reps — throttle only inflates), and bound
        # ops x cost against the measured wall
        span_ops = sum(telemetry.span_count(n)
                       for n in telemetry.span_stats())
        # one counter_inc per event regardless of the value added:
        # byte-valued counters (pad_bytes, h2d_bytes) are one op per
        # event too, and their event counts already ride in the
        # sibling unit counters — summing their VALUES would model
        # each byte as a registry op
        counter_ops = sum(v for k, v in telemetry.counters().items()
                          if k.startswith(("serving.", "dispatch.",
                                           "faults.", "transfer."))
                          and not k.endswith("_bytes"))
        event_ops = len(telemetry.events())
        ticks = len(flight.series())

        def op_cost(fn, iters=4000, reps=5):
            best = float("inf")
            for _ in range(reps):
                t1 = time.perf_counter_ns()
                for _ in range(iters):
                    fn()
                best = min(best, (time.perf_counter_ns() - t1) / iters)
            return best / 1e9

        ctx = {"req_id": 1}

        def one_span():
            with telemetry.span("_pm_probe", ctx=ctx):
                pass

        span_s = op_cost(one_span)
        counter_s = op_cost(
            lambda: telemetry.counter_inc("_pm_probe"))   # mxlint: disable=registry-consistency -- microbench probe counter (cost measurement), never a production metric

        event_s = op_cost(
            lambda: telemetry.record_event("_pm_probe", req_id=1))
        tick_s = op_cost(lambda: flight._build_sample({}, 0.025),
                         iters=200)
        overhead_s = (span_ops * span_s + counter_ops * counter_s
                      + event_ops * event_s + ticks * tick_s)
        out["overhead"] = {
            "span_ops": span_ops, "counter_ops": counter_ops,
            "event_ops": event_ops, "sampler_ticks": ticks,
            "span_us": round(span_s * 1e6, 3),
            "counter_us": round(counter_s * 1e6, 3),
            "event_us": round(event_s * 1e6, 3),
            "tick_us": round(tick_s * 1e6, 3),
            "work_ms": round(overhead_s * 1e3, 3),
            "wall_s": round(wall, 3),
            "frac": round(overhead_s / wall, 5),
            "gate": PM_OVERHEAD_FRAC,
        }
    finally:
        faults.clear()
        flight.sampler_stop()
        engine.close()
        flight.configure(None)

    out["series_window"] = flight.series_window(60)
    pm_path = flight.last_postmortem()
    out["postmortem_path"] = pm_path

    view = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "flight_view.py")

    def run_view(path, extra=()):
        return _subprocess.run(
            [sys.executable, view, path, *extra],
            stdout=_subprocess.PIPE, stderr=_subprocess.PIPE,
            text=True, timeout=60)

    try:
        # gate 1: the terminal fault produced a postmortem that PARSES
        assert pm_path is not None and os.path.exists(pm_path), pm_path
        proc = run_view(pm_path, ("--json",))
        assert proc.returncode == 0, proc.stderr[-1000:]
        summary = json.loads(proc.stdout)
        out["view_summary"] = {k: summary.get(k) for k in
                               ("reason", "exception", "extra",
                                "n_events", "n_spans", "n_series")}
        # gate 2: the dump names the injected fault's site...
        assert summary["reason"] == "serving_dispatch_failure", summary
        assert summary["exception"]["fault_site"] == "dispatch", summary
        # ...and exactly the dying batch's member req_ids
        assert failed_rids, "terminal fault failed no requests"
        assert sorted(summary["extra"]["req_ids"]) \
            == sorted(failed_rids), (summary["extra"], failed_rids)
        # gate 3: a corrupted dump is REJECTED non-zero
        bad = pm_path + ".corrupt"
        with open(pm_path) as f:
            with open(bad, "w") as g:
                g.write(f.read()[:200])   # truncated JSON
        proc_bad = run_view(bad)
        os.unlink(bad)
        assert proc_bad.returncode != 0, "flight_view accepted garbage"
        # gate 4: the sampler banked a real time-series window
        assert out["series_window"]["n"] > 0, out["series_window"]
        # gate 5: zero hung futures, and the recorder work fits the
        # existing <2% telemetry overhead guard
        assert out["hung"] == 0, out
        assert out["overhead"]["frac"] < PM_OVERHEAD_FRAC, out["overhead"]
        out["gates_passed"] = True
    except AssertionError:
        out["gates_passed"] = False
        raise
    finally:
        line = json.dumps(out)
        print(line, flush=True)
        if json_out:
            with open(json_out, "w") as f:
                f.write(line + "\n")
    return out


# decode-smoke knobs: the continuous-batching decode engine
# (mxnet_tpu/decode.py) vs wave-synchronized static whole-batch decode
# through the SAME engine and programs. The workload skews generation
# lengths (1 long per wave of 8) because that skew is WHY continuous
# batching exists: static batching pays the longest member's steps for
# every wave while finished lanes idle; slot-level admission keeps the
# pool full. On the dispatch-dominated CPU backend the dispatch-count
# ratio is the throughput ratio, so the 2x gate is conservative
# (measured ~2.5-3x; a real accelerator with wide decode batches gains
# more).
DEC_SLOTS = 8
DEC_WAVES = 6
DEC_SHORT, DEC_LONG = 4, 40        # generated tokens per sequence kind
DEC_PROMPT = 4
DEC_ROUNDS = 3
DECODE_SPEEDUP_GATE = 2.0
DEC_MP = 8                         # mp-sharded KV-cache leg mesh width


def _decode_cell(heads=8):
    from mxnet_tpu.decode import AttentionDecodeCell
    return AttentionDecodeCell(vocab=256, embed=64, heads=heads,
                               head_dim=16, max_len=64)


def decode_smoke(json_out=None):
    """Continuous-batching decode acceptance lane (tier-1 CI).

    Three legs, one artifact (``decode_smoke.json``):

    * correctness — slot-batched decode is BIT-EXACT (tokens and
      logits) against one-at-a-time decode through the same engine;
    * throughput — open-loop skewed-length stream through the
      continuous engine vs wave-synchronized static whole-batch
      submission of the same work, interleaved best-of; gates
      continuous >= 2x static tokens/s and ZERO ``jit_compile`` spans
      anywhere in the timed windows (per-token p50/p95/p99 ride along,
      coordinated-omission-free: the step spans time the dispatch
      cadence itself, all work is queued up front, so a slow step
      cannot hide follow-on latency);
    * mp-sharded KV cache — under ``DECODE_PARTITION_RULES`` on a
      1x{mp} mesh the cache pool's committed ledger bytes read exactly
      1/mp of the same pool replicated onto that mesh.
    """
    from mxnet_tpu.decode import DecodeEngine
    from mxnet_tpu.parallel.ring_attention import DECODE_PARTITION_RULES

    art_dir = os.environ.get("MXTPU_ARTIFACT_DIR", "/tmp/mxtpu_artifacts")
    os.environ.setdefault("MXNET_CARD_CORPUS",
                          os.path.join(art_dir, "card_corpus.jsonl"))
    rng = np.random.RandomState(0)
    out = {
        "lane": "decode_smoke",
        "platform": jax.devices()[0].platform,
        "devices": jax.device_count(),
        "slots": DEC_SLOTS,
        "waves": DEC_WAVES,
        "gen_short": DEC_SHORT,
        "gen_long": DEC_LONG,
        "speedup_gate": DECODE_SPEEDUP_GATE,
    }

    def prompt():
        return rng.randint(1, 255, DEC_PROMPT).astype(np.int32)

    # -- leg 1: bit-exact slot-batched vs one-at-a-time ---------------------
    cell = _decode_cell()
    eng = DecodeEngine(cell, cell.init_params(1), slots=4,
                       max_prompt_len=8, max_new_tokens=8,
                       keep_logits=True)
    probes = [prompt() for _ in range(4)]
    serial = [eng.generate(p) for p in probes]
    batched = [f.result(timeout=300)
               for f in [eng.submit(p) for p in probes]]
    bit_exact = all(
        a.tokens == b.tokens and np.array_equal(a.logits, b.logits)
        for a, b in zip(serial, batched))
    out["bit_exact"] = bit_exact
    eng.close()

    # -- leg 2: continuous vs static whole-batch throughput -----------------
    eng = DecodeEngine(cell, cell.init_params(1), slots=DEC_SLOTS,
                       max_prompt_len=8, max_new_tokens=DEC_LONG)
    # one wave = a slot pool's worth of sequences, one long member
    waves = [[(prompt(), DEC_LONG if s == 0 else DEC_SHORT)
              for s in range(DEC_SLOTS)] for _ in range(DEC_WAVES)]
    total_tokens = sum(n for wave in waves for _, n in wave)
    # continuous submission order: longs first, so their long tails
    # overlap the short churn instead of trailing an empty pool
    stream = sorted((seq for wave in waves for seq in wave),
                    key=lambda s: -s[1])

    def static_epoch():
        """Wave-synchronized static whole-batch decode: the next wave
        enters only when the whole previous wave finished — finished
        lanes idle exactly as a slotless whole-batch decoder's would
        (same dispatch count: the longest member's steps per wave)."""
        t0 = time.perf_counter()
        for wave in waves:
            futs = [eng.submit(p, max_new_tokens=n) for p, n in wave]
            for f in futs:
                f.result(timeout=300)
        return time.perf_counter() - t0

    def continuous_epoch():
        """Open-loop: every sequence queued up front; per-step slot
        admission keeps the pool full until the work runs dry."""
        t0 = time.perf_counter()
        futs = [eng.submit(p, max_new_tokens=n) for p, n in stream]
        for f in futs:
            f.result(timeout=300)
        return time.perf_counter() - t0

    was_enabled = telemetry.enabled()
    telemetry.enable()
    dt_st = dt_ct = float("inf")
    jit_compiles = 0
    window = {}
    try:
        for _ in range(DEC_ROUNDS):
            telemetry.reset()
            dt_st = min(dt_st, static_epoch())
            jit_compiles += telemetry.span_stats().get(
                "jit_compile", {}).get("count", 0)
            telemetry.reset()
            dt = continuous_epoch()
            snap = telemetry.snapshot()
            jit_compiles += snap["spans"].get(
                "jit_compile", {}).get("count", 0)
            if dt <= dt_ct:
                dt_ct = dt
                window = {
                    "counters": {k: v for k, v in
                                 snap["counters"].items()
                                 if k.startswith("decode.")},
                    "spans": {k: v for k, v in snap["spans"].items()
                              if k in telemetry.DECODE_SPANS},
                }
    finally:
        if not was_enabled:
            telemetry.disable()
    stats = eng.stats()
    eng.close()

    tok_lat = window.get("spans", {}).get("serve_decode_step", {})
    out.update({
        "total_tokens": total_tokens,
        "static_tok_s": round(total_tokens / dt_st, 1),
        "continuous_tok_s": round(total_tokens / dt_ct, 1),
        "decode_speedup": round(dt_st / dt_ct, 2),
        "token_latency_ms": {k: tok_lat.get(k)
                             for k in ("p50_ms", "p95_ms", "p99_ms")},
        "jit_compiles_timed": jit_compiles,
        "kv_cache_bytes": stats["kv_cache_bytes"],
        "kv_cache_bytes_per_slot": stats["kv_cache_bytes_per_slot"],
        "telemetry": window,
    })

    # -- leg 3: the mp-sharded KV cache on the rule engine -------------------
    if jax.device_count() >= DEC_MP:
        ctxs = [mx.context.cpu(i) for i in range(DEC_MP)]
        axes = {"dp": 1, "mp": DEC_MP}
        mp_cell = _decode_cell(heads=DEC_MP)
        sharded = DecodeEngine(mp_cell, mp_cell.init_params(1),
                               slots=4, max_prompt_len=8,
                               max_new_tokens=8,
                               partition_rules=DECODE_PARTITION_RULES,
                               mesh_axes=axes, contexts=ctxs)
        mp_tokens = sharded.generate(prompt()).tokens
        sharded_bytes = sharded.stats()["kv_cache_bytes"]
        sharded.close()
        repl = DecodeEngine(mp_cell, mp_cell.init_params(1), slots=4,
                            max_prompt_len=8, max_new_tokens=8,
                            partition_rules=[], mesh_axes=axes,
                            contexts=ctxs, warmup=False)
        repl_bytes = repl.stats()["kv_cache_bytes"]
        repl.close()
        out["mp"] = {
            "mesh": axes,
            "sharded_kv_bytes": sharded_bytes,
            "replicated_kv_bytes": repl_bytes,
            "ledger_ratio": round(repl_bytes / sharded_bytes, 2)
            if sharded_bytes else None,
            "decoded_tokens": len(mp_tokens),
        }
    else:
        out["mp"] = None

    # the ISSUE 16 decode acceptance gates, all deterministic except
    # the (conservative) throughput ratio:
    try:
        assert bit_exact, "slot-batched decode diverged from unbatched"
        assert jit_compiles == 0, \
            ("compiles inside the timed windows", jit_compiles)
        assert out["decode_speedup"] >= DECODE_SPEEDUP_GATE, \
            out["decode_speedup"]
        assert out["mp"] is not None, "mp leg needs %d devices" % DEC_MP
        assert out["mp"]["replicated_kv_bytes"] \
            == DEC_MP * out["mp"]["sharded_kv_bytes"], out["mp"]
        assert out["mp"]["decoded_tokens"] == 8, out["mp"]
        out["gates_passed"] = True
    except AssertionError:
        out["gates_passed"] = False
        raise
    finally:
        line = json.dumps(out)
        print(line, flush=True)
        if json_out:
            with open(json_out, "w") as f:
                f.write(line + "\n")
    return out


def _respawn_with_mesh(n):
    """Re-exec this probe with an ``n``-device forced host platform.
    The decode lane's mp leg needs the multi-device CPU mesh, and
    XLA_FLAGS must be set BEFORE the jax backend initialises — which
    module import already did — so a direct invocation without the
    flag bounces through one child process. Returns the child's exit
    code."""
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=%d"
                        % n).strip()
    env["MXTPU_PROBE_RESPAWNED"] = "1"
    proc = subprocess.run([sys.executable,
                           os.path.abspath(__file__)] + sys.argv[1:],
                          env=env)
    return proc.returncode


def _json_out_arg():
    if "--json-out" not in sys.argv:
        return None
    i = sys.argv.index("--json-out") + 1
    if i >= len(sys.argv) or sys.argv[i].startswith("--"):
        raise SystemExit("--json-out: missing output path")
    return sys.argv[i]


if __name__ == "__main__":
    if "--serve-smoke" in sys.argv:
        serve_smoke(json_out=_json_out_arg())
    elif "--warm-smoke" in sys.argv:
        warm_smoke(json_out=_json_out_arg())
    elif "--warm-child" in sys.argv:
        warm_child()
    elif "--chaos-smoke" in sys.argv:
        chaos_smoke(json_out=_json_out_arg())
    elif "--postmortem-smoke" in sys.argv:
        postmortem_smoke(json_out=_json_out_arg())
    elif "--decode-smoke" in sys.argv:
        if jax.device_count() < DEC_MP \
                and not os.environ.get("MXTPU_PROBE_RESPAWNED"):
            sys.exit(_respawn_with_mesh(DEC_MP))
        decode_smoke(json_out=_json_out_arg())
    else:
        raise SystemExit("usage: serve_probe.py --serve-smoke|"
                         "--warm-smoke|--chaos-smoke|--postmortem-smoke|"
                         "--decode-smoke [--json-out PATH]")
