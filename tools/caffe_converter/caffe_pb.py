"""Minimal Caffe protobuf access — no compiled schema.

Parity target: reference ``tools/caffe_converter`` (which compiles
``caffe.proto`` and imports caffe_pb2). This build instead ships two
small self-contained pieces:

- a protobuf **text-format** parser for ``.prototxt`` network
  definitions (nested ``key { ... }`` blocks and ``key: value`` pairs),
- a protobuf **wire-format** reader extracting exactly the fields the
  converter needs from a binary ``.caffemodel``: layers (V2 field 100 /
  V1 field 2), their name/type and blobs (shape + float data).

Both are format-level implementations written against the public
protobuf encoding spec; no schema file is vendored.
"""
from __future__ import annotations

import struct


# ---------------------------------------------------------------------------
# text-format (.prototxt)
# ---------------------------------------------------------------------------

class Msg(dict):
    """A parsed text-format message: repeated fields become lists."""

    def add(self, key, value):
        if key in self:
            if not isinstance(self[key], list):
                self[key] = [self[key]]
            self[key].append(value)
        else:
            self[key] = value

    def all(self, key):
        v = self.get(key, [])
        return v if isinstance(v, list) else [v]

    def one(self, key, default=None):
        v = self.get(key, default)
        return v[0] if isinstance(v, list) else v


def _tokenize(text):
    out = []
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        line = line.replace("{", " { ").replace("}", " } ")
        i = 0
        while i < len(line):
            ch = line[i]
            if ch.isspace():
                i += 1
                continue
            if ch in "{}":
                out.append(ch)
                i += 1
                continue
            if ch in "\"'":
                j = line.index(ch, i + 1)
                out.append(line[i:j + 1])
                i = j + 1
                continue
            j = i
            while j < len(line) and not line[j].isspace() \
                    and line[j] not in "{}":
                j += 1
            out.append(line[i:j])
            i = j
    return out


def _convert_scalar(tok):
    if tok and tok[0] in "\"'":
        return tok[1:-1]
    low = tok.lower()
    if low == "true":
        return True
    if low == "false":
        return False
    try:
        return int(tok)
    except ValueError:
        pass
    try:
        return float(tok)
    except ValueError:
        return tok


def parse_prototxt(text):
    """Parse protobuf text format into a tree of :class:`Msg`."""
    tokens = _tokenize(text)
    pos = 0

    def parse_block():
        nonlocal pos
        msg = Msg()
        while pos < len(tokens):
            tok = tokens[pos]
            if tok == "}":
                pos += 1
                return msg
            key = tok.rstrip(":")
            pos += 1
            if pos < len(tokens) and tokens[pos] == "{":
                pos += 1
                msg.add(key, parse_block())
            else:
                msg.add(key, _convert_scalar(tokens[pos]))
                pos += 1
        return msg

    return parse_block()


# ---------------------------------------------------------------------------
# wire-format (.caffemodel)
# ---------------------------------------------------------------------------

def _read_varint(buf, i):
    shift = 0
    out = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def iter_fields(buf, start=0, end=None):
    """Yield (field_number, wire_type, value-or-span) over a message."""
    i = start
    end = len(buf) if end is None else end
    while i < end:
        tag, i = _read_varint(buf, i)
        field, wt = tag >> 3, tag & 7
        if wt == 0:                       # varint
            val, i = _read_varint(buf, i)
            yield field, wt, val
        elif wt == 1:                     # 64-bit
            yield field, wt, buf[i:i + 8]
            i += 8
        elif wt == 2:                     # length-delimited
            n, i = _read_varint(buf, i)
            yield field, wt, buf[i:i + n]
            i += n
        elif wt == 5:                     # 32-bit
            yield field, wt, buf[i:i + 4]
            i += 4
        else:
            raise ValueError("unsupported wire type %d" % wt)


def _parse_blob(buf):
    """BlobProto: data=5 (repeated float), shape=7 (BlobShape.dim=1),
    legacy num/channels/height/width = 1..4."""
    data = []
    shape = []
    legacy = {}
    for field, wt, val in iter_fields(buf):
        if field == 5:
            if wt == 2:                    # packed floats
                data.extend(struct.unpack("<%df" % (len(val) // 4), val))
            else:
                data.append(struct.unpack("<f", val)[0])
        elif field == 7 and wt == 2:       # BlobShape
            for f2, w2, v2 in iter_fields(val):
                if f2 == 1:
                    if w2 == 2:            # packed int64
                        j = 0
                        while j < len(v2):
                            d, j = _read_varint(v2, j)
                            shape.append(d)
                    else:
                        shape.append(v2)
        elif field in (1, 2, 3, 4) and wt == 0:
            legacy[field] = val
    if not shape and legacy:
        shape = [legacy.get(i, 1) for i in (1, 2, 3, 4)]
    return shape, data


def parse_caffemodel(buf):
    """-> list of {name, type, blobs: [(shape, data), ...]} from a binary
    NetParameter. Supports V2 layers (field 100) and V1 (field 2)."""
    layers = []
    for field, wt, val in iter_fields(buf):
        if wt != 2 or field not in (100, 2):
            continue
        name = ""
        ltype = None
        blobs = []
        # LayerParameter: name=1, type=2(string); V1: name=4, type=5(enum),
        # blobs=6; V2 blobs=7
        for f2, w2, v2 in iter_fields(val):
            if field == 100:
                if f2 == 1 and w2 == 2:
                    name = v2.decode("utf-8", "replace")
                elif f2 == 2 and w2 == 2:
                    ltype = v2.decode("utf-8", "replace")
                elif f2 == 7 and w2 == 2:
                    blobs.append(_parse_blob(v2))
            else:                          # V1LayerParameter
                if f2 == 4 and w2 == 2:
                    name = v2.decode("utf-8", "replace")
                elif f2 == 5 and w2 == 0:
                    ltype = v2              # enum int
                elif f2 == 6 and w2 == 2:
                    blobs.append(_parse_blob(v2))
        layers.append({"name": name, "type": ltype, "blobs": blobs})
    return layers
