"""Caffe -> mxnet_tpu converter.

Parity target: reference ``tools/caffe_converter/convert_symbol.py`` +
``convert_model.py`` — turn a ``.prototxt`` into an ``mx.sym`` graph and
a ``.caffemodel`` into the matching arg/aux params, then save a standard
checkpoint. Layer coverage mirrors the reference converter's core set:
Data/Input, Convolution, InnerProduct, Pooling, ReLU, Dropout, LRN,
Concat, Eltwise, Flatten, BatchNorm(+Scale), Softmax/SoftmaxWithLoss.

Usage:
    python convert_model.py net.prototxt net.caffemodel out_prefix
"""
from __future__ import annotations

import sys

import numpy as np

from caffe_pb import parse_prototxt, parse_caffemodel  # noqa: E402


def _as_tuple2(param, key, default):
    v = param.one(key, None) if param is not None else None
    if v is None:
        h = param.one(key + "_h", None) if param is not None else None
        w = param.one(key + "_w", None) if param is not None else None
        if h is not None or w is not None:
            return (int(h or 0), int(w or 0))
        return (default, default)
    return (int(v), int(v))


def convert_symbol(prototxt_text):
    """prototxt text -> (mx Symbol, input_name). Returns the net output
    symbol (loss layers map to SoftmaxOutput)."""
    import mxnet_tpu as mx
    net = parse_prototxt(prototxt_text)
    layers = net.all("layer") or net.all("layers")
    tops = {}
    input_name = None
    for inp in net.all("input"):
        input_name = inp
        tops[inp] = mx.sym.Variable(inp)
    out = None
    for layer in layers:
        name = layer.one("name")
        ltype = layer.one("type")
        bottoms = [tops[b] for b in layer.all("bottom") if b in tops]
        top_names = layer.all("top") or [name]
        if ltype in ("Data", "Input", "HDF5Data", "ImageData"):
            input_name = input_name or top_names[0]
            sym = mx.sym.Variable(top_names[0])
            if top_names[0].lower() != "label":
                tops[top_names[0]] = sym
            for extra in top_names[1:]:
                tops[extra] = mx.sym.Variable(extra)
            continue
        data = bottoms[0] if bottoms else tops[input_name]
        if ltype == "Convolution":
            p = layer.one("convolution_param")
            kh, kw = _as_tuple2(p, "kernel_size", 1)
            sh, sw = _as_tuple2(p, "stride", 1)
            ph, pw = _as_tuple2(p, "pad", 0)
            sym = mx.sym.Convolution(
                data, name=name, kernel=(kh, kw), stride=(sh, sw),
                pad=(ph, pw), num_filter=int(p.one("num_output")),
                num_group=int(p.one("group", 1)),
                no_bias=not p.one("bias_term", True))
        elif ltype == "InnerProduct":
            p = layer.one("inner_product_param")
            sym = mx.sym.FullyConnected(
                mx.sym.Flatten(data), name=name,
                num_hidden=int(p.one("num_output")),
                no_bias=not p.one("bias_term", True))
        elif ltype == "Pooling":
            p = layer.one("pooling_param")
            kh, kw = _as_tuple2(p, "kernel_size", 1)
            sh, sw = _as_tuple2(p, "stride", 1)
            ph, pw = _as_tuple2(p, "pad", 0)
            pool = {0: "max", 1: "avg", "MAX": "max",
                    "AVE": "avg"}.get(p.one("pool", 0), "max")
            if p.one("global_pooling", False):
                sym = mx.sym.Pooling(data, name=name, pool_type=pool,
                                     global_pool=True, kernel=(1, 1))
            else:
                # caffe pooling uses ceil output sizing = 'full'
                sym = mx.sym.Pooling(data, name=name, kernel=(kh, kw),
                                     stride=(sh, sw), pad=(ph, pw),
                                     pool_type=pool,
                                     pooling_convention="full")
        elif ltype == "ReLU":
            sym = mx.sym.Activation(data, name=name, act_type="relu")
        elif ltype == "Sigmoid":
            sym = mx.sym.Activation(data, name=name, act_type="sigmoid")
        elif ltype == "TanH":
            sym = mx.sym.Activation(data, name=name, act_type="tanh")
        elif ltype == "Dropout":
            p = layer.one("dropout_param")
            ratio = float(p.one("dropout_ratio", 0.5)) if p else 0.5
            sym = mx.sym.Dropout(data, name=name, p=ratio)
        elif ltype == "LRN":
            p = layer.one("lrn_param")
            sym = mx.sym.LRN(data, name=name,
                             alpha=float(p.one("alpha", 1e-4)),
                             beta=float(p.one("beta", 0.75)),
                             knorm=float(p.one("k", 1.0)),
                             nsize=int(p.one("local_size", 5)))
        elif ltype == "Concat":
            p = layer.one("concat_param")
            dim = int(p.one("axis", 1)) if p else 1
            sym = mx.sym.Concat(*bottoms, name=name, dim=dim)
        elif ltype == "Eltwise":
            p = layer.one("eltwise_param")
            op = p.one("operation", "SUM") if p else "SUM"
            if op in ("SUM", 1):
                sym = bottoms[0]
                for b in bottoms[1:]:
                    sym = sym + b
            elif op in ("PROD", 0):
                sym = bottoms[0]
                for b in bottoms[1:]:
                    sym = sym * b
            else:
                sym = mx.sym.maximum(bottoms[0], bottoms[1])
        elif ltype == "Flatten":
            sym = mx.sym.Flatten(data, name=name)
        elif ltype == "BatchNorm":
            sym = mx.sym.BatchNorm(data, name=name, fix_gamma=True,
                                   use_global_stats=True, eps=1e-5)
        elif ltype == "Scale":
            # caffe Scale after BatchNorm folds into BN's gamma/beta; as a
            # standalone it is a per-channel affine -> BatchNorm with
            # fixed stats would double-normalise, so emit broadcast ops
            gamma = mx.sym.Variable(name + "_gamma", shape=(0,))
            beta = mx.sym.Variable(name + "_beta", shape=(0,))
            sym = mx.sym.broadcast_add(
                mx.sym.broadcast_mul(
                    data, mx.sym.reshape(gamma, shape=(1, -1, 1, 1))),
                mx.sym.reshape(beta, shape=(1, -1, 1, 1)))
        elif ltype in ("Softmax",):
            sym = mx.sym.softmax(data, name=name)
        elif ltype in ("SoftmaxWithLoss", "SoftmaxOutput"):
            sym = mx.sym.SoftmaxOutput(data, name="softmax")
        elif ltype == "Accuracy":
            continue
        else:
            raise NotImplementedError("caffe layer type %r is not "
                                      "supported" % ltype)
        for t in top_names:
            tops[t] = sym
        out = sym
    return out, input_name


def convert_model(prototxt_text, caffemodel_bytes):
    """-> (symbol, arg_params, aux_params)."""
    import mxnet_tpu as mx
    sym, _ = convert_symbol(prototxt_text)
    layers = parse_caffemodel(caffemodel_bytes)
    arg_names = set(sym.list_arguments())
    arg_params, aux_params = {}, {}
    for layer in layers:
        name = layer["name"]
        blobs = layer["blobs"]
        if not blobs:
            continue
        wshape, wdata = blobs[0]
        weight = np.asarray(wdata, np.float32).reshape(
            [d for d in wshape if d] or (len(wdata),))
        if layer["type"] == "InnerProduct" and weight.ndim > 2:
            weight = weight.reshape(weight.shape[-2], weight.shape[-1])
        if "%s_weight" % name in arg_names:
            arg_params["%s_weight" % name] = mx.nd.array(weight)
            if len(blobs) > 1:
                bshape, bdata = blobs[1]
                arg_params["%s_bias" % name] = mx.nd.array(
                    np.asarray(bdata, np.float32).ravel())
        elif layer["type"] == "BatchNorm":
            mean = np.asarray(blobs[0][1], np.float32).ravel()
            var = np.asarray(blobs[1][1], np.float32).ravel()
            scale = np.asarray(blobs[2][1], np.float32).ravel() \
                if len(blobs) > 2 else np.ones(1, np.float32)
            s = float(scale[0]) if scale.size else 1.0
            s = 1.0 / s if s else 1.0
            aux_params["%s_moving_mean" % name] = mx.nd.array(mean * s)
            aux_params["%s_moving_var" % name] = mx.nd.array(var * s)
            arg_params["%s_gamma" % name] = mx.nd.array(
                np.ones_like(mean))
            arg_params["%s_beta" % name] = mx.nd.array(
                np.zeros_like(mean))
        elif layer["type"] == "Scale":
            arg_params["%s_gamma" % name] = mx.nd.array(
                np.asarray(blobs[0][1], np.float32).ravel())
            if len(blobs) > 1:
                arg_params["%s_beta" % name] = mx.nd.array(
                    np.asarray(blobs[1][1], np.float32).ravel())
    return sym, arg_params, aux_params


def main():
    if len(sys.argv) != 4:
        print(__doc__)
        sys.exit(1)
    import mxnet_tpu as mx
    with open(sys.argv[1]) as f:
        prototxt = f.read()
    with open(sys.argv[2], "rb") as f:
        blob = f.read()
    sym, arg_params, aux_params = convert_model(prototxt, blob)
    mx.model.save_checkpoint(sys.argv[3], 0, sym, arg_params, aux_params)
    print("saved %s-symbol.json / %s-0000.params"
          % (sys.argv[3], sys.argv[3]))


if __name__ == "__main__":
    main()
