"""im2rec — pack an image dataset into RecordIO (parity: reference
tools/im2rec.py / im2rec.cc).

Two modes, same CLI surface as the reference:
  --list : walk an image directory and write a .lst index
           (``index\tlabel\trelpath`` lines)
  (pack) : read a .lst + image root and write .rec/.idx pair via
           MXIndexedRecordIO, optionally resizing/re-encoding (PIL here;
           the reference used OpenCV)
"""
import argparse
import io
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
# a host-side packing tool never needs the accelerator; skip TPU init
os.environ.setdefault("MXNET_TPU_FORCE_CPU", "1")
from mxnet_tpu import recordio  # noqa: E402

EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def list_images(root, recursive=True):
    cat = {}
    entries = []
    for dirpath, dirnames, filenames in sorted(os.walk(root)):
        dirnames.sort()
        for fname in sorted(filenames):
            if os.path.splitext(fname)[1].lower() in EXTS:
                rel = os.path.relpath(os.path.join(dirpath, fname), root)
                label_dir = os.path.dirname(rel) or "."
                if label_dir not in cat:
                    cat[label_dir] = len(cat)
                entries.append((rel, cat[label_dir]))
        if not recursive:
            break
    return entries


def write_list(args):
    entries = list_images(args.root, recursive=args.recursive)
    if args.shuffle:
        random.Random(100).shuffle(entries)
    chunks = max(args.chunks, 1)
    per = (len(entries) + chunks - 1) // chunks if entries else 0
    for c in range(chunks):
        suffix = "" if chunks == 1 else "_%d" % c
        path = args.prefix + suffix + ".lst"
        with open(path, "w") as f:
            for i, (rel, label) in enumerate(
                    entries[c * per:(c + 1) * per]):
                f.write("%d\t%f\t%s\n" % (c * per + i, float(label), rel))
        print("wrote %s" % path)


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx = int(parts[0])
            labels = [float(x) for x in parts[1:-1]]
            yield idx, labels, parts[-1]


def _encode(path, args):
    with open(path, "rb") as f:
        data = f.read()
    # pass raw bytes through unless the user asked for a transform —
    # re-encoding losslessly-stored images unprompted would degrade them
    if args.resize <= 0 and args.quality is None:
        return data
    try:
        from PIL import Image
    except ImportError:
        raise SystemExit("--resize/--quality need Pillow, which is not "
                         "installed; rerun without them to pack raw bytes")
    img = Image.open(io.BytesIO(data)).convert("RGB")
    if args.resize > 0:
        w, h = img.size
        scale = args.resize / min(w, h)
        img = img.resize((max(1, int(w * scale)), max(1, int(h * scale))))
    buf = io.BytesIO()
    img.save(buf, format="JPEG",
             quality=args.quality if args.quality else 95)
    return buf.getvalue()


def write_record(args, lst_path):
    prefix = os.path.splitext(lst_path)[0]
    record = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    count = 0
    for idx, labels, rel in read_list(lst_path):
        fullpath = os.path.join(args.root, rel)
        try:
            data = _encode(fullpath, args)
        except Exception as e:  # noqa: BLE001 — reference also skips+logs
            print("skipping %s: %s" % (rel, e))
            continue
        if len(labels) == 1:
            header = recordio.IRHeader(0, labels[0], idx, 0)
        else:
            header = recordio.IRHeader(0, labels, idx, 0)
        record.write_idx(idx, recordio.pack(header, data))
        count += 1
        if count % 1000 == 0:
            print("packed %d images" % count)
    record.close()
    print("wrote %s.rec (%d images)" % (prefix, count))


def main():
    parser = argparse.ArgumentParser(
        description="Create an image list or RecordIO file")
    parser.add_argument("prefix", help="prefix of .lst/.rec files")
    parser.add_argument("root", help="image root directory")
    parser.add_argument("--list", action="store_true",
                        help="create an image list instead of a record")
    parser.add_argument("--recursive", action=argparse.BooleanOptionalAction,
                        default=False,
                        help="walk subdirectories, labelling by directory "
                             "(reference default: off)")
    parser.add_argument("--shuffle", type=int, default=1)
    parser.add_argument("--chunks", type=int, default=1)
    parser.add_argument("--resize", type=int, default=0,
                        help="resize shorter edge to this (0 = keep raw "
                             "bytes untouched)")
    parser.add_argument("--quality", type=int, default=None,
                        help="JPEG re-encode quality (default: no "
                             "re-encode unless --resize is set)")
    args = parser.parse_args()

    if args.list:
        write_list(args)
    else:
        import glob
        prefix = args.prefix[:-4] if args.prefix.endswith(".lst") \
            else args.prefix
        lsts = [prefix + ".lst"] if os.path.exists(prefix + ".lst") \
            else sorted(glob.glob(prefix + "_*.lst"))
        if not lsts:
            raise SystemExit("no list file %s.lst or %s_*.lst found "
                             "(run --list first)" % (prefix, prefix))
        for lst in lsts:
            write_record(args, lst)


if __name__ == "__main__":
    main()
