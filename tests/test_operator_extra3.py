"""Ops from the final registry-gap sweep: forward vs a numpy oracle that
follows the reference kernels (psroi_pooling.cu, deformable_psroi_pooling.cu,
count_sketch.cu, la_op.cc, crop-inl.h, matrix_op.cc) + gradient checks
where the reference is differentiable."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import check_numeric_gradient


def _psroi_numpy(data, rois, scale, od, P, G):
    """Direct transcription of PSROIPoolForwardKernel's arithmetic."""
    N, C, H, W = data.shape
    R = rois.shape[0]
    out = np.zeros((R, od, P, P), np.float32)
    for r in range(R):
        b = int(rois[r, 0])
        x1 = round(rois[r, 1]) * scale
        y1 = round(rois[r, 2]) * scale
        x2 = (round(rois[r, 3]) + 1.0) * scale
        y2 = (round(rois[r, 4]) + 1.0) * scale
        rw = max(x2 - x1, 0.1)
        rh = max(y2 - y1, 0.1)
        bh, bw = rh / P, rw / P
        for ct in range(od):
            for ph in range(P):
                for pw in range(P):
                    hs = min(max(int(np.floor(ph * bh + y1)), 0), H)
                    he = min(max(int(np.ceil((ph + 1) * bh + y1)), 0), H)
                    ws = min(max(int(np.floor(pw * bw + x1)), 0), W)
                    we = min(max(int(np.ceil((pw + 1) * bw + x1)), 0), W)
                    gh = min(max(ph * G // P, 0), G - 1)
                    gw = min(max(pw * G // P, 0), G - 1)
                    c = (ct * G + gh) * G + gw
                    if he <= hs or we <= ws:
                        continue
                    out[r, ct, ph, pw] = data[b, c, hs:he, ws:we].mean()
    return out


def test_psroi_pooling_forward():
    rs = np.random.RandomState(0)
    od, G, P = 2, 3, 3
    data = rs.randn(2, od * G * G, 12, 12).astype(np.float32)
    rois = np.array([[0, 1, 1, 8, 8], [1, 0, 2, 11, 9], [0, 4, 4, 6, 7]],
                    np.float32)
    got = mx.nd.contrib.PSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), spatial_scale=0.8,
        output_dim=od, pooled_size=P, group_size=G).asnumpy()
    want = _psroi_numpy(data, rois, 0.8, od, P, G)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_psroi_pooling_grad():
    rs = np.random.RandomState(1)
    od, G, P = 1, 2, 2
    data = rs.randn(1, od * G * G, 6, 6).astype(np.float32)
    rois = np.array([[0, 0, 0, 4, 4]], np.float32)
    d = mx.sym.Variable("data")
    r = mx.sym.Variable("rois")
    out = mx.sym.contrib.PSROIPooling(d, r, spatial_scale=1.0,
                                      output_dim=od, pooled_size=P,
                                      group_size=G)
    # finite differences vs the symbolic backward, data input only
    check_numeric_gradient(out, [data, rois], grad_nodes=["data"],
                           numeric_eps=1e-2, rtol=1e-2, atol=1e-3)


def test_deformable_psroi_pooling_no_trans_matches_samples():
    """With no_trans the op reduces to sampled position-sensitive
    average pooling; oracle follows the CUDA kernel sample-for-sample."""
    rs = np.random.RandomState(2)
    od, G, P, sp = 2, 2, 2, 2
    H = W = 8
    data = rs.randn(1, od * G * G, H, W).astype(np.float32)
    rois = np.array([[0, 1, 1, 6, 6]], np.float32)
    trans = np.zeros((1, 2, P, P), np.float32)
    got = mx.nd.contrib.DeformablePSROIPooling(
        mx.nd.array(data), mx.nd.array(rois), mx.nd.array(trans),
        spatial_scale=1.0, output_dim=od, pooled_size=P, group_size=G,
        part_size=P, sample_per_part=sp, trans_std=0.1,
        no_trans=True).asnumpy()

    def bilinear(img, h, w):
        h0, w0 = int(np.floor(h)), int(np.floor(w))
        out = 0.0
        for dy in (0, 1):
            for dx in (0, 1):
                yy, xx = h0 + dy, w0 + dx
                if 0 <= yy < H and 0 <= xx < W:
                    wt = ((1 - abs(h - yy)) * (1 - abs(w - xx)))
                    out += img[yy, xx] * max(wt, 0.0)
        return out

    x1 = round(1) * 1.0 - 0.5
    y1 = x1
    x2 = (round(6) + 1.0) - 0.5
    y2 = x2
    rw, rh = max(x2 - x1, 0.1), max(y2 - y1, 0.1)
    bh, bw = rh / P, rw / P
    sh, sw = bh / sp, bw / sp
    want = np.zeros_like(got)
    for ct in range(od):
        for ph in range(P):
            for pw in range(P):
                gh = min(max(ph * G // P, 0), G - 1)
                gw = min(max(pw * G // P, 0), G - 1)
                c = (ct * G + gh) * G + gw
                acc, cnt = 0.0, 0
                for ihh in range(sp):
                    for iww in range(sp):
                        h = ph * bh + y1 + ihh * sh
                        w = pw * bw + x1 + iww * sw
                        if -0.5 < w < W - 0.5 and -0.5 < h < H - 0.5:
                            acc += bilinear(data[0, c],
                                            min(max(h, 0), H - 1),
                                            min(max(w, 0), W - 1))
                            cnt += 1
                want[0, ct, ph, pw] = acc / cnt if cnt else 0.0
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_multi_proposal_batches():
    rs = np.random.RandomState(3)
    B, A, Hf, Wf = 2, 3, 4, 4
    cls_prob = rs.uniform(size=(B, 2 * A, Hf, Wf)).astype(np.float32)
    bbox_pred = rs.randn(B, 4 * A, Hf, Wf).astype(np.float32) * 0.1
    im_info = np.array([[64, 64, 1.0], [64, 64, 1.0]], np.float32)
    post = 8
    rois = mx.nd.contrib.MultiProposal(
        mx.nd.array(cls_prob), mx.nd.array(bbox_pred), mx.nd.array(im_info),
        feature_stride=16, scales=(8,), ratios=(0.5, 1, 2),
        rpn_pre_nms_top_n=20, rpn_post_nms_top_n=post,
        rpn_min_size=4).asnumpy()
    assert rois.shape == (B * post, 5)
    assert np.all(rois[:post, 0] == 0) and np.all(rois[post:, 0] == 1)
    # per-image result equals single-image Proposal
    one = mx.nd.contrib.Proposal(
        mx.nd.array(cls_prob[1:2]), mx.nd.array(bbox_pred[1:2]),
        mx.nd.array(im_info[1:2]), feature_stride=16, scales=(8,),
        ratios=(0.5, 1, 2), rpn_pre_nms_top_n=20, rpn_post_nms_top_n=post,
        rpn_min_size=4).asnumpy()
    np.testing.assert_allclose(rois[post:, 1:], one[:, 1:], rtol=1e-5)


def test_count_sketch():
    rs = np.random.RandomState(4)
    n, in_dim, od = 5, 16, 8
    data = rs.randn(n, in_dim).astype(np.float32)
    h = rs.randint(0, od, size=in_dim).astype(np.float32)
    s = rs.choice([-1.0, 1.0], size=in_dim).astype(np.float32)
    got = mx.nd.contrib.count_sketch(mx.nd.array(data), mx.nd.array(h),
                                     mx.nd.array(s), out_dim=od).asnumpy()
    want = np.zeros((n, od), np.float32)
    for j in range(in_dim):
        want[:, int(h[j])] += s[j] * data[:, j]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_linalg_gelqf_syevd():
    rs = np.random.RandomState(5)
    a = rs.randn(3, 5).astype(np.float32)
    q, l = mx.nd.linalg_gelqf(mx.nd.array(a))
    q, l = q.asnumpy(), l.asnumpy()
    np.testing.assert_allclose(l @ q, a, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(q @ q.T, np.eye(3), rtol=1e-4, atol=1e-5)
    assert np.allclose(np.triu(l, 1), 0, atol=1e-6)  # lower triangular

    s = rs.randn(4, 4).astype(np.float32)
    s = (s + s.T) / 2
    u, lam = mx.nd.linalg_syevd(mx.nd.array(s))
    u, lam = u.asnumpy(), lam.asnumpy()
    np.testing.assert_allclose(u.T @ np.diag(lam) @ u, s, rtol=1e-3,
                               atol=1e-4)
    assert np.all(np.diff(lam) >= -1e-5)  # ascending


def test_reshape_like_and_slice_assign():
    a = mx.nd.arange(12).reshape((3, 4))
    b = mx.nd.zeros((4, 3))
    out = mx.nd.reshape_like(a, b)
    assert out.shape == (4, 3)

    lhs = mx.nd.zeros((4, 4))
    rhs = mx.nd.ones((2, 2))
    got = mx.nd._slice_assign(lhs, rhs, begin=(1, 1), end=(3, 3)).asnumpy()
    want = np.zeros((4, 4), np.float32)
    want[1:3, 1:3] = 1
    np.testing.assert_allclose(got, want)

    got = mx.nd._slice_assign_scalar(lhs, scalar=7.0, begin=(0, 2),
                                     end=(4, 4)).asnumpy()
    want = np.zeros((4, 4), np.float32)
    want[:, 2:] = 7
    np.testing.assert_allclose(got, want)


def test_crop_legacy():
    x = mx.nd.array(np.arange(2 * 3 * 6 * 6, dtype=np.float32)
                    .reshape(2, 3, 6, 6))
    got = mx.nd.Crop(x, h_w=(4, 4), offset=(1, 2), num_args=1).asnumpy()
    np.testing.assert_allclose(got, x.asnumpy()[:, :, 1:5, 2:6])
    # center crop
    got = mx.nd.Crop(x, h_w=(4, 4), center_crop=True, num_args=1).asnumpy()
    np.testing.assert_allclose(got, x.asnumpy()[:, :, 1:5, 1:5])
    # crop-like second input
    like = mx.nd.zeros((2, 3, 2, 2))
    got = mx.nd.Crop(x, like, num_args=2).asnumpy()
    np.testing.assert_allclose(got, x.asnumpy()[:, :, :2, :2])


def test_legacy_aliases_resolve():
    for name in ("Convolution_v1", "Pooling_v1", "CuDNNBatchNorm",
                 "_contrib_SparseEmbedding", "_CrossDeviceCopy"):
        assert mx.ops.get_op(name) is not None
    # v1 conv computes like modern conv
    rs = np.random.RandomState(6)
    x = mx.nd.array(rs.randn(1, 2, 5, 5).astype(np.float32))
    w = mx.nd.array(rs.randn(3, 2, 3, 3).astype(np.float32))
    b = mx.nd.zeros((3,))
    a = mx.nd.Convolution(x, w, b, kernel=(3, 3), num_filter=3).asnumpy()
    v1 = mx.nd.Convolution_v1(x, w, b, kernel=(3, 3), num_filter=3).asnumpy()
    np.testing.assert_allclose(a, v1, rtol=1e-5)


REF_SRC = "/root/reference/src/operator"

# reference-registered names deliberately NOT in the jnp op registry
OP_SKIP_LIST = {
    "_NDArray": "torch/numpy plugin embed op (plugin glue, no kernel)",
    "_Native": "torch/numpy plugin embed op (plugin glue, no kernel)",
    "_broadcast_backward": "internal backward node; jax.vjp owns grads",
    "_scatter_set_nd": "internal write-through for x[idx]=v; NDArray "
                       "setitem lowers to jnp .at[].set directly",
    "_sparse_retain": "sparse storage is a Python-level wrapper here; "
                      "exposed as mx.nd.sparse retain (ndarray/sparse.py)",
    "cast_storage": "same — mx.nd.cast_storage via ndarray/sparse.py",
    "name": "regex artifact of the reference's registration macro",
}


@pytest.mark.skipif(not os.path.isdir(REF_SRC), reason="no reference tree")
def test_registry_covers_reference_ops():
    """Every op name the reference registers resolves here or sits in the
    explicit skip list (reference NNVM_REGISTER_OP +
    MXNET_REGISTER_OP_PROPERTY across src/operator)."""
    import re
    names = set()
    for root, _, files in os.walk(REF_SRC):
        for fn in files:
            if not fn.endswith(".cc"):
                continue
            text = open(os.path.join(root, fn), errors="replace").read()
            names.update(re.findall(r"NNVM_REGISTER_OP\(([^)]+)\)", text))
            names.update(m.strip() for m in re.findall(
                r"MXNET_REGISTER_OP_PROPERTY\(([^,]+),", text))
    names = {n.strip('" ') for n in names if "##" not in n}
    registered = set(mx.ops.list_ops())
    missing = sorted(n for n in names
                     if n not in registered
                     and not n.startswith("_backward")
                     and n not in OP_SKIP_LIST)
    assert not missing, "unregistered reference ops: %s" % missing


def test_conv_stem_space_to_depth_rewrite():
    """The channels-last 7x7/s2 stem conv takes the space-to-depth
    lowering; it must be numerically identical to the NCHW reference
    path, gradients included."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.nn import _conv_nd, _s2d_applicable

    rs = np.random.RandomState(7)
    x = rs.randn(2, 16, 16, 3).astype(np.float32)        # NHWC
    w = rs.randn(8, 7, 7, 3).astype(np.float32)          # OHWI
    assert _s2d_applicable(jnp.asarray(x), (7, 7), (2, 2), (1, 1), (3, 3),
                           1, True, 2)

    def nhwc(xx, ww):
        return _conv_nd(xx, ww, None, (7, 7), (2, 2), (1, 1), (3, 3), 1,
                        True, layout="NHWC")

    def ref(xx, ww):   # NCHW path, no rewrite
        out = _conv_nd(jnp.transpose(xx, (0, 3, 1, 2)),
                       jnp.transpose(ww, (0, 3, 1, 2)), None, (7, 7),
                       (2, 2), (1, 1), (3, 3), 1, True, layout=None)
        return jnp.transpose(out, (0, 2, 3, 1))

    got = nhwc(jnp.asarray(x), jnp.asarray(w))
    want = ref(jnp.asarray(x), jnp.asarray(w))
    assert got.shape == (2, 8, 8, 8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)

    # gradients agree through the rewrite
    g = rs.randn(*got.shape).astype(np.float32)
    loss = lambda f: (lambda xx, ww: jnp.sum(f(xx, ww) * g))
    gx1, gw1 = jax.grad(loss(nhwc), argnums=(0, 1))(jnp.asarray(x),
                                                    jnp.asarray(w))
    gx2, gw2 = jax.grad(loss(ref), argnums=(0, 1))(jnp.asarray(x),
                                                   jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(gw1), np.asarray(gw2),
                               rtol=1e-3, atol=1e-3)

    # odd spatial size falls back to the plain lowering
    x_odd = jnp.asarray(rs.randn(1, 15, 15, 3).astype(np.float32))
    assert not _s2d_applicable(x_odd, (7, 7), (2, 2), (1, 1), (3, 3),
                               1, True, 2)
