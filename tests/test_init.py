"""Initializer suite (parity model: reference
tests/python/unittest/test_init.py — default_init, variance of the
scaled families, structural initializers, aux handling)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import initializer as init


def _materialise(initializer, name, shape):
    arr = mx.nd.zeros(shape)
    initializer(init.InitDesc(name), arr)
    return arr.asnumpy()


def test_constant_families():
    assert (_materialise(init.Zero(), "w_weight", (4, 3)) == 0).all()
    assert (_materialise(init.One(), "w_weight", (4, 3)) == 1).all()
    c = _materialise(init.Constant(2.5), "w_weight", (4, 3))
    np.testing.assert_allclose(c, 2.5)


def test_uniform_normal_ranges():
    mx.random.seed(0)
    u = _materialise(init.Uniform(0.1), "w_weight", (200, 50))
    assert abs(u.mean()) < 0.01 and u.min() >= -0.1 and u.max() <= 0.1
    n = _materialise(init.Normal(0.5), "w_weight", (200, 50))
    assert abs(n.std() - 0.5) < 0.02


@pytest.mark.parametrize("rnd_type,factor,magnitude", [
    ("uniform", "avg", 3.0),
    ("gaussian", "in", 2.0),
    ("uniform", "out", 1.0),
])
def test_xavier_variance(rnd_type, factor, magnitude):
    shape = (256, 128)
    w = _materialise(init.Xavier(rnd_type=rnd_type, factor_type=factor,
                                 magnitude=magnitude), "w_weight", shape)
    fan_in, fan_out = shape[1], shape[0]
    fan = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in,
           "out": fan_out}[factor]
    # scale = sqrt(magnitude/fan); uniform(-s, s) has var s^2/3,
    # normal(0, s) has var s^2 (reference initializer.py Xavier)
    expect_var = magnitude / fan / (3.0 if rnd_type == "uniform" else 1.0)
    assert abs(w.var() - expect_var) / expect_var < 0.15


def test_msra_prelu_is_xavier_gaussian_avg():
    w = _materialise(init.MSRAPrelu(slope=0.0), "w_weight", (256, 128))
    # magnitude 2/(1+slope^2)=2, default factor avg -> var = 2/192
    expect = 2.0 / 192
    assert abs(w.var() - expect) / expect < 0.15


def test_orthogonal_columns():
    mx.random.seed(3)
    w = _materialise(init.Orthogonal(scale=1.0), "w_weight", (64, 32))
    gram = w.T @ w
    np.testing.assert_allclose(gram, np.eye(32), atol=1e-4)


def test_bilinear_upsampling_kernel():
    w = _materialise(init.Bilinear(), "up_weight", (1, 1, 4, 4))
    k = w[0, 0]
    # symmetric, peak in the centre block, classic bilinear taps
    np.testing.assert_allclose(k, k[::-1, ::-1])
    np.testing.assert_allclose(k[1, 1], 0.5625, rtol=1e-6)


def test_lstmbias_sets_forget_gate():
    b = _materialise(init.LSTMBias(forget_bias=1.0), "lstm_bias", (32,))
    H = 8  # 4 gates x 8
    np.testing.assert_allclose(b[H:2 * H], 1.0)   # forget gate chunk
    np.testing.assert_allclose(b[:H], 0.0)


def test_name_dispatch_defaults():
    """Initializer base dispatches by suffix: bias/gamma/beta/moving_*."""
    ini = init.Xavier()
    assert (_materialise(ini, "fc_bias", (16,)) == 0).all()
    assert (_materialise(ini, "bn_gamma", (16,)) == 1).all()
    assert (_materialise(ini, "bn_beta", (16,)) == 0).all()
    assert (_materialise(ini, "bn_moving_var", (16,)) == 1).all()
    assert (_materialise(ini, "bn_moving_mean", (16,)) == 0).all()


def test_mixed_initializer_pattern_routing():
    # weight names, because suffix dispatch sends *_bias to _init_bias
    # (zeros) regardless of the routed initializer — reference semantics
    mixed = init.Mixed(["embed.*", ".*"], [init.Constant(3.0),
                                           init.Zero()])
    assert (_materialise(mixed, "embed_weight", (8, 4)) == 3.0).all()
    assert (_materialise(mixed, "fc_weight", (8, 8)) == 0.0).all()
    with pytest.raises(Exception):
        init.Mixed(["embed.*"], [init.Constant(3.0)])(
            init.InitDesc("no_match_weight"), mx.nd.zeros((2,)))


def test_load_initializer_with_default(tmp_path):
    params = {"arg:fc_weight": mx.nd.array(np.full((4, 4), 7.0,
                                                   np.float32))}
    path = str(tmp_path / "p.params")
    mx.nd.save(path, {k: v for k, v in params.items()})
    ld = init.Load(path, default_init=init.Zero(), verbose=False)
    got = _materialise(ld, "fc_weight", (4, 4))
    np.testing.assert_allclose(got, 7.0)
    other = _materialise(ld, "other_weight", (2, 2))
    np.testing.assert_allclose(other, 0.0)


def test_init_through_module_respects_families():
    """End to end: Module.init_params applies the name dispatch."""
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc")
    net = mx.sym.BatchNorm(net, name="bn")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 6))])
    mod.init_params(init.Xavier())
    args, aux = mod.get_params()
    assert (args["fc_bias"].asnumpy() == 0).all()
    assert (args["bn_gamma"].asnumpy() == 1).all()
    assert (aux["bn_moving_var"].asnumpy() == 1).all()
    assert args["fc_weight"].asnumpy().std() > 0
