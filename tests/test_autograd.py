"""Autograd tests (parity model: reference tests/python/unittest/test_autograd.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_basic_backward():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy())


def test_chain_and_reuse():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + x * 3  # x used twice: grads must accumulate
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2 * 2 + 3])


def test_multi_variable():
    a = nd.array([1.0, 2.0])
    b = nd.array([3.0, 4.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = (a * b).sum()
    c.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), b.asnumpy())
    np.testing.assert_allclose(b.grad.asnumpy(), a.asnumpy())


def test_head_grad():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = 2 * x
    y.backward(nd.array([10.0, 100.0]))
    np.testing.assert_allclose(x.grad.asnumpy(), [20.0, 200.0])


def test_pause_and_stop_gradient():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        with autograd.pause():
            z = y * 2  # not recorded
        w = y + nd.BlockGrad(y)
    w.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [6.0])  # only one path
    assert not autograd.is_recording()


def test_train_vs_predict_mode():
    assert not autograd.is_training()
    with autograd.record(train_mode=True):
        assert autograd.is_training()
        with autograd.predict_mode():
            assert not autograd.is_training()
    with autograd.record(train_mode=False):
        assert not autograd.is_training()


def test_grad_req_add():
    x = nd.array([1.0])
    grad = nd.zeros((1,))
    autograd.mark_variables([x], [grad], grad_reqs="add")
    for _ in range(3):
        with autograd.record():
            y = x * 2
        y.backward()
    np.testing.assert_allclose(grad.asnumpy(), [6.0])


def test_autograd_grad_api():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
    g = autograd.grad(y, x)
    np.testing.assert_allclose(g.asnumpy(), [12.0])


def test_nondiff_path():
    x = nd.array([1.0, 5.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * nd.argmax(x).reshape((1,))).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [1.0, 1.0, 1.0])


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            y, = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array([0.0, 1.0])
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    np.testing.assert_allclose(x.grad.asnumpy(), s * (1 - s), rtol=1e-5)


def test_deep_chain():
    x = nd.array([1.001])
    x.attach_grad()
    with autograd.record():
        y = x
        for _ in range(50):
            y = y * 1.01
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [1.01 ** 50], rtol=1e-4)


def test_contrib_grad_and_loss_tuple_outputs():
    # regression: functions returning tuples of outputs must work
    from mxnet_tpu.contrib import autograd as cag
    f = cag.grad_and_loss(lambda x: (x * x, x + 1))
    grads, outs = f(mx.nd.array([3.0]))
    assert len(outs) == 2
    np.testing.assert_allclose(grads[0].asnumpy(), [7.0], rtol=1e-6)


def test_grad_create_graph_second_order():
    """(parity: reference autograd.grad create_graph) d/dx of (dy/dx)."""
    x = mx.nd.array(np.array([1.0, 2.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = (x ** 3).sum()
        g1 = autograd.grad(y, [x], create_graph=True)[0]
        z = (g1 * g1).sum()
    z.backward()
    # d/dx (3x^2)^2 = 36 x^3
    np.testing.assert_allclose(x.grad.asnumpy(),
                               36 * np.array([1.0, 8.0]), rtol=1e-4)


def test_grad_create_graph_third_order():
    x = mx.nd.array(np.array([2.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = (x ** 4).sum()
        g1 = autograd.grad(y, [x], create_graph=True)[0]
        g2 = autograd.grad(g1.sum(), [x], create_graph=True)[0]
        w = g2.sum()
    w.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [48.0], rtol=1e-4)


def test_grad_create_graph_multivar():
    """Mixed partials through two variables."""
    a = mx.nd.array(np.array([1.5], np.float32)); a.attach_grad()
    b = mx.nd.array(np.array([0.5], np.float32)); b.attach_grad()
    with autograd.record():
        y = (a * a * b).sum()          # d/da = 2ab; d^2/dadb = 2a
        ga = autograd.grad(y, [a], create_graph=True)[0]
        s = ga.sum()
    s.backward()
    np.testing.assert_allclose(b.grad.asnumpy(), [3.0], rtol=1e-5)  # 2a
    np.testing.assert_allclose(a.grad.asnumpy(), [1.0], rtol=1e-5)  # 2b


def test_get_symbol_reconstructs_graph():
    """(parity: autograd.get_symbol / MXAutogradGetSymbol) — the symbol
    rebuilt from the tape reproduces the recorded forward."""
    x = mx.nd.array(np.random.RandomState(0).randn(2, 4).astype(np.float32))
    w = mx.nd.array(np.random.RandomState(1).randn(3, 4).astype(np.float32))
    x.attach_grad()
    w.attach_grad()
    with autograd.record():
        y = mx.nd.FullyConnected(x, w, None, num_hidden=3, no_bias=True)
        z = mx.nd.relu(y) * 2.0
    sym = autograd.get_symbol(z)
    args = sym.list_arguments()
    assert len(args) == 2
    exe = sym.bind(mx.cpu(), {args[0]: x, args[1]: w})
    np.testing.assert_allclose(exe.forward()[0].asnumpy(), z.asnumpy(),
                               rtol=1e-6)


def test_advanced_indexing_is_differentiable():
    """a[i, j] and fancy a[idx] stay on the tape (reference: gathers
    with scatter backward) — the lstm_crf example's CRF scoring relies
    on this."""
    import numpy as np
    w = mx.nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    w.attach_grad()
    with mx.autograd.record():
        s = w[1, 2] * 3.0 + w[0, 0]
    s.backward()
    expect = np.zeros((3, 4), np.float32)
    expect[1, 2], expect[0, 0] = 3.0, 1.0
    np.testing.assert_allclose(w.grad.asnumpy(), expect)
    assert s.shape == ()

    x = mx.nd.array(np.arange(6, dtype=np.float32))
    x.attach_grad()
    idx = mx.nd.array(np.array([1, 3, 3], np.float32))
    with mx.autograd.record():
        y = x[idx].sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [0, 1, 0, 2, 0, 0])


def test_advanced_indexing_matches_eager_semantics():
    """Recording-path gathers must agree with eager fancy indexing:
    mixed vector+int keys, negative indices, multi-dim index arrays."""
    import numpy as np
    a_np = np.arange(24, dtype=np.float32).reshape(4, 6)
    a = mx.nd.array(a_np)
    a.attach_grad()

    # mixed vector + int
    ridx = mx.nd.array(np.array([0, 2, 3], np.float32))
    with mx.autograd.record():
        picked = a[ridx, 1]
        loss = picked.sum()
    loss.backward()
    np.testing.assert_allclose(picked.asnumpy(), a_np[[0, 2, 3], 1])
    expect = np.zeros_like(a_np)
    expect[[0, 2, 3], 1] = 1.0
    np.testing.assert_allclose(a.grad.asnumpy(), expect)

    # negative fancy index wraps, as eagerly
    x = mx.nd.array(np.arange(6, dtype=np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = x[mx.nd.array(np.array([-1, 1], np.float32))].sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [0, 1, 0, 0, 0, 1])

    # 2-D index arrays keep their shape
    i = mx.nd.array(np.array([[0, 1], [2, 3]], np.float32))
    j = mx.nd.array(np.array([[5, 4], [3, 2]], np.float32))
    with mx.autograd.record():
        g = a[i, j]
        (g * g).sum().backward()
    assert g.shape == (2, 2)
    np.testing.assert_allclose(g.asnumpy(),
                               a_np[[[0, 1], [2, 3]], [[5, 4], [3, 2]]])
