"""Preemption-safe training (ISSUE 7): atomic checkpoints,
CheckpointManager rotation/latest/restore, signal-armed preemption,
``Module.fit(resume=...)`` equivalence, and the divergence sentinel."""
import json
import os
import signal

import numpy as np
import pytest

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import faults, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.checkpoint import (CheckpointManager, TrainingPreempted,
                                  DivergenceError, atomic_write,
                                  atomic_save_ndarrays)

D, HID, C, N, BATCH = 4, 8, 2, 32, 8


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=HID, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=C, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _iter():
    rng = np.random.RandomState(7)
    X = rng.normal(size=(N, D)).astype(np.float32)
    y = rng.randint(0, C, (N,)).astype(np.float32)
    return mx.io.NDArrayIter(X, y, batch_size=BATCH, shuffle=False,
                             label_name="softmax_label")


def _fresh_module():
    np.random.seed(0)
    mx.random.seed(0)
    return mx.mod.Module(_mlp(), label_names=["softmax_label"])


FIT_KW = dict(optimizer="sgd",
              optimizer_params=(("learning_rate", 0.1),
                                ("momentum", 0.9)))


# ---------------------------------------------------------------------------
# Atomic writers
# ---------------------------------------------------------------------------

def test_atomic_write_replaces_and_leaves_no_temp(tmp_path):
    p = tmp_path / "f.bin"
    atomic_write(str(p), b"one")
    atomic_write(str(p), b"two")
    assert p.read_bytes() == b"two"
    assert [x for x in os.listdir(tmp_path) if x != "f.bin"] == []


def test_atomic_write_failure_keeps_previous_file(tmp_path, monkeypatch):
    p = tmp_path / "f.bin"
    atomic_write(str(p), b"good")

    real_replace = os.replace

    def boom(src, dst):
        raise OSError("disk died mid-rename")

    monkeypatch.setattr(os, "replace", boom)
    with pytest.raises(OSError):
        atomic_write(str(p), b"partial")
    monkeypatch.setattr(os, "replace", real_replace)
    assert p.read_bytes() == b"good"                 # old file intact
    assert [x for x in os.listdir(tmp_path) if x != "f.bin"] == []


def test_atomic_save_ndarrays_roundtrip(tmp_path):
    p = str(tmp_path / "x.params")
    atomic_save_ndarrays(p, {"arg:w": mx.nd.ones((2, 3))})
    loaded = mx.nd.load(p)
    assert np.allclose(loaded["arg:w"].asnumpy(), 1.0)
    assert os.listdir(tmp_path) == ["x.params"]


def test_model_save_checkpoint_is_atomic(tmp_path):
    # the params file appears complete or not at all: the writer goes
    # through a temp name, so a concurrent load of the FINAL name never
    # sees a partial container
    from mxnet_tpu.model import save_checkpoint, load_checkpoint
    prefix = str(tmp_path / "m")
    sym = _mlp()
    arg = {"fc1_weight": mx.nd.ones((HID, D))}
    save_checkpoint(prefix, 1, sym, arg, {})
    s2, a2, x2 = load_checkpoint(prefix, 1)
    assert np.allclose(a2["fc1_weight"].asnumpy(), 1.0)
    leftovers = [f for f in os.listdir(tmp_path) if ".tmp" in f]
    assert leftovers == []


# ---------------------------------------------------------------------------
# CheckpointManager
# ---------------------------------------------------------------------------

def _fitted_module(tmp_path, epochs=1):
    mod = _fresh_module()
    mod.fit(_iter(), num_epoch=epochs, **FIT_KW)
    return mod


def test_manager_save_latest_meta_schema(tmp_path):
    mod = _fitted_module(tmp_path)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    meta = mgr.save(mod, epoch=1, nbatch=2)
    assert meta["epoch"] == 1 and meta["nbatch"] == 2
    got = mgr.latest()
    assert got["epoch"] == 1 and got["nbatch"] == 2
    assert got["optimizer_states"] is True
    assert isinstance(got["rng_state"], list)
    assert got["update_counts"]                      # sgd counts saved
    for suffix in ("-0001.params", "-0001.states", "-0001.meta.json",
                   "-symbol.json"):
        assert os.path.exists(str(tmp_path / "ck") + suffix)


def test_manager_keeps_last_k(tmp_path):
    mod = _fitted_module(tmp_path)
    mgr = CheckpointManager(str(tmp_path / "ck"), keep_last=2)
    for e in range(1, 6):
        mgr.save(mod, epoch=e)
    assert mgr.epochs() == [4, 5]
    assert not os.path.exists(str(tmp_path / "ck") + "-0001.params")
    assert os.path.exists(str(tmp_path / "ck") + "-0005.params")


def test_latest_skips_corrupt_meta(tmp_path):
    mod = _fitted_module(tmp_path)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(mod, epoch=1)
    mgr.save(mod, epoch=2)
    with open(str(tmp_path / "ck") + "-0002.meta.json", "w") as f:
        f.write('{"trunc')                           # killed mid-write
    assert mgr.latest()["epoch"] == 1


def test_epochs_sees_wide_ids_and_metachar_prefixes(tmp_path):
    # %04d widens past 4 digits at epoch 10000, and a prefix with glob
    # metacharacters must still resolve — epochs() matches by regex
    # over a listing, not by glob
    sub = tmp_path / "run[1]"
    sub.mkdir()
    mgr = CheckpointManager(str(sub / "ck"), keep_last=10)
    for e in (9999, 10000):
        with open("%s-%04d.meta.json" % (mgr.prefix, e), "w") as f:
            json.dump({"epoch": e, "nbatch": 0, "param_epoch": e}, f)
    assert mgr.epochs() == [9999, 10000]
    assert mgr.latest()["epoch"] == 10000


def test_latest_none_when_empty(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"))
    assert mgr.latest() is None
    with pytest.raises(MXNetError):
        mgr.load()


def test_restore_roundtrips_params_states_and_rng(tmp_path):
    mod = _fitted_module(tmp_path)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(mod, epoch=1)
    arg0, aux0 = mod.get_params()
    rng0 = mx.random.get_state()
    counts0 = dict(mod._optimizer._index_update_count)
    # wreck everything, then restore
    mod.set_params({k: mx.nd.zeros(v.shape) for k, v in arg0.items()},
                   aux0)
    mx.random.seed(999)
    meta = mgr.restore(mod)
    arg1, _ = mod.get_params()
    for k in arg0:
        assert np.allclose(arg0[k].asnumpy(), arg1[k].asnumpy())
    assert mx.random.get_state() == rng0
    assert dict(mod._optimizer._index_update_count) == counts0
    assert meta["epoch"] == 1


def test_rng_state_roundtrip_replays_key_sequence():
    mx.random.seed(3)
    mx.random.take_key()
    state = mx.random.get_state()
    a = np.asarray(jax.random.key_data(mx.random.take_key()))
    mx.random.set_state(state)
    b = np.asarray(jax.random.key_data(mx.random.take_key()))
    assert (a == b).all()


# ---------------------------------------------------------------------------
# Preemption
# ---------------------------------------------------------------------------

def test_programmatic_preempt_saves_and_raises(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mod = _fresh_module()

    def preempt(param):
        if param.epoch == 0 and param.nbatch == 1:
            mgr.request_preempt("maintenance-poller")

    with pytest.raises(TrainingPreempted) as ei:
        mod.fit(_iter(), num_epoch=2, checkpoint=mgr,
                batch_end_callback=preempt, **FIT_KW)
    assert ei.value.epoch == 0 and ei.value.nbatch == 2
    meta = mgr.latest()
    assert meta["epoch"] == 0 and meta["nbatch"] == 2


def test_sigterm_mid_epoch_then_resume_matches_uninterrupted(tmp_path):
    """The ISSUE 7 acceptance scenario: SIGTERM mid-epoch → auto
    checkpoint → ``fit(resume=...)`` in a fresh module reaches the SAME
    parameters as an uninterrupted run (deterministic data, momentum
    state + update counts + RNG restored)."""
    # leg A: uninterrupted oracle
    mod_a = _fresh_module()
    mod_a.fit(_iter(), num_epoch=3, **FIT_KW)
    arg_a, _ = mod_a.get_params()

    # leg B: SIGTERM at epoch 1, batch 1 (the armed handler sets the
    # flag; the loop finishes the batch, saves, raises)
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mod_b = _fresh_module()

    def kill(param):
        if param.epoch == 1 and param.nbatch == 1:
            os.kill(os.getpid(), signal.SIGTERM)

    prev = signal.getsignal(signal.SIGTERM)
    with pytest.raises(TrainingPreempted):
        mod_b.fit(_iter(), num_epoch=3, checkpoint=mgr,
                  batch_end_callback=kill, **FIT_KW)
    # the armed handler is restored on the way out
    assert signal.getsignal(signal.SIGTERM) == prev
    meta = mgr.latest()
    assert meta["epoch"] == 1 and meta["nbatch"] == 2

    # leg B resumed, in a FRESH module (new process semantics)
    mod_c = mx.mod.Module(_mlp(), label_names=["softmax_label"])
    mod_c.fit(_iter(), num_epoch=3, checkpoint=mgr, resume=True,
              **FIT_KW)
    arg_c, _ = mod_c.get_params()
    for k in arg_a:
        np.testing.assert_allclose(
            arg_a[k].asnumpy(), arg_c[k].asnumpy(),
            rtol=1e-5, atol=1e-6,
            err_msg="resumed run diverged from oracle at %s" % k)


def test_resume_with_no_checkpoint_is_fresh_start(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mod = _fresh_module()
    mod.fit(_iter(), num_epoch=1, checkpoint=mgr, resume=True, **FIT_KW)
    assert mgr.latest()["epoch"] == 1        # epoch-end save happened


def test_resume_requires_a_manager():
    mod = _fresh_module()
    with pytest.raises(MXNetError):
        mod.fit(_iter(), num_epoch=1, resume=True, **FIT_KW)


def test_epoch_end_saves_rotate(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"), keep_last=2)
    mod = _fresh_module()
    mod.fit(_iter(), num_epoch=4, checkpoint=mgr, **FIT_KW)
    assert mgr.epochs() == [3, 4]
    assert mgr.latest()["epoch"] == 4 and mgr.latest()["nbatch"] == 0


# ---------------------------------------------------------------------------
# Divergence sentinel
# ---------------------------------------------------------------------------

def test_finite_check_device_fold_detects_nan():
    mod = _fresh_module()
    it = _iter()
    mod.fit(it, num_epoch=1, **FIT_KW)
    assert mod.finite_check() is True
    arg, aux = mod.get_params()
    k = sorted(arg)[0]
    host = arg[k].asnumpy().copy()
    host.reshape(-1)[0] = np.nan
    arg[k] = mx.nd.array(host)
    mod.set_params(arg, aux)
    assert mod.finite_check() is False


def test_divergence_halt_policy_raises(tmp_path):
    faults.configure("io_next:nan:n=2")      # poison the 2nd batch
    mod = _fresh_module()
    with pytest.raises(DivergenceError):
        mod.fit(_iter(), num_epoch=1, divergence_check_every=1, **FIT_KW)


def test_divergence_skip_policy_continues(tmp_path):
    telemetry.enable()
    base = telemetry.counters().get("divergence.skipped", 0)
    faults.configure("io_next:nan:n=2")
    mod = _fresh_module()
    mod.fit(_iter(), num_epoch=1, divergence_check_every=1,
            divergence_policy="skip", **FIT_KW)
    # the poisoned batch's NaN sticks in the params, so every later
    # check also skips — at least the first detection must have counted
    assert telemetry.counters().get("divergence.skipped", 0) >= base + 1


def test_divergence_rollback_policy_restores_checkpoint(tmp_path):
    telemetry.enable()
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mod = _fresh_module()
    mod.fit(_iter(), num_epoch=1, checkpoint=mgr, **FIT_KW)   # ck @ ep1
    base = telemetry.counters().get("divergence.rollback", 0)
    faults.configure("io_next:nan:n=2")      # one poisoned batch
    mod.fit(_iter(), num_epoch=2, checkpoint=mgr, resume=True,
            divergence_check_every=1, divergence_policy="rollback",
            begin_epoch=1, **FIT_KW)
    assert telemetry.counters().get("divergence.rollback", 0) == base + 1
    assert mod.finite_check() is True        # recovered, finite params


def test_divergence_rollback_without_checkpoint_halts():
    faults.configure("io_next:nan:n=2")
    mod = _fresh_module()
    with pytest.raises(DivergenceError):
        mod.fit(_iter(), num_epoch=1, divergence_check_every=1,
                divergence_policy="rollback", **FIT_KW)


def test_bad_divergence_policy_rejected():
    mod = _fresh_module()
    with pytest.raises(MXNetError):
        mod.fit(_iter(), num_epoch=1, divergence_policy="explode",
                **FIT_KW)
