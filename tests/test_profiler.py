"""Profiler + monitor + viz suite — parity with reference test_profiler.py / test_viz.py."""
import json
import os
import subprocess
import sys

import numpy as np

import mxnet_tpu as mx


def test_profiler_chrome_trace(tmp_path):
    fname = str(tmp_path / "profile.json")
    mx.profiler.set_config(profile_all=True, filename=fname)
    mx.profiler.set_state("run")
    a = mx.nd.uniform(shape=(64, 64))
    b = mx.nd.dot(a, a)
    b.wait_to_read()
    mx.profiler.set_state("stop")
    mx.profiler.dump()
    assert os.path.exists(fname)
    with open(fname) as f:
        trace = json.load(f)
    events = trace.get("traceEvents", trace)
    assert isinstance(events, list) and len(events) > 0


def test_profiler_autostart_env(tmp_path):
    """MXNET_PROFILER_AUTOSTART=1 starts tracing at import (config.py
    _autostart_profiler); a later stop dumps the configured file."""
    code = (
        "import mxnet_tpu as mx\n"
        "assert mx.profiler._state['running'] is True, 'not autostarted'\n"
        "a = mx.nd.uniform(shape=(8, 8)); (a * a).wait_to_read()\n"
        "mx.profiler.set_state('stop')\n"
        "import os, json\n"
        "assert os.path.exists('profile.json')\n"
        "json.load(open('profile.json'))\n"
        "print('AUTOSTART_OK')\n")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, MXNET_PROFILER_AUTOSTART="1",
               JAX_PLATFORMS="cpu",
               PYTHONPATH=os.pathsep.join(
                   p for p in (root, os.environ.get("PYTHONPATH")) if p))
    proc = subprocess.run([sys.executable, "-c", code], cwd=str(tmp_path),
                          env=env, capture_output=True, text=True,
                          timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "AUTOSTART_OK" in proc.stdout


def test_profiler_scope_region_in_trace(tmp_path):
    """profiler.Scope annotates a region: the TraceAnnotation enters the
    device trace and the telemetry span lands in the merged dump."""
    fname = str(tmp_path / "scope_profile.json")
    mx.profiler.set_config(filename=fname)
    mx.profiler.set_state("run")
    with mx.profiler.Scope("my_hot_region"):
        a = mx.nd.uniform(shape=(32, 32))
        mx.nd.dot(a, a).wait_to_read()
    mx.profiler.set_state("stop")
    with open(fname) as f:
        trace = json.load(f)
    events = trace.get("traceEvents", trace)
    assert isinstance(events, list) and events
    host = [e for e in events if e.get("cat") == "host"]
    assert any(e["name"] == "my_hot_region" for e in host), \
        "Scope region missing from the merged host track"


def test_link_chrome_trace_fallback_no_gz(tmp_path):
    """When the backend produced NO .trace.json.gz (converter skipped),
    _link_chrome_trace must still materialise the configured filename —
    a host-span-only chrome trace, never a missing file."""
    from mxnet_tpu import telemetry
    fname = str(tmp_path / "fallback_profile.json")
    empty_dir = tmp_path / "empty_trace"
    empty_dir.mkdir()
    old = dict(mx.profiler._state)
    try:
        mx.profiler._state.update(
            {"running": False, "filename": fname, "dir": str(empty_dir)})
        telemetry.mark_trace_start()
        with telemetry.span("host_only_span"):
            pass
        mx.profiler._link_chrome_trace()
    finally:
        mx.profiler._state.update(old)
    with open(fname) as f:
        trace = json.load(f)
    names = {e["name"] for e in trace["traceEvents"]
             if e.get("cat") == "host"}
    assert "host_only_span" in names


def test_monitor_taps_outputs():
    mon = mx.monitor.Monitor(interval=1, sort=True)
    data = mx.sym.Variable("data")
    out = mx.sym.exp(data, name="expout")
    exe = out.simple_bind(ctx=mx.current_context(), data=(2, 2))
    mon.install(exe)
    exe.arg_dict["data"][:] = 1.0
    mon.tic()
    exe.forward()
    seen = [name for _, name, _ in mon.toc()]
    assert len(seen) > 0


def test_print_summary(capsys):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data=data, num_hidden=16, name="fc1")
    out = mx.sym.SoftmaxOutput(data=fc1, name="softmax")
    mx.visualization.print_summary(out, shape={"data": (1, 8)})
    captured = capsys.readouterr().out
    assert "fc1" in captured
    # 8*16 weights + 16 bias = 144 params
    assert "144" in captured


def test_plot_network_graphviz_or_skip():
    try:
        import graphviz  # noqa: F401
    except ImportError:
        return  # gated: graphviz not installed
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data=data, num_hidden=4)
    dot = mx.visualization.plot_network(out, shape={"data": (1, 8)})
    assert dot is not None


def test_scope_releases_span_when_annotation_fails():
    """mxlife resource-release fix: if the device TraceAnnotation
    fails to arm, the already-entered host span must close instead of
    staying open forever (every entered span exits)."""
    from mxnet_tpu import telemetry

    class _BoomAnn:
        def __enter__(self):
            raise RuntimeError("annotation failed to arm")

        def __exit__(self, *exc):
            return False

    telemetry.enable()
    scope = mx.profiler.Scope("failing_region")
    scope._ann = _BoomAnn()
    before = telemetry.span_count("failing_region")
    try:
        scope.__enter__()
    except RuntimeError:
        pass
    else:
        raise AssertionError("the arm failure must propagate")
    # the host span closed (one recorded sample), not leaked open
    assert telemetry.span_count("failing_region") == before + 1
