"""Profiler + monitor + viz suite — parity with reference test_profiler.py / test_viz.py."""
import json
import os

import numpy as np

import mxnet_tpu as mx


def test_profiler_chrome_trace(tmp_path):
    fname = str(tmp_path / "profile.json")
    mx.profiler.set_config(profile_all=True, filename=fname)
    mx.profiler.set_state("run")
    a = mx.nd.uniform(shape=(64, 64))
    b = mx.nd.dot(a, a)
    b.wait_to_read()
    mx.profiler.set_state("stop")
    mx.profiler.dump()
    assert os.path.exists(fname)
    with open(fname) as f:
        trace = json.load(f)
    events = trace.get("traceEvents", trace)
    assert isinstance(events, list) and len(events) > 0


def test_monitor_taps_outputs():
    mon = mx.monitor.Monitor(interval=1, sort=True)
    data = mx.sym.Variable("data")
    out = mx.sym.exp(data, name="expout")
    exe = out.simple_bind(ctx=mx.current_context(), data=(2, 2))
    mon.install(exe)
    exe.arg_dict["data"][:] = 1.0
    mon.tic()
    exe.forward()
    seen = [name for _, name, _ in mon.toc()]
    assert len(seen) > 0


def test_print_summary(capsys):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data=data, num_hidden=16, name="fc1")
    out = mx.sym.SoftmaxOutput(data=fc1, name="softmax")
    mx.visualization.print_summary(out, shape={"data": (1, 8)})
    captured = capsys.readouterr().out
    assert "fc1" in captured
    # 8*16 weights + 16 bias = 144 params
    assert "144" in captured


def test_plot_network_graphviz_or_skip():
    try:
        import graphviz  # noqa: F401
    except ImportError:
        return  # gated: graphviz not installed
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data=data, num_hidden=4)
    dot = mx.visualization.plot_network(out, shape={"data": (1, 8)})
    assert dot is not None
