"""Tier-1 smoke lanes for the user-facing Module.fit path.

Runs ``tools/module_fit_probe.py --fit-smoke`` (CPU backend, tiny MLP,
20 batches) as a subprocess and pins the two acceptance numbers:

- the fused whole-step program issues <= 2 jitted-program dispatches per
  batch (it is 1 today), the phase-split oracle exactly 3;
- fused Module.fit throughput >= the IN-RUN RECALIBRATED gate: the
  probe predicts the achievable speedup from the split leg's own phase
  spans (fused removes the dispatch chain, everything else stays) and
  gates at 70% of that, clamped to [1.2, 3.0] — the absolute >=3x gate
  false-failed on share-throttled boxes (2.4x at seed there) where
  inflated non-dispatch overhead shrinks the achievable ratio.

And ``--dp-smoke`` (the 8-device virtual CPU mesh): the fused SPMD
data-parallel step must issue EXACTLY 1 dispatch per batch and be at
least as fast as the kvstore phase-split path.

And ``--mp-smoke`` (the same mesh laid out 2x4 dp x mp with every
parameter rule-sharded over mp): 1 fused dispatch per batch, zero
fused fallbacks, per-device committed param bytes ~ 1/mp of the
replicated layout per the buffer ledger, fused >= phase-split.

The probes' JSON lands as artifacts (``$MXTPU_ARTIFACT_DIR/
module_fit_smoke.json`` / ``module_fit_dp_smoke.json``, default
/tmp/mxtpu_artifacts) so the img/s trajectory is captured every round
even when the TPU tunnel is down — the r03/r04 outages left no
user-path numbers at all.
"""
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_probe(art, lane_flag="--fit-smoke"):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # the fit lane measures single-program dispatch (the probe sets its
    # own virtual-mesh flag for --dp-smoke)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "module_fit_probe.py"),
         lane_flag, "--json-out", art],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, timeout=420, env=env, cwd=ROOT)
    assert proc.returncode == 0, proc.stdout[-2000:]
    with open(art) as f:
        return json.loads(f.read())


def test_module_fit_smoke_lane():
    art_dir = os.environ.get("MXTPU_ARTIFACT_DIR", "/tmp/mxtpu_artifacts")
    os.makedirs(art_dir, exist_ok=True)
    art = os.path.join(art_dir, "module_fit_smoke.json")
    try:
        out = _run_probe(art)
    except AssertionError:
        # epochs are ~10ms windows on share-throttled CI boxes — one
        # re-measure before declaring a throughput regression
        out = _run_probe(art)
    assert out["lane"] == "module_fit_smoke"
    fused, split = out["fused"], out["phase_split"]
    # the dispatch counts are the deterministic regression guard — any
    # extra program sneaking into either inner loop fails regardless of
    # timing noise
    assert fused["dispatches_per_batch"] <= 2.0, out
    assert split["dispatches_per_batch"] == 3.0, out
    assert fused["img_s"] > 0 and split["img_s"] > 0
    # the probe gates the throughput ratio against its in-run
    # recalibrated expectation and stamps the artifact; the gate value
    # itself must be sane (never laxer than 1.2x, never stricter than
    # the old absolute 3x)
    assert out["gates_passed"] is True, out
    assert 1.2 <= out["fit_gate"] <= 3.0, out
    assert out["fit_speedup"] >= out["fit_gate"], out
    assert out["fit_speedup_expected"] >= 1.0, out


def test_module_fit_mp_smoke_lane():
    """The dp x mp partition-rule lane (ISSUE 15 acceptance): tiny MLP
    on the 8-device CPU mesh as a 2x4 dp x mp layout, every parameter
    rule-sharded over mp. The probe gates 1 fused dispatch/batch, zero
    fused fallbacks, ledger param bytes per device ~ 1/mp of
    replicated, and fused >= phase-split; one re-measure under CI
    noise like the other lanes."""
    art_dir = os.environ.get("MXTPU_ARTIFACT_DIR", "/tmp/mxtpu_artifacts")
    os.makedirs(art_dir, exist_ok=True)
    art = os.path.join(art_dir, "module_fit_mp_smoke.json")
    try:
        out = _run_probe(art, "--mp-smoke")
    except AssertionError:
        out = _run_probe(art, "--mp-smoke")  # one retry under CI noise
    assert out["lane"] == "module_fit_mp_smoke"
    assert out["mesh_axes"] == {"dp": 2, "mp": 4}
    assert out["gates_passed"] is True, out
    assert out["fused"]["dispatches_per_batch"] == 1.0, out
    assert out["fused"]["dispatch_counts"] == {
        "train_step": out["nbatch"]}, out
    assert out["phase_split"]["dispatches_per_batch"] == 3.0, out
    assert out["mp_speedup"] >= 1.0, out
    led = out["ledger"]
    assert led["ratio"] <= 1.5 / led["mp"], led


def test_module_fit_dp_smoke_lane():
    """The data-parallel lane (ISSUE 2 acceptance): tiny MLP on the
    8-device virtual CPU mesh, fused-SPMD vs kvstore phase-split. The
    probe itself asserts the two gates — exactly 1 dispatch/batch on
    the fused path and dp-fused >= phase-split img/s — and banks the
    JSON artifact; timing noise gets one re-measure like the fit lane."""
    art_dir = os.environ.get("MXTPU_ARTIFACT_DIR", "/tmp/mxtpu_artifacts")
    os.makedirs(art_dir, exist_ok=True)
    art = os.path.join(art_dir, "module_fit_dp_smoke.json")
    try:
        out = _run_probe(art, "--dp-smoke")
    except AssertionError:
        out = _run_probe(art, "--dp-smoke")  # one retry under CI noise
    assert out["lane"] == "module_fit_dp_smoke"
    assert out["n_devices"] >= 2
    assert out["gates_passed"] is True, out
    assert out["fused"]["dispatches_per_batch"] == 1.0, out
    assert out["phase_split"]["dispatches_per_batch"] == 3.0, out
    assert out["dp_speedup"] >= 1.0, out
