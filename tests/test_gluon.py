"""Gluon suite — parity with reference tests/python/unittest/test_gluon.py."""
import os
import tempfile

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


def test_dense():
    layer = nn.Dense(5, in_units=3)
    layer.initialize()
    x = mx.nd.uniform(shape=(4, 3))
    y = layer(x)
    assert y.shape == (4, 5)
    w = layer.weight.data().asnumpy()
    b = layer.bias.data().asnumpy()
    np.testing.assert_allclose(y.asnumpy(), x.asnumpy().dot(w.T) + b,
                               rtol=1e-4, atol=1e-5)


def test_dense_deferred_init():
    layer = nn.Dense(7)  # in_units deferred
    layer.initialize()
    y = layer(mx.nd.uniform(shape=(2, 6)))
    assert y.shape == (2, 7)
    assert layer.weight.shape == (7, 6)


def test_sequential_and_hybrid_consistency():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dropout(0.0))
        net.add(nn.Dense(4))
    net.initialize()
    x = mx.nd.uniform(shape=(3, 8))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    np.testing.assert_allclose(eager, hybrid, rtol=1e-5, atol=1e-6)
    # second call hits the cached op
    hybrid2 = net(x).asnumpy()
    np.testing.assert_allclose(eager, hybrid2, rtol=1e-5, atol=1e-6)


def test_batchnorm_train_vs_eval():
    bn = nn.BatchNorm(in_channels=4)
    bn.initialize()
    x = mx.nd.uniform(shape=(8, 4), low=-1, high=3)
    with mx.autograd.record():
        y_train = bn(x)
    # training mode normalizes by batch stats
    out = y_train.asnumpy()
    assert abs(out.mean()) < 1e-2
    y_eval = bn(x)  # eval mode uses running stats (initially mean0/var1)
    assert not np.allclose(out, y_eval.asnumpy())


def test_conv_block():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(8, kernel_size=3, padding=1, in_channels=3))
        net.add(nn.MaxPool2D(pool_size=2))
        net.add(nn.Flatten())
        net.add(nn.Dense(6))
    net.initialize()
    y = net(mx.nd.uniform(shape=(2, 3, 8, 8)))
    assert y.shape == (2, 6)


def test_embedding():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    idx = mx.nd.array([1, 3, 1])
    out = emb(idx)
    assert out.shape == (3, 4)
    w = emb.weight.data().asnumpy()
    np.testing.assert_allclose(out.asnumpy(), w[[1, 3, 1]], rtol=1e-6)


def test_trainer_step_decreases_loss():
    np.random.seed(0)
    net = nn.Dense(1, in_units=2)
    net.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.3})
    x = mx.nd.uniform(shape=(16, 2))
    w_true = np.array([[2.0], [-3.0]], dtype=np.float32)
    y = mx.nd.array(x.asnumpy().dot(w_true))
    l2 = gluon.loss.L2Loss()
    losses = []
    for _ in range(80):
        with mx.autograd.record():
            loss = l2(net(x), y)
            total = loss.mean()
        total.backward()
        trainer.step(1)  # grads already averaged by the mean()
        losses.append(float(total.asnumpy()))
    assert losses[-1] < 0.05 * losses[0]


def test_save_load_params():
    net = nn.HybridSequential(prefix="model_")
    with net.name_scope():
        net.add(nn.Dense(4, in_units=3))
        net.add(nn.Dense(2, in_units=4))
    net.initialize()
    x = mx.nd.uniform(shape=(2, 3))
    y0 = net(x).asnumpy()
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "net.params")
        net.save_params(path)
        net2 = nn.HybridSequential(prefix="model_")
        with net2.name_scope():
            net2.add(nn.Dense(4, in_units=3))
            net2.add(nn.Dense(2, in_units=4))
        net2.load_params(path)
        np.testing.assert_allclose(net2(x).asnumpy(), y0, rtol=1e-6)


def test_parameter_dict_shared_scope():
    shared = gluon.ParameterDict("shared_")
    d1 = nn.Dense(4, in_units=4, params=shared.get_params()
                  if hasattr(shared, "get_params") else shared)
    assert d1 is not None


def test_dataloader_and_dataset():
    x = np.arange(20, dtype=np.float32).reshape(10, 2)
    y = np.arange(10, dtype=np.float32)
    ds = gluon.data.ArrayDataset(mx.nd.array(x), mx.nd.array(y))
    assert len(ds) == 10
    loader = gluon.data.DataLoader(ds, batch_size=4, shuffle=False)
    batches = list(loader)
    assert len(batches) == 3
    bx, by = batches[0]
    assert bx.shape == (4, 2) and by.shape == (4,)
    np.testing.assert_allclose(bx.asnumpy(), x[:4])
    loader = gluon.data.DataLoader(ds, batch_size=4, shuffle=False,
                                   last_batch="discard")
    assert len(list(loader)) == 2


def test_dataset_transform():
    ds = gluon.data.ArrayDataset(mx.nd.arange(10))
    ds2 = ds.transform(lambda x: x * 2) if hasattr(ds, "transform") else None
    if ds2 is not None:
        assert float(ds2[3].asnumpy()) == 6.0


def test_model_zoo_smoke():
    from mxnet_tpu.gluon.model_zoo import vision
    net = vision.resnet18_v1()
    net.initialize()
    y = net(mx.nd.uniform(shape=(1, 3, 32, 32)))
    assert y.shape == (1, 1000)


def test_rnn_layer():
    from mxnet_tpu.gluon import rnn
    layer = rnn.LSTM(hidden_size=8, num_layers=1)
    layer.initialize()
    x = mx.nd.uniform(shape=(5, 2, 4))  # (T, N, C)
    out = layer(x)
    assert out.shape == (5, 2, 8)


def test_block_apply_and_collect():
    net = nn.Sequential()
    net.add(nn.Dense(3, in_units=2))
    net.add(nn.Dense(2, in_units=3))
    names = list(net.collect_params().keys())
    assert len(names) == 4  # two weights + two biases
    seen = []
    net.apply(lambda b: seen.append(b.name))
    assert len(seen) >= 2


def test_dataloader_process_workers():
    """Process mode (reference's multiprocessing+shm DataLoader): forked
    accelerator-free workers ship batches through POSIX shared memory and
    reproduce the single-process output exactly, in order."""
    x = np.arange(48, dtype=np.float32).reshape(24, 2)
    y = np.arange(24, dtype=np.float32)
    ds = gluon.data.ArrayDataset(mx.nd.array(x), mx.nd.array(y))
    ref = [(bx.asnumpy(), by.asnumpy()) for bx, by in
           gluon.data.DataLoader(ds, batch_size=5, shuffle=False)]
    loader = gluon.data.DataLoader(ds, batch_size=5, shuffle=False,
                                   num_workers=2, thread_pool=False)
    got = [(bx.asnumpy(), by.asnumpy()) for bx, by in loader]
    assert len(got) == len(ref)
    for (gx, gy), (rx, ry) in zip(got, ref):
        np.testing.assert_allclose(gx, rx)
        np.testing.assert_allclose(gy, ry)
    # second epoch works (fresh worker pool)
    assert len(list(loader)) == len(ref)


def test_dataloader_process_fallback_warns():
    """Datasets without a raw host-only path fall back to threads."""
    ds = gluon.data.ArrayDataset(mx.nd.arange(10)).transform(lambda v: v)
    loader = gluon.data.DataLoader(ds, batch_size=2, num_workers=2,
                                   thread_pool=False)
    import warnings as _w
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        batches = list(loader)
    assert len(batches) == 5
    assert any("falling back to threads" in str(r.message) for r in rec)


def test_dataloader_rollover():
    """last_batch='rollover' carries the incomplete batch into the next
    epoch (reference BatchSampler semantics)."""
    ds = gluon.data.ArrayDataset(mx.nd.arange(10))
    loader = gluon.data.DataLoader(ds, batch_size=4, shuffle=False,
                                   last_batch="rollover")
    e1 = list(loader)
    assert [b.shape[0] for b in e1] == [4, 4]          # 2 left over
    e2 = list(loader)
    assert [b.shape[0] for b in e2] == [4, 4, 4]       # 2 + 10 = 12
    np.testing.assert_allclose(e2[0].asnumpy()[:2], [8.0, 9.0])


def test_model_zoo_reference_names():
    """Every get_model name the reference's model_store serves resolves
    here, including the dotted spellings (model_store.py:27-57)."""
    from mxnet_tpu.gluon.model_zoo import vision
    names = ["alexnet", "densenet121", "densenet161", "densenet169",
             "densenet201", "inceptionv3", "mobilenet0.25", "mobilenet0.5",
             "mobilenet0.75", "mobilenet1.0", "resnet18_v1", "resnet34_v1",
             "resnet50_v1", "resnet101_v1", "resnet152_v1", "resnet18_v2",
             "resnet34_v2", "resnet50_v2", "resnet101_v2", "resnet152_v2",
             "squeezenet1.0", "squeezenet1.1", "vgg11", "vgg11_bn", "vgg13",
             "vgg13_bn", "vgg16", "vgg16_bn", "vgg19", "vgg19_bn"]
    for n in names:
        net = vision.get_model(n)
        assert net is not None, n


def test_dataloader_custom_sampler_honored():
    """A user sampler drives index order (was silently ignored)."""
    ds = gluon.data.ArrayDataset(mx.nd.arange(8))
    order = [7, 6, 5, 4, 3, 2, 1, 0]

    class Rev(gluon.data.Sampler):
        def __iter__(self):
            return iter(order)

        def __len__(self):
            return 8

    loader = gluon.data.DataLoader(ds, batch_size=4, sampler=Rev())
    got = np.concatenate([b.asnumpy() for b in loader])
    np.testing.assert_allclose(got, order)
    with np.testing.assert_raises(Exception):
        gluon.data.DataLoader(ds, batch_size=4, sampler=Rev(), shuffle=True)


def test_sparse_array_scipy_and_dense_rejection():
    import pytest as _pytest
    import scipy.sparse as sps
    m = sps.csr_matrix(np.eye(3, dtype=np.float32))
    a = mx.nd.sparse.array(m)
    assert a.stype == "csr"
    np.testing.assert_allclose(a.asnumpy(), np.eye(3))
    with _pytest.raises(Exception):
        mx.nd.sparse.array([[0, 1], [2, 0]])


def test_dataloader_process_early_close_no_shm_leak():
    """Breaking out of a process-mode epoch reclaims every produced shm
    segment (regression: out_q results leaked on early close)."""
    import glob
    x = np.arange(80, dtype=np.float32).reshape(40, 2)
    ds = gluon.data.ArrayDataset(mx.nd.array(x))
    loader = gluon.data.DataLoader(ds, batch_size=4, num_workers=2,
                                   thread_pool=False)
    before = set(glob.glob("/dev/shm/*"))
    it = iter(loader)
    next(it)
    it.close()          # triggers the generator's finally
    import time
    leaked = set()
    for _ in range(10):  # teardown is async; poll before declaring a leak
        leaked = set(glob.glob("/dev/shm/*")) - before
        if not leaked:
            break
        time.sleep(0.5)
    assert not leaked, leaked
