"""Storage + resource manager suite (parity model: reference
tests/cpp/storage/storage_test.cc semantics exercised from Python)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.storage import Storage
from mxnet_tpu.resource import ResourceManager, request


def test_alloc_view_free():
    sto = Storage.get()
    h = sto.alloc(1024)
    arr = h.array((16, 16), np.float32)
    arr[:] = 3.0
    assert arr.sum() == 3.0 * 256
    sto.free(h)
    # double free is a no-op
    sto.free(h)


def test_use_after_free_rejected():
    sto = Storage.get()
    h = sto.alloc(64)
    sto.free(h)
    try:
        h.array((4,), np.float32)
        raise AssertionError("expected use-after-free error")
    except mx.MXNetError:
        pass


def test_pool_reuses_buffers():
    sto = Storage.get()
    if not sto.native:
        return  # fallback path has no pool
    h1 = sto.alloc(5000)
    ptr = h1.ptr
    sto.free(h1)
    h2 = sto.alloc(6000)  # same 8KB bucket -> same buffer back
    assert h2.ptr == ptr
    sto.free(h2)


def test_stats_track_allocation():
    sto = Storage.get()
    before = sto.stats()["allocated"]
    h = sto.alloc(4096)
    during = sto.stats()["allocated"]
    assert during >= before + 4096
    sto.free(h)
    assert sto.stats()["allocated"] <= before + (during - before) - 4096 + 1


def test_direct_free_bypasses_pool():
    sto = Storage.get()
    if not sto.native:
        return
    sto.release_all()
    h = sto.alloc(4096)
    sto.direct_free(h)
    assert sto.stats()["pooled"] == 0


def test_view_larger_than_alloc_rejected():
    sto = Storage.get()
    h = sto.alloc(64)
    try:
        h.array((1024,), np.float32)
        raise AssertionError("expected oversize view error")
    except mx.MXNetError:
        pass
    finally:
        sto.free(h)


def test_resource_temp_space_reuse():
    r1 = request(req="temp_space")
    a = r1.get_space((8, 8))
    a[:] = 1.0
    r2 = request(req="temp_space")  # MXNET_EXEC_NUM_TEMP=1 -> same slot
    b = r2.get_space((8, 8))
    assert a.ctypes.data == b.ctypes.data


def test_resource_random_keys_differ():
    import jax
    r = request(req="random")
    k1, k2 = r.get_key(), r.get_key()
    assert not np.array_equal(np.asarray(jax.random.key_data(k1)),
                              np.asarray(jax.random.key_data(k2)))


def test_device_stats_dict():
    stats = Storage.device_stats()
    assert isinstance(stats, dict)
