"""Shape-inference suite — parity with reference tests/python/unittest/test_infer_shape.py."""
import mxnet_tpu as mx


def _mlp():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data=data, num_hidden=128, name="fc1")
    act = mx.sym.Activation(data=fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(data=act, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(data=fc2, name="softmax")


def test_mlp_infer_shape():
    net = _mlp()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(32, 784))
    args = net.list_arguments()
    d = dict(zip(args, arg_shapes))
    assert d["fc1_weight"] == (128, 784)
    assert d["fc1_bias"] == (128,)
    assert d["fc2_weight"] == (10, 128)
    assert out_shapes[0] == (32, 10)


def test_conv_infer_shape():
    data = mx.sym.Variable("data")
    conv = mx.sym.Convolution(data=data, num_filter=16, kernel=(3, 3),
                              pad=(1, 1), name="conv")
    arg_shapes, out_shapes, _ = conv.infer_shape(data=(2, 3, 8, 8))
    d = dict(zip(conv.list_arguments(), arg_shapes))
    assert d["conv_weight"] == (16, 3, 3, 3)
    assert out_shapes[0] == (2, 16, 8, 8)


def test_partial_infer():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=4, name="fc")
    # without data shape, partial infer must not raise
    arg_shapes, out_shapes, _ = fc.infer_shape_partial()
    assert out_shapes is None or len(out_shapes) == 1


def test_elemwise_broadcast_infer():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = mx.sym.broadcast_add(a, b)
    _, out_shapes, _ = out.infer_shape(a=(3, 1), b=(1, 4))
    assert out_shapes[0] == (3, 4)


def test_infer_type():
    a = mx.sym.Variable("a")
    out = mx.sym.exp(a)
    arg_types, out_types, _ = out.infer_type(a="float32")
    assert out_types[0] == "float32" or str(out_types[0]).endswith("float32")


def test_reshape_transpose_chain():
    data = mx.sym.Variable("data")
    out = mx.sym.transpose(mx.sym.reshape(data, shape=(0, -1)))
    _, out_shapes, _ = out.infer_shape(data=(4, 2, 3))
    assert out_shapes[0] == (6, 4)
