"""Distributed/sharding tests on the virtual 8-device CPU mesh
(parity model: reference tests/nightly/dist_sync_kvstore.py run via
launch.py local mode — multi-device semantics without a cluster)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import parallel


def test_mesh_creation():
    mesh = parallel.make_mesh({"dp": 4, "tp": 2})
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2
    mesh2 = parallel.make_mesh({"dp": -1})
    assert mesh2.shape["dp"] == 8


def test_ring_attention_matches_reference():
    np.random.seed(0)
    B, H, S, D = 2, 4, 16, 8
    q = np.random.normal(size=(B, H, S, D)).astype(np.float32)
    k = np.random.normal(size=(B, H, S, D)).astype(np.float32)
    v = np.random.normal(size=(B, H, S, D)).astype(np.float32)
    mesh = parallel.make_mesh({"sp": 4})
    ref = parallel.attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    out = parallel.ring_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), mesh, axis_name="sp")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-5)


def test_ring_attention_causal():
    np.random.seed(1)
    B, H, S, D = 1, 2, 8, 4
    q = np.random.normal(size=(B, H, S, D)).astype(np.float32)
    k = np.random.normal(size=(B, H, S, D)).astype(np.float32)
    v = np.random.normal(size=(B, H, S, D)).astype(np.float32)
    mesh = parallel.make_mesh({"sp": 4})
    ref = parallel.attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             causal=True)
    out = parallel.ring_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), mesh, axis_name="sp",
                                  causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-5)


def test_spmd_trainer_dp():
    """Sharded dp training must match single-device numerics."""
    np.random.seed(0)
    W = np.random.normal(0, 0.1, (4, 8)).astype(np.float32)
    b = np.zeros((4,), np.float32)
    X = np.random.normal(size=(16, 8)).astype(np.float32)
    Y = np.random.randint(0, 4, 16).astype(np.int32)

    def apply_fn(params, x, y):
        logits = x @ params["w"].T + params["b"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    mesh = parallel.make_mesh({"dp": 8})
    tr = parallel.SPMDTrainer(apply_fn, {"w": W.copy(), "b": b.copy()}, mesh,
                              data_axis="dp", learning_rate=0.1)
    losses = [float(tr.step(X, Y)) for _ in range(3)]
    assert losses[2] < losses[0]

    # single-device reference
    params = {"w": jnp.asarray(W), "b": jnp.asarray(b)}
    for _ in range(3):
        loss, grads = jax.value_and_grad(apply_fn)(params, jnp.asarray(X),
                                                   jnp.asarray(Y))
        params = {k: params[k] - 0.1 * grads[k] for k in params}
    got = tr.get_params()
    np.testing.assert_allclose(got["w"], np.asarray(params["w"]), rtol=1e-4,
                               atol=1e-5)


def test_spmd_trainer_dp_tp():
    np.random.seed(0)
    W1 = np.random.normal(0, 0.1, (16, 8)).astype(np.float32)
    W2 = np.random.normal(0, 0.1, (4, 16)).astype(np.float32)
    X = np.random.normal(size=(8, 8)).astype(np.float32)
    Y = np.random.randint(0, 4, 8).astype(np.int32)

    def apply_fn(params, x, y):
        h = jnp.maximum(x @ params["w1"].T, 0)
        logits = h @ params["w2"].T
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    mesh = parallel.make_mesh({"dp": 4, "tp": 2})
    tr = parallel.SPMDTrainer(apply_fn, {"w1": W1, "w2": W2}, mesh,
                              data_axis="dp", tp_axis="tp",
                              learning_rate=0.1, momentum=0.9)
    l0 = float(tr.step(X, Y))
    l1 = float(tr.step(X, Y))
    l2 = float(tr.step(X, Y))
    assert l2 < l0


def test_collectives_shard_map():
    # parallel.shard_map is the version shim: jax.shard_map where the
    # installed JAX has it, the jax.experimental implementation otherwise
    mesh = parallel.make_mesh({"dp": 8})
    x = jnp.arange(8.0)

    def f(v):
        return parallel.all_reduce(v, "dp")

    out = parallel.shard_map(f, mesh=mesh,
                             in_specs=jax.sharding.PartitionSpec("dp"),
                             out_specs=jax.sharding.PartitionSpec("dp"))(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))


def test_kvstore_multi_device_push_pull():
    """The single-process multi-'device' kvstore semantics test
    (parity: tests/nightly/test_kvstore.py)."""
    from mxnet_tpu import nd
    kv = mx.kvstore.create("device")
    kv.init(3, nd.ones((2, 3)))
    grads = [nd.ones((2, 3)) * (i + 1) for i in range(4)]
    kv.push(3, grads)
    out = nd.zeros((2, 3))
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 3), 10.0))


def test_ulysses_attention_matches_reference():
    np.random.seed(2)
    B, H, S, D = 2, 8, 16, 4  # H divisible by sp=4
    q = np.random.normal(size=(B, H, S, D)).astype(np.float32)
    k = np.random.normal(size=(B, H, S, D)).astype(np.float32)
    v = np.random.normal(size=(B, H, S, D)).astype(np.float32)
    mesh = parallel.make_mesh({"sp": 4})
    ref = parallel.attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    out = parallel.ulysses_attention(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), mesh, axis_name="sp")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-5)


def test_ulysses_attention_causal():
    np.random.seed(3)
    B, H, S, D = 1, 4, 16, 4
    q = np.random.normal(size=(B, H, S, D)).astype(np.float32)
    k = np.random.normal(size=(B, H, S, D)).astype(np.float32)
    v = np.random.normal(size=(B, H, S, D)).astype(np.float32)
    mesh = parallel.make_mesh({"sp": 4})
    ref = parallel.attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             causal=True)
    out = parallel.ulysses_attention(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), mesh, axis_name="sp",
                                     causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4,
                               atol=2e-5)


def test_ulysses_rejects_uneven_heads():
    import pytest
    mesh = parallel.make_mesh({"sp": 4})
    q = jnp.zeros((1, 3, 16, 4))  # 3 heads not divisible by 4
    with pytest.raises(Exception, match="divisible"):
        parallel.ulysses_attention(q, q, q, mesh, axis_name="sp")


def test_ulysses_differentiable():
    np.random.seed(4)
    B, H, S, D = 1, 4, 16, 4
    q = jnp.asarray(np.random.normal(size=(B, H, S, D)).astype(np.float32))
    mesh = parallel.make_mesh({"sp": 4})

    def loss(q, k, v):
        return parallel.ulysses_attention(q, k, v, mesh,
                                          axis_name="sp").sum()

    g = jax.grad(loss)(q, q, q)
    assert g.shape == q.shape
    assert np.isfinite(np.asarray(g)).all()


def test_spmd_trainer_adam_matches_eager():
    """dp/tp Adam in the sharded step must match the eager mx.optimizer
    Adam applied to the same grads (VERDICT r1 #9 done-criterion)."""
    import mxnet_tpu as mx
    from mxnet_tpu import nd

    np.random.seed(0)
    W = np.random.normal(0, 0.1, (8, 8)).astype(np.float32)
    X = np.random.normal(size=(16, 8)).astype(np.float32)
    Y = np.random.randint(0, 8, 16).astype(np.int32)

    def apply_fn(params, x, y):
        logits = x @ params["w"].T
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    mesh = parallel.make_mesh({"dp": 4, "tp": 2})
    opt = mx.optimizer.Adam(learning_rate=0.05)
    tr = parallel.SPMDTrainer(apply_fn, {"w": W.copy()}, mesh,
                              data_axis="dp", tp_axis="tp", optimizer=opt)
    for _ in range(3):
        tr.step(X, Y)

    # eager reference: same grads through mx.optimizer.Adam
    eager_opt = mx.optimizer.Adam(learning_rate=0.05)
    weight = nd.array(W.copy())
    state = eager_opt.create_state(0, weight)
    params = {"w": jnp.asarray(W)}
    for _ in range(3):
        _, grads = jax.value_and_grad(apply_fn)(params, jnp.asarray(X),
                                                jnp.asarray(Y))
        eager_opt.update(0, weight, nd.array(np.asarray(grads["w"])), state)
        params = {"w": weight._data}
    np.testing.assert_allclose(tr.get_params()["w"], weight.asnumpy(),
                               rtol=1e-4, atol=1e-5)


def test_spmd_trainer_rmsprop_and_adagrad_run():
    np.random.seed(0)
    W = np.random.normal(0, 0.1, (4, 8)).astype(np.float32)
    X = np.random.normal(size=(8, 8)).astype(np.float32)
    Y = np.random.randint(0, 4, 8).astype(np.int32)

    def apply_fn(params, x, y):
        logits = x @ params["w"].T
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))

    mesh = parallel.make_mesh({"dp": 8})
    for name, kw in [("rmsprop", {"gamma1": 0.9, "epsilon": 1e-8}),
                     ("adagrad", {"eps": 1e-7}),
                     ("adagrad", {}),          # registry defaults path
                     ("nag", {"momentum": 0.9})]:
        tr = parallel.SPMDTrainer(apply_fn, {"w": W.copy()}, mesh,
                                  data_axis="dp", optimizer=name,
                                  learning_rate=0.05, **kw)
        l0 = float(tr.step(X, Y))
        l1 = float(tr.step(X, Y))
        l2 = float(tr.step(X, Y))
        assert np.isfinite(l1) and np.isfinite(l2) and l2 < l0, \
            (name, l0, l1, l2)


def test_moe_ffn_matches_dense_oracle():
    """Expert-parallel MoE (all_to_all dispatch) must equal the dense
    per-token oracle wherever capacity is not exceeded."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu import parallel

    n = 4
    mesh = parallel.make_mesh({"ep": n})
    rs = np.random.RandomState(0)
    B, T, E, F = 4, 8, 16, 32
    x = rs.randn(B, T, E).astype(np.float32) * 0.5
    wr = rs.randn(n, E).astype(np.float32)
    w1 = rs.randn(n, F, E).astype(np.float32) * 0.1
    w2 = rs.randn(n, E, F).astype(np.float32) * 0.1

    got = np.asarray(parallel.moe_ffn(jnp.asarray(x), jnp.asarray(wr),
                                      jnp.asarray(w1), jnp.asarray(w2),
                                      mesh, capacity_factor=8.0))

    flat = x.reshape(-1, E)
    logits = flat @ wr.T
    probs = np.exp(logits - logits.max(1, keepdims=True))
    probs /= probs.sum(1, keepdims=True)
    exp = probs.argmax(1)
    gate = probs[np.arange(len(flat)), exp]
    want = np.zeros_like(flat)
    for i, (tok, e) in enumerate(zip(flat, exp)):
        h = np.maximum(tok @ w1[e].T, 0)
        want[i] = (h @ w2[e].T) * gate[i]
    np.testing.assert_allclose(got.reshape(-1, E), want, rtol=1e-4,
                               atol=1e-4)


def test_moe_capacity_drops_tokens():
    """Overflow tokens contribute exactly zero (switch convention)."""
    import jax.numpy as jnp
    from mxnet_tpu import parallel

    n = 2
    mesh = parallel.make_mesh({"ep": n})
    rs = np.random.RandomState(1)
    B, T, E, F = 2, 8, 8, 8
    x = rs.randn(B, T, E).astype(np.float32)
    # router that sends EVERY token to expert 0
    wr = np.zeros((n, E), np.float32)
    wr[0] = 1e3 * np.ones(E) @ np.eye(E)
    wr[0, 0] = 1e3
    w1 = np.ones((n, F, E), np.float32) * 0.01
    w2 = np.ones((n, E, F), np.float32) * 0.01
    out = np.asarray(parallel.moe_ffn(
        jnp.asarray(np.abs(x)), jnp.asarray(wr), jnp.asarray(w1),
        jnp.asarray(w2), mesh, capacity_factor=0.3))
    # some tokens must be zeroed (capacity < tokens routed to expert 0)
    flat = out.reshape(-1, E)
    assert (np.abs(flat).sum(1) == 0).any()
    assert (np.abs(flat).sum(1) > 0).any()


def test_pipeline_matches_sequential():
    """GPipe pipeline over the 'pp' axis equals applying the stages in
    sequence; gradients flow through the scan/ppermute schedule."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu import parallel

    n = 4
    mesh = parallel.make_mesh({"pp": n})
    rs = np.random.RandomState(2)
    E = 8
    n_micro = 6
    x = rs.randn(n_micro, 3, E).astype(np.float32)
    w = rs.randn(n, E, E).astype(np.float32) * 0.3
    b = rs.randn(n, E).astype(np.float32) * 0.1

    def stage(params, mb):
        return jnp.tanh(mb @ params["w"] + params["b"])

    got = np.asarray(parallel.pipeline_apply(
        stage, {"w": jnp.asarray(w), "b": jnp.asarray(b)},
        jnp.asarray(x), mesh, axis_name="pp"))

    want = x.copy()
    for s in range(n):
        want = np.tanh(want @ w[s] + b[s])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    # differentiable end to end
    def loss(ws):
        out = parallel.pipeline_apply(
            stage, {"w": ws, "b": jnp.asarray(b)}, jnp.asarray(x), mesh)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(jnp.asarray(w))
    assert np.isfinite(np.asarray(g)).all() and np.abs(g).sum() > 0
