"""Predictor (c_predict_api parity) tests: checkpoint -> standalone
inference round trip (reference model: c_predict_api.cc + amalgamation)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.predictor import Predictor, create as pred_create


def _mlp_symbol():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=3, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _trained_params(symbol):
    rng = np.random.RandomState(0)
    shapes, _, _ = symbol.infer_shape_partial(data=(2, 5))
    args = {}
    for name, shape in zip(symbol.list_arguments(), shapes):
        if name in ("data", "softmax_label"):
            continue
        args[name] = mx.nd.array(rng.normal(0, 0.1, shape)
                                 .astype(np.float32))
    return args


def test_predictor_matches_executor(tmp_path):
    symbol = _mlp_symbol()
    arg_params = _trained_params(symbol)
    prefix = str(tmp_path / "model")
    mx.model.save_checkpoint(prefix, 7, symbol, arg_params, {})

    x = np.random.RandomState(1).normal(size=(2, 5)).astype(np.float32)

    # ground truth through the training-side executor
    args = dict(arg_params)
    args["data"] = mx.nd.array(x)
    args["softmax_label"] = mx.nd.zeros((2,))
    ref = symbol.bind(None, args, grad_req="null").forward(is_train=False)

    pred = pred_create(prefix + "-symbol.json", prefix + "-0007.params",
                       {"data": (2, 5)})
    pred.forward(data=x)
    out = pred.get_output(0)
    np.testing.assert_allclose(out.asnumpy(), ref[0].asnumpy(), rtol=1e-5,
                               atol=1e-6)


def test_predictor_reshape(tmp_path):
    symbol = _mlp_symbol()
    arg_params = _trained_params(symbol)
    pred = Predictor(symbol, {("arg:%s" % k): v
                              for k, v in arg_params.items()},
                     {"data": (2, 5)})
    p2 = pred.reshape({"data": (4, 5)})
    x = np.random.RandomState(2).normal(size=(4, 5)).astype(np.float32)
    p2.forward(data=x)
    assert p2.get_output(0).shape == (4, 3)


def test_predictor_reshape_one_compile_per_signature():
    """reshape shares the donor's compiled-program cache: bouncing
    between two shapes compiles each (shape, dtype) signature ONCE —
    asserted via telemetry.programs() (one card per compiled signature)
    and the jit compile/hit counters."""
    from mxnet_tpu import telemetry
    symbol = _mlp_symbol()
    arg_params = _trained_params(symbol)
    params = {("arg:%s" % k): v for k, v in arg_params.items()}
    telemetry.reset()
    pred = Predictor(symbol, params, {"data": (2, 5)})
    rng = np.random.RandomState(3)
    pred.forward(data=rng.normal(size=(2, 5)).astype(np.float32))
    entry = pred._executor._prog.forward_fn(False).entry
    p2 = pred.reshape({"data": (4, 5)})
    p2.forward(data=rng.normal(size=(4, 5)).astype(np.float32))
    p3 = p2.reshape({"data": (2, 5)})     # back to the original shape
    p3.forward(data=rng.normal(size=(2, 5)).astype(np.float32))
    p2.forward(data=rng.normal(size=(4, 5)).astype(np.float32))
    cards = [k for k in telemetry.programs()
             if k.startswith(entry + "/")]
    assert len(cards) == 2, cards          # (2,5) and (4,5) — no more
    counters = telemetry.counters()
    # five forward_fn lookups on ONE shared program (4 forwards + the
    # entry read above): 1 build + 4 hits
    assert counters.get("jit.compile.forward", 0) == 1
    assert counters.get("jit.hit.forward", 0) == 4


def test_predictor_reshape_then_results_match_fresh_bind():
    """The shared-cache reshape is numerically the same predictor a
    fresh bind would build."""
    symbol = _mlp_symbol()
    arg_params = _trained_params(symbol)
    params = {("arg:%s" % k): v for k, v in arg_params.items()}
    pred = Predictor(symbol, params, {"data": (2, 5)})
    p2 = pred.reshape({"data": (4, 5)})
    x = np.random.RandomState(4).normal(size=(4, 5)).astype(np.float32)
    p2.forward(data=x)
    fresh = Predictor(symbol, params, {"data": (4, 5)})
    fresh.forward(data=x)
    np.testing.assert_array_equal(p2.get_output(0).asnumpy(),
                                  fresh.get_output(0).asnumpy())


def test_c_predict_reshape_helper():
    """c_predict.reshape (MXPredReshape parity) routes through the
    shared-cache Predictor.reshape."""
    from mxnet_tpu import c_predict
    symbol = _mlp_symbol()
    arg_params = _trained_params(symbol)
    pred = Predictor(symbol, {("arg:%s" % k): v
                              for k, v in arg_params.items()},
                     {"data": (2, 5)})
    p2 = c_predict.reshape(pred, ["data"], [(4, 5)])
    assert p2._input_shapes["data"] == (4, 5)
    assert p2._executor._prog is pred._executor._prog
    p2.forward(data=np.zeros((4, 5), np.float32))
    assert p2.get_output(0).shape == (4, 3)


def test_predictor_rejects_bad_shape():
    symbol = _mlp_symbol()
    arg_params = _trained_params(symbol)
    pred = Predictor(symbol, arg_params, {"data": (2, 5)})
    try:
        pred.set_input("data", np.zeros((3, 5), np.float32))
    except mx.MXNetError as e:
        assert "reshape" in str(e)
    else:
        raise AssertionError("shape mismatch not caught")
