"""Visualization suite (parity model: reference
tests/python/unittest/test_viz.py — print_summary over an MLP/conv net,
plot_network gated on graphviz)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _net():
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4,
                             pad=(1, 1), name="conv")
    net = mx.sym.Activation(net, act_type="relu", name="relu")
    net = mx.sym.Flatten(net, name="flatten")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_print_summary_param_counts(capsys):
    mx.viz.print_summary(_net(), shape={"data": (1, 3, 8, 8)})
    out = capsys.readouterr().out
    assert "conv" in out and "fc" in out
    # conv: 3*3*3*4 + 4 = 112; fc: 4*8*8*10 + 10 = 2570
    assert "112" in out
    assert "2570" in out
    total = [ln for ln in out.splitlines() if "Total params" in ln]
    assert total and "2682" in total[0]


def test_print_summary_without_shape(capsys):
    mx.viz.print_summary(_net())
    out = capsys.readouterr().out
    assert "softmax" in out


def test_plot_network_nodes():
    try:
        import graphviz  # noqa: F401
    except ImportError:
        pytest.skip("graphviz not installed")
    dot = mx.viz.plot_network(_net(), shape={"data": (1, 3, 8, 8)})
    src = dot.source
    for node in ("conv", "fc", "softmax"):
        assert node in src
