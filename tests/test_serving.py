"""Inference serving engine: bucketed AOT programs + micro-batcher.

Equivalence methodology: XLA specializes kernels per batch SHAPE, so two
programs at different batch sizes can differ by 1 ULP in row results
even for a plain FC stack (fusion/vectorization choices — measured, not
a batching artifact). The batching machinery itself must therefore be
bit-exact at FIXED program shape:

- requests whose rows fill a bucket exactly compare bit-exact against an
  unbatched forward of the same rows (same signature -> same program);
- padded dispatches compare bit-exact against the same padded batch fed
  through a plain Predictor at the bucket shape, sliced;
- cross-bucket-shape comparisons are ULP-tight (atol 1e-6) and exist to
  document the kernel-specialization reality.
"""
import logging
import time

import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.predictor import Predictor
from mxnet_tpu.serving import InferenceEngine, bucket_sizes

D, C = 5, 3


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=C, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _params(symbol, dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    shapes, _, _ = symbol.infer_shape_partial(data=(2, D))
    out = {}
    for name, shape in zip(symbol.list_arguments(), shapes):
        if name in ("data", "softmax_label"):
            continue
        out["arg:" + name] = mx.nd.array(
            rng.normal(0, 0.5, shape).astype(np.float32)).astype(dtype)
    return out


def _engine(params=None, dtype=None, **kw):
    sym = _mlp()
    params = params if params is not None else _params(sym)
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 20.0)
    return sym, params, InferenceEngine(sym, params, {"data": (1, D)},
                                        dtype=dtype, **kw)


def test_bucket_sizes():
    assert bucket_sizes(8) == [1, 2, 4, 8]
    assert bucket_sizes(1) == [1]
    # a non-pow2 max_batch stays a bucket so a full batch never pads
    assert bucket_sizes(12) == [1, 2, 4, 8, 12]
    with pytest.raises(mx.MXNetError):
        bucket_sizes(0)


def test_full_bucket_bit_exact_vs_unbatched():
    """Requests coalescing to EXACTLY a bucket are bit-exact against an
    unbatched forward of the same rows — same abstract signature, same
    program."""
    sym, params, eng = _engine(max_wait_ms=500.0)
    rng = np.random.RandomState(1)
    xs = [rng.normal(size=(1, D)).astype(np.float32) for _ in range(4)]
    with eng:
        futs = [eng.submit(data=x) for x in xs]
        outs = [f.result(timeout=60) for f in futs]
    assert eng.stats()["buckets"] == {"4": 1}
    oracle = Predictor(sym, params, {"data": (4, D)})
    oracle.forward(data=np.concatenate(xs, axis=0))
    ref = oracle.get_output(0).asnumpy()
    for i, o in enumerate(outs):
        np.testing.assert_array_equal(o[0], ref[i:i + 1])


@pytest.mark.parametrize("dtype", [np.float32, jnp.bfloat16])
def test_padded_slice_bit_exact(dtype):
    """bucket_size+1 rows land in the next bucket zero-padded; the
    sliced result is bit-exact against the same padded batch through a
    plain Predictor at the bucket shape (fp32 and bf16)."""
    dtype = np.dtype(dtype)
    sym = _mlp()
    params = _params(sym, dtype=dtype)
    eng = InferenceEngine(sym, params, {"data": (1, D)}, dtype=dtype,
                          max_batch=8, max_wait_ms=10_000.0)
    rng = np.random.RandomState(2)
    x = rng.normal(size=(5, D)).astype(np.float32)  # 4+1: pads to 8
    with eng:
        fut = eng.submit(data=x)
        eng.flush()
        out = fut.result(timeout=60)
    st = eng.stats()
    assert st["buckets"] == {"8": 1}
    assert st["pad_rows"] == 3
    padded = np.zeros((8, D), np.float32)
    padded[:5] = x
    oracle = Predictor(sym, params, {"data": (8, D)}, dtype=dtype)
    oracle.forward(data=padded)
    ref = oracle.get_output(0).asnumpy()[:5]
    np.testing.assert_array_equal(out[0], ref)


@pytest.mark.parametrize("dtype", [np.float32, jnp.bfloat16])
def test_multi_request_packing_bit_exact(dtype):
    """Mixed-row requests (1+2+1 rows) pack into one bucket in FIFO
    order; each request's slice is bit-exact against the unbatched
    forward of the packed batch (rows sum to the bucket — no padding)."""
    dtype = np.dtype(dtype)
    sym = _mlp()
    params = _params(sym, dtype=dtype)
    eng = InferenceEngine(sym, params, {"data": (1, D)}, dtype=dtype,
                          max_batch=4, max_wait_ms=10_000.0)
    rng = np.random.RandomState(3)
    parts = [rng.normal(size=(r, D)).astype(np.float32) for r in (1, 2, 1)]
    with eng:
        futs = [eng.submit(data=p) for p in parts]
        eng.flush()
        outs = [f.result(timeout=60) for f in futs]
    assert eng.stats()["batches"] == 1
    oracle = Predictor(sym, params, {"data": (4, D)}, dtype=dtype)
    oracle.forward(data=np.concatenate(parts, axis=0))
    ref = oracle.get_output(0).asnumpy()
    off = 0
    for p, o in zip(parts, outs):
        np.testing.assert_array_equal(o[0], ref[off:off + len(p)])
        off += len(p)


def test_cross_bucket_shape_ulp_tolerance():
    """Engine output vs a PER-REQUEST unbatched forward crosses program
    shapes (bucket 4 vs batch 1) — ULP-level agreement, not bitwise
    (XLA's shape-specialized kernels; see module docstring)."""
    sym, params, eng = _engine(max_wait_ms=500.0)
    rng = np.random.RandomState(4)
    xs = [rng.normal(size=(1, D)).astype(np.float32) for _ in range(4)]
    with eng:
        outs = [f.result(timeout=60)
                for f in [eng.submit(data=x) for x in xs]]
    oracle = Predictor(sym, params, {"data": (1, D)})
    for x, o in zip(xs, outs):
        oracle.forward(data=x)
        np.testing.assert_allclose(
            o[0], oracle.get_output(0).asnumpy(), rtol=0, atol=1e-6)


def test_bucket_selection_boundaries():
    """rows == bucket size -> that bucket, zero pad; rows == bucket
    size + 1 -> next bucket, bucket-1 pad rows."""
    sym, params, eng = _engine(max_batch=16, max_wait_ms=10_000.0)
    with eng:
        assert [eng.bucket_for(r) for r in (1, 2, 3, 4, 5, 8, 9, 16)] == \
            [1, 2, 4, 4, 8, 8, 16, 16]
        with pytest.raises(mx.MXNetError):
            eng.bucket_for(17)
        rng = np.random.RandomState(5)
        f4 = eng.submit(data=rng.normal(size=(4, D)).astype(np.float32))
        eng.flush()
        f4.result(timeout=60)
        st = eng.stats()
        assert st["buckets"] == {"4": 1} and st["pad_rows"] == 0
        f5 = eng.submit(data=rng.normal(size=(5, D)).astype(np.float32))
        eng.flush()
        f5.result(timeout=60)
        st = eng.stats()
        assert st["buckets"] == {"4": 1, "8": 1} and st["pad_rows"] == 3
        assert st["batch_fill"] == pytest.approx(9.0 / 12.0)


def test_deadline_flush_under_trickle_load():
    """A lone request must not wait for co-batchable traffic forever:
    the max_wait_ms deadline flushes a partial bucket."""
    sym, params, eng = _engine(max_batch=8, max_wait_ms=30.0)
    rng = np.random.RandomState(6)
    with eng:
        t0 = time.perf_counter()
        out = eng.submit(data=rng.normal(size=(1, D)).astype(np.float32)) \
            .result(timeout=60)
        dt = time.perf_counter() - t0
    assert out[0].shape == (1, C)
    st = eng.stats()
    assert st["batches"] == 1 and st["buckets"] == {"1": 1}
    # generous CI bound: the deadline is 30ms, a stuck coalescer would
    # only resolve at close()
    assert dt < 30.0


def test_fill_flush_coalesces_bursts():
    """A burst under a long deadline coalesces on FILL: 16 one-row
    requests at max_batch=8 dispatch as two full buckets."""
    sym, params, eng = _engine(max_batch=8, max_wait_ms=5_000.0)
    rng = np.random.RandomState(7)
    xs = [rng.normal(size=(1, D)).astype(np.float32) for _ in range(16)]
    with eng:
        futs = [eng.submit(data=x) for x in xs]
        for f in futs:
            f.result(timeout=60)
    st = eng.stats()
    assert st["batches"] == 2
    assert st["buckets"] == {"8": 2}
    assert st["batch_fill"] == 1.0 and st["pad_rows"] == 0


def test_clean_shutdown_with_inflight_requests():
    """close() drains: every already-submitted future resolves, and
    later submits raise."""
    sym, params, eng = _engine(max_batch=4, max_wait_ms=10_000.0,
                               max_inflight=2)
    rng = np.random.RandomState(8)
    futs = [eng.submit(data=rng.normal(size=(1, D)).astype(np.float32))
            for _ in range(11)]
    eng.close()
    for f in futs:
        assert f.result(timeout=60)[0].shape == (1, C)
    st = eng.stats()
    assert st["resolved"] == 11 and st["queue_depth"] == 0
    with pytest.raises(mx.MXNetError):
        eng.submit(data=rng.normal(size=(1, D)).astype(np.float32))
    eng.close()  # idempotent


def test_one_compile_per_bucket_signature():
    """The bucket cache's load-bearing property: warmup compiles each
    bucket ONCE; steady-state traffic (two rounds) adds no programs and
    no jit compiles — asserted via telemetry.programs()."""
    telemetry.reset()
    sym, params, eng = _engine(max_batch=8, max_wait_ms=5.0)
    with eng:
        cards = eng.program_cards()
        assert len(cards) == len(eng.buckets) == 4
        assert all(c["kind"] == "forward" for c in cards.values())
        # every program BUILD records a jit_compile span — the signal
        # that catches a steady-state recompile (the jit.compile
        # counter only counts _GraphProgram entry-point lookups, which
        # the engine's cached dispatch path never repeats)
        builds0 = telemetry.span_count("jit_compile")
        rng = np.random.RandomState(9)
        for _ in range(2):
            futs = [eng.submit(
                data=rng.normal(size=(1, D)).astype(np.float32))
                for _ in range(12)]
            for f in futs:
                f.result(timeout=60)
        cards = eng.program_cards()
        assert len(cards) == 4, "steady-state traffic grew the cache"
        assert telemetry.span_count("jit_compile") == builds0
        # planned bucket compiles are not recompile storms
        assert not any(k.startswith("recompile.")
                       for k in telemetry.counters())
        # dispatch accounting: every launch bumped its bucket's card
        # (warmup BUILDS without dispatching since the compile-cache
        # tier, so traffic is the only dispatch source)
        assert sum(c["dispatches"] for c in cards.values()) >= \
            eng.stats()["batches"]
        assert eng.stats()["batches"] > 0


def test_serving_telemetry_counters_and_spans():
    """snapshot() carries the serving story: request/batch counters,
    pad accounting and the serve_* span percentiles."""
    telemetry.reset()
    sym, params, eng = _engine(max_batch=4, max_wait_ms=10_000.0)
    rng = np.random.RandomState(10)
    with eng:
        fut = eng.submit(data=rng.normal(size=(3, D)).astype(np.float32))
        eng.flush()
        fut.result(timeout=60)
    snap = telemetry.snapshot()
    c = snap["counters"]
    assert c["serving.requests"] == 1 and c["serving.resolved"] == 1
    assert c["serving.batches"] == 1
    assert c["serving.batch_rows"] == 3 and c["serving.pad_rows"] == 1
    assert c["serving.pad_bytes"] == D * 4
    assert c["dispatch.serve"] == 1
    for name in ("serve_wait", "serve_batch", "serve_d2h", "serve_request"):
        assert snap["spans"][name]["count"] >= 1, name
        assert snap["spans"][name]["p95_ms"] >= 0.0
    st = eng.stats()
    assert st["latency_ms"]["p95_ms"] is not None


def test_request_validation():
    sym, params, eng = _engine(max_batch=4)
    rng = np.random.RandomState(11)
    with eng:
        with pytest.raises(mx.MXNetError, match="max_batch"):
            eng.submit(data=rng.normal(size=(5, D)).astype(np.float32))
        with pytest.raises(mx.MXNetError, match="shape"):
            eng.submit(data=rng.normal(size=(1, D + 1)).astype(np.float32))
        with pytest.raises(mx.MXNetError, match="inputs"):
            eng.submit(bogus=rng.normal(size=(1, D)).astype(np.float32))
        # a bare row without the batch dim is accepted as rows=1, and a
        # single-input graph takes one positional array
        out = eng.predict(np.zeros((D,), np.float32))
        assert out[0].shape == (1, C)


def test_predictor_engine_share_one_program_cache():
    """Predictor.engine(): the engine and the predictor dispatch through
    ONE _GraphProgram — a predictor forward at a bucket shape is a cache
    hit for the engine and vice versa."""
    telemetry.reset()
    sym = _mlp()
    params = _params(sym)
    pred = Predictor(sym, params, {"data": (4, D)})
    rng = np.random.RandomState(12)
    x4 = rng.normal(size=(4, D)).astype(np.float32)
    pred.forward(data=x4)                     # compiles signature (4, D)
    eng = pred.engine(max_batch=8, max_wait_ms=500.0)
    with eng:
        cards = eng.program_cards()
        # buckets 1/2/8 compiled fresh; bucket 4 reused the predictor's
        # program — 4 signatures total, not 5
        assert len(cards) == 4
        futs = [eng.submit(data=x4[i:i + 1]) for i in range(4)]
        ref = pred.get_output(0).asnumpy()
        for i, f in enumerate(futs):
            np.testing.assert_array_equal(f.result(timeout=60)[0],
                                          ref[i:i + 1])


def test_telemetry_logger_serving(caplog):
    """A running engine with telemetry_logger= logs queue depth, fill
    and the request p95 periodically."""
    telemetry.reset()
    logger = mx.callback.TelemetryLogger(frequent=1)
    sym, params, eng = _engine(max_batch=4, max_wait_ms=20.0,
                               telemetry_logger=logger)
    rng = np.random.RandomState(13)
    with caplog.at_level(logging.INFO, logger="mxnet_tpu.telemetry"):
        with eng:
            for _ in range(3):
                futs = [eng.submit(
                    data=rng.normal(size=(1, D)).astype(np.float32))
                    for _ in range(4)]
                for f in futs:
                    f.result(timeout=60)
    lines = [r.message for r in caplog.records
             if r.message.startswith("serving:")]
    assert lines, "engine logged no serving lines"
    assert any("queue_depth=" in ln for ln in lines)
    assert any("p50/p95/p99=" in ln for ln in lines)
    assert any("batch_fill=" in ln for ln in lines)


# ---------------------------------------------------------------------------
# ISSUE 6: warmup hygiene, custom buckets, corpus + autotune
# ---------------------------------------------------------------------------

def test_warmup_restores_warn_recompile_on_failure(monkeypatch):
    """The recompile-warning suppression must restore in a finally even
    when a bucket build raises mid-warmup, and must tolerate a forward
    callable without the attribute at all."""
    from mxnet_tpu import executor as _ex
    sym, params, eng = _engine(max_batch=4, warmup=False)
    with eng:
        assert eng._forward.warn_recompile is True

        def boom(self, *a):
            raise RuntimeError("bucket build exploded")
        monkeypatch.setattr(_ex._InstrumentedProgram, "build", boom)
        with pytest.raises(RuntimeError):
            eng.warmup()
        monkeypatch.undo()
        # the flag came back despite the mid-warmup raise
        assert eng._forward.warn_recompile is True

    # a forward wrapper WITHOUT the attribute passes through untouched
    from mxnet_tpu.serving import _quiet_recompile

    class Bare:
        pass
    bare = Bare()
    with _quiet_recompile(bare):
        assert not hasattr(bare, "warn_recompile")
    assert not hasattr(bare, "warn_recompile")


def test_custom_bucket_set():
    from mxnet_tpu.serving import validate_buckets
    assert validate_buckets([3, 10], 16) == [3, 10, 16]
    assert validate_buckets([16, 3, 3, 10], 16) == [3, 10, 16]
    assert validate_buckets([99, -2], 16) == [16]     # clamp + top
    with pytest.raises(mx.MXNetError):
        validate_buckets(["x"], 16)

    sym, params, eng = _engine(max_batch=16, buckets=[3, 10],
                               max_wait_ms=5.0)
    with eng:
        assert eng.buckets == [3, 10, 16]
        assert len(eng.program_cards()) == 3
        # requests route to the smallest covering custom bucket
        assert eng.bucket_for(2) == 3
        assert eng.bucket_for(4) == 10
        rng = np.random.RandomState(3)
        ref = Predictor(sym, params, {"data": (3, D)})
        x = rng.normal(size=(3, D)).astype(np.float32)
        out = eng.predict(data=x)
        ref.forward(data=x)
        np.testing.assert_array_equal(out[0],
                                      np.asarray(ref.get_output(0)))


def test_stats_rows_hist_and_bucket_ms():
    telemetry.reset()
    sym, params, eng = _engine(max_batch=8, max_wait_ms=1.0)
    with eng:
        rng = np.random.RandomState(5)
        for _ in range(4):
            eng.predict(data=rng.normal(size=(3, D)).astype(np.float32))
        st = eng.stats()
        assert st["rows_hist"].get("3") == 4
        assert st["max_inflight"] == 2          # the default
        assert st["autotune_plan"] is None
        ms = st["bucket_ms"].get("4")
        assert ms and ms["count"] == 4 and ms["mean_ms"] > 0


def test_corpus_record_and_append_on_close(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_CARD_CORPUS", str(tmp_path / "c.jsonl"))
    monkeypatch.delenv("MXNET_COMPILE_CACHE", raising=False)
    from mxnet_tpu import compile_cache
    telemetry.reset()
    sym, params, eng = _engine(max_batch=8, max_wait_ms=1.0)
    rng = np.random.RandomState(5)
    for _ in range(3):
        eng.predict(data=rng.normal(size=(2, D)).astype(np.float32))
    rec = eng.corpus_record()
    assert rec["kind"] == "serving" and rec["max_batch"] == 8
    assert rec["rows_hist"].get("2") == 3
    assert rec["buckets"] == [1, 2, 4, 8]
    assert rec["cards"]                      # per-bucket card features
    eng.close()                              # appends the record
    got = compile_cache.corpus_records(kind="serving")
    assert len(got) == 1
    assert got[0]["rows_hist"] == rec["rows_hist"]
    # JSON-safe end to end (it came back through json.loads already)
    assert got[0]["batches"] == rec["batches"]


def test_idle_engine_appends_nothing(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_CARD_CORPUS", str(tmp_path / "c.jsonl"))
    from mxnet_tpu import compile_cache
    sym, params, eng = _engine(max_batch=4)
    assert eng.corpus_record() is None       # nothing served
    eng.close()
    assert compile_cache.corpus_records() == []


def test_autotune_engine_plans_from_corpus(tmp_path, monkeypatch):
    """The tune-once-serve-forever loop end to end IN PROCESS: run one
    engine over skewed traffic, bank its corpus record, then construct
    an autotuned engine that picks the measured bucket set and stamps
    the plan onto its cards."""
    monkeypatch.setenv("MXNET_CARD_CORPUS", str(tmp_path / "c.jsonl"))
    monkeypatch.delenv("MXNET_COMPILE_CACHE", raising=False)
    telemetry.reset()
    sym, params, eng = _engine(max_batch=8, max_wait_ms=1.0)
    rng = np.random.RandomState(7)
    for _ in range(6):
        eng.predict(data=rng.normal(size=(3, D)).astype(np.float32))
    eng.close()

    telemetry.reset()
    sym2, params2, tuned = _engine(max_batch=8, autotune=True,
                                   max_wait_ms=1.0)
    with tuned:
        plan = tuned.stats()["autotune_plan"]
        assert plan is not None and plan["kind"] == "autotune_plan"
        # observed 3-row batches -> 3 became a bucket; max_batch tops
        assert 3 in tuned.buckets and tuned.buckets[-1] == 8
        assert tuned.buckets == plan["buckets"]
        assert tuned._max_inflight == plan["max_inflight"]
        # the plan rode onto every bucket card
        cards = tuned.program_cards()
        assert cards and all(c.get("autotune_plan") == plan
                             for c in cards.values())
        # and the tuned engine still serves correctly
        x = rng.normal(size=(3, D)).astype(np.float32)
        ref = Predictor(sym2, params2, {"data": (3, D)})
        ref.forward(data=x)
        np.testing.assert_array_equal(
            tuned.predict(data=x)[0], np.asarray(ref.get_output(0)))


def test_autotune_without_corpus_falls_back(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_CARD_CORPUS", str(tmp_path / "none.jsonl"))
    monkeypatch.delenv("MXNET_COMPILE_CACHE", raising=False)
    sym, params, eng = _engine(max_batch=8, autotune=True)
    with eng:
        assert eng.buckets == bucket_sizes(8)    # pow-2 defaults
        assert eng.stats()["autotune_plan"] is None
        assert eng._max_inflight == 2


def test_explicit_buckets_override_autotune(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_CARD_CORPUS", str(tmp_path / "c.jsonl"))
    from mxnet_tpu import compile_cache
    compile_cache.corpus_append({"kind": "serving", "max_batch": 8,
                                 "rows_hist": {"3": 10}})
    sym, params, eng = _engine(max_batch=8, autotune=True,
                               buckets=[5])
    with eng:
        # explicit buckets win; the plan is not even consulted
        assert eng.buckets == [5, 8]
        assert eng.stats()["autotune_plan"] is None


def test_single_bucket_engine_dummies():
    """max_batch=1 (one bucket) skips batch-major calibration entirely
    — the calibrated inference IS the only bucket's shape."""
    sym, params, eng = _engine(max_batch=1, max_wait_ms=1.0)
    with eng:
        assert eng.buckets == [1]
        rng = np.random.RandomState(11)
        x = rng.normal(size=(1, D)).astype(np.float32)
        ref = Predictor(sym, params, {"data": (1, D)})
        ref.forward(data=x)
        np.testing.assert_array_equal(
            eng.predict(data=x)[0], np.asarray(ref.get_output(0)))


def test_corpus_records_carry_graph_identity(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_CARD_CORPUS", str(tmp_path / "c.jsonl"))
    from mxnet_tpu import compile_cache
    telemetry.reset()
    sym, params, eng = _engine(max_batch=4, max_wait_ms=1.0)
    eng.predict(data=np.zeros((2, D), np.float32))
    fp = eng._prog.graph_fingerprint()
    eng.close()
    [rec] = compile_cache.corpus_records(kind="serving")
    assert fp is not None and rec["graph"] == fp
    # a DIFFERENT symbol's autotune ignores this record
    from mxnet_tpu.tuner import plan_serving
    assert plan_serving([rec], graph=["other", None]) is None
    assert plan_serving([rec], graph=fp) is not None
