"""SPMD data-parallel fused train step (ISSUE 2).

A multi-context Module with an in-process kvstore now runs the WHOLE
train step — forward, backward, cross-replica gradient all-reduce,
optimizer update, metric accumulation — as ONE donated-buffer SPMD
program over the dp mesh (the kvstore reduce is SUBSUMED: for a single
mesh program the push/pull was an identity round-trip staged through
software). Pinned properties:

1. DISPATCH COUNT — exactly 1 jitted-program execution per batch on the
   8-device CPU mesh, with a live ``local`` kvstore.
2. EQUIVALENCE — dp-fused is BIT-identical to the dp phase-split kvstore
   path (same mesh, same reduction order — the oracle), including bf16
   weights + fp32 master and ``grad_req='add'``; and matches the
   single-device fused step to float tolerance (per-shard partial sums
   reassociate the batch reduction, so cross-mesh-size bit-equality is
   not a property ANY data-parallel implementation can offer — the
   dp-vs-single tolerance here is the reassociation noise floor, same
   as the pre-existing ``test_dp_module_matches_single_device`` gate).
3. FALLBACK — ``dist_*`` kvstores keep the push/pull path and record
   the stable ``kvstore_dist`` reason code; mid-training fallback
   continues bit-exactly (the subsumed store's weight copies are kept
   coherent by the fused step).
4. FEEDING — a runtime batch whose global size does not divide over the
   dp axis raises the same clear error as the bind-time check (no
   silent pad).
"""
import contextlib
import os

import numpy as np

import mxnet_tpu as mx
import mxnet_tpu.executor as _ex
from mxnet_tpu import nd, sym
from mxnet_tpu.io import DataBatch, DataDesc
from mxnet_tpu.module import FusedFallback, FUSED_FALLBACK_CODES

import jax
import jax.numpy as jnp

N_DEV = min(8, jax.device_count())


@contextlib.contextmanager
def _pin(value):
    old = os.environ.get("MXNET_MODULE_FUSED_STEP")
    os.environ["MXNET_MODULE_FUSED_STEP"] = value
    try:
        yield
    finally:
        if old is None:
            del os.environ["MXNET_MODULE_FUSED_STEP"]
        else:
            os.environ["MXNET_MODULE_FUSED_STEP"] = old


@contextlib.contextmanager
def _count_dispatches(counts):
    _ex.dispatch_hook = \
        lambda kind: counts.__setitem__(kind, counts.get(kind, 0) + 1)
    try:
        yield counts
    finally:
        _ex.dispatch_hook = None


def _mlp(c=4):
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=c, name="fc2")
    return sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                             name="softmax")


def _batches(nbatch, batch=16, d=8, c=4, seed=7):
    rs = np.random.RandomState(seed)
    return [DataBatch(
        data=[nd.array(rs.uniform(-1, 1, (batch, d)).astype(np.float32))],
        label=[nd.array(rs.randint(0, c, batch).astype(np.float32))],
        pad=0) for _ in range(nbatch)]


def _make_module(n_dev, kvstore, bf16=False, grad_req="write", batch=16,
                 d=8):
    ctx = [mx.cpu(i) for i in range(n_dev)] if n_dev > 1 else mx.cpu()
    mod = mx.mod.Module(_mlp(), context=ctx)
    ddtype = np.dtype(jnp.bfloat16) if bf16 else None
    mod.bind(data_shapes=[DataDesc("data", (batch, d), dtype=ddtype)],
             label_shapes=[DataDesc("softmax_label", (batch,))],
             grad_req=grad_req)
    np.random.seed(11)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(
        kvstore=kvstore, optimizer="sgd",
        optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                          "wd": 1e-4, "multi_precision": bf16})
    return mod


def _effective_updater(mod):
    """The updater that owns the optimizer state: the kvstore's
    server-side one under update_on_kvstore, else the module's."""
    if mod._kvstore is not None and mod._update_on_kvstore:
        return mod._kvstore._updater
    return mod._updater


def _state_arrays(updater):
    out = []
    for i in sorted(updater.states):
        for leaf in jax.tree_util.tree_leaves(updater.states[i]):
            out.append(np.asarray(leaf._data if hasattr(leaf, "_data")
                                  else leaf))
    return out


def _train(fused, n_dev, kvstore, bf16=False, grad_req="write", nbatch=6):
    with _pin("1" if fused else "0"):
        mod = _make_module(n_dev, kvstore, bf16=bf16, grad_req=grad_req)
        metric = mx.metric.Accuracy()
        for b in _batches(nbatch):
            ran_fused = mod.fused_step(b, eval_metric=metric)
            assert ran_fused == fused, mod._fused_fallback_reason
    params = {n: np.asarray(mod._exec.arg_dict[n]._data)
              for n in mod._param_names}
    grads = {n: np.asarray(g._data)
             for n, g in mod._exec.grad_dict.items() if g is not None}
    return params, _state_arrays(_effective_updater(mod)), metric.get(), \
        grads


def _assert_bit_equal(run_a, run_b):
    params_a, states_a, metric_a, _ = run_a
    params_b, states_b, metric_b, _ = run_b
    for n in params_a:
        np.testing.assert_array_equal(params_a[n], params_b[n], err_msg=n)
    assert len(states_a) == len(states_b)
    for i, (a, b) in enumerate(zip(states_a, states_b)):
        np.testing.assert_array_equal(a, b, err_msg="state %d" % i)
    assert metric_a == metric_b, (metric_a, metric_b)


# ---------------------------------------------------------------------------
# 1. dispatch-count guard on the mesh, kvstore live
# ---------------------------------------------------------------------------

def test_dp_fused_dispatch_guard():
    """Multi-context + ``local`` kvstore must run the fused SPMD path at
    EXACTLY 1 jitted-program dispatch per batch (the acceptance gate:
    the kvstore reduce is inside the program, not a second dispatch)."""
    assert N_DEV >= 2, "conftest sets an 8-device virtual CPU mesh"
    nbatch = 5
    with _pin("1"):
        mod = _make_module(N_DEV, "local")
        metric = mx.metric.Accuracy()
        for b in _batches(2):  # warm: compiles the SPMD program
            assert mod.fused_step(b, eval_metric=metric), \
                mod._fused_fallback_reason
        with _count_dispatches({}) as counts:
            for b in _batches(nbatch):
                assert mod.fused_step(b, eval_metric=metric)
    assert mod._fused_fallback_reason is None
    assert counts == {"train_step": nbatch}, counts


# ---------------------------------------------------------------------------
# 2. equivalence: dp-fused vs dp phase-split kvstore vs single-device fused
# ---------------------------------------------------------------------------

def test_dp_equivalence_fp32():
    dp_fused = _train(True, N_DEV, "local")
    dp_split = _train(False, N_DEV, "local")
    _assert_bit_equal(dp_fused, dp_split)
    # vs the single-device fused step: per-shard partial sums + psum
    # reassociate the batch reduction — tight allclose, not bit-equal
    single = _train(True, 1, None)
    for n in dp_fused[0]:
        np.testing.assert_allclose(dp_fused[0][n], single[0][n],
                                   rtol=1e-5, atol=1e-6, err_msg=n)
    assert dp_fused[2] == single[2]  # integer metric counts agree exactly


def test_dp_equivalence_bf16_master():
    """bf16-resident weights + fp32 master on the mesh: the fused SPMD
    program must round exactly like the phase-split kvstore chain."""
    _assert_bit_equal(_train(True, N_DEV, "local", bf16=True),
                      _train(False, N_DEV, "local", bf16=True))


def test_dp_equivalence_grad_add():
    """grad_req='add' on the mesh: the gradient accumulator (a fused-
    program OUTPUT) must match the phase-split accumulation bit for
    bit."""
    fused = _train(True, N_DEV, "local", grad_req="add")
    split = _train(False, N_DEV, "local", grad_req="add")
    _assert_bit_equal(fused, split)
    assert fused[3], "grad_req='add' run must expose accumulators"
    for n in fused[3]:
        np.testing.assert_array_equal(fused[3][n], split[3][n], err_msg=n)


def test_dp_fallback_continuity_mid_training():
    """3 fused steps then 3 phase-split steps == 6 phase-split steps,
    bit for bit: the subsumed kvstore's weight copies are kept coherent
    by the fused step, so flipping the pin mid-training (or any dynamic
    fallback) continues the exact same trajectory."""
    mod = _make_module(N_DEV, "local")
    metric = mx.metric.Accuracy()
    batches = _batches(6)
    with _pin("1"):
        for b in batches[:3]:
            assert mod.fused_step(b, eval_metric=metric)
    with _pin("0"):
        for b in batches[3:]:
            assert not mod.fused_step(b, eval_metric=metric)
    split = _train(False, N_DEV, "local")
    for n in split[0]:
        np.testing.assert_array_equal(
            np.asarray(mod._exec.arg_dict[n]._data), split[0][n], err_msg=n)
    assert metric.get() == split[2]


# ---------------------------------------------------------------------------
# 3. fallback rules + stable reason codes
# ---------------------------------------------------------------------------

def test_dp_fallback_code_dist_kvstore():
    """``dist_async`` keeps the explicit wire path (async application
    is wire-emulated) — the step must phase-split with the stable
    ``kvstore_dist`` code, and still train. (``dist_sync`` no longer
    falls back: the fused step spans processes — ISSUE 12.)"""
    with _pin("1"):
        mod = _make_module(2, "dist_async")
        before = np.asarray(mod._exec.arg_dict["fc1_weight"]._data).copy()
        assert not mod.fused_step(_batches(1)[0])
        reason = mod._fused_fallback_reason
        assert isinstance(reason, FusedFallback)
        assert reason.code == "kvstore_dist"
        assert reason == "kvstore-mediated update"  # legacy text pinned
        after = np.asarray(mod._exec.arg_dict["fc1_weight"]._data)
        assert not np.array_equal(before, after), "fallback must train"


def test_dist_sync_fuses_single_process():
    """The dist tier (ISSUE 12): ``dist_sync`` rides the fused
    donated-buffer step — in a single-process job the process-spanning
    mesh degenerates to the local program (``_dist_spec`` is None) and
    the step fuses with NO ``kvstore_dist`` fallback event."""
    from mxnet_tpu import telemetry
    with _pin("1"):
        telemetry.reset()
        mod = _make_module(2, "dist_sync")
        assert mod._update_on_kvstore        # dist_* forces kvstore-side
        assert mod._dist_spec is None        # one process: local program
        before = np.asarray(mod._exec.arg_dict["fc1_weight"]._data).copy()
        assert mod.fused_step(_batches(1)[0])
        assert mod._fused_fallback_reason is None
        assert telemetry.counters().get("fused_fallback.kvstore_dist",
                                        0) == 0
        after = np.asarray(mod._exec.arg_dict["fc1_weight"]._data)
        assert not np.array_equal(before, after), "fused step must train"


def test_fallback_codes_are_stable_and_enumerable():
    """Every recorded reason is a FusedFallback whose code is in the
    published registry; the str VALUE keeps the legacy message so
    message-text consumers (bench JSON, old asserts) never broke."""
    mod = _make_module(1, None)
    with _pin("0"):
        assert not mod.fused_step(_batches(1)[0])
    r = mod._fused_fallback_reason
    assert r.code == "env_pin" and r == "MXNET_MODULE_FUSED_STEP=0"
    assert r.code in FUSED_FALLBACK_CODES

    mod = _make_module(1, None)
    mon = mx.monitor.Monitor(1, pattern=".*weight")
    mod.install_monitor(mon)
    with _pin("1"):
        assert not mod.fused_step(_batches(1)[0])
    r = mod._fused_fallback_reason
    assert r.code == "monitor" and r == "monitor installed"

    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (16, 8))],
             label_shapes=[("softmax_label", (16,))],
             inputs_need_grad=True)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd")
    with _pin("1"):
        assert not mod.fused_step(_batches(1)[0])
    assert mod._fused_fallback_reason.code == "inputs_need_grad"


# ---------------------------------------------------------------------------
# 4. sharded feeding: no silent pad
# ---------------------------------------------------------------------------

def test_dp_runtime_batch_not_divisible_raises():
    """A hand-fed batch whose global size does not divide over the dp
    axis must raise the SAME clear error as the bind-time check — on
    both the fused and the phase-split feed paths — never silently pad
    or die inside XLA."""
    rs = np.random.RandomState(3)
    bad = DataBatch(
        data=[nd.array(rs.uniform(-1, 1, (14, 8)).astype(np.float32))],
        label=[nd.array(rs.randint(0, 4, 14).astype(np.float32))], pad=0)
    for pin in ("1", "0"):
        mod = _make_module(4, "local")
        with _pin(pin):
            try:
                mod.fused_step(bad)
            except mx.base.MXNetError as e:
                assert "not divisible" in str(e), e
            else:
                raise AssertionError("expected divisibility error "
                                     "(pin=%s)" % pin)


def test_dp_optimizer_states_roundtrip_stays_on_mesh():
    """save/load_optimizer_states mid-training on the mesh: loaded
    states must re-commit to the weights' mesh placement (not re-enter
    single-device) and the fused trajectory must continue bit-exactly."""
    import tempfile
    batches = _batches(6)
    with _pin("1"):
        mod = _make_module(N_DEV, "local")
        metric = mx.metric.Accuracy()
        for b in batches[:3]:
            assert mod.fused_step(b, eval_metric=metric)
        with tempfile.NamedTemporaryFile(suffix=".states") as f:
            mod.save_optimizer_states(f.name)
            mod.load_optimizer_states(f.name)
        for b in batches[3:]:
            assert mod.fused_step(b, eval_metric=metric), \
                mod._fused_fallback_reason
    ref = _train(True, N_DEV, "local")
    for n in ref[0]:
        np.testing.assert_array_equal(
            np.asarray(mod._exec.arg_dict[n]._data), ref[0][n], err_msg=n)
