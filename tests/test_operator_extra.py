"""Tests for the detection/flow/signal/quantization operator set
(reference parity: contrib/proposal.cc, contrib/deformable_convolution.cc,
correlation.cc, contrib/fft.cc, contrib/quantize.cc, batch_norm_v1.cc,
identity_attach_KL_sparse_reg.cc)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.test_utils import check_numeric_gradient


# ---------------------------------------------------------------------------
# Correlation
# ---------------------------------------------------------------------------

def _np_correlation(d1, d2, k, md, s1, s2, pad, mul):
    """Independent numpy oracle (scalar-loop formulation of the FlowNet
    correlation layer; ceil output shapes like the reference InferShape,
    zero beyond the padded extent)."""
    B, C, H, W = d1.shape
    ph, pw = H + 2 * pad, W + 2 * pad
    kr = (k - 1) // 2
    bs = md + kr
    th = int(np.ceil((ph - 2 * bs) / s1))
    tw = int(np.ceil((pw - 2 * bs) / s1))
    r = md // s2
    D = 2 * r + 1
    extra = 2 * md + max((th - 1) * s1, (tw - 1) * s1) + k
    t1 = np.zeros((B, C, max(ph, extra), max(pw, extra)), d1.dtype)
    t2 = np.zeros_like(t1)
    t1[:, :, pad:pad + H, pad:pad + W] = d1
    t2[:, :, pad:pad + H, pad:pad + W] = d2
    out = np.zeros((B, D * D, th, tw), np.float32)
    for i in range(th):
        for j in range(tw):
            y1, x1 = i * s1 + md, j * s1 + md
            for tc in range(D * D):
                dy = (tc // D - r) * s2
                dx = (tc % D - r) * s2
                p1 = t1[:, :, y1:y1 + k, x1:x1 + k]
                p2 = t2[:, :, y1 + dy:y1 + dy + k, x1 + dx:x1 + dx + k]
                v = p1 * p2 if mul else np.abs(p1 - p2)
                out[:, tc, i, j] = v.sum(axis=(1, 2, 3))
    return out / float(k * k * C)


@pytest.mark.parametrize("cfg", [
    dict(k=1, md=1, s1=1, s2=1, pad=1, mul=True),
    dict(k=3, md=2, s1=2, s2=2, pad=2, mul=True),
    dict(k=1, md=1, s1=1, s2=1, pad=1, mul=False),
    # non-divisible span: exercises the reference's ceil output shape
    dict(k=1, md=1, s1=2, s2=1, pad=0, mul=True),
])
def test_correlation_forward(cfg):
    rs = np.random.RandomState(0)
    shape = (2, 3, 9, 9) if cfg["s1"] == 2 and cfg["pad"] == 0 else (2, 3, 8, 8)
    d1 = rs.uniform(-1, 1, shape).astype(np.float32)
    d2 = rs.uniform(-1, 1, shape).astype(np.float32)
    got = nd.Correlation(nd.array(d1), nd.array(d2), kernel_size=cfg["k"],
                         max_displacement=cfg["md"], stride1=cfg["s1"],
                         stride2=cfg["s2"], pad_size=cfg["pad"],
                         is_multiply=cfg["mul"]).asnumpy()
    want = _np_correlation(d1, d2, cfg["k"], cfg["md"], cfg["s1"],
                           cfg["s2"], cfg["pad"], cfg["mul"])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_correlation_grad():
    a = sym.Variable("a")
    b = sym.Variable("b")
    net = sym.Correlation(a, b, kernel_size=1, max_displacement=1,
                          pad_size=1)
    rs = np.random.RandomState(0)
    loc = {"a": rs.uniform(-1, 1, (1, 2, 5, 5)).astype(np.float32),
           "b": rs.uniform(-1, 1, (1, 2, 5, 5)).astype(np.float32)}
    check_numeric_gradient(net, loc, rtol=1e-2, atol=1e-2)


# ---------------------------------------------------------------------------
# fft / ifft
# ---------------------------------------------------------------------------

def test_fft_matches_numpy():
    rs = np.random.RandomState(0)
    x = rs.normal(size=(3, 8)).astype(np.float32)
    got = nd.contrib.fft(nd.array(x)).asnumpy()
    c = np.fft.fft(x, axis=-1)
    want = np.empty((3, 16), np.float32)
    want[:, 0::2] = c.real
    want[:, 1::2] = c.imag
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_ifft_unnormalised_roundtrip():
    rs = np.random.RandomState(0)
    x = rs.normal(size=(2, 3, 2, 6)).astype(np.float32)
    inter = nd.contrib.fft(nd.array(x))
    back = nd.contrib.ifft(inter).asnumpy()
    # reference ifft is unnormalised: ifft(fft(x)) == n * x
    np.testing.assert_allclose(back, x.shape[-1] * x, rtol=1e-4, atol=1e-3)


# ---------------------------------------------------------------------------
# quantize / dequantize
# ---------------------------------------------------------------------------

def test_quantize_dequantize_roundtrip():
    rs = np.random.RandomState(0)
    x = rs.uniform(-3, 5, (4, 7)).astype(np.float32)
    lo = nd.array(np.array([-3.0], np.float32))
    hi = nd.array(np.array([5.0], np.float32))
    q, qlo, qhi = nd.contrib.quantize(nd.array(x), lo, hi)
    assert q.dtype == np.uint8
    assert qlo.asnumpy().item() == -3.0 and qhi.asnumpy().item() == 5.0
    want_q = np.clip((x - (-3.0)) * (255.0 / 8.0) + 0.5, 0, 255) \
        .astype(np.uint8)
    np.testing.assert_array_equal(q.asnumpy(), want_q)
    deq = nd.contrib.dequantize(q, lo, hi).asnumpy()
    # quantization error bounded by one step
    assert np.abs(deq - x).max() <= 8.0 / 255.0 + 1e-6


# ---------------------------------------------------------------------------
# BatchNorm_v1
# ---------------------------------------------------------------------------

def test_batchnorm_v1_against_numpy():
    rs = np.random.RandomState(0)
    x = rs.uniform(-1, 1, (4, 3, 5, 5)).astype(np.float32)
    g = rs.uniform(0.5, 1.5, (3,)).astype(np.float32)
    b = rs.uniform(-0.5, 0.5, (3,)).astype(np.float32)
    data = sym.Variable("data")
    net = sym.BatchNorm_v1(data, fix_gamma=False, eps=1e-3, name="bn")
    ex = net.simple_bind(ctx=mx.cpu(), data=x.shape)
    ex.arg_dict["data"][:] = x
    ex.arg_dict["bn_gamma"][:] = g
    ex.arg_dict["bn_beta"][:] = b
    ex.aux_dict["bn_moving_mean"][:] = np.zeros(3, np.float32)
    ex.aux_dict["bn_moving_var"][:] = np.ones(3, np.float32)
    out = ex.forward(is_train=True)[0].asnumpy()
    mean = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    want = (x - mean[None, :, None, None]) / \
        np.sqrt(var + 1e-3)[None, :, None, None] * \
        g[None, :, None, None] + b[None, :, None, None]
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)
    # train-mode pass updates the moving stats (the legacy kernel's
    # in-place aux contract)
    np.testing.assert_allclose(ex.aux_dict["bn_moving_mean"].asnumpy(),
                               0.1 * mean, rtol=1e-4, atol=1e-6)


# ---------------------------------------------------------------------------
# IdentityAttachKLSparseReg
# ---------------------------------------------------------------------------

def test_identity_attach_kl_sparse_reg():
    rs = np.random.RandomState(0)
    x = rs.uniform(0.05, 0.95, (6, 4)).astype(np.float32)
    data = sym.Variable("data")
    net = sym.IdentityAttachKLSparseReg(data, sparseness_target=0.2,
                                        penalty=0.01, momentum=0.9,
                                        name="klreg")
    ex = net.simple_bind(ctx=mx.cpu(), grad_req="write", data=x.shape)
    ex.arg_dict["data"][:] = x
    mov0 = np.full(4, 0.5, np.float32)
    ex.aux_dict["klreg_moving_avg"][:] = mov0
    # one fused fwd+bwd pass (the Module path) so the moving average
    # updates exactly once
    out = ex.forward_backward(out_grads=nd.array(np.ones_like(x)),
                              is_train=True)[0].asnumpy()
    np.testing.assert_allclose(out, x, rtol=1e-6)  # forward identity
    avg = x.mean(axis=0)
    mov_new = 0.9 * mov0 + 0.1 * avg
    reg = 0.01 * (-0.2 / mov_new + 0.8 / (1 - mov_new))
    want = np.broadcast_to(1.0 + reg[None, :], x.shape)
    np.testing.assert_allclose(ex.grad_dict["data"].asnumpy(), want,
                               rtol=1e-5, atol=1e-6)
    # aux moving average updated by the train-mode pass
    np.testing.assert_allclose(ex.aux_dict["klreg_moving_avg"].asnumpy(),
                               mov_new, rtol=1e-5)


# ---------------------------------------------------------------------------
# DeformableConvolution
# ---------------------------------------------------------------------------

def test_deformable_conv_zero_offset_equals_conv():
    rs = np.random.RandomState(0)
    x = rs.uniform(-1, 1, (2, 3, 7, 7)).astype(np.float32)
    w = rs.uniform(-0.5, 0.5, (4, 3, 3, 3)).astype(np.float32)
    off = np.zeros((2, 2 * 9, 5, 5), np.float32)
    got = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), None, kernel=(3, 3),
        num_filter=4, no_bias=True).asnumpy()
    want = nd.Convolution(nd.array(x), nd.array(w), None, kernel=(3, 3),
                          num_filter=4, no_bias=True).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_deformable_conv_fractional_offset_bilinear():
    # constant 0.5-pixel x-shift on a linear ramp == exact interpolation
    H = 6
    ramp = np.tile(np.arange(H, dtype=np.float32), (H, 1))
    x = ramp[None, None]
    w = np.ones((1, 1, 1, 1), np.float32)
    off = np.zeros((1, 2, H, H), np.float32)
    off[:, 1] = 0.5  # x offset
    got = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), None, kernel=(1, 1),
        num_filter=1, no_bias=True).asnumpy()[0, 0]
    want = np.minimum(ramp + 0.5, H - 1)
    np.testing.assert_allclose(got[:, :-1], want[:, :-1], rtol=1e-5)


def test_deformable_conv_grad():
    data = sym.Variable("data")
    offset = sym.Variable("offset")
    net = sym.contrib.DeformableConvolution(
        data, offset, kernel=(3, 3), num_filter=2, no_bias=True,
        name="dconv")
    rs = np.random.RandomState(0)
    loc = {"data": rs.uniform(-1, 1, (1, 2, 6, 6)).astype(np.float32),
           "offset": rs.uniform(-0.3, 0.3, (1, 18, 4, 4)).astype(np.float32),
           "dconv_weight":
               rs.uniform(-0.5, 0.5, (2, 2, 3, 3)).astype(np.float32)}
    check_numeric_gradient(net, loc, grad_nodes=["data", "dconv_weight"],
                           rtol=1e-2, atol=1e-2)


# ---------------------------------------------------------------------------
# Proposal
# ---------------------------------------------------------------------------

def _np_nms(dets, thresh, post_n):
    x1, y1, x2, y2, sc = dets.T
    areas = (x2 - x1 + 1) * (y2 - y1 + 1)
    suppressed = np.zeros(len(dets), bool)
    keep = []
    for i in range(len(dets)):
        if suppressed[i] or len(keep) >= post_n:
            continue
        keep.append(i)
        xx1 = np.maximum(x1[i], x1)
        yy1 = np.maximum(y1[i], y1)
        xx2 = np.minimum(x2[i], x2)
        yy2 = np.minimum(y2[i], y2)
        inter = np.maximum(0, xx2 - xx1 + 1) * np.maximum(0, yy2 - yy1 + 1)
        iou = inter / (areas[i] + areas - inter)
        suppressed |= (iou > thresh) & (np.arange(len(dets)) > i)
    return keep


def test_proposal_shapes_and_validity():
    rs = np.random.RandomState(0)
    A, Hf, Wf = 6, 4, 4
    cls_prob = rs.uniform(0, 1, (1, 2 * A, Hf, Wf)).astype(np.float32)
    bbox = rs.uniform(-0.2, 0.2, (1, 4 * A, Hf, Wf)).astype(np.float32)
    im_info = np.array([[64.0, 64.0, 1.0]], np.float32)
    rois = nd.contrib.Proposal(
        nd.array(cls_prob), nd.array(bbox), nd.array(im_info),
        rpn_pre_nms_top_n=50, rpn_post_nms_top_n=8, threshold=0.7,
        rpn_min_size=4, scales=(2.0, 4.0), ratios=(0.5, 1.0, 2.0),
        feature_stride=16).asnumpy()
    assert rois.shape == (8, 5)
    assert (rois[:, 0] == 0).all()          # batch index
    assert (rois[:, 1] <= rois[:, 3]).all()  # x1 <= x2
    assert (rois[:, 2] <= rois[:, 4]).all()  # y1 <= y2
    assert rois[:, 1:].min() >= -4.0         # min_size enlargement bound
    assert rois[:, [1, 3]].max() <= 64.0 + 4.0


def test_proposal_picks_top_scoring_anchor():
    """Put one overwhelming fg score on a single anchor location; the
    first roi must be that anchor's (delta-0) box."""
    A, Hf, Wf = 3, 3, 3
    cls_prob = np.zeros((1, 2 * A, Hf, Wf), np.float32)
    cls_prob[0, A:, :, :] = 0.1
    cls_prob[0, A + 1, 1, 2] = 0.99          # anchor 1 at (h=1, w=2)
    bbox = np.zeros((1, 4 * A, Hf, Wf), np.float32)
    im_info = np.array([[48.0, 48.0, 1.0]], np.float32)
    rois = nd.contrib.Proposal(
        nd.array(cls_prob), nd.array(bbox), nd.array(im_info),
        rpn_pre_nms_top_n=20, rpn_post_nms_top_n=4, threshold=0.5,
        rpn_min_size=1, scales=(1.0,), ratios=(0.5, 1.0, 2.0),
        feature_stride=16).asnumpy()
    from mxnet_tpu.ops.contrib_extra import _generate_anchors
    anchors = _generate_anchors(16, (0.5, 1.0, 2.0), (1.0,))
    want = anchors[1] + np.array([2 * 16, 1 * 16, 2 * 16, 1 * 16])
    want = np.clip(want, 0, 47)
    np.testing.assert_allclose(rois[0, 1:], want, atol=1e-4)
