"""Optimizer tests (parity model: reference test_optimizer.py — each
optimizer is checked against a numpy reference implementation)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import optimizer as opt


def _setup(seed=0, shape=(10, 4)):
    rng = np.random.RandomState(seed)
    w = rng.normal(size=shape).astype(np.float32)
    g = rng.normal(size=shape).astype(np.float32)
    return w, g


def test_sgd_matches_numpy():
    w, g = _setup()
    weight, grad = nd.array(w), nd.array(g)
    sgd = opt.SGD(learning_rate=0.1, wd=0.01, rescale_grad=0.5)
    state = sgd.create_state(0, weight)
    sgd.update(0, weight, grad, state)
    expected = w - 0.1 * (0.5 * g + 0.01 * w)
    np.testing.assert_allclose(weight.asnumpy(), expected, rtol=1e-5)


def test_sgd_momentum():
    w, g = _setup()
    weight, grad = nd.array(w), nd.array(g)
    sgd = opt.SGD(learning_rate=0.1, momentum=0.9)
    state = sgd.create_state(0, weight)
    mom_ref = np.zeros_like(w)
    w_ref = w.copy()
    for _ in range(3):
        sgd.update(0, weight, grad, state)
        mom_ref = 0.9 * mom_ref - 0.1 * g
        w_ref = w_ref + mom_ref
    np.testing.assert_allclose(weight.asnumpy(), w_ref, rtol=1e-5)


def test_clip_gradient():
    w, g = _setup()
    g = g * 100
    weight, grad = nd.array(w), nd.array(g)
    sgd = opt.SGD(learning_rate=1.0, clip_gradient=1.0)
    sgd.update(0, weight, grad, None)
    expected = w - np.clip(g, -1, 1)
    np.testing.assert_allclose(weight.asnumpy(), expected, rtol=1e-5)


def test_adam_matches_numpy():
    w, g = _setup()
    weight, grad = nd.array(w), nd.array(g)
    adam = opt.Adam(learning_rate=0.01)
    state = adam.create_state(0, weight)
    m_ref = np.zeros_like(w)
    v_ref = np.zeros_like(w)
    w_ref = w.copy()
    for t in range(1, 4):
        adam.update(0, weight, grad, state)
        lr_t = 0.01 * np.sqrt(1 - 0.999 ** t) / (1 - 0.9 ** t)
        m_ref = 0.9 * m_ref + 0.1 * g
        v_ref = 0.999 * v_ref + 0.001 * g * g
        w_ref = w_ref - lr_t * m_ref / (np.sqrt(v_ref) + 1e-8)
    np.testing.assert_allclose(weight.asnumpy(), w_ref, rtol=1e-4)


def test_rmsprop():
    w, g = _setup()
    weight, grad = nd.array(w), nd.array(g)
    rms = opt.RMSProp(learning_rate=0.01, gamma1=0.9)
    state = rms.create_state(0, weight)
    rms.update(0, weight, grad, state)
    n_ref = 0.1 * g * g
    w_ref = w - 0.01 * g / np.sqrt(n_ref + 1e-8)
    np.testing.assert_allclose(weight.asnumpy(), w_ref, rtol=1e-4)


def test_adagrad():
    w, g = _setup()
    weight, grad = nd.array(w), nd.array(g)
    ada = opt.AdaGrad(learning_rate=0.1)
    state = ada.create_state(0, weight)
    ada.update(0, weight, grad, state)
    h = g * g
    w_ref = w - 0.1 * g / np.sqrt(h + 1e-7)
    np.testing.assert_allclose(weight.asnumpy(), w_ref, rtol=1e-4)


def test_ftrl_runs():
    w, g = _setup()
    weight, grad = nd.array(w), nd.array(g)
    f = opt.Ftrl(learning_rate=0.1)
    state = f.create_state(0, weight)
    f.update(0, weight, grad, state)
    assert np.isfinite(weight.asnumpy()).all()


@pytest.mark.parametrize("name", ["sgd", "adam", "adagrad", "rmsprop",
                                  "adadelta", "ftrl", "nag", "sgld",
                                  "dcasgd", "test"])
def test_registry_create_and_run(name):
    o = opt.create(name)
    w, g = _setup()
    weight, grad = nd.array(w), nd.array(g)
    state = o.create_state(0, weight)
    o.update(0, weight, grad, state)
    assert np.isfinite(weight.asnumpy()).all()
    assert not np.allclose(weight.asnumpy(), w)


def test_lr_scheduler():
    from mxnet_tpu.lr_scheduler import FactorScheduler, MultiFactorScheduler
    s = FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert s(5) == 1.0
    assert s(15) == 0.5
    m = MultiFactorScheduler(step=[5, 10], factor=0.1, base_lr=1.0)
    assert m(3) == 1.0
    assert abs(m(7) - 0.1) < 1e-9
    assert abs(m(12) - 0.01) < 1e-9


def test_lr_wd_mult():
    sgd = opt.SGD(learning_rate=1.0, param_idx2name={0: "w_weight", 1: "b_bias"},
                  wd=0.1)
    # bias gets wd_mult 0 by the reference's rule
    assert sgd._get_wd(1) == 0.0
    assert sgd._get_wd(0) == pytest.approx(0.1)
    sgd.set_lr_mult({"w_weight": 0.5})
    assert sgd._get_lr(0) == pytest.approx(0.5)


def test_updater_states_roundtrip():
    sgd = opt.SGD(learning_rate=0.1, momentum=0.9)
    upd = opt.get_updater(sgd)
    w, g = _setup()
    weight, grad = nd.array(w), nd.array(g)
    upd(0, grad, weight)
    blob = upd.get_states()
    upd2 = opt.get_updater(opt.SGD(learning_rate=0.1, momentum=0.9))
    upd2.set_states(blob)
    assert 0 in upd2.states
