"""Random-op suite — parity with reference tests/python/unittest/test_random.py."""
import numpy as np

import mxnet_tpu as mx


def test_seed_reproducibility():
    mx.random.seed(42)
    a = mx.nd.random.uniform(shape=(100,)).asnumpy()
    mx.random.seed(42)
    b = mx.nd.random.uniform(shape=(100,)).asnumpy()
    np.testing.assert_array_equal(a, b)
    c = mx.nd.random.uniform(shape=(100,)).asnumpy()
    assert not np.array_equal(a, c)


def test_uniform_range_and_moments():
    x = mx.nd.random.uniform(low=2.0, high=5.0, shape=(20000,)).asnumpy()
    assert x.min() >= 2.0 and x.max() < 5.0
    assert abs(x.mean() - 3.5) < 0.05


def test_normal_moments():
    x = mx.nd.random.normal(loc=1.0, scale=2.0, shape=(40000,)).asnumpy()
    assert abs(x.mean() - 1.0) < 0.05
    assert abs(x.std() - 2.0) < 0.05


def test_gamma_moments():
    x = mx.nd.random.gamma(alpha=4.0, beta=0.5, shape=(40000,)).asnumpy()
    assert abs(x.mean() - 2.0) < 0.1  # mean = alpha * beta


def test_exponential_poisson():
    x = mx.nd.random.exponential(lam=2.0, shape=(40000,)).asnumpy()
    assert abs(x.mean() - 0.5) < 0.05
    p = mx.nd.random.poisson(lam=3.0, shape=(40000,)).asnumpy()
    assert abs(p.mean() - 3.0) < 0.1


def test_multinomial():
    probs = mx.nd.array([[0.1, 0.9]])
    draws = mx.nd.random.multinomial(probs, shape=(5000,)).asnumpy()
    frac1 = (draws == 1).mean()
    assert abs(frac1 - 0.9) < 0.05


def test_shuffle_is_permutation():
    x = mx.nd.arange(100)
    y = mx.nd.random.shuffle(x).asnumpy()
    np.testing.assert_array_equal(np.sort(y), np.arange(100))


def test_sample_ops_on_nd_module():
    # mx.nd-level sampling aliases exist (reference autogen surface)
    x = mx.nd.random_uniform(shape=(4, 4))
    assert x.shape == (4, 4)
    x = mx.nd.random_normal(shape=(4, 4))
    assert x.shape == (4, 4)


def test_symbol_random_in_graph():
    # random inside a compiled graph: different per executor run
    data = mx.sym.Variable("data")
    noise = mx.sym.random_uniform(shape=(2, 2))
    out = data + noise
    exe = out.simple_bind(ctx=mx.current_context(), data=(2, 2))
    exe.arg_dict["data"][:] = 0
    a = exe.forward()[0].asnumpy()
    b = exe.forward()[0].asnumpy()
    assert not np.array_equal(a, b)
