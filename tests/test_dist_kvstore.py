"""dist_* kvstore tier satellites (ISSUE 12).

Pins the pieces of the dist wire the 2-process lane cannot conveniently
isolate:

- the push-discipline guard's ERROR path (workers pushed different key
  sets — the SPMD collective requirement the reference's parameter
  server never had);
- gradient compression ROUND-TRIP semantics on the dist wire path
  (2-bit with error-feedback residuals, and the new fp16 wire cast) —
  previously only the non-dist path was pinned;
- ``Module.init_optimizer``'s dist predicate: EVERY ``dist_*`` type
  forces update-on-kvstore explicitly (the old predicate named only
  ``dist_sync`` and let ``dist_sync_device`` et al ride the
  ``_create_kvstore`` default);
- the fused-step eligibility split: sync dist types fuse, ``dist_async``
  and compressed stores keep the explicit wire.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import kvstore as kvs
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gradient_compression import (GradientCompression,
                                            dequantize_2bit, quantize_2bit)
from mxnet_tpu.io import DataDesc


# ---------------------------------------------------------------------------
# push discipline
# ---------------------------------------------------------------------------

def _mismatched_allgather(self, h):
    """Fake a 2-worker gather where the peer pushed something else.
    Patches ``KVStore._host_allgather`` — the LIVE-membership gather
    every dist host exchange (discipline check, row-sparse counts,
    barrier) routes through."""
    h = np.asarray(h)
    return np.stack([h, h + 1])


def _matching_allgather(self, h):
    h = np.asarray(h)
    return np.stack([h, h])


def test_push_discipline_violation_raises(monkeypatch):
    kv = kvs.create("dist_sync")
    monkeypatch.setattr(kvs.KVStore, "_host_allgather",
                        _mismatched_allgather)
    vals = [mx.nd.array(np.ones((4,), np.float32))]
    with pytest.raises(MXNetError) as ei:
        kv._assert_push_discipline(["w0"], vals)
    msg = str(ei.value)
    assert "push discipline violated" in msg
    # the error must name THIS worker's push signature so the two sides
    # of the mismatch can be diffed from two logs
    assert "w0" in msg and "(4,)" in msg and "float32" in msg


def test_push_discipline_matching_passes(monkeypatch):
    kv = kvs.create("dist_sync")
    monkeypatch.setattr(kvs.KVStore, "_host_allgather",
                        _matching_allgather)
    vals = [mx.nd.array(np.ones((4,), np.float32))]
    kv._assert_push_discipline(["w0"], vals)   # no raise


def test_push_discipline_env_kill_switch(monkeypatch):
    def _boom(self, _):
        raise AssertionError("guard must be skipped")

    monkeypatch.setenv("MXNET_KVSTORE_CHECK_PUSH", "0")
    monkeypatch.setattr(kvs.KVStore, "_host_allgather", _boom)
    kv = kvs.create("dist_sync")
    kv._assert_push_discipline(["w0"],
                               [mx.nd.array(np.ones((2,), np.float32))])


# ---------------------------------------------------------------------------
# gradient compression on the dist wire path
# ---------------------------------------------------------------------------

def test_dist_wire_2bit_roundtrip_with_residual():
    """A dist_sync push quantises the merged gradient toward the wire
    (single-worker: the reference worker would quantise toward its
    server) — the stored value equals an explicit
    quantize->dequantize, and the SECOND push carries the first push's
    residual (error feedback across steps)."""
    kv = kvs.create("dist_sync")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    g1 = np.array([0.3, 0.7, -0.9, 0.1], np.float32)
    g2 = np.array([0.4, -0.2, 0.6, 0.2], np.float32)
    kv.init("w", mx.nd.array(np.zeros(4, np.float32)))

    kv.push("w", mx.nd.array(g1))
    out = mx.nd.array(np.zeros(4, np.float32))
    kv.pull("w", out=out)
    p1, r1 = quantize_2bit(jnp.asarray(g1), jnp.zeros(4), 0.5)
    want1 = np.asarray(dequantize_2bit(p1, (4,), 0.5))
    np.testing.assert_allclose(out.asnumpy(), want1, rtol=1e-6)

    kv.push("w", mx.nd.array(g2))
    kv.pull("w", out=out)
    p2, _ = quantize_2bit(jnp.asarray(g2), r1, 0.5)
    want2 = np.asarray(dequantize_2bit(p2, (4,), 0.5))
    np.testing.assert_allclose(out.asnumpy(), want2, rtol=1e-6)


def test_dist_wire_fp16_roundtrip():
    """fp16 wire: a half-precision cast each way — values round to
    fp16 resolution, nothing else changes."""
    kv = kvs.create("dist_sync")
    kv.set_gradient_compression({"type": "fp16"})
    g = np.array([0.30001, -1.5, 3.14159, 0.125], np.float32)
    kv.init("w", mx.nd.array(np.zeros(4, np.float32)))
    kv.push("w", mx.nd.array(g))
    out = mx.nd.array(np.zeros(4, np.float32))
    kv.pull("w", out=out)
    np.testing.assert_array_equal(out.asnumpy(),
                                  g.astype(np.float16)
                                  .astype(np.float32))


def test_fp16_compressor_unit():
    c = GradientCompression(type="fp16")
    g = jnp.asarray(np.linspace(-2, 2, 37, dtype=np.float32))
    packed = c.compress("k", g)
    assert packed.dtype == jnp.float16
    back = c.decompress(packed, g.shape)
    np.testing.assert_array_equal(np.asarray(back),
                                  np.asarray(g, np.float16)
                                  .astype(np.float32))


def test_unknown_compression_type_rejected():
    with pytest.raises(MXNetError):
        GradientCompression(type="1bit")


# ---------------------------------------------------------------------------
# Module dist predicate + fused-step eligibility
# ---------------------------------------------------------------------------

def _bound_module(kv):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[DataDesc("data", (4, 3))],
             label_shapes=[DataDesc("softmax_label", (4,))],
             for_training=True)
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(kvstore=kv, optimizer="sgd")
    return mod


@pytest.mark.parametrize("kv_type", ["dist_sync", "dist_sync_device",
                                     "dist_device_sync", "dist_async"])
def test_all_dist_types_force_update_on_kvstore(kv_type):
    """Regression (ISSUE 12 satellite): the old predicate
    ``kv.type == "dist_sync" or update_on_kvstore`` named ONE dist type
    and let the others ride whatever ``_create_kvstore`` defaulted to.
    Every ``dist_*`` type must force update-on-kvstore explicitly —
    reference semantics: the server applies updates."""
    mod = _bound_module(kvs.create(kv_type))
    assert mod._update_on_kvstore is True
    # kvstore-side application really is wired: the store owns the
    # optimizer's updater
    assert mod._kvstore._updater is not None
    assert mod._updater is None


def test_fused_dist_step_eligibility_split():
    """Sync dist types fuse; dist_async and compressed dist stores keep
    the explicit wire path."""
    assert kvs.create("dist_sync").fused_dist_step
    assert kvs.create("dist_sync_device").fused_dist_step
    assert kvs.create("dist_device_sync").fused_dist_step
    assert not kvs.create("dist_async").fused_dist_step
    kv = kvs.create("dist_sync")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    assert not kv.fused_dist_step
    # and none of the dist types are in-process subsumable
    assert not kvs.create("dist_sync").fused_step_subsumable
