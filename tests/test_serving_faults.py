"""Serving overload control + failure resolution (ISSUE 7): bounded
admission, deadlines at coalesce/resolve, shed-vs-block, the transient
retry budget, the dispatch breaker, and the no-hung-futures contract."""
import threading
import time

import numpy as np
import pytest

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import faults
from mxnet_tpu.base import MXNetError
from mxnet_tpu.serving import (InferenceEngine, DeadlineExceeded,
                               QueueOverflow, CircuitOpen, EngineClosed)

D, HID, C = 4, 8, 2


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=HID, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=C, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _params(sym):
    rng = np.random.RandomState(0)
    shapes, _, _ = sym.infer_shape_partial(data=(2, D))
    return {"arg:" + n: mx.nd.array(rng.normal(0, 0.1, s)
                                    .astype(np.float32))
            for n, s in zip(sym.list_arguments(), shapes)
            if n not in ("data", "softmax_label")}


def _engine(**kw):
    sym = _mlp()
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_wait_ms", 1.0)
    return InferenceEngine(sym, _params(sym), {"data": (1, D)}, **kw)


def _req():
    return np.random.RandomState(1).normal(size=(1, D)).astype(np.float32)


# ---------------------------------------------------------------------------
# Deadlines
# ---------------------------------------------------------------------------

def test_deadline_shed_at_coalesce_time():
    eng = _engine(max_wait_ms=10000)        # only flush() dispatches
    try:
        f = eng.submit(data=_req(), deadline_ms=10)
        time.sleep(0.05)
        eng.flush()
        with pytest.raises(DeadlineExceeded):
            f.result(timeout=10)
        st = eng.stats()
        assert st["shed_requests"] == 1 and st["shed_rows"] == 1
        assert st["shed_by_cause"] == {"coalesce": 1}
        # a shed request is not queue depth
        assert st["queue_depth"] == 0
    finally:
        eng.close()


def test_deadline_shed_at_resolve_time():
    # delay the d2h fetch past the deadline: the batch DID run, but the
    # result arrives late and must resolve DeadlineExceeded, not succeed
    eng = _engine()
    try:
        faults.configure("d2h:delay=120")
        with pytest.raises(DeadlineExceeded):
            eng.submit(data=_req(), deadline_ms=30).result(timeout=10)
        assert eng.stats()["shed_by_cause"] == {"resolve": 1}
    finally:
        eng.close()


def test_engine_default_deadline_applies():
    eng = _engine(max_wait_ms=10000, deadline_ms=10)
    try:
        f = eng.submit(data=_req())          # no per-request deadline
        time.sleep(0.05)
        eng.flush()
        with pytest.raises(DeadlineExceeded):
            f.result(timeout=10)
    finally:
        eng.close()


def test_no_deadline_means_no_shedding():
    eng = _engine(max_wait_ms=10000)
    try:
        f = eng.submit(data=_req())
        time.sleep(0.05)
        eng.flush()
        assert f.result(timeout=10)[0].shape == (1, C)
        assert eng.stats()["shed_requests"] == 0
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# Bounded admission queue
# ---------------------------------------------------------------------------

def test_admission_shed_when_queue_full():
    eng = _engine(max_wait_ms=10000, max_queue_rows=3)
    try:
        fs = [eng.submit(data=_req()) for _ in range(3)]
        with pytest.raises(QueueOverflow):
            eng.submit(data=_req())
        st = eng.stats()
        assert st["queued_rows"] == 3
        assert st["shed_by_cause"] == {"admission": 1}
        eng.flush()
        for f in fs:                         # admitted requests resolve
            assert f.result(timeout=10)[0].shape == (1, C)
        assert eng.stats()["queued_rows"] == 0
    finally:
        eng.close()


def test_block_policy_backpressures_until_space():
    eng = _engine(max_wait_ms=10000, max_queue_rows=2, overload="block")
    try:
        fs = [eng.submit(data=_req()) for _ in range(2)]
        done = threading.Event()
        holder = {}

        def blocked_submit():
            holder["future"] = eng.submit(data=_req())
            done.set()

        t = threading.Thread(target=blocked_submit, daemon=True)
        t.start()
        assert not done.wait(0.1)            # genuinely blocked
        eng.flush()                          # drains the queue -> space
        assert done.wait(5)
        eng.flush()
        assert holder["future"].result(timeout=10)[0].shape == (1, C)
        for f in fs:
            assert f.result(timeout=10)[0].shape == (1, C)
    finally:
        eng.close()


def test_block_policy_gives_up_at_deadline():
    eng = _engine(max_wait_ms=10000, max_queue_rows=1, overload="block")
    try:
        f0 = eng.submit(data=_req())
        t0 = time.perf_counter()
        with pytest.raises(DeadlineExceeded):
            eng.submit(data=_req(), deadline_ms=50)
        assert time.perf_counter() - t0 >= 0.04
        eng.flush()
        assert f0.result(timeout=10)[0].shape == (1, C)
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# Retry budget + breaker
# ---------------------------------------------------------------------------

def test_transient_dispatch_fault_retried_within_budget():
    eng = _engine(retry_budget=2, retry_backoff_ms=1.0)
    try:
        faults.configure("dispatch:raise:n=1")
        out = eng.submit(data=_req()).result(timeout=10)
        assert out[0].shape == (1, C)
        st = eng.stats()
        assert st["retries"] == 1 and st["dispatch_failures"] == 0
        assert faults.counts()["dispatch"]["fired"] == 1
    finally:
        eng.close()


def test_retry_budget_exhausts_then_fails_structured():
    eng = _engine(retry_budget=1, retry_backoff_ms=1.0,
                  breaker_threshold=0)
    try:
        faults.configure("dispatch:raise")       # every attempt fails
        with pytest.raises(faults.InjectedFault):
            eng.submit(data=_req()).result(timeout=10)
        st = eng.stats()
        assert st["retries"] == 1 and st["dispatch_failures"] == 1
        faults.clear()
        # engine still usable after a failed batch (breaker disabled)
        assert eng.submit(data=_req()).result(timeout=10)[0].shape \
            == (1, C)
    finally:
        eng.close()


def test_program_errors_never_retry():
    eng = _engine(retry_budget=3, breaker_threshold=0)
    try:
        real = eng._forward

        def broken(*a, **k):
            raise ValueError("rank mismatch — a program error")

        eng._forward = broken
        with pytest.raises(ValueError):
            eng.submit(data=_req()).result(timeout=10)
        assert eng.stats()["retries"] == 0
        eng._forward = real
        assert eng.submit(data=_req()).result(timeout=10)[0].shape \
            == (1, C)
    finally:
        eng.close()


def test_breaker_trips_then_fast_fails_then_half_open_recovers():
    eng = _engine(retry_budget=0, breaker_threshold=2,
                  breaker_reset_s=0.15)
    try:
        faults.configure("dispatch:raise")
        for _ in range(2):
            with pytest.raises(faults.InjectedFault):
                eng.submit(data=_req()).result(timeout=10)
        st = eng.stats()
        assert st["breaker"]["open"] is True
        assert st["breaker"]["trips"] == 1
        assert st["breaker"]["consecutive_failures"] == 2
        # open breaker: submit fast-fails without touching the device
        with pytest.raises(CircuitOpen):
            eng.submit(data=_req())
        assert eng.stats()["breaker"]["fastfail"] >= 1
        # backend recovers; after the cooldown the half-open trial
        # closes the breaker
        faults.clear()
        time.sleep(0.2)
        assert eng.submit(data=_req()).result(timeout=10)[0].shape \
            == (1, C)
        st = eng.stats()
        assert st["breaker"]["open"] is False
        assert st["breaker"]["consecutive_failures"] == 0
    finally:
        eng.close()


def test_breaker_fast_fails_queued_requests():
    # a request that was ADMITTED before the trip still resolves (with
    # CircuitOpen, at dispatch time) — an open breaker never strands a
    # future, and new submits fast-fail at admission
    eng = _engine(max_wait_ms=10000, retry_budget=0, breaker_threshold=1,
                  breaker_reset_s=30.0)
    try:
        faults.configure("dispatch:raise")
        f0 = eng.submit(data=_req())
        eng.flush()
        with pytest.raises(faults.InjectedFault):
            f0.result(timeout=10)            # trips the breaker
        faults.clear()
        assert eng._breaker_tripped()
        with pytest.raises(CircuitOpen):
            eng.submit(data=_req())
        # a request that races past admission before the trip reaches
        # _dispatch with the breaker open: resolved, never stranded
        from mxnet_tpu.serving import _Request
        raced = _Request({"data": _req()}, 1)
        eng._dispatch([raced])
        with pytest.raises(CircuitOpen):
            raced.future.result(timeout=10)
        assert eng.stats()["breaker"]["fastfail"] >= 2
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# In-flight failure resolution (the no-hung-futures contract)
# ---------------------------------------------------------------------------

def test_midflight_failure_resolves_every_pending_future():
    eng = _engine(max_wait_ms=10000, retry_budget=0, breaker_threshold=0)
    try:
        faults.configure("dispatch:raise")
        futs = [eng.submit(data=_req()) for _ in range(5)]
        eng.flush()
        for f in futs:
            with pytest.raises(faults.InjectedFault):
                f.result(timeout=10)         # resolves, never hangs
        assert all(f.done() for f in futs)
        faults.clear()
        # breaker never tripped (disabled): engine fully usable
        f = eng.submit(data=_req())
        eng.flush()
        assert f.result(timeout=10)[0].shape == (1, C)
    finally:
        eng.close()


def test_d2h_failure_resolves_every_pending_future():
    eng = _engine(max_wait_ms=10000, breaker_threshold=0)
    try:
        faults.configure("d2h:raise:n=1")
        futs = [eng.submit(data=_req()) for _ in range(3)]
        eng.flush()
        for f in futs:
            with pytest.raises(faults.InjectedFault):
                f.result(timeout=10)
        faults.clear()
        f = eng.submit(data=_req())
        eng.flush()
        assert f.result(timeout=10)[0].shape == (1, C)
    finally:
        eng.close()


def test_d2h_nan_corruption_reaches_the_client():
    eng = _engine()
    try:
        faults.configure("d2h:nan:n=1")
        out = eng.submit(data=_req()).result(timeout=10)
        assert np.isnan(np.asarray(out[0]).reshape(-1)[0])
        out = eng.submit(data=_req()).result(timeout=10)
        assert np.isfinite(np.asarray(out[0])).all()
    finally:
        eng.close()


def test_queue_depth_stays_consistent_under_sheds_and_failures():
    # admission sheds never entered the queue (depth must not go
    # negative); failed requests terminated (depth must not stay
    # inflated) — the number a load balancer's health endpoint reads
    eng = _engine(max_wait_ms=10000, max_queue_rows=2,
                  retry_budget=0, breaker_threshold=0)
    try:
        fs = [eng.submit(data=_req()) for _ in range(2)]
        for _ in range(3):
            with pytest.raises(QueueOverflow):
                eng.submit(data=_req())
        assert eng.stats()["queue_depth"] == 2      # not -1
        faults.configure("dispatch:raise")
        eng.flush()
        for f in fs:
            with pytest.raises(faults.InjectedFault):
                f.result(timeout=10)
        st = eng.stats()
        assert st["failed_requests"] == 2
        assert st["queue_depth"] == 0               # not 2 forever
        assert st["shed_by_cause"] == {"admission": 3}
    finally:
        faults.clear()
        eng.close()


def test_fetch_failure_feeds_the_breaker():
    # on an async backend a dead device surfaces at the d2h fetch, not
    # at launch — the breaker must see those failures too
    eng = _engine(retry_budget=0, breaker_threshold=2,
                  breaker_reset_s=30.0)
    try:
        faults.configure("d2h:raise")
        for _ in range(2):
            with pytest.raises(faults.InjectedFault):
                eng.submit(data=_req()).result(timeout=10)
        st = eng.stats()
        assert st["breaker"]["open"] is True
        assert st["dispatch_failures"] == 2
        with pytest.raises(CircuitOpen):
            eng.submit(data=_req())
    finally:
        faults.clear()
        eng.close()


def test_admission_shed_still_lands_a_latency_sample():
    # the shed-at-admission request's serve_request span closes — the
    # overload percentiles include rejected requests, same as the
    # coalesce/resolve shed paths
    from mxnet_tpu import telemetry
    telemetry.enable()
    eng = _engine(max_wait_ms=10000, max_queue_rows=1)
    try:
        base = telemetry.span_count("serve_request")
        f0 = eng.submit(data=_req())
        with pytest.raises(QueueOverflow):
            eng.submit(data=_req())
        assert telemetry.span_count("serve_request") == base + 1
        eng.flush()
        f0.result(timeout=10)
    finally:
        eng.close()


def test_close_resolves_inflight_then_fails_fast():
    eng = _engine(max_wait_ms=10000)
    futs = [eng.submit(data=_req()) for _ in range(3)]
    eng.close()
    for f in futs:                           # drained, resolved
        assert f.result(timeout=10)[0].shape == (1, C)
    with pytest.raises(EngineClosed):
        eng.submit(data=_req())
    with pytest.raises(EngineClosed):
        eng.flush()
    # EngineClosed is a structured MXNetError
    assert issubclass(EngineClosed, MXNetError)


def test_shed_errors_are_structured_mxnet_errors():
    for cls in (DeadlineExceeded, QueueOverflow, CircuitOpen):
        assert issubclass(cls, MXNetError)


# ---------------------------------------------------------------------------
# mxlife future-lifecycle regressions (ISSUE 14): failed requests keep
# their span accounting, and a dying coalescer strands nothing
# ---------------------------------------------------------------------------

def test_failed_batch_still_records_request_spans():
    """Requests failing through _fail_requests must still close their
    serve_request/serve_wait spans — before the fix the latency
    percentiles and the flight recorder silently excluded exactly the
    interesting (failing) requests."""
    from mxnet_tpu import telemetry
    telemetry.enable()
    eng = _engine(max_wait_ms=10000, retry_budget=0,
                  breaker_threshold=0)
    try:
        before_req = telemetry.span_count("serve_request")
        before_wait = telemetry.span_count("serve_wait")
        faults.configure("dispatch:raise")
        futs = [eng.submit(data=_req()) for _ in range(4)]
        eng.flush()
        for f in futs:
            with pytest.raises(faults.InjectedFault):
                f.result(timeout=10)
        # the spans closed BEFORE each future resolved, so by now all
        # four latency samples are banked on both span names
        assert telemetry.span_count("serve_request") - before_req >= 4
        assert telemetry.span_count("serve_wait") - before_wait >= 4
    finally:
        faults.clear()
        eng.close()


def test_coalescer_death_fails_queued_futures_not_hangs():
    """The coalescer is the ONLY consumer of the admission queue: if
    it dies on an unexpected exception, every queued future must
    resolve with a structured error (and later submits fast-fail with
    EngineClosed) instead of hanging forever — the zero-hung-futures
    promise on the exception path the mxlife audit polices."""
    eng = _engine(max_wait_ms=10000)
    try:
        def _boom(batch):
            raise RuntimeError("seeded coalescer bug")

        eng._launch = _boom
        f = eng.submit(data=_req())
        eng.flush()
        with pytest.raises(MXNetError) as ei:
            f.result(timeout=10)
        assert "coalescer" in str(ei.value)
        # the engine closed itself: no new request can queue into the
        # dead queue
        with pytest.raises(EngineClosed):
            eng.submit(data=_req())
        st = eng.stats()
        assert st["shed_by_cause"].get("coalescer_death") == 1
        assert st["queued_rows"] == 0
        # the FIRST close() after a coalescer death keeps its full
        # contract: pool shutdown + corpus/logger flush still run
        # (only a completed close() makes later calls no-ops)
        eng.close()
        assert eng._pool._shutdown
    finally:
        eng.close()


def test_coalescer_death_mid_launch_keeps_queue_accounting():
    """A batch whose _launch died AFTER releasing its rows from the
    admission queue is handed back for terminal cleanup — the rows
    must be re-charged first, or the uniform cleanup decrement drives
    queued_rows negative (corrupting the postmortem's engine
    snapshot)."""
    eng = _engine(max_wait_ms=10000)
    try:
        def _boom(reqs):
            raise RuntimeError("seeded dispatch bug")

        # die INSIDE _launch, after its queued-rows release
        eng._dispatch = _boom
        f = eng.submit(data=_req())
        eng.flush()
        with pytest.raises(MXNetError):
            f.result(timeout=10)
        st = eng.stats()
        assert st["queued_rows"] == 0, st
        assert st["shed_by_cause"].get("coalescer_death") == 1
    finally:
        eng.close()
