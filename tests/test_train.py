"""End-to-end convergence tests — parity with reference tests/python/train/
(test_mlp.py / test_conv.py): train small nets to a threshold accuracy."""
import numpy as np

import mxnet_tpu as mx


def _synthetic_classification(n=512, dim=16, classes=4, seed=7):
    rng = np.random.RandomState(seed)
    centers = rng.uniform(-3, 3, size=(classes, dim)).astype(np.float32)
    labels = rng.randint(0, classes, size=n)
    x = centers[labels] + rng.normal(scale=0.5, size=(n, dim)).astype(np.float32)
    return x.astype(np.float32), labels.astype(np.float32)


def test_mlp_module_fit_converges():
    x, y = _synthetic_classification()
    train = mx.io.NDArrayIter(x, y, batch_size=64, shuffle=True)
    val = mx.io.NDArrayIter(x, y, batch_size=64)

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data=data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    mod = mx.mod.Module(net, data_names=["data"], label_names=["softmax_label"],
                        context=mx.current_context())
    mod.fit(train, eval_data=val, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5, "momentum": 0.9,
                              "rescale_grad": 1.0 / 64},
            num_epoch=8, eval_metric="acc")
    val.reset()
    score = mod.score(val, mx.metric.Accuracy())
    acc = dict(score)["accuracy"]
    assert acc > 0.95, "MLP failed to converge: acc=%f" % acc


def test_lenet_style_conv_converges():
    rng = np.random.RandomState(3)
    n = 256
    # images of vertical vs horizontal bars
    x = np.zeros((n, 1, 8, 8), dtype=np.float32)
    y = rng.randint(0, 2, size=n)
    for i in range(n):
        pos = rng.randint(0, 8)
        if y[i] == 0:
            x[i, 0, :, pos] = 1.0
        else:
            x[i, 0, pos, :] = 1.0
    train = mx.io.NDArrayIter(x, y.astype(np.float32), batch_size=32,
                              shuffle=True)

    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data=data, num_filter=8, kernel=(3, 3),
                             pad=(1, 1), name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    mod = mx.mod.Module(net, context=mx.current_context())
    mod.fit(train, optimizer="adam",
            optimizer_params={"learning_rate": 0.01,
                              "rescale_grad": 1.0 / 32},
            num_epoch=6, eval_metric="acc")
    train.reset()
    acc = dict(mod.score(train, mx.metric.Accuracy()))["accuracy"]
    assert acc > 0.95, "conv net failed to converge: acc=%f" % acc


def test_gluon_training_converges():
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    x, y = _synthetic_classification(n=256, dim=8, classes=3, seed=11)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"))
        net.add(nn.Dense(3))
    net.initialize(mx.initializer.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    l2 = gluon.loss.SoftmaxCrossEntropyLoss()
    xs, ys = mx.nd.array(x), mx.nd.array(y)
    for _ in range(60):
        with mx.autograd.record():
            loss = l2(net(xs), ys).mean()
        loss.backward()
        trainer.step(x.shape[0])
    pred = net(xs).asnumpy().argmax(axis=1)
    acc = (pred == y).mean()
    assert acc > 0.95, "gluon training failed to converge: acc=%f" % acc


def test_cifar_shape_conv_bf16_converges():
    """The reference-scale dtype workload (tests/python/train/
    test_dtype.py run_cifar10 shape: conv+BN stack on 3x32x32, low-
    precision data iterator): bf16 activations with fp32 master weights
    (multi_precision) and fp32 BN params via the InferType pass — the
    exact numeric regime bench.py's ResNet-50 measurement relies on.
    Must clear an accuracy threshold far above the reference's 0.08."""
    import jax.numpy as jnp
    rng = np.random.RandomState(5)
    n, classes = 384, 4
    # separable color-geometry task: class = which quadrant carries the
    # dominant channel energy
    x = rng.uniform(0, 0.3, size=(n, 3, 32, 32)).astype(np.float32)
    y = rng.randint(0, classes, size=n)
    for i in range(n):
        q = y[i]
        r0, c0 = (q // 2) * 16, (q % 2) * 16
        x[i, :, r0:r0 + 16, c0:c0 + 16] += 0.7
    bf16 = np.dtype(jnp.bfloat16)

    train = mx.io.NDArrayIter(x.astype(bf16), y.astype(np.float32),
                              batch_size=32, shuffle=True)

    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data=data, num_filter=8, kernel=(3, 3),
                             pad=(1, 1), name="conv1")
    net = mx.sym.BatchNorm(net, fix_gamma=False, name="bn1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Convolution(net, num_filter=16, kernel=(3, 3),
                             pad=(1, 1), name="conv2")
    net = mx.sym.BatchNorm(net, fix_gamma=False, name="bn2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    mod = mx.mod.Module(net, context=mx.current_context())
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "rescale_grad": 1.0 / 32,
                              "multi_precision": True},
            num_epoch=6, eval_metric="acc")
    # executor ran bf16 end to end (InferType pinned the data path)
    assert mod._exec.arg_dict["data"].dtype == bf16
    assert mod._exec.arg_dict["conv1_weight"].dtype == bf16
    # fp32 master weights exist in the optimizer (mp_sgd scheme):
    # multi-precision states are (state, fp32 master) tuples
    updater = mod._updater
    if updater is None and mod._kvstore is not None:
        updater = mod._kvstore._updater
    states = getattr(updater, "states", {})
    assert any(
        isinstance(st, tuple) and len(st) == 2
        and getattr(st[1], "dtype", None) == np.float32
        for st in states.values()), \
        "no fp32 master weights found (multi_precision was a no-op)"
    train.reset()
    acc = dict(mod.score(train, mx.metric.Accuracy()))["accuracy"]
    assert acc > 0.9, "bf16 conv net failed to converge: acc=%f" % acc
