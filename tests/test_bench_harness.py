"""The bench harness must be un-losable: a child that already printed its
measurement and THEN hangs (the round-3 failure mode — a stall in the
optional module phase, or a PJRT hang the parent can only kill from
outside) must still yield a parsed result in the supervisor.

Mirrors the reference's benchmark_score.py contract of always emitting a
number; the robustness layer is ours (the reference never ran against a
backend that hangs at init)."""
import json
import os
import subprocess
import sys
import textwrap

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import bench  # noqa: E402


def test_last_json_line_picks_last_parseable():
    text = "\n".join([
        "noise",
        json.dumps({"value": 1}),
        "bench: warming up",
        json.dumps({"value": 2, "unit": "img/s"}),
        "{truncated",  # a partial line from a killed child
    ])
    assert bench._last_json_line(text) == {"value": 2, "unit": "img/s"}


def test_last_json_line_accepts_bytes():
    # TimeoutExpired.stdout can be bytes even under text=True
    raw = (json.dumps({"value": 3.5}) + "\n").encode()
    assert bench._last_json_line(raw) == {"value": 3.5}
    assert bench._last_json_line(None) is None
    assert bench._last_json_line("") is None


def test_run_phase_salvages_stdout_of_hung_child(tmp_path, monkeypatch):
    """A child that prints its JSON then hangs forever: _run_phase must
    kill it at the timeout and return the salvaged measurement."""
    stub = tmp_path / "hang_after_print.py"
    stub.write_text(textwrap.dedent("""
        import json, sys, time
        print(json.dumps({"value": 42.0, "unit": "img/s"}), flush=True)
        time.sleep(3600)
    """))
    orig = subprocess.run

    def fake_run(cmd, **kw):
        # route the harness's child invocation to the hanging stub
        return orig([sys.executable, str(stub)], **kw)

    monkeypatch.setattr(subprocess, "run", fake_run)
    # interpreter startup here is ~4s (axon sitecustomize); the
    # timeout must comfortably cover it so the print lands first
    parsed, timed_out = bench._run_phase("--child", timeout=20)
    assert timed_out
    assert parsed == {"value": 42.0, "unit": "img/s"}


def test_run_phase_handles_crash_without_output(tmp_path, monkeypatch):
    stub = tmp_path / "crash.py"
    stub.write_text("import sys; sys.exit(7)\n")
    orig = subprocess.run

    def fake_run(cmd, **kw):
        return orig([sys.executable, str(stub)], **kw)

    monkeypatch.setattr(subprocess, "run", fake_run)
    parsed, timed_out = bench._run_phase("--child", timeout=10)
    assert parsed is None and not timed_out


@pytest.mark.slow
def test_smoke_end_to_end():
    """Full harness in smoke mode: one JSON line on stdout, rc 0."""
    env = dict(os.environ, MXTPU_BENCH_SMOKE="1")
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        stdout=subprocess.PIPE, text=True, timeout=900, env=env)
    assert proc.returncode == 0
    out = bench._last_json_line(proc.stdout)
    assert out is not None and "value" in out and out["unit"] == "img/s"
