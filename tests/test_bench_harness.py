"""The bench harness must be un-losable: a child that already printed its
measurement and THEN hangs (the round-3 failure mode — a stall in the
optional module phase, or a PJRT hang the parent can only kill from
outside) must still yield a parsed result in the supervisor.

Mirrors the reference's benchmark_score.py contract of always emitting a
number; the robustness layer is ours (the reference never ran against a
backend that hangs at init)."""
import json
import os
import subprocess
import sys
import textwrap

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import bench  # noqa: E402


def test_last_json_line_picks_last_parseable():
    text = "\n".join([
        "noise",
        json.dumps({"value": 1}),
        "bench: warming up",
        json.dumps({"value": 2, "unit": "img/s"}),
        "{truncated",  # a partial line from a killed child
    ])
    assert bench._last_json_line(text) == {"value": 2, "unit": "img/s"}


def test_last_json_line_accepts_bytes():
    # TimeoutExpired.stdout can be bytes even under text=True
    raw = (json.dumps({"value": 3.5}) + "\n").encode()
    assert bench._last_json_line(raw) == {"value": 3.5}
    assert bench._last_json_line(None) is None
    assert bench._last_json_line("") is None


def test_run_phase_salvages_stdout_of_hung_child(tmp_path, monkeypatch):
    """A child that prints its JSON then hangs forever: _run_phase must
    kill it at the timeout and return the salvaged measurement."""
    stub = tmp_path / "hang_after_print.py"
    stub.write_text(textwrap.dedent("""
        import json, sys, time
        print(json.dumps({"value": 42.0, "unit": "img/s"}), flush=True)
        time.sleep(3600)
    """))
    orig = subprocess.run

    def fake_run(cmd, **kw):
        # route the harness's child invocation to the hanging stub
        return orig([sys.executable, str(stub)], **kw)

    monkeypatch.setattr(subprocess, "run", fake_run)
    # interpreter startup here is ~4s (axon sitecustomize); the
    # timeout must comfortably cover it so the print lands first
    parsed, timed_out = bench._run_phase("--child", timeout=20)
    assert timed_out
    assert parsed == {"value": 42.0, "unit": "img/s"}


def test_run_phase_handles_crash_without_output(tmp_path, monkeypatch):
    stub = tmp_path / "crash.py"
    stub.write_text("import sys; sys.exit(7)\n")
    orig = subprocess.run

    def fake_run(cmd, **kw):
        return orig([sys.executable, str(stub)], **kw)

    monkeypatch.setattr(subprocess, "run", fake_run)
    parsed, timed_out = bench._run_phase("--child", timeout=10)
    assert parsed is None and not timed_out


@pytest.mark.slow
def test_smoke_end_to_end():
    """Full harness in smoke mode: one JSON line on stdout, rc 0."""
    env = dict(os.environ, MXTPU_BENCH_SMOKE="1")
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py")],
        stdout=subprocess.PIPE, text=True, timeout=900, env=env)
    assert proc.returncode == 0
    out = bench._last_json_line(proc.stdout)
    assert out is not None and "value" in out and out["unit"] == "img/s"


def _patched_supervise(monkeypatch, phases, deadline=30.0, smoke=False,
                       ab=False):
    """Run supervise() with _run_phase replaced by a scripted stub.
    `phases` maps mode -> callable returning (parsed, timed_out); the
    stub records the call sequence. Returns (rc, calls, stdout_json)."""
    calls = []

    def fake_phase(mode, timeout, env_extra=None):
        calls.append(mode)
        n = calls.count(mode)
        fn = phases[mode]
        if fn.__code__.co_argcount >= 2:
            return fn(n, env_extra)
        return fn(n)

    monkeypatch.setenv("MXTPU_BENCH_AB", "1" if ab else "0")
    # optional phases default OFF here; dedicated tests opt back in
    monkeypatch.setenv("MXTPU_BENCH_DP", "0")
    monkeypatch.setenv("MXTPU_BENCH_SERVE", "0")
    monkeypatch.setenv("MXTPU_BENCH_DECODE", "0")
    monkeypatch.setattr(bench, "_run_phase", fake_phase)
    monkeypatch.setattr(bench, "TOTAL_DEADLINE", deadline)
    monkeypatch.setattr(bench, "SMOKE", smoke)
    monkeypatch.setattr(bench, "PROBE_TIMEOUT", 1.0)
    monkeypatch.setattr(bench, "PROBE_GAP", 0.0)
    monkeypatch.setattr(bench, "RAW_MIN", 0.5)
    import io
    from contextlib import redirect_stdout
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench.supervise()
    return rc, calls, bench._last_json_line(buf.getvalue())


def test_supervise_emits_error_json_when_backend_never_up(monkeypatch):
    """Probes that never succeed: no raw child is ever launched, and a
    diagnostic JSON line is still printed (the round-4 rc=124/parsed-null
    failure mode must be impossible)."""
    import time as _time

    def failing_probe(n):
        _time.sleep(0.2)  # a real probe child costs wall-clock
        return None, True

    rc, calls, out = _patched_supervise(
        monkeypatch,
        {"--probe": failing_probe},
        deadline=2.0)
    assert rc == 1
    assert "--child" not in calls          # raw child is probe-gated
    assert calls.count("--probe") >= 2     # it LOOPS, not one-shot
    assert out is not None and "error" in out and out["probe_ok"] is False


def test_supervise_probe_gates_then_measures(monkeypatch):
    """First probe fails, second succeeds, raw child then measures; the
    module phase result is merged in."""
    meas = {"value": 123.0, "unit": "img/s"}
    rc, calls, out = _patched_supervise(
        monkeypatch,
        {"--probe": lambda n: ((None, True) if n == 1
                               else ({"device": "x"}, False)),
         "--child": lambda n: (dict(meas), False),
         "--module-child": lambda n: ({"module_fit_img_s": 99.0}, False)},
        deadline=600.0)
    assert rc == 0
    assert calls.index("--child") > calls.index("--probe")
    assert out["value"] == 123.0 and out["module_fit_img_s"] == 99.0


def test_supervise_raw_failure_returns_to_probing(monkeypatch):
    """A raw child that dies after a good probe sends the loop back to
    probing; a later raw attempt can still win."""
    state = {"raw": 0}

    def raw(n):
        state["raw"] = n
        if n < 2:
            return None, False
        return {"value": 7.0, "unit": "img/s"}, False

    monkeypatch.setenv("MXTPU_BENCH_MODULE", "0")
    rc, calls, out = _patched_supervise(
        monkeypatch,
        {"--probe": lambda n: ({"device": "x"}, False), "--child": raw},
        deadline=600.0)
    assert rc == 0 and out["value"] == 7.0 and state["raw"] == 2


def test_supervise_fused_bn_ab_phase(monkeypatch):
    """With budget left after the raw number, a second raw child runs
    with the fused-BN knob pinned on; the baseline pins it off."""
    envs = []

    def raw(n, env_extra=None):
        envs.append(env_extra)
        return {"value": 100.0 + n, "unit": "img/s"}, False

    monkeypatch.setenv("MXTPU_BENCH_MODULE", "0")
    rc, calls, out = _patched_supervise(
        monkeypatch,
        {"--probe": lambda n: ({"device": "x"}, False), "--child": raw},
        deadline=600.0, ab=True)
    assert rc == 0
    assert envs[0] == {"MXNET_FUSED_BN_ADD_RELU": "0"}
    assert envs[1] == {"MXNET_FUSED_BN_ADD_RELU": "1"}
    assert out["value"] == 101.0 and out["img_s_fused_bn_tail"] == 102.0


def test_budget_args_bare_number(monkeypatch):
    """--budget-s 1200 rescales the total deadline and strips the flag
    (the BENCH_r03/r04 rc=124 fix: the driver hands its window in)."""
    monkeypatch.setattr(bench, "TOTAL_DEADLINE", 1500.0)
    rest = bench._apply_budget_args(["--budget-s", "1200", "--child"])
    assert rest == ["--child"]
    assert bench.TOTAL_DEADLINE == 1200.0


def test_budget_args_per_phase(monkeypatch):
    for name in ("TOTAL_DEADLINE", "PROBE_TIMEOUT", "RAW_TIMEOUT",
                 "MODULE_TIMEOUT"):
        monkeypatch.setattr(bench, name, getattr(bench, name))
    rest = bench._apply_budget_args(
        ["--budget-s=probe=60,raw=600", "--budget-s", "module=300"])
    assert rest == []
    assert bench.PROBE_TIMEOUT == 60.0
    assert bench.RAW_TIMEOUT == 600.0
    assert bench.MODULE_TIMEOUT == 300.0


def test_budget_args_unknown_phase_fails_loudly(monkeypatch):
    monkeypatch.setattr(bench, "TOTAL_DEADLINE", 1500.0)
    with pytest.raises(SystemExit):
        bench._apply_budget_args(["--budget-s", "warmup=10"])


def test_budget_args_malformed_fails_loudly(monkeypatch):
    """A trailing --budget-s with no value, or a non-numeric seconds
    value, must exit with a usage error — not an IndexError/ValueError
    traceback that skips the harness's final-JSON-line contract."""
    monkeypatch.setattr(bench, "TOTAL_DEADLINE", 1500.0)
    with pytest.raises(SystemExit):
        bench._apply_budget_args(["--child", "--budget-s"])
    with pytest.raises(SystemExit):
        bench._apply_budget_args(["--budget-s", "1.5x"])
    with pytest.raises(SystemExit):
        bench._apply_budget_args(["--budget-s", "raw=fast"])


def test_no_backend_round_marked_skipped(monkeypatch):
    """A round where the backend never initialises must read as
    unmeasurable (skipped: true), not as a zero — a tunnel outage can
    no longer zero out a round's numbers."""
    import time as _time

    def failing_probe(n):
        _time.sleep(0.2)
        return None, True

    rc, calls, out = _patched_supervise(
        monkeypatch, {"--probe": failing_probe}, deadline=2.0)
    assert rc == 1
    assert out["skipped"] is True


def test_backend_up_but_raw_failed_not_skipped(monkeypatch):
    """Probe succeeded but every raw child died: that IS a measurement
    failure (skipped: false) — the backend was reachable."""
    rc, calls, out = _patched_supervise(
        monkeypatch,
        {"--probe": lambda n: ({"device": "x"}, False),
         "--child": lambda n: (None, False)},
        deadline=8.0)
    assert rc == 1
    assert "error" in out and out["skipped"] is False


def test_module_phase_ab_merge_and_partial_emission(monkeypatch):
    """The module child's fused + phase-split numbers both merge into
    the final line, and the raw number is banked as a partial line
    BEFORE the module phase runs (an outer kill mid-module-phase
    salvages it)."""
    import io
    from contextlib import redirect_stdout

    calls = []

    def fake_phase(mode, timeout, env_extra=None):
        calls.append(mode)
        if mode == "--probe":
            return {"device": "x"}, False
        if mode == "--child":
            return {"value": 500.0, "unit": "img/s"}, False
        return {"module_fit_img_s": 90.0,
                "module_fit_phase_split_img_s": 30.0}, False

    monkeypatch.setenv("MXTPU_BENCH_AB", "0")
    monkeypatch.setenv("MXTPU_BENCH_MODULE", "1")
    monkeypatch.setenv("MXTPU_BENCH_DP", "0")
    monkeypatch.setattr(bench, "_run_phase", fake_phase)
    monkeypatch.setattr(bench, "TOTAL_DEADLINE", 600.0)
    monkeypatch.setattr(bench, "SMOKE", False)
    monkeypatch.setattr(bench, "PROBE_TIMEOUT", 1.0)
    monkeypatch.setattr(bench, "PROBE_GAP", 0.0)
    monkeypatch.setattr(bench, "RAW_MIN", 0.5)
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench.supervise()
    assert rc == 0
    lines = [json.loads(l) for l in buf.getvalue().splitlines()
             if l.strip().startswith("{")]
    # a partial line with the raw number lands before the module phase
    partials = [l for l in lines if l.get("partial")]
    assert partials and partials[0]["value"] == 500.0
    assert "module_fit_img_s" not in partials[0]
    final = lines[-1]
    assert not final.get("partial")
    assert final["module_fit_img_s"] == 90.0
    assert final["module_fit_phase_split_img_s"] == 30.0


def test_supervise_dp_phase_merges(monkeypatch):
    """With budget left, the dp A/B child runs and its per-axis-size
    table merges into the final line."""
    dp_table = {"1": {"fused_img_s": 150.0, "kvstore_img_s": 150.0},
                "8": {"fused_img_s": 1000.0, "kvstore_img_s": 400.0}}

    def fake_phase(mode, timeout, env_extra=None):
        if mode == "--probe":
            return {"device": "x"}, False
        if mode == "--child":
            return {"value": 500.0, "unit": "img/s"}, False
        assert mode == "--dp-child", mode
        return {"lane": "dp_ab", "dp": dict(dp_table),
                "per_chip_batch": 128}, False

    import io
    from contextlib import redirect_stdout
    monkeypatch.setenv("MXTPU_BENCH_AB", "0")
    monkeypatch.setenv("MXTPU_BENCH_MODULE", "0")
    monkeypatch.setenv("MXTPU_BENCH_DP", "1")
    monkeypatch.setenv("MXTPU_BENCH_SERVE", "0")
    monkeypatch.setenv("MXTPU_BENCH_DECODE", "0")
    monkeypatch.setattr(bench, "_run_phase", fake_phase)
    monkeypatch.setattr(bench, "TOTAL_DEADLINE", 600.0)
    monkeypatch.setattr(bench, "SMOKE", False)
    monkeypatch.setattr(bench, "PROBE_TIMEOUT", 1.0)
    monkeypatch.setattr(bench, "PROBE_GAP", 0.0)
    monkeypatch.setattr(bench, "RAW_MIN", 0.5)
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench.supervise()
    assert rc == 0
    out = bench._last_json_line(buf.getvalue())
    assert out["dp"] == dp_table
    assert out["dp_per_chip_batch"] == 128
    assert out["value"] == 500.0


def test_dp_child_per_axis_partials_and_artifact(tmp_path, monkeypatch):
    """dp_child emits a partial line per axis size (a hang at a larger
    mesh salvages the smaller sizes), marks a silently-fallen-back fused
    leg by its stable reason CODE, and banks the MULTICHIP-schema
    artifact."""
    import io
    from contextlib import redirect_stdout
    from mxnet_tpu.module import FusedFallback

    class _Dev:
        platform = "cpu"
        device_kind = "cpu"

    calls = []

    def fake_throughput(dev, contexts=None, kvstore=None):
        calls.append((len(contexts), kvstore,
                      os.environ["MXNET_MODULE_FUSED_STEP"]))
        if len(contexts) == 2 and os.environ[
                "MXNET_MODULE_FUSED_STEP"] == "1":
            return 100.0, FusedFallback("monitor", "monitor installed")
        return 100.0 * len(contexts), None

    monkeypatch.setattr(bench, "_init_device", lambda jax: _Dev())
    monkeypatch.setattr(bench, "_module_fit_throughput", fake_throughput)
    # the oversized 999 must be SKIPPED, not abort the later valid sizes
    monkeypatch.setenv("MXTPU_BENCH_DP_AXES", "1,999,2")
    monkeypatch.setenv("MXTPU_ARTIFACT_DIR", str(tmp_path))
    # dp_child mutates the fused-step pin; monkeypatch restores it
    monkeypatch.setenv("MXNET_MODULE_FUSED_STEP", "1")
    buf = io.StringIO()
    with redirect_stdout(buf):
        bench.dp_child()
    lines = [json.loads(l) for l in buf.getvalue().splitlines()
             if l.strip().startswith("{")]
    partials = [l for l in lines if l.get("partial")]
    assert len(partials) == 2          # one banked line per axis size
    assert set(partials[0]["dp"]) == {"1"}
    final = lines[-1]
    assert set(final["dp"]) == {"1", "2"}
    assert final["dp"]["2"]["fused_fallback"] == "monitor"
    assert final["dp"]["1"]["fused_img_s"] == 100.0
    # at k=1 the 'device' kvstore resolves to None — the split leg must
    # be marked as the plain phase-split baseline, not a kvstore number
    assert final["dp"]["1"]["split_kvstore_active"] is False
    assert final["dp"]["2"]["split_kvstore_active"] is True
    # the A/B drove both legs through the same in-process kvstore
    assert all(kv == "device" for _, kv, _ in calls)
    with open(tmp_path / "multichip_dp_ab.json") as f:
        art = json.load(f)
    # the completed sweep reads as a clean round (per-size interim
    # writes carry ok=False/truncated=True so a killed run reads as
    # partial — that state must be gone after the final bank)
    assert art["ok"] is True and art["skipped"] is False
    assert "truncated" not in art
    assert art["dp"] == final["dp"]


def test_budget_args_dp_phase(monkeypatch):
    monkeypatch.setattr(bench, "DP_TIMEOUT", bench.DP_TIMEOUT)
    rest = bench._apply_budget_args(["--budget-s", "dp=120"])
    assert rest == [] and bench.DP_TIMEOUT == 120.0


def test_budget_args_serve_phase(monkeypatch):
    monkeypatch.setattr(bench, "SERVE_TIMEOUT", bench.SERVE_TIMEOUT)
    rest = bench._apply_budget_args(["--budget-s", "serve=90"])
    assert rest == [] and bench.SERVE_TIMEOUT == 90.0


def test_supervise_serve_phase_merges(monkeypatch):
    """With budget left, the serving sweep child runs and its
    throughput/latency table merges into the final line under
    "serving"."""
    sv = {"lane": "serving", "unbatched_req_s": 100.0,
          "burst_req_s": 900.0, "serve_speedup": 9.0,
          "burst_latency_ms": {"p50_ms": 4.0, "p95_ms": 9.0,
                               "p99_ms": 11.0},
          "offered_loads": {"0.80": {"achieved_req_s": 700.0}},
          "compiles_per_bucket": 1.0}

    def fake_phase(mode, timeout, env_extra=None):
        if mode == "--probe":
            return {"device": "x"}, False
        if mode == "--child":
            return {"value": 500.0, "unit": "img/s"}, False
        assert mode == "--serve-child", mode
        return dict(sv), False

    import io
    from contextlib import redirect_stdout
    monkeypatch.setenv("MXTPU_BENCH_AB", "0")
    monkeypatch.setenv("MXTPU_BENCH_MODULE", "0")
    monkeypatch.setenv("MXTPU_BENCH_DP", "0")
    monkeypatch.setenv("MXTPU_BENCH_SERVE", "1")
    monkeypatch.setenv("MXTPU_BENCH_DECODE", "0")
    monkeypatch.setattr(bench, "_run_phase", fake_phase)
    monkeypatch.setattr(bench, "TOTAL_DEADLINE", 600.0)
    monkeypatch.setattr(bench, "SMOKE", False)
    monkeypatch.setattr(bench, "PROBE_TIMEOUT", 1.0)
    monkeypatch.setattr(bench, "PROBE_GAP", 0.0)
    monkeypatch.setattr(bench, "RAW_MIN", 0.5)
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench.supervise()
    assert rc == 0
    out = bench._last_json_line(buf.getvalue())
    assert out["value"] == 500.0
    assert out["serving"]["serve_speedup"] == 9.0
    assert out["serving"]["burst_latency_ms"]["p95_ms"] == 9.0
    assert "lane" not in out["serving"]


def test_supervise_decode_phase_merges(monkeypatch):
    """With budget left, the continuous-batching decode child runs and
    its throughput/per-token-latency table merges into the final line
    under "decode"."""
    dc = {"lane": "decode", "static_tok_s": 7000.0,
          "continuous_tok_s": 20000.0, "decode_speedup": 2.86,
          "token_latency_ms": {"p50_ms": 0.21, "p95_ms": 0.26,
                               "p99_ms": 0.32},
          "jit_compiles_timed": 0, "kv_cache_bytes": 524288}

    def fake_phase(mode, timeout, env_extra=None):
        if mode == "--probe":
            return {"device": "x"}, False
        if mode == "--child":
            return {"value": 500.0, "unit": "img/s"}, False
        assert mode == "--decode-child", mode
        return dict(dc), False

    import io
    from contextlib import redirect_stdout
    monkeypatch.setenv("MXTPU_BENCH_AB", "0")
    monkeypatch.setenv("MXTPU_BENCH_MODULE", "0")
    monkeypatch.setenv("MXTPU_BENCH_DP", "0")
    monkeypatch.setenv("MXTPU_BENCH_SERVE", "0")
    monkeypatch.setenv("MXTPU_BENCH_DECODE", "1")
    monkeypatch.setattr(bench, "_run_phase", fake_phase)
    monkeypatch.setattr(bench, "TOTAL_DEADLINE", 600.0)
    monkeypatch.setattr(bench, "SMOKE", False)
    monkeypatch.setattr(bench, "PROBE_TIMEOUT", 1.0)
    monkeypatch.setattr(bench, "PROBE_GAP", 0.0)
    monkeypatch.setattr(bench, "RAW_MIN", 0.5)
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = bench.supervise()
    assert rc == 0
    out = bench._last_json_line(buf.getvalue())
    assert out["value"] == 500.0
    assert out["decode"]["decode_speedup"] == 2.86
    assert out["decode"]["token_latency_ms"]["p99_ms"] == 0.32
    assert out["decode"]["jit_compiles_timed"] == 0
    assert "lane" not in out["decode"]


def test_serve_child_smoke_sweep(monkeypatch):
    """serve_child end to end in smoke mode (tiny MLP on CPU): partial
    emission per phase, one compile per bucket, p95 in the artifact and
    the offered-load ladder populated."""
    import io
    from contextlib import redirect_stdout
    monkeypatch.setattr(bench, "SMOKE", True)

    class _Dev:
        device_kind = "cpu"
        platform = "cpu"

    def init(jax):
        jax.config.update("jax_platforms", "cpu")
        return jax.devices()[0]

    monkeypatch.setattr(bench, "_init_device", init)
    buf = io.StringIO()
    with redirect_stdout(buf):
        bench.serve_child()
    lines = [json.loads(l) for l in buf.getvalue().splitlines()
             if l.strip().startswith("{")]
    partials = [l for l in lines if l.get("partial")]
    # one partial per phase: unbatched, burst, 3 load points
    assert len(partials) >= 5
    out = lines[-1]
    assert out["lane"] == "serving"
    assert out["compiles_per_bucket"] == 1.0
    assert out["unbatched_req_s"] > 0 and out["burst_req_s"] > 0
    assert out["burst_latency_ms"]["p95_ms"] is not None
    assert set(out["offered_loads"]) == {"0.50", "0.80", "0.95"}
    for pt in out["offered_loads"].values():
        assert pt["achieved_req_s"] > 0
        assert pt["latency_ms"]["p95"] >= pt["latency_ms"]["p50"] >= 0
    # the serving telemetry rode into the artifact summary
    assert "serve_request" in out["telemetry"]["spans"]


def test_module_child_marks_silent_fallback(monkeypatch):
    """module_child must not record two phase-split numbers as a fused
    A/B: when the fused leg silently falls back, the emitted JSON
    carries the fallback reason."""
    import io
    from contextlib import redirect_stdout
    monkeypatch.setattr(bench, "_init_device", lambda jax: None)
    monkeypatch.setattr(bench, "_module_fit_throughput",
                        lambda dev: (42.0, "kvstore-mediated update"))
    buf = io.StringIO()
    with redirect_stdout(buf):
        bench.module_child()
    lines = [json.loads(l) for l in buf.getvalue().splitlines()]
    assert lines[-1]["module_fit_img_s"] == 42.0
    assert lines[-1]["module_fit_fused_fallback"] == \
        "kvstore-mediated update"
    # a clean fused leg carries no fallback marker
    monkeypatch.setattr(bench, "_module_fit_throughput",
                        lambda dev: (42.0, None))
    buf = io.StringIO()
    with redirect_stdout(buf):
        bench.module_child()
    lines = [json.loads(l) for l in buf.getvalue().splitlines()]
    assert "module_fit_fused_fallback" not in lines[-1]


def test_supervise_aborts_after_consecutive_dead_probes(monkeypatch):
    """ISSUE 6: r03-r05 burned 10+ probes rediscovering the same dead
    tunnel. After PROBE_FAIL_LIMIT consecutive failures the supervisor
    must stop probing IMMEDIATELY (despite budget remaining) and emit
    the diagnostic, with the cold-start seconds of every attempt
    recorded."""
    import time as _time

    def failing_probe(n):
        _time.sleep(0.05)
        return None, True

    monkeypatch.setattr(bench, "PROBE_FAIL_LIMIT", 3)
    rc, calls, out = _patched_supervise(
        monkeypatch, {"--probe": failing_probe}, deadline=600.0)
    assert rc == 1
    # the loop stopped at the limit, not at the (10-minute) deadline
    assert calls.count("--probe") == 3
    assert out["probe_aborted"] is True
    assert out["skipped"] is True
    assert len(out["probe_seconds"]) == 3
    assert all(s >= 0 for s in out["probe_seconds"])


def test_supervise_probe_fail_counter_resets_on_success(monkeypatch):
    """Two dead probes, a good one, then the raw child measures: the
    consecutive-failure counter resets on success so a flaky (but
    live) tunnel is NOT declared down, and the probe cold-start
    seconds ride in the successful JSON too."""
    meas = {"value": 55.0, "unit": "img/s"}
    monkeypatch.setenv("MXTPU_BENCH_MODULE", "0")
    monkeypatch.setattr(bench, "PROBE_FAIL_LIMIT", 3)
    rc, calls, out = _patched_supervise(
        monkeypatch,
        {"--probe": lambda n: ((None, True) if n <= 2
                               else ({"device": "x"}, False)),
         "--child": lambda n: (dict(meas), False)},
        deadline=600.0)
    assert rc == 0
    assert calls.count("--probe") == 3
    assert out["value"] == 55.0
    assert len(out["probe_seconds"]) == 3
