"""Unified runtime telemetry suite (ISSUE 3): counter registry, host-span
tracing, multi-subscriber dispatch registry, fused-fallback logging, the
merged host+device chrome trace, and the tier-1 <2% overhead guard."""
import json
import logging
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry


@pytest.fixture(autouse=True)
def _clean_registry():
    """Each test sees a fresh, enabled registry and leaves it that way
    (telemetry is process-global)."""
    telemetry.enable()
    telemetry.reset()
    yield
    telemetry.enable()
    telemetry.reset()


# ---------------------------------------------------------------------------
# Registry basics
# ---------------------------------------------------------------------------

def test_counters_and_reset():
    telemetry.counter_inc("a")
    telemetry.counter_inc("a", 4)
    telemetry.counter_inc("b")
    assert telemetry.counters() == {"a": 5, "b": 1}
    telemetry.reset()
    assert telemetry.counters() == {}


def test_span_records_histogram_and_percentiles():
    for _ in range(20):
        with telemetry.span("phase"):
            pass
    stats = telemetry.span_stats("phase")["phase"]
    assert stats["count"] == 20
    assert stats["p50_ms"] <= stats["p95_ms"] <= stats["p99_ms"] \
        <= stats["max_ms"]
    assert stats["total_ms"] >= 0
    snap = telemetry.snapshot()
    assert "phase" in snap["spans"] and snap["enabled"] is True


def test_disable_stops_recording():
    telemetry.disable()
    with telemetry.span("off"):
        pass
    telemetry.counter_inc("off", 3)
    telemetry.enable()
    assert telemetry.counters() == {}
    assert telemetry.span_stats("off") == {}


def test_span_ring_is_bounded():
    for i in range(telemetry.SPAN_RING_SIZE + 100):
        with telemetry.span("ring"):
            pass
    assert len(telemetry.chrome_events(since_trace_start=False)) \
        <= telemetry.SPAN_RING_SIZE + 16   # + metadata rows


# ---------------------------------------------------------------------------
# Multi-subscriber dispatch registry (+ legacy single-slot shim)
# ---------------------------------------------------------------------------

def test_dispatch_multi_subscriber_and_legacy_shim():
    import mxnet_tpu.executor as _ex
    seen_a, seen_b, legacy = [], [], []
    cb_a = telemetry.on_dispatch(seen_a.append)
    cb_b = telemetry.on_dispatch(seen_b.append)
    old = _ex.dispatch_hook
    _ex.dispatch_hook = legacy.append
    try:
        _ex.record_dispatch("k1")
    finally:
        _ex.dispatch_hook = old
        telemetry.remove_dispatch(cb_a)
        telemetry.remove_dispatch(cb_b)
    # every subscriber AND the legacy slot saw the dispatch — no
    # clobbering — and the counter registry recorded it too
    assert seen_a == ["k1"] and seen_b == ["k1"] and legacy == ["k1"]
    assert telemetry.dispatch_counts() == {"k1": 1}
    # removal is effective and idempotent
    _ex.record_dispatch("k2")
    assert seen_a == ["k1"]
    telemetry.remove_dispatch(cb_a)   # second remove: no error


def _mlp(hidden=32, classes=4):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _iter(n_batches, batch=32, d=16, classes=4):
    rs = np.random.RandomState(0)
    X = rs.uniform(-1, 1, (batch * n_batches, d)).astype(np.float32)
    Y = rs.randint(0, classes, batch * n_batches).astype(np.float32)
    return mx.io.NDArrayIter(X, Y, batch_size=batch)


def _fit(mod, it, metric, n_epoch=1, **kwargs):
    mod.fit(it, eval_metric=metric, num_epoch=n_epoch,
            initializer=mx.initializer.Xavier(), optimizer="sgd",
            optimizer_params={"learning_rate": 0.05}, **kwargs)


# ---------------------------------------------------------------------------
# Module integration: snapshot + fallback accounting
# ---------------------------------------------------------------------------

def test_module_fit_snapshot_fused():
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    metric = mx.metric.Accuracy()
    _fit(mod, _iter(6), metric)
    telemetry.reset()
    _fit(mod, _iter(6), metric)
    snap = mod.telemetry_snapshot()
    assert snap["fused_fallback_code"] is None
    c = snap["counters"]
    # ONE whole-step program per batch, no phase-split dispatches
    assert c.get("dispatch.train_step") == 6
    assert "dispatch.fwd_bwd" not in c
    # the second fit reuses the cached plan: no new train_step compile
    assert c.get("jit.compile.train_step", 0) == 0
    # step-span percentiles present and ordered
    st = snap["spans"]["step"]
    assert st["count"] == 6
    assert st["p50_ms"] <= st["p95_ms"] <= st["p99_ms"]
    for name in ("fit_batch", "feed", "io_next"):
        assert name in snap["spans"], name


def test_module_fit_fallback_counted_and_logged_once(caplog):
    os.environ["MXNET_MODULE_FUSED_STEP"] = "0"
    try:
        mod = mx.mod.Module(_mlp(), context=mx.cpu())
        metric = mx.metric.Accuracy()
        with caplog.at_level(logging.WARNING, logger="mxnet_tpu.module"):
            _fit(mod, _iter(5), metric)
    finally:
        os.environ.pop("MXNET_MODULE_FUSED_STEP", None)
    snap = mod.telemetry_snapshot()
    # every phase-split step counted under the STABLE code...
    assert snap["counters"].get("fused_fallback.env_pin") == 5
    assert snap["fused_fallback_code"] == "env_pin"
    # ...but logged ONCE per module, with the code in the message
    msgs = [r.message for r in caplog.records
            if "fused-step fallback" in r.message]
    assert len(msgs) == 1 and "code=env_pin" in msgs[0]
    # phase-split dispatch mix: fwd_bwd + opt_update + metric per batch
    c = snap["counters"]
    assert c.get("dispatch.fwd_bwd") == 5
    assert c.get("dispatch.opt_update") == 5


def test_host_sync_and_transfer_counters():
    a = mx.nd.ones((8, 8))
    telemetry.reset()
    a.asnumpy()
    a.wait_to_read()
    c = telemetry.counters()
    assert c.get("host_sync.blocking") == 2
    assert c.get("host_sync.asnumpy") == 1
    assert c.get("host_sync.wait_to_read") == 1
    assert c.get("transfer.d2h_bytes") == 8 * 8 * 4


# ---------------------------------------------------------------------------
# Merged host+device chrome trace (the acceptance artifact)
# ---------------------------------------------------------------------------

def test_fit_profiler_merged_chrome_trace(tmp_path):
    """A Module.fit run under profiler.set_state('run') must yield ONE
    chrome-trace JSON containing BOTH device ops and the host spans
    (feed/shard_put/step/metric_fetch) — the unified perfetto view."""
    fname = str(tmp_path / "merged_profile.json")
    mx.profiler.set_config(filename=fname)
    # two contexts: the dp mesh exercises the shard_put feed path
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(0), mx.cpu(1)])
    metric = mx.metric.Accuracy()
    _fit(mod, _iter(4), metric)          # bind+compile outside the trace
    mx.profiler.set_state("run")
    _fit(mod, _iter(4), metric)
    metric.get()                         # metric host sync inside window
    mx.profiler.set_state("stop")
    mx.profiler.dump()
    with open(fname) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    host = [e for e in events if e.get("cat") == "host"]
    device = [e for e in events
              if e.get("cat") != "host" and e.get("ph") == "X"]
    names = {e["name"] for e in host}
    assert {"feed", "shard_put", "step", "metric_fetch"} <= names, names
    assert device, "device ops missing from the merged trace"
    # the host track is labelled for perfetto
    assert any(e.get("ph") == "M" and e.get("name") == "process_name"
               and e["args"]["name"] == "mxnet_tpu host" for e in events)


# ---------------------------------------------------------------------------
# TelemetryLogger callback
# ---------------------------------------------------------------------------

def test_telemetry_logger_callback(caplog):
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    metric = mx.metric.Accuracy()
    with caplog.at_level(logging.INFO, logger="mxnet_tpu.telemetry"):
        _fit(mod, _iter(6), metric,
             batch_end_callback=mx.callback.TelemetryLogger(frequent=2))
    lines = [r.message for r in caplog.records
             if "dispatches/batch" in r.message]
    assert lines, "TelemetryLogger logged nothing"
    assert "jit compile/hit" in lines[-1]
    # steady-state window: one fused dispatch per batch
    assert "dispatches/batch=1.00" in lines[-1]


# ---------------------------------------------------------------------------
# Tier-1 overhead guard (<2% on the CPU smoke workload)
# ---------------------------------------------------------------------------

def test_telemetry_overhead_guard(tmp_path):
    """Telemetry-enabled Module.fit must add <2% overhead vs disabled
    on the CPU smoke workload. A naive wall-clock A/B cannot RESOLVE 2%
    here: share-throttled CI boxes burst-stall at sub-epoch granularity
    (measured adjacent-leg ratios swing 0.4x-2.2x; 50-batch windows
    still flip sign), so any direct timing assertion flakes regardless
    of interleaving. The guard instead bounds the measured telemetry
    WORK against the measured batch time: count the actual per-batch
    registry operations the fit loop performs (the registry reports its
    own op counts exactly — spans, counters, the ISSUE-4 paths (buffer-
    ledger tracks and program-card dispatch bumps) AND the ISSUE-10
    flight-recorder paths: causal-id spans — the fit loop stamps
    (epoch, nbatch) on every batch's spans now — discrete events, and
    the metrics sampler's ticks, which run DURING the counted epoch),
    microbenchmark the per-op costs (min over repeated tight loops —
    robust to throttle, which can only inflate them), and assert
    ops x cost < 2% of the batch-time floor. A lock storm or heavy
    span/ledger/card/sampler path fails this immediately; box noise
    cannot."""
    from mxnet_tpu import flight
    batch, nbatch = 512, 12
    rs = np.random.RandomState(0)
    X = rs.uniform(-1, 1, (batch * nbatch, 64)).astype(np.float32)
    Y = rs.randint(0, 8, batch * nbatch).astype(np.float32)
    mod = mx.mod.Module(_mlp(hidden=256, classes=8), context=mx.cpu())
    metric = mx.metric.Accuracy()

    def epoch():
        it = mx.io.NDArrayIter(X, Y, batch_size=batch)
        t0 = time.perf_counter()
        _fit(mod, it, metric)
        metric.get()
        float(np.asarray(
            mod._exec.arg_dict[mod._param_names[0]]._data).sum())
        return time.perf_counter() - t0

    epoch()  # warm: bind + compile outside every timed window
    # batch-time floor over a few epochs (min: throttle only inflates)
    batch_s = min(epoch() for _ in range(5)) / nbatch

    # exact per-batch telemetry op counts from the steady-state epoch —
    # with the flight-recorder sampler RUNNING, as the acceptance gate
    # demands (its ticks are counted and costed like every other op)
    telemetry.reset()
    flight.series_clear()
    sampler_interval_s = 0.02
    flight.sampler_start(sampler_interval_s * 1e3)
    try:
        epoch()
    finally:
        flight.sampler_stop()
    ticks = len(flight.series()) / nbatch
    flight.series_clear()
    spans = sum(telemetry.span_count(n)
                for n in telemetry.span_stats()) / nbatch
    counts = telemetry.counters()
    counter_ops = sum(v for k, v in counts.items()
                      if k.endswith("_count") or k.startswith(
                          ("dispatch.", "host_sync.", "jit."))) / nbatch
    event_ops = len(telemetry.events()) / nbatch
    # ISSUE-4 instrumentation: buffer-ledger tracks (NDArray wraps,
    # shard_put) and program-card dispatch bumps the epoch performed
    ledger_ops = sum(st.get("tracked_total", 0)
                     for st in telemetry.ledger().values()) / nbatch
    card_ops = sum(c.get("dispatches", 0)
                   for c in telemetry.programs().values()) / nbatch
    # ISSUE-18 instrumentation: gate crossings the epoch performed
    # (zero in this single-process workload — the dist fit loop pays
    # one per batch, priced below at the measured per-crossing cost)
    gate_ops = sum(v for k, v in counts.items()
                   if k.startswith("heartbeat.gate_crossings.")) / nbatch

    def op_cost(fn, iters=20000, reps=5):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter_ns()
            for _ in range(iters):
                fn()
            best = min(best, (time.perf_counter_ns() - t0) / iters)
        return best / 1e9

    def one_span():
        # measured INSIDE a causal scope: every fit-loop span now pays
        # the ambient-ids capture, so the probe must too
        with telemetry.span("_guard_probe"):
            pass

    class _Obj:
        pass

    def one_track():
        # full lifecycle: track + immediate finalize on refcount drop
        telemetry.ledger_track(_Obj(), "cpu(0)", 128,
                               shape=(32,), dtype="float32")

    _card = {"id": "_guard_card"}
    with telemetry.causal(epoch=0, nbatch=0):
        span_s = op_cost(one_span)
    counter_s = op_cost(lambda: telemetry.counter_inc("_guard_probe"))
    event_s = op_cost(lambda: telemetry.record_event("_guard_probe"))
    track_s = op_cost(one_track, iters=5000)
    card_s = op_cost(lambda: telemetry.program_dispatch(_card))
    tick_s = op_cost(lambda: flight._build_sample({},
                                                  sampler_interval_s),
                     iters=500)
    # per-crossing gate attribution (ISSUE 18): _record_crossing on a
    # REAL two-member gate directory — the arrival-file scan, the
    # span/counter records and the streak machine, exactly what every
    # dist-step crossing pays after its barrier completes
    from mxnet_tpu import heartbeat
    groot = str(tmp_path)
    gate = heartbeat.CollectiveGate(0, (0, 1), root=groot, poll=0.05)
    gate._publish(1, self_ms=5.0)
    with open(gate._member_path(1), "w") as f:
        f.write("1 %.6f 5.0" % time.time())
    crossing_s = op_cost(
        lambda: gate._record_crossing(1, time.perf_counter_ns()),
        iters=2000)
    overhead_s = spans * span_s + counter_ops * counter_s \
        + event_ops * event_s + ledger_ops * track_s \
        + card_ops * card_s + ticks * tick_s + gate_ops * crossing_s
    telemetry.reset()
    # the dist fit loop pays ONE crossing per batch, and every crossing
    # already waits at least one gate-poll interval in steady state —
    # attribution must stay under 2% of that per-crossing floor, so it
    # can never add 2% to a dist step's wall time
    assert crossing_s < 0.02 * gate.poll, \
        "gate attribution %.1fus/crossing exceeds 2%% of the %.0fms " \
        "gate poll quantum" % (crossing_s * 1e6, gate.poll * 1e3)
    frac = overhead_s / batch_s
    assert frac < 0.02, \
        "telemetry work %.1fus/batch (%.1f spans x %.2fus + %.1f counter " \
        "ops x %.2fus + %.1f events x %.2fus + %.1f ledger tracks x " \
        "%.2fus + %.1f card bumps x %.2fus + %.2f sampler ticks x " \
        "%.1fus) is %.2f%% of the %.0fus batch floor — exceeds the 2%% " \
        "guard" % (overhead_s * 1e6, spans, span_s * 1e6, counter_ops,
                   counter_s * 1e6, event_ops, event_s * 1e6,
                   ledger_ops, track_s * 1e6, card_ops, card_s * 1e6,
                   ticks, tick_s * 1e6, frac * 100, batch_s * 1e6)
