"""Compliant user module: declared sites/codes/counters only, dynamic
tails covered by a declared wildcard."""


def work(faults, telemetry, FusedFallback, cause):
    faults.fire("dispatch")
    faults.fire("d2h")
    FusedFallback("monitor", "monitor installed")
    telemetry.counter_inc("serving.requests")
    telemetry.counter_inc("serving.shed.%s" % cause)
    telemetry.counter_inc("serving.shed.admission")
