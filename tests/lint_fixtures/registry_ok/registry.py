"""Compliant miniature registries: every declaration used, every use
declared."""

SITES = ("dispatch", "d2h")

FUSED_FALLBACK_CODES = {
    "monitor": "per-op monitor taps need the phase-split programs",
}

COUNTERS = (
    "serving.requests",
    "serving.shed.*",
)
