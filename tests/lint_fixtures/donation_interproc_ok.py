"""Compliant twin: callers of donating wrappers rebind at the call
(the idiomatic fix), and a dict-lookup callable stays BOUNDED — no
marker means no donation assumption, no finding. Zero findings."""
import jax


def fused_step(fn, w, s, batch):
    step = jax.jit(fn, donate_argnums=(0, 1))
    w, s = step(w, s, batch)
    return w, s


def train(fn, weights, states, batches):
    for b in batches:
        weights, states = fused_step(fn, weights, states, b)
    return weights, states


def apply_plan(plan, weights, batch):
    out = plan["fn"](weights, batch)    # dynamic: bounded without a marker
    return out, weights
