"""Compliant twin of thread_race_violation.py: the coalescer state is
locked on BOTH sides (and annotated, so lock-discipline owns it), and
the finalizer uses the PR-4 lock-free pending pattern — a GIL-atomic
deque append with a justified disable, drained under the lock (a
finalizer taking the lock would deadlock under cyclic GC)."""
import collections
import threading
import weakref

_lock = threading.Lock()
_pending_gc = collections.deque()


class Coalescer:
    def __init__(self):
        self._lock = threading.Lock()
        self._depth = 0     # guarded by: self._lock

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            self._schedule(self._flush)

    def _schedule(self, cb):
        cb()

    def _flush(self):
        with self._lock:
            self._depth += 1

    def depth(self):
        with self._lock:
            return self._depth


def track(obj):
    weakref.finalize(obj, _note_gc)


def _note_gc():
    _pending_gc.append(1)   # mxlint: disable=thread-race -- GIL-atomic deque append from the finalizer; the reader drains under _lock (the PR 4 lock-free finalizer pattern)


def drain():
    with _lock:
        n = len(_pending_gc)
        for _ in range(n):
            _pending_gc.popleft()
        return n
