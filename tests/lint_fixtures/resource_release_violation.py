"""Seeded resource-release violations (mxlife family b): a bare lock
acquire with no finally release, an entered span that never exits,
an exit a may-raise callee can jump over, a temp file renamed with
no unlink-on-failure, and non-daemon threads leaked on the exception
path. Parsed, never imported."""
import os
import threading

from mxnet_tpu import telemetry

_lock = threading.Lock()


def must_raise(x):
    if x < 0:
        raise ValueError(x)
    return x


def bump(stats):
    _lock.acquire()
    stats["n"] += 1
    _lock.release()


def measure(fn, x):
    s = telemetry.span("work").__enter__()
    return fn(x)


def measure2(x):
    s = telemetry.span("work").__enter__()
    y = must_raise(x)
    s.__exit__(None, None, None)
    return y


def write_state(path, payload):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(payload)
    os.replace(tmp, path)


def fire_and_forget(work):
    t = threading.Thread(target=work)
    t.start()


def run_with_risk(work, x):
    t = threading.Thread(target=work)
    t.start()
    must_raise(x)
    t.join()
