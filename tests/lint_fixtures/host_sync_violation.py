"""Seeded host-sync violations: all three blocking forms inside a
function marked ``# mxlint: hot`` (one DECORATED). Four findings expected."""
import numpy as np


def fit_batch_loop(batches, program):   # mxlint: hot
    for batch in batches:
        out = program(batch)
        host = out.asnumpy()            # VIOLATION 1: blocking fetch
        out.wait_to_read()              # VIOLATION 2: blocking sync
        arr = np.asarray(out)           # VIOLATION 3: device->host
        yield host, arr


# mxlint: hot
@property
def hot_decorated(self):
    return self._out.asnumpy()          # VIOLATION 4: marker above decorator
