"""Seeded collective-discipline violations (mxsync ISSUE 13): an
UNGATED _host_allgather reachable from a public entry, a channel
MISMATCH (step gate guarding a kv exchange), and a rank-divergent
branch whose arms reach different collective sequences (one rank
skips the psum its peers block in). See test_mxlint.py."""
import numpy as np
from jax import lax


class CollectiveGate:
    def __init__(self, rank, members, channel="step"):
        self.rank = rank
        self.members = members
        self.channel = channel

    def arrive_and_wait(self):
        return 0


class KV:
    def __init__(self, rank, members):
        self.rank = rank
        self.members = members
        self._gate = CollectiveGate(rank, members, channel="step")

    def _host_allgather(self, arr):
        return arr[None]

    def push(self, grads):
        return self._host_allgather(grads)

    def barrier(self):
        self._gate.arrive_and_wait()
        self._host_allgather(np.zeros((1,), np.int32))

    def fit_step(self, rank, x):
        if rank == 0:
            return x
        return lax.psum(x, "dp")
