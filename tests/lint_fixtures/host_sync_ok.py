"""Compliant twin: the same calls OUTSIDE a hot function are the
designated blocking path (a resolver pool, an epoch boundary); inside a
hot function, ``np.asarray`` over a host literal is host work; and a
legitimate hot-path marshalling site carries a justified disable.
Zero findings expected."""
import numpy as np


def resolver(outs):
    # not marked hot: this IS the designated blocking d2h path
    return [np.asarray(o) for o in outs]


def fit_batch_loop(batches, program, scale):   # mxlint: hot
    lrs = np.asarray([scale * 2], np.float32)    # host literal: exempt
    for batch in batches:
        host = np.asarray(batch.labels)   # mxlint: disable=host-sync -- labels arrive as host lists from the iterator, not device values
        yield program(batch, lrs), host
