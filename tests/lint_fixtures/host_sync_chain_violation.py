"""Seeded transitive host-sync violations: a hot loop reaching
blocking fetches through a 3-deep call chain and through a mutually
recursive (SCC) pair. The dynamic call through ``cb`` is NOT traversed
(bounded). Two findings expected, both anchored at the SINK lines."""


def hot_loop(batches, program, cb):   # mxlint: hot
    for b in batches:
        out = program(b)
        log_metrics(out)
        drain(out, 0)
        cb(out)                 # dynamic: bounded, never traversed
    return out


def log_metrics(out):
    summarize(out)


def summarize(out):
    return out.asnumpy()        # VIOLATION 1 (sink): 3-deep chain


def drain(out, depth):
    if depth > 3:
        return fetch(out, depth)
    return drain(out, depth + 1)


def fetch(out, depth):
    out.wait_to_read()          # VIOLATION 2 (sink): through the SCC
    return drain(out, depth + 1)
