"""Seeded interprocedural donation violations, NO ``# mxlint:
donates`` markers anywhere: a wrapper that passes its params on at
donated positions (callers inherit the donation), and a factory that
RETURNS a donating program (calls through the bound name donate).
Four findings expected."""
import jax


def fused_step(fn, w, s, batch):
    step = jax.jit(fn, donate_argnums=(0, 1))
    return step(w, s, batch)


def train(fn, weights, states, batches):
    for b in batches:
        out = fused_step(fn, weights, states, b)    # VIOLATIONS 1+2: loop never rebinds either donated arg
    return out


def train_once(fn, weights, states, batch):
    out = fused_step(fn, weights, states, batch)
    norm = sum(weights.values())        # VIOLATION 3: use after donation
    return out, norm


def _update(w):
    return w


def make_updater():
    return jax.jit(_update, donate_argnums=(0,))


def apply_update(weights):
    upd = make_updater()
    upd(weights)
    return weights                      # VIOLATION 4: dead after donation
