"""Compliant twin: the hot loop stays async. The blocking resolver is
handed to the pool as a VALUE (a ref edge — it blocks on the pool's
thread, legally, so it is not traversed), and the epoch-boundary fetch
is not reachable from the hot function at all. Zero findings."""


def hot_loop(batches, program, pool):   # mxlint: hot
    outs = []
    for b in batches:
        outs.append(program(b))
        pool.submit(resolve_one, outs[-1])
    return outs


def resolve_one(out):
    return out.asnumpy()        # legal: runs on the resolver thread


def epoch_end(outs):
    return [o.asnumpy() for o in outs]   # legal: epoch boundary
