"""Compliant twin: every guarded access under the lock — including
through a ``threading.Condition`` ALIAS of it — a ``_locked``-suffix
helper that documents caller-holds-the-lock, an ``__init__``
constructor, and the lock-free finalizer pattern (pending deque drained
under the lock). Zero findings expected."""
import collections
import threading
import weakref

_lock = threading.Lock()
_registry = {}                      # guarded by: _lock
_pending = collections.deque()      # lock-free landing zone (unguarded)


def lookup(key):
    with _lock:
        _drain_locked()
        return _registry.get(key)


def _drain_locked():
    # caller holds _lock (the suffix is the lint-checked contract)
    while _pending:
        _registry.pop(_pending.popleft(), None)


def _release(token):
    _pending.append(token)          # GIL-atomic: NO lock in a finalizer


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self._space = threading.Condition(self._lock)
        self._stats = {}            # guarded by: self._lock

    def bump(self, key):
        with self._space:           # Condition over the SAME lock
            self._stats[key] = self._stats.get(key, 0) + 1

    def snapshot(self):
        with self._lock:
            return dict(self._stats)

    def track(self, obj, token):
        weakref.finalize(obj, _release, token)


class Deferred:
    def __init__(self, pool):
        self._lock = threading.Lock()
        self._jobs = []             # guarded by: self._lock
        self._pool = pool

    def kick(self):
        with self._lock:
            def cb():
                with self._lock:    # re-acquired where the body RUNS
                    self._jobs.append(1)
            self._pool.submit(cb)
