"""Seeded lock-discipline violations: an unlocked read and write of a
guarded attribute, an unlocked guarded-global read, the PR 4 deadlock
class — a ``weakref.finalize`` callback that takes a lock — and a
deferred callback whose body, defined under ``with lock:`` (or inside
``__init__``), runs later without it. Six findings expected."""
import threading
import weakref

_lock = threading.Lock()
_registry = {}                      # guarded by: _lock


def lookup(key):
    return _registry.get(key)       # VIOLATION 1: unlocked global read


def _release(token):
    with _lock:                     # VIOLATION 4: lock in finalizer
        _registry.pop(token, None)


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self._stats = {}            # guarded by: self._lock

    def bump(self, key):
        self._stats[key] = self._stats.get(key, 0) + 1   # VIOLATIONS 2+3: unlocked write (and read)

    def track(self, obj, token):
        weakref.finalize(obj, _release, token)


class Deferred:
    def __init__(self, pool):
        self._lock = threading.Lock()
        self._jobs = []             # guarded by: self._lock
        self._pool = pool

    def kick(self):
        with self._lock:
            def cb():
                self._jobs.append(1)   # VIOLATION 5: deferred body runs unlocked
            self._pool.submit(cb)


class InitCallback:
    def __init__(self, pool):
        self._lock = threading.Lock()
        self._stats = {}                # guarded by: self._lock

        def on_done(kind):
            self._stats[kind] = 1       # VIOLATION 6: runs after __init__, unlocked
        pool.submit(on_done)
