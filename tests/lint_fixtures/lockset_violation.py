"""Seeded lockset violations: unannotated shared attributes written
under ``self._lock`` on some paths — including through a private
helper whose ENTRY lockset is inferred from its call sites — and
accessed lock-free on others. Two findings expected, at the lock-free
access lines, each proposing the ``# guarded by:`` annotation."""
import threading


class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self._counts = {}
        self._total = 0

    def add(self, key):
        with self._lock:
            self._bump(key)

    def add_many(self, keys):
        with self._lock:
            for k in keys:
                self._bump(k)

    def _bump(self, key):
        # entry lockset {self._lock}: every call site holds it
        self._counts[key] = self._counts.get(key, 0) + 1
        self._total += 1

    def peek(self, key):
        return self._counts.get(key, 0)     # VIOLATION 1: lock-free read

    def grand_total(self):
        return self._total                  # VIOLATION 2: lock-free read
