"""Seeded future-lifecycle violations (mxlife family a): a strand
through a may-raise callee's exception edge, a strand on a bare
return path, a double resolve, and a terminal resolver that skips
the request's entered spans. Parsed, never imported."""
from concurrent.futures import Future

from mxnet_tpu import telemetry


class Request:
    def __init__(self, rows):
        self.rows = rows
        self.future = Future()
        self.span = telemetry.span("serve_request").__enter__()


def risky(batch):
    if not batch:
        raise ValueError("empty batch")
    return len(batch)


def worker(q, out):
    req = q.get()
    n = risky(out)
    req.span.__exit__(None, None, None)
    req.future.set_result(n)
    req.future.set_result(n)


def maybe_resolve(q):
    req = q.get()
    if req.rows:
        req.future.set_result(req.rows)
    return None


def fail_all(reqs, exc):
    for r in reqs:
        if not r.future.done():
            r.future.set_exception(exc)


def shed(req, exc):
    if req.future.done():
        return
    req.span.__exit__(None, None, None)
    req.future.set_exception(exc)
