"""Compliant twin: dispatches report through record_dispatch, and
INSTALLING the legacy shim (an assignment, the documented back-compat
monkeypatch) is not a call."""
from mxnet_tpu import executor, telemetry


def report(kind):
    executor.record_dispatch(kind)          # the one entry point


def install(cb):
    executor.dispatch_hook = cb             # assignment: legal shim
    telemetry.on_dispatch(cb)               # preferred registry
