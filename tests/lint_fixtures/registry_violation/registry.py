"""Miniature registry module for the cross-file pass: one entry in
each registry is declared but never used by the sibling user module
(three unused-declaration findings anchor HERE)."""

SITES = ("dispatch", "d2h", "kv_push")        # kv_push: never fired

FUSED_FALLBACK_CODES = {
    "monitor": "per-op monitor taps need the phase-split programs",
    "group2ctx": "declared but never constructed",
}

COUNTERS = (
    "serving.requests",
    "faults.injected.*",                      # never bumped anywhere
)
