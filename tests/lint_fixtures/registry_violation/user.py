"""User module with one undeclared use per registry kind (plus an
uncovered dynamic counter prefix). Four findings anchor here."""


def work(faults, telemetry, FusedFallback, cause):
    faults.fire("dispatch")                       # declared: ok
    faults.fire("d2h")                            # declared: ok
    faults.fire("d2h_typo")                       # VIOLATION: not in SITES
    FusedFallback("monitor", "monitor installed")     # declared: ok
    FusedFallback("bad_code", "made-up reason")   # VIOLATION: unknown code
    telemetry.counter_inc("serving.requests")     # declared: ok
    telemetry.counter_inc("serving.requets")      # VIOLATION: typo
    telemetry.counter_inc("serving.shed.%s" % cause)   # VIOLATION: no '.*'
