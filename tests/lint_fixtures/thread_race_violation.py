"""Seeded thread-race violations (mxsync ISSUE 13): a write under a
thread root reached THROUGH A REF EDGE (a method the thread loop hands
onward as a callback value) racing a main-thread read, and a
weakref.finalize callback (finalizer thread root) writing a module
global the main thread reads. See test_mxlint.py."""
import threading
import weakref

_last_gc = None     # written by the finalizer, read from main


class Coalescer:
    def __init__(self):
        self._lock = threading.Lock()
        self._batches = []
        self._depth = 0

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            # _flush ESCAPES AS A VALUE: the race rule must carry the
            # thread root across this ref edge
            self._schedule(self._flush)

    def _schedule(self, cb):
        cb()

    def _flush(self):
        self._depth = len(self._batches)

    def depth(self):
        return self._depth


def track(obj):
    weakref.finalize(obj, _on_gc)


def _on_gc():
    global _last_gc
    _last_gc = 1


def report():
    return _last_gc
