"""Compliant twin: programs go through the instrumented wrapper, and
names that merely LOOK like jit (a local helper, another module's
attribute) do not fire."""
import functools
import jax.numpy as jnp

from mxnet_tpu.executor import _InstrumentedProgram


def compiled(fn):
    # the sanctioned route: wrapper owns the one real jax.jit site
    return _InstrumentedProgram("fixture", fn)


def lookalikes(module, fn):
    jit = module.build_jit                  # a local name, not jax.jit
    out = jit(fn)                           # fine: not import-rooted
    return out, jnp.asarray([1.0])          # jnp use is not a jit site


def curried(fn, n):
    # partial over a NON-jit callable is not a compile site
    run = functools.partial(fn, n)
    return run()
