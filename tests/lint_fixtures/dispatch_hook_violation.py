"""Seeded dispatch-hook violations: a raw CALL of the legacy
single-slot hook outside executor.py silently clobbers every other
subscriber. Two findings expected."""
from mxnet_tpu import executor


def report(kind):
    executor.dispatch_hook(kind)            # VIOLATION 1: attr call


def report_local(dispatch_hook, kind):
    dispatch_hook(kind)                     # VIOLATION 2: name call
