"""Compliant twin of collective_violation.py: every host exchange is
dominated by a matching-channel gate crossing (lexically, or at ENTRY
through the private-helper meet), the marked broadcast primitive is
gated at its call site, and the rank-conditional arm calls no
collective — both arms reach the same sequence, so nothing diverges."""
import numpy as np
from jax import lax


class CollectiveGate:
    def __init__(self, rank, members, channel="step"):
        self.rank = rank
        self.members = members
        self.channel = channel

    def arrive_and_wait(self):
        return 0


def broadcast_from_zero(tree):   # mxsync: collective channel=kv
    return tree


class KV:
    def __init__(self, rank, members):
        self.rank = rank
        self.members = members
        self._gate = None

    def _collective_gate(self):
        if self._gate is None:
            self._gate = CollectiveGate(self.rank, self.members,
                                        channel="kv")
        return self._gate

    def _host_allgather(self, arr):
        return arr[None]

    def push(self, grads):
        self._collective_gate().arrive_and_wait()
        self._check(grads)
        return self._host_allgather(grads)

    def _check(self, grads):
        # entry-gated: every call site crossed the kv gate first
        self._host_allgather(np.zeros((1,), np.int32))

    def seed(self, tree):
        self._collective_gate().arrive_and_wait()
        return broadcast_from_zero(tree)

    def fit_step(self, rank, x):
        y = lax.psum(x, "dp")
        if rank == 0:
            self._log(y)
        return y

    def _log(self, y):
        return y
