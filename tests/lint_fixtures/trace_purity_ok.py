"""Compliant twin: the trace cone stays pure — telemetry and clock
reads live OUTSIDE the traced functions (at build time and around the
program call), randomness enters as an explicit key argument, and the
impure helper is only reachable from untraced code. Zero findings."""
import time

import jax

from mxnet_tpu import telemetry


def build(graph):
    def step(args, key):
        noise = jax.random.uniform(key)     # explicit key: pure
        return scale(args, noise)
    telemetry.counter_inc("fixture.builds")  # legal: build time, untraced
    return _InstrumentedProgram("step", step)       # noqa: F821


def scale(args, k):
    return [a * k for a in args]


def run_eager(prog, args, key):
    t0 = time.time()                        # legal: untraced caller
    out = prog(args, key)
    telemetry.counter_inc("fixture.steps")  # legal: after the dispatch
    return out, time.time() - t0
