"""Compliant twin of torn_state_violation.py: the restore runs in a
finally (so the raise path restores too), the risky call is guarded
by a try, the initialize-to-constant-then-publish idiom keeps its
chosen reset value on a raise, and a lone mutation with no restore
pairs with nothing. Parsed, never imported."""


def boom(x):
    if x:
        raise RuntimeError("boom")
    return x


class Tracker:
    def __init__(self):
        self._depth = 0
        self._busy = False
        self._bytes = 0
        self._count = 0

    def step(self, x):
        self._depth += 1
        try:
            boom(x)
        finally:
            self._depth -= 1

    def flagged(self, x):
        self._busy = True
        try:
            boom(x)
        except RuntimeError:
            pass
        self._busy = False

    def publish(self, items):
        # initialize-to-constant then publish-a-computed-value: a
        # raise leaves the chosen reset value, not a torn one
        self._bytes = 0
        boom(len(items))
        self._bytes = sum(items)

    def tally(self, x):
        self._count += 1
        return boom(x)
