"""Compliant twin of future_lifecycle_violation.py: the exception
path resolves in the handler, a sentinel-checked dequeue is not a
request, transfer to a resolving callee discharges, the done-guard
makes late resolution idempotent, and every terminal resolver closes
the entered spans. Parsed, never imported."""
from concurrent.futures import Future

from mxnet_tpu import telemetry

_STOP = object()


class Request:
    def __init__(self, rows):
        self.rows = rows
        self.future = Future()
        self.span = telemetry.span("serve_request").__enter__()


def risky(batch):
    if not batch:
        raise ValueError("empty batch")
    return len(batch)


def worker(q, out):
    req = q.get()
    try:
        n = risky(out)
    except Exception as e:
        req.span.__exit__(None, None, None)
        req.future.set_exception(e)
        return
    req.span.__exit__(None, None, None)
    req.future.set_result(n)


def drain(q, out):
    item = q.get()
    if item is _STOP:
        return
    out.append(item)


def launch(batch):
    live = []
    for r in batch:
        if r.rows:
            shed(r, ValueError("stale"))
        else:
            live.append(r)
    return live


def shed(req, exc):
    if req.future.done():
        return
    req.span.__exit__(None, None, None)
    req.future.set_exception(exc)
