"""Seeded trace-purity violations: side effects inside the trace cone
of an ``_InstrumentedProgram`` build and a ``@jax.jit`` kernel — one
reached through a 3-deep call chain, one through a local-instance
method call. Five findings expected, anchored at the impure lines."""
import random
import time

import jax

from mxnet_tpu import telemetry

_STEP_COUNT = {}


def build(graph):
    def step(args):
        return level1(graph, args)
    return _InstrumentedProgram("step", step)       # noqa: F821


def level1(graph, args):
    return level2(graph, args)


def level2(graph, args):
    telemetry.counter_inc("fixture.step")   # VIOLATION 1: telemetry, 2 deep
    return level3(args)


def level3(args):
    h = Holder()
    h.bump(args)
    _STEP_COUNT["n"] = len(args)            # VIOLATION 2: global, 3 deep
    return args


class Holder:
    def __init__(self):
        self.count = 0

    def bump(self, x):
        self.count += 1                     # VIOLATION 3: self mutation
        return x


@jax.jit
def kernel(x):
    stamp = time.time()                     # VIOLATION 4: wall clock
    noise = random.random()                 # VIOLATION 5: global RNG
    return x * noise + stamp
