"""Compliant twin: the idiomatic shapes — rebind at the donating call
(including through an alias, including self-attributes), rebind before
the next use, and non-donated positions stay freely reusable.
Zero findings expected."""
import jax


def train(loss_fn, params, state, batch):
    step = jax.jit(loss_fn, donate_argnums=(0, 1))
    run = step                            # alias still tracked
    params, state = run(params, state, batch)   # rebind AT the call
    return params, state, batch           # batch (arg 2) not donated


def train_marked(plan, params, batch):
    out, params = plan["fn"](params, batch)   # mxlint: donates 0
    norm = sum(v.sum() for v in params.values())   # fresh binding: fine
    return out, norm


def warmup(fn, weights, batches):
    run = jax.jit(fn, donate_argnums=(0,))
    for b in batches:
        weights, loss = run(weights, b)   # loop rebinds each iteration
    return weights, loss


class Trainer:
    def __init__(self, fn):
        self._step = jax.jit(fn, donate_argnums=(0,))
        self.params = {}

    def step(self, batch):
        self.params, loss = self._step(self.params, batch)
        return loss


def retry(fn, params, batch):
    run = jax.jit(fn, donate_argnums=(0,))
    try:
        out, params = run(params, batch)
    except RuntimeError:
        out, params = run(params, batch)   # handler rebinds too
    return out, params
