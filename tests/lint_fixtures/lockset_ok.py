"""Compliant twin: annotated attrs belong to lock-discipline (not
re-flagged here), consistently-locked attrs are clean (a
``threading.Condition`` alias counts as its lock), and init-once
read-only config never trips the write requirement. Zero findings."""
import threading


class Stats:
    def __init__(self, limit):
        self._lock = threading.Lock()
        self._space = threading.Condition(self._lock)
        self._counts = {}       # guarded by: self._lock
        self._total = 0
        self.limit = limit      # init-once config, read-only after init

    def add(self, key):
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + 1
            self._total += 1

    def wait_add(self, key):
        with self._space:       # Condition over self._lock: counts
            self._total += 1

    def total(self):
        with self._lock:
            return self._total

    def room_left(self):
        return self.limit       # read-only config: no write, no race
