"""Seeded jit-site violations: plain call, the ALIASED import form the
old grep lint (`grep "jax\\.jit("`) walked straight past, pjit, pmap,
decorator and functools.partial-wrap forms. Six findings expected."""
import functools
import jax
from jax import jit as J                     # alias the grep never saw
from jax.experimental.pjit import pjit as P


def plain(fn):
    return jax.jit(fn)                       # VIOLATION 1: direct call


def aliased(fn):
    return J(fn)                             # VIOLATION 2: aliased jit


def sharded(fn):
    return P(fn)                             # VIOLATION 3: aliased pjit


def mapped(fn):
    return jax.pmap(fn)                      # VIOLATION 4: pmap


@jax.jit                                     # VIOLATION 5: decorator
def decorated(x):
    return x


@functools.partial(jax.jit, static_argnums=(1,))   # VIOLATION 6: partial wrap
def partial_decorated(x, n):
    return x * n
