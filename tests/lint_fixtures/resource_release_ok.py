"""Compliant twin of resource_release_violation.py: with-statement
locks (and acquire with a finally release), finally-guarded span
exits, unlink-on-failure for the temp+rename protocol, daemon
threads, finally-guarded joins, and an escape to an owner. Parsed,
never imported."""
import os
import threading

from mxnet_tpu import telemetry

_lock = threading.Lock()


def must_raise(x):
    if x < 0:
        raise ValueError(x)
    return x


def bump(stats):
    with _lock:
        stats["n"] += 1


def bump_manual(stats):
    _lock.acquire()
    try:
        stats["n"] += 1
    finally:
        _lock.release()


def measure2(x):
    s = telemetry.span("work").__enter__()
    try:
        return must_raise(x)
    finally:
        s.__exit__(None, None, None)


def handoff():
    # ownership escapes to the caller, who pairs the exit
    return telemetry.span("work").__enter__()


def write_state(path, payload):
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _unlink_quiet(path):
    try:
        os.unlink(path)
    except OSError:
        pass


def write_state_helper(path, payload):
    # cleanup through an extracted in-scan helper counts too
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, path)
    except OSError:
        _unlink_quiet(tmp)
        raise


def fire_daemon(work):
    t = threading.Thread(target=work, daemon=True)
    t.start()


def run_with_risk(work, x):
    t = threading.Thread(target=work)
    t.start()
    try:
        must_raise(x)
    finally:
        t.join()


class Owner:
    def __init__(self, work):
        self._thread = None
        self._work = work

    def start(self, work):
        t = threading.Thread(target=work)
        self._thread = t
        t.start()
