"""Seeded donation-safety violations: a name reused after riding a
donated position (locally-inferred donate_argnums AND the explicit
``# mxlint: donates`` marker for opaque callees), and a donating call
in a loop that never rebinds, and a use after an except-handler
donation (handler bodies are part of the linear order). Four findings
expected."""
import jax


def train(loss_fn, params, state, batch):
    step = jax.jit(loss_fn, donate_argnums=(0, 1))
    new_params, new_state = step(params, state, batch)
    print(params.keys())                 # VIOLATION 1: use after donation
    return new_params, new_state


def train_marked(plan, params, batch):
    out = plan["fn"](params, batch)      # mxlint: donates 0
    norm = sum(v.sum() for v in params.values())   # VIOLATION 2
    return out, norm


def warmup(fn, weights, batches):
    run = jax.jit(fn, donate_argnums=(0,))
    for b in batches:
        loss = run(weights, b)           # VIOLATION 3: loop, no rebind
    return loss


def retry(fn, params, batch):
    run = jax.jit(fn, donate_argnums=(0,))
    try:
        out, params = run(params, batch)
    except RuntimeError:
        out = run(params, batch)     # donates params again, no rebind
    return out, params               # VIOLATION 4: dead after except path
