"""Seeded torn-state-on-raise violations (mxlife family c): a depth
counter bumped and only un-bumped on the fall-through path, and a
busy flag set and only cleared on the fall-through path, with an
unguarded may-raise callee in between. Parsed, never imported."""


def boom(x):
    if x:
        raise RuntimeError("boom")
    return x


class Tracker:
    def __init__(self):
        self._depth = 0
        self._busy = False

    def step(self, x):
        self._depth += 1
        boom(x)
        self._depth -= 1

    def flagged(self, x):
        self._busy = True
        boom(x)
        self._busy = False
