"""Edge-case operator semantics ported (behaviourally) from the
reference unittest suite (tests/python/unittest/test_operator.py) —
the cases that most often diverge between backends: indexing modes,
ordering ops, masking, transpose combos, padding modes."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import check_numeric_gradient


def _nd(x):
    return mx.nd.array(np.asarray(x, np.float32))


def test_take_modes():
    """(ref test_operator.py:2699 test_take) axis + clip/wrap modes."""
    rs = np.random.RandomState(0)
    a = rs.randn(4, 5).astype(np.float32)
    idx = np.array([0, 3, -1, 4, 7], np.float32)   # out of range on purpose
    got = mx.nd.take(_nd(a), _nd(idx), axis=0, mode="clip").asnumpy()
    want = a[np.clip(idx.astype(np.int64), 0, 3)]
    np.testing.assert_allclose(got, want)
    got = mx.nd.take(_nd(a), _nd(idx), axis=0, mode="wrap").asnumpy()
    want = a[idx.astype(np.int64) % 4]
    np.testing.assert_allclose(got, want)
    # axis=1
    idx2 = np.array([1, 4], np.float32)
    got = mx.nd.take(_nd(a), _nd(idx2), axis=1).asnumpy()
    np.testing.assert_allclose(got, a[:, [1, 4]])


def test_pick_modes():
    """(ref test_operator.py pick) axis selection + keepdims."""
    rs = np.random.RandomState(1)
    a = rs.randn(3, 4).astype(np.float32)
    idx = np.array([0, 3, 2], np.float32)
    got = mx.nd.pick(_nd(a), _nd(idx), axis=1).asnumpy()
    want = a[np.arange(3), idx.astype(np.int64)]
    np.testing.assert_allclose(got, want)
    got = mx.nd.pick(_nd(a), _nd(idx), axis=1, keepdims=True).asnumpy()
    np.testing.assert_allclose(got, want[:, None])


def test_one_hot_values():
    """(ref test_operator.py:3169) on/off values and float indices."""
    idx = np.array([1, 0, 2, 0], np.float32)
    got = mx.nd.one_hot(_nd(idx), depth=3, on_value=8.0,
                        off_value=-1.0).asnumpy()
    want = np.full((4, 3), -1.0, np.float32)
    want[np.arange(4), idx.astype(np.int64)] = 8.0
    np.testing.assert_allclose(got, want)


def test_where_forms():
    """(ref test_operator.py:3225) same-shape and vector conditions."""
    rs = np.random.RandomState(2)
    x = rs.randn(3, 4).astype(np.float32)
    y = rs.randn(3, 4).astype(np.float32)
    cond = (rs.uniform(size=(3, 4)) > 0.5).astype(np.float32)
    got = mx.nd.where(_nd(cond), _nd(x), _nd(y)).asnumpy()
    np.testing.assert_allclose(got, np.where(cond > 0, x, y))
    # 1-D condition selects rows
    vcond = np.array([0, 1, 0], np.float32)
    got = mx.nd.where(_nd(vcond), _nd(x), _nd(y)).asnumpy()
    want = np.where(vcond[:, None] > 0, x, y)
    np.testing.assert_allclose(got, want)


def test_batch_dot_transpose_combos():
    """(ref test_operator.py:1832) all four transpose combinations,
    forward + gradient."""
    rs = np.random.RandomState(3)
    for ta, tb in [(False, False), (True, False), (False, True),
                   (True, True)]:
        a_shape = (2, 5, 3) if ta else (2, 3, 5)
        b_shape = (2, 4, 5) if tb else (2, 5, 4)
        a = rs.randn(*a_shape).astype(np.float32)
        b = rs.randn(*b_shape).astype(np.float32)
        an = np.transpose(a, (0, 2, 1)) if ta else a
        bn = np.transpose(b, (0, 2, 1)) if tb else b
        want = np.einsum("bij,bjk->bik", an, bn)
        got = mx.nd.batch_dot(_nd(a), _nd(b), transpose_a=ta,
                              transpose_b=tb).asnumpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        sa, sb = mx.sym.Variable("a"), mx.sym.Variable("b")
        out = mx.sym.batch_dot(sa, sb, transpose_a=ta, transpose_b=tb)
        check_numeric_gradient(out, [a, b], numeric_eps=1e-3, rtol=2e-2,
                               atol=1e-2)


def test_dot_transpose_combos():
    rs = np.random.RandomState(4)
    for ta, tb in [(False, False), (True, False), (False, True),
                   (True, True)]:
        a = rs.randn(*((5, 3) if ta else (3, 5))).astype(np.float32)
        b = rs.randn(*((4, 5) if tb else (5, 4))).astype(np.float32)
        want = (a.T if ta else a) @ (b.T if tb else b)
        got = mx.nd.dot(_nd(a), _nd(b), transpose_a=ta,
                        transpose_b=tb).asnumpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_order_ops():
    """(ref test_operator.py:2589 test_order) topk ret_typ variants,
    argsort/sort on axis, descending."""
    rs = np.random.RandomState(5)
    a = rs.permutation(20).reshape(4, 5).astype(np.float32)
    got = mx.nd.topk(_nd(a), k=2, axis=1).asnumpy()      # default: indices
    want_idx = np.argsort(-a, axis=1)[:, :2]
    np.testing.assert_allclose(got, want_idx.astype(np.float32))
    got_v = mx.nd.topk(_nd(a), k=2, axis=1, ret_typ="value").asnumpy()
    np.testing.assert_allclose(got_v, -np.sort(-a, axis=1)[:, :2])
    both = mx.nd.topk(_nd(a), k=2, axis=1, ret_typ="both")
    np.testing.assert_allclose(both[0].asnumpy(), got_v)
    np.testing.assert_allclose(both[1].asnumpy(),
                               want_idx.astype(np.float32))
    # sort / argsort, ascending and descending
    np.testing.assert_allclose(mx.nd.sort(_nd(a), axis=1).asnumpy(),
                               np.sort(a, axis=1))
    np.testing.assert_allclose(
        mx.nd.sort(_nd(a), axis=1, is_ascend=False).asnumpy(),
        -np.sort(-a, axis=1))
    np.testing.assert_allclose(mx.nd.argsort(_nd(a), axis=1).asnumpy(),
                               np.argsort(a, axis=1).astype(np.float32))


def test_slice_axis_negative_bounds():
    """(ref test_operator.py:1673) negative begin/end and None end."""
    rs = np.random.RandomState(6)
    a = rs.randn(4, 6).astype(np.float32)
    got = mx.nd.slice_axis(_nd(a), axis=1, begin=-3, end=None).asnumpy()
    np.testing.assert_allclose(got, a[:, -3:])
    got = mx.nd.slice_axis(_nd(a), axis=0, begin=1, end=-1).asnumpy()
    np.testing.assert_allclose(got, a[1:-1])


def test_sequence_ops_with_lengths():
    """(ref test_operator.py:2265,2337) SequenceMask/Reverse/Last with
    use_sequence_length."""
    a = np.arange(2 * 3 * 2, dtype=np.float32).reshape(3, 2, 2)  # (T,N,C)
    lengths = np.array([2, 3], np.float32)
    got = mx.nd.SequenceMask(_nd(a), _nd(lengths), use_sequence_length=True,
                             value=-1.0).asnumpy()
    want = a.copy()
    want[2:, 0] = -1.0
    np.testing.assert_allclose(got, want)
    got = mx.nd.SequenceLast(_nd(a), _nd(lengths),
                             use_sequence_length=True).asnumpy()
    want = np.stack([a[1, 0], a[2, 1]])
    np.testing.assert_allclose(got, want)
    got = mx.nd.SequenceReverse(_nd(a), _nd(lengths),
                                use_sequence_length=True).asnumpy()
    want = a.copy()
    want[:2, 0] = a[:2, 0][::-1]
    want[:3, 1] = a[:3, 1][::-1]
    np.testing.assert_allclose(got, want)


def test_pad_modes():
    """(ref test_operator.py pad) constant and edge modes on 4-D."""
    rs = np.random.RandomState(7)
    a = rs.randn(1, 1, 3, 3).astype(np.float32)
    pw = (0, 0, 0, 0, 1, 1, 2, 2)
    got = mx.nd.pad(_nd(a), mode="constant", pad_width=pw,
                    constant_value=5.0).asnumpy()
    want = np.pad(a, ((0, 0), (0, 0), (1, 1), (2, 2)), mode="constant",
                  constant_values=5.0)
    np.testing.assert_allclose(got, want)
    got = mx.nd.pad(_nd(a), mode="edge", pad_width=pw).asnumpy()
    want = np.pad(a, ((0, 0), (0, 0), (1, 1), (2, 2)), mode="edge")
    np.testing.assert_allclose(got, want)
    got = mx.nd.pad(_nd(a), mode="reflect", pad_width=pw).asnumpy()
    want = np.pad(a, ((0, 0), (0, 0), (1, 1), (2, 2)), mode="reflect")
    np.testing.assert_allclose(got, want)


def test_broadcast_binary_backward_shapes():
    """(ref test_operator.py:1270) gradients reduce correctly over the
    broadcast dimensions."""
    rs = np.random.RandomState(8)
    a = rs.uniform(0.5, 1.5, (2, 3, 1, 4)).astype(np.float32)
    b = rs.uniform(0.5, 1.5, (1, 3, 5, 1)).astype(np.float32)
    for op in ["broadcast_add", "broadcast_mul", "broadcast_div"]:
        sa, sb = mx.sym.Variable("a"), mx.sym.Variable("b")
        out = getattr(mx.sym, op)(sa, sb)
        check_numeric_gradient(out, [a, b], numeric_eps=1e-3, rtol=2e-2,
                               atol=1e-2)


def test_repeat_and_tile():
    rs = np.random.RandomState(9)
    a = rs.randn(2, 3).astype(np.float32)
    np.testing.assert_allclose(
        mx.nd.repeat(_nd(a), repeats=2, axis=1).asnumpy(),
        np.repeat(a, 2, axis=1))
    np.testing.assert_allclose(   # axis=None flattens, reference-style
        mx.nd.repeat(_nd(a), repeats=3).asnumpy(), np.repeat(a, 3))
    np.testing.assert_allclose(
        mx.nd.tile(_nd(a), reps=(2, 3)).asnumpy(), np.tile(a, (2, 3)))


def test_reverse_and_flip():
    a = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    np.testing.assert_allclose(mx.nd.reverse(_nd(a), axis=1).asnumpy(),
                               a[:, ::-1])
    np.testing.assert_allclose(mx.nd.flip(_nd(a), axis=2).asnumpy(),
                               a[..., ::-1])


def test_clip_gradient_boundaries():
    """clip's gradient is zero outside [a_min, a_max] (reference clip
    backward semantics)."""
    a = np.array([-2.0, -0.5, 0.5, 2.0], np.float32)
    s = mx.sym.Variable("a")
    out = mx.sym.clip(s, a_min=-1.0, a_max=1.0)
    exe = out.simple_bind(mx.cpu(), a=(4,), grad_req="write")
    exe.arg_dict["a"][:] = a
    exe.forward(is_train=True)
    exe.backward(out_grads=[mx.nd.ones((4,))])
    np.testing.assert_allclose(exe.grad_dict["a"].asnumpy(),
                               [0.0, 1.0, 1.0, 0.0])


def test_expand_dims_squeeze_roundtrip():
    a = np.zeros((2, 3), np.float32)
    e = mx.nd.expand_dims(_nd(a), axis=1)
    assert e.shape == (2, 1, 3)
    e2 = mx.nd.expand_dims(_nd(a), axis=-1)
    assert e2.shape == (2, 3, 1)


def test_softmax_output_label_shape_validated():
    """(reference InferShape contract) a label that is not data-minus-
    class-axis raises a clear error instead of a broadcast assertion."""
    d = mx.nd.zeros((4, 2))
    with pytest.raises(Exception, match="label shape"):
        mx.nd.SoftmaxOutput(d, mx.nd.zeros((4, 8)))
    # valid forms still work
    mx.nd.SoftmaxOutput(d, mx.nd.zeros((4,)))
    mx.nd.SoftmaxOutput(mx.nd.zeros((4, 3, 5)), mx.nd.zeros((4, 5)),
                        multi_output=True)
