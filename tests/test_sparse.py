"""Sparse NDArray + sparse training path tests (parity model: reference
tests/python/unittest/test_sparse_ndarray.py / test_sparse_operator.py /
test_optimizer.py sparse sections)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray import sparse as sp


def test_row_sparse_roundtrip():
    data = np.array([[1., 2.], [3., 4.]], np.float32)
    rsp = sp.row_sparse_array((data, [1, 3]), shape=(5, 2))
    assert rsp.stype == "row_sparse"
    dense = rsp.asnumpy()
    expect = np.zeros((5, 2), np.float32)
    expect[[1, 3]] = data
    np.testing.assert_allclose(dense, expect)
    back = sp.cast_storage(mx.nd.array(expect), "row_sparse")
    np.testing.assert_allclose(back.data.asnumpy(), data)
    np.testing.assert_allclose(back.indices.asnumpy(), [1, 3])


def test_csr_roundtrip():
    dense = np.array([[0, 1, 0], [2, 0, 3], [0, 0, 0]], np.float32)
    csr = sp.cast_storage(mx.nd.array(dense), "csr")
    assert csr.stype == "csr"
    np.testing.assert_allclose(csr.asnumpy(), dense)
    np.testing.assert_allclose(csr.data.asnumpy(), [1, 2, 3])
    np.testing.assert_allclose(csr.indices.asnumpy(), [1, 0, 2])
    np.testing.assert_allclose(csr.indptr.asnumpy(), [0, 1, 3, 3])
    # tostype round trip
    np.testing.assert_allclose(csr.tostype("row_sparse").asnumpy(), dense)


def test_retain():
    rsp = sp.row_sparse_array((np.ones((3, 2), np.float32), [0, 2, 4]),
                              shape=(6, 2))
    kept = rsp.retain([2, 4, 5])
    np.testing.assert_allclose(kept.indices.asnumpy(), [2, 4])
    assert kept.shape == (6, 2)


def test_add_n_union_of_rows():
    a = sp.row_sparse_array((np.array([[1., 1.], [2., 2.]]), [0, 2]),
                            shape=(4, 2))
    b = sp.row_sparse_array((np.array([[10., 10.], [20., 20.]]), [2, 3]),
                            shape=(4, 2))
    s = sp.add_n([a, b])
    assert s.stype == "row_sparse"
    np.testing.assert_allclose(s.indices.asnumpy(), [0, 2, 3])
    expect = np.zeros((4, 2))
    expect[0] = 1
    expect[2] = [12, 12]
    expect[3] = [20, 20]
    np.testing.assert_allclose(s.asnumpy(), expect)


def test_sparse_dot():
    rng = np.random.RandomState(0)
    dense = rng.normal(size=(4, 6)).astype(np.float32)
    dense[dense < 0.5] = 0
    rhs = rng.normal(size=(6, 3)).astype(np.float32)
    csr = sp.cast_storage(mx.nd.array(dense), "csr")
    out = sp.dot(csr, mx.nd.array(rhs))
    np.testing.assert_allclose(out.asnumpy(), dense @ rhs, rtol=1e-5,
                               atol=1e-5)
    # transpose_a: csr^T . dense — the sparse-linear-regression grad path
    out_t = sp.dot(csr, mx.nd.array(rng.normal(size=(4, 3))
                                    .astype(np.float32)), transpose_a=True)
    assert out_t.shape == (6, 3)


def _lazy_rows_check(opt_name, **kwargs):
    """Rows absent from a row_sparse grad must stay untouched."""
    opt = mx.optimizer.create(opt_name, learning_rate=0.1, **kwargs)
    w = mx.nd.array(np.ones((5, 3), np.float32))
    state = opt.create_state(0, w)
    grad = sp.row_sparse_array((np.full((2, 3), 0.5, np.float32), [1, 3]),
                               shape=(5, 3))
    w_before = w.asnumpy().copy()
    opt.update(0, w, grad, state)
    w_after = w.asnumpy()
    untouched = [0, 2, 4]
    np.testing.assert_allclose(w_after[untouched], w_before[untouched])
    assert np.all(w_after[[1, 3]] != w_before[[1, 3]])
    return w_after


def test_sgd_lazy_update():
    w = _lazy_rows_check("sgd", momentum=0.9)
    # exact value: mom=0 -> m = -lr*g = -0.05; w = 1 - 0.05
    np.testing.assert_allclose(w[[1, 3]], 0.95, rtol=1e-6)


def test_sgd_lazy_no_momentum():
    w = _lazy_rows_check("sgd")
    np.testing.assert_allclose(w[[1, 3]], 0.95, rtol=1e-6)


def test_adam_lazy_update():
    _lazy_rows_check("adam")


def test_adagrad_lazy_update():
    _lazy_rows_check("adagrad")


def test_kvstore_row_sparse_push_pull():
    kv = mx.kv.create("device")
    kv.init("emb", mx.nd.zeros((6, 2)))
    g1 = sp.row_sparse_array((np.ones((2, 2), np.float32), [0, 2]),
                             shape=(6, 2))
    g2 = sp.row_sparse_array((np.full((1, 2), 3.0, np.float32), [2]),
                             shape=(6, 2))
    kv.push("emb", [g1, g2])
    out = sp.zeros("row_sparse", (6, 2))
    kv.row_sparse_pull("emb", out=out, row_ids=mx.nd.array([0, 2]))
    expect = np.zeros((6, 2), np.float32)
    expect[0] = 1
    expect[2] = 4
    np.testing.assert_allclose(out.asnumpy(), expect)


def test_kvstore_mixed_sparse_dense_push():
    """Mixed shard lists fall back to a dense sum keeping every
    contribution."""
    kv = mx.kv.create("local")
    kv.init(0, mx.nd.zeros((4, 2)))
    rsp = sp.row_sparse_array((np.ones((1, 2), np.float32), [1]),
                              shape=(4, 2))
    dense = mx.nd.ones((4, 2))
    kv.push(0, [rsp, dense])
    out = mx.nd.zeros((4, 2))
    kv.pull(0, out=out)
    expect = np.ones((4, 2), np.float32)
    expect[1] += 1
    np.testing.assert_allclose(out.asnumpy(), expect)


def test_compression_rejects_sparse():
    kv = mx.kv.create("local")
    kv.set_gradient_compression({"type": "2bit"})
    kv.init(0, mx.nd.zeros((4, 2)))
    rsp = sp.row_sparse_array((np.ones((1, 2), np.float32), [1]),
                              shape=(4, 2))
    with pytest.raises(mx.MXNetError):
        kv.push(0, [rsp])


def test_sgd_multi_precision_sparse():
    """fp16 weight + fp32 master copy with a row_sparse grad (reference
    MP_SGD row_sparse kernels)."""
    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9,
                              multi_precision=True)
    w = mx.nd.array(np.ones((5, 3)), dtype="float16")
    state = opt.create_state_multi_precision(0, w)
    assert isinstance(state, tuple)
    grad = sp.row_sparse_array((np.full((2, 3), 0.5, np.float32), [1, 3]),
                               shape=(5, 3))
    opt.update_multi_precision(0, w, grad, state)
    w_after = w.asnumpy()
    assert w.dtype == np.float16
    np.testing.assert_allclose(w_after[[0, 2, 4]], 1.0)
    np.testing.assert_allclose(w_after[[1, 3]], 0.95, rtol=1e-3)
    # master copy stays fp32 and matches
    np.testing.assert_allclose(state[1].asnumpy()[[1, 3]], 0.95, rtol=1e-6)


def test_sparse_grad_stays_sparse_through_kvstore():
    """Aggregation must not densify (the merged store value is rsp)."""
    kv = mx.kv.create("local")
    kv.init(0, mx.nd.zeros((4, 2)))
    g = sp.row_sparse_array((np.ones((1, 2), np.float32), [1]), shape=(4, 2))
    kv.push(0, [g, g])
    assert isinstance(kv._store[0], sp.RowSparseNDArray)
    np.testing.assert_allclose(kv._store[0].data.asnumpy(), [[2., 2.]])


def test_csr_dot_native_vs_numpy():
    """csr . dense and csr^T . dense run on the compressed representation
    (reference dot-inl.h sparse kernels); checked against numpy on random
    matrices with empty rows."""
    rs = np.random.RandomState(3)
    dense = rs.uniform(-1, 1, (17, 9)).astype(np.float32)
    dense[dense < 0.4] = 0          # ~70% sparse
    dense[5] = 0                    # fully empty row
    dense[12] = 0
    csr = mx.nd.sparse.csr_matrix(dense)
    rhs = rs.uniform(-1, 1, (9, 4)).astype(np.float32)
    rhs_t = rs.uniform(-1, 1, (17, 4)).astype(np.float32)

    out = mx.nd.sparse.dot(csr, mx.nd.array(rhs))
    assert out.stype == "default"
    np.testing.assert_allclose(out.asnumpy(), dense @ rhs, rtol=1e-5,
                               atol=1e-6)
    out_t = mx.nd.sparse.dot(csr, mx.nd.array(rhs_t), transpose_a=True)
    np.testing.assert_allclose(out_t.asnumpy(), dense.T @ rhs_t, rtol=1e-5,
                               atol=1e-6)


def test_cast_storage_csr_vectorized_roundtrip():
    rs = np.random.RandomState(4)
    dense = rs.uniform(-1, 1, (31, 23)).astype(np.float32)
    dense[dense < 0.5] = 0
    dense[0] = 0                     # leading empty row
    dense[-1] = 0                    # trailing empty row
    csr = mx.nd.sparse.csr_matrix(dense)
    # canonical CSR invariants
    ptr = csr.indptr.asnumpy()
    assert ptr[0] == 0 and ptr[-1] == csr.data.shape[0]
    assert (np.diff(ptr) >= 0).all()
    np.testing.assert_allclose(csr.tostype("default").asnumpy(), dense)
    # columns sorted within each row (row-major nonzero order)
    ind = csr.indices.asnumpy()
    for r in range(31):
        row = ind[ptr[r]:ptr[r + 1]]
        assert (np.diff(row) > 0).all() if len(row) > 1 else True


def test_retain_device_side():
    data = np.arange(12, dtype=np.float32).reshape(4, 3)
    rsp = mx.nd.sparse.row_sparse_array(
        (data, [1, 3, 5, 8]), shape=(10, 3))
    kept = rsp.retain(mx.nd.array(np.array([3, 8, 9], np.float32)))
    np.testing.assert_array_equal(kept.indices.asnumpy(), [3, 8])
    np.testing.assert_allclose(kept.data.asnumpy(), data[[1, 3]])
    # dense view agrees
    want = np.zeros((10, 3), np.float32)
    want[3] = data[1]
    want[8] = data[3]
    np.testing.assert_allclose(kept.tostype("default").asnumpy(), want)


def test_csr_dot_empty_matrix():
    csr = mx.nd.sparse.zeros("csr", (5, 7))
    rhs = mx.nd.array(np.ones((7, 2), np.float32))
    out = mx.nd.sparse.dot(csr, rhs)
    np.testing.assert_allclose(out.asnumpy(), np.zeros((5, 2)))


def test_csr_dot_shape_mismatch_raises():
    csr = mx.nd.sparse.csr_matrix(np.eye(4, 6, dtype=np.float32))
    with pytest.raises(mx.MXNetError):
        sp.dot(csr, mx.nd.array(np.ones((5, 2), np.float32)))
    with pytest.raises(mx.MXNetError):
        sp.dot(csr, mx.nd.array(np.ones((6, 2), np.float32)),
               transpose_a=True)


def test_csr_dot_vector_rhs_falls_back_dense():
    dense = np.eye(4, 6, dtype=np.float32) * 2
    csr = mx.nd.sparse.csr_matrix(dense)
    v = np.arange(6, dtype=np.float32)
    out = sp.dot(csr, mx.nd.array(v))
    np.testing.assert_allclose(out.asnumpy(), dense @ v)


def test_csr_elemwise_add():
    """csr + csr keeps csr storage (reference elemwise add with the
    storage-fallback path for kernel-less combinations)."""
    d = np.random.RandomState(0).uniform(size=(4, 6)).astype(np.float32)
    d[d < 0.5] = 0
    c = sp.csr_matrix(d)
    s = sp.elemwise_add(c, c)
    assert s.stype == "csr"
    np.testing.assert_allclose(s.asnumpy(), 2 * d, rtol=1e-6)


def test_cast_storage_sparse_to_sparse_native():
    """rsp<->csr conversions run on the compressed representation —
    correct for unsorted rsp indices and explicit zeros inside stored
    rows, and the input's dense cache must stay cold (no densify).
    Parity: reference cast_storage-inl.h sparse-to-sparse paths."""
    # unsorted indices + a zero inside a stored row + an all-zero row
    data = np.array([[0., 5., 0.], [1., 0., 2.], [0., 0., 0.]], np.float32)
    rsp = sp.row_sparse_array((data, [4, 1, 2]), shape=(6, 3))
    csr = rsp.tostype("csr")
    back = csr.tostype("row_sparse")
    # both conversions ran before any dense access: caches stay cold
    assert rsp._dense_cache is None
    assert csr._dense_cache is None
    expect = np.zeros((6, 3), np.float32)
    expect[[4, 1, 2]] = data
    np.testing.assert_allclose(csr.asnumpy(), expect)
    np.testing.assert_allclose(csr.indptr.asnumpy(),
                               [0, 0, 2, 2, 2, 3, 3])
    # all-zero stored row 2 disappears; row order is sorted
    np.testing.assert_allclose(back.indices.asnumpy(), [1, 4])
    np.testing.assert_allclose(back.data.asnumpy(),
                               [[1., 0., 2.], [0., 5., 0.]])


def test_csr_dot_backward_native():
    """Autograd through the native csr.dot path: grad w.r.t. the dense
    rhs is the transposed O(nnz) kernel, and the csr lhs is never
    densified (reference dot-inl.h fwd/bwd kernel pair)."""
    from mxnet_tpu import autograd
    rng = np.random.RandomState(3)
    lhs = ((rng.rand(6, 5) < 0.4) * rng.randn(6, 5)).astype(np.float32)
    csr = sp.cast_storage(mx.nd.array(lhs), "csr")
    csr._dense_cache = None  # cast from dense caches; reset for the probe
    w = mx.nd.array(rng.randn(5, 4).astype(np.float32))
    w.attach_grad()
    with autograd.record():
        out = sp.dot(csr, w)
        loss = (out * out).sum()
    loss.backward()
    # d/dW sum((A W)^2) = 2 A^T (A W)
    expect = 2.0 * lhs.T @ (lhs @ np.asarray(w.asnumpy()))
    np.testing.assert_allclose(w.grad.asnumpy(), expect, rtol=1e-5,
                               atol=1e-5)
    assert csr._dense_cache is None

    # transpose_a path: d/dW sum((A^T W)^2) = 2 A (A^T W)
    w2 = mx.nd.array(rng.randn(6, 3).astype(np.float32))
    w2.attach_grad()
    with autograd.record():
        out2 = sp.dot(csr, w2, transpose_a=True)
        loss2 = (out2 * out2).sum()
    loss2.backward()
    expect2 = 2.0 * lhs @ (lhs.T @ np.asarray(w2.asnumpy()))
    np.testing.assert_allclose(w2.grad.asnumpy(), expect2, rtol=1e-5,
                               atol=1e-5)
    assert csr._dense_cache is None


def _live_device_bytes():
    import jax
    return sum(int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
               for a in jax.live_arrays())


def test_sparse_embedding_scale_o_nnz_memory():
    """The SURVEY §2.3 case: a 1M x 512 embedding gradient. Every sparse
    op in the chain (add_n, retain, rsp->csr->rsp) must stay O(nnz +
    nrows-metadata): live device bytes may grow by a small fraction of
    the 2 GB dense shape, and no dense cache may be populated."""
    NROWS, NCOLS, NNZ = 1_000_000, 512, 1024
    dense_bytes = NROWS * NCOLS * 4
    rng = np.random.RandomState(0)
    rows = np.unique(rng.randint(0, NROWS, NNZ * 2))[:NNZ].astype(np.int64)
    vals = rng.randn(len(rows), NCOLS).astype(np.float32)
    base = _live_device_bytes()
    g1 = sp.row_sparse_array((vals, rows), shape=(NROWS, NCOLS))
    g2 = sp.row_sparse_array((vals * 2.0, rows), shape=(NROWS, NCOLS))
    s = sp.add_n([g1, g2])
    kept = s.retain(rows[:16].tolist())
    csr = s.tostype("csr")
    back = csr.tostype("row_sparse")
    import jax
    jax.block_until_ready(back._rsp_data)
    grown = _live_device_bytes() - base
    assert grown < dense_bytes // 10, \
        "sparse chain allocated %d bytes (dense would be %d)" % (
            grown, dense_bytes)
    for a in (g1, g2, s, kept, csr, back):
        assert a._dense_cache is None
    # spot-check values without densifying
    np.testing.assert_allclose(s.data.asnumpy(), vals * 3.0, rtol=1e-6)
    np.testing.assert_allclose(back.indices.asnumpy(), rows)
    np.testing.assert_allclose(back.data.asnumpy(), vals * 3.0, rtol=1e-6)
    np.testing.assert_allclose(kept.indices.asnumpy(), rows[:16])


def test_cast_storage_duplicate_rsp_rows_matches_dense_view():
    """Duplicate row ids in a user-built rsp: the csr conversion must
    agree with the dense view's scatter-set semantics (last stored
    occurrence wins), not scatter values into unrelated rows."""
    rsp = sp.row_sparse_array(
        (np.array([[1., 2.], [3., 4.], [5., 0.]], np.float32), [1, 1, 3]),
        shape=(5, 2))
    dense = rsp.asnumpy()
    np.testing.assert_allclose(dense[1], [3., 4.])  # last wins
    csr = sp.row_sparse_array(
        (np.array([[1., 2.], [3., 4.], [5., 0.]], np.float32), [1, 1, 3]),
        shape=(5, 2)).tostype("csr")
    np.testing.assert_allclose(csr.asnumpy(), dense)


def test_csr_elemwise_add_native_no_densify():
    """csr + csr merges on the compressed representation: correct for
    overlapping and disjoint coordinates, never materialises dense, and
    stays O(nnz) at the 1M x 512 embedding scale."""
    rs = np.random.RandomState(7)
    a_dense = (rs.rand(6, 5) < 0.4) * rs.randn(6, 5)
    b_dense = (rs.rand(6, 5) < 0.4) * rs.randn(6, 5)
    a = sp.csr_matrix(a_dense.astype(np.float32))
    b = sp.csr_matrix(b_dense.astype(np.float32))
    a._dense_cache = None
    b._dense_cache = None
    out = sp.elemwise_add(a, b)
    assert a._dense_cache is None and b._dense_cache is None
    np.testing.assert_allclose(out.asnumpy(),
                               (a_dense + b_dense).astype(np.float32),
                               rtol=1e-6)

    # scale: live device bytes stay O(nnz), not O(1M x 512)
    NROWS, NCOLS, NNZ = 1_000_000, 512, 2048
    rows = np.sort(rs.choice(NROWS, NNZ, replace=False)).astype(np.int64)
    cols = rs.randint(0, NCOLS, NNZ).astype(np.int64)
    # CSR construction wants per-row sorted cols; build via indptr
    order = np.lexsort((cols, rows))
    rows, cols = rows[order], cols[order]
    counts = np.bincount(rows, minlength=NROWS)
    indptr = np.concatenate([[0], np.cumsum(counts)])
    vals = rs.randn(NNZ).astype(np.float32)
    big_a = sp.CSRNDArray(vals, cols, indptr, (NROWS, NCOLS))
    big_b = sp.CSRNDArray(vals * 2.0, cols, indptr, (NROWS, NCOLS))
    base = _live_device_bytes()
    big = sp.elemwise_add(big_a, big_b)
    import jax
    jax.block_until_ready(big._csr_data)
    grown = _live_device_bytes() - base
    assert grown < (NROWS * NCOLS * 4) // 10, grown
    assert big._dense_cache is None
    np.testing.assert_allclose(np.asarray(big._csr_data), vals * 3.0,
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# round-5 native kernel set: sub/mul, scalar ops, square, _square_sum,
# sum(csr, axis) — the remaining reference FComputeEx table
# (elemwise_binary_op_basic.cc, elemwise_binary_scalar_op_basic.cc,
# elemwise_unary_op_basic.cc square, square_sum-inl.h,
# broadcast_reduce_op_value.cc) — VERDICT r4 next #5.
# ---------------------------------------------------------------------------

def _rand_sparse_pair(rs, shape, density=0.4):
    a = ((rs.rand(*shape) < density) * rs.randn(*shape)).astype(np.float32)
    b = ((rs.rand(*shape) < density) * rs.randn(*shape)).astype(np.float32)
    return a, b


@pytest.mark.parametrize("op,npop", [
    ("elemwise_sub", np.subtract), ("elemwise_mul", np.multiply)])
@pytest.mark.parametrize("stype", ["csr", "row_sparse"])
def test_elemwise_sub_mul_native(op, npop, stype):
    rs = np.random.RandomState(11)
    ad, bd = _rand_sparse_pair(rs, (7, 5))
    a = sp.csr_matrix(ad) if stype == "csr" else sp.row_sparse_array(ad)
    b = sp.csr_matrix(bd) if stype == "csr" else sp.row_sparse_array(bd)
    a._dense_cache = None
    b._dense_cache = None
    out = getattr(sp, op)(a, b)
    assert out.stype == stype          # reference storage table
    assert a._dense_cache is None and b._dense_cache is None
    assert out._dense_cache is None
    np.testing.assert_allclose(out.asnumpy(), npop(ad, bd), rtol=1e-6)


@pytest.mark.parametrize("stype", ["csr", "row_sparse"])
def test_elemwise_dispatch_via_registered_ops(stype):
    """mx.nd.elemwise_* and the NDArray dunders route sparse/sparse
    pairs through the native kernels — the FInferStorageType dispatch,
    not the python sparse module only."""
    rs = np.random.RandomState(12)
    ad, bd = _rand_sparse_pair(rs, (6, 4))
    mk = sp.csr_matrix if stype == "csr" else sp.row_sparse_array
    a, b = mk(ad), mk(bd)
    for fn, ref in [(mx.nd.elemwise_add, ad + bd),
                    (mx.nd.elemwise_sub, ad - bd),
                    (mx.nd.elemwise_mul, ad * bd),
                    (lambda x, y: x - y, ad - bd),
                    (lambda x, y: x * y, ad * bd)]:
        a._dense_cache = None
        b._dense_cache = None
        out = fn(a, b)
        assert out.stype == stype, fn
        assert a._dense_cache is None and b._dense_cache is None
        np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-6)


def test_scalar_ops_preserve_stype():
    """_mul_scalar/_div_scalar operate on the data array only (reference
    `only operates on data array of input if input is sparse`);
    plus_scalar produces dense (reference WITH_DENSE_RESULT macro)."""
    rs = np.random.RandomState(13)
    ad = ((rs.rand(5, 3) < 0.5) * rs.randn(5, 3)).astype(np.float32)
    for mk, stype in [(sp.csr_matrix, "csr"),
                      (sp.row_sparse_array, "row_sparse")]:
        arr = mk(ad)
        arr._dense_cache = None
        out = arr * 2.5
        assert out.stype == stype
        assert arr._dense_cache is None
        np.testing.assert_allclose(out.asnumpy(), ad * 2.5, rtol=1e-6)
        out = arr / 2.0
        assert out.stype == stype
        np.testing.assert_allclose(out.asnumpy(), ad / 2.0, rtol=1e-6)
        out = -arr
        assert out.stype == stype
        np.testing.assert_allclose(out.asnumpy(), -ad, rtol=1e-6)
        dense_out = arr + 1.0           # f(0) != 0 -> dense result
        assert dense_out.stype == "default"
        np.testing.assert_allclose(dense_out.asnumpy(), ad + 1.0, rtol=1e-6)


def test_square_preserves_stype():
    rs = np.random.RandomState(14)
    ad = ((rs.rand(6, 3) < 0.5) * rs.randn(6, 3)).astype(np.float32)
    for mk, stype in [(sp.csr_matrix, "csr"),
                      (sp.row_sparse_array, "row_sparse")]:
        arr = mk(ad)
        arr._dense_cache = None
        out = mx.nd.square(arr)
        assert out.stype == stype
        assert arr._dense_cache is None and out._dense_cache is None
        np.testing.assert_allclose(out.asnumpy(), ad * ad, rtol=1e-6)


def test_square_sum_storage_table():
    """_square_sum storage rules (square_sum-inl.h
    SquareSumForwardInferStorageType): axis=1+keepdims -> rsp;
    axis=1 -> dense vector; axis=0 -> dense."""
    data = np.array([[1., 2.], [0., 3.]], np.float32)
    rows = [1, 4]
    rsp = sp.row_sparse_array((data, rows), shape=(6, 2))
    dense = rsp.asnumpy()

    out = sp.square_sum(rsp, axis=1, keepdims=True)
    assert out.stype == "row_sparse" and out.shape == (6, 1)
    np.testing.assert_allclose(out.indices.asnumpy(), rows)
    np.testing.assert_allclose(out.asnumpy(),
                               (dense ** 2).sum(axis=1, keepdims=True))

    out = sp.square_sum(rsp, axis=1)
    assert out.stype == "default"
    np.testing.assert_allclose(out.asnumpy(), (dense ** 2).sum(axis=1))

    out = sp.square_sum(rsp, axis=0)
    assert out.stype == "default"
    np.testing.assert_allclose(out.asnumpy(), (dense ** 2).sum(axis=0))

    # registered-op route (reference mx.nd._internal._square_sum call
    # site, square_sum.cc:39)
    out = mx.nd._square_sum(rsp, axis=1, keepdims=True)
    assert out.stype == "row_sparse"
    # dense input has no kernel in the reference either
    with pytest.raises(mx.MXNetError):
        mx.nd._square_sum(mx.nd.array(dense))


def test_sum_csr_axis_native():
    """sum(csr, axis=0/1) reduces on the compressed representation
    (broadcast_reduce_op_value.cc csr FComputeEx), dense output."""
    rs = np.random.RandomState(15)
    ad = ((rs.rand(6, 5) < 0.4) * rs.randn(6, 5)).astype(np.float32)
    csr = sp.csr_matrix(ad)
    csr._dense_cache = None
    for kwargs, ref in [({"axis": 1}, ad.sum(axis=1)),
                        ({"axis": 0}, ad.sum(axis=0)),
                        ({"axis": 1, "keepdims": True},
                         ad.sum(axis=1, keepdims=True)),
                        ({"axis": 0, "keepdims": True},
                         ad.sum(axis=0, keepdims=True))]:
        out = mx.nd.sum(csr, **kwargs)
        assert out.stype == "default"
        assert csr._dense_cache is None, kwargs
        np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5)


def test_native_kernels_no_densify_at_scale():
    """The round-5 kernel set at 1M x 512: sub, mul, scalar-mul, square,
    _square_sum chained on rsp inputs grow live device bytes by O(nnz),
    never the 2 GB dense shape; csr sub/mul/sum at the same scale."""
    import jax
    NROWS, NCOLS, NNZ = 1_000_000, 512, 1024
    dense_bytes = NROWS * NCOLS * 4
    rs = np.random.RandomState(16)
    rows = np.unique(rs.randint(0, NROWS, NNZ * 2))[:NNZ].astype(np.int64)
    vals = rs.randn(len(rows), NCOLS).astype(np.float32)
    base = _live_device_bytes()
    g1 = sp.row_sparse_array((vals, rows), shape=(NROWS, NCOLS))
    g2 = sp.row_sparse_array((vals * 2.0, rows), shape=(NROWS, NCOLS))
    diff = sp.elemwise_sub(g1, g2)
    prod = sp.elemwise_mul(g1, g2)
    scaled = g1 * 0.5
    sq = mx.nd.square(g1)
    norms = sp.square_sum(g1, axis=1, keepdims=True)
    jax.block_until_ready(norms._rsp_data)
    grown = _live_device_bytes() - base
    assert grown < dense_bytes // 10, grown
    for a in (g1, g2, diff, prod, scaled, sq, norms):
        assert a._dense_cache is None
    np.testing.assert_allclose(diff.data.asnumpy(), -vals, rtol=1e-6)
    np.testing.assert_allclose(prod.data.asnumpy(), vals * vals * 2.0,
                               rtol=1e-6)
    np.testing.assert_allclose(sq.data.asnumpy(), vals * vals, rtol=1e-6)


def _random_dense(rs, shape, density):
    d = rs.randn(*shape).astype(np.float32)
    mask = rs.rand(*shape) < density
    return d * mask


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_csr_kernels_randomised_midscale(seed):
    """Property check at awkward (non-aligned) shapes: the native csr
    kernel chain against numpy oracles on random 513x257 operands."""
    rs = np.random.RandomState(seed)
    shape = (513, 257)
    a = _random_dense(rs, shape, 0.05)
    b = _random_dense(rs, shape, 0.05)
    ca = sp.cast_storage(mx.nd.array(a), "csr")
    cb = sp.cast_storage(mx.nd.array(b), "csr")

    # structural round trip
    np.testing.assert_allclose(ca.asnumpy(), a, rtol=1e-6)
    assert int(ca.indptr.asnumpy()[-1]) == int((a != 0).sum())

    # csr + csr (native COO-merge path) stays csr and matches numpy
    s = mx.nd.elemwise_add(ca, cb)
    assert s.stype == "csr"
    np.testing.assert_allclose(s.asnumpy(), a + b, rtol=1e-5)
    m = mx.nd.elemwise_mul(ca, cb)
    assert m.stype == "csr"
    np.testing.assert_allclose(m.asnumpy(), a * b, rtol=1e-5)

    # csr . dense and csr^T . dense with gradient through the dense rhs
    w = rs.randn(shape[1], 31).astype(np.float32)
    out = mx.nd.dot(ca, mx.nd.array(w))
    np.testing.assert_allclose(out.asnumpy(), a @ w, rtol=1e-4, atol=1e-4)
    wt = rs.randn(shape[0], 17).astype(np.float32)
    outt = mx.nd.dot(ca, mx.nd.array(wt), transpose_a=True)
    np.testing.assert_allclose(outt.asnumpy(), a.T @ wt, rtol=1e-4,
                               atol=1e-4)

    # sparse<->sparse casts agree with the dense path
    rsp = ca.tostype("row_sparse")
    np.testing.assert_allclose(rsp.asnumpy(), a, rtol=1e-6)
    back = rsp.tostype("csr")
    np.testing.assert_allclose(back.asnumpy(), a, rtol=1e-6)


@pytest.mark.parametrize("seed", [3, 4])
def test_rsp_kernels_randomised_midscale(seed):
    rs = np.random.RandomState(seed)
    nrows, ncols, k = 997, 129, 41
    rows = np.sort(rs.choice(nrows, size=k, replace=False)).astype(np.int64)
    va = rs.randn(k, ncols).astype(np.float32)
    vb = rs.randn(k, ncols).astype(np.float32)
    ga = sp.row_sparse_array((va, rows), shape=(nrows, ncols))
    gb = sp.row_sparse_array((vb, rows), shape=(nrows, ncols))
    dense_a = np.zeros((nrows, ncols), np.float32); dense_a[rows] = va
    dense_b = np.zeros((nrows, ncols), np.float32); dense_b[rows] = vb

    for op, ref in [(mx.nd.elemwise_add, dense_a + dense_b),
                    (mx.nd.elemwise_sub, dense_a - dense_b),
                    (mx.nd.elemwise_mul, dense_a * dense_b)]:
        got = op(ga, gb)
        assert got.stype == "row_sparse"
        np.testing.assert_allclose(got.asnumpy(), ref, rtol=1e-5)

    sq = mx.nd.square(ga)
    assert sq.stype == "row_sparse"
    np.testing.assert_allclose(sq.asnumpy(), dense_a ** 2, rtol=1e-5)
    ssum = sp.square_sum(ga, axis=1, keepdims=True)
    np.testing.assert_allclose(
        ssum.asnumpy(), (dense_a ** 2).sum(axis=1, keepdims=True),
        rtol=1e-4)

    # retain an awkward subset, compare against dense masking
    keep = np.sort(rs.choice(nrows, size=211, replace=False))
    kept = ga.retain(keep)
    dense_keep = np.zeros_like(dense_a)
    dense_keep[keep] = dense_a[keep]
    np.testing.assert_allclose(kept.asnumpy(), dense_keep, rtol=1e-6)
