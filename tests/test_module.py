"""Module API tests (parity model: reference tests/python/unittest/test_module.py
and tests/python/train/test_mlp.py convergence gate)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym, nd
from mxnet_tpu.io import NDArrayIter, DataBatch


def _toy_data(n=512, d=32, c=4, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.normal(0, 2, (c, d)).astype(np.float32)
    y = rng.randint(0, c, n)
    x = ((centers[y] + rng.normal(0, 0.5, (n, d))) / 3.0).astype(np.float32)
    return x, y.astype(np.float32)


def _mlp(c=4):
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=c, name="fc2")
    return sym.SoftmaxOutput(net, sym.Variable("softmax_label"), name="softmax")


def test_bind_init_forward():
    net = _mlp()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 32))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(mx.initializer.Xavier())
    x, y = _toy_data(8)
    mod.forward(DataBatch(data=[nd.array(x[:8])], label=[nd.array(y[:8])]),
                is_train=False)
    out = mod.get_outputs()[0]
    assert out.shape == (8, 4)
    np.testing.assert_allclose(out.asnumpy().sum(1), np.ones(8), rtol=1e-5)


def test_fit_convergence():
    """The MNIST-MLP convergence gate of the reference, on synthetic data."""
    x, y = _toy_data(512)
    train = NDArrayIter(x, y, batch_size=64, shuffle=True)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.initializer.Xavier(), num_epoch=5)
    score = mod.score(NDArrayIter(x, y, batch_size=64), "acc")
    assert score[0][1] > 0.95, "did not converge: %s" % score


def test_eval_different_batch_size():
    x, y = _toy_data(256)
    train = NDArrayIter(x, y, batch_size=64, shuffle=True)
    val = NDArrayIter(x[:112], y[:112], batch_size=56)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, eval_data=val, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            initializer=mx.initializer.Xavier(), num_epoch=2)


def test_predict():
    x, y = _toy_data(128)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    it = NDArrayIter(x, y, batch_size=32)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=False)
    mod.init_params(mx.initializer.Xavier())
    out = mod.predict(it)
    assert out.shape == (128, 4)


def test_checkpoint_roundtrip(tmp_path):
    prefix = str(tmp_path / "model")
    x, y = _toy_data(128)
    train = NDArrayIter(x, y, batch_size=32)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            initializer=mx.initializer.Xavier(), num_epoch=1)
    mod.save_checkpoint(prefix, 1)

    mod2 = mx.mod.Module.load(prefix, 1, context=mx.cpu())
    mod2.bind(data_shapes=train.provide_data,
              label_shapes=train.provide_label, for_training=False)
    mod2.init_params()
    # identical predictions
    b = DataBatch(data=[nd.array(x[:32])], label=[nd.array(y[:32])])
    mod.forward(b, is_train=False)
    mod2.forward(b, is_train=False)
    np.testing.assert_allclose(mod.get_outputs()[0].asnumpy(),
                               mod2.get_outputs()[0].asnumpy(), rtol=1e-5)


def test_optimizer_state_save_load(tmp_path):
    x, y = _toy_data(64)
    train = NDArrayIter(x, y, batch_size=32)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.initializer.Xavier(), num_epoch=1)
    f = str(tmp_path / "opt.states")
    mod.save_optimizer_states(f)
    mod.load_optimizer_states(f)


def test_fixed_params():
    net = _mlp()
    mod = mx.mod.Module(net, context=mx.cpu(),
                        fixed_param_names=["fc1_weight", "fc1_bias"])
    mod.bind(data_shapes=[("data", (8, 32))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    x, y = _toy_data(8)
    w_before = mod._exec.arg_dict["fc1_weight"].asnumpy().copy()
    b = DataBatch(data=[nd.array(x[:8])], label=[nd.array(y[:8])])
    mod.forward_backward(b)
    mod.update()
    np.testing.assert_array_equal(
        mod._exec.arg_dict["fc1_weight"].asnumpy(), w_before)


def test_update_on_kvstore():
    x, y = _toy_data(256)
    train = NDArrayIter(x, y, batch_size=64)
    kv = mx.kvstore.create("device")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, optimizer="sgd", kvstore=kv,
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.initializer.Xavier(), num_epoch=3)
    score = mod.score(NDArrayIter(x, y, batch_size=64), "acc")
    assert score[0][1] > 0.9, score


def test_bucketing_module():
    def sym_gen(seq_len):
        data = sym.Variable("data")
        net = sym.FullyConnected(data, num_hidden=8, name="fc_shared")
        net = sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                                name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=16,
                                 context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 16))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd")
    for key in (16, 16, 16):
        b = DataBatch(data=[nd.ones((4, key))], label=[nd.zeros((4,))],
                      bucket_key=key,
                      provide_data=[("data", (4, key))],
                      provide_label=[("softmax_label", (4,))])
        mod.forward_backward(b)
        mod.update()


def test_module_output_shapes_before_forward():
    # regression: SequentialModule chains stages through output_shapes at
    # bind time, before any forward has run
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=4, name="fc")
    m = mx.mod.Module(fc, label_names=[])
    m.bind(data_shapes=[("data", (2, 3))], label_shapes=None,
           for_training=False)
    assert m.output_shapes == [("fc_output", (2, 4))]


def test_sequential_module_chain():
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data=data, num_hidden=8, name="fc1")
    stage1 = mx.mod.Module(fc1, label_names=[])

    data2 = mx.sym.Variable("data")
    net2 = mx.sym.FullyConnected(data=data2, num_hidden=2, name="fc2")
    net2 = mx.sym.SoftmaxOutput(net2, name="softmax")
    stage2 = mx.mod.Module(net2)

    seq = mx.mod.SequentialModule()
    seq.add(stage1).add(stage2, take_labels=True, auto_wiring=True)
    seq.bind(data_shapes=[("data", (4, 6))],
             label_shapes=[("softmax_label", (4,))])
    seq.init_params(mx.initializer.Xavier())
    seq.init_optimizer(optimizer="sgd")
    batch = DataBatch(data=[nd.ones((4, 6))], label=[nd.zeros((4,))])
    seq.forward_backward(batch)
    seq.update()
    out = seq.get_outputs()[0]
    assert out.shape == (4, 2)


def test_registry_shared_with_builtin_factories():
    # regression: mx.registry must see classes registered via
    # optimizer/metric/initializer @register (shared backing store)
    create = mx.registry.get_create_func(mx.optimizer.Optimizer, "optimizer")
    assert type(create("sgd")).__name__ == "SGD"
    import json
    opt = create(json.dumps(["adam", {"learning_rate": 0.1}]))
    assert type(opt).__name__ == "Adam"


def test_group2ctx_model_parallel_matches_single_device():
    """group2ctx places op groups on different devices with cross-device
    copies at boundaries (parity: reference AssignContext +
    cross_device_copy, tests/python/unittest/test_model_parallel.py).
    Runs on the 8-device virtual CPU mesh."""
    import jax
    if len(jax.devices("cpu")) < 2:
        import pytest as _pytest
        _pytest.skip("needs 2 cpu devices")
    rs = np.random.RandomState(0)
    x_np = rs.uniform(-1, 1, (4, 6)).astype(np.float32)
    w1 = rs.uniform(-0.5, 0.5, (5, 6)).astype(np.float32)
    w2 = rs.uniform(-0.5, 0.5, (3, 5)).astype(np.float32)

    def build():
        with mx.AttrScope(ctx_group="dev1"):
            data = mx.sym.Variable("data")
            net = mx.sym.FullyConnected(data, num_hidden=5, no_bias=True,
                                        name="fc1")
            net = mx.sym.Activation(net, act_type="tanh")
        with mx.AttrScope(ctx_group="dev2"):
            net = mx.sym.FullyConnected(net, num_hidden=3, no_bias=True,
                                        name="fc2")
        return net

    def run(group2ctx):
        net = build()
        ex = net.simple_bind(ctx=mx.cpu(0), grad_req="write",
                             group2ctx=group2ctx, data=(4, 6))
        ex.arg_dict["data"][:] = x_np
        ex.arg_dict["fc1_weight"][:] = w1
        ex.arg_dict["fc2_weight"][:] = w2
        out = ex.forward_backward(out_grads=mx.nd.ones((4, 3)),
                                  is_train=True)[0].asnumpy()
        return out, ex.grad_dict["fc1_weight"].asnumpy()

    base_out, base_g = run(None)
    mp_out, mp_g = run({"dev1": mx.cpu(0), "dev2": mx.cpu(1)})
    np.testing.assert_allclose(mp_out, base_out, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(mp_g, base_g, rtol=1e-5, atol=1e-6)
    # the grouped program really assigned two distinct devices
    net = build()
    ex = net.simple_bind(ctx=mx.cpu(0),
                         group2ctx={"dev1": mx.cpu(0), "dev2": mx.cpu(1)},
                         data=(4, 6))
    devs = set(ex._prog.node_devices.values())
    assert len(devs) == 2, devs


def test_group2ctx_placement_details():
    """Parameters live on their group's device (no per-step re-copy),
    gradients land there too, outputs report the group context, and
    Module forwards group2ctxs."""
    import jax
    if len(jax.devices("cpu")) < 2:
        import pytest as _pytest
        _pytest.skip("needs 2 cpu devices")
    with mx.AttrScope(ctx_group="dev1"):
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, num_hidden=5, no_bias=True,
                                    name="fc1")
    with mx.AttrScope(ctx_group="dev2"):
        net = mx.sym.FullyConnected(net, num_hidden=3, no_bias=True,
                                    name="fc2")
    g2c = {"dev1": mx.cpu(0), "dev2": mx.cpu(1)}
    ex = net.simple_bind(ctx=mx.cpu(0), grad_req="write", group2ctx=g2c,
                         data=(4, 6))
    cpu1 = mx.cpu(1).jax_device()
    # fc2's weight storage committed to cpu(1) at bind
    assert list(ex.arg_dict["fc2_weight"]._data.devices())[0] == cpu1
    ex.arg_dict["data"][:] = np.ones((4, 6), np.float32)
    ex.arg_dict["fc1_weight"][:] = np.ones((5, 6), np.float32) * 0.1
    ex.arg_dict["fc2_weight"][:] = np.ones((3, 5), np.float32) * 0.1
    outs = ex.forward_backward(out_grads=mx.nd.ones((4, 3)), is_train=True)
    # output data AND reported context are the group device
    assert list(outs[0]._data.devices())[0] == cpu1
    assert outs[0].context == mx.cpu(1)
    # fc2's gradient stays on its group device
    assert list(ex.grad_dict["fc2_weight"]._data.devices())[0] == cpu1

    # Module-level plumbing
    mod = mx.mod.Module(net, context=mx.cpu(0), group2ctxs=g2c)
    mod.bind(data_shapes=[("data", (4, 6))], label_shapes=None)
    assert mod._exec._prog.node_devices


def test_group2ctx_misplacement_raises():
    """Caller-owned arrays on the wrong group device raise at bind (no
    silent relocation of shared storage); multi-device context lists
    reject group2ctxs (the dp mesh shards one program — incompatible
    with per-op device pinning)."""
    import jax
    import pytest
    if len(jax.devices("cpu")) < 2:
        pytest.skip("needs 2 cpu devices")
    with mx.AttrScope(ctx_group="dev1"):
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, num_hidden=5, no_bias=True,
                                    name="fc1")
    with mx.AttrScope(ctx_group="dev2"):
        net = mx.sym.FullyConnected(net, num_hidden=3, no_bias=True,
                                    name="fc2")
    g2c = {"dev1": mx.cpu(0), "dev2": mx.cpu(1)}
    args = {"data": mx.nd.zeros((4, 6), ctx=mx.cpu(0)),
            "fc1_weight": mx.nd.zeros((5, 6), ctx=mx.cpu(0)),
            "fc2_weight": mx.nd.zeros((3, 5), ctx=mx.cpu(0))}
    with pytest.raises(mx.MXNetError, match="fc2_weight"):
        net.bind(ctx=mx.cpu(0), args=args, group2ctx=g2c)
    # correctly placed caller arrays bind fine and are not moved
    args["fc2_weight"] = mx.nd.zeros((3, 5), ctx=mx.cpu(1))
    ex = net.bind(ctx=mx.cpu(0), args=args, group2ctx=g2c)
    assert args["fc2_weight"].context == mx.cpu(1)
    ex.forward(is_train=False)

    mod = mx.mod.Module(net, context=[mx.cpu(0), mx.cpu(1)],
                        group2ctxs=g2c)
    with pytest.raises(mx.MXNetError, match="group2ctxs"):
        mod.bind(data_shapes=[("data", (4, 6))], label_shapes=None)


def test_module_load_bind_predict():
    """Module.load -> bind -> forward installs the checkpointed params at
    bind time (reference module.py:126-183) — regression: predictions
    after reload must match the trained module, BN aux states included."""
    import tempfile
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8)
    net = mx.sym.BatchNorm(net, name="bn")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(net, num_hidden=2),
                               name="softmax")
    x = np.random.RandomState(0).randn(32, 4).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.float32)
    it = mx.io.NDArrayIter(x, y, batch_size=8)
    mod = mx.mod.Module(net)
    mod.fit(it, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            initializer=mx.initializer.Xavier())
    batch = DataBatch([mx.nd.array(x[:8])], [mx.nd.zeros((8,))])
    mod.forward(batch, is_train=False)
    want = mod.get_outputs()[0].asnumpy()
    with tempfile.TemporaryDirectory() as d:
        import os
        mod.save_checkpoint(os.path.join(d, "m"), 2)
        m2 = mx.mod.Module.load(os.path.join(d, "m"), 2)
        m2.bind(data_shapes=[("data", (8, 4))], for_training=False,
                label_shapes=[("softmax_label", (8,))])
        m2.forward(batch, is_train=False)
        got = m2.get_outputs()[0].asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_executor_group_facade_forward_feeds_batch():
    """Regression: the DataParallelExecutorGroup compatibility facade
    discarded the batch in forward() — any direct user forward-ran
    whatever was last bound."""
    from mxnet_tpu.module.executor_group import DataParallelExecutorGroup

    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=4, name="fc")
    net = sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                            name="softmax")
    g = DataParallelExecutorGroup(
        net, [mx.cpu()], None, [("data", (2, 3))],
        [("softmax_label", (2,))], ["fc_weight", "fc_bias"], True, False)
    g.execs[0].arg_dict["fc_weight"][:] = \
        np.arange(12).reshape(4, 3).astype(np.float32)
    g.forward(DataBatch([nd.ones((2, 3))], [nd.zeros((2,))]),
              is_train=False)
    o1 = np.asarray(g.get_outputs()[0]._data).copy()
    g.forward(DataBatch([nd.zeros((2, 3))], [nd.zeros((2,))]),
              is_train=False)
    o2 = np.asarray(g.get_outputs()[0]._data)
    assert not np.array_equal(o1, o2), "forward must see fresh batch data"


def test_executor_group_facade_multi_context_shards():
    """A multi-context facade commits the dp mesh on its ONE executor:
    the global batch feeds through a sharded device_put (no host-side
    decide_slices split) and matches the single-context result; a batch
    that does not divide over the contexts is rejected at construction
    with the same clear error as Module.bind."""
    import jax
    from mxnet_tpu.module.executor_group import DataParallelExecutorGroup

    n_dev = min(4, jax.device_count())
    assert n_dev >= 2, "conftest sets an 8-device virtual CPU mesh"
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=4, name="fc")
    net = sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                            name="softmax")
    rs = np.random.RandomState(0)
    w = rs.uniform(-1, 1, (4, 3)).astype(np.float32)
    x = rs.uniform(-1, 1, (8, 3)).astype(np.float32)

    def run(contexts):
        g = DataParallelExecutorGroup(
            net, contexts, None, [("data", (8, 3))],
            [("softmax_label", (8,))], ["fc_weight", "fc_bias"], True,
            False)
        g.execs[0].arg_dict["fc_weight"][:] = w
        g.forward(DataBatch([nd.array(x)], [nd.zeros((8,))]),
                  is_train=False)
        return np.asarray(g.get_outputs()[0]._data)

    single = run([mx.cpu()])
    sharded = run([mx.cpu(i) for i in range(n_dev)])
    np.testing.assert_allclose(sharded, single, rtol=1e-5, atol=1e-6)

    try:
        DataParallelExecutorGroup(
            net, [mx.cpu(i) for i in range(3)], None, [("data", (8, 3))],
            [("softmax_label", (8,))], ["fc_weight", "fc_bias"], True,
            False)
    except mx.base.MXNetError as e:
        assert "not divisible" in str(e)
    else:
        raise AssertionError("expected divisibility error")
