"""General C API suite (parity model: reference include/mxnet/c_api.h as
consumed by cpp-package — NDArray create/copy/wait, imperative invoke,
symbol load + infer shape, executor bind/forward/backward)."""
import ctypes
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

REPO = os.path.join(os.path.dirname(__file__), "..")
LIB = os.path.join(REPO, "mxnet_tpu", "_lib", "libmxtpu_c_api.so")

pytestmark = pytest.mark.skipif(not os.path.exists(LIB),
                                reason="native lib not built")


def _lib():
    L = ctypes.CDLL(LIB)
    L.MXGetLastError.restype = ctypes.c_char_p
    # Explicit argtypes throughout: bare python ints (e.g. a dereferenced
    # handle `outs[0]`) otherwise marshal as 32-bit c_int, truncating
    # 64-bit pointers/size_t.
    vp, u, i = ctypes.c_void_p, ctypes.c_uint, ctypes.c_int
    P = ctypes.POINTER
    L.MXNDArrayCreateEx.argtypes = [P(u), u, i, i, i, i, P(vp)]
    L.MXNDArrayFree.argtypes = [vp]
    L.MXNDArraySyncCopyFromCPU.argtypes = [vp, vp, ctypes.c_size_t]
    L.MXNDArraySyncCopyToCPU.argtypes = [vp, vp, ctypes.c_size_t]
    L.MXNDArrayGetShape.argtypes = [vp, P(u), P(P(u))]
    L.MXNDArrayGetDType.argtypes = [vp, P(i)]
    L.MXNDArrayWaitToRead.argtypes = [vp]
    L.MXImperativeInvoke.argtypes = [vp, i, P(vp), P(i), P(P(vp)), i,
                                     P(ctypes.c_char_p),
                                     P(ctypes.c_char_p)]
    return L


def test_ndarray_roundtrip_and_invoke():
    L = _lib()
    shape = (ctypes.c_uint * 2)(2, 3)
    h = ctypes.c_void_p()
    assert L.MXNDArrayCreateEx(shape, 2, 1, 0, 0, 0, ctypes.byref(h)) == 0, \
        L.MXGetLastError()

    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    buf = (ctypes.c_float * 6)(*x.ravel())
    assert L.MXNDArraySyncCopyFromCPU(h, buf, 6) == 0, L.MXGetLastError()
    assert L.MXNDArrayWaitToRead(h) == 0

    ndim = ctypes.c_uint()
    pdata = ctypes.POINTER(ctypes.c_uint)()
    assert L.MXNDArrayGetShape(h, ctypes.byref(ndim),
                               ctypes.byref(pdata)) == 0
    assert tuple(pdata[i] for i in range(ndim.value)) == (2, 3)
    dt = ctypes.c_int()
    assert L.MXNDArrayGetDType(h, ctypes.byref(dt)) == 0 and dt.value == 0

    # imperative invoke: exp(x), op allocates outputs
    op = ctypes.c_void_p()
    assert L.NNGetOpHandle(b"exp", ctypes.byref(op)) == 0
    n_out = ctypes.c_int(0)
    outs = ctypes.POINTER(ctypes.c_void_p)()
    ins = (ctypes.c_void_p * 1)(h)
    assert L.MXImperativeInvoke(op, 1, ins, ctypes.byref(n_out),
                                ctypes.byref(outs), 0, None, None) == 0, \
        L.MXGetLastError()
    assert n_out.value == 1
    got = (ctypes.c_float * 6)()
    assert L.MXNDArraySyncCopyToCPU(outs[0], got, 6) == 0, L.MXGetLastError()
    np.testing.assert_allclose(np.array(got[:6]).reshape(2, 3), np.exp(x),
                               rtol=1e-5)
    assert L.MXNDArrayFree(outs[0]) == 0
    assert L.MXNDArrayFree(h) == 0


def test_list_op_names():
    L = _lib()
    n = ctypes.c_uint()
    arr = ctypes.POINTER(ctypes.c_char_p)()
    assert L.MXListAllOpNames(ctypes.byref(n), ctypes.byref(arr)) == 0
    names = {arr[i].decode() for i in range(n.value)}
    assert n.value > 200
    assert {"Convolution", "FullyConnected", "sgd_update"} <= names


def _save_lenet_json(tmp_path):
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                             name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, mx.sym.Variable("softmax_label"),
                               name="softmax", normalization="batch")
    path = str(tmp_path / "lenet-symbol.json")
    net.save(path)
    return path


DRIVER_SRC = r'''
// cpp-package-style LeNet training driver over the general C API.
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
typedef unsigned int mx_uint;
typedef void* NDArrayHandle;
typedef void* SymbolHandle;
typedef void* ExecutorHandle;
typedef void* AtomicSymbolCreator;
extern const char* MXGetLastError();
extern int MXNDArrayCreateEx(const mx_uint*, mx_uint, int, int, int, int,
                             NDArrayHandle*);
extern int MXNDArraySyncCopyFromCPU(NDArrayHandle, const void*, size_t);
extern int MXNDArraySyncCopyToCPU(NDArrayHandle, void*, size_t);
extern int MXNDArrayWaitAll();
extern int NNGetOpHandle(const char*, AtomicSymbolCreator*);
extern int MXImperativeInvoke(AtomicSymbolCreator, int, NDArrayHandle*,
                              int*, NDArrayHandle**, int, const char**,
                              const char**);
extern int MXSymbolCreateFromFile(const char*, SymbolHandle*);
extern int MXSymbolListArguments(SymbolHandle, mx_uint*, const char***);
extern int MXSymbolInferShape(SymbolHandle, mx_uint, const char**,
                              const mx_uint*, const mx_uint*, mx_uint*,
                              const mx_uint**, const mx_uint***, mx_uint*,
                              const mx_uint**, const mx_uint***, mx_uint*,
                              const mx_uint**, const mx_uint***, int*);
extern int MXExecutorBind(SymbolHandle, int, int, mx_uint, NDArrayHandle*,
                          NDArrayHandle*, mx_uint*, mx_uint,
                          NDArrayHandle*, ExecutorHandle*);
extern int MXExecutorForward(ExecutorHandle, int);
extern int MXExecutorBackward(ExecutorHandle, mx_uint, NDArrayHandle*);
extern int MXExecutorOutputs(ExecutorHandle, mx_uint*, NDArrayHandle**);

#define CHECK(x) do { if ((x) != 0) { \
    printf("FAIL %s: %s\n", #x, MXGetLastError()); exit(1); } } while (0)

#define B 32
static unsigned int seed = 7;
static float frand() { /* deterministic LCG in [0,1) */
    seed = seed * 1103515245u + 12345u;
    return (float)((seed >> 8) & 0xffffff) / (float)0x1000000;
}

/* synthetic separable task: class 1 iff left half brighter than right */
static void make_batch(float* x, float* y) {
    for (int b = 0; b < B; ++b) {
        int label = (b % 2);
        for (int i = 0; i < 64; ++i) {
            int col = i % 8;
            float base = frand() * 0.5f;
            if (label == 1 && col < 4) base += 0.8f;
            if (label == 0 && col >= 4) base += 0.8f;
            x[b * 64 + i] = base;
        }
        y[b] = (float)label;
    }
}

int main(int argc, char** argv) {
    SymbolHandle sym;
    CHECK(MXSymbolCreateFromFile(argv[1], &sym));

    mx_uint n_args; const char** arg_names;
    CHECK(MXSymbolListArguments(sym, &n_args, &arg_names));

    /* infer all shapes from data/label */
    const char* keys[] = {"data", "softmax_label"};
    mx_uint indptr[] = {0, 4, 5};
    mx_uint sdata[] = {B, 1, 8, 8, B};
    mx_uint in_size, out_size, aux_size;
    const mx_uint *in_ndim, *out_ndim, *aux_ndim;
    const mx_uint **in_shapes, **out_shapes, **aux_shapes;
    int complete;
    CHECK(MXSymbolInferShape(sym, 2, keys, indptr, sdata, &in_size, &in_ndim,
                             &in_shapes, &out_size, &out_ndim, &out_shapes,
                             &aux_size, &aux_ndim, &aux_shapes, &complete));
    if (!complete || in_size != n_args) { printf("FAIL infer\n"); return 1; }

    /* allocate args + grads; save copies of shapes (the pointers are
       thread-local and clobbered by later API calls) */
    NDArrayHandle args[64], grads[64];
    mx_uint reqs[64];
    long arg_elems[64];
    int data_idx = -1, label_idx = -1;
    for (mx_uint i = 0; i < n_args; ++i) {
        mx_uint shp[8];
        long n = 1;
        for (mx_uint j = 0; j < in_ndim[i]; ++j) {
            shp[j] = in_shapes[i][j];
            n *= shp[j];
        }
        arg_elems[i] = n;
        CHECK(MXNDArrayCreateEx(shp, in_ndim[i], 1, 0, 0, 0, &args[i]));
        if (strcmp(arg_names[i], "data") == 0) data_idx = (int)i;
        if (strcmp(arg_names[i], "softmax_label") == 0) label_idx = (int)i;
        int is_param = strcmp(arg_names[i], "data") != 0 &&
                       strcmp(arg_names[i], "softmax_label") != 0;
        reqs[i] = is_param ? 1 : 0;
        if (is_param) {
            CHECK(MXNDArrayCreateEx(shp, in_ndim[i], 1, 0, 0, 0, &grads[i]));
            /* xavier-ish init */
            float* w = (float*)malloc(n * sizeof(float));
            float scale = 0.35f;
            for (long k = 0; k < n; ++k) w[k] = (frand() - 0.5f) * scale;
            CHECK(MXNDArraySyncCopyFromCPU(args[i], w, (size_t)n));
            free(w);
        } else {
            grads[i] = NULL;
        }
    }
    if (data_idx < 0 || label_idx < 0) { printf("FAIL names\n"); return 1; }

    ExecutorHandle ex;
    CHECK(MXExecutorBind(sym, 1, 0, n_args, args, grads, reqs, 0, NULL, &ex));

    AtomicSymbolCreator sgd;
    CHECK(NNGetOpHandle("sgd_update", &sgd));
    const char* pk[] = {"lr"};
    const char* pv[] = {"0.2"};

    float x[B * 64], y[B];
    for (int step = 0; step < 60; ++step) {
        make_batch(x, y);
        CHECK(MXNDArraySyncCopyFromCPU(args[data_idx], x, B * 64));
        CHECK(MXNDArraySyncCopyFromCPU(args[label_idx], y, B));
        CHECK(MXExecutorForward(ex, 1));
        CHECK(MXExecutorBackward(ex, 0, NULL));
        for (mx_uint i = 0; i < n_args; ++i) {
            if (grads[i] == NULL) continue;
            NDArrayHandle ins[2]; ins[0] = args[i]; ins[1] = grads[i];
            NDArrayHandle* outs = &args[i];  /* in-place update */
            int n_out = 1;
            CHECK(MXImperativeInvoke(sgd, 2, ins, &n_out, &outs, 1, pk, pv));
        }
    }
    CHECK(MXNDArrayWaitAll());

    /* eval */
    make_batch(x, y);
    CHECK(MXNDArraySyncCopyFromCPU(args[data_idx], x, B * 64));
    CHECK(MXExecutorForward(ex, 0));
    mx_uint n_outs; NDArrayHandle* outs;
    CHECK(MXExecutorOutputs(ex, &n_outs, &outs));
    float prob[B * 2];
    CHECK(MXNDArraySyncCopyToCPU(outs[0], prob, B * 2));
    int correct = 0;
    for (int b = 0; b < B; ++b) {
        int pred = prob[b * 2 + 1] > prob[b * 2] ? 1 : 0;
        if (pred == (int)y[b]) correct++;
    }
    printf("TRAIN_OK acc=%.4f\n", (float)correct / B);
    return 0;
}
'''


def test_c_train_driver(tmp_path):
    """Compile and run a standalone C LeNet training driver — the
    cpp-package deployment story over the general C API."""
    import shutil
    cc = shutil.which("gcc") or shutil.which("cc")
    if cc is None:
        pytest.skip("no C compiler")
    json_path = _save_lenet_json(tmp_path)

    driver = tmp_path / "train_driver.c"
    driver.write_text(DRIVER_SRC)
    exe = str(tmp_path / "train_driver")
    subprocess.run([cc, str(driver), "-o", exe,
                    "-L" + os.path.dirname(LIB), "-lmxtpu_c_api",
                    "-Wl,-rpath," + os.path.dirname(LIB)], check=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.abspath(REPO) + os.pathsep + \
        env.get("PYTHONPATH", "")
    env["MXNET_TPU_FORCE_CPU"] = "1"
    p = subprocess.run([exe, json_path], capture_output=True, text=True,
                       timeout=600, env=env)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "TRAIN_OK" in p.stdout, p.stdout
    acc = float(p.stdout.split("acc=")[1].split()[0])
    assert acc > 0.8, p.stdout


# ===========================================================================
# Round-4 tranche tests (runtime knobs, NDArray extras, full symbol
# surface, SimpleBind, CachedOp, autograd, data iters, kvstore, recordio)
# ===========================================================================

def _lib2():
    L = ctypes.CDLL(LIB)
    L.MXGetLastError.restype = ctypes.c_char_p
    vp, u, i = ctypes.c_void_p, ctypes.c_uint, ctypes.c_int
    P, cp = ctypes.POINTER, ctypes.c_char_p
    L.MXNDArrayCreateEx.argtypes = [P(u), u, i, i, i, i, P(vp)]
    L.MXNDArraySyncCopyFromCPU.argtypes = [vp, vp, ctypes.c_size_t]
    L.MXNDArraySyncCopyToCPU.argtypes = [vp, vp, ctypes.c_size_t]
    L.MXNDArrayFree.argtypes = [vp]
    L.MXNDArraySlice.argtypes = [vp, u, u, P(vp)]
    L.MXNDArrayAt.argtypes = [vp, u, P(vp)]
    L.MXNDArrayReshape.argtypes = [vp, i, P(i), P(vp)]
    L.MXNDArrayGetContext.argtypes = [vp, P(i), P(i)]
    L.MXNDArrayGetStorageType.argtypes = [vp, P(i)]
    L.MXNDArraySaveRawBytes.argtypes = [vp, P(ctypes.c_size_t), P(vp)]
    L.MXNDArrayLoadFromRawBytes.argtypes = [vp, ctypes.c_size_t, P(vp)]
    L.MXNDArrayGetShape.argtypes = [vp, P(u), P(P(u))]
    L.MXNDArraySyncCopyFromNDArray.argtypes = [vp, vp, i]
    L.MXNDArrayGetGrad.argtypes = [vp, P(vp)]
    L.MXRecordIOWriterWriteRecord.argtypes = [vp, cp, ctypes.c_size_t]
    L.MXRecordIOReaderSeek.argtypes = [vp, ctypes.c_size_t]
    L.MXKVStoreSetUpdater.argtypes = [vp, vp, vp]
    L.MXSymbolSaveToJSON.argtypes = [vp, P(cp)]
    L.MXSymbolGetName.argtypes = [vp, P(cp), P(i)]
    L.MXSymbolGetAttr.argtypes = [vp, cp, P(cp), P(i)]
    L.MXSymbolSetAttr.argtypes = [vp, cp, cp]
    L.MXKVStoreGetType.argtypes = [vp, P(cp)]
    L.MXExecutorPrint.argtypes = [vp, P(cp)]
    # handle values dereferenced from arrays arrive as bare ints — these
    # MUST have argtypes or the pointer truncates to 32 bits
    L.MXSymbolListAtomicSymbolCreators.argtypes = [P(u), P(P(vp))]
    L.MXSymbolGetAtomicSymbolName.argtypes = [vp, P(cp)]
    L.MXSymbolGetAtomicSymbolInfo.argtypes = [vp, P(cp), P(cp), P(u),
                                              P(P(cp)), P(P(cp)), P(P(cp)),
                                              P(cp), P(cp)]
    L.MXListDataIters.argtypes = [P(u), P(P(vp))]
    L.MXDataIterGetIterInfo.argtypes = [vp, P(cp), P(cp), P(u), P(P(cp)),
                                        P(P(cp)), P(P(cp))]
    L.MXDataIterCreateIter.argtypes = [vp, u, P(cp), P(cp), P(vp)]
    L.MXInvokeCachedOp.argtypes = [vp, i, P(vp), P(i), P(P(vp))]
    L.MXImperativeInvoke.argtypes = [vp, i, P(vp), P(i), P(P(vp)), i,
                                     P(cp), P(cp)]
    return L


def test_runtime_knobs():
    L = _lib2()
    v = ctypes.c_int()
    assert L.MXGetVersion(ctypes.byref(v)) == 0 and v.value == 1201
    assert L.MXRandomSeed(42) == 0
    prev = ctypes.c_int(-1)
    assert L.MXEngineSetBulkSize(16, ctypes.byref(prev)) == 0
    assert prev.value >= 0
    assert L.MXSetNumOMPThreads(2) == 0
    worker = ctypes.c_int()
    assert L.MXKVStoreIsWorkerNode(ctypes.byref(worker)) == 0
    assert worker.value == 1


def _make_nd(L, arr):
    shape = (ctypes.c_uint * arr.ndim)(*arr.shape)
    h = ctypes.c_void_p()
    assert L.MXNDArrayCreateEx(shape, arr.ndim, 1, 0, 0, 0,
                               ctypes.byref(h)) == 0, L.MXGetLastError()
    buf = (ctypes.c_float * arr.size)(*arr.ravel())
    assert L.MXNDArraySyncCopyFromCPU(h, buf, arr.size) == 0
    return h


def _read_nd(L, h, n):
    got = (ctypes.c_float * n)()
    assert L.MXNDArraySyncCopyToCPU(h, got, n) == 0, L.MXGetLastError()
    return np.array(got[:n])


def test_ndarray_extras():
    L = _lib2()
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    h = _make_nd(L, x)

    s = ctypes.c_void_p()
    assert L.MXNDArraySlice(h, 1, 3, ctypes.byref(s)) == 0
    np.testing.assert_allclose(_read_nd(L, s, 8), x[1:3].ravel())

    a = ctypes.c_void_p()
    assert L.MXNDArrayAt(h, 2, ctypes.byref(a)) == 0
    np.testing.assert_allclose(_read_nd(L, a, 4), x[2])

    dims = (ctypes.c_int * 2)(4, 3)
    r = ctypes.c_void_p()
    assert L.MXNDArrayReshape(h, 2, dims, ctypes.byref(r)) == 0
    ndim = ctypes.c_uint()
    pdata = ctypes.POINTER(ctypes.c_uint)()
    assert L.MXNDArrayGetShape(r, ctypes.byref(ndim), ctypes.byref(pdata)) == 0
    assert tuple(pdata[i] for i in range(ndim.value)) == (4, 3)

    dev_type, dev_id = ctypes.c_int(), ctypes.c_int()
    assert L.MXNDArrayGetContext(h, ctypes.byref(dev_type),
                                 ctypes.byref(dev_id)) == 0
    assert dev_type.value == 1 and dev_id.value == 0
    st = ctypes.c_int(-2)
    assert L.MXNDArrayGetStorageType(h, ctypes.byref(st)) == 0
    assert st.value == 0  # kDefaultStorage

    # raw-bytes roundtrip
    size = ctypes.c_size_t()
    buf = ctypes.c_void_p()
    assert L.MXNDArraySaveRawBytes(h, ctypes.byref(size),
                                   ctypes.byref(buf)) == 0
    h2 = ctypes.c_void_p()
    assert L.MXNDArrayLoadFromRawBytes(buf, size.value,
                                       ctypes.byref(h2)) == 0, \
        L.MXGetLastError()
    np.testing.assert_allclose(_read_nd(L, h2, 12), x.ravel())

    # none + copy-from-ndarray
    none_h = ctypes.c_void_p()
    assert L.MXNDArrayCreateNone(ctypes.byref(none_h)) == 0
    assert L.MXNDArraySyncCopyFromNDArray(none_h, h, -1) == 0
    np.testing.assert_allclose(_read_nd(L, none_h, 12), x.ravel())

    for hh in (h, s, a, r, h2, none_h):
        assert L.MXNDArrayFree(hh) == 0


def test_symbol_surface_and_compose():
    L = _lib2()
    # variable + atomic symbol + compose
    data = ctypes.c_void_p()
    assert L.MXSymbolCreateVariable(b"data", ctypes.byref(data)) == 0
    op = ctypes.c_void_p()
    assert L.NNGetOpHandle(b"FullyConnected", ctypes.byref(op)) == 0
    keys = (ctypes.c_char_p * 1)(b"num_hidden")
    vals = (ctypes.c_char_p * 1)(b"8")
    fc = ctypes.c_void_p()
    assert L.MXSymbolCreateAtomicSymbol(op, 1, keys, vals,
                                        ctypes.byref(fc)) == 0, \
        L.MXGetLastError()
    args = (ctypes.c_void_p * 1)(data)
    assert L.MXSymbolCompose(fc, b"fc1", 1, None, args) == 0, \
        L.MXGetLastError()

    n = ctypes.c_uint()
    arr = ctypes.POINTER(ctypes.c_char_p)()
    assert L.MXSymbolListArguments(fc, ctypes.byref(n), ctypes.byref(arr)) == 0
    assert [arr[i].decode() for i in range(n.value)] == \
        ["data", "fc1_weight", "fc1_bias"]

    name = ctypes.c_char_p()
    ok = ctypes.c_int()
    assert L.MXSymbolGetName(fc, ctypes.byref(name), ctypes.byref(ok)) == 0
    assert ok.value == 1 and name.value == b"fc1"

    # attrs
    assert L.MXSymbolSetAttr(fc, b"lr_mult", b"2.0") == 0
    got = ctypes.c_char_p()
    assert L.MXSymbolGetAttr(fc, b"lr_mult", ctypes.byref(got),
                             ctypes.byref(ok)) == 0
    assert ok.value == 1 and got.value == b"2.0"

    # json roundtrip + copy + internals/output
    js = ctypes.c_char_p()
    assert L.MXSymbolSaveToJSON(fc, ctypes.byref(js)) == 0
    h2 = ctypes.c_void_p()
    assert L.MXSymbolCreateFromJSON(js.value, ctypes.byref(h2)) == 0, \
        L.MXGetLastError()
    cp = ctypes.c_void_p()
    assert L.MXSymbolCopy(fc, ctypes.byref(cp)) == 0
    internals = ctypes.c_void_p()
    assert L.MXSymbolGetInternals(fc, ctypes.byref(internals)) == 0
    out0 = ctypes.c_void_p()
    assert L.MXSymbolGetOutput(internals, 0, ctypes.byref(out0)) == 0
    children = ctypes.c_void_p()
    assert L.MXSymbolGetChildren(fc, ctypes.byref(children)) == 0
    assert children.value is not None

    # infer type: float32 in -> float32 out
    tk = (ctypes.c_char_p * 1)(b"data")
    tc = (ctypes.c_int * 1)(0)
    in_n, out_n, aux_n = ctypes.c_uint(), ctypes.c_uint(), ctypes.c_uint()
    in_t = ctypes.POINTER(ctypes.c_int)()
    out_t = ctypes.POINTER(ctypes.c_int)()
    aux_t = ctypes.POINTER(ctypes.c_int)()
    comp = ctypes.c_int()
    assert L.MXSymbolInferType(fc, 1, tk, tc, ctypes.byref(in_n),
                               ctypes.byref(in_t), ctypes.byref(out_n),
                               ctypes.byref(out_t), ctypes.byref(aux_n),
                               ctypes.byref(aux_t), ctypes.byref(comp)) == 0
    assert comp.value == 1 and out_t[0] == 0

    for h in (data, fc, h2, cp, internals, out0, children):
        L.MXSymbolFree(h)


def test_atomic_symbol_info():
    L = _lib2()
    n = ctypes.c_uint()
    creators = ctypes.POINTER(ctypes.c_void_p)()
    assert L.MXSymbolListAtomicSymbolCreators(ctypes.byref(n),
                                              ctypes.byref(creators)) == 0
    assert n.value > 200
    name = ctypes.c_char_p()
    assert L.MXSymbolGetAtomicSymbolName(creators[0],
                                         ctypes.byref(name)) == 0
    assert len(name.value) > 0

    op = ctypes.c_void_p()
    assert L.NNGetOpHandle(b"Convolution", ctypes.byref(op)) == 0
    desc = ctypes.c_char_p()
    num_args = ctypes.c_uint()
    an = ctypes.POINTER(ctypes.c_char_p)()
    at = ctypes.POINTER(ctypes.c_char_p)()
    ad = ctypes.POINTER(ctypes.c_char_p)()
    kv = ctypes.c_char_p()
    rt = ctypes.c_char_p()
    assert L.MXSymbolGetAtomicSymbolInfo(
        op, ctypes.byref(name), ctypes.byref(desc), ctypes.byref(num_args),
        ctypes.byref(an), ctypes.byref(at), ctypes.byref(ad),
        ctypes.byref(kv), ctypes.byref(rt)) == 0
    assert name.value == b"Convolution"
    names = [an[i].decode() for i in range(num_args.value)]
    assert "data" in names and "weight" in names


def test_simple_bind_forward_backward():
    L = _lib2()
    sym = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                                name="fc")
    js = sym.tojson().encode()
    h = ctypes.c_void_p()
    assert L.MXSymbolCreateFromJSON(js, ctypes.byref(h)) == 0

    # simple bind: data shape provided, grad_req write for all
    shape_names = (ctypes.c_char_p * 1)(b"data")
    shape_idx = (ctypes.c_uint * 2)(0, 2)
    shape_data = (ctypes.c_uint * 2)(5, 3)
    req_types = (ctypes.c_char_p * 1)(b"write")
    num_in = ctypes.c_uint()
    in_args = ctypes.POINTER(ctypes.c_void_p)()
    arg_grads = ctypes.POINTER(ctypes.c_void_p)()
    num_aux = ctypes.c_uint()
    aux = ctypes.POINTER(ctypes.c_void_p)()
    ex = ctypes.c_void_p()
    shared_len = ctypes.c_int(-1)
    assert L.MXExecutorSimpleBind(
        h, 1, 0,
        0, None, None, None,            # group2ctx
        1, None, req_types,             # grad reqs (global "write")
        1, shape_names, shape_data, shape_idx,
        0, None, None,                  # dtypes
        0, None, None,                  # stypes
        0, None,                        # shared arg names
        ctypes.byref(shared_len), None, None, None, None,
        ctypes.byref(num_in), ctypes.byref(in_args), ctypes.byref(arg_grads),
        ctypes.byref(num_aux), ctypes.byref(aux),
        None, ctypes.byref(ex)) == 0, L.MXGetLastError()
    assert num_in.value == 3  # data, fc_weight, fc_bias
    assert in_args[0] is not None and arg_grads[0] is not None

    # seed inputs, forward, backward
    x = np.random.RandomState(0).rand(5, 3).astype(np.float32)
    buf = (ctypes.c_float * x.size)(*x.ravel())
    assert L.MXNDArraySyncCopyFromCPU(ctypes.c_void_p(in_args[0]), buf,
                                      x.size) == 0
    assert L.MXExecutorForward(ex, 1) == 0
    n_outs = ctypes.c_uint()
    outs = ctypes.POINTER(ctypes.c_void_p)()
    assert L.MXExecutorOutputs(ex, ctypes.byref(n_outs),
                               ctypes.byref(outs)) == 0
    assert n_outs.value == 1
    og = _make_nd(L, np.ones((5, 4), np.float32))
    heads = (ctypes.c_void_p * 1)(og)
    assert L.MXExecutorBackwardEx(ex, 1, heads, 1) == 0, L.MXGetLastError()
    s = ctypes.c_char_p()
    assert L.MXExecutorPrint(ex, ctypes.byref(s)) == 0
    assert b"Executor" in s.value
    L.MXExecutorFree(ex)
    L.MXSymbolFree(h)


def test_cached_op():
    L = _lib2()
    sym = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2,
                                name="fc")
    h = ctypes.c_void_p()
    assert L.MXSymbolCreateFromJSON(sym.tojson().encode(),
                                    ctypes.byref(h)) == 0
    cop = ctypes.c_void_p()
    assert L.MXCreateCachedOp(h, ctypes.byref(cop)) == 0, L.MXGetLastError()
    rs = np.random.RandomState(1)
    x = rs.rand(3, 4).astype(np.float32)
    w = rs.rand(2, 4).astype(np.float32)
    b = np.zeros(2, np.float32)
    ins = (ctypes.c_void_p * 3)(_make_nd(L, x), _make_nd(L, w),
                                _make_nd(L, b))
    n_out = ctypes.c_int(0)
    outs = ctypes.POINTER(ctypes.c_void_p)()
    assert L.MXInvokeCachedOp(cop, 3, ins, ctypes.byref(n_out),
                              ctypes.byref(outs)) == 0, L.MXGetLastError()
    assert n_out.value == 1
    np.testing.assert_allclose(_read_nd(L, outs[0], 6).reshape(3, 2),
                               x @ w.T, rtol=1e-5)
    assert L.MXFreeCachedOp(cop) == 0
    L.MXSymbolFree(h)


def test_autograd_c_api():
    L = _lib2()
    x = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    h = _make_nd(L, x)
    g = _make_nd(L, np.zeros_like(x))
    vars_ = (ctypes.c_void_p * 1)(h)
    reqs = (ctypes.c_uint * 1)(1)  # write
    grads = (ctypes.c_void_p * 1)(g)
    assert L.MXAutogradMarkVariables(1, vars_, reqs, grads) == 0, \
        L.MXGetLastError()
    prev = ctypes.c_int(-1)
    assert L.MXAutogradSetIsRecording(1, ctypes.byref(prev)) == 0
    assert L.MXAutogradSetIsTraining(1, ctypes.byref(prev)) == 0
    rec = ctypes.c_bool(False)
    assert L.MXAutogradIsRecording(ctypes.byref(rec)) == 0
    assert rec.value

    op = ctypes.c_void_p()
    assert L.NNGetOpHandle(b"square", ctypes.byref(op)) == 0
    ins = (ctypes.c_void_p * 1)(h)
    n_out = ctypes.c_int(0)
    outs = ctypes.POINTER(ctypes.c_void_p)()
    assert L.MXImperativeInvoke(op, 1, ins, ctypes.byref(n_out),
                                ctypes.byref(outs), 0, None, None) == 0
    y = ctypes.c_void_p(outs[0])
    out_handles = (ctypes.c_void_p * 1)(y)
    assert L.MXAutogradBackward(1, out_handles, None, 0) == 0, \
        L.MXGetLastError()
    assert L.MXAutogradSetIsRecording(0, ctypes.byref(prev)) == 0
    assert L.MXAutogradSetIsTraining(0, ctypes.byref(prev)) == 0
    np.testing.assert_allclose(_read_nd(L, g, 4).reshape(2, 2), 2 * x)

    gh = ctypes.c_void_p()
    assert L.MXNDArrayGetGrad(h, ctypes.byref(gh)) == 0
    assert gh.value is not None
    for hh in (h, g, y, gh):
        L.MXNDArrayFree(hh)


def test_data_iter_c_api(tmp_path):
    L = _lib2()
    n = ctypes.c_uint()
    creators = ctypes.POINTER(ctypes.c_void_p)()
    assert L.MXListDataIters(ctypes.byref(n), ctypes.byref(creators)) == 0
    names = {}
    for i in range(n.value):
        nm = ctypes.c_char_p()
        assert L.MXSymbolGetAtomicSymbolName(creators[i],
                                             ctypes.byref(nm)) == 0
        names[nm.value.decode()] = creators[i]
    assert "CSVIter" in names and "MNISTIter" in names

    # iter info
    nm = ctypes.c_char_p()
    desc = ctypes.c_char_p()
    num_args = ctypes.c_uint()
    an = ctypes.POINTER(ctypes.c_char_p)()
    at = ctypes.POINTER(ctypes.c_char_p)()
    ad = ctypes.POINTER(ctypes.c_char_p)()
    assert L.MXDataIterGetIterInfo(names["CSVIter"], ctypes.byref(nm),
                                   ctypes.byref(desc), ctypes.byref(num_args),
                                   ctypes.byref(an), ctypes.byref(at),
                                   ctypes.byref(ad)) == 0
    assert nm.value == b"CSVIter"

    # create + drain a CSVIter over a small file
    data = np.arange(24, dtype=np.float32).reshape(6, 4)
    csv = tmp_path / "d.csv"
    np.savetxt(str(csv), data, delimiter=",", fmt="%.1f")
    keys = (ctypes.c_char_p * 3)(b"data_csv", b"data_shape", b"batch_size")
    vals = (ctypes.c_char_p * 3)(str(csv).encode(), b"(4,)", b"2")
    it = ctypes.c_void_p()
    assert L.MXDataIterCreateIter(names["CSVIter"], 3, keys, vals,
                                  ctypes.byref(it)) == 0, L.MXGetLastError()
    seen = 0
    has = ctypes.c_int(1)
    while True:
        assert L.MXDataIterNext(it, ctypes.byref(has)) == 0
        if not has.value:
            break
        d = ctypes.c_void_p()
        assert L.MXDataIterGetData(it, ctypes.byref(d)) == 0
        vals_np = _read_nd(L, d, 8).reshape(2, 4)
        np.testing.assert_allclose(vals_np, data[seen * 2:(seen + 1) * 2])
        pad = ctypes.c_int(-1)
        assert L.MXDataIterGetPadNum(it, ctypes.byref(pad)) == 0
        assert pad.value == 0
        L.MXNDArrayFree(d)
        seen += 1
    assert seen == 3
    assert L.MXDataIterBeforeFirst(it) == 0
    assert L.MXDataIterNext(it, ctypes.byref(has)) == 0 and has.value == 1
    assert L.MXDataIterFree(it) == 0


def test_kvstore_c_api():
    L = _lib2()
    kv = ctypes.c_void_p()
    assert L.MXKVStoreCreate(b"local", ctypes.byref(kv)) == 0
    t = ctypes.c_char_p()
    assert L.MXKVStoreGetType(kv, ctypes.byref(t)) == 0
    assert t.value == b"local"
    r = ctypes.c_int(-1)
    assert L.MXKVStoreGetRank(kv, ctypes.byref(r)) == 0 and r.value == 0
    assert L.MXKVStoreGetGroupSize(kv, ctypes.byref(r)) == 0 and r.value == 1

    init_v = _make_nd(L, np.zeros((2, 2), np.float32))
    keys = (ctypes.c_int * 1)(7)
    vals = (ctypes.c_void_p * 1)(init_v)
    assert L.MXKVStoreInit(kv, 1, keys, vals) == 0, L.MXGetLastError()

    push_v = _make_nd(L, np.full((2, 2), 3.0, np.float32))
    vals2 = (ctypes.c_void_p * 1)(push_v)
    assert L.MXKVStorePush(kv, 1, keys, vals2, 0) == 0, L.MXGetLastError()

    out_v = _make_nd(L, np.zeros((2, 2), np.float32))
    vals3 = (ctypes.c_void_p * 1)(out_v)
    assert L.MXKVStorePull(kv, 1, keys, vals3, 0) == 0, L.MXGetLastError()
    np.testing.assert_allclose(_read_nd(L, out_v, 4), 3.0)

    # C-callback updater: new = local - 0.5 * recv
    calls = []
    CB = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_void_p,
                          ctypes.c_void_p, ctypes.c_void_p)

    def updater(key, recv, local, handle):
        calls.append(key)
        rbuf = (ctypes.c_float * 4)()
        lbuf = (ctypes.c_float * 4)()
        assert L.MXNDArraySyncCopyToCPU(recv, rbuf, 4) == 0
        assert L.MXNDArraySyncCopyToCPU(local, lbuf, 4) == 0
        new = (ctypes.c_float * 4)(*[lbuf[i] - 0.5 * rbuf[i]
                                     for i in range(4)])
        assert L.MXNDArraySyncCopyFromCPU(local, new, 4) == 0
        L.MXNDArrayFree(recv)
        L.MXNDArrayFree(local)

    cb = CB(updater)
    assert L.MXKVStoreSetUpdater(kv, ctypes.cast(cb, ctypes.c_void_p),
                                 None) == 0, L.MXGetLastError()
    assert L.MXKVStorePush(kv, 1, keys, vals2, 0) == 0, L.MXGetLastError()
    assert calls == [7]
    assert L.MXKVStorePull(kv, 1, keys, vals3, 0) == 0
    np.testing.assert_allclose(_read_nd(L, out_v, 4), 3.0 - 1.5)

    assert L.MXKVStoreBarrier(kv) == 0
    assert L.MXKVStoreSetBarrierBeforeExit(kv, 1) == 0
    dead = ctypes.c_int(-1)
    assert L.MXKVStoreGetNumDeadNode(kv, 0, ctypes.byref(dead), 60) == 0
    assert dead.value == 0
    assert L.MXKVStoreFree(kv) == 0
    for hh in (init_v, push_v, out_v):
        L.MXNDArrayFree(hh)


def test_recordio_c_api(tmp_path):
    L = _lib2()
    path = str(tmp_path / "c.rec").encode()
    w = ctypes.c_void_p()
    assert L.MXRecordIOWriterCreate(path, ctypes.byref(w)) == 0, \
        L.MXGetLastError()
    for payload in (b"first-record", b"second"):
        assert L.MXRecordIOWriterWriteRecord(w, payload, len(payload)) == 0
    pos = ctypes.c_size_t()
    assert L.MXRecordIOWriterTell(w, ctypes.byref(pos)) == 0
    assert pos.value > 0
    assert L.MXRecordIOWriterFree(w) == 0

    r = ctypes.c_void_p()
    assert L.MXRecordIOReaderCreate(path, ctypes.byref(r)) == 0
    buf = ctypes.c_char_p()
    size = ctypes.c_size_t()
    assert L.MXRecordIOReaderReadRecord(r, ctypes.byref(buf),
                                        ctypes.byref(size)) == 0
    assert ctypes.string_at(buf, size.value) == b"first-record"
    assert L.MXRecordIOReaderReadRecord(r, ctypes.byref(buf),
                                        ctypes.byref(size)) == 0
    assert ctypes.string_at(buf, size.value) == b"second"
    assert L.MXRecordIOReaderReadRecord(r, ctypes.byref(buf),
                                        ctypes.byref(size)) == 0
    assert size.value == 0  # EOF
    assert L.MXRecordIOReaderFree(r) == 0


def test_kvstore_str_updater_ex():
    """MXKVStoreSetUpdaterEx installs BOTH key forms; string-key pushes
    route to the str updater (reference MXKVStoreStrUpdater contract)."""
    L = _lib2()
    L.MXKVStoreSetUpdaterEx.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                        ctypes.c_void_p, ctypes.c_void_p]
    kv = ctypes.c_void_p()
    assert L.MXKVStoreCreate(b"local", ctypes.byref(kv)) == 0
    init_v = _make_nd(L, np.zeros((2,), np.float32))
    keys = (ctypes.c_char_p * 1)(b"weight")
    vals = (ctypes.c_void_p * 1)(init_v)
    assert L.MXKVStoreInitEx(kv, 1, keys, vals) == 0, L.MXGetLastError()

    got_keys = []
    ICB = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_void_p,
                           ctypes.c_void_p, ctypes.c_void_p)
    SCB = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_void_p,
                           ctypes.c_void_p, ctypes.c_void_p)

    def int_updater(key, recv, local, handle):
        got_keys.append(key)
        L.MXNDArrayFree(recv)
        L.MXNDArrayFree(local)

    def str_updater(key, recv, local, handle):
        got_keys.append(key)
        buf = (ctypes.c_float * 2)()
        assert L.MXNDArraySyncCopyToCPU(recv, buf, 2) == 0
        assert L.MXNDArraySyncCopyFromCPU(local, buf, 2) == 0
        L.MXNDArrayFree(recv)
        L.MXNDArrayFree(local)

    icb, scb = ICB(int_updater), SCB(str_updater)
    assert L.MXKVStoreSetUpdaterEx(kv, ctypes.cast(icb, ctypes.c_void_p),
                                   ctypes.cast(scb, ctypes.c_void_p),
                                   None) == 0, L.MXGetLastError()
    push_v = _make_nd(L, np.array([1.5, 2.5], np.float32))
    vals2 = (ctypes.c_void_p * 1)(push_v)
    assert L.MXKVStorePushEx(kv, 1, keys, vals2, 0) == 0, L.MXGetLastError()
    assert got_keys == [b"weight"]
    out_v = _make_nd(L, np.zeros((2,), np.float32))
    vals3 = (ctypes.c_void_p * 1)(out_v)
    assert L.MXKVStorePullEx(kv, 1, keys, vals3, 0) == 0
    np.testing.assert_allclose(_read_nd(L, out_v, 2), [1.5, 2.5])
    assert L.MXKVStoreFree(kv) == 0


# ===========================================================================
# Final tranche: sparse ABI, legacy MXFunc*, BindX, monitor callback,
# RTC, shared-mem transport, Ex invoke variants
# ===========================================================================

def _lib3():
    L = _lib2()
    vp, u, i = ctypes.c_void_p, ctypes.c_uint, ctypes.c_int
    P, cp = ctypes.POINTER, ctypes.c_char_p
    L.MXNDArrayCreateSparseEx.argtypes = [i, P(u), u, i, i, i, i, u, P(i),
                                          P(u), P(u), P(vp)]
    L.MXNDArrayGetAuxType.argtypes = [vp, u, P(i)]
    L.MXNDArrayGetAuxNDArray.argtypes = [vp, u, P(vp)]
    L.MXNDArrayGetDataNDArray.argtypes = [vp, P(vp)]
    L.MXNDArraySyncCheckFormat.argtypes = [vp, ctypes.c_bool]
    L.MXNDArrayGetData.argtypes = [vp, P(vp)]
    L.MXGetFunction.argtypes = [cp, P(vp)]
    L.MXFuncDescribe.argtypes = [vp, P(u), P(u), P(u), P(i)]
    L.MXFuncGetInfo.argtypes = [vp, P(cp), P(cp), P(u), P(P(cp)),
                                P(P(cp)), P(P(cp)), P(cp)]
    L.MXFuncInvoke.argtypes = [vp, P(vp), P(ctypes.c_float), P(vp)]
    L.MXExecutorSetMonitorCallback.argtypes = [vp, vp, vp]
    L.MXRtcCudaModuleCreate.argtypes = [cp, i, P(cp), i, P(cp), P(vp)]
    L.MXRtcCudaKernelCreate.argtypes = [vp, cp, i, P(i), P(i), P(i), P(vp)]
    L.MXRtcCudaKernelCall.argtypes = [vp, i, P(vp), u, u, u, u, u, u, u]
    L.MXNDArrayGetSharedMemHandle.argtypes = [vp, P(i), P(i)]
    L.MXNDArrayCreateFromSharedMem.argtypes = [i, i, P(u), u, i, P(vp)]
    L.MXCustomOpRegister.argtypes = [cp, vp]
    return L


def test_sparse_ndarray_c_api():
    L = _lib3()
    shape = (ctypes.c_uint * 2)(4, 3)
    aux_t = (ctypes.c_int * 2)(6, 6)
    h = ctypes.c_void_p()
    assert L.MXNDArrayCreateSparseEx(2, shape, 2, 1, 0, 0, 0, 2, aux_t,
                                     None, None, ctypes.byref(h)) == 0, \
        L.MXGetLastError()
    st = ctypes.c_int(-1)
    assert L.MXNDArrayGetStorageType(h, ctypes.byref(st)) == 0
    assert st.value == 2  # kCSRStorage
    assert L.MXNDArraySyncCheckFormat(h, True) == 0, L.MXGetLastError()

    # cast a dense array to csr through the imperative ABI, then read
    # its aux/data arrays back out
    dense = np.array([[0, 1, 0], [2, 0, 3], [0, 0, 0], [4, 0, 0]],
                     np.float32)
    dh = _make_nd(L, dense)
    op = ctypes.c_void_p()
    assert L.NNGetOpHandle(b"cast_storage", ctypes.byref(op)) == 0
    n_out = ctypes.c_int(0)
    outs = ctypes.POINTER(ctypes.c_void_p)()
    ins = (ctypes.c_void_p * 1)(dh)
    keys = (ctypes.c_char_p * 1)(b"stype")
    vals = (ctypes.c_char_p * 1)(b"csr")
    assert L.MXImperativeInvoke(op, 1, ins, ctypes.byref(n_out),
                                ctypes.byref(outs), 1, keys, vals) == 0, \
        L.MXGetLastError()
    csr = ctypes.c_void_p(outs[0])
    assert L.MXNDArrayGetStorageType(csr, ctypes.byref(st)) == 0
    assert st.value == 2
    assert L.MXNDArraySyncCheckFormat(csr, True) == 0, L.MXGetLastError()

    data_nd = ctypes.c_void_p()
    assert L.MXNDArrayGetDataNDArray(csr, ctypes.byref(data_nd)) == 0
    np.testing.assert_allclose(_read_nd(L, data_nd, 4), [1, 2, 3, 4])
    aux_nd = ctypes.c_void_p()
    assert L.MXNDArrayGetAuxNDArray(csr, 0, ctypes.byref(aux_nd)) == 0
    ndim = ctypes.c_uint()
    pdata = ctypes.POINTER(ctypes.c_uint)()
    assert L.MXNDArrayGetShape(aux_nd, ctypes.byref(ndim),
                               ctypes.byref(pdata)) == 0
    assert pdata[0] == 5  # indptr has nrows+1 entries
    t = ctypes.c_int(-1)
    assert L.MXNDArrayGetAuxType(csr, 0, ctypes.byref(t)) == 0
    assert t.value in (4, 6)  # int32/int64
    for hh in (h, dh, csr, data_nd, aux_nd):
        L.MXNDArrayFree(hh)


def test_ndarray_get_data_pointer():
    L = _lib3()
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    h = _make_nd(L, x)
    ptr = ctypes.c_void_p()
    assert L.MXNDArrayGetData(h, ctypes.byref(ptr)) == 0, L.MXGetLastError()
    view = np.ctypeslib.as_array(
        ctypes.cast(ptr, ctypes.POINTER(ctypes.c_float)), shape=(6,))
    np.testing.assert_allclose(view, x.ravel())
    L.MXNDArrayFree(h)


def test_legacy_function_api():
    L = _lib3()
    n = ctypes.c_uint()
    funcs = ctypes.POINTER(ctypes.c_void_p)()
    assert L.MXListFunctions(ctypes.byref(n), ctypes.byref(funcs)) == 0
    assert n.value > 200

    f = ctypes.c_void_p()
    assert L.MXGetFunction(b"sgd_update", ctypes.byref(f)) == 0
    nu, ns, nm = ctypes.c_uint(), ctypes.c_uint(), ctypes.c_uint()
    mask = ctypes.c_int()
    assert L.MXFuncDescribe(f, ctypes.byref(nu), ctypes.byref(ns),
                            ctypes.byref(nm), ctypes.byref(mask)) == 0
    assert nu.value == 1 and nm.value == 1  # grad in, weight in/out

    name = ctypes.c_char_p()
    desc = ctypes.c_char_p()
    na = ctypes.c_uint()
    an = ctypes.POINTER(ctypes.c_char_p)()
    at = ctypes.POINTER(ctypes.c_char_p)()
    ad = ctypes.POINTER(ctypes.c_char_p)()
    rt = ctypes.c_char_p()
    assert L.MXFuncGetInfo(f, ctypes.byref(name), ctypes.byref(desc),
                           ctypes.byref(na), ctypes.byref(an),
                           ctypes.byref(at), ctypes.byref(ad),
                           ctypes.byref(rt)) == 0
    scalar_names = [an[i].decode() for i in range(na.value)]
    assert "lr" in scalar_names

    # invoke: w -= lr * g with lr read from the scalar slot
    w = _make_nd(L, np.ones(4, np.float32))
    g = _make_nd(L, np.full(4, 0.5, np.float32))
    scalars = (ctypes.c_float * na.value)()
    for i, s in enumerate(scalar_names):
        scalars[i] = {"lr": 0.2, "rescale_grad": 1.0, "wd": 0.0,
                      "clip_gradient": -1.0}.get(s, 0.0)
    use = (ctypes.c_void_p * 1)(g)
    mut = (ctypes.c_void_p * 1)(w)
    assert L.MXFuncInvoke(f, use, scalars, mut) == 0, L.MXGetLastError()
    np.testing.assert_allclose(_read_nd(L, w, 4), 0.9, rtol=1e-6)
    for hh in (w, g):
        L.MXNDArrayFree(hh)


def test_executor_bindx_and_monitor():
    L = _lib3()
    sym = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=3,
                                name="fc")
    h = ctypes.c_void_p()
    assert L.MXSymbolCreateFromJSON(sym.tojson().encode(),
                                    ctypes.byref(h)) == 0
    rs = np.random.RandomState(0)
    args = [_make_nd(L, rs.rand(2, 4).astype(np.float32)),
            _make_nd(L, rs.rand(3, 4).astype(np.float32)),
            _make_nd(L, np.zeros(3, np.float32))]
    arr = (ctypes.c_void_p * 3)(*args)
    grads = (ctypes.c_void_p * 3)(None, None, None)
    reqs = (ctypes.c_uint * 3)(0, 0, 0)
    ex = ctypes.c_void_p()
    assert L.MXExecutorBindEX(h, 1, 0, 0, None, None, None, 3, arr, grads,
                              reqs, 0, None, None, ctypes.byref(ex)) == 0, \
        L.MXGetLastError()

    seen = []
    CB = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_void_p,
                          ctypes.c_void_p)

    def monitor(nm, arr_h, _):
        seen.append(nm.decode())
        L.MXNDArrayFree(arr_h)

    cb = CB(monitor)
    assert L.MXExecutorSetMonitorCallback(
        ex, ctypes.cast(cb, ctypes.c_void_p), None) == 0
    assert L.MXExecutorForward(ex, 0) == 0
    assert seen, "monitor callback never fired"
    L.MXExecutorFree(ex)
    L.MXSymbolFree(h)
    for a in args:
        L.MXNDArrayFree(a)


def test_rtc_cuda_module_c_api():
    L = _lib3()
    src = b"import jax.numpy as jnp\n" \
          b"def axpy(alpha, x, y):\n" \
          b"    return y + alpha * x\n"
    exports = (ctypes.c_char_p * 1)(b"axpy")
    mod = ctypes.c_void_p()
    assert L.MXRtcCudaModuleCreate(src, 0, None, 1, exports,
                                   ctypes.byref(mod)) == 0, \
        L.MXGetLastError()
    is_nd = (ctypes.c_int * 3)(0, 1, 1)
    is_const = (ctypes.c_int * 3)(0, 1, 0)
    types = (ctypes.c_int * 3)(0, 0, 0)  # float
    k = ctypes.c_void_p()
    assert L.MXRtcCudaKernelCreate(mod, b"axpy", 3, is_nd, is_const, types,
                                   ctypes.byref(k)) == 0, L.MXGetLastError()
    x = _make_nd(L, np.ones(4, np.float32))
    y = _make_nd(L, np.full(4, 2.0, np.float32))
    alpha = ctypes.c_float(3.0)
    call_args = (ctypes.c_void_p * 3)(
        ctypes.cast(ctypes.byref(alpha), ctypes.c_void_p), x, y)
    assert L.MXRtcCudaKernelCall(k, 0, call_args, 1, 1, 1, 4, 1, 1, 0) \
        == 0, L.MXGetLastError()
    np.testing.assert_allclose(_read_nd(L, y, 4), 5.0)
    assert L.MXRtcCudaKernelFree(k) == 0
    assert L.MXRtcCudaModuleFree(mod) == 0
    for hh in (x, y):
        L.MXNDArrayFree(hh)


def test_shared_mem_c_api():
    L = _lib3()
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    h = _make_nd(L, x)
    pid = ctypes.c_int()
    sid = ctypes.c_int()
    assert L.MXNDArrayGetSharedMemHandle(h, ctypes.byref(pid),
                                         ctypes.byref(sid)) == 0, \
        L.MXGetLastError()
    shape = (ctypes.c_uint * 2)(3, 4)
    h2 = ctypes.c_void_p()
    assert L.MXNDArrayCreateFromSharedMem(pid.value, sid.value, shape, 2,
                                          0, ctypes.byref(h2)) == 0, \
        L.MXGetLastError()
    np.testing.assert_allclose(_read_nd(L, h2, 12), x.ravel())
    # one-shot transport: the consumer unlinked the segment
    assert not os.path.exists(
        "/dev/shm/mxtpu_%d_%d" % (pid.value, sid.value))
    for hh in (h, h2):
        L.MXNDArrayFree(hh)


def test_op_handle_rejects_nd_module_attrs():
    """NNGetOpHandle must NOT hand out handles for arbitrary mx.nd
    attributes (save/array/NDArray are not operators)."""
    L = _lib3()
    op = ctypes.c_void_p()
    assert L.NNGetOpHandle(b"save", ctypes.byref(op)) == -1
    assert L.NNGetOpHandle(b"cast_storage", ctypes.byref(op)) == 0


def test_custom_op_register_reports_divergence():
    L = _lib3()
    assert L.MXCustomOpRegister(b"my_op", None) == -1
    msg = L.MXGetLastError().decode()
    assert "CustomOp" in msg and "Python" in msg


def test_symbol_grad_matches_reference_contract():
    """MXSymbolGrad is unimplemented in the reference itself
    (c_api_symbolic.cc:564 LOG(FATAL)); ours errors with the same
    contract instead of crashing the process."""
    L = _lib3()
    out = ctypes.c_void_p()
    assert L.MXSymbolGrad(None, 0, None, ctypes.byref(out)) == -1
    assert b"not implemented" in L.MXGetLastError()
