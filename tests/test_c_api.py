"""General C API suite (parity model: reference include/mxnet/c_api.h as
consumed by cpp-package — NDArray create/copy/wait, imperative invoke,
symbol load + infer shape, executor bind/forward/backward)."""
import ctypes
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

REPO = os.path.join(os.path.dirname(__file__), "..")
LIB = os.path.join(REPO, "mxnet_tpu", "_lib", "libmxtpu_c_api.so")

pytestmark = pytest.mark.skipif(not os.path.exists(LIB),
                                reason="native lib not built")


def _lib():
    L = ctypes.CDLL(LIB)
    L.MXGetLastError.restype = ctypes.c_char_p
    # Explicit argtypes throughout: bare python ints (e.g. a dereferenced
    # handle `outs[0]`) otherwise marshal as 32-bit c_int, truncating
    # 64-bit pointers/size_t.
    vp, u, i = ctypes.c_void_p, ctypes.c_uint, ctypes.c_int
    P = ctypes.POINTER
    L.MXNDArrayCreateEx.argtypes = [P(u), u, i, i, i, i, P(vp)]
    L.MXNDArrayFree.argtypes = [vp]
    L.MXNDArraySyncCopyFromCPU.argtypes = [vp, vp, ctypes.c_size_t]
    L.MXNDArraySyncCopyToCPU.argtypes = [vp, vp, ctypes.c_size_t]
    L.MXNDArrayGetShape.argtypes = [vp, P(u), P(P(u))]
    L.MXNDArrayGetDType.argtypes = [vp, P(i)]
    L.MXNDArrayWaitToRead.argtypes = [vp]
    L.MXImperativeInvoke.argtypes = [vp, i, P(vp), P(i), P(P(vp)), i,
                                     P(ctypes.c_char_p),
                                     P(ctypes.c_char_p)]
    return L


def test_ndarray_roundtrip_and_invoke():
    L = _lib()
    shape = (ctypes.c_uint * 2)(2, 3)
    h = ctypes.c_void_p()
    assert L.MXNDArrayCreateEx(shape, 2, 1, 0, 0, 0, ctypes.byref(h)) == 0, \
        L.MXGetLastError()

    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    buf = (ctypes.c_float * 6)(*x.ravel())
    assert L.MXNDArraySyncCopyFromCPU(h, buf, 6) == 0, L.MXGetLastError()
    assert L.MXNDArrayWaitToRead(h) == 0

    ndim = ctypes.c_uint()
    pdata = ctypes.POINTER(ctypes.c_uint)()
    assert L.MXNDArrayGetShape(h, ctypes.byref(ndim),
                               ctypes.byref(pdata)) == 0
    assert tuple(pdata[i] for i in range(ndim.value)) == (2, 3)
    dt = ctypes.c_int()
    assert L.MXNDArrayGetDType(h, ctypes.byref(dt)) == 0 and dt.value == 0

    # imperative invoke: exp(x), op allocates outputs
    op = ctypes.c_void_p()
    assert L.NNGetOpHandle(b"exp", ctypes.byref(op)) == 0
    n_out = ctypes.c_int(0)
    outs = ctypes.POINTER(ctypes.c_void_p)()
    ins = (ctypes.c_void_p * 1)(h)
    assert L.MXImperativeInvoke(op, 1, ins, ctypes.byref(n_out),
                                ctypes.byref(outs), 0, None, None) == 0, \
        L.MXGetLastError()
    assert n_out.value == 1
    got = (ctypes.c_float * 6)()
    assert L.MXNDArraySyncCopyToCPU(outs[0], got, 6) == 0, L.MXGetLastError()
    np.testing.assert_allclose(np.array(got[:6]).reshape(2, 3), np.exp(x),
                               rtol=1e-5)
    assert L.MXNDArrayFree(outs[0]) == 0
    assert L.MXNDArrayFree(h) == 0


def test_list_op_names():
    L = _lib()
    n = ctypes.c_uint()
    arr = ctypes.POINTER(ctypes.c_char_p)()
    assert L.MXListAllOpNames(ctypes.byref(n), ctypes.byref(arr)) == 0
    names = {arr[i].decode() for i in range(n.value)}
    assert n.value > 200
    assert {"Convolution", "FullyConnected", "sgd_update"} <= names


def _save_lenet_json(tmp_path):
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                             name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, mx.sym.Variable("softmax_label"),
                               name="softmax", normalization="batch")
    path = str(tmp_path / "lenet-symbol.json")
    net.save(path)
    return path


DRIVER_SRC = r'''
// cpp-package-style LeNet training driver over the general C API.
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
typedef unsigned int mx_uint;
typedef void* NDArrayHandle;
typedef void* SymbolHandle;
typedef void* ExecutorHandle;
typedef void* AtomicSymbolCreator;
extern const char* MXGetLastError();
extern int MXNDArrayCreateEx(const mx_uint*, mx_uint, int, int, int, int,
                             NDArrayHandle*);
extern int MXNDArraySyncCopyFromCPU(NDArrayHandle, const void*, size_t);
extern int MXNDArraySyncCopyToCPU(NDArrayHandle, void*, size_t);
extern int MXNDArrayWaitAll();
extern int NNGetOpHandle(const char*, AtomicSymbolCreator*);
extern int MXImperativeInvoke(AtomicSymbolCreator, int, NDArrayHandle*,
                              int*, NDArrayHandle**, int, const char**,
                              const char**);
extern int MXSymbolCreateFromFile(const char*, SymbolHandle*);
extern int MXSymbolListArguments(SymbolHandle, mx_uint*, const char***);
extern int MXSymbolInferShape(SymbolHandle, mx_uint, const char**,
                              const mx_uint*, const mx_uint*, mx_uint*,
                              const mx_uint**, const mx_uint***, mx_uint*,
                              const mx_uint**, const mx_uint***, mx_uint*,
                              const mx_uint**, const mx_uint***, int*);
extern int MXExecutorBind(SymbolHandle, int, int, mx_uint, NDArrayHandle*,
                          NDArrayHandle*, mx_uint*, mx_uint,
                          NDArrayHandle*, ExecutorHandle*);
extern int MXExecutorForward(ExecutorHandle, int);
extern int MXExecutorBackward(ExecutorHandle, mx_uint, NDArrayHandle*);
extern int MXExecutorOutputs(ExecutorHandle, mx_uint*, NDArrayHandle**);

#define CHECK(x) do { if ((x) != 0) { \
    printf("FAIL %s: %s\n", #x, MXGetLastError()); exit(1); } } while (0)

#define B 32
static unsigned int seed = 7;
static float frand() { /* deterministic LCG in [0,1) */
    seed = seed * 1103515245u + 12345u;
    return (float)((seed >> 8) & 0xffffff) / (float)0x1000000;
}

/* synthetic separable task: class 1 iff left half brighter than right */
static void make_batch(float* x, float* y) {
    for (int b = 0; b < B; ++b) {
        int label = (b % 2);
        for (int i = 0; i < 64; ++i) {
            int col = i % 8;
            float base = frand() * 0.5f;
            if (label == 1 && col < 4) base += 0.8f;
            if (label == 0 && col >= 4) base += 0.8f;
            x[b * 64 + i] = base;
        }
        y[b] = (float)label;
    }
}

int main(int argc, char** argv) {
    SymbolHandle sym;
    CHECK(MXSymbolCreateFromFile(argv[1], &sym));

    mx_uint n_args; const char** arg_names;
    CHECK(MXSymbolListArguments(sym, &n_args, &arg_names));

    /* infer all shapes from data/label */
    const char* keys[] = {"data", "softmax_label"};
    mx_uint indptr[] = {0, 4, 5};
    mx_uint sdata[] = {B, 1, 8, 8, B};
    mx_uint in_size, out_size, aux_size;
    const mx_uint *in_ndim, *out_ndim, *aux_ndim;
    const mx_uint **in_shapes, **out_shapes, **aux_shapes;
    int complete;
    CHECK(MXSymbolInferShape(sym, 2, keys, indptr, sdata, &in_size, &in_ndim,
                             &in_shapes, &out_size, &out_ndim, &out_shapes,
                             &aux_size, &aux_ndim, &aux_shapes, &complete));
    if (!complete || in_size != n_args) { printf("FAIL infer\n"); return 1; }

    /* allocate args + grads; save copies of shapes (the pointers are
       thread-local and clobbered by later API calls) */
    NDArrayHandle args[64], grads[64];
    mx_uint reqs[64];
    long arg_elems[64];
    int data_idx = -1, label_idx = -1;
    for (mx_uint i = 0; i < n_args; ++i) {
        mx_uint shp[8];
        long n = 1;
        for (mx_uint j = 0; j < in_ndim[i]; ++j) {
            shp[j] = in_shapes[i][j];
            n *= shp[j];
        }
        arg_elems[i] = n;
        CHECK(MXNDArrayCreateEx(shp, in_ndim[i], 1, 0, 0, 0, &args[i]));
        if (strcmp(arg_names[i], "data") == 0) data_idx = (int)i;
        if (strcmp(arg_names[i], "softmax_label") == 0) label_idx = (int)i;
        int is_param = strcmp(arg_names[i], "data") != 0 &&
                       strcmp(arg_names[i], "softmax_label") != 0;
        reqs[i] = is_param ? 1 : 0;
        if (is_param) {
            CHECK(MXNDArrayCreateEx(shp, in_ndim[i], 1, 0, 0, 0, &grads[i]));
            /* xavier-ish init */
            float* w = (float*)malloc(n * sizeof(float));
            float scale = 0.35f;
            for (long k = 0; k < n; ++k) w[k] = (frand() - 0.5f) * scale;
            CHECK(MXNDArraySyncCopyFromCPU(args[i], w, (size_t)n));
            free(w);
        } else {
            grads[i] = NULL;
        }
    }
    if (data_idx < 0 || label_idx < 0) { printf("FAIL names\n"); return 1; }

    ExecutorHandle ex;
    CHECK(MXExecutorBind(sym, 1, 0, n_args, args, grads, reqs, 0, NULL, &ex));

    AtomicSymbolCreator sgd;
    CHECK(NNGetOpHandle("sgd_update", &sgd));
    const char* pk[] = {"lr"};
    const char* pv[] = {"0.2"};

    float x[B * 64], y[B];
    for (int step = 0; step < 60; ++step) {
        make_batch(x, y);
        CHECK(MXNDArraySyncCopyFromCPU(args[data_idx], x, B * 64));
        CHECK(MXNDArraySyncCopyFromCPU(args[label_idx], y, B));
        CHECK(MXExecutorForward(ex, 1));
        CHECK(MXExecutorBackward(ex, 0, NULL));
        for (mx_uint i = 0; i < n_args; ++i) {
            if (grads[i] == NULL) continue;
            NDArrayHandle ins[2]; ins[0] = args[i]; ins[1] = grads[i];
            NDArrayHandle* outs = &args[i];  /* in-place update */
            int n_out = 1;
            CHECK(MXImperativeInvoke(sgd, 2, ins, &n_out, &outs, 1, pk, pv));
        }
    }
    CHECK(MXNDArrayWaitAll());

    /* eval */
    make_batch(x, y);
    CHECK(MXNDArraySyncCopyFromCPU(args[data_idx], x, B * 64));
    CHECK(MXExecutorForward(ex, 0));
    mx_uint n_outs; NDArrayHandle* outs;
    CHECK(MXExecutorOutputs(ex, &n_outs, &outs));
    float prob[B * 2];
    CHECK(MXNDArraySyncCopyToCPU(outs[0], prob, B * 2));
    int correct = 0;
    for (int b = 0; b < B; ++b) {
        int pred = prob[b * 2 + 1] > prob[b * 2] ? 1 : 0;
        if (pred == (int)y[b]) correct++;
    }
    printf("TRAIN_OK acc=%.4f\n", (float)correct / B);
    return 0;
}
'''


def test_c_train_driver(tmp_path):
    """Compile and run a standalone C LeNet training driver — the
    cpp-package deployment story over the general C API."""
    import shutil
    cc = shutil.which("gcc") or shutil.which("cc")
    if cc is None:
        pytest.skip("no C compiler")
    json_path = _save_lenet_json(tmp_path)

    driver = tmp_path / "train_driver.c"
    driver.write_text(DRIVER_SRC)
    exe = str(tmp_path / "train_driver")
    subprocess.run([cc, str(driver), "-o", exe,
                    "-L" + os.path.dirname(LIB), "-lmxtpu_c_api",
                    "-Wl,-rpath," + os.path.dirname(LIB)], check=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.abspath(REPO) + os.pathsep + \
        env.get("PYTHONPATH", "")
    env["MXNET_TPU_FORCE_CPU"] = "1"
    p = subprocess.run([exe, json_path], capture_output=True, text=True,
                       timeout=600, env=env)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "TRAIN_OK" in p.stdout, p.stdout
    acc = float(p.stdout.split("acc=")[1].split()[0])
    assert acc > 0.8, p.stdout
