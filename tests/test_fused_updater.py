"""FusedUpdater: the one-dispatch batched optimizer step must be
numerically identical to the per-parameter eager Updater path for every
kernel-backed optimizer (parity target: reference optimizer.py Updater +
optimizer_op.cc fused kernels; the batching itself has no reference
counterpart — it amortises device dispatch, which the reference's
in-process engine never paid)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt


def _params(seed, n=5, low=None):
    rs = np.random.RandomState(seed)
    shapes = [(7, 3), (16,), (4, 5, 2), (1,), (3, 8)]
    ws, gs = [], []
    for i, s in enumerate(shapes[:n]):
        w = rs.randn(*s).astype(np.float32)
        g = rs.randn(*s).astype(np.float32)
        if low is not None and i % 2 == 0:
            w = w.astype(low)
            g = g.astype(low)
        ws.append(mx.nd.array(w, dtype=w.dtype))
        gs.append(mx.nd.array(g, dtype=g.dtype))
    return ws, gs


OPTS = [
    ("sgd", {"learning_rate": 0.1}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01}),
    ("rmsprop", {"learning_rate": 0.01}),
    ("adagrad", {"learning_rate": 0.05}),
    ("adadelta", {}),
    ("ftrl", {"learning_rate": 0.05}),
]


@pytest.mark.parametrize("name,kw", OPTS,
                         ids=[f"{n}-{i}" for i, (n, _) in enumerate(OPTS)])
def test_fused_matches_eager(name, kw):
    steps = 4
    ref_ws, ref_gs = _params(0)
    fus_ws, fus_gs = _params(0)

    eager = opt.Updater(opt.create(name, **kw))
    fused = opt.get_updater(opt.create(name, **kw))
    assert isinstance(fused, opt.FusedUpdater)

    idx = list(range(len(ref_ws)))
    for step in range(steps):
        for i in idx:
            eager(i, ref_gs[i], ref_ws[i])
        fused.update_batch(idx, fus_gs, fus_ws)
    # adam's bias correction runs in f32 on device (traced t) vs f64 on
    # host in the eager path — a few-ulp difference, not a semantic one
    for a, b in zip(ref_ws, fus_ws):
        np.testing.assert_allclose(a.asnumpy(), b.asnumpy(),
                                   rtol=1e-5, atol=1e-6)
    # states advanced identically too (t-dependent rules: adam bias corr)
    for i in idx:
        sa, sb = eager.states[i], fused.states[i]
        flat_a = sa if isinstance(sa, tuple) else (sa,)
        flat_b = sb if isinstance(sb, tuple) else (sb,)
        for x, y in zip(flat_a, flat_b):
            if x is not None:
                np.testing.assert_allclose(x.asnumpy(), y.asnumpy(),
                                           rtol=1e-5, atol=1e-6)


def test_fused_multi_precision_sgd():
    import jax.numpy as jnp
    bf16 = np.dtype(jnp.bfloat16)
    steps = 3
    ref_ws, ref_gs = _params(1, low=bf16)
    fus_ws, fus_gs = _params(1, low=bf16)
    mk = lambda: opt.create("sgd", learning_rate=0.1, momentum=0.9,
                            multi_precision=True)
    eager, fused = opt.Updater(mk()), opt.FusedUpdater(mk())
    idx = list(range(len(ref_ws)))
    for _ in range(steps):
        for i in idx:
            eager(i, ref_gs[i], ref_ws[i])
        fused.update_batch(idx, fus_gs, fus_ws)
    for i, (a, b) in enumerate(zip(ref_ws, fus_ws)):
        assert a.dtype == b.dtype
        np.testing.assert_allclose(
            a.asnumpy().astype(np.float32), b.asnumpy().astype(np.float32),
            rtol=1e-2, atol=1e-3)
    # fp32 masters must match tightly (bf16 rounding only at the cast);
    # only bf16 params carry the (mom, w32) multi-precision tuple —
    # fp32 params' state is the bare momentum array
    for i in idx:
        if not isinstance(eager.states[i], tuple):
            continue
        ma = eager.states[i][1].asnumpy()
        mb = fused.states[i][1].asnumpy()
        np.testing.assert_allclose(ma, mb, rtol=2e-6, atol=2e-7)


def test_fused_lr_scheduler_and_mults():
    """Scheduler-driven lr changes must NOT be baked into the compiled
    program, and per-param lr/wd multipliers must apply."""
    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)
    mk = lambda: opt.create("sgd", learning_rate=0.4, lr_scheduler=sched.__class__(step=2, factor=0.5))
    ref_ws, ref_gs = _params(2, n=3)
    fus_ws, fus_gs = _params(2, n=3)
    o1, o2 = mk(), mk()
    for o in (o1, o2):
        o.set_lr_mult({0: 0.1})
        o.set_wd_mult({1: 2.0})
    eager, fused = opt.Updater(o1), opt.FusedUpdater(o2)
    idx = [0, 1, 2]
    for _ in range(5):
        for i in idx:
            eager(i, ref_gs[i], ref_ws[i])
        fused.update_batch(idx, fus_gs, fus_ws)
    assert o1.num_update == o2.num_update == 5
    for a, b in zip(ref_ws, fus_ws):
        np.testing.assert_allclose(a.asnumpy(), b.asnumpy(),
                                   rtol=2e-6, atol=2e-7)


def test_fused_fallbacks():
    """Sparse grads, centered rmsprop, and kernel-less optimizers all
    take the per-index path and still produce correct updates."""
    # kernel-less: Test optimizer
    fused = opt.FusedUpdater(opt.create("test", rescale_grad=1.0))
    w = mx.nd.ones((3,))
    g = mx.nd.ones((3,)) * 0.5
    fused.update_batch([0], [g], [w])
    np.testing.assert_allclose(w.asnumpy(), np.full((3,), 1.5), rtol=1e-6)

    # centered rmsprop falls back (3-array state)
    fused = opt.FusedUpdater(opt.create("rmsprop", learning_rate=0.01,
                                        centered=True))
    eager = opt.Updater(opt.create("rmsprop", learning_rate=0.01,
                                   centered=True))
    wf, wg = mx.nd.ones((4,)), mx.nd.ones((4,)) * 0.3
    we, ge = mx.nd.ones((4,)), mx.nd.ones((4,)) * 0.3
    fused.update_batch([0], [wg], [wf])
    eager(0, ge, we)
    np.testing.assert_allclose(wf.asnumpy(), we.asnumpy(), rtol=1e-6)

    # row_sparse grad falls back to the lazy update
    from mxnet_tpu.ndarray import sparse as sp
    w = mx.nd.zeros((6, 4))
    data = np.ones((2, 4), np.float32)
    g = sp.row_sparse_array((data, [1, 4]), shape=(6, 4))
    fused = opt.FusedUpdater(opt.create("sgd", learning_rate=1.0))
    fused.update_batch([0], [g], [w])
    out = w.asnumpy()
    assert np.allclose(out[[1, 4]], -1.0)
    assert np.allclose(out[[0, 2, 3, 5]], 0.0)


def test_fused_state_roundtrip():
    """get_states/set_states stay pickle-compatible across the fused
    path (reference updater serialisation contract)."""
    fused = opt.FusedUpdater(opt.create("adam", learning_rate=0.01))
    ws, gs = _params(3, n=2)
    fused.update_batch([0, 1], gs, ws)
    blob = fused.get_states()
    other = opt.FusedUpdater(opt.create("adam", learning_rate=0.01))
    other.set_states(blob)
    assert set(other.states) == {0, 1}
    # and it keeps updating through the fused path after a load
    other.update_batch([0, 1], gs, ws)


def test_nag_multi_precision_eager_path():
    """NAG with multi_precision on the per-index (non-kernel) path must
    apply NAG's rule to the fp32 master and cast back — regression: the
    class-level alias crashed on the (mom, w32) state tuple."""
    import jax.numpy as jnp
    bf16 = np.dtype(jnp.bfloat16)
    w = mx.nd.array(np.linspace(-1, 1, 8).astype(np.float32).astype(bf16),
                    dtype=bf16)
    g = mx.nd.array(np.full((8,), 0.25, np.float32).astype(bf16),
                    dtype=bf16)
    up = opt.Updater(opt.create("nag", learning_rate=0.1, momentum=0.9,
                                multi_precision=True))
    # fp32 shadow of the same rule
    w32 = np.linspace(-1, 1, 8).astype(np.float32).astype(bf16)
    w32 = w32.astype(np.float32)
    mom = np.zeros(8, np.float32)
    g32 = np.full((8,), 0.25, np.float32).astype(bf16).astype(np.float32)
    for _ in range(3):
        up(0, g, w)
        mom = 0.9 * mom + g32
        w32 -= 0.1 * (g32 + 0.9 * mom)
    np.testing.assert_allclose(up.states[0][1].asnumpy(), w32,
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(w.asnumpy().astype(np.float32),
                               w32.astype(bf16).astype(np.float32),
                               rtol=1e-2, atol=1e-3)
    # momentum-less NAG mp path too (state = (None, w32))
    up2 = opt.Updater(opt.create("nag", learning_rate=0.1,
                                 multi_precision=True))
    up2(0, g, w)


def test_fused_set_states_recomputes_mp_flags():
    """Loading states saved under a different multi_precision config must
    not reuse stale flags — regression: _mp_flags survived set_states."""
    import jax.numpy as jnp
    bf16 = np.dtype(jnp.bfloat16)
    mk_w = lambda: mx.nd.array(np.ones(4, np.float32).astype(bf16),
                               dtype=bf16)
    g = mx.nd.array(np.full((4,), 0.5, np.float32).astype(bf16),
                    dtype=bf16)
    # steps under multi_precision=False → flags cached False
    plain = opt.FusedUpdater(opt.create("sgd", learning_rate=0.1,
                                        momentum=0.9))
    w = mk_w()
    plain.update_batch([0], [g], [w])
    # load states from a multi_precision=True run (optimizer dumped too)
    mp = opt.FusedUpdater(opt.create("sgd", learning_rate=0.1, momentum=0.9,
                                     multi_precision=True))
    w2 = mk_w()
    mp.update_batch([0], [g], [w2])
    plain.set_states(mp.get_states(dump_optimizer=True))
    w3 = mk_w()
    plain.update_batch([0], [g], [w3])  # must classify (mom, w32) as mp
    assert isinstance(plain.states[0], tuple) and len(plain.states[0]) == 2
    assert plain.states[0][1].dtype == np.float32  # master survived
