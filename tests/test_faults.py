"""Fault-injection registry (mxnet_tpu/faults.py): spec grammar,
deterministic schedules, exact fire counts, and the wired sites
(dispatch / io_next / compile_cache.load / kv_push)."""
import os
import time

import numpy as np
import pytest

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx
from mxnet_tpu import faults, telemetry
from mxnet_tpu.base import MXNetError


@pytest.fixture(autouse=True)
def _clean_registry():
    faults.clear()
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# Spec grammar
# ---------------------------------------------------------------------------

def test_parse_basic_rules():
    rules = faults.parse_spec(
        "dispatch:raise:n=3;d2h:nan:every=2;io_next:delay=50:first=4")
    assert [r.site for r in rules] == ["dispatch", "d2h", "io_next"]
    assert rules[0].action == "raise" and rules[0].n == 3
    assert rules[1].action == "nan" and rules[1].every == 2
    assert rules[2].action == "delay" and rules[2].delay_ms == 50.0 \
        and rules[2].first == 4


def test_parse_probability_with_seed():
    (r,) = faults.parse_spec("kv_push:raise:p=0.25,seed=9")
    assert r.p == 0.25 and r.seed == 9


@pytest.mark.parametrize("bad", [
    "nosuchsite:raise",                 # unknown site
    "dispatch:explode",                 # unknown action
    "dispatch:raise:n=3:extra",         # too many fields
    "dispatch:raise:n=0",               # n < 1
    "dispatch:raise:p=1.5",             # p out of range
    "dispatch:raise:n=2,every=3",       # exclusive schedules
    "dispatch:delay=abc",               # bad delay
])
def test_parse_rejects_bad_specs(bad):
    with pytest.raises(MXNetError):
        faults.parse_spec(bad)


def test_invalid_env_spec_is_ignored_not_fatal(monkeypatch):
    # a typo'd MXNET_FAULTS must not brick the process at an arbitrary
    # dispatch site — it warns and runs fault-free
    monkeypatch.setenv(faults.ENV, "dispatch:bogus")
    faults._loaded = False
    assert faults.active() is False
    assert faults.fire("dispatch") is None


def test_env_spec_loads_lazily(monkeypatch):
    monkeypatch.setenv(faults.ENV, "io_next:raise:n=1")
    faults._loaded = False
    assert faults.active() is True
    assert faults.spec() == "io_next:raise:n=1"


# ---------------------------------------------------------------------------
# Schedules + exact counts
# ---------------------------------------------------------------------------

def test_nth_call_schedule_exact():
    faults.configure("dispatch:raise:n=3")
    fired = []
    for i in range(1, 6):
        try:
            faults.fire("dispatch")
        except faults.InjectedFault:
            fired.append(i)
    assert fired == [3]
    assert faults.counts() == {"dispatch": {"calls": 5, "fired": 1}}


def test_every_schedule_exact():
    faults.configure("dispatch:raise:every=2")
    fired = []
    for i in range(1, 7):
        try:
            faults.fire("dispatch")
        except faults.InjectedFault:
            fired.append(i)
    assert fired == [2, 4, 6]
    assert faults.counts()["dispatch"] == {"calls": 6, "fired": 3}


def test_first_schedule_exact():
    faults.configure("d2h:nan:first=2")
    got = [faults.fire("d2h") for _ in range(5)]
    assert got == ["nan", "nan", None, None, None]


def test_probability_schedule_is_deterministic():
    faults.configure("dispatch:raise:p=0.5,seed=42")
    seq1 = []
    for _ in range(20):
        try:
            faults.fire("dispatch")
            seq1.append(0)
        except faults.InjectedFault:
            seq1.append(1)
    # same seed -> same schedule, exactly
    faults.reset_counts()
    seq2 = []
    for _ in range(20):
        try:
            faults.fire("dispatch")
            seq2.append(0)
        except faults.InjectedFault:
            seq2.append(1)
    assert seq1 == seq2
    assert 0 < sum(seq1) < 20      # p=0.5 over 20 draws: some of each
    assert faults.counts()["dispatch"]["fired"] == sum(seq2)


def test_delay_action_sleeps():
    faults.configure("io_next:delay=30")
    t0 = time.perf_counter()
    assert faults.fire("io_next") is None
    assert time.perf_counter() - t0 >= 0.025


def test_injections_counted_in_telemetry():
    telemetry.enable()
    base = telemetry.counters().get("faults.injected.dispatch", 0)
    faults.configure("dispatch:raise:first=2")
    for _ in range(4):
        try:
            faults.fire("dispatch")
        except faults.InjectedFault:
            pass
    assert telemetry.counters().get("faults.injected.dispatch", 0) \
        - base == 2


def test_raise_rule_does_not_short_circuit_sibling_counts():
    # a raise sharing the call with another firing rule must not eat
    # its telemetry count: registry and telemetry stay EXACTLY equal
    telemetry.enable()
    base = telemetry.counters().get("faults.injected.dispatch", 0)
    faults.configure("dispatch:raise:n=1;dispatch:delay=1")
    with pytest.raises(faults.InjectedFault):
        faults.fire("dispatch")
    assert faults.counts()["dispatch"]["fired"] == 2
    assert telemetry.counters().get("faults.injected.dispatch", 0) \
        - base == 2
    # call 2: only the always-on delay rule fires
    assert faults.fire("dispatch") is None
    assert faults.counts()["dispatch"]["fired"] == 3
    assert telemetry.counters().get("faults.injected.dispatch", 0) \
        - base == 3


def test_injected_fault_is_transient_mxnet_error():
    err = faults.InjectedFault("dispatch")
    assert isinstance(err, MXNetError)
    assert err.transient is True and err.site == "dispatch"


def test_poison_sets_nan_and_skips_non_float():
    f = np.ones((2, 3), np.float32)
    i = np.ones((2,), np.int32)
    ro = np.ones((2,), np.float32)
    ro.setflags(write=False)
    out = faults.poison([f, i, ro])
    assert np.isnan(out[0].reshape(-1)[0])
    assert (out[1] == 1).all()
    assert np.isnan(out[2].reshape(-1)[0])    # copied, then poisoned
    assert not np.isnan(ro.reshape(-1)[0])    # original untouched


# ---------------------------------------------------------------------------
# Wired sites
# ---------------------------------------------------------------------------

def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_dispatch_site_fires_in_executor():
    sym = _mlp()
    ex = sym.simple_bind(ctx=mx.cpu(), data=(2, 4))
    ex.forward(is_train=False)        # compile + first dispatch, clean
    faults.configure("dispatch:raise:n=1")
    with pytest.raises(faults.InjectedFault):
        ex.forward(is_train=False)
    faults.clear()
    ex.forward(is_train=False)        # executor still healthy after


def test_io_next_site_raises_and_poisons():
    X = np.random.RandomState(0).normal(size=(8, 4)).astype(np.float32)
    it = mx.io.NDArrayIter(X, None, batch_size=4)
    faults.configure("io_next:raise:n=1")
    it.reset()
    with pytest.raises(faults.InjectedFault):
        next(iter(it))
    # nan action corrupts the DATA arrays
    faults.configure("io_next:nan:n=1")
    it.reset()
    batch = next(iter(it))
    arr = batch.data[0]
    host = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
    assert np.isnan(host.reshape(-1)[0])


def test_compile_cache_load_site_degrades_to_reject(tmp_path, monkeypatch):
    from mxnet_tpu import compile_cache
    if not compile_cache._serialize_api():
        pytest.skip("no serialize_executable on this jax")
    monkeypatch.setenv("MXNET_COMPILE_CACHE", str(tmp_path))
    monkeypatch.setattr(compile_cache, "_DIR_TRUST", {})
    telemetry.enable()
    telemetry.reset()
    sym = _mlp()
    ex = sym.simple_bind(ctx=mx.cpu(), data=(2, 4))
    ex.forward(is_train=False)        # compiles + stores
    assert telemetry.counters().get("compile_cache.store", 0) >= 1
    # an injected load failure must fall back to a fresh compile, not
    # break dispatch
    faults.configure("compile_cache.load:raise")
    telemetry.reset()
    ex2 = sym.simple_bind(ctx=mx.cpu(), data=(2, 4))
    out = ex2.forward(is_train=False)[0].asnumpy()
    assert np.isfinite(out).all()
    c = telemetry.counters()
    assert c.get("compile_cache.reject.injected", 0) >= 1
    assert c.get("compile_cache.hit", 0) == 0


def test_kv_push_site():
    kv = mx.kv.create("local")
    a = mx.nd.ones((4,))
    kv.init(0, a)
    faults.configure("kv_push:raise:n=1")
    with pytest.raises(faults.InjectedFault):
        kv.push(0, mx.nd.ones((4,)))
    # engine healthy after
    kv.push(0, mx.nd.ones((4,)))
    out = mx.nd.zeros((4,))
    kv.pull(0, out=out)
    assert np.isfinite(out.asnumpy()).all()
