"""Model-zoo pretrained-weight store (parity: reference
python/mxnet/gluon/model_zoo/model_store.py — zero-egress build resolves
local paths and file:// mirrors instead of downloading)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.model_zoo import vision
from mxnet_tpu.gluon.model_zoo.model_store import get_model_file, purge


def test_get_model_file_missing_raises(tmp_path):
    with pytest.raises(mx.MXNetError):
        get_model_file("resnet18_v1", root=str(tmp_path))


def test_pretrained_resnet_scores_fixture_batch(tmp_path):
    np.random.seed(0)
    mx.random.seed(0)
    net = vision.resnet18_v1(classes=10)
    net.initialize(mx.initializer.Xavier())
    x = mx.nd.array(np.random.RandomState(0)
                    .uniform(-1, 1, (2, 3, 32, 32)).astype(np.float32))
    want = net(x).asnumpy()
    net.save_params(str(tmp_path / "resnet18_v1.params"))

    loaded = vision.resnet18_v1(classes=10, pretrained=True,
                                root=str(tmp_path))
    got = loaded(x).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_pretrained_via_file_mirror(tmp_path, monkeypatch):
    mirror = tmp_path / "mirror"
    cache = tmp_path / "cache"
    mirror.mkdir()
    np.random.seed(0)
    net = vision.squeezenet1_0(classes=7)
    net.initialize(mx.initializer.Xavier())
    x = mx.nd.array(np.random.RandomState(1)
                    .uniform(-1, 1, (1, 3, 64, 64)).astype(np.float32))
    want = net(x).asnumpy()
    # the reference's hash-suffixed blob naming also resolves
    net.save_params(str(mirror / "squeezenet1.0-33ba0f93.params"))
    monkeypatch.setenv("MXNET_GLUON_REPO", "file://" + str(mirror))
    loaded = vision.squeezenet1_0(classes=7, pretrained=True,
                                  root=str(cache))
    got = loaded(x).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # blob copied into the cache root; purge clears it
    assert any(f.endswith(".params") for f in os.listdir(cache))
    purge(str(cache))
    assert not any(f.endswith(".params") for f in os.listdir(cache))
