"""tools/ suite — im2rec packing, parse_log, launch.py multi-process SPMD
(parity model: the reference exercised tools/launch.py --launcher local in
tests/nightly/dist_sync_kvstore.py)."""
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.join(os.path.dirname(__file__), "..")
TOOLS = os.path.join(REPO, "tools")


def _run(cmd, **kw):
    env = dict(kw.pop("env", None) or os.environ)
    env["MXNET_TPU_FORCE_CPU"] = "1"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    kw.setdefault("timeout", 300)
    return subprocess.run([sys.executable] + cmd, capture_output=True,
                          text=True, env=env, **kw)


def test_im2rec_roundtrip(tmp_path):
    PIL = pytest.importorskip("PIL.Image")
    for cls in ("a", "b"):
        os.makedirs(tmp_path / cls)
        for i in range(2):
            arr = (np.random.rand(16, 16, 3) * 255).astype(np.uint8)
            PIL.fromarray(arr).save(str(tmp_path / cls / ("%d.jpg" % i)))
    prefix = str(tmp_path / "data")
    p = _run([os.path.join(TOOLS, "im2rec.py"), prefix, str(tmp_path),
              "--list", "--recursive"])
    assert p.returncode == 0, p.stderr
    p = _run([os.path.join(TOOLS, "im2rec.py"), prefix, str(tmp_path)])
    assert p.returncode == 0, p.stderr
    assert os.path.exists(prefix + ".rec") and os.path.exists(prefix + ".idx")

    from mxnet_tpu import recordio
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "r")
    header, img = recordio.unpack(rec.read_idx(0))
    assert len(img) > 0
    assert header.label in (0.0, 1.0)


def test_parse_log():
    log = ("INFO:root:Epoch[0] Batch [20]\tSpeed: 100.5 samples/sec\t"
           "accuracy=0.5\n"
           "INFO:root:Epoch[0] Train-accuracy=0.9\n"
           "INFO:root:Epoch[0] Validation-accuracy=0.8\n")
    p = subprocess.run([sys.executable, os.path.join(TOOLS, "parse_log.py"),
                        "-", "--format", "tsv"], input=log,
                       capture_output=True, text=True, timeout=60)
    assert p.returncode == 0, p.stderr
    lines = p.stdout.strip().splitlines()
    assert lines[0].split("\t") == ["epoch", "speed", "train-accuracy",
                                    "validation-accuracy"]
    assert lines[1].split("\t") == ["0", "100.5", "0.9", "0.8"]


def test_launch_local_two_process_spmd(tmp_path):
    """launch.py forks 2 workers that form one jax.distributed job and
    run a cross-process allgather (the dist_sync smoke)."""
    script = tmp_path / "worker.py"
    script.write_text(
        "import sys; sys.path.insert(0, %r)\n" % REPO +
        "import mxnet_tpu as mx\n"
        "import jax, jax.numpy as jnp\n"
        "from jax.experimental import multihost_utils\n"
        "assert jax.process_count() == 2\n"
        "kv = mx.kv.create('dist_sync')\n"
        "v = multihost_utils.process_allgather("
        "jnp.array([float(kv.rank + 1)]))\n"
        "assert float(v.sum()) == 3.0\n"
        "print('OK rank', kv.rank)\n")
    p = _run([os.path.join(TOOLS, "launch.py"), "-n", "2",
              "--force-cpu", "--port", "9411",
              sys.executable, str(script)])
    assert p.returncode == 0, p.stderr
    assert p.stdout.count("OK rank") == 2


def test_launch_local_dist_kvstore_push_pull(tmp_path):
    """2-process dist_sync kvstore: batched dense push reduces on device
    across processes; row_sparse keeps the union of pushed rows even when
    the global sum of a row is zero (reference dist-server semantics,
    kvstore_dist_server.h:261-312)."""
    script = tmp_path / "worker_kv.py"
    script.write_text(
        "import sys; sys.path.insert(0, %r)\n" % REPO +
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import nd\n"
        "from mxnet_tpu.ndarray import sparse as sp\n"
        "import jax\n"
        "assert jax.process_count() == 2\n"
        "kv = mx.kv.create('dist_sync')\n"
        "r = kv.rank\n"
        "kv.init(['a', 'b'], [nd.zeros((2, 3)), nd.zeros((4,))])\n"
        "# batched push of two keys at once -> one jitted collective\n"
        "kv.push(['a', 'b'], [nd.ones((2, 3)) * (r + 1),\n"
        "                     nd.ones((4,)) * (10 * (r + 1))])\n"
        "oa, ob = nd.zeros((2, 3)), nd.zeros((4,))\n"
        "kv.pull(['a', 'b'], out=[oa, ob])\n"
        "np.testing.assert_allclose(oa.asnumpy(), np.full((2, 3), 3.0))\n"
        "np.testing.assert_allclose(ob.asnumpy(), np.full((4,), 30.0))\n"
        "# row_sparse: rank0 pushes +1 on row 1, rank1 pushes -1 on row 1\n"
        "# (sum 0) and +2 on row 3; union must keep BOTH rows 1 and 3\n"
        "val = np.array([[1.0, 1.0]]) if r == 0 else np.array([[-1.0, -1.0]])\n"
        "rows = [1] if r == 0 else [1, 3]\n"
        "if r == 1:\n"
        "    val = np.array([[-1.0, -1.0], [2.0, 2.0]])\n"
        "g = sp.row_sparse_array((val.astype(np.float32), rows), shape=(5, 2))\n"
        "kv.init('c', sp.zeros('row_sparse', (5, 2)))\n"
        "kv.push('c', g)\n"
        "got = kv._store['c']\n"
        "assert sorted(np.asarray(got._rsp_indices).tolist()) == [1, 3], \\\n"
        "    np.asarray(got._rsp_indices)\n"
        "dense = got.tostype('default').asnumpy()\n"
        "np.testing.assert_allclose(dense[3], [2.0, 2.0])\n"
        "np.testing.assert_allclose(dense[1], [0.0, 0.0])\n"
        "print('KV OK rank', r)\n")
    p = _run([os.path.join(TOOLS, "launch.py"), "-n", "2",
              "--force-cpu", "--port", "9413",
              sys.executable, str(script)])
    assert p.returncode == 0, p.stderr + p.stdout
    assert p.stdout.count("KV OK rank") == 2


def test_bandwidth_probe():
    p = _run([os.path.join(TOOLS, "bandwidth", "measure.py"),
              "--force-cpu", "--size-mb", "1", "--rounds", "2"])
    assert p.returncode == 0, p.stderr
    assert "GB/s" in p.stdout


def test_recordio_multilabel_pack_roundtrip():
    from mxnet_tpu import recordio
    header = recordio.IRHeader(0, [1.0, 2.5, -3.0], 7, 0)
    s = recordio.pack(header, b"payload")
    back, payload = recordio.unpack(s)
    assert payload == b"payload"
    np.testing.assert_allclose(np.asarray(back.label), [1.0, 2.5, -3.0])
    assert back.id == 7


def test_im2rec_chunked_pack(tmp_path):
    PIL = pytest.importorskip("PIL.Image")
    for i in range(4):
        arr = (np.random.rand(8, 8, 3) * 255).astype(np.uint8)
        PIL.fromarray(arr).save(str(tmp_path / ("%d.jpg" % i)))
    prefix = str(tmp_path / "data")
    p = _run([os.path.join(TOOLS, "im2rec.py"), prefix, str(tmp_path),
              "--list", "--chunks", "2"])
    assert p.returncode == 0, p.stderr
    p = _run([os.path.join(TOOLS, "im2rec.py"), prefix, str(tmp_path)])
    assert p.returncode == 0, p.stderr
    assert os.path.exists(prefix + "_0.rec")
    assert os.path.exists(prefix + "_1.rec")


def test_launch_dist_sync_kvstore(tmp_path):
    """2-worker dist_sync push/pull exactness (parity model: reference
    tests/nightly/dist_sync_kvstore.py run via launch.py local mode)."""
    script = tmp_path / "dist_kv.py"
    script.write_text(
        "import sys; sys.path.insert(0, %r)\n" % REPO +
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "kv = mx.kv.create('dist_sync')\n"
        "kv.init(3, mx.nd.zeros((4, 2)))\n"
        "kv.barrier()\n"
        "kv.push(3, mx.nd.ones((4, 2)) * (kv.rank + 1))\n"
        "out = mx.nd.zeros((4, 2))\n"
        "kv.pull(3, out=out)\n"
        "np.testing.assert_allclose(out.asnumpy(), 3.0)\n"  # 1 + 2
        "kv.barrier()\n"
        "print('DIST_KV_OK rank', kv.rank)\n")
    p = _run([os.path.join(TOOLS, "launch.py"), "-n", "2",
              "--force-cpu", "--port", "9413",
              sys.executable, str(script)])
    assert p.returncode == 0, p.stderr
    assert p.stdout.count("DIST_KV_OK") == 2


def test_launch_dist_wire_compression_and_sparse_payload(tmp_path):
    """The dist wire actually shrinks: 2-bit pushes ship packed words
    (~16x smaller than fp32) and row_sparse pushes ship only touched rows
    (O(nnz), not O(full embedding)) — reference gradient_compression.cc
    and kvstore_dist.h:430-496 payload semantics."""
    script = tmp_path / "wire_kv.py"
    script.write_text(
        "import sys; sys.path.insert(0, %r)\n" % REPO +
        "import numpy as np\n"
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu.ndarray import sparse as sp\n"
        "import jax\n"
        "assert jax.process_count() == 2\n"
        "kv = mx.kv.create('dist_sync')\n"
        "kv.set_gradient_compression({'type': '2bit', 'threshold': 0.5})\n"
        "kv.init(0, mx.nd.zeros((64, 64)))\n"
        "kv.push(0, mx.nd.ones((64, 64)) * 0.3)\n"
        "dense_bytes = 64 * 64 * 4\n"
        "wire = kv.wire_bytes_last_push\n"
        "assert wire <= dense_bytes // 16 + 64, (wire, dense_bytes)\n"
        "out = mx.nd.zeros((64, 64))\n"
        "kv.pull(0, out=out)\n"
        "# 0.3 < threshold 0.5 -> quantised to 0 on both ranks\n"
        "np.testing.assert_allclose(out.asnumpy(), 0.0)\n"
        "# error feedback: residual 0.3 + new 0.3 = 0.6 >= 0.5 -> +0.5\n"
        "kv.push(0, mx.nd.ones((64, 64)) * 0.3)\n"
        "kv.pull(0, out=out)\n"
        "np.testing.assert_allclose(out.asnumpy(), 1.0)\n"
        "# row_sparse payload: a (1000, 4) embedding, <=3 touched rows\n"
        "kv2 = mx.kv.create('dist_sync')\n"
        "kv2.init('e', sp.zeros('row_sparse', (1000, 4)))\n"
        "r = kv2.rank\n"
        "rows = [5, 17, 900] if r == 0 else [17, 42]\n"
        "vals = np.ones((len(rows), 4), np.float32) * (r + 1)\n"
        "g = sp.row_sparse_array((vals, rows), shape=(1000, 4))\n"
        "kv2.push('e', g)\n"
        "wire2 = kv2.wire_bytes_last_push\n"
        "full_bytes = 1000 * 4 * 4\n"
        "assert wire2 <= 512, (wire2, full_bytes)\n"
        "got = kv2._store['e']\n"
        "assert sorted(np.asarray(got._rsp_indices).tolist()) == \\\n"
        "    [5, 17, 42, 900]\n"
        "dense = got.tostype('default').asnumpy()\n"
        "np.testing.assert_allclose(dense[17], 3.0)\n"
        "np.testing.assert_allclose(dense[5], 1.0)\n"
        "np.testing.assert_allclose(dense[42], 2.0)\n"
        "np.testing.assert_allclose(dense[900], 1.0)\n"
        "print('WIRE OK rank', r)\n")
    p = _run([os.path.join(TOOLS, "launch.py"), "-n", "2",
              "--force-cpu", "--port", "9417",
              sys.executable, str(script)])
    assert p.returncode == 0, p.stderr + p.stdout
    assert p.stdout.count("WIRE OK rank") == 2


def test_launch_dead_node_visibility(tmp_path):
    """A worker that dies is visible to survivors via num_dead_node
    (parity: reference get_num_dead_node over scheduler heartbeats,
    include/mxnet/kvstore.h:338)."""
    script = tmp_path / "dead_kv.py"
    script.write_text(
        "import sys, time, os; sys.path.insert(0, %r)\n" % REPO +
        "import mxnet_tpu as mx\n"
        # fast beats + a WIDE staleness margin (25 beats): this test
        # pins visibility semantics, not detection latency — under
        # full-suite load a 1s-interval beat thread can gap past a 2s
        # timeout and a live peer reads as dead (flaky)
        "os.environ['MXTPU_HEARTBEAT_INTERVAL'] = '0.2'\n"
        "os.environ['MXTPU_HEARTBEAT_TIMEOUT'] = '5'\n"
        "kv = mx.kv.create('dist_sync')\n"
        "kv.barrier()\n"
        "assert kv.num_dead_node() == 0, kv.num_dead_node()\n"
        "if kv.rank == 1:\n"
        "    from mxnet_tpu import heartbeat\n"
        "    heartbeat.stop_heartbeat()\n"
        "    print('DEAD OK rank 1')\n"
        "    os._exit(0)   # worker dies (cleanly, to keep exit code 0)\n"
        "deadline = time.time() + 20\n"
        "while time.time() < deadline and kv.num_dead_node() == 0:\n"
        "    time.sleep(0.5)\n"
        "assert kv.num_dead_node() == 1, kv.num_dead_node()\n"
        "print('DEAD OK rank 0', flush=True)\n"
        "os._exit(0)  # skip jax's shutdown barrier (peer already gone)\n")
    p = _run([os.path.join(TOOLS, "launch.py"), "-n", "2",
              "--force-cpu", "--port", "9419",
              sys.executable, str(script)])
    assert p.returncode == 0, p.stderr + p.stdout
    assert p.stdout.count("DEAD OK") == 2


def test_launch_push_discipline_mismatch_fails_loudly(tmp_path):
    """Workers pushing DIFFERENT keys must die with a clear error, not
    deadlock or silently corrupt (SPMD collective discipline; the
    reference's server tolerated arbitrary arrival,
    kvstore_dist_server.h:173-310 — we guard instead)."""
    script = tmp_path / "bad_kv.py"
    script.write_text(
        "import sys; sys.path.insert(0, %r)\n" % REPO +
        "import mxnet_tpu as mx\n"
        "kv = mx.kv.create('dist_sync')\n"
        "kv.init(['a', 'b'], [mx.nd.zeros((2, 2)), mx.nd.zeros((3,))])\n"
        "kv.barrier()\n"
        "# rank 0 pushes key 'a', rank 1 pushes key 'b': mismatch\n"
        "key = 'a' if kv.rank == 0 else 'b'\n"
        "val = mx.nd.ones((2, 2)) if kv.rank == 0 else mx.nd.ones((3,))\n"
        "kv.push(key, val)\n"
        "print('UNREACHABLE rank', kv.rank)\n")
    p = _run([os.path.join(TOOLS, "launch.py"), "-n", "2",
              "--force-cpu", "--port", "9421",
              sys.executable, str(script)])
    assert p.returncode != 0
    combined = p.stdout + p.stderr
    assert "discipline violated" in combined, combined
    assert "UNREACHABLE" not in p.stdout


def test_mfu_capture_smoke():
    """The fresh-capture roofline tool: traced bench child on CPU, xplane
    parsed, category shares extracted (the on-chip run reuses this path)."""
    import json
    p = _run([os.path.join(TOOLS, "mfu_capture.py"), "--timeout", "420"],
             env={**os.environ, "MXTPU_BENCH_SMOKE": "1"}, timeout=500)
    assert p.returncode == 0, p.stderr[-1500:]
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["hlo_rows"] > 100
    shares = out["self_time_share"]
    assert "convolution fusions" in shares
    assert abs(sum(shares.values()) - 1.0) < 0.01


def test_accnn_low_rank_factorization(tmp_path):
    """tools/accnn: SVD-split convs + FCs. Full rank reproduces the
    original network almost exactly; reduced rank shrinks params and
    stays close (reference tools/accnn workflow)."""
    import json
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.io import DataBatch

    np.random.seed(0)
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8,
                             pad=(1, 1), name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=16, name="fc1")
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(net, num_hidden=4, name="fc2"),
        name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 3, 6, 6))], for_training=False)
    mod.init_params(mx.initializer.Xavier())
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 0)

    def run_acc(ranks, out):
        p = _run([os.path.join(TOOLS, "accnn", "accnn.py"),
                  "--model", prefix, "--epoch", "0",
                  "--ranks", json.dumps(ranks), "--output", out])
        assert p.returncode == 0, p.stderr[-1500:]
        return p.stdout

    x = mx.nd.array(np.random.rand(2, 3, 6, 6).astype(np.float32))
    mod.forward(DataBatch([x]), is_train=False)
    ref = mod.get_outputs()[0].asnumpy()

    def run_net(out_prefix):
        sym2, a2, x2 = mx.model.load_checkpoint(out_prefix, 0)
        m2 = mx.mod.Module(sym2, context=mx.cpu())
        m2.bind(data_shapes=[("data", (2, 3, 6, 6))], for_training=False)
        m2.set_params(a2, x2)
        m2.forward(DataBatch([x]), is_train=False)
        return m2.get_outputs()[0].asnumpy()

    # full rank: numerically faithful
    run_acc({"conv1": 64, "fc1": 64}, prefix + "-full")
    np.testing.assert_allclose(run_net(prefix + "-full"), ref,
                               atol=1e-4)

    # reduced rank: smaller and still close
    out = run_acc({"conv1": 4, "fc1": 6}, prefix + "-lo")
    pct = float(out.split("(")[1].split("%")[0])
    assert pct < 100.0
    assert np.abs(run_net(prefix + "-lo") - ref).max() < 0.2


def test_rec2idx_roundtrip(tmp_path):
    from mxnet_tpu import recordio
    rec = str(tmp_path / "a.rec")
    w = recordio.MXRecordIO(rec, "w")
    for i in range(5):
        w.write(recordio.pack(recordio.IRHeader(0, float(i), i * 10, 0),
                              b"x" * (10 + i)))
    w.close()
    idx = str(tmp_path / "a.idx")
    p = _run([os.path.join(TOOLS, "rec2idx.py"), rec, idx])
    assert p.returncode == 0, p.stderr
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    hdr, payload = recordio.unpack(r.read_idx(30))
    assert hdr.label == 3.0 and payload == b"x" * 13


def test_diagnose_runs():
    p = _run([os.path.join(TOOLS, "diagnose.py"), "--accelerator", "0"])
    assert p.returncode == 0, p.stderr
    assert "Framework" in p.stdout and "native C ABI : built" in p.stdout


def test_rec2idx_duplicate_ids_key_sequentially(tmp_path):
    from mxnet_tpu import recordio
    rec = str(tmp_path / "dup.rec")
    w = recordio.MXRecordIO(rec, "w")
    for i in range(4):
        w.write(recordio.pack(recordio.IRHeader(0, float(i), 0, 0),
                              bytes([i]) * 4))
    w.close()
    idx = str(tmp_path / "dup.idx")
    p = _run([os.path.join(TOOLS, "rec2idx.py"), rec, idx])
    assert p.returncode == 0, p.stderr
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    for i in range(4):
        hdr, payload = recordio.unpack(r.read_idx(i))
        assert payload == bytes([i]) * 4


def test_accnn_speedup_rank_selection(tmp_path):
    """--speedup picks conv ranks automatically and the factored graph's
    conv FLOPs land at or under cost/speedup."""
    import json
    import numpy as np
    import mxnet_tpu as mx

    np.random.seed(1)
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=16,
                             pad=(1, 1), name="c1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Convolution(net, kernel=(5, 5), num_filter=16,
                             pad=(2, 2), name="c2")
    net = mx.sym.Flatten(net)
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(net, num_hidden=4, name="fc"),
        name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[("data", (1, 3, 10, 10))], for_training=False)
    mod.init_params(mx.initializer.Xavier())
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 0)

    p = _run([os.path.join(TOOLS, "accnn", "accnn.py"),
              "--model", prefix, "--epoch", "0", "--speedup", "2.0",
              "--data-shape", "1,3,10,10", "--output", prefix + "-sp"])
    assert p.returncode == 0, p.stderr[-1500:]
    ranks = json.loads(p.stdout.split("selected ranks:")[1]
                       .strip().splitlines()[0])
    assert set(ranks) == {"c1", "c2"}
    assert all(1 <= r for r in ranks.values())
    # the central property: factored conv cost <= original cost / 2
    # (10x10 outputs at pad=same; cost model from select_ranks)
    xy = 100
    full = (3 * 3 * 16 * 3 + 5 * 5 * 16 * 16) * xy
    cost = (ranks["c1"] * (3 * 3 + 3 * 16)
            + ranks["c2"] * (5 * 16 + 5 * 16)) * xy
    assert cost <= full / 2.0, (ranks, cost, full)
    # the factored net loads and runs
    sym2, a2, x2 = mx.model.load_checkpoint(prefix + "-sp", 0)
    m2 = mx.mod.Module(sym2, context=mx.cpu())
    m2.bind(data_shapes=[("data", (1, 3, 10, 10))], for_training=False)
    m2.set_params(a2, x2)
    from mxnet_tpu.io import DataBatch
    m2.forward(DataBatch([mx.nd.ones((1, 3, 10, 10))]), is_train=False)
    assert m2.get_outputs()[0].shape == (1, 4)
