"""Dependency-engine tests (parity: reference
tests/cpp/engine/threaded_engine_test.cc + tests/python/unittest/
test_engine.py)."""
import threading
import time

import pytest

import mxnet_tpu as mx
from mxnet_tpu import engine as eng
from mxnet_tpu.base import MXNetError


def test_write_ordering():
    e = eng.Engine(num_workers=4)
    v = e.new_var()
    out = []

    def mk(i):
        def f():
            time.sleep(0.0005)
            out.append(i)
        return f

    for i in range(200):
        e.push(mk(i), mutable_vars=[v])
    e.wait_for_var(v)
    assert out == list(range(200))


def test_concurrent_readers_exclusive_writer():
    e = eng.Engine(num_workers=4)
    v = e.new_var()
    lock = threading.Lock()
    active = [0]
    peak = [0]
    writer_saw_readers = []

    def reader():
        with lock:
            active[0] += 1
            peak[0] = max(peak[0], active[0])
        time.sleep(0.01)
        with lock:
            active[0] -= 1

    def writer():
        with lock:
            writer_saw_readers.append(active[0])

    for _ in range(6):
        e.push(reader, const_vars=[v])
    e.push(writer, mutable_vars=[v])
    for _ in range(6):
        e.push(reader, const_vars=[v])
    e.wait_all()
    if e._h is not None:  # native engine: readers overlap
        assert peak[0] > 1
    # the writer never ran concurrently with a reader
    assert writer_saw_readers == [0]


def test_diamond_dependency():
    e = eng.Engine(num_workers=4)
    a, b = e.new_var(), e.new_var()
    events = []
    lock = threading.Lock()

    def log(tag):
        def f():
            with lock:
                events.append(tag)
        return f

    e.push(log("w_a"), mutable_vars=[a])
    e.push(log("r_ab_w_b"), const_vars=[a], mutable_vars=[b])
    e.push(log("r_b"), const_vars=[b])
    e.wait_all()
    assert events.index("w_a") < events.index("r_ab_w_b") < events.index("r_b")


def test_overlapping_sets_rejected():
    e = eng.Engine(num_workers=2)
    v = e.new_var()
    with pytest.raises(MXNetError):
        e.push(lambda: None, const_vars=[v], mutable_vars=[v])
    with pytest.raises(MXNetError):
        e.push(lambda: None, mutable_vars=[v, v])


def test_naive_engine_synchronous():
    e = eng.NaiveEngine()
    v = e.new_var()
    out = []
    e.push(lambda: out.append(1), mutable_vars=[v])
    assert out == [1]  # ran inline, no wait needed


def test_wait_all_drains():
    e = eng.Engine(num_workers=2)
    v = e.new_var()
    done = []
    for i in range(50):
        e.push(lambda i=i: done.append(i), mutable_vars=[v])
    e.wait_all()
    assert len(done) == 50


def test_bulk_scope():
    prev = eng.set_bulk_size(5)
    try:
        with mx.engine.bulk(10):
            x = mx.nd.zeros((1,))
            for _ in range(20):
                x += 1
        assert x.asnumpy()[0] == 20
    finally:
        eng.set_bulk_size(prev)


def test_delete_var_while_busy():
    e = eng.Engine(num_workers=2)
    v = e.new_var()
    e.push(lambda: time.sleep(0.01), mutable_vars=[v])
    e.delete_var(v)  # deferred until quiescent; must not crash
    e.wait_all()
