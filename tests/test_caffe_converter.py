"""Caffe converter: prototxt -> Symbol, caffemodel wire format -> params
(parity model: reference tools/caffe_converter). The binary fixture is
built by an independent protobuf wire-format writer in this test, so the
reader is validated against the encoding spec, not against itself."""
import os
import struct
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools", "caffe_converter"))

from caffe_pb import parse_prototxt, parse_caffemodel   # noqa: E402
from convert_model import convert_symbol, convert_model  # noqa: E402


PROTOTXT = """
name: "TinyNet"
input: "data"
layer {
  name: "conv1"
  type: "Convolution"
  bottom: "data"
  top: "conv1"
  convolution_param { num_output: 2 kernel_size: 3 stride: 1 pad: 1 }
}
layer {
  name: "relu1"
  type: "ReLU"
  bottom: "conv1"
  top: "conv1"
}
layer {
  name: "pool1"
  type: "Pooling"
  bottom: "conv1"
  top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer {
  name: "fc1"
  type: "InnerProduct"
  bottom: "pool1"
  top: "fc1"
  inner_product_param { num_output: 3 }
}
layer {
  name: "loss"
  type: "SoftmaxWithLoss"
  bottom: "fc1"
  top: "loss"
}
"""


# -- independent wire-format writer ----------------------------------------

def _varint(n):
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _tag(field, wt):
    return _varint((field << 3) | wt)


def _len_delim(field, payload):
    return _tag(field, 2) + _varint(len(payload)) + payload


def _blob(arr):
    arr = np.asarray(arr, np.float32)
    shape_msg = b"".join(_tag(1, 0) + _varint(d) for d in arr.shape)
    packed = struct.pack("<%df" % arr.size, *arr.ravel())
    return _len_delim(7, shape_msg) + _len_delim(5, packed)


def _layer(name, ltype, blobs):
    msg = _len_delim(1, name.encode()) + _len_delim(2, ltype.encode())
    for b in blobs:
        msg += _len_delim(7, _blob(b))
    return _len_delim(100, msg)


def test_prototxt_parser():
    net = parse_prototxt(PROTOTXT)
    assert net.one("name") == "TinyNet"
    layers = net.all("layer")
    assert [l.one("name") for l in layers] == \
        ["conv1", "relu1", "pool1", "fc1", "loss"]
    conv = layers[0].one("convolution_param")
    assert conv.one("num_output") == 2 and conv.one("kernel_size") == 3


def test_convert_symbol_structure():
    sym, input_name = convert_symbol(PROTOTXT)
    assert input_name == "data"
    args = sym.list_arguments()
    assert "conv1_weight" in args and "fc1_weight" in args
    _, out_shapes, _ = sym.infer_shape(data=(2, 1, 8, 8))
    assert out_shapes[0] == (2, 3)


def test_convert_model_end_to_end(tmp_path):
    rs = np.random.RandomState(0)
    conv_w = rs.randn(2, 1, 3, 3).astype(np.float32)
    conv_b = rs.randn(2).astype(np.float32)
    fc_w = rs.randn(3, 32).astype(np.float32)
    fc_b = rs.randn(3).astype(np.float32)
    blob = (_len_delim(1, b"TinyNet")
            + _layer("conv1", "Convolution", [conv_w, conv_b])
            + _layer("fc1", "InnerProduct", [fc_w, fc_b]))

    # wire reader sees exactly what the writer wrote
    layers = parse_caffemodel(blob)
    assert [l["name"] for l in layers] == ["conv1", "fc1"]
    shape, data = layers[0]["blobs"][0]
    assert list(shape) == [2, 1, 3, 3]
    np.testing.assert_allclose(np.asarray(data, np.float32),
                               conv_w.ravel())

    sym, arg_params, aux_params = convert_model(PROTOTXT, blob)
    np.testing.assert_allclose(arg_params["conv1_weight"].asnumpy(),
                               conv_w)
    np.testing.assert_allclose(arg_params["fc1_bias"].asnumpy(), fc_b)

    # converted net runs and matches a manual forward
    x = rs.randn(2, 1, 8, 8).astype(np.float32)
    mod = mx.mod.Module(sym, data_names=["data"],
                        label_names=["softmax_label"])
    mod.bind(data_shapes=[("data", (2, 1, 8, 8))],
             label_shapes=[("softmax_label", (2,))], for_training=False)
    mod.init_params(arg_params=arg_params, aux_params=aux_params,
                    allow_missing=False)
    from mxnet_tpu.io import DataBatch
    mod.forward(DataBatch([mx.nd.array(x)], [mx.nd.zeros((2,))]),
                is_train=False)
    got = mod.get_outputs()[0].asnumpy()

    conv = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(conv_w),
                             mx.nd.array(conv_b), kernel=(3, 3),
                             pad=(1, 1), num_filter=2).asnumpy()
    relu = np.maximum(conv, 0)
    pool = relu.reshape(2, 2, 4, 2, 4, 2).max(axis=(3, 5))
    logits = pool.reshape(2, -1) @ fc_w.T + fc_b
    want = np.exp(logits - logits.max(1, keepdims=True))
    want /= want.sum(1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
