"""Executor-level suite (parity model: reference
tests/python/unittest/test_executor.py — bind/simple_bind forward and
gradient equivalence, reshape, monitor callback, dict views)."""
import numpy as np

import mxnet_tpu as mx


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
    return net


def test_bind_forward_backward_matches_numpy():
    rs = np.random.RandomState(0)
    x = rs.randn(4, 5).astype(np.float32)
    w = rs.randn(3, 5).astype(np.float32)
    lhs = mx.sym.Variable("x")
    out = mx.sym.FullyConnected(lhs, num_hidden=3, no_bias=True,
                                name="fc")
    args = [mx.nd.array(x), mx.nd.array(w)]
    grads = [mx.nd.zeros((4, 5)), mx.nd.zeros((3, 5))]
    ex = out._bind_legacy(mx.cpu(), args, grads, "write") \
        if hasattr(out, "_bind_legacy") else out.bind(
            mx.cpu(), args=args, args_grad=grads, grad_req="write")
    ex.forward(is_train=True)
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), x @ w.T,
                               rtol=1e-5)
    head = np.ones((4, 3), np.float32)
    ex.backward(out_grads=[mx.nd.array(head)])
    np.testing.assert_allclose(ex.grad_arrays[0].asnumpy(), head @ w,
                               rtol=1e-5)
    np.testing.assert_allclose(ex.grad_arrays[1].asnumpy(), head.T @ x,
                               rtol=1e-5)


def test_simple_bind_dict_views():
    ex = _mlp().simple_bind(ctx=mx.cpu(), data=(2, 6))
    assert set(ex.arg_dict) == {"data", "fc1_weight", "fc1_bias",
                                "fc2_weight", "fc2_bias"}
    assert ex.arg_dict["fc1_weight"].shape == (8, 6)
    assert set(ex.output_dict) == {"fc2_output"}
    # grad_dict mirrors arg_dict for grad_req='write'
    assert ex.grad_dict["fc1_weight"].shape == (8, 6)


def test_reshape_batch_dim():
    ex = _mlp().simple_bind(ctx=mx.cpu(), data=(2, 6))
    for name, arr in ex.arg_dict.items():
        if name != "data":
            arr[:] = 0.1
    ex2 = ex.reshape(data=(5, 6))
    assert ex2.arg_dict["data"].shape == (5, 6)
    # params carry over by reference — same values, same buffers
    np.testing.assert_allclose(ex2.arg_dict["fc1_weight"].asnumpy(), 0.1)
    ex2.forward(is_train=False,
                data=mx.nd.array(np.ones((5, 6), np.float32)))
    assert ex2.outputs[0].shape == (5, 3)


def test_monitor_callback_sees_internal_outputs():
    seen = []

    def cb(name, arr):
        seen.append(name)

    ex = _mlp().simple_bind(ctx=mx.cpu(), data=(2, 6))
    ex.set_monitor_callback(cb)
    ex.forward(is_train=False,
               data=mx.nd.array(np.zeros((2, 6), np.float32)))
    assert any("fc1" in n for n in seen), seen


def test_copy_params_from():
    ex = _mlp().simple_bind(ctx=mx.cpu(), data=(2, 6))
    src = {"fc1_weight": mx.nd.ones((8, 6)),
           "fc1_bias": mx.nd.zeros((8,)),
           "fc2_weight": mx.nd.ones((3, 8)),
           "fc2_bias": mx.nd.zeros((3,))}
    ex.copy_params_from(src)
    np.testing.assert_allclose(ex.arg_dict["fc2_weight"].asnumpy(), 1.0)


def test_debug_str_lists_nodes():
    s = _mlp().simple_bind(ctx=mx.cpu(), data=(2, 6)).debug_str()
    assert "fc1" in s and "fc2" in s


def test_monitor_all_includes_params():
    seen = []
    ex = _mlp().simple_bind(ctx=mx.cpu(), data=(2, 6))
    ex.set_monitor_callback(lambda n, a: seen.append(n), monitor_all=True)
    ex.forward(is_train=False,
               data=mx.nd.array(np.zeros((2, 6), np.float32)))
    assert "fc1_weight" in seen and "fc1_output" in seen


def test_monitor_covers_multi_output_ops():
    data = mx.sym.Variable("data")
    parts = mx.sym.SliceChannel(data, num_outputs=2, name="sp")
    out = parts[0] + parts[1]
    ex = out.simple_bind(ctx=mx.cpu(), data=(2, 4))
    seen = []
    ex.set_monitor_callback(lambda n, a: seen.append(n))
    ex.forward(is_train=False,
               data=mx.nd.array(np.ones((2, 4), np.float32)))
    assert any(n.startswith("sp_output") for n in seen), seen
