"""Partition-rule sharding engine (ISSUE 15): ONE declarative spec for
dp x mp meshes, shared by training and serving.

Pinned properties:

1. RULE TREE — ordered (regex, PartitionSpec) pairs, first match wins,
   scalars never shard, explicit UNMATCHED policy (replicate or error),
   matched-but-nondivisible specs downgrade to replicate (warned +
   counted, never silent).
2. TRAINING — a rules-sharded Module on the 2x4 (and 4x2) dp x mp CPU
   mesh runs the whole train step as ONE fused dispatch per batch,
   BIT-equal to the same-mesh phase-split oracle and matching the
   single-device fused oracle at the reassociation noise floor
   (rtol 1e-5); the buffer ledger's committed ``param`` bytes show the
   1/mp per-device saving.
3. CHECKPOINTS — save gathers per-shard to ONE host file with the
   layout in meta; restore re-shards onto whatever mesh the resuming
   process binds (dp-only ckpt -> dp x mp and vice versa), including
   optimizer state re-committed to the weight's RULE-derived placement
   (the ``Updater._sync_state`` regression).
4. SERVING — ``InferenceEngine(partition_rules=...)`` serves with
   mp-sharded device-resident params BIT-equal to the replicated path.
5. ERRORS — batch divisibility on a 2-D mesh is checked (and reported)
   against the ``dp`` AXIS, not the device count.
"""
import contextlib
import os

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import nd, sym, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.io import DataBatch, DataDesc
from mxnet_tpu.parallel import (PartitionRules, mesh_from_contexts,
                                rule_spec)
from mxnet_tpu.parallel import spmd as _spmd
from mxnet_tpu.parallel.partition import (committed_nbytes,
                                          partition_summary)

N_DEV = min(8, jax.device_count())

needs_mesh = pytest.mark.skipif(
    N_DEV < 8, reason="needs the 8-device virtual CPU mesh")

RULES = PartitionRules([
    (r"fc\d+_weight$", P("mp", None)),
    (r"fc\d+_bias$", P("mp")),
])


@contextlib.contextmanager
def _pin(value):
    old = os.environ.get("MXNET_MODULE_FUSED_STEP")
    os.environ["MXNET_MODULE_FUSED_STEP"] = value
    try:
        yield
    finally:
        if old is None:
            del os.environ["MXNET_MODULE_FUSED_STEP"]
        else:
            os.environ["MXNET_MODULE_FUSED_STEP"] = old


# ---------------------------------------------------------------------------
# 1. Rule-tree matching
# ---------------------------------------------------------------------------

def test_first_match_wins_in_order():
    rules = PartitionRules([
        (r"weight", P("mp", None)),
        (r"fc1_weight", P(None, "mp")),   # unreachable: later in order
        (r".*", P()),
    ])
    assert tuple(rules.spec_for("fc1_weight", (8, 8))) == ("mp", None)
    # order is the spec: reversing the rules flips the winner
    flipped = PartitionRules([
        (r"fc1_weight", P(None, "mp")),
        (r"weight", P("mp", None)),
    ])
    assert tuple(flipped.spec_for("fc1_weight", (8, 8))) == (None, "mp")


def test_scalars_never_shard():
    rules = PartitionRules([(r".*", P("mp"))])
    assert tuple(rules.spec_for("gamma", ())) == ()
    assert tuple(rules.spec_for("beta", (1,))) == ()
    assert tuple(rules.spec_for("w", (8,))) == ("mp",)


def test_unmatched_replicate_default():
    assert tuple(RULES.spec_for("bn_gamma", (32,))) == ()


def test_unmatched_error_policy():
    rules = PartitionRules([(r"weight$", P("mp"))], unmatched="error")
    assert tuple(rules.spec_for("a_weight", (8,))) == ("mp",)
    with pytest.raises(MXNetError, match="no rule matches"):
        rules.spec_for("stray_bias", (8,))


def test_bad_policy_and_bad_rule_rejected():
    with pytest.raises(MXNetError, match="unmatched policy"):
        PartitionRules([], unmatched="ignore")
    with pytest.raises(MXNetError, match="pattern, spec"):
        PartitionRules(["not-a-pair"])


def test_apply_maps_shapes_and_arrays():
    rules = PartitionRules([(r"w$", P("mp", None))])
    out = rules.apply({"w": np.zeros((8, 4)), "b": (4,), "s": ()})
    assert tuple(out["w"]) == ("mp", None)
    assert tuple(out["b"]) == ()
    assert tuple(out["s"]) == ()


def test_rules_hashable_and_eq():
    a = PartitionRules([(r"w$", P("mp"))])
    b = PartitionRules([(r"w$", P("mp"))])
    c = PartitionRules([(r"w$", P("mp"))], unmatched="error")
    assert a == b and hash(a) == hash(b)
    assert a != c


@needs_mesh
def test_nondivisible_matched_spec_downgrades_with_counter():
    contexts = [mx.cpu(i) for i in range(8)]
    mesh = mesh_from_contexts(contexts, axes={"dp": 2, "mp": 4})
    spec = rule_spec(mesh, PartitionRules([(r".*", P("mp"))]))
    was = telemetry.enabled()
    telemetry.enable()
    try:
        telemetry.reset()
        sh = spec.param_sharding("odd", (6,))     # 6 % 4 != 0
        assert tuple(sh.spec) == ()
        assert telemetry.counters().get(
            "partition.replicated_fallback", 0) >= 1
        # an unknown axis downgrades the same way
        spec2 = rule_spec(mesh, PartitionRules([(r".*", P("tp"))]))
        assert tuple(spec2.param_sharding("w", (8,)).spec) == ()
    finally:
        if not was:
            telemetry.disable()


@needs_mesh
def test_mesh_from_contexts_axes_form():
    contexts = [mx.cpu(i) for i in range(8)]
    mesh = mesh_from_contexts(contexts, axes={"dp": 2, "mp": -1})
    assert dict(mesh.shape) == {"dp": 2, "mp": 4}
    with pytest.raises(MXNetError, match="need 6 devices"):
        mesh_from_contexts(contexts, axes={"dp": 2, "mp": 3})
    with pytest.raises(MXNetError, match="at most one"):
        mesh_from_contexts(contexts, axes={"dp": -1, "mp": -1})


@needs_mesh
def test_batch_divisibility_error_names_the_axis():
    # with a 2-D mesh, a global batch of 6 IS divisible by dp=2 even
    # though it is not divisible by the 8 devices — and the failing
    # case must name the axis, not the device count
    contexts = [mx.cpu(i) for i in range(8)]
    mod = mx.mod.Module(_mlp(), context=contexts, partition_rules=RULES,
                        mesh_axes={"dp": 2, "mp": 4})
    mod.bind(data_shapes=[DataDesc("data", (6, 16))],
             label_shapes=[DataDesc("softmax_label", (6,))])   # 6 % 2 == 0
    mod2 = mx.mod.Module(_mlp(), context=contexts,
                         partition_rules=RULES,
                         mesh_axes={"dp": 2, "mp": 4})
    with pytest.raises(MXNetError) as e:
        mod2.bind(data_shapes=[DataDesc("data", (7, 16))],
                  label_shapes=[DataDesc("softmax_label", (7,))])
    msg = str(e.value)
    assert "'dp' mesh axis" in msg and "size 2" in msg
    assert "8 devices" not in msg


def test_check_batch_divisible_default_message_unchanged():
    with pytest.raises(MXNetError, match="not divisible by 8 devices"):
        _spmd.check_batch_divisible(6, 8)


# ---------------------------------------------------------------------------
# 2. dp x mp fused training
# ---------------------------------------------------------------------------

def _mlp(c=4):
    net = sym.FullyConnected(sym.Variable("data"), num_hidden=64,
                             name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=c, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def _batches(n, batch=32, d=16, c=4, seed=7):
    rs = np.random.RandomState(seed)
    return [DataBatch(
        data=[nd.array(rs.uniform(-1, 1, (batch, d)).astype(np.float32))],
        label=[nd.array(rs.randint(0, c, batch).astype(np.float32))],
        pad=0) for _ in range(n)]


def _make(ctx, **kw):
    mod = mx.mod.Module(_mlp(), context=ctx, **kw)
    mod.bind(data_shapes=[DataDesc("data", (32, 16))],
             label_shapes=[DataDesc("softmax_label", (32,))])
    np.random.seed(11)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore="device", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9, "wd": 1e-4})
    return mod


def _train(fused, ctx, nbatch=6, **kw):
    import mxnet_tpu.executor as _ex
    counts = {}
    with _pin("1" if fused else "0"):
        mod = _make(ctx, **kw)
        metric = mx.metric.Accuracy()
        prev, _ex.dispatch_hook = _ex.dispatch_hook, \
            lambda k: counts.__setitem__(k, counts.get(k, 0) + 1)
        try:
            for b in _batches(nbatch):
                ok = mod._fused_batch_step(b, metric)
                if fused:
                    assert ok, mod._fused_fallback_reason
                if not ok:
                    mod.forward_backward(b)
                    mod.update()
                    mod.update_metric(metric, b.label)
        finally:
            _ex.dispatch_hook = prev
    params, _ = mod.get_params()
    return ({k: v.asnumpy() for k, v in params.items()}, counts, mod,
            metric)


@needs_mesh
@pytest.mark.parametrize("axes", [{"dp": 2, "mp": 4}, {"dp": 4, "mp": 2}])
def test_dpxmp_fused_one_dispatch_and_matches_oracles(axes):
    contexts = [mx.cpu(i) for i in range(8)]
    kw = dict(partition_rules=RULES, mesh_axes=axes)
    p_fused, counts, mod, _ = _train(True, contexts, **kw)
    # exactly ONE jitted-program dispatch per batch
    assert counts == {"train_step": 6}, counts
    # mp-sharded params really are sharded on device
    w = mod._exec.arg_dict["fc1_weight"]._data
    assert "mp" in tuple(w.sharding.spec)
    # bit-equal to the same-mesh phase-split oracle (same committed
    # placements, same kernels — reduction order identical)
    p_split, _, _, _ = _train(False, contexts, **kw)
    for k in p_fused:
        assert np.array_equal(p_fused[k], p_split[k]), k
    # matches the single-device fused oracle at the dp-reassociation
    # noise floor
    p_one, _, _, _ = _train(True, mx.cpu())
    for k in p_fused:
        assert np.allclose(p_fused[k], p_one[k], rtol=1e-5,
                           atol=1e-6), k


@needs_mesh
def test_dpxmp_ledger_param_bytes_one_over_mp():
    contexts = [mx.cpu(i) for i in range(8)]
    was = telemetry.enabled()
    telemetry.enable()
    try:
        def param_bytes(**kw):
            telemetry.reset()
            mod = _make(contexts, **kw)
            led = telemetry.ledger().get("mesh(%ddev)" % N_DEV, {})
            by_kind = led.get("by_kind", {})
            n = by_kind.get("param", 0)
            del mod
            return n
        repl = param_bytes()
        mp = param_bytes(partition_rules=RULES,
                         mesh_axes={"dp": 2, "mp": 4})
        assert repl > 0 and mp > 0
        ratio = mp / repl
        # all four tensors shard over mp=4 -> per-device (== total/8)
        # parameter bytes land at ~1/4 of the replicated layout
        assert 0.2 <= ratio <= 0.35, (mp, repl, ratio)
    finally:
        if not was:
            telemetry.disable()


@needs_mesh
def test_dpxmp_fused_plan_and_card_record_layout():
    contexts = [mx.cpu(i) for i in range(8)]
    was = telemetry.enabled()
    telemetry.enable()
    try:
        telemetry.reset()
        p, counts, mod, _ = _train(True, contexts,
                                   partition_rules=RULES,
                                   mesh_axes={"dp": 2, "mp": 4})
        plan = mod._fused_plan
        assert plan["layout"]["mesh_axes"] == {"dp": 2, "mp": 4}
        assert "fc1_weight" in \
            plan["layout"]["partition"]["sharded_params"]
        cards = [c for c in telemetry.programs().values()
                 if c.get("kind") == "train_step" and c.get("partition")]
        assert cards, "no train_step card carries the partition summary"
        part = cards[0]["partition"]
        assert part["mesh_axes"] == {"dp": 2, "mp": 4}
        assert part["sharded_params"] == 4
    finally:
        if not was:
            telemetry.disable()


@needs_mesh
def test_mesh_axes_without_rules_is_plain_dp():
    # mesh_axes={"dp": -1} with no rule tree: everything replicated,
    # fused step runs — the reshaped-mesh path is rule-free compatible
    contexts = [mx.cpu(i) for i in range(8)]
    p, counts, _, _ = _train(True, contexts, mesh_axes={"dp": -1})
    assert counts == {"train_step": 6}
    p_one, _, _, _ = _train(True, mx.cpu())
    for k in p:
        assert np.allclose(p[k], p_one[k], rtol=1e-5, atol=1e-6), k


# ---------------------------------------------------------------------------
# 3. Sharded checkpoints across mesh-shape changes
# ---------------------------------------------------------------------------

@needs_mesh
def test_checkpoint_restores_across_mesh_shapes(tmp_path):
    contexts = [mx.cpu(i) for i in range(8)]
    bs = _batches(6)
    with _pin("1"):
        # oracle: uninterrupted dp-only run over all 6 batches
        oracle = _make(contexts)
        met = mx.metric.Accuracy()
        for b in bs:
            assert oracle._fused_batch_step(b, met)
        p_oracle, _ = oracle.get_params()

        # dp-only for 3 batches -> checkpoint (ONE host file, layout in
        # meta) -> restore onto a dp x mp mesh -> 3 more batches
        a = _make(contexts)
        for b in bs[:3]:
            assert a._fused_batch_step(b, met)
        mgr = mx.CheckpointManager(str(tmp_path / "model"))
        meta = mgr.save(a, 0)
        assert meta["layout"]["mesh_axes"] == {"dp": 8}
        assert meta["layout"]["partition"] is None
        b_mod = _make(contexts, partition_rules=RULES,
                      mesh_axes={"dp": 2, "mp": 4})
        mgr.restore(b_mod)
        for b in bs[3:]:
            assert b_mod._fused_batch_step(b, met), \
                b_mod._fused_fallback_reason
        p_b, _ = b_mod.get_params()
        for k in p_b:
            assert np.allclose(p_b[k].asnumpy(),
                               p_oracle[k].asnumpy(),
                               rtol=1e-5, atol=1e-6), k

        # the dp x mp -> dp-only direction, with the layout recorded
        c = _make(contexts, partition_rules=RULES,
                  mesh_axes={"dp": 2, "mp": 4})
        for b in bs[:3]:
            assert c._fused_batch_step(b, met)
        mgr2 = mx.CheckpointManager(str(tmp_path / "m2"))
        meta2 = mgr2.save(c, 0)
        assert meta2["layout"]["mesh_axes"] == {"dp": 2, "mp": 4}
        assert set(meta2["layout"]["partition"]["sharded_params"]) == {
            "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"}
        d = _make(contexts)
        mgr2.restore(d)
        for b in bs[3:]:
            assert d._fused_batch_step(b, met)
        p_d, _ = d.get_params()
        for k in p_d:
            assert np.allclose(p_d[k].asnumpy(),
                               p_oracle[k].asnumpy(),
                               rtol=1e-5, atol=1e-6), k


@needs_mesh
def test_sync_state_recommits_to_rule_placement(tmp_path):
    """The Updater._sync_state regression (dp x mp round trip): loaded
    optimizer states re-commit to the WEIGHT's rule-derived placement,
    not the replicated dp layout the old code assumed."""
    contexts = [mx.cpu(i) for i in range(8)]
    bs = _batches(4)
    with _pin("1"):
        a = _make(contexts, partition_rules=RULES,
                  mesh_axes={"dp": 2, "mp": 4})
        met = mx.metric.Accuracy()
        for b in bs[:2]:
            assert a._fused_batch_step(b, met)
        states = tmp_path / "opt.states"
        a.save_optimizer_states(str(states))

        b_mod = _make(contexts, partition_rules=RULES,
                      mesh_axes={"dp": 2, "mp": 4})
        arg_p, aux_p = a.get_params()
        b_mod.set_params(arg_p, aux_p)
        b_mod.load_optimizer_states(str(states))
        for b in bs[2:]:
            assert b_mod._fused_batch_step(b, met), \
                b_mod._fused_fallback_reason
        # momentum state landed on the weight's mp-sharded placement
        upd = b_mod._kvstore._updater if b_mod._update_on_kvstore \
            else b_mod._updater
        i = b_mod._param_names.index("fc1_weight")
        st = upd.states[i]
        leaf = st[0] if isinstance(st, tuple) else st
        wsh = b_mod._exec.arg_dict["fc1_weight"]._data.sharding
        assert leaf._data.sharding.spec == wsh.spec
        assert "mp" in tuple(leaf._data.sharding.spec)
        # and the round trip is exact: continuing A is bit-identical
        for b in bs[2:]:
            assert a._fused_batch_step(b, met)
        pa, _ = a.get_params()
        pb, _ = b_mod.get_params()
        for k in pa:
            assert np.array_equal(pa[k].asnumpy(), pb[k].asnumpy()), k


# ---------------------------------------------------------------------------
# 4. Serving with mp-sharded params
# ---------------------------------------------------------------------------

@needs_mesh
def test_serving_mp_sharded_bit_equal_to_replicated():
    from mxnet_tpu.serving import InferenceEngine
    net = sym.FullyConnected(sym.Variable("data"), num_hidden=64,
                             name="fc1")
    net = sym.Activation(net, act_type="relu")
    rs = np.random.RandomState(3)
    params = {
        "arg:fc1_weight": nd.array(
            rs.uniform(-1, 1, (64, 16)).astype(np.float32)),
        "arg:fc1_bias": nd.array(
            rs.uniform(-1, 1, (64,)).astype(np.float32)),
    }
    rules = PartitionRules([(r"fc1_weight$", P("mp", None)),
                            (r"fc1_bias$", P("mp"))])
    x = rs.uniform(-1, 1, (5, 16)).astype(np.float32)
    with InferenceEngine(net, params, {"data": (8, 16)},
                         max_batch=8) as repl:
        r_repl = repl.predict(data=x)
        r_repl1 = repl.predict(data=x[:1])
    contexts = [mx.cpu(i) for i in range(8)]
    with InferenceEngine(net, params, {"data": (8, 16)}, max_batch=8,
                         partition_rules=rules,
                         contexts=contexts) as eng:
        # params really live mp-sharded across the serving mesh
        w = eng._param_raw["fc1_weight"]
        assert "mp" in tuple(w.sharding.spec)
        assert len(w.addressable_shards) == 8
        summary = eng.partition_summary()
        assert summary["mesh_axes"] == {"dp": 1, "mp": 8}
        r_mp = eng.predict(data=x)
        # a second request exercises a different bucket
        r_mp1 = eng.predict(data=x[:1])
    assert all(np.array_equal(a, b) for a, b in zip(r_repl, r_mp))
    # per-bucket comparison: each bucket's program vs the SAME bucket
    # on the replicated engine (different buckets may legitimately
    # compile different kernels)
    assert all(np.array_equal(a, b) for a, b in zip(r_repl1, r_mp1))


@needs_mesh
def test_serving_bucket_divisibility_checked_against_dp():
    from mxnet_tpu.serving import InferenceEngine
    net = sym.FullyConnected(sym.Variable("data"), num_hidden=8,
                             name="fc1")
    rs = np.random.RandomState(0)
    params = {
        "arg:fc1_weight": nd.array(
            rs.uniform(-1, 1, (8, 4)).astype(np.float32)),
        "arg:fc1_bias": nd.array(np.zeros(8, np.float32)),
    }
    rules = PartitionRules([(r".*weight$", P("mp", None))])
    with pytest.raises(MXNetError, match="'dp' mesh axis"):
        InferenceEngine(net, params, {"data": (8, 4)}, max_batch=8,
                        buckets=[1, 8], warmup=False,
                        partition_rules=rules,
                        mesh_axes={"dp": 2, "mp": 4},
                        contexts=[mx.cpu(i) for i in range(8)])


# ---------------------------------------------------------------------------
# 5. The parallel kernels' exported layouts
# ---------------------------------------------------------------------------

def test_kernels_export_partition_rules():
    import importlib
    from mxnet_tpu.parallel import moe, pipeline, ulysses
    # the package re-exports the ring_attention FUNCTION under the
    # submodule's name; import the module explicitly
    ring_attention = importlib.import_module(
        "mxnet_tpu.parallel.ring_attention")
    fake = {
        "router_w": (4, 32), "blk0_expert_w1": (4, 64, 32),
        "stage_stack": (4, 8, 8),
        "q_proj_weight": (64, 32), "out_proj_weight": (32, 64),
        "ln_gamma": (32,),
    }
    for mod, axis in ((moe, "ep"), (pipeline, "pp"),
                      (ring_attention, None), (ulysses, "sp")):
        rules = PartitionRules(mod.PARTITION_RULES)
        specs = rules.apply(fake)
        flat_axes = {a for s in specs.values()
                     for e in tuple(s) if e is not None
                     for a in (e if isinstance(e, tuple) else (e,))}
        if axis is None:
            assert flat_axes == set(), flat_axes
        else:
            assert flat_axes == {axis}, (mod.__name__, flat_axes)
    # the moe rules route router vs expert weights differently
    moe_specs = PartitionRules(moe.PARTITION_RULES).apply(fake)
    assert tuple(moe_specs["router_w"]) == ()
    assert tuple(moe_specs["blk0_expert_w1"]) == ("ep",)


def test_plan_serving_layout_filter_both_directions():
    """The tuner's layout filter ALWAYS applies: mp-sharded corpus rows
    never shape a replicated engine's plan and vice versa — and the
    derived ``sharded_params`` map (absent at plan-load time, present
    on banked rows) does not split otherwise-identical layouts."""
    from mxnet_tpu.tuner import plan_serving

    def rec(layout=None):
        return {"kind": "serving", "max_batch": 16, "layout": layout,
                "rows_hist": {"3": 50, "16": 5},
                "bucket_ms": {"16": {"total_ms": 160.0, "count": 10}},
                "spans": {}}

    banked = {"mesh_axes": {"dp": 1, "mp": 8}, "data_axis": "dp",
              "partition": {"rules": [["w$", ["mp"]]],
                            "unmatched": "replicate",
                            "sharded_params": {"w": ["mp"]}}}
    query = {"mesh_axes": {"dp": 1, "mp": 8}, "data_axis": "dp",
             "partition": {"rules": [["w$", ["mp"]]],
                           "unmatched": "replicate"}}
    # replicated engine ignores mp rows (and still plans from its own)
    assert plan_serving([rec(banked)], layout=None) is None
    assert plan_serving([rec(None)], layout=None) is not None
    # mp engine plans from mp rows despite the sharded_params delta,
    # and ignores replicated rows
    plan = plan_serving([rec(banked), rec(None)], layout=query)
    assert plan is not None
    assert plan["basis"]["records"] == 1
    assert plan["layout"] == query
    # a genuinely different layout (other mesh) never matches
    other = dict(query, mesh_axes={"dp": 1, "mp": 4})
    assert plan_serving([rec(banked)], layout=other) is None


# ---------------------------------------------------------------------------
# 6. Ledger / summary helpers
# ---------------------------------------------------------------------------

@needs_mesh
def test_committed_nbytes_counts_per_shard():
    contexts = [mx.cpu(i) for i in range(8)]
    mesh = mesh_from_contexts(contexts, axes={"dp": 2, "mp": 4})
    spec = rule_spec(mesh, RULES)
    w = jax.device_put(np.zeros((64, 16), np.float32),
                       spec.param_sharding("fc1_weight", (64, 16)))
    # sharded over mp=4: 2048 bytes/shard-group x 8 devices = 2x global
    assert committed_nbytes(w) == 64 * 16 * 4 // 4 * 8
    r = jax.device_put(np.zeros((64,), np.float32), spec.repl_sharding)
    assert committed_nbytes(r) == 64 * 4 * 8


@needs_mesh
def test_partition_summary_shape():
    contexts = [mx.cpu(i) for i in range(8)]
    spec = rule_spec(mesh_from_contexts(contexts,
                                        axes={"dp": 2, "mp": 4}), RULES)
    s = partition_summary(spec, {"fc1_weight": (64, 16), "other": (3,)})
    assert s["mesh_axes"] == {"dp": 2, "mp": 4}
    assert s["data_axis"] == "dp"
    assert s["partition"]["unmatched"] == "replicate"
    assert s["partition"]["sharded_params"] == {
        "fc1_weight": ["mp", None]}
    assert partition_summary(None) is None
