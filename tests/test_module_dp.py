"""Multi-device data-parallel Module: one GSPMD-sharded program.

Parity model: reference multi-GPU DataParallelExecutorGroup + KVStore
reduction (tests/python/unittest/test_multi_device_exec.py and
nightly/multi_lenet.py) — validated here the TPU-native way: a Module
bound on N contexts shards the batch over a dp mesh and must produce the
SAME losses/params as the single-device Module, because the gradient
all-reduce happens inside the compiled step.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym, nd
from mxnet_tpu.io import NDArrayIter, DataBatch

import jax


def _toy_data(n=256, d=16, c=4, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.normal(0, 2, (c, d)).astype(np.float32)
    y = rng.randint(0, c, n)
    x = ((centers[y] + rng.normal(0, 0.5, (n, d))) / 3.0).astype(np.float32)
    return x, y.astype(np.float32)


def _mlp(c=4):
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=c, name="fc2")
    return sym.SoftmaxOutput(net, sym.Variable("softmax_label"), name="softmax")


def _fit(contexts, nbatch=4, batch_size=64):
    np.random.seed(0)
    mx.random.seed(0)
    x, y = _toy_data()
    mod = mx.mod.Module(_mlp(), context=contexts)
    mod.bind(data_shapes=[("data", (batch_size, 16))],
             label_shapes=[("softmax_label", (batch_size,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore="local", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    losses = []
    for i in range(nbatch):
        xs = x[i * batch_size:(i + 1) * batch_size]
        ys = y[i * batch_size:(i + 1) * batch_size]
        batch = DataBatch(data=[nd.array(xs)], label=[nd.array(ys)])
        mod.forward_backward(batch)
        out = mod.get_outputs()[0].asnumpy()
        nll = -np.log(np.maximum(
            out[np.arange(batch_size), ys.astype(int)], 1e-8)).mean()
        losses.append(nll)
        mod.update()
    arg_p, _ = mod.get_params()
    return losses, {k: v.asnumpy() for k, v in arg_p.items()}


def test_dp_module_matches_single_device():
    n_dev = min(8, jax.device_count())
    assert n_dev >= 2, "conftest sets an 8-device virtual CPU mesh"
    ref_losses, ref_params = _fit([mx.cpu(0)])
    dp_losses, dp_params = _fit([mx.cpu(i) for i in range(n_dev)])
    np.testing.assert_allclose(dp_losses, ref_losses, rtol=1e-5, atol=1e-6)
    for k in ref_params:
        np.testing.assert_allclose(dp_params[k], ref_params[k],
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_dp_module_fit_loop():
    """Module.fit end-to-end over 8 virtual devices (convergence gate)."""
    x, y = _toy_data(512)
    n_dev = min(8, jax.device_count())
    train = NDArrayIter(x, y, batch_size=64, shuffle=True)
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(n_dev)])
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.initializer.Xavier(), num_epoch=4)
    score = mod.score(NDArrayIter(x, y, batch_size=64), "acc")
    assert score[0][1] > 0.9, "did not converge: %s" % score


def test_dp_batch_not_divisible_raises():
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(3)])
    try:
        mod.bind(data_shapes=[("data", (62, 16))],
                 label_shapes=[("softmax_label", (62,))])
    except mx.base.MXNetError:
        return
    raise AssertionError("expected divisibility error")
